"""Validate the degree-class slotted layout on real RMAT data.

Host: degree-sort vertices, build per-class slot index arrays
[T_i, R_i, 128] pointing into the permuted state vector (dead slot for
padding).  Device: per class, out = state[slots].sum(axis=1) — fused
gather+reduce, fully static.  Measures total step time vs the old
engine's 433 ms.
"""

from __future__ import annotations

import sys
import time

import numpy as np

SCALE = int(sys.argv[1]) if len(sys.argv) > 1 else 21
EF = 16
W = 128
REPS = 10

from lux_tpu.convert import rmat_edges
from lux_tpu.graph import Graph

t0 = time.perf_counter()
src, dst, nv = rmat_edges(scale=SCALE, edge_factor=EF, seed=0)
g = Graph.from_edges(src, dst, nv)
indeg = g.in_degrees().astype(np.int64)
print(f"graph {time.perf_counter() - t0:.1f}s  nv={g.nv} ne={g.ne}")

t0 = time.perf_counter()
# ---- degree-sorted permutation: perm[new] = old, rank[old] = new
perm = np.argsort(-indeg, kind="stable")
rank = np.empty(nv, dtype=np.int64)
rank[perm] = np.arange(nv)

ntile = (nv + W - 1) // W
vpad = ntile * W
dead = vpad  # one extra state row, holds identity

# per-tile row depth = max indeg in tile (degree-sorted -> first lane)
d_sorted = indeg[perm]
tile_depth = np.zeros(ntile, dtype=np.int64)
tile_depth[:] = d_sorted[::W][:ntile]   # max = first element of each tile
tile_depth = np.maximum(tile_depth, 1)

# ---- class boundaries: exact depths up to 8, then x1.5 steps
levels = [1, 2, 3, 4, 5, 6, 8]
v = 8
while v < int(tile_depth.max()):
    v = int(v * 1.5) + 1
    levels.append(v)
lev = np.asarray(levels, dtype=np.int64)
tile_lvl = lev[np.searchsorted(lev, tile_depth)]

slots_total = int((tile_lvl * W).sum())
print(f"inflation {slots_total / g.ne:.3f}x, classes used "
      f"{len(np.unique(tile_lvl))}, rows {slots_total // W}")

# ---- build slot arrays per class (vectorized)
# edges sorted by (new dst): edge list (src_new, dst_new)
src_new = rank[g.col_idx.astype(np.int64)]
dst_new = rank[np.repeat(np.arange(nv, dtype=np.int64), indeg)]
order = np.argsort(dst_new, kind="stable")
src_new = src_new[order]
dst_new = dst_new[order]
# row index of each edge within its dst vertex
rp = np.concatenate(([0], np.cumsum(np.bincount(dst_new, minlength=vpad))))
erow = np.arange(g.ne, dtype=np.int64) - rp[dst_new]

classes = []
for L in np.unique(tile_lvl):
    tids = np.nonzero(tile_lvl == L)[0]          # tile ids, contiguous? (sorted by depth desc -> yes)
    T_i = len(tids)
    slots = np.full((T_i, int(L), W), dead, dtype=np.int32)
    # edges whose dst tile is in this class
    tile_of_edge = dst_new // W
    sel = np.isin(tile_of_edge, tids)
    e = np.nonzero(sel)[0]
    tpos = np.searchsorted(tids, tile_of_edge[e])
    slots[tpos, erow[e], dst_new[e] % W] = src_new[e]
    classes.append((tids, slots))
print(f"layout build {time.perf_counter() - t0:.1f}s")

# ---- device
import jax
import jax.numpy as jnp

state_h = np.random.default_rng(0).random(vpad + 1, np.float32)
state_h[dead] = 0.0
state = jnp.asarray(state_h)
slot_d = [jnp.asarray(s) for _, s in classes]


def step(state, *slot_arrays):
    outs = [jnp.sum(jnp.take(state, s, axis=0), axis=1)
            for s in slot_arrays]
    return jnp.concatenate(outs, axis=0)        # [ntile, W] tiles in class order


jstep = jax.jit(step)


def timeit(name, fn, x0, *rest):
    """Round 15: observatory recipe (lux_tpu.timing.loop_bench) —
    loop-dependent x carry, scalar output, one jit; block_until_ready
    fencing is grep-gated out of scripts/ (lint_lux bench-fence)."""
    from lux_tpu.observe import median_mad
    from lux_tpu.timing import loop_bench

    def step(c):
        x, extra = c
        out = fn(x, *extra)
        sv = jnp.sum(jax.tree.leaves(out)[0].ravel()[:1]).astype(
            jnp.float32)
        return sv, (x + (sv * 1e-30).astype(x.dtype), extra)

    samples, _ = loop_bench(step, (x0, tuple(rest)), REPS, repeats=3)
    dt, _mad = median_mad(samples)
    print(f"{name:44s} {dt * 1e3:8.2f} ms  ({g.ne / dt / 1e9:6.2f} GTEPS)")
    return dt


timeit("class gather+sum step", jstep, state, *slot_d)

# correctness vs numpy
out = np.asarray(jax.device_get(jstep(state, *slot_d)))
ref = np.zeros(vpad)
np.add.at(ref, dst_new, state_h[src_new])
tid_order = np.concatenate([t for t, _ in classes])
ref_tiles = ref.reshape(ntile, W)[tid_order]
err = np.abs(out - ref_tiles).max()
print(f"max err vs numpy: {err:.2e}")
