"""Sweep pallas kernel variants for the chunk partial reduction."""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

C = 80 * 1024
E = 512
W = 128
REPS = 10

rng = np.random.default_rng(0)
vals_h = rng.random((C, E), np.float32)
rel_h = np.sort(rng.integers(0, W + 1, (C, E)), axis=1).astype(np.int32)
start_h = (rng.random(C) < 0.2).astype(np.int32)
start_h[0] = 1

vals = jnp.asarray(vals_h)
rel = jnp.asarray(rel_h)
start = jnp.asarray(start_h).reshape(C, 1)


def timeit(name, fn, x0, *rest):
    """Round 15: observatory recipe (lux_tpu.timing.loop_bench) —
    loop-dependent x carry, scalar output, one jit; block_until_ready
    fencing is grep-gated out of scripts/ (lint_lux bench-fence)."""
    from lux_tpu.observe import median_mad
    from lux_tpu.timing import loop_bench

    def step(c):
        x, extra = c
        out = fn(x, *extra)
        sv = jnp.sum(jax.tree.leaves(out)[0].ravel()[:1]).astype(
            jnp.float32)
        return sv, (x + (sv * 1e-30).astype(x.dtype), extra)

    samples, _ = loop_bench(step, (x0, tuple(rest)), REPS, repeats=3)
    dt, _mad = median_mad(samples)
    ed = C * E / dt / 1e9
    print(f"{name:40s} {dt * 1e3:8.2f} ms  ({ed:6.2f} Gedge/s)")
    return dt


# -- current kernel (3D), block sweep --------------------------------------
from lux_tpu.ops.pallas_reduce import chunk_partials_pallas

if "--3d" in sys.argv:
    f = jax.jit(functools.partial(chunk_partials_pallas, W=W, kind="sum",
                                  block_c=8))
    timeit("3d kernel block_c=8", f, vals, rel)


# -- 2D row-loop kernel with fused carry ----------------------------------
def _fused_kernel(start_ref, vals_ref, rel_ref, out_ref, carry, *, B):
    lanes = jax.lax.broadcasted_iota(jnp.int32, (E, W), 1)

    @pl.when(pl.program_id(0) == 0)
    def _():
        carry[:] = jnp.zeros_like(carry)

    def body(i, _):
        v = vals_ref[i, :]
        r = rel_ref[i, :]
        m = r[:, None] == lanes
        part = jnp.sum(jnp.where(m, v[:, None], 0.0), axis=0)  # [W]
        acc = jnp.where(start_ref[i, 0] == 1, part, carry[0, :] + part)
        carry[0, :] = acc
        out_ref[i, :] = acc
        return 0

    jax.lax.fori_loop(0, B, body, 0, unroll=True)


def fused(vals, rel, start, bc):
    kern = functools.partial(_fused_kernel, B=bc)
    return pl.pallas_call(
        kern,
        grid=(C // bc,),
        in_specs=[
            pl.BlockSpec((bc, 1), lambda b: (b, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((bc, E), lambda b: (b, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bc, E), lambda b: (b, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bc, W), lambda b: (b, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((C, W), vals.dtype),
        scratch_shapes=[pltpu.VMEM((1, W), vals.dtype)],
    )(start, vals, rel)


for bc in (32, 128):
    f = jax.jit(functools.partial(fused, bc=bc))
    timeit(f"2d fused-carry block_c={bc}", f, vals, rel, start)


# -- MXU one-hot variant: partial = onehot(rel).T @ vals per row? ----------
# batched matvec via dot_general inside kernel, one chunk at a time
def _mxu_kernel(vals_ref, rel_ref, out_ref, *, B):
    lanes = jax.lax.broadcasted_iota(jnp.int32, (E, W), 1)

    def body(i, _):
        r = rel_ref[i, :]
        oh = (r[:, None] == lanes).astype(jnp.float32)      # [E, W]
        v = vals_ref[i, :].reshape(1, E)
        out_ref[i, :] = jnp.dot(
            v, oh, preferred_element_type=jnp.float32)[0]
        return 0

    jax.lax.fori_loop(0, B, body, 0, unroll=True)


def mxu(vals, rel, bc):
    kern = functools.partial(_mxu_kernel, B=bc)
    return pl.pallas_call(
        kern,
        grid=(C // bc,),
        in_specs=[
            pl.BlockSpec((bc, E), lambda b: (b, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bc, E), lambda b: (b, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bc, W), lambda b: (b, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((C, W), vals.dtype),
    )(vals, rel)


f = jax.jit(functools.partial(mxu, bc=32))
timeit("mxu onehot block_c=32", f, vals, rel)
