"""A/B the pull exchanges: gather (all-gather + big-table gather) vs
owner (per-src-part small-shard gathers + reduce_scatter), driver
methodology (fused iterations, host-fetch fence).

Usage: PYTHONPATH=/root/repo:/root/.axon_site python \
    scripts/bench_owner.py [scale] [ef] [np] [pair] [owner_E] [ni]
"""

import sys
import time

import numpy as np

scale = int(sys.argv[1]) if len(sys.argv) > 1 else 21
ef = int(sys.argv[2]) if len(sys.argv) > 2 else 16
nparts = int(sys.argv[3]) if len(sys.argv) > 3 else 4
pair = int(sys.argv[4]) if len(sys.argv) > 4 else 0
owner_E = int(sys.argv[5]) if len(sys.argv) > 5 else 256
ni = int(sys.argv[6]) if len(sys.argv) > 6 else 10

from lux_tpu.apps import pagerank
from lux_tpu.convert import rmat_graph
from lux_tpu.engine.pull import PullEngine
from lux_tpu.graph import ShardedGraph, pair_relabel
from lux_tpu.timing import timed_fused_run

t0 = time.time()
g = rmat_graph(scale=scale, edge_factor=ef, seed=0)
print(f"graph nv={g.nv} ne={g.ne} ({time.time() - t0:.0f}s)",
      flush=True)
pair_t = pair if pair > 0 else None
t0 = time.time()
g2, _perm, starts = pair_relabel(g, nparts, pair_threshold=pair_t or 16)
sg = ShardedGraph.build(g2, nparts, starts=starts,
                        pair_threshold=pair_t or 16)
print(f"relabel+build ({time.time() - t0:.0f}s) vpad={sg.vpad} "
      f"epad={sg.epad}", flush=True)


def bench(tag, **kw):
    t0 = time.time()
    eng = PullEngine(sg, pagerank.make_program(), pair_threshold=pair_t,
                     **kw)
    own = getattr(eng, "owner", None)
    extra = f" owner_stats={own.stats}" if own is not None else ""
    print(f"{tag}: engine ({time.time() - t0:.0f}s){extra}", flush=True)
    state, [el] = timed_fused_run(eng, ni)
    assert np.isfinite(eng.unpad(state)).all()
    gteps = g.ne * ni / el / 1e9
    print(f"{tag}: {el / ni * 1e3:.0f} ms/iter  "
          f"{el / ni / g.ne * 1e9:.1f} ns/edge  {gteps:.4f} GTEPS",
          flush=True)
    del eng


order = sys.argv[7] if len(sys.argv) > 7 else "go"
for c in order:           # interleavable A/B: e.g. "gogo"
    if c == "g":
        bench("gather", tile_e=128 if pair_t else 512)
    else:
        bench("owner", exchange="owner", owner_tile_e=owner_E)
