"""VERDICT r1 #8 / r4 #1-2: prove the >=0.5B-edge path on one chip,
for BOTH engine families.

Generates RMAT{scale} with the native C++ generator, builds a
multi-part ShardedGraph within host RAM, runs the app on the real TPU,
and prints one JSON line per stage plus the final GTEPS (driver
methodology: pull apps time a loop-dependent fused run, push apps time
whole while_loop converges; host-fetch fence either way).

Usage (key=value args, any order):
  PYTHONPATH=/root/repo:/root/.axon_site \
      python scripts/bench_bigscale.py [scale=25] [np=4] [pair=0] \
          [ni=3] [tile_e=0] [exchange=gather] [owner_e=0] \
          [app=pagerank|cc|sssp|sssp-w] [sparse=1] [repeats=1] \
          [preset=rmat27pair]

preset=rmat27pair expands to the scale-27 pair record configuration
(round-5 pointer #4): pagerank scale=27 np=8 pair=16 min_fill=16
exchange=owner owner_e=128 ni=1 repeats=3 — pair(16)+owner+min_fill
on the 2.1B-edge flagship graph.  The geometry stays inside the
proven RMAT26 pair+owner shapes (min_fill thins the residual toward
well-packed E=128 chunks; the packed uint32 owner encoding holds the
arrays), ni=1 keeps each execution under the ~55 s duration wall
(PERF_NOTES round 5), and the relabel needs ~60-80 GB host peak.
Explicit key=value args override preset fields.

pair > 0 additionally runs graph.pair_relabel + pair-lane delivery
(slower host prep; measures the fast path at scale).  tile_e=0 uses
the engine default (512; 128 for the pair residual); bigger values
halve the [P, C, 128] partials temporary but grow per-tile chunk
padding — measured NET WORSE at RMAT26 (PERF_NOTES).

Push apps: cc symmetrizes (and caches) the graph and converges
max-propagation; sssp converges hop frontiers from vertex 0; sssp-w
attaches uniform 1..5 int weights (the bench convention) and converges
weighted frontiers.  sparse=0 drops the src-sorted frontier view
(halves edge memory; every iteration dense) — the big-scale fit lever
priced by ShardedGraph.memory_report(push_sparse=...).
"""

from __future__ import annotations

import json
import resource
import sys
import time


def log(stage, t0, **kw):
    peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
    print(json.dumps(dict(stage=stage, secs=round(time.time() - t0, 1),
                          peak_host_gb=round(peak, 1), **kw)),
          flush=True)
    return time.time()


DEFAULTS = dict(scale=25, np=4, pair=0, ni=3, tile_e=0,
                exchange="gather", owner_e=0, app="pagerank",
                sparse=1, repeats=1, min_fill=0, seg=0, preset="")

# the scale-27 pair record configuration (round-5 pointer #4); see
# the module docstring
PRESETS = dict(rmat27pair=dict(
    app="pagerank", scale=27, np=8, pair=16, min_fill=16,
    exchange="owner", owner_e=128, ni=1, repeats=3))


def parse_args(argv):
    cfg = dict(DEFAULTS)
    explicit = {}
    pos = 0
    for a in argv:
        if "=" in a:
            k, v = a.split("=", 1)
            if k not in cfg:
                raise SystemExit(f"unknown arg {k!r} (known: "
                                 f"{', '.join(cfg)})")
        else:   # legacy positional order
            if pos >= len(DEFAULTS):
                raise SystemExit(f"too many positional args at {a!r}")
            k, v = list(DEFAULTS)[pos], a
            pos += 1
        explicit[k] = v if k in ("exchange", "app", "preset") else int(v)
    preset = explicit.pop("preset", "")
    if preset:
        if preset not in PRESETS:
            raise SystemExit(f"unknown preset {preset!r} (known: "
                             f"{', '.join(PRESETS)})")
        cfg.update(PRESETS[preset])
    cfg.update(explicit)        # explicit args override the preset
    return cfg


def main():
    cfg = parse_args(sys.argv[1:])
    scale, np_parts, pair = cfg["scale"], cfg["np"], cfg["pair"]
    app, exchange = cfg["app"], cfg["exchange"]

    import os

    import numpy as np

    from lux_tpu.format import write_lux
    from lux_tpu.graph import Graph, pair_relabel

    t = time.time()
    cache = f"/tmp/rmat{scale}_ef16_s0.lux"
    if os.path.exists(cache):
        g = Graph.from_file(cache, use_native=True)
        t = log("load_cached", t, nv=g.nv, ne=g.ne)
    else:
        from lux_tpu.convert import rmat_graph
        g = rmat_graph(scale=scale, edge_factor=16, seed=0)
        t = log("generate", t, nv=g.nv, ne=g.ne)
        write_lux(cache, g.row_ptrs, g.col_idx, degrees=g.out_degrees)
        t = log("cache_write", t)

    if app == "cc":
        # CC needs the symmetrized edge set (bench.py convention);
        # cache it — the 2x-edge from_edges sort is minutes at scale 25
        sym = f"/tmp/rmat{scale}_ef16_s0_sym.lux"
        if os.path.exists(sym):
            g = Graph.from_file(sym, use_native=True)
            t = log("load_sym_cached", t, ne=g.ne)
        else:
            from lux_tpu.apps.components import symmetrize
            s, d = symmetrize(*g.edge_arrays())
            g = Graph.from_edges(s, d, g.nv)
            # temp + rename: a crash mid-write must never leave a
            # truncated cache that a later run would load as the graph
            write_lux(sym + ".tmp", g.row_ptrs, g.col_idx,
                      degrees=g.out_degrees)
            os.replace(sym + ".tmp", sym)
            t = log("symmetrize", t, ne=g.ne)
    elif app == "sssp-w":
        rng = np.random.default_rng(1)
        g.weights = rng.integers(1, 6, size=g.ne).astype(np.int32)
        t = log("weights", t)

    starts = None
    if pair:
        # pair_relabel is deterministic: cache the relabeled graph +
        # cut points so repeat runs (phase probes, exchange A/Bs) skip
        # the ~20-min billion-edge relabel.  RELAB_VER must be bumped
        # whenever pair_relabel's PARTITIONING changes, or a stale
        # cache silently benchmarks the old cuts; the .starts.npy is
        # written LAST and gates the load, so a crash mid-write never
        # serves a partial cache.
        RELAB_VER = "v5p"   # v5p: cache gained .perm.npy (round 5)
        sym_tag = "_sym" if app == "cc" else ""
        rcache = (f"/tmp/rmat{scale}_ef16_s0{sym_tag}_relab_np{np_parts}"
                  f"_p{pair}{RELAB_VER}")
        if os.path.exists(rcache + ".starts.npy"):
            if g.weights is not None:
                # weights are attached PRE-relabel in this script only
                # for sssp-w; the unweighted cache cannot serve them
                raise SystemExit("pair cache + weighted: rebuild the "
                                 "cache with weights in the .lux file")
            g = Graph.from_file(rcache + ".lux", use_native=True)
            starts = np.load(rcache + ".starts.npy")
            perm = np.load(rcache + ".perm.npy")
            t = log("load_relabel_cache", t)
        else:
            g, perm, starts = pair_relabel(g, np_parts,
                                           pair_threshold=pair,
                                           verbose=True)
            t = log("pair_relabel", t)
            if g.weights is None:
                write_lux(rcache + ".lux", g.row_ptrs, g.col_idx,
                          degrees=g.out_degrees)
                np.save(rcache + ".perm.npy", perm)
                # written LAST: gates the whole cache load
                np.save(rcache + ".starts.npy", starts)
                t = log("relabel_cache_write", t)
        # start from the top-degree hub = relabeled vertex 0 (original
        # vertex 0 IS isolated at rmat25+ seed 0 — the reached-fraction
        # assert below caught exactly that; a hub start guarantees a
        # meaningful frontier cascade at every scale)
        start_vertex = 0
    else:
        # no relabel: the max-out-degree vertex, for the same reason
        start_vertex = int(np.argmax(g.out_degrees))

    kw = dict(num_parts=np_parts, pair_threshold=pair or None,
              pair_min_fill=cfg["min_fill"] or None,
              starts=starts, exchange=exchange)
    if cfg["owner_e"]:
        kw["owner_tile_e"] = cfg["owner_e"]
    if app == "pagerank":
        from lux_tpu.apps import pagerank
        if cfg["tile_e"]:
            kw["tile_e"] = cfg["tile_e"]
        eng = pagerank.build_engine(g, **kw)
    elif app == "cc":
        from lux_tpu.apps import components
        eng = components.build_engine(g, enable_sparse=bool(cfg["sparse"]),
                                      **kw)
    elif app in ("sssp", "sssp-w"):
        from lux_tpu.apps import sssp as sssp_app
        eng = sssp_app.build_engine(g, start_vertex=start_vertex,
                                    weighted=app == "sssp-w",
                                    enable_sparse=bool(cfg["sparse"]),
                                    **kw)
    else:
        raise SystemExit(f"unknown app {app!r}")

    rep = eng.sg.memory_report(
        exchange=eng.exchange,   # the RESOLVED value ('auto' -> real)
        owner_slots_per_part=(
            eng.owner.stats["slots"] // len(eng.sg.part_ids())
            if eng.owner is not None else None),
        owner_packed=(eng.owner.packed if eng.owner is not None
                      else None),
        push_sparse=app != "pagerank" and bool(cfg["sparse"]))
    t = log("build_engine", t,
            vpad=eng.sg.vpad, epad=eng.sg.epad,
            device_gb=round(rep["total_bytes"] / 1e9, 2),
            pair_cov=(round(eng.pairs.stats["coverage"], 3)
                      if eng.pairs is not None else None),
            pair_inflation=(round(eng.pairs.stats["inflation"], 2)
                            if eng.pairs is not None else None),
            owner_stats=(eng.owner.stats if eng.owner is not None
                         else None))

    if app == "pagerank":
        from lux_tpu.timing import timed_fused_run
        ni = cfg["ni"]
        state, elapsed = timed_fused_run(eng, ni, repeats=cfg["repeats"])
        out = eng.unpad(state)
        assert np.isfinite(out).all(), "non-finite result"
        iters = ni
    elif cfg["seg"]:
        # SEGMENTED converge: cap each while_loop execution at seg
        # iterations with host round-trips between segments — bounds
        # single-execution duration under the TPU-worker crash
        # envelope (PERF_NOTES round 5: a ~2x-longer all-dense CC
        # converge died where the same-shape sssp converge ran).
        # Timing includes the segment round-trips (honest; recorded).
        from lux_tpu.timing import fence, fetch
        label, active = eng.init_state()
        _l, _a, _it = eng.converge(label, active, 1)   # compile
        fence(_l)
        label, active = eng.init_state()
        fence((label, active))
        t0 = time.perf_counter()
        iters = 0
        while True:
            label, active, it = eng.converge(label, active,
                                             cfg["seg"])
            it = int(fetch(it))
            iters += it
            if it < cfg["seg"]:
                break
        elapsed = [time.perf_counter() - t0]
        out = eng.unpad(label)
        if app == "cc":
            assert out.min() >= 0, "CC label underflow"
        else:
            from lux_tpu.apps import sssp as _s
            reached = int((~_s.unreachable(out)).sum())
            assert reached > g.nv // 100, "vacuous sssp run"
    else:
        from lux_tpu.timing import timed_converge
        # timed_converge returns labels already unpadded to [nv]
        out, iters, elapsed = timed_converge(eng, repeats=cfg["repeats"])
        if app == "cc":
            assert out.min() >= 0, "CC label underflow"
        else:
            from lux_tpu.apps import sssp as _s
            reached = int((~_s.unreachable(out)).sum())
            assert reached > g.nv // 100, (
                f"sssp reached only {reached} vertices — vacuous run "
                f"(isolated start?); GTEPS would be meaningless")
    from statistics import median

    from lux_tpu.resilience import screen_outliers
    raw = [g.ne * iters / e / 1e9 for e in elapsed]
    # outlier-screened like bench.py (>3x tunnel collapses discarded,
    # never medianed; no rerun here — scripts run one batch)
    samples, discarded, attempts = screen_outliers(raw, None,
                                                   factor=3.0)
    gteps = median(samples)
    log("run", t, iters=int(iters), elapsed=[round(e, 2) for e in elapsed],
        gteps=round(gteps, 4))
    print(json.dumps({
        "metric": f"{app}_rmat{scale}_np{np_parts}_gteps_per_chip",
        "value": round(gteps, 4), "unit": "GTEPS",
        "vs_baseline": round(gteps, 4),
        "samples": [round(s, 4) for s in samples],
        "attempts": attempts,
        "discarded": [round(d, 4) for d in discarded],
        "np": np_parts,
        "scale": scale, "ne": g.ne, "pair_threshold": pair or None,
        "min_fill": cfg["min_fill"] or None,
        "exchange": exchange, "sparse": bool(cfg["sparse"]),
        "start": (start_vertex if app in ("sssp", "sssp-w") else None),
        "seg": cfg["seg"] or None,
        "telemetry": {"runs": [
            {"repeat": i, "iters": int(iters), "seconds": e}
            for i, e in enumerate(elapsed)], "counters": None},
        # session-calibration fingerprint (lux_tpu/observe.py):
        # check_bench rejects lines from degraded/uncalibrated
        # sessions, so a 10x tunnel collapse is labeled at the source
        "calibration": _calibration(),
        "iters": int(iters)}))


def _calibration():
    from lux_tpu import observe
    try:
        return observe.fingerprint_digest()
    except Exception as e:  # noqa: BLE001 — labeling must not kill the run
        print(f"# calibration probe failed ({type(e).__name__}: {e})",
              file=sys.stderr)
        return None


if __name__ == "__main__":
    main()
