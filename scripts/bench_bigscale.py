"""VERDICT r1 #8: prove the >=0.5B-edge build path on one chip.

Generates RMAT{scale} with the native C++ generator, builds a
multi-part ShardedGraph within host RAM, runs a few timed pagerank
iterations on the real TPU, and prints one JSON line per stage plus
the final GTEPS (driver methodology: loop-dependent fused run, host
fetch fence).

Usage:
  PYTHONPATH=/root/repo:/root/.axon_site \
      python scripts/bench_bigscale.py [scale=25] [np=4] [pair=0] [ni=3] \
                                       [tile_e=0] [exchange=gather] \
                                       [owner_tile_e=256]

pair > 0 additionally runs graph.pair_relabel + pair-lane delivery
(slower host prep; measures the fast path at scale).  tile_e=0 uses
the engine default (512; 128 for the pair residual); bigger values
halve the [P, C, 128] partials temporary but grow per-tile chunk
padding — measured NET WORSE at RMAT26 (PERF_NOTES).
"""

from __future__ import annotations

import json
import resource
import sys
import time


def log(stage, t0, **kw):
    peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
    print(json.dumps(dict(stage=stage, secs=round(time.time() - t0, 1),
                          peak_host_gb=round(peak, 1), **kw)),
          flush=True)
    return time.time()


def main():
    scale = int(sys.argv[1]) if len(sys.argv) > 1 else 25
    np_parts = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    pair = int(sys.argv[3]) if len(sys.argv) > 3 else 0
    ni = int(sys.argv[4]) if len(sys.argv) > 4 else 3
    tile_e = int(sys.argv[5]) if len(sys.argv) > 5 else 0
    exchange = sys.argv[6] if len(sys.argv) > 6 else "gather"
    owner_e = int(sys.argv[7]) if len(sys.argv) > 7 else 0

    import os

    import numpy as np

    from lux_tpu.apps import pagerank
    from lux_tpu.convert import rmat_graph
    from lux_tpu.format import write_lux
    from lux_tpu.graph import Graph, pair_relabel
    from lux_tpu.timing import timed_fused_run

    t = time.time()
    cache = f"/tmp/rmat{scale}_ef16_s0.lux"
    if os.path.exists(cache):
        g = Graph.from_file(cache, use_native=True)
        t = log("load_cached", t, nv=g.nv, ne=g.ne)
    else:
        g = rmat_graph(scale=scale, edge_factor=16, seed=0)
        t = log("generate", t, nv=g.nv, ne=g.ne)
        write_lux(cache, g.row_ptrs, g.col_idx, degrees=g.out_degrees)
        t = log("cache_write", t)

    starts = None
    if pair:
        # pair_relabel is deterministic: cache the relabeled graph +
        # cut points so repeat runs (phase probes, exchange A/Bs) skip
        # the ~20-min billion-edge relabel.  RELAB_VER must be bumped
        # whenever pair_relabel's PARTITIONING changes, or a stale
        # cache silently benchmarks the old cuts; the .starts.npy is
        # written LAST and gates the load, so a crash mid-write never
        # serves a partial cache.  ("" = the round-4 algorithm.)
        RELAB_VER = ""
        rcache = (f"/tmp/rmat{scale}_ef16_s0_relab_np{np_parts}"
                  f"_p{pair}{RELAB_VER}")
        if os.path.exists(rcache + ".starts.npy"):
            g = Graph.from_file(rcache + ".lux", use_native=True)
            starts = np.load(rcache + ".starts.npy")
            t = log("load_relabel_cache", t)
        else:
            g, _perm, starts = pair_relabel(g, np_parts,
                                            pair_threshold=pair,
                                            verbose=True)
            t = log("pair_relabel", t)
            write_lux(rcache + ".lux", g.row_ptrs, g.col_idx,
                      degrees=g.out_degrees)
            np.save(rcache + ".starts.npy", starts)
            t = log("relabel_cache_write", t)

    eng = pagerank.build_engine(g, num_parts=np_parts,
                                pair_threshold=pair or None,
                                starts=starts,
                                tile_e=tile_e or None,
                                exchange=exchange,
                                owner_tile_e=owner_e or None)
    rep = eng.sg.memory_report()
    t = log("build_engine", t,
            vpad=eng.sg.vpad, epad=eng.sg.epad,
            device_gb=round(rep["total_bytes"] / 1e9, 2),
            pair_cov=(round(eng.pairs.stats["coverage"], 3)
                      if eng.pairs is not None else None),
            pair_inflation=(round(eng.pairs.stats["inflation"], 2)
                            if eng.pairs is not None else None),
            owner_stats=(eng.owner.stats if eng.owner is not None
                         else None))

    state, [elapsed] = timed_fused_run(eng, ni)
    out = eng.unpad(state)
    assert np.isfinite(out).all(), "non-finite result"
    gteps = g.ne * ni / elapsed / 1e9
    log("run", t, iters=ni, elapsed=round(elapsed, 2),
        gteps=round(gteps, 4))
    print(json.dumps({
        "metric": f"pagerank_rmat{scale}_np{np_parts}_gteps_per_chip",
        "value": round(gteps, 4), "unit": "GTEPS",
        "vs_baseline": round(gteps, 4), "np": np_parts,
        "scale": scale, "pair_threshold": pair or None,
        "exchange": exchange}))


if __name__ == "__main__":
    main()
