"""Hoisting-proof microbenchmarks on the observatory recipe: every
input is loop-dependent, output is a scalar, work runs K times inside
one jit, the scalar fetch is the fence.  The ground truth for
architecture selection.

Round 12: the fence/loop-dependent-input boilerplate this script
pioneered now lives in ``lux_tpu.timing.loop_bench`` (the calibration
probe of ``lux_tpu/observe.py`` runs the same recipe at pinned
shapes); this script keeps the architecture-selection kernels and
reports median-of-3 per kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from lux_tpu.observe import median_mad
from lux_tpu.timing import loop_bench

K = 10
rng = np.random.default_rng(0)


def bench(name, step, carry, n, unit="elem"):
    samples, _ = loop_bench(step, carry, K, repeats=3)
    dt, mad = median_mad(samples)
    print(f"{name:46s} {dt * 1e3:8.2f} ms  "
          f"({dt / n * 1e9:6.2f} ns/{unit}, mad {mad / n * 1e9:.2f})")


# ---- 1. XLA gather, loop-dependent table --------------------------------
N = 1 << 25
V = 1 << 21
table0 = jnp.asarray(rng.random(V, np.float32))
idx = jnp.asarray(rng.integers(0, V, N).astype(np.int32))


def g_step(c):
    t, i = c
    sv = jnp.sum(jnp.take(t, i, axis=0))
    return sv, (t + sv * 1e-30, i)


bench("xla gather 33.5M (loop-dep)", g_step, (table0, idx), N)

# ---- 2. pallas lane shuffle axis=1 --------------------------------------
R = 1 << 18
x0 = jnp.asarray(rng.random((R, 128), np.float32))
sidx = jnp.asarray(rng.integers(0, 128, (R, 128)).astype(np.int32))


def shuffle_kernel(x_ref, i_ref, o_ref):
    o_ref[:] = jnp.take_along_axis(x_ref[:], i_ref[:], axis=1)


def lane_shuffle(x, i):
    return pl.pallas_call(
        shuffle_kernel,
        grid=(R // 1024,),
        in_specs=[pl.BlockSpec((1024, 128), lambda b: (b, 0),
                               memory_space=pltpu.VMEM)] * 2,
        out_specs=pl.BlockSpec((1024, 128), lambda b: (b, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((R, 128), x.dtype),
    )(x, i)


def s_step(c):
    x, i = c
    sv = jnp.sum(lane_shuffle(x, i)[0])
    return sv, (x + sv * 1e-30, i)


bench("pallas lane shuffle 33.5M (loop-dep)", s_step, (x0, sidx),
      R * 128)

# ---- 3. sublane gather axis=0, M=8 --------------------------------------
sidx8 = jnp.asarray(rng.integers(0, 8, (R, 128)).astype(np.int32))


def sub_kernel(x_ref, i_ref, o_ref):
    o_ref[:] = jnp.take_along_axis(x_ref[:], i_ref[:], axis=0)


def sub_shuffle(x, i):
    return pl.pallas_call(
        sub_kernel,
        grid=(R // 8,),
        in_specs=[pl.BlockSpec((8, 128), lambda b: (b, 0),
                               memory_space=pltpu.VMEM)] * 2,
        out_specs=pl.BlockSpec((8, 128), lambda b: (b, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((R, 128), x.dtype),
    )(x, i)


def sub_step(c):
    x, i = c
    sv = jnp.sum(sub_shuffle(x, i)[0])
    return sv, (x + sv * 1e-30, i)


bench("pallas sublane shuffle M=8 (loop-dep)", sub_step, (x0, sidx8),
      R * 128)

# ---- 4. transpose -------------------------------------------------------
xt0 = jnp.asarray(rng.random((8192, 4096), np.float32))


def t_step(c):
    (x,) = c
    sv = jnp.sum(x.T[0])
    return sv, (x + sv * 1e-30,)


bench("xla transpose 33.5M f32 (loop-dep)", t_step, (xt0,),
      8192 * 4096)

# ---- 5. v3 compare kernel -----------------------------------------------
E = 512
NB = 512
vals0 = jnp.asarray(rng.random((E, NB * 128), np.float32))
rel = jnp.asarray(
    np.sort(rng.integers(0, 128, (E, NB * 128)), axis=0).astype(np.int32))


def v3_kernel(vals_ref, rel_ref, out_ref):
    v = vals_ref[:]
    r = rel_ref[:]
    g = pl.program_id(1)
    for j in range(8):
        wd = g * 8 + j
        row = jnp.sum(jnp.where(r == wd, v, 0.0), axis=0, keepdims=True)
        out_ref[j:j + 1, :] = row


def v3(vals, rel):
    return pl.pallas_call(
        v3_kernel,
        grid=(NB, 16),
        in_specs=[
            pl.BlockSpec((E, 128), lambda b, g: (0, b),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((E, 128), lambda b, g: (0, b),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((8, 128), lambda b, g: (g, b),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((128, NB * 128), vals.dtype),
    )(vals, rel)


def v3_step(c):
    x, r = c
    sv = jnp.sum(v3(x, r)[0])
    return sv, (x + sv * 1e-30, r)


bench("v3 compare reduce 33.5M edges (loop-dep)", v3_step, (vals0, rel),
      E * NB * 128, "edge")

# ---- 6. VPU chained adds ------------------------------------------------
def chain_kernel(x_ref, o_ref):
    v = x_ref[:]
    acc = v
    for _ in range(32):
        acc = acc * 1.0000001 + v
    o_ref[:] = acc


def chain(x):
    return pl.pallas_call(
        chain_kernel,
        grid=(R // 1024,),
        in_specs=[pl.BlockSpec((1024, 128), lambda b: (b, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1024, 128), lambda b: (b, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((R, 128), x.dtype),
    )(x)


def c_step(c):
    (x,) = c
    sv = jnp.sum(chain(x)[0])
    return sv, (x + sv * 1e-30,)


samples, _ = loop_bench(c_step, (x0,), K, repeats=3)
dt, _mad = median_mad(samples)
ops = 64 * R * 128
print(f"{'vpu 64 ops/elem chain (loop-dep)':46s} {dt * 1e3:8.2f} ms  "
      f"({ops / dt / 1e12:6.2f} Tops/s)")
