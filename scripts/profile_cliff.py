"""Find the big-scale cliff: RMAT25/np4 measured 184 ns/edge (no
pair), vs ~18 at scale 23/np1.  Build one graph, time fused runs and
the calibrated phase decomposition across partition counts.

Round 12: the phase split is the observatory's ``decompose``
(lux_tpu/observe.py) — median-of-k + MAD per phase, measured against
the session-scaled scalemodel prediction with drift verdicts, all on
the trusted fence recipe.  The session fingerprint header labels a
degraded tunnel session before any number is read.

Usage: PYTHONPATH=/root/repo:/root/.axon_site \
    python scripts/profile_cliff.py [scale=24] [np list...]
"""

import sys
import time

import numpy as np


def main():
    scale = int(sys.argv[1]) if len(sys.argv) > 1 else 24
    nps = [int(x) for x in sys.argv[2:]] or [1, 4]

    from lux_tpu import observe
    from lux_tpu.apps import pagerank
    from lux_tpu.convert import rmat_graph
    from lux_tpu.timing import timed_fused_run

    fp = observe.calibrate()
    t0 = time.time()
    g = rmat_graph(scale=scale, edge_factor=16, seed=0)
    print(f"# graph {time.time() - t0:.0f}s ne={g.ne}", flush=True)

    decomps = []
    for np_parts in nps:
        t0 = time.time()
        eng = pagerank.build_engine(g, num_parts=np_parts,
                                    exchange="gather")
        print(f"# np={np_parts} build {time.time() - t0:.0f}s "
              f"vpad={eng.sg.vpad} epad={eng.sg.epad}", flush=True)
        state, [elapsed] = timed_fused_run(eng, 3)
        assert np.isfinite(eng.unpad(state)).all()
        per_edge = elapsed / 3 / g.ne * 1e9
        print(f"np={np_parts}: {elapsed / 3 * 1e3:.0f} ms/iter  "
              f"{per_edge:.1f} ns/edge  "
              f"({g.ne * 3 / elapsed / 1e9:.4f} GTEPS)", flush=True)
        decomps.append(observe.decompose(
            eng, f"pagerank_np{np_parts}", iters=2, fingerprint=fp))
        del eng, state
    print(observe.render_report(decomps, fp), flush=True)


if __name__ == "__main__":
    main()
