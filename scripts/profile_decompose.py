"""Decompose the real engine step cost in-context (same vmap/jit shape)."""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu.apps import pagerank
from lux_tpu.convert import rmat_edges
from lux_tpu.graph import Graph

SCALE = 21
REPS = 5

src, dst, nv = rmat_edges(scale=SCALE, edge_factor=16, seed=0)
g = Graph.from_edges(src, dst, nv)
eng = pagerank.build_engine(g, num_parts=1)
sg, lay = eng.sg, eng.tiles
state = eng.init_state()
keys = eng._graph_keys
gargs = eng.graph_args
print(f"nv={sg.nv} ne={sg.ne} vpad={sg.vpad} C={lay.n_chunks} E={lay.E}")


def timeit(name, fn, x0, *rest):
    """Round 15: observatory recipe (lux_tpu.timing.loop_bench) —
    loop-dependent x carry, scalar output, one jit; block_until_ready
    fencing is grep-gated out of scripts/ (lint_lux bench-fence)."""
    from lux_tpu.observe import median_mad
    from lux_tpu.timing import loop_bench

    def step(c):
        x, extra = c
        out = fn(x, *extra)
        sv = jnp.sum(jax.tree.leaves(out)[0].ravel()[:1]).astype(
            jnp.float32)
        return sv, (x + (sv * 1e-30).astype(x.dtype), extra)

    samples, _ = loop_bench(step, (x0, tuple(rest)), REPS, repeats=3)
    dt, _mad = median_mad(samples)
    print(f"{name:46s} {dt * 1e3:8.2f} ms")
    return dt


def make(stage):
    def core(state, *ga):
        gd = dict(zip(keys, ga))
        flat = state.reshape((sg.num_parts * sg.vpad,) + state.shape[2:])

        def part(old_p, gp):
            src_vals = jnp.take(flat, gp["src_slot"], axis=0)
            if stage == "gather":
                return jnp.sum(src_vals, axis=1)
            from lux_tpu.ops.pallas_reduce import chunk_partials_pallas
            partials = chunk_partials_pallas(src_vals, lay.rel_dst.shape
                                             and lay.W, "sum") \
                if False else chunk_partials_pallas(src_vals, lay.W, "sum")
            return partials

        return jax.vmap(part)(state, gd)

    return core


# stage: gather only (in-context shape), cheap consume
def core_gather(state, *ga):
    gd = dict(zip(keys, ga))
    flat = state.reshape((sg.num_parts * sg.vpad,) + state.shape[2:])
    def part(old_p, gp):
        sv = jnp.take(flat, gp["src_slot"], axis=0)
        return jnp.sum(sv, axis=1)
    return jax.vmap(lambda old, gp: part(old, gp))(state, gd)


def core_gather_mat(state, *ga):
    """Materialize the gather output (no reduce)."""
    gd = dict(zip(keys, ga))
    flat = state.reshape((sg.num_parts * sg.vpad,) + state.shape[2:])
    def part(old_p, gp):
        return jnp.take(flat, gp["src_slot"], axis=0)
    return jax.vmap(lambda old, gp: part(old, gp))(state, gd)


def core_gp(state, *ga):
    """gather + pallas partials."""
    from lux_tpu.ops.pallas_reduce import chunk_partials_pallas
    gd = dict(zip(keys, ga))
    flat = state.reshape((sg.num_parts * sg.vpad,) + state.shape[2:])
    def part(old_p, gp):
        sv = jnp.take(flat, gp["src_slot"], axis=0)
        return chunk_partials_pallas(sv, lay.W, "sum")
    return jax.vmap(lambda old, gp: part(old, gp))(state, gd)


def core_gpc(state, *ga):
    """gather + pallas + combine (no apply)."""
    from lux_tpu.ops.pallas_reduce import chunk_partials_pallas
    from lux_tpu.ops.tiled import combine_chunks
    gd = dict(zip(keys, ga))
    flat = state.reshape((sg.num_parts * sg.vpad,) + state.shape[2:])
    def part(old_p, gp):
        sv = jnp.take(flat, gp["src_slot"], axis=0)
        partials = chunk_partials_pallas(sv, lay.W, "sum")
        return combine_chunks(partials, lay, gp["chunk_start"],
                              gp["last_chunk"], "sum")
    return jax.vmap(lambda old, gp: part(old, gp))(state, gd)


timeit("in-context gather (+cheap sum over E)", jax.jit(core_gather),
       state, *gargs)
timeit("in-context gather (materialized)", jax.jit(core_gather_mat),
       state, *gargs)
timeit("gather + pallas partials", jax.jit(core_gp), state, *gargs)
timeit("gather + pallas + combine", jax.jit(core_gpc), state, *gargs)
timeit("full step", jax.jit(eng._step_core), state, *gargs)
