"""Decompose the owner-side step cost on synthetic scale-23-like
geometry: where do the ns/edge go?

Stages (cumulative, all inside one jit, loop-dependent, scalar out):
  gather      scan over P parts: take(state_s, src [C, E])
  +partials   ... + per-chunk compare-reduce (pallas or xla)
  +combine    ... + segmented associative_scan + last-chunk take
  +acc        ... + [P, ntw] accumulate (the full owner contribs)

Usage: PYTHONPATH=/root/repo:/root/.axon_site python \
    scripts/profile_owner2.py [P vpad_m C E method]
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

P = int(sys.argv[1]) if len(sys.argv) > 1 else 4
vpad = int(float(sys.argv[2]) * 1e6) if len(sys.argv) > 2 else 4_930_304
C = int(sys.argv[3]) if len(sys.argv) > 3 else 157_000
E = int(sys.argv[4]) if len(sys.argv) > 4 else 256
method = sys.argv[5] if len(sys.argv) > 5 else "pallas"
W = 128
K = 5
vpad = -(-vpad // W) * W
C = -(-C // 8) * 8
n_tiles = vpad // W
G = P * n_tiles
slots = P * C * E

rng = np.random.default_rng(0)
state = jnp.asarray(rng.random((P, vpad), np.float32))
src = jnp.asarray(rng.integers(0, vpad, (P, C, E)).astype(np.int32))
# ~E edges per tile -> chunk tiles mostly distinct, last_chunk ~ identity
rel = jnp.asarray(rng.integers(0, W, (P, C, E)).astype(np.int8))
cs = jnp.asarray(np.ones((P, C), bool))
lc = jnp.asarray(
    np.minimum(np.arange(G) % C, C - 1).astype(np.int32)[None].repeat(
        P, 0))


def bench(name, per_part):
    # big arrays MUST be jit arguments — closed-over constants hang /
    # 413 the remote compiler (CLAUDE.md)
    def run(s0, src_a, rel_a, cs_a, lc_a):
        def body(_, c):
            acc, t = c
            def step(a, x):
                return a + per_part(x[0], x[1], x[2], x[3], x[4]), None
            out, _ = jax.lax.scan(step, jnp.float32(0),
                                  (t, src_a, rel_a, cs_a, lc_a))
            return (acc + out, t + out * 1e-30)
        return jax.lax.fori_loop(0, K, body,
                                 (jnp.float32(0), s0))[0]

    r = jax.jit(run)
    float(r(state, src, rel, cs, lc))
    t0 = time.perf_counter()
    float(r(state, src, rel, cs, lc))
    dt = (time.perf_counter() - t0) / K
    print(f"{name:10s} {dt * 1e3:8.0f} ms  ({dt / slots * 1e9:5.2f} "
          f"ns/slot)", flush=True)


def g_only(st, sr, rl, cs_r, lc_r):
    return jnp.sum(jnp.take(st, sr, axis=0))


def _partials(st, sr, rl):
    from lux_tpu.ops.pallas_reduce import chunk_partials_pallas
    from lux_tpu.ops.tiled import chunk_partials
    vals = jnp.take(st, sr, axis=0)
    if method == "pallas":
        return chunk_partials_pallas(vals, rl, W, "sum")
    vals = jax.lax.optimization_barrier(vals)
    return chunk_partials(vals, rl, W, "sum")


def g_partials(st, sr, rl, cs_r, lc_r):
    return jnp.sum(_partials(st, sr, rl))


class _Lay:
    needs_scan = True


def g_combine(st, sr, rl, cs_r, lc_r):
    from lux_tpu.ops.tiled import combine_chunks
    p = _partials(st, sr, rl)
    tiles = combine_chunks(p, _Lay, cs_r, lc_r, "sum")
    return jnp.sum(tiles)


print(f"P={P} vpad={vpad} C={C} E={E} G={G} slots={slots/1e6:.0f}M "
      f"method={method}")
bench("gather", g_only)
bench("+partials", g_partials)
bench("+combine", g_combine)
