#!/usr/bin/env python
"""Offline .lux structural checker (round-9 validated loading).

The engines' gathers CLAMP out-of-range indices, so a malformed .lux
file — non-monotone ``row_ptrs``, out-of-range ``col_idx``, a
truncated payload, inconsistent trailing degrees — used to flow
through a run and produce wrong results instead of an error.  This
checker runs ``format.validate_graph`` (the same pass as the apps'
``-validate`` flag) against files at rest, so bad conversions and
torn copies fail HERE, before a multi-hour run:

- header + section sizes vs file length (format.peek_lux layout
  inference — a truncated file can't match any layout);
- ``row_ptrs`` monotone END offsets with ``row_ptrs[-1] == ne``;
- every ``col_idx`` source in ``[0, nv)``;
- trailing degrees (when present) exactly the out-degree histogram.

Round 20 (live graphs): the checker also knows the mutation-log
format (lux_tpu/livegraph.py WAL, format.py ``read_wal_header``).
``.wal`` files on the command line — and a ``<graph>.wal`` sidecar
beside any checked ``.lux`` — are verified at rest: header magic /
version / nv-vs-graph, the CRC CHAIN over every record, monotone
epochs, known record kinds, COMPACT_START/DONE bracket pairing.  A
recoverable torn tail (a crash mid-append) is REPORTED but clean —
``MutationLog.replay`` truncates it deterministically; hard
corruption (typed ``MutationLogError``) fails the file.

Round 24 (self-healing serving): the serving tier's ADMISSION
JOURNAL (lux_tpu/journal.py, LUXJ) gets the same treatment —
``.journal`` files on the command line and the ``<graph>.journal``
sidecar beside any checked ``.lux``: header magic / version /
nv-vs-graph, the CRC chain, known record kinds, qid monotonicity,
and ADMIT/RETIRE pairing at rest.  Torn tail recoverable; a
full-size bad-CRC record is rot (typed ``AdmissionJournalError``)
and fails the file — the MutationLog contract, mirrored.

Usage:
    python scripts/fsck_lux.py [-weighted | -unweighted] FILE...

Weightedness is inferred from the file size by default (pass
-weighted/-unweighted for the ambiguous nv*4 == ne*w case).

Exit status: 0 every file clean, 1 any .lux structural failure,
2 any mutation-log or admission-journal failure (the typed
MutationLogError / AdmissionJournalError class — wrong graph, broken
chain, non-monotone epochs/qids; matches the apps' ``-validate``
exit-2 convention for integrity refusals).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from lux_tpu import format as luxfmt  # noqa: E402


def fsck_wal(path: str, nv: int | None = None) -> str | None:
    """Verify one mutation log at rest (lux_tpu/livegraph.py WAL):
    header, CRC chain, monotone epochs, record kinds, compaction
    bracket pairing — through ``MutationLog.scan``, the SAME pass the
    recovery path replays through, so the checker and recovery can
    never disagree on validity.  Returns None when clean (a
    recoverable torn tail is reported but clean), the failure
    message otherwise."""
    from lux_tpu.livegraph import (MutationLog, MutationLogError,
                                   REC_COMPACT_DONE,
                                   REC_COMPACT_START, REC_DELETE,
                                   REC_EDGE, REC_REWEIGHT)

    try:
        recs, hnv, cap, torn = MutationLog.scan(path, nv=nv)
        _hnv2, _cap2, ver = luxfmt.read_wal_header(path, nv=nv)
    except MutationLogError as e:
        return f"[{e.check}] {e.detail}"
    except luxfmt.GraphFormatError as e:
        return f"[{e.check}] {e.detail}"
    except (OSError, ValueError) as e:
        return f"[wal unreadable] {type(e).__name__}: {e}"
    # scan validates chain/epochs/kinds (a v2 mutation kind inside a
    # v1 header is typed record_kind corruption — the kind set is
    # part of the header version's contract); the bracket pairing is
    # the replay loop's invariant — check it at rest too
    pending = 0
    for r in recs:
        if r.kind == REC_COMPACT_START:
            pending += 1
        elif r.kind == REC_COMPACT_DONE:
            if pending == 0:
                return ("[compact_pair] COMPACT_DONE at epoch "
                        f"{r.epoch} without a preceding "
                        f"COMPACT_START")
            pending -= 1
    edges = sum(1 for r in recs if r.kind == REC_EDGE)
    dels = sum(1 for r in recs if r.kind == REC_DELETE)
    rews = sum(1 for r in recs if r.kind == REC_REWEIGHT)
    epoch = max((r.epoch for r in recs), default=0)
    tornmsg = f" TORN-TAIL={torn}B (recoverable)" if torn else ""
    mut = (f" deletes={dels} reweights={rews}"
           if (dels or rews or ver >= 2) else "")
    print(f"{path}: OK wal v{ver} nv={hnv} capacity={cap} "
          f"records={len(recs)} edges={edges}{mut} epoch={epoch}"
          f"{' open-compaction' if pending else ''}{tornmsg}")
    return None


def fsck_journal(path: str, nv: int | None = None) -> str | None:
    """Verify one admission journal at rest (lux_tpu/journal.py LUXJ
    sidecar, round 24): header, CRC chain, record kinds, qid
    monotonicity, ADMIT/RETIRE pairing (a RETIRE must name an open
    ADMIT, no qid retires twice) — through ``AdmissionJournal.scan``,
    the SAME pass ``FleetServer.recover`` replays through, so the
    checker and recovery can never disagree on validity.  Mirrors
    the MutationLog contract: a strict-prefix torn tail (a crash
    mid-append) is REPORTED but clean — recovery truncates it
    deterministically; a full-size bad-CRC record is rot and fails
    the file.  Returns None when clean, the failure message
    otherwise."""
    from lux_tpu.journal import AdmissionJournal, AdmissionJournalError

    try:
        opens, retired, hnv, torn = AdmissionJournal.scan(path, nv=nv)
        _hnv2, ver = luxfmt.read_journal_header(path, nv=nv)
    except AdmissionJournalError as e:
        return f"[{e.check}] {e.detail}"
    except luxfmt.GraphFormatError as e:
        return f"[{e.check}] {e.detail}"
    except (OSError, ValueError) as e:
        return f"[journal unreadable] {type(e).__name__}: {e}"
    tornmsg = f" TORN-TAIL={torn}B (recoverable)" if torn else ""
    shed = sum(1 for c in retired.values() if c == "shed")
    print(f"{path}: OK journal v{ver} nv={hnv} "
          f"open={len(opens)} retired={len(retired)} shed={shed}"
          f"{tornmsg}")
    return None


def fsck(path: str, weighted: bool | None) -> str | None:
    """Returns None when clean, the failure message otherwise."""
    try:
        hdr, _rp, _ci, _w, degrees = luxfmt.read_lux(
            path, weighted=weighted, validate=True)
    except luxfmt.GraphFormatError as e:
        return f"[{e.check}] {e.detail}"
    except (OSError, ValueError) as e:
        return f"[unreadable] {type(e).__name__}: {e}"
    # the page-aware reorder's .perm sidecar (round 16,
    # lux_tpu/reorder.py): validated whenever present — length nv,
    # bijection of [0, nv) — so a torn or mismatched sidecar fails
    # at rest, not as a silent wrong-answer relabel at load
    perm_state = "no"
    sidecar = luxfmt.perm_sidecar_path(path)
    if os.path.exists(sidecar):
        try:
            luxfmt.read_perm_sidecar(path, nv=hdr.nv)
            perm_state = "yes"
        except luxfmt.GraphFormatError as e:
            return f"[{e.check}] {e.detail}"
        except (OSError, ValueError) as e:
            return f"[perm unreadable] {type(e).__name__}: {e}"
    print(f"{path}: OK nv={hdr.nv} ne={hdr.ne} "
          f"weights={'yes' if hdr.has_weights else 'no'} "
          f"degrees={'yes' if hdr.has_degrees else 'no'} "
          f"perm={perm_state}")
    return None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate .lux graph files (structural invariants "
                    "+ section sizes); see lux_tpu/format.py")
    ap.add_argument("files", nargs="+", metavar="FILE")
    grp = ap.add_mutually_exclusive_group()
    grp.add_argument("-weighted", action="store_true",
                     help="treat the files as weighted (default: "
                          "infer from file size)")
    grp.add_argument("-unweighted", action="store_true",
                     help="treat the files as unweighted")
    args = ap.parse_args(argv)
    weighted = True if args.weighted else \
        False if args.unweighted else None

    bad_lux = bad_wal = checked = 0
    for path in args.files:
        checked += 1
        if path.endswith(luxfmt.WAL_SUFFIX):
            err = fsck_wal(path)
            if err is not None:
                bad_wal += 1
                print(f"ERROR: {path}: {err}", file=sys.stderr)
            continue
        if path.endswith(luxfmt.JOURNAL_SUFFIX):
            err = fsck_journal(path)
            if err is not None:
                bad_wal += 1
                print(f"ERROR: {path}: {err}", file=sys.stderr)
            continue
        err = fsck(path, weighted)
        if err is not None:
            bad_lux += 1
            print(f"ERROR: {path}: {err}", file=sys.stderr)
            continue
        # a mutation-log sidecar beside a clean graph is checked
        # AGAINST that graph (nv must match) — a foreign log fails
        # here, at rest, never as wrong replayed mutations
        wal = luxfmt.wal_sidecar_path(path)
        if os.path.exists(wal):
            checked += 1
            hdr = luxfmt.peek_lux(path, weighted=weighted)
            err = fsck_wal(wal, nv=hdr.nv)
            if err is not None:
                bad_wal += 1
                print(f"ERROR: {wal}: {err}", file=sys.stderr)
        # an admission-journal sidecar (round 24, serving-tier crash
        # recovery) is likewise checked AGAINST its graph: a journal
        # for a different nv fails at rest, never as re-dispatched
        # queries against the wrong graph
        jrn = luxfmt.journal_sidecar_path(path)
        if os.path.exists(jrn):
            checked += 1
            hdr = luxfmt.peek_lux(path, weighted=weighted)
            err = fsck_journal(jrn, nv=hdr.nv)
            if err is not None:
                bad_wal += 1
                print(f"ERROR: {jrn}: {err}", file=sys.stderr)
    bad = bad_lux + bad_wal
    if bad:
        print(f"fsck_lux: {bad} of {checked} file(s) FAILED",
              file=sys.stderr)
        # mutation-log corruption exits 2 (the typed-integrity-
        # refusal convention of the apps' -validate flag)
        return 2 if bad_wal else 1
    print(f"fsck_lux: {checked} file(s) OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
