#!/usr/bin/env python
"""Offline .lux structural checker (round-9 validated loading).

The engines' gathers CLAMP out-of-range indices, so a malformed .lux
file — non-monotone ``row_ptrs``, out-of-range ``col_idx``, a
truncated payload, inconsistent trailing degrees — used to flow
through a run and produce wrong results instead of an error.  This
checker runs ``format.validate_graph`` (the same pass as the apps'
``-validate`` flag) against files at rest, so bad conversions and
torn copies fail HERE, before a multi-hour run:

- header + section sizes vs file length (format.peek_lux layout
  inference — a truncated file can't match any layout);
- ``row_ptrs`` monotone END offsets with ``row_ptrs[-1] == ne``;
- every ``col_idx`` source in ``[0, nv)``;
- trailing degrees (when present) exactly the out-degree histogram.

Usage:
    python scripts/fsck_lux.py [-weighted | -unweighted] FILE...

Weightedness is inferred from the file size by default (pass
-weighted/-unweighted for the ambiguous nv*4 == ne*w case).

Exit status: 0 every file clean, 1 any failure (listed on stderr).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from lux_tpu import format as luxfmt  # noqa: E402


def fsck(path: str, weighted: bool | None) -> str | None:
    """Returns None when clean, the failure message otherwise."""
    try:
        hdr, _rp, _ci, _w, degrees = luxfmt.read_lux(
            path, weighted=weighted, validate=True)
    except luxfmt.GraphFormatError as e:
        return f"[{e.check}] {e.detail}"
    except (OSError, ValueError) as e:
        return f"[unreadable] {type(e).__name__}: {e}"
    # the page-aware reorder's .perm sidecar (round 16,
    # lux_tpu/reorder.py): validated whenever present — length nv,
    # bijection of [0, nv) — so a torn or mismatched sidecar fails
    # at rest, not as a silent wrong-answer relabel at load
    perm_state = "no"
    sidecar = luxfmt.perm_sidecar_path(path)
    if os.path.exists(sidecar):
        try:
            luxfmt.read_perm_sidecar(path, nv=hdr.nv)
            perm_state = "yes"
        except luxfmt.GraphFormatError as e:
            return f"[{e.check}] {e.detail}"
        except (OSError, ValueError) as e:
            return f"[perm unreadable] {type(e).__name__}: {e}"
    print(f"{path}: OK nv={hdr.nv} ne={hdr.ne} "
          f"weights={'yes' if hdr.has_weights else 'no'} "
          f"degrees={'yes' if hdr.has_degrees else 'no'} "
          f"perm={perm_state}")
    return None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate .lux graph files (structural invariants "
                    "+ section sizes); see lux_tpu/format.py")
    ap.add_argument("files", nargs="+", metavar="FILE")
    grp = ap.add_mutually_exclusive_group()
    grp.add_argument("-weighted", action="store_true",
                     help="treat the files as weighted (default: "
                          "infer from file size)")
    grp.add_argument("-unweighted", action="store_true",
                     help="treat the files as unweighted")
    args = ap.parse_args(argv)
    weighted = True if args.weighted else \
        False if args.unweighted else None

    bad = 0
    for path in args.files:
        err = fsck(path, weighted)
        if err is not None:
            bad += 1
            print(f"ERROR: {path}: {err}", file=sys.stderr)
    if bad:
        print(f"fsck_lux: {bad} of {len(args.files)} file(s) FAILED",
              file=sys.stderr)
        return 1
    print(f"fsck_lux: {len(args.files)} file(s) OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
