"""Collective payload x ndev sweep (round 19, the comm observatory).

Replaces ad-hoc comm timing: every point runs on the trusted
microbenchmark recipe (lux_tpu.timing.loop_bench — loop-DEPENDENT
carry, scalar output, one jit, host-fetch fence), via the library
probe the debts and the comms CLI share (observe.calibrate_links'
``_link_step``).  For each sub-mesh size and payload, one collective
launch per loop step; the wire bytes per step follow the ledger's
ring-algorithm convention (lux_tpu/comms.shipped_bytes), so the
printed GB/s figures are the SAME quantity the per-config comm
ledger prices and ``observe.decompose``'s comm verdict divides by.

On the CPU test mesh the figures are host memcpy rates — recorded,
labeled by the session fingerprint, never fed into scalemodel
(observe.calibrate_links feeds measured rates only on canonical
platforms).  On a live multi-chip tunnel this script IS the
ici-bandwidth-probe debt's sweep, one table per mesh size.

Usage: PYTHONPATH=/root/repo:/root/.axon_site \
    python scripts/profile_comm.py [ndevs=2,4,8] [logpayloads=12,16,20]
"""

import sys
from statistics import median

import numpy as np

from lux_tpu import comms
from lux_tpu.observe import _link_step
from lux_tpu.timing import loop_bench

K = 8


def parse_kv(argv):
    out = {}
    for a in argv:
        k, _, v = a.partition("=")
        out[k] = v
    return out


def main(argv=None) -> int:
    import jax

    from lux_tpu.parallel.mesh import make_mesh

    kv = parse_kv(argv if argv is not None else sys.argv[1:])
    avail = len(jax.devices())
    ndevs = [int(x) for x in kv.get("ndevs", "2,4,8").split(",")
             if int(x) <= avail]
    logp = [int(x) for x in kv.get("logpayloads", "12,16,20").split(",")]
    if not ndevs:
        print(f"needs >= 2 devices (have {avail})", file=sys.stderr)
        return 1
    platform = jax.devices()[0].platform
    print(f"platform={platform} devices={avail}  (wire convention: "
          f"lux_tpu/comms.shipped_bytes; K={K} launches/step, "
          f"median of 3)")
    print(f"{'prim':12s} {'ndev':>4s} {'payload/dev':>12s} "
          f"{'s/step':>10s} {'wire B/step':>12s} {'GB/s':>8s}")
    for nd in ndevs:
        mesh = make_mesh(nd)
        tier = comms.mesh_tier(mesh)
        for prim in ("ppermute", "all_to_all"):
            step = _link_step(mesh, prim)
            for lp in logp:
                elems = 1 << lp
                rng = np.random.default_rng(11)
                carry = rng.random(nd * elems, np.float32)
                samples, _ = loop_bench(step, carry, K, repeats=3)
                m = median(samples)
                payload = elems * 4
                wire = comms.shipped_bytes(prim, payload, nd)
                rate = wire / m if m > 0 else 0.0
                print(f"{prim:12s} {nd:>4d} {payload:>10d} B "
                      f"{m:>10.6f} {wire:>12d} "
                      f"{rate / 1e9:>8.3f}  [{tier}]", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
