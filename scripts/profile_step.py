"""Microbenchmark the pull-step components on the live TPU.

Times, for one PageRank iteration at the bench shape (rmat scale S):
  - full fused step
  - src gather alone (jnp.take of flat state by src_slot)
  - pallas chunk partial reduce alone
  - combine_chunks alone
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu.apps import pagerank
from lux_tpu.convert import rmat_edges
from lux_tpu.graph import Graph

SCALE = int(sys.argv[1]) if len(sys.argv) > 1 else 21
EF = 16
REPS = 10


def timeit(name, fn, *args):
    out = fn(*args)  # compile
    jax.block_until_ready(out)
    _ = np.asarray(jax.device_get(jax.tree.leaves(out)[0])).ravel()[:1]
    t0 = time.perf_counter()
    for _ in range(REPS):
        out = fn(*args)
    jax.block_until_ready(out)
    _ = np.asarray(jax.device_get(jax.tree.leaves(out)[0])).ravel()[:1]
    dt = (time.perf_counter() - t0) / REPS
    print(f"{name:32s} {dt * 1e3:9.2f} ms")
    return dt


def main():
    src, dst, nv = rmat_edges(scale=SCALE, edge_factor=EF, seed=0)
    g = Graph.from_edges(src, dst, nv)
    print(f"nv={g.nv} ne={g.ne}")
    eng = pagerank.build_engine(g, num_parts=1)
    lay = eng.tiles
    state = eng.init_state()
    gd = eng.arrays

    step = jax.jit(eng._step_core)
    dt = timeit("full step", step, state, *eng.graph_args)
    print(f"  -> {g.ne / dt / 1e9:.3f} GTEPS")

    flat = state.reshape((-1,) + state.shape[2:])
    src_slot = gd["src_slot"][0]
    gather = jax.jit(lambda f, s: jnp.take(f, s, axis=0))
    timeit("src gather (take)", gather, flat, src_slot)

    vals = gather(flat, src_slot)
    jax.block_until_ready(vals)
    rel = gd["rel_dst"][0]

    from lux_tpu.ops.pallas_reduce import chunk_partials_pallas
    pr = jax.jit(lambda v, r: chunk_partials_pallas(v, r, lay.W, "sum"))
    timeit("pallas chunk partials", pr, vals, rel)

    partials = pr(vals, rel)
    jax.block_until_ready(partials)

    from lux_tpu.ops.tiled import combine_chunks
    cc = jax.jit(lambda p, s, l: combine_chunks(p, lay, s, l, "sum"))
    timeit("combine_chunks", cc, partials, gd["chunk_start"][0],
           gd["last_chunk"][0])

    # gather variants
    timeit("gather bf16", gather, flat.astype(jnp.bfloat16), src_slot)
    srt = jnp.sort(src_slot.ravel()).reshape(src_slot.shape)
    timeit("gather sorted idx", gather, flat, srt)


if __name__ == "__main__":
    main()
