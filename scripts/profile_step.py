"""Microbenchmark the pull-step components on the live TPU.

Times, for one PageRank iteration at the bench shape (rmat scale S):
  - full fused step
  - src gather alone (jnp.take of flat state by src_slot)
  - pallas chunk partial reduce alone
  - combine_chunks alone

Round 15: ported onto the observatory recipe (lux_tpu.timing
.loop_bench — loop-dependent carry, scalar output, one jit, fetch
fence); the old block_until_ready pattern is the PERF_NOTES trap and
is now grep-gated out of scripts/ (lint_lux bench-fence).
"""

from __future__ import annotations

import sys

import jax
import jax.numpy as jnp

from lux_tpu.apps import pagerank
from lux_tpu.convert import rmat_edges
from lux_tpu.graph import Graph
from lux_tpu.observe import median_mad
from lux_tpu.timing import loop_bench

SCALE = int(sys.argv[1]) if len(sys.argv) > 1 else 21
EF = 16
REPS = 10


def timeit(name, fn, x0, *rest):
    """fn(x, *rest) -> array; x rides a loop-dependent carry, the
    other operands stay constant in the carry (jit arguments, not
    baked constants)."""
    def step(c):
        x, extra = c
        out = fn(x, *extra)
        sv = jnp.sum(jax.tree.leaves(out)[0].ravel()[:1]).astype(
            jnp.float32)
        return sv, (x + (sv * 1e-30).astype(x.dtype), extra)

    samples, _ = loop_bench(step, (x0, tuple(rest)), REPS, repeats=3)
    dt, _mad = median_mad(samples)
    print(f"{name:32s} {dt * 1e3:9.2f} ms")
    return dt


def main():
    src, dst, nv = rmat_edges(scale=SCALE, edge_factor=EF, seed=0)
    g = Graph.from_edges(src, dst, nv)
    print(f"nv={g.nv} ne={g.ne}")
    eng = pagerank.build_engine(g, num_parts=1)
    lay = eng.tiles
    state = eng.init_state()
    gd = eng.arrays

    dt = timeit("full step", eng._step_core, state, *eng.graph_args)
    print(f"  -> {g.ne / dt / 1e9:.3f} GTEPS")

    flat = state.reshape((-1,) + state.shape[2:])
    src_slot = gd["src_slot"][0]

    def gather(f, s):
        return jnp.take(f, s, axis=0)

    timeit("src gather (take)", gather, flat, src_slot)

    vals = gather(flat, src_slot)
    rel = gd["rel_dst"][0]

    from lux_tpu.ops.pallas_reduce import chunk_partials_pallas
    timeit("pallas chunk partials",
           lambda v, r: chunk_partials_pallas(v, r, lay.W, "sum"),
           vals, rel)

    partials = chunk_partials_pallas(vals, rel, lay.W, "sum")

    from lux_tpu.ops.tiled import combine_chunks
    timeit("combine_chunks",
           lambda p, s, l: combine_chunks(p, lay, s, l, "sum"),
           partials, gd["chunk_start"][0], gd["last_chunk"][0])

    # gather variants
    timeit("gather bf16", gather, flat.astype(jnp.bfloat16), src_slot)
    srt = jnp.sort(src_slot.ravel()).reshape(src_slot.shape)
    timeit("gather sorted idx", gather, flat, srt)


if __name__ == "__main__":
    main()
