"""Coverage experiment (VERDICT r2 #4, PERF_NOTES round-3 #2): does
re-sorting the degree-sorted TAIL by dominant source tile densify
(src-tile, dst-tile) pairs at the same threshold?

Under a plain degree sort, a tail vertex's in-edges come mostly from
hub tiles, but degree-ordering scatters vertices with the SAME hub
neighborhood across dst tiles.  Grouping tail vertices by their
dominant (most frequent) src tile packs them into shared dst tiles,
raising pair multiplicity.

Pure host computation (no TPU): coverage = fraction of edges whose
(src//128, dst//128) pair holds >= threshold edges.

Usage: python scripts/exp_tailsort.py [scale ef threshold head_tiles]
"""

import sys
import time

import numpy as np

scale = int(sys.argv[1]) if len(sys.argv) > 1 else 21
ef = int(sys.argv[2]) if len(sys.argv) > 2 else 16
threshold = int(sys.argv[3]) if len(sys.argv) > 3 else 16
head_tiles = int(sys.argv[4]) if len(sys.argv) > 4 else 512
W = 128

from lux_tpu.convert import rmat_edges

t0 = time.time()
src, dst, nv = rmat_edges(scale=scale, edge_factor=ef, seed=0)
print(f"graph nv={nv} ne={len(src)} ({time.time() - t0:.0f}s)",
      flush=True)


def coverage(rank):
    s = rank[src] // W
    d = rank[dst] // W
    n_t = -(-nv // W)
    key = s * np.int64(n_t) + d
    _u, inv, cnt = np.unique(key, return_inverse=True,
                             return_counts=True)
    cov = float((cnt[inv] >= threshold).mean())
    rows = cnt[cnt >= threshold]
    # lane-inflation proxy: delivered rows ~ sum over dense pairs of
    # max-multiplicity ~ cnt/unique srcs; report edges/pair instead
    return cov, float(rows.mean()) if len(rows) else 0.0


deg = (np.bincount(src, minlength=nv) + np.bincount(dst, minlength=nv))
by_deg = np.argsort(-deg, kind="stable")
rank0 = np.empty(nv, np.int64)
rank0[by_deg] = np.arange(nv)
cov0, epp0 = coverage(rank0)
print(f"degree sort:      coverage {cov0 * 100:5.1f}%  "
      f"(edges/dense-pair {epp0:.0f})", flush=True)

# tail re-sort: vertices past the head keep only their degree ORDER
# WITHIN groups keyed by dominant src tile (tiles under rank0)
head_v = head_tiles * W
s0 = rank0[src]
d0 = rank0[dst]
tail_mask_e = d0 >= head_v                  # edges into tail vertices
t0 = time.time()
# dominant src tile per tail DST vertex: mode over its in-edges
key = d0[tail_mask_e] * np.int64(1 << 32) + (s0[tail_mask_e] // W)
ks = np.sort(key)
newg = np.ones(len(ks), bool)
newg[1:] = ks[1:] != ks[:-1]
grp = np.cumsum(newg) - 1
grp_cnt = np.bincount(grp)
grp_v = (ks[newg] >> 32).astype(np.int64)         # tail dst vertex
grp_t = (ks[newg] & ((1 << 32) - 1)).astype(np.int64)  # src tile
# per vertex: the src tile with max count
order = np.lexsort((-grp_cnt, grp_v))             # by v, count desc
first = np.ones(len(order), bool)
gv = grp_v[order]
first[1:] = gv[1:] != gv[:-1]
dom_tile = np.full(nv, -1, np.int64)
dom_tile[gv[first]] = grp_t[order][first]
print(f"dominant tiles ({time.time() - t0:.0f}s)", flush=True)

tail_vs = np.arange(head_v, nv)                   # rank0 positions
dom = dom_tile[tail_vs]                           # -1 = no in-edges
# stable sort tail positions by dominant tile (keeps degree order
# within a group); -1 group (no in-edges) sinks to the end
sort_key = np.where(dom < 0, np.int64(1 << 40), dom)
tail_order = tail_vs[np.argsort(sort_key, kind="stable")]
new_pos = np.concatenate([np.arange(head_v), tail_order])
# new_pos[i] = rank0-position placed at new position i; build rank1
rank1 = np.empty(nv, np.int64)
rank1[by_deg[new_pos]] = np.arange(nv)
cov1, epp1 = coverage(rank1)
print(f"tail src-tile sort: coverage {cov1 * 100:5.1f}%  "
      f"(edges/dense-pair {epp1:.0f})", flush=True)
