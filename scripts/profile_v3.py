"""Kernel v3: transposed-chunk compare reduce.

Layout: block = [E sublanes, 128 chunk-lanes]; each lane is one chunk of
E edges, all edges of a chunk target one dst tile of W=128 vertices.
Grid = (NB, WG): wd-group g computes 8 output rows (dst offsets) of the
block's 128 chunks via scalar-broadcast compares — fully static ops.

out[wd, chunk] = sum_e (rel[e, chunk] == wd) * vals[e, chunk]
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

E = 512
W = 128
NB = 512            # blocks of 128 chunks
REPS = 10
NEDGE = NB * 128 * E

rng = np.random.default_rng(0)
vals_h = rng.random((E, NB * 128), np.float32)
rel_h = np.sort(rng.integers(0, W, (E, NB * 128)), axis=0).astype(np.int32)

vals = jnp.asarray(vals_h)
rel = jnp.asarray(rel_h)


def timeit(name, fn, x0, *rest):
    """Round 15: observatory recipe (lux_tpu.timing.loop_bench) —
    loop-dependent x carry, scalar output, one jit; block_until_ready
    fencing is grep-gated out of scripts/ (lint_lux bench-fence)."""
    from lux_tpu.observe import median_mad
    from lux_tpu.timing import loop_bench

    def step(c):
        x, extra = c
        out = fn(x, *extra)
        sv = jnp.sum(jax.tree.leaves(out)[0].ravel()[:1]).astype(
            jnp.float32)
        return sv, (x + (sv * 1e-30).astype(x.dtype), extra)

    samples, _ = loop_bench(step, (x0, tuple(rest)), REPS, repeats=3)
    dt, _mad = median_mad(samples)
    print(f"{name:44s} {dt * 1e3:8.2f} ms  ({NEDGE / dt / 1e9:6.2f} Gedge/s)")
    return dt


def kernel(vals_ref, rel_ref, out_ref, *, wg):
    v = vals_ref[:]
    r = rel_ref[:]
    g = pl.program_id(1)
    for j in range(wg):
        wd = g * wg + j
        row = jnp.sum(jnp.where(r == wd, v, 0.0), axis=0, keepdims=True)
        out_ref[j:j + 1, :] = row


def reduce_v3(vals, rel, wg):
    kern = functools.partial(kernel, wg=wg)
    return pl.pallas_call(
        kern,
        grid=(NB, W // wg),
        in_specs=[
            pl.BlockSpec((E, 128), lambda b, g: (0, b),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((E, 128), lambda b, g: (0, b),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((wg, 128), lambda b, g: (g, b),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((W, NB * 128), vals.dtype),
    )(vals, rel)


for wg in (8, 16, 32):
    f = jax.jit(functools.partial(reduce_v3, wg=wg))
    timeit(f"v3 transposed compare wg={wg}", f, vals, rel)

# sanity check
out = np.asarray(jax.device_get(jax.jit(
    functools.partial(reduce_v3, wg=8))(vals, rel)))
ref = np.zeros((W, 128), np.float32)
for wd in range(W):
    ref[wd] = np.where(rel_h[:, :128] == wd, vals_h[:, :128], 0).sum(axis=0)
print("max err:", np.abs(out[:, :128] - ref).max())
