"""ColFilter at the NetFlix workload shape (BASELINE config #5).

The reference benches collaborative filtering on NetFlix: ~480K users
x ~17.7K items, ~100M weighted ratings on a skewed bipartite graph
(reference README.md:88, col_filter/colfilter_gpu.cu:32-104).  The
dataset itself is not distributable, so this synthesizes the shape
(convert.netflix_like_edges: power-law skew both sides, integer
ratings 1..5, both edge directions) and runs the SGD engine at full
scale: GTEPS by the driver methodology plus the RMSE trajectory —
the factorization must actually LEARN, or the GTEPS line is noise.

Usage:
  PYTHONPATH=/root/repo:/root/.axon_site \
      python scripts/bench_netflix.py [ratings=100000000] [np=4] \
          [pair=16] [ni=3] [repeats=3] [min_fill=-1]

min_fill: -1 (default) = the K-AWARE modeled break-even for K=20
SDDMM rows (~22; ops/pairs.resolve_min_fill), 0 = off, > 0 explicit.
The pair-composed run rides the STREAMED SDDMM delivery
(ops/pairs.pair_partial_dot_streamed) past the 1 GB budget — the
67.7 GB monolithic compile allocation this shape used to hit is the
round-5 ledger entry the streamed path exists to remove; the
build_engine log line records the priced ledger
(memory_report(pairs=...)).
"""

from __future__ import annotations

import json
import os
import resource
import sys
import time

DEFAULTS = dict(ratings=100_000_000, np=4, pair=16, ni=3, repeats=3,
                min_fill=-1)


def log(stage, t0, **kw):
    peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
    print(json.dumps(dict(stage=stage, secs=round(time.time() - t0, 1),
                          peak_host_gb=round(peak, 1), **kw)),
          flush=True)
    return time.time()


def main():
    cfg = dict(DEFAULTS)
    pos = 0
    for a in sys.argv[1:]:
        if "=" in a:
            k, v = a.split("=", 1)
            if k not in cfg:
                raise SystemExit(f"unknown arg {k!r}")
        else:
            k, v = list(DEFAULTS)[pos], a
            pos += 1
        cfg[k] = int(v)
    ratings, np_parts, pair = cfg["ratings"], cfg["np"], cfg["pair"]
    min_fill = ("auto" if cfg["min_fill"] < 0
                else cfg["min_fill"] or None)

    import numpy as np

    from lux_tpu.apps import colfilter
    from lux_tpu.format import write_lux
    from lux_tpu.graph import Graph, pair_relabel
    from lux_tpu.timing import timed_fused_run

    t = time.time()
    cache = f"/tmp/netflix_{ratings}_s0.lux"
    if os.path.exists(cache):
        g = Graph.from_file(cache, use_native=True)
        t = log("load_cached", t, nv=g.nv, ne=g.ne)
    else:
        from lux_tpu.convert import edges_to_csc, netflix_like_edges
        src, dst, w, nv = netflix_like_edges(n_ratings=ratings)
        t = log("generate", t, nv=nv, ne=len(src))
        row_ptrs, col_idx, w_sorted, deg = edges_to_csc(src, dst, nv, w)
        del src, dst, w
        g = Graph(nv=nv, ne=len(col_idx), row_ptrs=row_ptrs,
                  col_idx=col_idx, weights=w_sorted, out_degrees=deg)
        write_lux(cache + ".tmp", row_ptrs, col_idx, w_sorted, deg)
        os.replace(cache + ".tmp", cache)
        t = log("build_csc", t)

    starts = None
    if pair:
        g, _perm, starts = pair_relabel(g, np_parts, pair_threshold=pair,
                                        verbose=True)
        t = log("pair_relabel", t)

    eng = colfilter.build_engine(g, num_parts=np_parts,
                                 pair_threshold=pair or None,
                                 pair_min_fill=min_fill,
                                 starts=starts)
    # the priced fit ledger: pair arrays + STREAMED delivery blocks
    # (not the monolithic [Rp, 128, K] tensor), K = colfilter.K
    rep = eng.sg.memory_report(pairs=eng.pairs, pair_kdim=colfilter.K)
    t = log("build_engine", t, vpad=eng.sg.vpad, epad=eng.sg.epad,
            device_gb=round(rep["total_bytes"] / 1e9, 2),
            pair_gb=round(np_parts * rep["pair_bytes_per_part"] / 1e9,
                          2),
            pair_temp_gb=round(
                np_parts * rep["pair_temp_bytes_per_part"] / 1e9, 2),
            pair_dot_stream=eng.pair_dot_stream,
            min_fill=min_fill,
            pair_cov=(round(eng.pairs.stats["coverage"], 3)
                      if eng.pairs is not None else None),
            pair_inflation=(round(eng.pairs.stats["inflation"], 2)
                            if eng.pairs is not None else None))

    # RMSE trajectory: init -> ni -> 2*ni iterations must descend.
    # (The timed run below re-executes the first ni from scratch.)
    s0 = eng.init_state()
    rmse0 = colfilter.rmse(g, eng.unpad(s0))
    s1 = eng.run(eng.init_state(), cfg["ni"])
    rmse1 = colfilter.rmse(g, eng.unpad(s1))
    s2 = eng.run(s1, cfg["ni"])
    rmse2 = colfilter.rmse(g, eng.unpad(s2))
    t = log("rmse", t, rmse=[round(r, 6) for r in (rmse0, rmse1, rmse2)])
    assert rmse1 < rmse0 and rmse2 < rmse1, "RMSE must decrease"

    state, elapsed = timed_fused_run(eng, cfg["ni"],
                                     repeats=cfg["repeats"])
    assert np.isfinite(eng.unpad(state)).all()
    from statistics import median

    from lux_tpu.resilience import screen_outliers
    raw = [g.ne * cfg["ni"] / e / 1e9 for e in elapsed]
    # outlier-screened like bench.py (>3x tunnel collapses discarded,
    # never medianed; no rerun here — scripts run one batch)
    samples, discarded, attempts = screen_outliers(raw, None,
                                                   factor=3.0)
    gteps = median(samples)
    log("run", t, iters=cfg["ni"],
        elapsed=[round(e, 2) for e in elapsed], gteps=round(gteps, 4))
    print(json.dumps({
        "metric": f"colfilter_netflix{ratings // 1_000_000}m_np"
                  f"{np_parts}_gteps_per_chip",
        "value": round(gteps, 4), "unit": "GTEPS",
        "vs_baseline": round(gteps, 4),
        "samples": [round(s, 4) for s in samples],
        "attempts": attempts,
        "discarded": [round(d, 4) for d in discarded],
        "np": np_parts, "ne": g.ne, "iters": cfg["ni"],
        "pair_threshold": pair or None, "min_fill": min_fill,
        "pair_stream": (eng.pair_dot_stream if pair else None),
        "telemetry": {"runs": [
            {"repeat": i, "iters": cfg["ni"], "seconds": e}
            for i, e in enumerate(elapsed)], "counters": None},
        # session-calibration fingerprint (lux_tpu/observe.py):
        # check_bench rejects lines from degraded/uncalibrated
        # sessions, so a 10x tunnel collapse is labeled at the source
        "calibration": _calibration(),
        "rmse": [round(r, 6) for r in (rmse0, rmse1, rmse2)]}))


def _calibration():
    from lux_tpu import observe
    try:
        return observe.fingerprint_digest()
    except Exception as e:  # noqa: BLE001 — labeling must not kill the run
        print(f"# calibration probe failed ({type(e).__name__}: {e})",
              file=sys.stderr)
        return None


if __name__ == "__main__":
    main()
