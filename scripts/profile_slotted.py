"""Validate the slotted positional layout idea.

1. Host: compute padding inflation of the k-slot layout on RMAT21
   (with and without per-part degree sorting).
2. TPU: microbench the step core it enables:
     vals = take(state, slot_idx [C,k,W]); partials = sum(vals, axis=1)
   fused by XLA, plus a trivial carry kernel on [C, W].
"""

from __future__ import annotations

import functools
import sys

import numpy as np

SCALE = int(sys.argv[1]) if len(sys.argv) > 1 else 21
EF = 16
K = 8
W = 128
REPS = 10

from lux_tpu.convert import rmat_edges
from lux_tpu.graph import Graph

src, dst, nv = rmat_edges(scale=SCALE, edge_factor=EF, seed=0)
g = Graph.from_edges(src, dst, nv)
indeg = g.in_degrees()


def inflation(indeg, sort: bool, k=K, w=W):
    d = np.sort(indeg)[::-1] if sort else np.asarray(indeg)
    ntile = (len(d) + w - 1) // w
    pad = np.zeros(ntile * w, dtype=np.int64)
    pad[:len(d)] = d
    tiles = pad.reshape(ntile, w)
    chunks = np.maximum(1, -(-tiles.max(axis=1) // k))  # per-tile chunks
    slots = int(chunks.sum()) * k * w
    return slots / int(indeg.sum()), int(chunks.sum())


for sort in (False, True):
    inf, C = inflation(indeg, sort)
    print(f"sorted={sort}: slot inflation {inf:.3f}x, chunks={C}")

inf, C = inflation(indeg, True)

# --- TPU microbench -------------------------------------------------------
import jax
import jax.numpy as jnp

C = -(-C // 64) * 64
V = 1 << SCALE
rng = np.random.default_rng(0)
slots = rng.integers(0, V, (C, K, W)).astype(np.int32)
state = rng.random(V, np.float32)

slots_d = jnp.asarray(slots)
state_d = jnp.asarray(state)
ne = g.ne


def timeit(name, fn, x0, *rest):
    """Round 15: observatory recipe (lux_tpu.timing.loop_bench) —
    loop-dependent x carry, scalar output, one jit; the old
    block_until_ready pattern is grep-gated out of scripts/
    (lint_lux bench-fence)."""
    from lux_tpu.observe import median_mad
    from lux_tpu.timing import loop_bench

    def step(c):
        x, extra = c
        out = fn(x, *extra)
        sv = jnp.sum(jax.tree.leaves(out)[0].ravel()[:1]).astype(
            jnp.float32)
        return sv, (x + (sv * 1e-30).astype(x.dtype), extra)

    samples, _ = loop_bench(step, (x0, tuple(rest)), REPS, repeats=3)
    dt, _mad = median_mad(samples)
    print(f"{name:42s} {dt * 1e3:8.2f} ms  ({ne / dt / 1e9:6.2f} GTEPS-equiv)")
    return dt


@jax.jit
def gather_sum(state, slots):
    vals = jnp.take(state, slots, axis=0)        # [C, K, W]
    return jnp.sum(vals, axis=1)                 # [C, W]


timeit("xla gather+sum (fused)", gather_sum, state_d, slots_d)


@jax.jit
def gather_only(state, slots):
    return jnp.take(state, slots, axis=0)


timeit("xla gather only (materialized)", gather_only, state_d, slots_d)

bf = state_d.astype(jnp.bfloat16)
timeit("xla gather+sum bf16 state", gather_sum, bf, slots_d)

# carry kernel over [C, W]
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

starts = (rng.random(C) < 0.3)
starts[0] = True
start_d = jnp.asarray(starts.astype(np.int32).reshape(C, 1))


def _carry_kernel(start_ref, part_ref, out_ref, carry, *, B):
    @pl.when(pl.program_id(0) == 0)
    def _():
        carry[:] = jnp.zeros_like(carry)

    def body(i, _):
        part = part_ref[i, :]
        acc = jnp.where(start_ref[i, 0] == 1, part, carry[0, :] + part)
        carry[0, :] = acc
        out_ref[i, :] = acc
        return 0

    jax.lax.fori_loop(0, B, body, 0, unroll=False)


def carry(partials, start, bc=256):
    kern = functools.partial(_carry_kernel, B=bc)
    return pl.pallas_call(
        kern,
        grid=(C // bc,),
        in_specs=[
            pl.BlockSpec((bc, 1), lambda b: (b, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((bc, W), lambda b: (b, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bc, W), lambda b: (b, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((C, W), partials.dtype),
        scratch_shapes=[pltpu.VMEM((1, W), partials.dtype)],
    )(start, partials)


partials = gather_sum(state_d, slots_d)
f = jax.jit(functools.partial(carry, bc=256))
timeit("pallas carry combine [C,W]", f, partials, start_d)


@jax.jit
def full(state, slots, start):
    return carry(gather_sum(state, slots), start)


timeit("gather+sum+carry (one jit)", full, state_d, slots_d, start_d)
