#!/usr/bin/env python
"""Render a telemetry event-log JSONL into the reference-style table.

The reference's -verbose run prints a per-iteration
loadTime/compTime/updateTime breakdown and little else (reference
sssp_gpu.cu:513-518, pagerank.cc:108-118).  ``-events FILE`` runs
(lux_tpu/cli.py, bench.py) leave a structured JSONL instead
(lux_tpu/telemetry.py); this script renders one back into that
human shape — and audits it while doing so:

- unparseable lines or events without a ``kind`` FAIL the render, as
  do timed events (timed_run/segment/run_done) missing their
  ``seconds``
- per run: segment seconds must not sum PAST the ``run_done``
  elapsed (20% + 50 ms slack) — overshoot means segments overlap or
  double-count, i.e. the fenced slice timings are lying.  Summing
  UNDER the elapsed is expected: the elapsed legitimately includes
  checkpoint saves and host driver time between slices.
- round 9: ``health_trip`` events (the device-side watchdog,
  lux_tpu/health.py) must carry flags/iteration/part/engine — an
  undiagnosable trip fails the audit; ``health`` digests and
  ``checkpoint_fallback`` generation-fallback events are rendered.
- round 11 (elastic recovery, lux_tpu/resilience.py): a
  ``topology_fault`` without its error FAILS, as does a
  ``mesh_shrink`` that does not record a shrinking from/to device
  (or heartbeat-protocol process) count, and a ``replace`` without
  its from/to mesh — a degraded continuation must be fully diagnosed
  in its event trail.  ``budget_reset`` and ``straggler`` render.
- round 12 (observatory, lux_tpu/observe.py): every event now carries
  a monotonic ``tm`` plus ``pid``/``session`` fields, so
  multi-process logs (heartbeat drills append several processes into
  ONE file) merge unambiguously: events are grouped per
  (session, pid) stream before run-splitting, each stream renders
  under its own header, and a stream whose ``tm`` goes BACKWARDS
  fails the audit (two processes' events conflated under one pid
  means the merge key is lying).  ``calibration`` fingerprints and
  ``drift``/``phase_cost`` attribution events render.

- round 17 (serving observability, lux_tpu/metrics.py + serve.py):
  ``metrics_snapshot`` events render the per-kind latency table
  (count / p50 / p99 from the snapshot's log-linear histograms),
  queue depths and the SLO burn record — and are CROSS-AUDITED
  against the raw ``query_done`` stream: a snapshot whose
  ``serve_latency_seconds`` histogram claims MORE retired queries of
  a kind than ``query_done`` events exist in the run FAILS (the
  established contradiction-check pattern), as does a histogram
  whose ``count`` disagrees with the sum of its own bucket cells or
  whose p99 lies under its p50.  ``log_rotate`` markers render, and
  every FILE argument is expanded to its rotated ``.2/.1/live``
  generation set (telemetry.EventLog(rotate_bytes=...)) and
  consumed, oldest first, as ONE stream.

- round 13 (tracing & imbalance attribution, lux_tpu/tracing.py):
  ``iter_stats`` digests carrying per-part counters render a
  per-part table with the imbalance index, and the AUDIT checks that
  the per-part totals SUM to the scalar counter (bitwise — the
  engines reduce the same device-side values part-first) and that
  the index equals max/mean of its own parts; ``heartbeat`` boundary
  syncs and ``flight_dump`` records render; ``-flight FILE`` renders
  a crash-flight-recorder FLIGHT.json postmortem instead of an event
  log.

- round 19 (communication observatory, lux_tpu/comms.py):
  ``comm_ledger`` events render the per-collective byte table
  (prim / launches / payload / wire bytes, branch-tagged for the
  sparse-dense alternatives) and are AUDITED against the
  collective-schedule eqn set they carry: a breakdown whose per-prim
  eqn counts disagree with ``audit_eqns`` FAILS — the ledger and the
  auditor walk the same program registry, so a mismatch means the
  trail lies about the program.  ``link_calibration`` events (the
  measured ICI/DCN bytes/s probes, observe.calibrate_links) render
  with their fed-scalemodel flag.

- round 20 (live graphs, lux_tpu/livegraph.py): the mutation /
  epoch / compaction / cache trail renders (mutation batches, epoch
  advances, peak delta occupancy, compaction fold counts, WAL
  truncate/replay records, epoch-keyed cache hits) and is AUDITED
  for the snapshot-isolation contract: a ``query_done`` whose
  ``answer_epoch`` differs from its admission ``epoch`` is a
  TORN-EPOCH answer and FAILS (as does an epoch-carrying answer
  with no answer_epoch at all); a ``compact_done`` whose generation
  has no preceding ``compact_start`` breaks the WAL compaction
  bracket and FAILS; a ``wal_replay`` that recovers a LOWER epoch
  than the trail already published is a replay-after-crash epoch
  regression (acknowledged mutations vanished) and FAILS — checked
  both in-stream (render_run's ordered walk) and CROSS-process
  (audit_wal_replays pairs wal-carrying publishes with replays on
  the log path across (session, pid) streams, wall-clock ordered:
  the crashing publisher and the recovering process are never the
  same pid).

- round 24 (self-healing fleet, lux_tpu/fleet.py + journal.py): the
  respawn / quarantine / canary trail renders, as do the admission-
  journal truncate/replay records, and the ORDERED audits hold: a
  ``replica_respawn`` without a preceding ``replica_lost`` of that
  name FAILS (a resurrection of a replica that never died), as does
  one without a PASSING ``canary`` since the loss (a replica whose
  oracle probe failed — or never ran — re-entered routing), a
  malformed ``canary``/``replica_quarantine`` record, and a
  recovered re-dispatch (``query_enqueue`` with ``recovered``) with
  no preceding ``journal_replay`` naming the journal it came from.

Usage:
    python scripts/events_summary.py FILE [FILE...]
    python scripts/events_summary.py -flight FLIGHT.json

Exit status: 0 clean, 1 any error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

KNOWN = {"run_start", "config_start", "header", "timed_run",
         "segment", "run_done", "iter_stats", "phases",
         "checkpoint_save", "checkpoint_resume", "checkpoint_fallback",
         "retry", "failure", "budget_lock", "budget_halve",
         "budget_reset", "outlier_discard", "outlier_rerun", "health",
         "health_trip", "topology_fault", "mesh_shrink", "replace",
         "straggler", "calibration", "phase_cost", "drift",
         "debt_collected", "heartbeat", "flight_dump",
         "query_enqueue", "query_start", "query_done", "serve_refill",
         "metrics_snapshot", "log_rotate",
         "replica_up", "replica_lost", "failover", "query_shed",
         "brownout", "comm_ledger", "link_calibration",
         "mutation", "epoch_advance", "compact_start", "compact_done",
         "wal_truncate", "wal_replay", "reseed", "compact_scheduled",
         "mem_sample", "mem_watermark", "mem_pressure",
         "replica_respawn", "replica_quarantine", "canary",
         "journal_truncate", "journal_replay"}

# round 19 (communication observatory, lux_tpu/comms.py): the
# collective primitives a comm_ledger breakdown may name — matching
# comms.COLLECTIVE_PRIMS with psum_scatter normalized away
COMM_PRIMS = {"ppermute", "all_to_all", "reduce_scatter",
              "all_gather", "psum", "pmin", "pmax"}

# a query_shed without these cannot be diagnosed — the serving
# fleet's typed-rejection contract (lux_tpu/fleet.py)
QUERY_SHED_REQUIRED = ("qid", "query_kind", "reason")

# round 21 (mutation algebra, lux_tpu/livegraph.py
# CompactionScheduler): a scheduler compaction must carry the
# economics that justified it, or the decision cannot be audited
COMPACT_SCHEDULED_REQUIRED = ("occupancy", "threshold", "delta_count",
                              "drag_ns", "drag_source", "reason")

# round 22 (memory observatory, lux_tpu/memwatch.py): a mem_pressure
# without these cannot justify the forecast it claims — the
# burn-rate/time-to-full decision contract
MEM_PRESSURE_REQUIRED = ("reason", "live_bytes", "budget_bytes",
                         "burn")

# a failover without these cannot name the transition it claims
FAILOVER_REQUIRED = ("qid", "from_replica", "to_replica")

# a query_done without these cannot account for the query's cost —
# the serving front-end's per-query latency contract (lux_tpu/serve.py)
QUERY_DONE_REQUIRED = ("qid", "query_kind", "iters", "segments",
                       "latency_s")

# a health_trip without these fields cannot be diagnosed — the whole
# point of the watchdog is a NAMED check at a NAMED iteration
HEALTH_TRIP_REQUIRED = ("flags", "iteration", "part", "engine")


def _shrink_pair(ev):
    """(from, to) of a mesh_shrink/replace event — device counts for
    the in-process elastic path, process counts for the heartbeat
    shrink protocol.  None when neither pair is present/numeric."""
    for a, b in (("from_ndev", "to_ndev"), ("from_nproc", "to_nproc")):
        f, t = ev.get(a), ev.get(b)
        if (isinstance(f, int) and not isinstance(f, bool)
                and isinstance(t, int) and not isinstance(t, bool)):
            return f, t
    return None


def rotated_set(path: str) -> list[str]:
    """[path.N, ..., path.1, path] — the oldest-first generation set
    a size-rotated EventLog leaves behind (mirrors
    lux_tpu.telemetry.rotated_paths; re-implemented so this script
    stays stdlib-only)."""
    n = 1
    while os.path.exists(f"{path}.{n}"):
        n += 1
    return [f"{path}.{g}" for g in range(n - 1, 0, -1)] + [path]


def load_events(path: str):
    """Parse one JSONL file.  Returns (events, errors)."""
    events, errs = [], []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError as e:
                errs.append(f"line {i}: unparseable JSON ({e})")
                continue
            if not isinstance(ev, dict) or "kind" not in ev:
                errs.append(f"line {i}: event without a 'kind'")
                continue
            events.append(ev)
    if not events and not errs:
        errs.append("no events found")
    return events, errs


def split_streams(events):
    """Partition a flat (possibly multi-process) event list into
    per-process streams keyed by (session, pid) — the round-12 merge
    key that makes several processes appending into ONE file
    unambiguous.  Events predating the fields (or hand-written logs)
    share the legacy ``None`` stream.  Returns ([(key, events)],
    errors) in first-appearance order; a stream whose monotonic
    ``tm`` DECREASES is an error — one (session, pid) key can only
    belong to one process, whose monotonic clock never goes back."""
    streams, order, errs = {}, [], []
    for ev in events:
        key = None
        if "session" in ev or "pid" in ev:
            key = (ev.get("session"), ev.get("pid"))
        if key not in streams:
            streams[key] = []
            order.append(key)
        streams[key].append(ev)
    for key in order:
        last = None
        for ev in streams[key]:
            tm = ev.get("tm")
            if not isinstance(tm, (int, float)) \
                    or isinstance(tm, bool):
                continue
            if last is not None and tm < last:
                errs.append(
                    f"stream {key}: monotonic tm went backwards "
                    f"({last} -> {tm}) — two processes' events "
                    f"conflated under one (session, pid) key")
            last = tm
    return [(k, streams[k]) for k in order], errs


def split_runs(events):
    """Group one stream into runs at run_start/config_start
    boundaries (one CLI invocation / bench config each); a log
    without boundary events is one anonymous run."""
    runs, cur = [], []
    for ev in events:
        if ev["kind"] in ("run_start", "config_start") and cur:
            runs.append(cur)
            cur = []
        cur.append(ev)
    if cur:
        runs.append(cur)
    return runs


def _fmt_s(x: float) -> str:
    return f"{x:9.3f} s"


def _is_int(x) -> bool:
    return isinstance(x, int) and not isinstance(x, bool)


def _is_num(x) -> bool:
    return isinstance(x, (int, float)) and not isinstance(x, bool) \
        and x == x and abs(x) != float("inf")


def render_parts_table(title, st, out) -> list[str]:
    """Round-13 per-part attribution table of one ``iter_stats``
    digest — and its audit: the per-part totals must SUM to the
    scalar counter bitwise (the engines reduce the very same
    device-side values part-first; a mismatch means the imbalance
    signal is lying about the series it claims to decompose)."""
    errs = []
    metric = "edges" if "parts_edges" in st else \
        "changed" if "parts_changed" in st else None
    if metric is None:
        return errs
    parts = st.get(f"parts_{metric}")
    if (not isinstance(parts, list) or not parts
            or not all(_is_int(p) and p >= 0 for p in parts)):
        errs.append(f"{title}: parts_{metric} must be a non-empty "
                    f"list of ints >= 0, got {parts!r}"[:200])
        return errs
    scalar = st.get(f"{metric}_sum")
    # congruence mod 2^32, not plain equality: each scalar series
    # entry is a device-side uint32 (sum of its per-part row, which
    # wraps past 2^32 edges/iteration) while the host part totals
    # sum exactly — Σ(wrapped) ≡ Σ(exact) (mod 2^32) always holds
    if _is_int(scalar) and (sum(parts) - scalar) % (1 << 32):
        errs.append(
            f"{title}: per-part {metric} sum {sum(parts)} != scalar "
            f"{metric}_sum {scalar} (mod 2^32) — the imbalance "
            f"table contradicts the counters it decomposes")
    imb = st.get("imbalance")
    if imb is not None and (not isinstance(imb, (int, float))
                            or isinstance(imb, bool)):
        errs.append(f"{title}: non-numeric imbalance {imb!r}")
        imb = None
    tot = sum(parts) or 1
    print(f"  per-part {metric} (P={len(parts)}, imbalance "
          f"{imb if imb is not None else 'n/a'} max/mean):", file=out)
    for p, v in enumerate(parts):
        print(f"    part {p}: {v:>12d} ({v / tot * 100:5.1f}%)",
              file=out)
    if imb is not None:
        mean = sum(parts) / len(parts)
        want = max(parts) / mean if mean else None
        if want is not None and abs(imb - want) > 1e-3 * max(1, want):
            errs.append(
                f"{title}: imbalance {imb} contradicts its own "
                f"per-part totals (max/mean = {want:.4f})")
    return errs


def render_metrics_snapshot(title, snap, qdone_by_kind, out,
                            render: bool = True,
                            truncated: bool = False) -> list[str]:
    """Round-17 serving snapshot (lux_tpu/metrics.py): render the
    per-kind latency table, queue depths and SLO burn — and audit it
    against the raw query_done stream: a snapshot claiming MORE
    retired queries of a kind than query_done events exist is lying
    about the stream it aggregates (the contradiction-check
    pattern), as is a histogram whose count disagrees with its own
    bucket cells or whose p99 undercuts its p50.  ``truncated``
    disarms the overcount check ONLY: when rotation dropped
    generations (more rotations than kept generations), the raw
    stream is known-incomplete and a cumulative registry count
    legitimately exceeds the surviving query_done events."""
    errs = []
    step = f" (step {snap['step']})" if "step" in snap else ""
    hists = snap.get("histograms")
    gauges = snap.get("gauges") or []
    counters = snap.get("counters") or []
    if not isinstance(hists, list):
        return [f"{title}: metrics_snapshot without a histograms "
                f"list: {snap!r}"[:200]]
    lat = [h for h in hists
           if h.get("name") == "serve_latency_seconds"]
    if lat and render:
        print(f"  metrics snapshot{step} — per-kind latency:",
              file=out)
    for h in lat:
        kind = (h.get("labels") or {}).get("kind", "?")
        count, buckets = h.get("count"), h.get("buckets")
        if not _is_int(count) or count < 0:
            errs.append(f"{title}: snapshot latency histogram "
                        f"[{kind}] non-int count {count!r}")
            continue
        if isinstance(buckets, dict):
            cells = sum(int(v) for v in buckets.values())
            if cells != count:
                errs.append(
                    f"{title}: snapshot latency histogram [{kind}] "
                    f"count {count} != sum of its bucket cells "
                    f"{cells} — the histogram contradicts itself")
        seen = qdone_by_kind.get(kind, 0)
        if count > seen and not truncated:
            errs.append(
                f"{title}: metrics snapshot claims {count} retired "
                f"{kind!r} queries but only {seen} query_done "
                f"event(s) exist — the snapshot contradicts the raw "
                f"per-query stream")
        p50, p99 = h.get("p50"), h.get("p99")
        if _is_num(p50) and _is_num(p99) and p99 < p50:
            errs.append(f"{title}: snapshot latency histogram "
                        f"[{kind}] p99 {p99} < p50 {p50}")
        if render:
            p50s = "-" if not _is_num(p50) else f"{p50 * 1e3:8.1f}ms"
            p99s = "-" if not _is_num(p99) else f"{p99 * 1e3:8.1f}ms"
            print(f"    {kind:12s} count {count:>5d}  "
                  f"p50 {p50s:>10s}  p99 {p99s:>10s}", file=out)
    def _gval(g, what):
        """Numeric gauge/counter value or an audit error (a
        malformed trail must FAIL the render, never crash it)."""
        v = g.get("value")
        if _is_num(v):
            return v
        errs.append(f"{title}: snapshot {what} "
                    f"[{(g.get('labels') or {}).get('kind', '?')}] "
                    f"non-numeric value {v!r}")
        return None

    depths = [g for g in gauges
              if g.get("name") == "serve_queue_depth"]
    dvals = [(g, _gval(g, "queue-depth gauge")) for g in depths]
    if depths and render:
        cells = "  ".join(
            f"{(g.get('labels') or {}).get('kind', '?')}="
            f"{'?' if v is None else f'{v:g}'}" for g, v in dvals)
        print(f"    queue depth: {cells}", file=out)
    burn = [g for g in gauges
            if g.get("name") == "serve_slo_burn_rate"]
    slo_counts = {}
    for c in counters:
        if c.get("name") in ("serve_slo_good_total",
                             "serve_slo_violation_total"):
            kind = (c.get("labels") or {}).get("kind", "?")
            key = "good" if c["name"].endswith("good_total") \
                else "bad"
            slo_counts.setdefault(kind, {})[key] = c.get("value")
    bvals = [(g, _gval(g, "burn-rate gauge")) for g in burn]
    if (burn or slo_counts) and render:
        def num(v):
            return f"{v:g}" if _is_num(v) else "?"

        cells = []
        for g, v in bvals:
            kind = (g.get("labels") or {}).get("kind", "?")
            gb = slo_counts.get(kind, {})
            cells.append(f"{kind}: burn {num(v)} "
                         f"(good {num(gb.get('good', 0))} / viol "
                         f"{num(gb.get('bad', 0))})")
        print(f"    SLO burn: {'; '.join(cells)}", file=out)
    return errs


def render_comm_ledger(title, cl, out) -> list[str]:
    """Round-19 comm-ledger event (lux_tpu/comms.py via
    observe.decompose / python -m lux_tpu.comms -events): render the
    per-collective table and AUDIT it — the breakdown's per-prim eqn
    counts must match the ``audit_eqns`` set the collective-schedule
    auditor sees on the same program (the two subsystems walk one
    registry, so a published mismatch means the trail is lying about
    the program), shipped bytes must be non-negative ints, and prims
    must be known collectives."""
    errs = []
    where = f"{title}/{cl.get('app', cl.get('config', '?'))}"
    pcs = cl.get("per_collective")
    audit_eqns = cl.get("audit_eqns")
    if not isinstance(pcs, list) or not isinstance(audit_eqns, dict):
        return [f"{where}: comm_ledger without its per_collective "
                f"list + audit_eqns dict: {cl!r}"[:200]]
    seen: dict = {}
    for g in pcs:
        if not isinstance(g, dict):
            errs.append(f"{where}: malformed comm_ledger group "
                        f"{g!r}"[:160])
            continue
        prim = g.get("prim")
        if prim not in COMM_PRIMS:
            errs.append(f"{where}: comm_ledger names unknown "
                        f"collective {prim!r}")
            continue
        ec = g.get("eqns")
        sb = g.get("shipped_bytes")
        if not _is_int(ec) or ec < 1:
            errs.append(f"{where}: comm_ledger [{prim}] eqns={ec!r} "
                        f"must be an int >= 1")
            continue
        if not _is_int(sb) or sb < 0:
            errs.append(f"{where}: comm_ledger [{prim}] "
                        f"shipped_bytes={sb!r} must be an int >= 0")
        seen[prim] = seen.get(prim, 0) + ec
    want = {k: v for k, v in audit_eqns.items() if _is_int(v) and v}
    if seen != want:
        errs.append(
            f"{where}: comm_ledger breakdown counts {seen} contradict "
            f"the audit collective-schedule eqn set {want} — ledger "
            f"and auditor walk ONE registry, so the published trail "
            f"is lying about the program")
    bpi = cl.get("bytes_per_iter")
    print(f"  comm ledger [{cl.get('app', cl.get('config', '?'))}]: "
          f"{bpi} B/iter over {cl.get('messages')} collective(s) "
          f"[{cl.get('tier')}] verdict={cl.get('verdict', '-')}",
          file=out)
    for g in pcs:
        if isinstance(g, dict) and g.get("prim") in COMM_PRIMS:
            br = f" ({g['branch']})" if g.get("branch") else ""
            print(f"    {g['prim']:14s}{br} x{g.get('count')}  "
                  f"payload {g.get('payload_bytes')} B  wire "
                  f"{g.get('shipped_bytes')} B", file=out)
    return errs


def render_run(run, out=sys.stdout) -> list[str]:
    """Print one run's table; returns audit errors."""
    errs = []
    by = {}
    for ev in run:
        by.setdefault(ev["kind"], []).append(ev)

    def seconds_of(kind):
        """[seconds] of every ``kind`` event; missing/non-numeric
        seconds become audit errors instead of a crash."""
        vals = []
        for ev in by.get(kind, []):
            s = ev.get("seconds")
            if isinstance(s, (int, float)) and not isinstance(s, bool):
                vals.append(s)
            else:
                errs.append(f"{kind} event without numeric "
                            f"'seconds': {ev!r}"[:160])
        return vals

    head = (by.get("run_start") or by.get("config_start") or [{}])[0]
    title = head.get("app") or head.get("config") or "run"
    print(f"== {title} ==", file=out)
    for h in by.get("header", []):
        mem = h.get("memory", {})
        per_part = mem.get("edge_bytes_per_part", 0) \
            + mem.get("vertex_bytes_per_part", 0)
        print(f"  graph: nv={h.get('nv')} ne={h.get('ne')} "
              f"parts={h.get('num_parts')} "
              f"(~{per_part / 1e6:.1f} MB/part HBM, "
              f"{mem.get('total_bytes', 0) / 1e6:.1f} MB total)",
              file=out)

    # the reference's per-iteration loadTime/compTime/updateTime
    # table, from the CLI's -phases instrumented iterations
    META = ("frontier", "bucket", "advances")   # counters, not times
    for ph in by.get("phases", []):
        print("  per-iteration phases (reference loadTime/compTime/"
              "updateTime analogue):", file=out)
        for i, t in enumerate(ph.get("report", [])):
            cells = "  ".join(
                (f"{k}={v:g}" if k in META
                 else f"{k}={v * 1e3:8.2f}ms") for k, v in t.items()
                if isinstance(v, (int, float)))
            print(f"    iter {i}: {cells}", file=out)

    for st in by.get("iter_stats", []):
        eng = st.get("engine")
        # a zero-iteration digest carries only kind/iters/truncated
        if eng == "push" and "frontier_max" in st:
            print(f"  counters (push): {st.get('iters')} iters, "
                  f"frontier max {st.get('frontier_max')} "
                  f"sum {st.get('frontier_sum')}, "
                  f"edges relaxed {st.get('edges_sum')}", file=out)
        elif eng == "pull" and "residual_first" in st:
            print(f"  counters (pull): {st.get('iters')} iters, "
                  f"residual {st['residual_first']:.3e} -> "
                  f"{st['residual_last']:.3e}, "
                  f"changed_last {st.get('changed_last')}", file=out)
        else:
            print(f"  counters ({eng}): {st.get('iters')} iters",
                  file=out)
        if st.get("truncated"):
            print("    WARNING: counter buffers truncated", file=out)
        errs += render_parts_table(title, st, out)

    timed = by.get("timed_run", [])
    if timed:
        secs = seconds_of("timed_run")
        print(f"  timed runs: {len(timed)}  "
              f"[{' '.join(f'{s:.3f}s' for s in secs)}]", file=out)

    segs = by.get("segment", [])
    seg_s = sum(seconds_of("segment"))
    if segs:
        print(f"  segments: {len(segs)}  compTime {_fmt_s(seg_s)}",
              file=out)
    saves = by.get("checkpoint_save", [])
    if saves:
        print(f"  checkpoint saves: {len(saves)}  updateTime "
              f"{_fmt_s(sum(s.get('seconds', 0) for s in saves))}",
              file=out)
    for r in by.get("checkpoint_resume", []):
        print(f"  resumed from iter {r.get('iter')} "
              f"({r.get('path')})", file=out)
    for r in by.get("checkpoint_fallback", []):
        print(f"  CHECKPOINT FALLBACK: {r.get('path')} corrupt -> "
              f"{r.get('fallback')} ({r.get('error')})", file=out)
    for h in by.get("health", []):
        flags = h.get("flags")
        if (not isinstance(flags, list)
                or not all(isinstance(f, str) for f in flags)
                or not isinstance(h.get("tripped"), bool)):
            errs.append(f"{title}: malformed health event (flags "
                        f"must be a list of names, tripped a bool): "
                        f"{h!r}"[:200])
            continue
        print(f"  watchdog ({h.get('engine')}): "
              f"{'TRIPPED ' + '+'.join(flags) if h['tripped'] else 'clean'}"
              f" over {h.get('iters')} iters", file=out)
    for h in by.get("health_trip", []):
        missing = [k for k in HEALTH_TRIP_REQUIRED if k not in h]
        if missing:
            errs.append(f"{title}: health_trip event missing "
                        f"{missing} — an undiagnosable trip: {h!r}"[:200])
            continue
        print(f"  WATCHDOG TRIPPED ({h['engine']}): "
              f"{'+'.join(h['flags'])} at iteration {h['iteration']}"
              f", part {h['part']} ({h.get('where', '?')})", file=out)
    for tf in by.get("topology_fault", []):
        if not tf.get("error"):
            errs.append(f"{title}: topology_fault event without an "
                        f"'error': {tf!r}"[:200])
            continue
        print(f"  TOPOLOGY FAULT: {tf['error']} (attempt "
              f"{tf.get('attempt')}, "
              f"{'re-placed' if tf.get('handled') else 'UNHANDLED'})",
              file=out)
    for ms in by.get("mesh_shrink", []):
        pair = _shrink_pair(ms)
        if pair is None or pair[1] >= pair[0]:
            errs.append(f"{title}: mesh_shrink event must record a "
                        f"SHRINKING from/to device (or process) "
                        f"count: {ms!r}"[:200])
            continue
        unit = "process" if "from_nproc" in ms else "device"
        # in-process shrinks name the LOST devices; the heartbeat
        # protocol names the SURVIVORS — never conflate the two
        who = (f"lost {ms['lost']}" if "lost" in ms
               else f"survivors {ms.get('survivors')}")
        print(f"  MESH SHRINK: {pair[0]} -> {pair[1]} {unit}s "
              f"({who}, parts {ms.get('parts', '?')})", file=out)
    for rp in by.get("replace", []):
        pair = _shrink_pair(rp)
        if pair is None:
            errs.append(f"{title}: replace event without numeric "
                        f"from_ndev/to_ndev: {rp!r}"[:200])
            continue
        print(f"  re-placement: checkpoint from a {pair[0]}-device "
              f"mesh resumed on {pair[1]} (iter {rp.get('iter')}, "
              f"{rp.get('path')})", file=out)
    for br in by.get("budget_reset", []):
        print(f"  budget rate reset ({br.get('reason') or '?'}; "
              f"was locked at {br.get('locked')})", file=out)
    for sgl in by.get("straggler", []):
        print(f"  straggler: peer(s) {sgl.get('peers')} "
              f"{sgl.get('behind_s')}s behind at boundary "
              f"{sgl.get('boundary')}", file=out)
    hbs = by.get("heartbeat", [])
    if hbs:
        last = max((h.get("boundary", 0) for h in hbs), default=0)
        print(f"  heartbeats: {len(hbs)} boundary sync(s), last "
              f"boundary {last}", file=out)
    for fd in by.get("flight_dump", []):
        print(f"  FLIGHT RECORDER: {fd.get('events')} event(s) "
              f"dumped to {fd.get('path')} "
              f"[{fd.get('classification')}] {fd.get('reason')}",
              file=out)
    for r in by.get("retry", []):
        print(f"  retry: attempt {r.get('attempt')} "
              f"{r.get('error')} [{r.get('classification')}] "
              f"backoff {r.get('backoff_s')}s", file=out)
    for r in by.get("failure", []):
        print(f"  FAILURE: {r.get('error')} "
              f"[{r.get('classification')}]", file=out)
    for d in by.get("outlier_discard", []):
        print(f"  outlier discarded: {d.get('sample')} "
              f"(median {d.get('median')})", file=out)
    for c in by.get("calibration", []):
        probe = c.get("probe") or {}
        print(f"  calibration: session {c.get('session')} "
              f"{c.get('platform')}/{c.get('backend')} "
              f"ndev={c.get('ndev')} grade={c.get('grade')} "
              f"(gather {probe.get('gather_small_ns')} ns/elem, "
              f"deviation {c.get('deviation')}x)", file=out)
    pc = by.get("phase_cost", [])
    if pc:
        apps = sorted({p.get("app") for p in pc})
        print(f"  phase attribution: {len(pc)} phase(s) over "
              f"{', '.join(str(a) for a in apps)}", file=out)
    for d in by.get("drift", []):
        print(f"  DRIFT ({d.get('app')}/{d.get('phase')}): "
              f"{d.get('verdict')} — measured {d.get('measured_s')}s "
              f"vs model {d.get('predicted_s')}s "
              f"({d.get('ratio')}x)", file=out)
    for d in by.get("debt_collected", []):
        print(f"  carried debt collected: {d.get('debt')}", file=out)
    for lc in by.get("link_calibration", []):
        print(f"  link calibration [{lc.get('tier')}]: "
              f"{lc.get('bytes_per_s')} B/s ({lc.get('prim')}, "
              f"payload {lc.get('payload_bytes')} B, ndev "
              f"{lc.get('ndev')}"
              f"{', fed scalemodel' if lc.get('fed_scalemodel') else ''})",
              file=out)
    for cl in by.get("comm_ledger", []):
        errs += render_comm_ledger(title, cl, out)

    # serving front-end (round 14, lux_tpu/serve.py): per-query
    # latency accounting.  AUDIT: every query_done carries its
    # qid/kind/iters/segments/latency, latencies are finite and >=
    # the query's wait (enqueue -> column), and every retired qid was
    # enqueued — a served answer with no matching request means the
    # per-query trail is lying.
    qdone = by.get("query_done", [])
    if qdone:
        enq = {e.get("qid") for e in by.get("query_enqueue", [])}
        lats = []
        for q in qdone:
            missing = [k for k in QUERY_DONE_REQUIRED if k not in q]
            if missing:
                errs.append(f"{title}: query_done missing {missing}: "
                            f"{q!r}"[:200])
                continue
            lat, wait = q["latency_s"], q.get("wait_s", 0)
            if not _is_num(lat) or lat < 0:
                errs.append(f"{title}: query_done qid={q['qid']} "
                            f"non-finite latency {lat!r}")
                continue
            if _is_num(wait) and lat + 1e-9 < wait:
                errs.append(f"{title}: query_done qid={q['qid']} "
                            f"latency {lat} < wait {wait} — the "
                            f"per-query clock is inconsistent")
            # no `if enq` guard: a trail with ZERO enqueue events is
            # the maximally-broken case and must fail loudest
            if q["qid"] not in enq:
                errs.append(f"{title}: query_done qid={q['qid']} was "
                            f"never enqueued")
            lats.append(lat)
        if lats:
            lats.sort()
            kinds = {}
            for q in qdone:
                k = q.get("query_kind", "?")
                kinds[k] = kinds.get(k, 0) + 1
            mix = ", ".join(f"{k} x{n}" for k, n in sorted(kinds.items()))
            print(f"  queries served: {len(qdone)} ({mix})  latency "
                  f"p50 {_fmt_s(lats[len(lats) // 2])} max "
                  f"{_fmt_s(lats[-1])}", file=out)
        refills = by.get("serve_refill", [])
        live = sum(1 for r in refills
                   if r.get("retired", 0) and r.get("filled", 0))
        if refills:
            print(f"  continuous batching: {len(refills)} refill "
                  f"boundary(ies), {live} retire+refill", file=out)

    # round 18 (serving fleet, lux_tpu/fleet.py): the resilience
    # trail — replica membership, failovers, sheds, brownout — and
    # its exactly-once / typed-rejection audits:
    # - a qid that retires TWICE violates exactly-once retirement
    # - a query_done for a SHED qid means a rejected query ran anyway
    # - a replica_lost with in-flight queries but no failover (or
    #   shed) accounting for them is an UNDIAGNOSED loss
    done_count = {}
    for q in by.get("query_done", []):
        if "qid" in q:
            done_count[q["qid"]] = done_count.get(q["qid"], 0) + 1
    # round 24: a journal re-dispatch (query_enqueue recovered=true)
    # legitimately RE-ANSWERS a query whose pre-crash answer was
    # computed but never acknowledged — the crash interposed between
    # the runner's retire and the fleet's delivery, so the client
    # saw it at most once.  ONE extra query_done per recovered qid
    # is that at-least-once-compute seam; a third is still a dup.
    recovered_qids = {e.get("qid")
                      for e in by.get("query_enqueue", [])
                      if e.get("recovered")}
    for qid, n in sorted(done_count.items()):
        if n > 1 and not (qid in recovered_qids and n == 2):
            errs.append(f"{title}: qid={qid} retired {n} times — "
                        f"exactly-once retirement violated")
    sheds = []          # WELL-FORMED sheds only: a malformed record
    shed_qids = set()   # must not vouch for anything below
    for s in by.get("query_shed", []):
        missing = [k for k in QUERY_SHED_REQUIRED if k not in s]
        if missing:
            errs.append(f"{title}: query_shed missing {missing} — "
                        f"an unaccountable rejection: {s!r}"[:200])
            continue
        sheds.append(s)
        shed_qids.add(s["qid"])
    for qid in sorted(shed_qids & set(done_count)):
        errs.append(f"{title}: query_done for qid={qid} which was "
                    f"SHED — a rejected query must never retire")
    fos = []
    for f in by.get("failover", []):
        missing = [k for k in FAILOVER_REQUIRED if k not in f]
        if missing:
            errs.append(f"{title}: failover missing {missing} — an "
                        f"unaccountable transition: {f!r}"[:200])
            continue
        fos.append(f)
    ups = by.get("replica_up", [])
    losts = by.get("replica_lost", [])
    for rl in losts:
        if not rl.get("replica") or not rl.get("error"):
            errs.append(f"{title}: replica_lost without its "
                        f"replica/error: {rl!r}"[:200])
            continue
        inflight = rl.get("inflight")
        if _is_int(inflight) and inflight > 0:
            # only failovers FROM this replica, or sheds with the
            # failover-path reasons (no_capacity / retries), diagnose
            # a loss — an unrelated admission-time shed (brownout,
            # quota, queue_full, deadline) must not vouch for
            # vanished in-flight queries
            accounted = any(f.get("from_replica") == rl["replica"]
                            for f in fos) \
                or any(s.get("reason") in ("no_capacity", "retries")
                       for s in sheds)
            if not accounted:
                errs.append(
                    f"{title}: replica_lost {rl['replica']!r} with "
                    f"{inflight} in-flight query(ies) but no "
                    f"failover or shed accounts for them — an "
                    f"undiagnosed loss")
    if ups or losts:
        lost_names = sorted(str(rl.get("replica")) for rl in losts)
        print(f"  replicas: {len(ups)} up, {len(losts)} lost"
              + (f" ({', '.join(lost_names)})" if lost_names else ""),
              file=out)
    if fos:
        qids = sorted({f.get("qid") for f in fos})
        print(f"  failovers: {len(fos)} re-dispatch(es) over "
              f"{len(qids)} qid(s)", file=out)
    if sheds:
        reasons = {}
        for s in sheds:
            r = s.get("reason", "?")
            reasons[r] = reasons.get(r, 0) + 1
        mix = ", ".join(f"{r} x{n}"
                        for r, n in sorted(reasons.items()))
        print(f"  shed: {len(sheds)} query(ies) ({mix})", file=out)
    for b in by.get("brownout", []):
        print(f"  BROWNOUT level={b.get('level')} capacity "
              f"{b.get('capacity_frac')} min_priority="
              f"{b.get('min_priority')}", file=out)

    # round 20 (live graphs, lux_tpu/livegraph.py): the mutation /
    # epoch / compaction / cache trail and its audits:
    # - TORN-EPOCH: a query_done carrying an admission ``epoch`` must
    #   carry ``answer_epoch`` EQUAL to it — the answer was computed
    #   at a different epoch than the query pinned at admission,
    #   which is a torn read published as an answer (serve.py stamps
    #   answer_epoch from the serving MECHANISM: the column's delta
    #   mask / the engine's base generation — never from the request)
    # - a compact_done whose generation has no preceding
    #   compact_start breaks the WAL compaction bracket
    # - a wal_replay that comes up at a LOWER epoch than the trail
    #   already published is a replay-after-crash epoch REGRESSION:
    #   acknowledged mutations vanished
    # round 21 (mutation algebra): two more ordered audits —
    # - a ``reseed`` is the anti-monotone revalidation of a deletion
    #   or weight update; one appearing BEFORE any delete/reweight
    #   mutation publish on its log (or a wal_replay, which can
    #   restore pending anti ops from a crashed publisher) re-seeded
    #   state that had nothing to re-seed — the trail is incoherent
    # - a ``compact_scheduled`` missing its economics fields
    #   (COMPACT_SCHEDULED_REQUIRED) is a fold that cannot justify
    #   itself — the scheduler's decision contract
    muts = by.get("mutation", [])
    for q in qdone:
        if "epoch" not in q:
            continue
        if "answer_epoch" not in q:
            errs.append(f"{title}: query_done qid={q.get('qid')} "
                        f"carries admission epoch {q['epoch']} but "
                        f"no answer_epoch — the live-serving answer "
                        f"cannot prove it was computed at its "
                        f"admission epoch")
        elif q["answer_epoch"] != q["epoch"]:
            errs.append(f"{title}: TORN-EPOCH answer qid="
                        f"{q.get('qid')}: admitted at epoch "
                        f"{q['epoch']} but answered at epoch "
                        f"{q['answer_epoch']} — snapshot isolation "
                        f"violated")
    # order-sensitive audits walk the raw run, not the by-kind map
    pending_gens, compacts_done = set(), 0
    # per-WAL-path epoch high-water marks (same pairing rule as the
    # cross-process audit_wal_replays): a replay of log B must never
    # be judged against epochs published to log A in the same run —
    # two LiveGraphs beside each other is a clean trail, not a
    # regression.  No-WAL publishes key on None and no replay can
    # ever pair with them (a replay always carries its path).
    max_epoch_seen: dict = {}
    # wal keys that have seen a delete/reweight publish (or a
    # wal_replay, which can restore a crashed publisher's pending
    # anti ops) — the only trails a reseed may follow
    anti_published: set = set()
    # round 24 (self-healing fleet, lux_tpu/fleet.py + journal.py):
    # ordered respawn-trail state — a resurrection must FOLLOW a
    # loss of that name AND a passing canary (routing a replica
    # whose canary failed — or that never ran one — is serving wrong
    # or unproven answers), and a recovered re-dispatch
    # (query_enqueue recovered=true) must follow its journal_replay
    heal_lost: set = set()
    canary_passed: set = set()
    saw_journal_replay = False
    # round 22 (memory observatory, lux_tpu/memwatch.py): replica
    # keys (None = unlabelled trail) that have published at least one
    # occupancy sample.  A mem_pressure — or a query_shed with the
    # typed ``memory`` reason — with NO preceding mem_sample /
    # mem_watermark anywhere in the run is a forecast with no
    # evidence: the decision claims a burn rate no sample fed
    mem_sampled: set = set()
    mem_peak, mem_pressures = 0, 0

    def _saw_epoch(path, e):
        max_epoch_seen[path] = max(max_epoch_seen.get(path, 0), e)

    for ev in run:
        k = ev["kind"]
        if k == "mutation":
            e = ev.get("epoch")
            if _is_int(e):
                _saw_epoch(ev.get("wal"), e)
            # ``op`` is round 21; its absence means an append-only
            # round-20 publisher — never an anti op
            if ev.get("op") in ("delete", "reweight"):
                anti_published.add(ev.get("wal"))
        elif k == "reseed":
            if ev.get("wal") not in anti_published:
                errs.append(f"{title}: reseed at epoch "
                            f"{ev.get('epoch')} without any preceding "
                            f"delete/reweight publish (or wal_replay) "
                            f"on its log — anti-monotone revalidation "
                            f"with nothing to revalidate")
        elif k in ("mem_sample", "mem_watermark"):
            mem_sampled.add(ev.get("replica"))
            pk = ev.get("peak_bytes")
            if _is_num(pk):
                mem_peak = max(mem_peak, pk)
        elif k == "mem_pressure":
            mem_pressures += 1
            missing = [f for f in MEM_PRESSURE_REQUIRED if f not in ev]
            if missing:
                errs.append(f"{title}: mem_pressure missing "
                            f"field(s) {missing} — a forecast that "
                            f"cannot justify itself")
            if ev.get("replica") not in mem_sampled \
                    and None not in mem_sampled:
                errs.append(f"{title}: mem_pressure (reason="
                            f"{ev.get('reason')!r}, replica="
                            f"{ev.get('replica')!r}) with no "
                            f"preceding mem_sample/mem_watermark — "
                            f"the forecaster claims a burn rate no "
                            f"occupancy sample ever fed")
        elif k == "query_shed" and ev.get("reason") == "memory" \
                and not mem_sampled:
            errs.append(f"{title}: memory-reason query_shed qid="
                        f"{ev.get('qid')} with no preceding "
                        f"occupancy sample — an admission decision "
                        f"priced against a byte trail that was "
                        f"never observed")
        elif k == "compact_scheduled":
            missing = [f for f in COMPACT_SCHEDULED_REQUIRED
                       if f not in ev]
            if missing:
                errs.append(f"{title}: compact_scheduled missing "
                            f"economics field(s) {missing} — a "
                            f"scheduler fold that cannot justify "
                            f"itself")
        elif k == "epoch_advance":
            e = ev.get("to_epoch")
            if _is_int(e):
                _saw_epoch(ev.get("wal"), e)
        elif k == "compact_start":
            pending_gens.add(ev.get("generation"))
        elif k == "compact_done":
            g_ = ev.get("generation")
            if g_ not in pending_gens:
                errs.append(f"{title}: compact_done generation={g_} "
                            f"without a preceding compact_start — "
                            f"the compaction bracket is broken")
            else:
                pending_gens.discard(g_)
                compacts_done += 1
        elif k == "wal_replay":
            e = ev.get("epoch")
            seen = max_epoch_seen.get(ev.get("path"), 0)
            if _is_int(e) and e < seen:
                errs.append(f"{title}: wal_replay recovered epoch "
                            f"{e} < already-published epoch "
                            f"{seen} — replay-after-crash "
                            f"epoch regression (acknowledged "
                            f"mutations vanished)")
            if _is_int(e):
                _saw_epoch(ev.get("path"), e)
            anti_published.add(ev.get("path"))
        elif k == "replica_lost":
            if ev.get("replica"):
                heal_lost.add(ev["replica"])
                # a fresh death invalidates any earlier canary pass
                canary_passed.discard(ev["replica"])
        elif k == "canary":
            r_ = ev.get("replica")
            if not r_ or not isinstance(ev.get("ok"), bool):
                errs.append(f"{title}: canary without its "
                            f"replica/ok verdict: {ev!r}"[:200])
            elif ev["ok"]:
                canary_passed.add(r_)
            else:
                canary_passed.discard(r_)
        elif k == "replica_respawn":
            r_ = ev.get("replica")
            if not r_:
                errs.append(f"{title}: replica_respawn without its "
                            f"replica: {ev!r}"[:200])
            else:
                if r_ not in heal_lost:
                    errs.append(
                        f"{title}: replica_respawn {r_!r} without a "
                        f"preceding replica_lost — a resurrection "
                        f"of a replica that never died")
                if r_ not in canary_passed:
                    errs.append(
                        f"{title}: replica_respawn {r_!r} without a "
                        f"passing canary since its loss — the "
                        f"replica re-entered routing unproven (or "
                        f"with a FAILED canary): wrong answers "
                        f"could route")
        elif k == "replica_quarantine":
            if not ev.get("replica") or not ev.get("reason"):
                errs.append(f"{title}: replica_quarantine without "
                            f"its replica/reason: {ev!r}"[:200])
        elif k == "journal_replay":
            saw_journal_replay = True
        elif k == "query_enqueue" and ev.get("recovered"):
            if not saw_journal_replay:
                errs.append(
                    f"{title}: recovered query_enqueue qid="
                    f"{ev.get('qid')} with no preceding "
                    f"journal_replay — a re-dispatch that cannot "
                    f"name the journal it recovered from")
    if mem_sampled or mem_pressures:
        n_s = len(by.get("mem_sample", []))
        n_w = len(by.get("mem_watermark", []))
        print(f"  memory: {n_s} sample(s), {n_w} watermark(s), "
              f"peak {mem_peak} bytes"
              + (f", {mem_pressures} PRESSURE signal(s)"
                 if mem_pressures else ""), file=out)
    if muts:
        edges = sum(m.get("edges", 0) for m in muts
                    if _is_int(m.get("edges")))
        advances = len(by.get("epoch_advance", []))
        occ = max((m.get("occupancy", 0) for m in muts
                   if _is_num(m.get("occupancy"))), default=0)
        n_del = sum(1 for m in muts if m.get("op") == "delete")
        n_rew = sum(1 for m in muts if m.get("op") == "reweight")
        mix = (f" ({n_del} delete, {n_rew} reweight batch(es))"
               if (n_del or n_rew) else "")
        print(f"  live graph: {edges} edge(s) over {len(muts)} "
              f"mutation batch(es){mix}, {advances} epoch advance(s), "
              f"peak delta occupancy {occ}", file=out)
    reseeds = by.get("reseed", [])
    if reseeds:
        fb = sum(1 for r in reseeds if r.get("fallback"))
        cone = max((r.get("cone", 0) for r in reseeds
                    if _is_int(r.get("cone"))), default=0)
        print(f"  re-seed: {len(reseeds)} anti-monotone "
              f"revalidation(s), peak cone {cone} vertex(ices), "
              f"{fb} full-recompute fallback(s)", file=out)
    scheds = by.get("compact_scheduled", [])
    if scheds:
        reasons = {}
        for s_ in scheds:
            r_ = s_.get("reason", "?")
            reasons[r_] = reasons.get(r_, 0) + 1
        mix = ", ".join(f"{v} {k}" for k, v in sorted(reasons.items()))
        drag = max((s_.get("drag_ns", 0) for s_ in scheds
                    if _is_num(s_.get("drag_ns"))), default=0)
        print(f"  compaction scheduler: {len(scheds)} fold(s) "
              f"scheduled ({mix}), peak delta drag {drag} "
              f"ns/boundary", file=out)
    if by.get("compact_start") or compacts_done:
        folded = sum(c.get("folded", 0)
                     for c in by.get("compact_done", [])
                     if _is_int(c.get("folded")))
        open_note = (f", {len(pending_gens)} OPEN (crashed "
                     f"mid-compaction)" if pending_gens else "")
        print(f"  compaction: {compacts_done} completed, {folded} "
              f"edge(s) folded{open_note}", file=out)
    for wt in by.get("wal_truncate", []):
        print(f"  WAL torn tail truncated: {wt.get('torn_bytes')} "
              f"byte(s) after {wt.get('records')} good record(s) "
              f"({wt.get('path')})", file=out)
    for wr in by.get("wal_replay", []):
        print(f"  WAL replay: {wr.get('records')} record(s) -> "
              f"epoch {wr.get('epoch')} generation "
              f"{wr.get('generation')} delta {wr.get('delta_count')} "
              f"(truncated {wr.get('truncated_bytes')} B)", file=out)
    # round 24 (self-healing fleet): the respawn / quarantine /
    # canary trail and the admission-journal recovery records
    respawns_ = by.get("replica_respawn", [])
    quars_ = by.get("replica_quarantine", [])
    canaries_ = by.get("canary", [])
    if respawns_ or quars_ or canaries_:
        npass = sum(1 for c in canaries_ if c.get("ok") is True)
        qmix = {}
        for q_ in quars_:
            r_ = q_.get("reason", "?")
            qmix[r_] = qmix.get(r_, 0) + 1
        qnote = ("" if not qmix else " ("
                 + ", ".join(f"{n} {r}"
                             for r, n in sorted(qmix.items())) + ")")
        print(f"  self-healing: {len(respawns_)} respawn(s), "
              f"{len(quars_)} quarantine(s){qnote}, canaries "
              f"{npass}/{len(canaries_)} passed", file=out)
    for jt in by.get("journal_truncate", []):
        print(f"  admission journal torn tail truncated: "
              f"{jt.get('torn_bytes')} byte(s), {jt.get('open')} "
              f"open / {jt.get('retired')} retired record(s) "
              f"({jt.get('path')})", file=out)
    for jr_ in by.get("journal_replay", []):
        print(f"  admission journal replay: {jr_.get('replayed')} "
              f"re-dispatched, {jr_.get('retired')} already retired "
              f"(torn {jr_.get('torn_bytes')} B) ({jr_.get('path')})",
              file=out)
    cached = [q for q in qdone if q.get("cached")]
    if cached:
        n_live = sum(1 for q in qdone if "epoch" in q)
        print(f"  answer cache: {len(cached)} of {n_live or len(qdone)}"
              f" served cached (epoch-keyed)", file=out)

    # round 17: serving metrics snapshots, cross-audited against the
    # raw query_done stream they claim to aggregate
    qdone_by_kind = {}
    for q in by.get("query_done", []):
        k = q.get("query_kind", "?")
        qdone_by_kind[k] = qdone_by_kind.get(k, 0) + 1
    # the live file's newest log_rotate carries the cumulative
    # rotation count: more rotations than kept generations means the
    # oldest query_done events were dropped with their generation, so
    # the overcount audit would indict an honest long-lived trail
    truncated = any(
        _is_int(lr.get("rotation")) and _is_int(lr.get("generations"))
        and lr["rotation"] > lr["generations"]
        for lr in by.get("log_rotate", []))
    snaps = by.get("metrics_snapshot", [])
    for i, snap in enumerate(snaps):
        # audit EVERY snapshot; render only the newest (the periodic
        # cadence otherwise floods the table)
        errs += render_metrics_snapshot(title, snap, qdone_by_kind,
                                        out,
                                        render=i == len(snaps) - 1,
                                        truncated=truncated)
    for lr in by.get("log_rotate", []):
        print(f"  log rotated (#{lr.get('rotation')}): "
              f"{lr.get('path')} -> .1 at {lr.get('rotate_bytes')} "
              f"bytes, {lr.get('generations')} generation(s) kept",
              file=out)

    done = by.get("run_done", [])
    if done:
        total = sum(seconds_of("run_done"))
        print(f"  ELAPSED TIME = {total:.6f} s", file=out)
        # segments are slices OF the elapsed: summing past it means
        # they overlap or double-count (under-sum is fine — elapsed
        # also bills checkpoint saves and host driver time)
        if segs and seg_s > total * 1.2 + 0.05:
            errs.append(
                f"{title}: segment seconds sum to {seg_s:.3f}s > "
                f"run_done elapsed {total:.3f}s — segments overlap "
                f"or double-count")

    unknown = sorted(set(by) - KNOWN)
    if unknown:
        print(f"  (other events: "
              f"{', '.join(f'{k} x{len(by[k])}' for k in unknown)})",
              file=out)
    return errs


def audit_wal_replays(events) -> list[str]:
    """CROSS-process replay-after-crash epoch regression (round 20,
    lux_tpu/livegraph.py): a real crash and its recovery are
    DIFFERENT processes, so the per-run walk in render_run — scoped
    to one (session, pid) stream — can never see the publisher's
    epochs.  Publishes (mutation / epoch_advance events carrying a
    ``wal`` path) and recoveries (wal_replay, ``path``) pair on the
    log path; wall-clock ``t`` orders across processes (the tracing
    alignment convention — a crash and its recovery are seconds
    apart, far past clock skew).  A replay recovering a LOWER epoch
    than one already published to the same WAL by an earlier other
    process means acknowledged mutations vanished: FAIL.  Same-
    process regressions stay with render_run's in-order walk (no
    double report: this audit skips same-stream pairs)."""
    pubs, reps = [], []
    for ev in events:
        k = ev.get("kind")
        t = ev.get("t")
        if not _is_num(t):
            continue
        key = (ev.get("session"), ev.get("pid"))
        if k in ("mutation", "epoch_advance"):
            wal = ev.get("wal")
            e = (ev.get("epoch") if k == "mutation"
                 else ev.get("to_epoch"))
            if wal and _is_int(e):
                pubs.append((t, key, wal, e))
        elif k == "wal_replay":
            e = ev.get("epoch")
            if ev.get("path") and _is_int(e):
                reps.append((t, key, ev.get("path"), e))
    errs = []
    for rt, rkey, rpath, re_ in reps:
        prior = [e for (t, key, wal, e) in pubs
                 if wal == rpath and t < rt and key != rkey]
        if prior and re_ < max(prior):
            errs.append(
                f"wal_replay ({rpath}) recovered epoch {re_} < "
                f"epoch {max(prior)} published by an earlier "
                f"process — cross-process replay-after-crash epoch "
                f"regression (acknowledged mutations vanished)")
    return errs


def render_flight(path: str, out=sys.stdout) -> list[str]:
    """Render one crash-flight-recorder dump (lux_tpu/tracing.py
    FLIGHT.json): reason, placement, last health word, and the tail
    of the recent-event ring.  Audited like the event log: a dump
    without its events ring, or with unparseable structure, fails."""
    errs = []
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return [f"{path}: unreadable flight dump ({e})"]
    if not isinstance(doc, dict) or not isinstance(doc.get("events"),
                                                  list):
        return [f"{path}: not a flight-recorder dump (no events "
                f"ring)"]
    print(f"== FLIGHT {path} ==", file=out)
    print(f"  session {doc.get('session')} pid {doc.get('pid')}",
          file=out)
    print(f"  reason: [{doc.get('classification')}] "
          f"{doc.get('reason')}", file=out)
    if doc.get("placement"):
        pl = doc["placement"]
        print("  placement: " + " ".join(f"{k}={v}" for k, v in
                                         sorted(pl.items())),
              file=out)
    h = doc.get("health")
    if h:
        flags = h.get("flags")
        print(f"  last health word: "
              f"{'+'.join(flags) if flags else 'clean'} "
              f"({h.get('engine')}, iteration "
              f"{h.get('iteration', '-')}, part {h.get('part', '-')})",
              file=out)
    cal = doc.get("calibration")
    if cal:
        print(f"  calibration: {cal.get('platform')} "
              f"grade={cal.get('grade')} "
              f"deviation={cal.get('deviation')}", file=out)
    # round 22: the memory trail at the moment of death — the flight
    # recorder keeps the last mem_sample/mem_watermark/mem_pressure
    # events so an OOM postmortem can read the occupancy ramp
    mt = doc.get("mem_trail")
    if mt:
        last = mt[-1] if isinstance(mt[-1], dict) else {}
        print(f"  memory trail: {len(mt)} sample(s), last "
              f"live={last.get('live_bytes', '-')} "
              f"peak={last.get('peak_bytes', '-')} "
              f"({last.get('grade', '-')})", file=out)
        for ev in mt[-4:]:
            if isinstance(ev, dict) and ev.get("kind") == \
                    "mem_pressure":
                print(f"    PRESSURE reason={ev.get('reason')} "
                      f"live={ev.get('live_bytes')} "
                      f"budget={ev.get('budget_bytes')} "
                      f"burn={ev.get('burn')}", file=out)
    evs = doc["events"]
    counts = doc.get("counts") or {}
    print(f"  ring: {len(evs)} event(s) "
          f"({', '.join(f'{k} x{v}' for k, v in sorted(counts.items()))})",
          file=out)
    for ev in evs[-12:]:
        if not isinstance(ev, dict) or "kind" not in ev:
            errs.append(f"{path}: malformed ring event {ev!r}"[:160])
            continue
        extra = " ".join(
            f"{k}={ev[k]}" for k in ("iteration", "part", "flags",
                                     "error", "seconds", "boundary",
                                     "attempt")
            if k in ev)
        print(f"    tm={ev.get('tm')} {ev['kind']} {extra}", file=out)
    return errs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render a lux_tpu telemetry event JSONL "
                    "(-events FILE) into the reference-style table")
    ap.add_argument("files", nargs="+", metavar="FILE")
    ap.add_argument("-flight", action="store_true",
                    help="FILEs are crash-flight-recorder dumps "
                         "(lux_tpu/tracing.py FLIGHT.json), not "
                         "event JSONLs — render the postmortem view")
    args = ap.parse_args(argv)

    all_errs = []
    if args.flight:
        for path in args.files:
            all_errs += render_flight(path)
        for e in all_errs:
            print(f"ERROR: {e}", file=sys.stderr)
        if all_errs:
            print(f"events_summary: {len(all_errs)} error(s)",
                  file=sys.stderr)
            return 1
        return 0
    for path in args.files:
        # a rotated EventLog (telemetry rotate_bytes) leaves .1/.2
        # generations beside the live file: consume the whole set,
        # oldest first, as ONE stream — runs spanning a rotation must
        # not split at the file boundary
        gens = rotated_set(path)
        events, errs = [], []
        try:
            for gen in gens:
                evs, es = load_events(gen)
                events += evs
                errs += [e if len(gens) == 1 else f"{gen}: {e}"
                         for e in es]
        except OSError as e:
            all_errs.append(f"{path}: unreadable ({e})")
            continue
        all_errs += [f"{path}: {e}" for e in errs]
        streams, serrs = split_streams(events)
        all_errs += [f"{path}: {e}" for e in serrs]
        all_errs += [f"{path}: {e}"
                     for e in audit_wal_replays(events)]
        for key, stream in streams:
            if key is not None and len(streams) > 1:
                print(f"-- process session={key[0]} pid={key[1]} --")
            for run in split_runs(stream):
                all_errs += [f"{path}: {e}" for e in render_run(run)]
    for e in all_errs:
        print(f"ERROR: {e}", file=sys.stderr)
    if all_errs:
        print(f"events_summary: {len(all_errs)} error(s)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
