"""Probe the owner engine's real-graph cost vs the synthetic floor
(profile_owner2.py measured gather+partials+combine = 9.9 ns/slot on
the same geometry; the engine A/B read 21-33 ns/edge).

Caches the pair-relabeled graph + starts in /tmp so repeated probes
skip the ~6 min gen+relabel.

Usage: PYTHONPATH=/root/repo:/root/.axon_site python \
    scripts/probe_owner23.py [scale np E ni]
"""

import os
import sys
import time

import numpy as np

args = [a for a in sys.argv[1:] if not a.startswith("-")]
flags = {a for a in sys.argv[1:] if a.startswith("-")}
scale = int(args[0]) if len(args) > 0 else 23
nparts = int(args[1]) if len(args) > 1 else 4
owner_E = int(args[2]) if len(args) > 2 else 256
ni = int(args[3]) if len(args) > 3 else 6

from lux_tpu.apps import pagerank
from lux_tpu.convert import rmat_graph
from lux_tpu.engine.pull import PullEngine
from lux_tpu.graph import Graph, ShardedGraph, pair_relabel
from lux_tpu.timing import timed_fused_run

cache = f"/tmp/relab_s{scale}_np{nparts}.npz"
t0 = time.time()
if os.path.exists(cache):
    z = np.load(cache)
    g2 = Graph(nv=int(z["nv"]), ne=int(z["ne"]), row_ptrs=z["row_ptrs"],
               col_idx=z["col_idx"], weights=None,
               out_degrees=z["deg"])
    starts = z["starts"]
    print(f"cache hit ({time.time() - t0:.0f}s)", flush=True)
else:
    g = rmat_graph(scale=scale, edge_factor=16, seed=0)
    g2, _perm, starts = pair_relabel(g, nparts, pair_threshold=16)
    np.savez(cache, nv=g2.nv, ne=g2.ne, row_ptrs=g2.row_ptrs,
             col_idx=g2.col_idx, deg=g2.out_degrees, starts=starts)
    print(f"gen+relabel+cache ({time.time() - t0:.0f}s)", flush=True)

t0 = time.time()
sg = ShardedGraph.build(g2, nparts, starts=starts, pair_threshold=16)
print(f"sg build ({time.time() - t0:.0f}s) vpad={sg.vpad} "
      f"({sg.vpad * 4 / 1e6:.0f} MB/shard)", flush=True)

t0 = time.time()
eng = PullEngine(sg, pagerank.make_program(), exchange="owner",
                 owner_tile_e=owner_E)
print(f"owner engine ({time.time() - t0:.0f}s) stats={eng.owner.stats} "
      f"C={eng.owner.n_chunks} streams={eng.owner.streams()}",
      flush=True)

# phase split (separate fenced programs; relative weights)
if "-no-phases" not in flags:
    _s, rep = eng.timed_phases(eng.init_state(), 3)
    for i, t in enumerate(rep):
        print(f"iter {i}: " + "  ".join(f"{k}={v * 1e3:7.1f}ms"
                                        for k, v in t.items()),
              flush=True)

from lux_tpu.timing import fence

if "-stepwise" in flags:
    # per-iteration jitted steps (async dispatch, one final fence) —
    # isolates the fori_loop program from the step program
    state = eng.init_state()
    state = eng.step(state)
    fence(state)                       # compile + settle
    state = eng.init_state()
    fence(state)
    t0 = time.time()
    for _ in range(ni):
        state = eng.step(state)
    fence(state)
    el = time.time() - t0
    print(f"owner stepwise: {el / ni * 1e3:.0f} ms/iter  "
          f"{el / ni / g2.ne * 1e9:.1f} ns/edge  "
          f"{g2.ne * ni / el / 1e9:.4f} GTEPS", flush=True)

# fused timing
state, [el] = timed_fused_run(eng, ni)
assert np.isfinite(eng.unpad(state)).all()
print(f"owner fused: {el / ni * 1e3:.0f} ms/iter  "
      f"{el / ni / g2.ne * 1e9:.1f} ns/edge  "
      f"{g2.ne * ni / el / 1e9:.4f} GTEPS", flush=True)
