#!/usr/bin/env python
"""Incremental-vs-full revalidation sweep (round 20, live graphs).

The live-graph subsystem's third pillar (lux_tpu/livegraph.py
``LiveGraph.revalidate``) claims frontier-seeded incremental
re-convergence beats recomputing from scratch when the touched
fraction is small — the whole point of keeping converged state warm
under a mutation stream.  This sweep MEASURES that claim on CPU
(PERF_NOTES round 20; the on-device crossover is carried as debt
``live-mutation-on-device``, lux_tpu/observe.py):

- per touched-fraction point f: append ``max(1, f * ne)`` random
  edges to a converged push engine's graph, then time
  (a) INCREMENTAL — ``LiveGraph.revalidate`` from the old fixed
      point (the delta-relax step + the engine's own compiled
      converge, delta blocks as jit arguments), vs
  (b) FULL — ``init_state + converge`` on an engine built over the
      augmented graph (what a rebuild-per-epoch serving tier would
      pay, compile excluded by warmup on both sides);
- each point PROVES equality first: the incremental fixed point must
  be bitwise-identical to the full recompute (the integer apps'
  proof obligation from the module docstring) before its timing may
  print — a fast wrong answer is not a speedup.

Timing fences with host fetches of the results (jax.device_get), the
round-3 discipline; medians of -reps timed runs with MAD spread.

Round 21 (the mutation algebra): ``-mode delete`` sweeps DELETION
fractions instead — per point it deletes ``max(1, f * ne)`` random
base edges and times the anti-monotone cone RE-SEED
(``LiveGraph.revalidate`` dispatching to ``_revalidate_anti``: host
re-seed of the forward-reachability cone from the deleted edges'
destinations, then the compiled converge) against the full recompute
it must bitwise-equal, reporting the measured cone fraction and
whether the cone cap forced the full-recompute fallback.  The
on-device deletion path is carried as debt
``live-deletion-on-device`` (lux_tpu/observe.py).

Usage:
    PYTHONPATH=. python scripts/sweep_live.py [-scale N] [-ef E]
        [-np P] [-kind sssp|components] [-mode append|delete]
        [-fracs f1,f2,...] [-reps R]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _median_mad(xs):
    xs = sorted(xs)
    med = xs[len(xs) // 2]
    mad = sorted(abs(x - med) for x in xs)[len(xs) // 2]
    return med, mad


def sweep_point(g, eng, lab0, act0, frac, *, kind, num_parts, reps,
                seed):
    """One touched-fraction point.  Returns a result dict (timings in
    ms) after proving incremental == full bitwise."""
    import jax

    from lux_tpu import timing

    from lux_tpu.graph import Graph  # noqa: F401 (doc pointer)
    from lux_tpu.livegraph import LiveGraph
    from lux_tpu.apps import components, sssp

    m = max(1, int(frac * g.ne))
    rng = np.random.default_rng(seed)
    src = rng.integers(g.nv, size=m)
    dst = rng.integers(g.nv, size=m)
    live = LiveGraph(g, capacity=m)
    live.append_edges(src, dst)

    # warm both sides so neither bills XLA compilation to the timings
    inc_lab, inc_act, _ = live.revalidate(eng, lab0, act0)
    g_new = live.graph_at(live.epoch)
    app = sssp if kind == "sssp" else components
    build = (lambda gg: app.build_engine(gg, 0, num_parts=num_parts)) \
        if kind == "sssp" else \
        (lambda gg: app.build_engine(gg, num_parts=num_parts))
    eng_full = build(g_new)
    flab, fact = eng_full.init_state()
    flab, fact, _ = eng_full.converge(flab, fact)

    # the proof obligation first: bitwise-equal fixed points
    inc_h = eng.sg.from_padded(np.asarray(jax.device_get(inc_lab)))
    full_h = eng_full.sg.from_padded(np.asarray(jax.device_get(flab)))
    if not np.array_equal(inc_h, full_h):
        raise AssertionError(
            f"frac={frac}: incremental fixed point differs from full "
            f"recompute — a fast wrong answer is not a speedup")

    # fence with the O(1)-byte checksum, NEVER a full-state fetch:
    # on the owed on-device run a device_get of the whole label
    # table bills the tunnel transfer to BOTH sides and drowns the
    # millisecond incremental timings (CLAUDE.md fencing rule)
    timing.fence(inc_lab)           # warm the fence jit outside
    t_inc = []
    for _ in range(reps):
        t0 = time.perf_counter()
        il, ia, _ = live.revalidate(eng, lab0, act0)
        timing.fence(il)
        t_inc.append((time.perf_counter() - t0) * 1e3)
    t_full = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fl, fa = eng_full.init_state()
        fl, fa, _ = eng_full.converge(fl, fa)
        timing.fence(fl)
        t_full.append((time.perf_counter() - t0) * 1e3)
    inc_med, inc_mad = _median_mad(t_inc)
    full_med, full_mad = _median_mad(t_full)
    return {"frac": frac, "edges": m, "inc_ms": inc_med,
            "inc_mad": inc_mad, "full_ms": full_med,
            "full_mad": full_mad,
            "speedup": full_med / inc_med if inc_med > 0 else
            float("inf")}


def _forward_cone(g_new, seeds):
    """Forward reachability from ``seeds`` over ``g_new`` — the same
    rule ``_revalidate_anti`` re-seeds by, recomputed here so the
    sweep can REPORT the cone it measured."""
    reach = np.zeros(g_new.nv, bool)
    frontier = np.unique(np.asarray(seeds))
    reach[frontier] = True
    s_a, d_a = g_new.edge_arrays()
    while frontier.size:
        nxt = np.unique(d_a[np.isin(s_a, frontier)])
        nxt = nxt[~reach[nxt]]
        reach[nxt] = True
        frontier = nxt
    return int(reach.sum())


def sweep_delete_point(g, eng, lab0, act0, frac, *, kind, num_parts,
                       reps, seed):
    """One DELETION-fraction point (round 21).  Times the
    anti-monotone re-seed (place the old converged state onto an
    engine over ``graph_at(target)``, then ``revalidate``) against
    ``init_state + converge`` on the same engine, after proving the
    two fixed points bitwise-equal."""
    import jax

    from lux_tpu import timing
    from lux_tpu.livegraph import LiveGraph
    from lux_tpu.apps import components, sssp

    m = max(1, int(frac * g.ne))
    rng = np.random.default_rng(seed)
    idx = rng.choice(g.ne, size=min(m, g.ne), replace=False)
    esrc, edst = g.edge_arrays()
    live = LiveGraph(g, capacity=len(idx))
    live.delete_edges(esrc[idx], edst[idx])
    g_new = live.graph_at(live.epoch)
    app = sssp if kind == "sssp" else components
    eng_t = (app.build_engine(g_new, 0, num_parts=num_parts)
             if kind == "sssp"
             else app.build_engine(g_new, num_parts=num_parts))
    old_h = eng.sg.from_padded(np.asarray(jax.device_get(lab0)))
    zeros = np.zeros(g.nv, bool)

    def reseed():
        lab, act = eng_t.place(eng_t.sg.to_padded(old_h),
                               eng_t.sg.to_padded(zeros))
        return live.revalidate(eng_t, lab, act)

    # warm both sides (compile excluded), then the proof obligation
    rlab, _ract, _ = reseed()
    flab, fact = eng_t.init_state()
    flab, fact, _ = eng_t.converge(flab, fact)
    r_h = eng_t.sg.from_padded(np.asarray(jax.device_get(rlab)))
    f_h = eng_t.sg.from_padded(np.asarray(jax.device_get(flab)))
    if not np.array_equal(r_h, f_h):
        raise AssertionError(
            f"frac={frac}: re-seeded fixed point differs from full "
            f"recompute — a fast wrong repair is not a speedup")
    cone = _forward_cone(g_new, edst[idx])
    fell_back = live.reseed_fallbacks > 0

    timing.fence(rlab)
    t_rs = []
    for _ in range(reps):
        t0 = time.perf_counter()
        rl, _ra, _ = reseed()
        timing.fence(rl)
        t_rs.append((time.perf_counter() - t0) * 1e3)
    t_full = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fl, fa = eng_t.init_state()
        fl, fa, _ = eng_t.converge(fl, fa)
        timing.fence(fl)
        t_full.append((time.perf_counter() - t0) * 1e3)
    rs_med, rs_mad = _median_mad(t_rs)
    full_med, full_mad = _median_mad(t_full)
    return {"frac": frac, "edges": len(idx),
            "cone_frac": cone / g.nv, "fallback": fell_back,
            "reseed_ms": rs_med, "reseed_mad": rs_mad,
            "full_ms": full_med, "full_mad": full_mad,
            "speedup": full_med / rs_med if rs_med > 0 else
            float("inf")}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="incremental-vs-full revalidation sweep "
                    "(lux_tpu/livegraph.py round 20)")
    ap.add_argument("-scale", type=int, default=14)
    ap.add_argument("-ef", type=int, default=16)
    ap.add_argument("-np", type=int, default=2, dest="num_parts")
    ap.add_argument("-kind", default="sssp",
                    choices=["sssp", "components"])
    ap.add_argument("-mode", default="append",
                    choices=["append", "delete"])
    ap.add_argument("-fracs", default=None,
                    help="touched fractions (default depends on "
                         "-mode: deletions cone out fast on "
                         "scale-free graphs, so the delete sweep "
                         "defaults to smaller points)")
    ap.add_argument("-reps", type=int, default=5)
    ap.add_argument("-seed", type=int, default=7)
    args = ap.parse_args(argv)
    if args.fracs is None:
        args.fracs = ("0.0005,0.002,0.01,0.05,0.2"
                      if args.mode == "append"
                      else "0.00001,0.0001,0.001,0.01")

    from lux_tpu import convert
    from lux_tpu.graph import Graph
    from lux_tpu.apps import components, sssp

    fracs = [float(f) for f in args.fracs.split(",") if f.strip()]
    src, dst, nv = convert.rmat_edges(args.scale, args.ef,
                                      seed=args.seed)
    g = Graph.from_edges(src.astype(np.int64), dst.astype(np.int64),
                         nv)
    app = sssp if args.kind == "sssp" else components
    eng = (app.build_engine(g, 0, num_parts=args.num_parts)
           if args.kind == "sssp"
           else app.build_engine(g, num_parts=args.num_parts))
    lab0, act0 = eng.init_state()
    lab0, act0, _ = eng.converge(lab0, act0)

    print(f"# sweep_live kind={args.kind} mode={args.mode} "
          f"rmat{args.scale} ef{args.ef} nv={g.nv} ne={g.ne} "
          f"np={args.num_parts} reps={args.reps}")
    if args.mode == "append":
        print(f"{'frac':>8} {'edges':>8} {'incr_ms':>10} "
              f"{'full_ms':>10} {'speedup':>8}")
        for i, f in enumerate(fracs):
            r = sweep_point(g, eng, lab0, act0, f, kind=args.kind,
                            num_parts=args.num_parts,
                            reps=args.reps,
                            seed=args.seed + 100 + i)
            print(f"{r['frac']:>8g} {r['edges']:>8d} "
                  f"{r['inc_ms']:>7.1f}±{r['inc_mad']:<4.1f} "
                  f"{r['full_ms']:>7.1f}±{r['full_mad']:<4.1f} "
                  f"{r['speedup']:>7.2f}x")
        return 0
    print(f"{'frac':>8} {'edges':>7} {'cone':>7} {'fb':>3} "
          f"{'reseed_ms':>11} {'full_ms':>10} {'speedup':>8}")
    for i, f in enumerate(fracs):
        r = sweep_delete_point(g, eng, lab0, act0, f,
                               kind=args.kind,
                               num_parts=args.num_parts,
                               reps=args.reps,
                               seed=args.seed + 200 + i)
        print(f"{r['frac']:>8g} {r['edges']:>7d} "
              f"{r['cone_frac']:>6.1%} "
              f"{'Y' if r['fallback'] else 'n':>3} "
              f"{r['reseed_ms']:>8.1f}±{r['reseed_mad']:<4.1f} "
              f"{r['full_ms']:>7.1f}±{r['full_mad']:<4.1f} "
              f"{r['speedup']:>7.2f}x")
    return 0


if __name__ == "__main__":
    sys.exit(main())
