#!/usr/bin/env python
"""Validate bench metric lines against the resilience-era schema.

The driver's BENCH_*.json artifacts wrap bench.py's stdout; each
metric line there is one JSON object.  Round 6 added an audit trail
(lux_tpu/resilience.py): ``attempts`` (total timed runs, outlier
reruns included), ``discarded`` (samples thrown out by the >3x
discard-and-rerun rule), and ``run_attempts`` when a whole config was
retried after a transient crash.  A headline number whose line lacks
that metadata can silently median over a tunnel collapse — exactly
the BENCH_r05 pagerank-mp incident ([0.1116, 0.0107, 0.1118]) this
schema exists to make impossible — so missing metadata FAILS the
check.

Usage:
    python scripts/check_bench.py [-legacy-ok] FILE...

FILE is a driver artifact (JSON object with a ``tail`` transcript), a
raw JSONL of metric lines, or a single JSON metric object.
``-legacy-ok`` downgrades pre-round-6 metadata gaps (missing
samples/attempts/discarded) to warnings so the historical BENCH_r01-05
artifacts still audit cleanly; structural errors (bad median,
inconsistent counts, malformed lines) always fail.

Checked per metric line:
- required keys: metric, value, unit, vs_baseline
- samples: non-empty list of finite numbers, value == median(samples)
  (to rounding)
- attempts: int, == len(samples) + len(discarded) — every discarded
  sample was either re-run (adding a kept sample) or counted
- discarded: list of finite numbers, each >FACTORx off the kept median
  is not re-checked here (the factor is a bench flag), but discarded
  samples must not also appear in samples
- run_attempts (optional): int >= 2
- *_FAILED lines: error message plus attempts and failure_class
  ("retryable" | "fatal")
- round-8 script lines: colfilter-netflix (scripts/bench_netflix.py)
  must carry a strictly-decreasing ``rmse`` trajectory plus the pair
  configuration; bigscale lines (scripts/bench_bigscale.py, e.g. the
  RMAT27 pair record) must carry scale/ne/iters/exchange consistent
  with the metric name — both now emit the same samples/attempts/
  discarded + telemetry audit schema as bench.py, so the outlier
  screen is checked on them too
- telemetry (round 7, lux_tpu/telemetry.py): ``runs`` — one
  {repeat, iters, seconds} per timed run, straight from the
  ``timed_run`` events — and ``counters`` (the device-side
  per-iteration digest, or null when -iter-stats was off).  Checked:
  len(runs) == attempts (every sample and every discard has its
  seconds on record), and with ``ne`` present each run's
  ne*iters/seconds re-derives a recorded sample — the per-run
  decomposition summing back to the published number, so a collapsed
  run can't hide behind its median.  Both loosen to >= / skip when
  the line carries run_attempts (whole config retried) or
  rerun_error (an outlier rerun crashed after its timed_run event
  landed) — those runs legitimately have no recorded sample.  Missing
  telemetry fails strict mode like the round-6 keys (the round-1..6
  artifacts predate it: -legacy-ok).

- audit (round 10, bench.py -audit / lux_tpu/audit.py): optional
  digest of the static program audit that ran at the config's engine
  build — {mode: warn|error, errors: int, warnings: int,
  failed_checks: [known check names]}.  A digest with errors (or any
  failed_checks) on a PUBLISHED metric line is rejected: the number
  was measured on a build that violates the framework's structural
  invariants (double gather, baked-in constants, broken collective
  schedule...), so it cannot stand as a metric of record.

- telemetry.imbalance (round 13, lux_tpu/tracing.py era): the
  per-part imbalance digest — {kind, index = max/mean per-part work,
  parts = per-part totals} — null when -iter-stats was off.  Checked:
  index recomputes from the parts, and the parts SUM to the counter
  digest's edges_sum/changed_sum (the same contradiction pattern as
  the health digest: per-part and scalar counters are the same
  device-side values reduced in a different order, so disagreement
  means the published skew signal is lying).

- telemetry.topology (round 11, lux_tpu/resilience.py elastic
  recovery): optional; null when the mesh never changed.  A non-null
  digest ({shrinks, ndev_final}) REJECTS the line — a mid-run mesh
  shrink means part of the measurement ran degraded, and a
  degraded-mesh GTEPS must never be compared against full-mesh lines
  silently.

- calibration (round 12, lux_tpu/observe.py): the session-calibration
  fingerprint digest every bench.py / bench_netflix / bench_bigscale
  line now carries — {session, platform, backend, ndev, grade,
  deviation, probe}.  Missing fails strict mode (pre-round-12
  artifacts: -legacy-ok); null (a crashed probe) or any grade other
  than "canonical" REJECTS the line: a session whose reference probe
  ran >3x off the canonical PERF_NOTES figures (the 10x
  tunnel-variance trap) or on a non-canonical platform is detected
  and labeled at the source, and its numbers never enter the
  trajectory silently.

- serve-slo lines (round 17, bench.py -config serve-slo +
  scripts/loadgen.py): the value is the measured achieved qps of one
  open-loop Poisson load step; the line must carry offered_qps /
  achieved_qps / p50_ms / p99_ms / slo_target_ms / slo_good_fraction
  and is rejected on the contradictions an honest open-loop run
  cannot produce: p99 < p50, achieved > offered, a good fraction
  outside [0, 1], or a headline value disagreeing with the recorded
  achieved rate.

- serve-chaos lines (round 18, bench.py -config serve-chaos +
  lux_tpu/fleet.py): the serve-slo record under an injected replica
  kill, extended with replicas/failovers/shed/shed_fraction/
  slo_accounted plus the round-24 self-healing gauges respawns/
  quarantines/mttr_s/journal_replayed; rejected on shed_fraction
  outside [0, 1] (or disagreeing with shed/submitted), failovers or
  respawns with replicas=1, served+shed != submitted, slo_accounted
  > served (an SLO fraction computed over shed queries), mttr_s
  with neither failovers nor respawns (repair time without an
  outage), or journal_replayed > submitted (a recovery claiming
  queries the load never offered).

- comm (round 19, lux_tpu/comms.py): the per-collective byte-ledger
  digest engine metric lines now carry — {errors, ndev, exchange,
  tier, bytes_per_iter, comm_bytes_per_edge, messages, comm_frac}.
  Rejected on: a ledger-failing build (errors > 0 — the oracle/audit
  cross-check failed), comm_frac outside [0, 1], bytes or messages
  on a single device, a mesh owner/gather exchange shipping zero
  bytes, or a per-edge figure contradicting bytes_per_iter*ndev/ne.

- telemetry.health (round 9, bench.py -health): the device-side
  watchdog digest — optional and null when off; present it must be a
  clean bill ({engine, tripped=false, flags=[], iters >= 0}; known
  check names only) — a tripped watchdog fails its config with a
  _FAILED line, so a published metric line claiming a trip is a
  contradiction and fails the audit.

Exit status: 0 clean, 1 any error (loud, listed on stderr).
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from statistics import median

LEGACY_KEYS = ("samples", "attempts", "discarded")

# Round-8 script metric lines (scripts/bench_netflix.py and
# scripts/bench_bigscale.py emit the same resilience/telemetry schema
# as bench.py plus script-specific fields, validated below):
# colfilter-netflix carries the RMSE learning trajectory, bigscale
# carries the scale/exchange/pair configuration of record.
NETFLIX_METRIC = re.compile(
    r"^colfilter_netflix(\d+)m_np(\d+)_gteps_per_chip$")
BIGSCALE_METRIC = re.compile(
    r"^(pagerank|cc|sssp|sssp-w)_rmat(\d+)_np(\d+)_gteps_per_chip$")
# query-batched lines (bench.py ksssp-batch/ppr-batch, ROADMAP item
# 2): the metric name carries the batch width B, the line carries
# batch + query_gteps (= B x value, the delivered query-edge rate) —
# cross-checked below so a published per-query claim can never
# contradict the machine rate it was derived from
BATCH_METRIC = re.compile(
    r"^(ksssp|ppr)_b(\d+)_rmat(\d+)_gteps_per_chip$")
# paged-vs-flat A/B lines (bench.py -config gather-ab, round 15,
# ops/pagegather.py): the metric name carries the delivery mode, the
# line carries gather + the plan's measured page stats — the ratio
# the break-even claim rests on must be on the record, both sides.
# Round 16 grows the reorder token (none|native|hillclimb,
# lux_tpu/reorder.py — absent in the name means none), the pagemajor
# mode and the community shape; a reordered line is additionally
# cross-checked against its paired none line (check_reorder_pairs:
# the fill must not DECREASE under a reorder, or the published gain
# is a contradiction)
GATHER_AB_METRIC = re.compile(
    r"^pagerank_(paged|flat|pagemajor)_(?:(native|hillclimb)_)?"
    r"(rmat|comm)(\d+)_gteps_per_chip$")
REORDER_METHODS = ("none", "native", "hillclimb")
# round-23 MXU-vs-VPU reduce A/B lines (bench.py -config mxu-ab,
# ops/tiled.py): the metric name carries the reduce path, the line
# carries mxu (the mode of record), use_mxu (the engine's RESOLVED
# flag — a name/mode/flag disagreement is the mode-vs-name
# contradiction class), the scalemodel per-row rates for BOTH paths
# (the modeled step-change the measured pair is read against) and
# the plan fill.  An mxu line is only publishable NEXT TO its paired
# vpu baseline (check_mxu_pairs) — a lone MXU number has no
# step-change to show.
MXU_AB_METRIC = re.compile(
    r"^ppr_(mxu|vpu)_comm(\d+)_gteps_per_chip$")
# round-17 serving SLO lines (bench.py -config serve-slo +
# scripts/loadgen.py): one open-loop Poisson load step per line, the
# value is the MEASURED achieved qps.  The line must carry the whole
# latency-vs-offered-rate record (offered/achieved qps, snapshot
# p50/p99 ms, the per-kind SLO targets and the good fraction), and
# three contradictions reject outright: p99 < p50 (a percentile pair
# no real distribution produces), achieved > offered (the open-loop
# harness measures both from the same load-start clock, so service
# cannot outrun arrivals), and an SLO good fraction outside [0, 1].
SERVE_SLO_METRIC = re.compile(
    r"^serve_slo_q([0-9pm]+)_rmat(\d+)_qps_per_chip$")
# round-18 serving chaos lines (bench.py -config serve-chaos +
# lux_tpu/fleet.py): the serve-slo record under an injected replica
# kill, extended with replicas/failovers/shed/shed_fraction/
# slo_accounted.  Contradiction rejects on top of the serve-slo set:
# shed_fraction outside [0, 1] (or disagreeing with shed/submitted),
# failovers > 0 with replicas = 1 (no survivor to fail over TO),
# served + shed != submitted (admitted and shed must partition the
# offered load), and slo_accounted > served (the SLO fraction was
# computed over shed queries — the accounting covers ADMITTED
# retirements only).  Round 24 adds the self-healing gauges
# (respawns/quarantines/mttr_s/journal_replayed) and their rejects:
# respawns with replicas = 1, mttr_s without any failover or
# respawn, journal_replayed > submitted.
SERVE_CHAOS_METRIC = re.compile(
    r"^serve_chaos_q([0-9pm]+)_rmat(\d+)_qps_per_chip$")
# round-20 live-graph serving lines (bench.py -config serve-live +
# lux_tpu/livegraph.py): mixed traffic over a mutating graph with
# epoch-pinned answers, the epoch-keyed cache and threshold-triggered
# compaction.  Contradiction rejects: epochs_advanced > 0 with
# mutations = 0 (epochs only advance when a mutation batch publishes)
# and vice versa, cache_hit_fraction outside [0, 1], compactions > 0
# with peak_occupancy strictly under compact_threshold AND no
# pending anti-monotone op (neither trigger the line claims could
# have fired).  Round 21 adds the mutation-algebra counters
# (deletions / reweights / reseeds / scheduler_compactions) with
# their own contradictions: a re-seed without any deletion/reweight
# to re-seed from, algebra ops exceeding the mutation total, and
# scheduler folds exceeding the compaction count or justified by no
# evidenceable trigger.
SERVE_LIVE_METRIC = re.compile(
    r"^serve_live_rmat(\d+)_qps_per_chip$")


def iter_metric_lines(path: str):
    """Yield (lineno_label, dict) metric objects from ``path``."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
    if isinstance(doc, dict) and "tail" in doc:      # driver artifact
        src = doc["tail"].splitlines()
        label = "tail line"
    elif isinstance(doc, dict) and "metric" in doc:  # one bare object
        yield "object", doc
        return
    else:                                            # raw JSONL
        src = text.splitlines()
        label = "line"
    for i, line in enumerate(src, 1):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            yield f"{label} {i}", {"_unparseable": line[:120]}
            continue
        if isinstance(obj, dict) and "metric" in obj:
            yield f"{label} {i}", obj


def _is_num(x) -> bool:
    return isinstance(x, (int, float)) and not isinstance(x, bool) \
        and x == x and abs(x) != float("inf")


def check_line(obj: dict, *, legacy_ok: bool):
    """Returns (errors, warnings) string lists for one metric line."""
    errs, warns = [], []
    if "_unparseable" in obj:
        return [f"unparseable JSON: {obj['_unparseable']}"], []
    name = obj.get("metric", "?")

    if name.endswith("_FAILED"):
        if not obj.get("error"):
            errs.append(f"{name}: failure line without an 'error'")
        missing = [k for k in ("attempts", "failure_class")
                   if k not in obj]
        if missing:
            (warns if legacy_ok else errs).append(
                f"{name}: failure line missing {missing}")
        elif obj["failure_class"] not in ("retryable", "fatal"):
            errs.append(f"{name}: failure_class="
                        f"{obj['failure_class']!r} not retryable|fatal")
        return errs, warns

    for k in ("metric", "value", "unit", "vs_baseline"):
        if k not in obj:
            errs.append(f"{name}: missing required key {k!r}")
    if "value" in obj and not _is_num(obj["value"]):
        errs.append(f"{name}: non-finite value {obj['value']!r}")

    missing = [k for k in LEGACY_KEYS if k not in obj]
    if missing:
        msg = (f"{name}: missing resilience metadata {missing} "
               f"(pre-round-6 schema)")
        (warns if legacy_ok else errs).append(msg)

    samples = obj.get("samples")
    if samples is not None:
        if (not isinstance(samples, list) or not samples
                or not all(_is_num(s) for s in samples)):
            errs.append(f"{name}: samples must be a non-empty list "
                        f"of finite numbers, got {samples!r}")
            samples = None
    if samples and _is_num(obj.get("value")):
        m = median(samples)
        # value = round(median(raw), 4) while samples are rounded
        # individually: the two medians agree to ~1e-4
        if abs(obj["value"] - m) > 2e-4:
            errs.append(f"{name}: value {obj['value']} is not the "
                        f"median of samples ({m:.4f}) — collapsed "
                        f"sample silently medianed?")

    discarded = obj.get("discarded")
    if discarded is not None:
        if (not isinstance(discarded, list)
                or not all(_is_num(d) for d in discarded)):
            errs.append(f"{name}: discarded must be a list of finite "
                        f"numbers, got {discarded!r}")
            discarded = None
    if samples and discarded:
        # a kept sample equal to a discarded one is a contradiction
        # (discards are >FACTORx off the median the keeps define) —
        # it means a discarded collapse was ALSO medianed
        overlap = sorted(set(samples) & set(discarded))
        if overlap:
            errs.append(f"{name}: {overlap} appear in both samples "
                        f"and discarded — discarded sample medianed")

    attempts = obj.get("attempts")
    if attempts is not None:
        if not isinstance(attempts, int) or attempts < 1:
            errs.append(f"{name}: attempts must be a positive int, "
                        f"got {attempts!r}")
        elif samples is not None and discarded is not None:
            want = len(samples) + len(discarded)
            if attempts != want:
                errs.append(
                    f"{name}: attempts={attempts} inconsistent with "
                    f"{len(samples)} samples + {len(discarded)} "
                    f"discarded (= {want})")

    ra = obj.get("run_attempts")
    if ra is not None and (not isinstance(ra, int) or ra < 2):
        errs.append(f"{name}: run_attempts={ra!r} (recorded only "
                    f"when >= 2)")

    if "telemetry" not in obj:
        (warns if legacy_ok else errs).append(
            f"{name}: missing telemetry field (pre-round-7 schema)")
    else:
        errs += check_telemetry(name, obj)

    errs += check_audit_field(name, obj)
    errs += check_comm_field(name, obj)
    errs += check_mem_field(name, obj)

    if "calibration" not in obj:
        (warns if legacy_ok else errs).append(
            f"{name}: missing calibration field (pre-round-12 "
            f"schema)")
    else:
        errs += check_calibration_field(name, obj)

    if NETFLIX_METRIC.match(name):
        errs += check_netflix_fields(name, obj)
    else:
        m = BIGSCALE_METRIC.match(name)
        if m:
            errs += check_bigscale_fields(name, obj, int(m.group(2)))
    m = BATCH_METRIC.match(name)
    if m or "batch" in obj:
        errs += check_batch_fields(name, obj,
                                   int(m.group(2)) if m else None)
    m = GATHER_AB_METRIC.match(name)
    if m or "gather" in obj:
        errs += check_gather_fields(name, obj,
                                    m.group(1) if m else None,
                                    (m.group(2) or "none") if m
                                    else None)
    m = MXU_AB_METRIC.match(name)
    if m or "mxu" in obj:
        errs += check_mxu_fields(name, obj, m.group(1) if m else None)
    if SERVE_SLO_METRIC.match(name) or SERVE_CHAOS_METRIC.match(name) \
            or "offered_qps" in obj:
        errs += check_serve_slo_fields(name, obj)
    if SERVE_CHAOS_METRIC.match(name) or "shed_fraction" in obj \
            or "failovers" in obj:
        errs += check_serve_chaos_fields(name, obj)
    if SERVE_LIVE_METRIC.match(name) or "epochs_advanced" in obj \
            or "cache_hit_fraction" in obj:
        errs += check_serve_live_fields(name, obj)
    return errs, warns


def _check_pair_cfg(name: str, obj: dict) -> list[str]:
    """pair_threshold / min_fill fields shared by the netflix and
    bigscale lines: positive int or null (min_fill also 'auto', the
    K-aware break-even)."""
    errs = []
    pt = obj.get("pair_threshold")
    if pt is not None and (not isinstance(pt, int) or pt < 1):
        errs.append(f"{name}: pair_threshold={pt!r} must be a "
                    f"positive int or null")
    mf = obj.get("min_fill")
    if mf is not None and mf != "auto" and (
            not isinstance(mf, int) or mf < 1):
        errs.append(f"{name}: min_fill={mf!r} must be a positive "
                    f"int, 'auto' or null")
    return errs


def check_netflix_fields(name: str, obj: dict) -> list[str]:
    """colfilter-netflix lines (scripts/bench_netflix.py): the RMSE
    trajectory must be recorded and STRICTLY DECREASING — a GTEPS
    number on a factorization that is not learning is noise (the
    script asserts this at run time; the audit re-checks the
    artifact), plus the pair configuration fields."""
    errs = []
    missing = [k for k in ("rmse", "ne", "np", "iters",
                           "pair_threshold") if k not in obj]
    if missing:
        errs.append(f"{name}: netflix line missing {missing}")
    rmse = obj.get("rmse")
    if rmse is not None:
        if (not isinstance(rmse, list) or len(rmse) < 2
                or not all(_is_num(r) for r in rmse)):
            errs.append(f"{name}: rmse must be a list of >= 2 finite "
                        f"numbers, got {rmse!r}")
        elif not all(b < a for a, b in zip(rmse, rmse[1:])):
            errs.append(f"{name}: rmse {rmse} is not strictly "
                        f"decreasing — the factorization did not "
                        f"learn; the GTEPS line is noise")
    return errs + _check_pair_cfg(name, obj)


def check_bigscale_fields(name: str, obj: dict,
                          name_scale: int) -> list[str]:
    """bigscale lines (scripts/bench_bigscale.py, e.g. the RMAT27
    pair record): configuration of record must be present and
    self-consistent with the metric name."""
    errs = []
    missing = [k for k in ("scale", "ne", "iters", "exchange")
               if k not in obj]
    if missing:
        errs.append(f"{name}: bigscale line missing {missing}")
    scale = obj.get("scale")
    if isinstance(scale, int) and scale != name_scale:
        errs.append(f"{name}: scale={scale} contradicts the metric "
                    f"name's rmat{name_scale}")
    ex = obj.get("exchange")
    if ex is not None and ex not in ("gather", "owner", "auto"):
        errs.append(f"{name}: exchange={ex!r} not "
                    f"gather|owner|auto")
    it = obj.get("iters")
    if it is not None and (not isinstance(it, int) or it < 1):
        errs.append(f"{name}: iters={it!r} must be a positive int")
    ne = obj.get("ne")
    if ne is not None and (not isinstance(ne, int) or ne < 1):
        errs.append(f"{name}: ne={ne!r} must be a positive int")
    return errs + _check_pair_cfg(name, obj)


def check_batch_fields(name: str, obj: dict,
                       name_b: int | None) -> list[str]:
    """Query-batched lines (bench.py batch-sweep, ROADMAP item 2):
    ``batch`` must be a positive int matching the metric name's _bN_,
    and ``query_gteps`` — the delivered query-edge rate the per-query
    amortization claim rests on — must equal batch x value (to
    rounding): a per-query number that contradicts the machine rate
    it was derived from is rejected, the same contradiction pattern
    as the imbalance/health digests."""
    errs = []
    b = obj.get("batch")
    if not isinstance(b, int) or isinstance(b, bool) or b < 1:
        errs.append(f"{name}: batch={b!r} must be a positive int")
        return errs
    if name_b is not None and b != name_b:
        errs.append(f"{name}: batch={b} contradicts the metric "
                    f"name's _b{name_b}_")
    qg = obj.get("query_gteps")
    if qg is None:
        errs.append(f"{name}: batched line missing query_gteps "
                    f"(= batch x value, the per-query metric of "
                    f"record)")
    elif not _is_num(qg):
        errs.append(f"{name}: query_gteps={qg!r} must be a finite "
                    f"number")
    elif _is_num(obj.get("value")):
        want = b * obj["value"]
        # value and query_gteps round independently to 4 decimals
        if abs(qg - want) > 1e-4 * (b + 1):
            errs.append(
                f"{name}: query_gteps={qg} != batch x value "
                f"({b} x {obj['value']} = {want:.4f}) — the "
                f"per-query claim contradicts the machine rate")
    pq = obj.get("per_query_edge_ns")
    if pq is not None and _is_num(qg) and qg > 0:
        if not _is_num(pq) or abs(pq - 1.0 / qg) > 2e-3 * max(
                1.0, 1.0 / qg):
            errs.append(
                f"{name}: per_query_edge_ns={pq!r} contradicts "
                f"1/query_gteps ({1.0 / qg:.4f})")
    return errs


def check_gather_fields(name: str, obj: dict,
                        name_mode: str | None,
                        name_reorder: str | None = None) -> list[str]:
    """Gather A/B lines (bench.py -config gather-ab, round 15): the
    ``gather`` mode must be paged|flat|pagemajor and match the metric
    name, and BOTH sides must record the plan's measured page stats —
    ``page_ratio`` (unique page elements per edge, finite > 0) and
    ``page_fill`` (live lanes per PADDED delivery row, (0, 128] —
    the exact padded_fill gather="auto" and the phase model consume,
    not the live-rows-only figure): the modeled break-even
    (scalemodel.page_gather_ns) is resolved FROM these numbers, so a
    published A/B without them cannot be audited.  Round 16: the
    ``reorder`` field (none|native|hillclimb, lux_tpu/reorder.py)
    must match the metric name's reorder token — a line claiming a
    reordered fill under an unreordered name (or vice versa) is the
    same contradiction class as mode-vs-name."""
    errs = []
    mode = obj.get("gather")
    if mode not in ("paged", "flat", "pagemajor"):
        errs.append(f"{name}: gather={mode!r} must be 'paged', "
                    f"'flat' or 'pagemajor'")
        return errs
    if name_mode is not None and mode != name_mode:
        errs.append(f"{name}: gather={mode!r} contradicts the metric "
                    f"name's _{name_mode}_")
    ro = obj.get("reorder")
    if ro is not None and ro not in REORDER_METHODS:
        errs.append(f"{name}: reorder={ro!r} must be one of "
                    f"{'|'.join(REORDER_METHODS)}")
    elif name_reorder is not None and (ro or "none") != name_reorder:
        errs.append(f"{name}: reorder={ro!r} contradicts the metric "
                    f"name's reorder token {name_reorder!r}")
    pr = obj.get("page_ratio")
    if not _is_num(pr) or pr <= 0:
        errs.append(f"{name}: page_ratio={pr!r} must be a finite "
                    f"number > 0 (the plan's measured unique-page "
                    f"ratio, the break-even model's input)")
    pf = obj.get("page_fill")
    if not _is_num(pf) or not 0.0 < pf <= 128.0:
        errs.append(f"{name}: page_fill={pf!r} must be a finite "
                    f"number in (0, 128] (live lanes per padded "
                    f"128-lane delivery row)")
    return errs


def check_mxu_fields(name: str, obj: dict,
                     name_mode: str | None) -> list[str]:
    """Round-23 MXU A/B lines (see MXU_AB_METRIC): ``mxu`` must be
    mxu|vpu and match the metric name, ``use_mxu`` must be the
    matching resolved boolean (the engine flag of record — a vpu line
    claiming use_mxu=true ran the wrong path), and BOTH modeled
    per-chunk-row rates (``mxu_row_ns``/``vpu_row_ns``,
    lux_tpu/scalemodel.py) must be present, finite > 0 and DISTINCT:
    the pair exists to show a step-change, and identical models mean
    the line was stamped without resolving the payload width."""
    errs = []
    mode = obj.get("mxu")
    if mode not in ("mxu", "vpu"):
        errs.append(f"{name}: mxu={mode!r} must be 'mxu' or 'vpu'")
        return errs
    if name_mode is not None and mode != name_mode:
        errs.append(f"{name}: mxu={mode!r} contradicts the metric "
                    f"name's _{name_mode}_")
    um = obj.get("use_mxu")
    if not isinstance(um, bool):
        errs.append(f"{name}: use_mxu={um!r} must be a bool (the "
                    f"engine's resolved flag)")
    elif um != (mode == "mxu"):
        errs.append(f"{name}: use_mxu={um} contradicts mxu={mode!r} "
                    f"— the engine ran the other reduce path")
    kind = obj.get("reduce_kind")
    if kind not in ("sum", "min", "max"):
        errs.append(f"{name}: reduce_kind={kind!r} must be "
                    f"sum|min|max")
    rates = {}
    for k in ("mxu_row_ns", "vpu_row_ns"):
        v = obj.get(k)
        if not _is_num(v) or v <= 0:
            errs.append(f"{name}: {k}={v!r} must be a finite number "
                        f"> 0 (the scalemodel per-chunk-row rate)")
        else:
            rates[k] = v
    if len(rates) == 2 and abs(
            rates["mxu_row_ns"] - rates["vpu_row_ns"]) < 1e-9:
        errs.append(f"{name}: mxu_row_ns == vpu_row_ns "
                    f"({rates['mxu_row_ns']}) — the modeled pair "
                    f"shows no step-change; the payload width was "
                    f"not resolved")
    pf = obj.get("page_fill")
    if not _is_num(pf) or not 0.0 < pf <= 128.0:
        errs.append(f"{name}: page_fill={pf!r} must be a finite "
                    f"number in (0, 128] (live lanes per padded "
                    f"128-lane row — the A/B's dense-fill evidence)")
    return errs


def check_serve_slo_fields(name: str, obj: dict) -> list[str]:
    """Round-17 serving SLO lines (see SERVE_SLO_METRIC): the full
    latency-vs-offered-rate record must be present, self-consistent
    (value == achieved qps), and free of the three contradictions an
    honest open-loop run cannot produce — p99 < p50, achieved >
    offered, SLO good fraction outside [0, 1]."""
    errs = []
    missing = [k for k in ("offered_qps", "achieved_qps", "p50_ms",
                           "p99_ms", "slo_target_ms",
                           "slo_good_fraction") if k not in obj]
    if missing:
        errs.append(f"{name}: serve-slo line missing {missing}")
    off, ach = obj.get("offered_qps"), obj.get("achieved_qps")
    if off is not None and (not _is_num(off) or off <= 0):
        errs.append(f"{name}: offered_qps={off!r} must be a finite "
                    f"number > 0")
        off = None
    if ach is not None and (not _is_num(ach) or ach < 0):
        errs.append(f"{name}: achieved_qps={ach!r} must be a finite "
                    f"number >= 0")
        ach = None
    if off is not None and ach is not None \
            and ach > off + 3e-4 * max(1.0, off):
        errs.append(
            f"{name}: achieved_qps={ach} > offered_qps={off} — the "
            f"open-loop harness measures both from the load-start "
            f"clock, so service cannot outrun arrivals; the line "
            f"contradicts its own schedule")
    if ach is not None and _is_num(obj.get("value")) \
            and abs(obj["value"] - ach) > 2e-4 * max(1.0, ach):
        errs.append(f"{name}: value={obj['value']} is not the "
                    f"recorded achieved_qps ({ach}) — the headline "
                    f"and the SLO record disagree")
    p50, p99 = obj.get("p50_ms"), obj.get("p99_ms")
    for k, v in (("p50_ms", p50), ("p99_ms", p99)):
        if v is not None and (not _is_num(v) or v < 0):
            errs.append(f"{name}: {k}={v!r} must be a finite "
                        f"number >= 0")
    if _is_num(p50) and _is_num(p99) \
            and p99 < p50 - 2e-4 * max(1.0, p50):
        errs.append(
            f"{name}: p99_ms={p99} < p50_ms={p50} — no latency "
            f"distribution has a 99th percentile under its median; "
            f"the published percentile pair is a contradiction")
    frac = obj.get("slo_good_fraction")
    if frac is not None and (not _is_num(frac)
                             or not 0.0 <= frac <= 1.0):
        errs.append(f"{name}: slo_good_fraction={frac!r} must be a "
                    f"finite number in [0, 1]")
    tgt = obj.get("slo_target_ms")
    if tgt is not None:
        if _is_num(tgt):
            ok = tgt > 0
        elif isinstance(tgt, dict) and tgt:
            ok = all(_is_num(v) and v > 0 for v in tgt.values())
        else:
            ok = False
        if not ok:
            errs.append(f"{name}: slo_target_ms={tgt!r} must be a "
                        f"positive number or a non-empty "
                        f"{{kind: positive ms}} dict")
    return errs


def check_serve_chaos_fields(name: str, obj: dict) -> list[str]:
    """Round-18 serving chaos lines (see SERVE_CHAOS_METRIC): the
    resilience record must be present and free of the contradictions
    an honest kill-under-load run cannot produce."""
    errs = []

    def _int(x) -> bool:
        # bool is an int subclass: a JSON-boolean chaos record must
        # not validate as 0/1
        return isinstance(x, int) and not isinstance(x, bool)

    missing = [k for k in ("replicas", "failovers", "shed",
                           "shed_fraction") if k not in obj]
    if missing:
        errs.append(f"{name}: serve-chaos line missing {missing}")
    reps = obj.get("replicas")
    if reps is not None and (not _int(reps) or reps < 1):
        errs.append(f"{name}: replicas={reps!r} must be an int >= 1")
        reps = None
    fo = obj.get("failovers")
    if fo is not None and (not _int(fo) or fo < 0):
        errs.append(f"{name}: failovers={fo!r} must be an int >= 0")
        fo = None
    if fo is not None and fo > 0 and reps == 1:
        errs.append(
            f"{name}: failovers={fo} with replicas=1 — there is no "
            f"surviving replica to fail over TO; the line "
            f"contradicts its own topology")
    shed = obj.get("shed")
    if shed is not None and (not _int(shed) or shed < 0):
        errs.append(f"{name}: shed={shed!r} must be an int >= 0")
        shed = None
    frac = obj.get("shed_fraction")
    if frac is not None and (not _is_num(frac)
                             or not 0.0 <= frac <= 1.0):
        errs.append(f"{name}: shed_fraction={frac!r} must be a "
                    f"finite number in [0, 1]")
        frac = None
    served, submitted = obj.get("served"), obj.get("submitted")
    ints = all(_int(x) for x in (served, submitted))
    if ints and shed is not None and served + shed != submitted:
        errs.append(
            f"{name}: served={served} + shed={shed} != "
            f"submitted={submitted} — admitted and shed queries "
            f"must partition the offered load")
    if ints and frac is not None and shed is not None \
            and submitted > 0 \
            and abs(frac - shed / submitted) > 2e-4:
        errs.append(
            f"{name}: shed_fraction={frac} disagrees with "
            f"shed/submitted = {shed / submitted:.4f}")
    acc = obj.get("slo_accounted")
    if acc is not None and (not _int(acc) or acc < 0):
        errs.append(f"{name}: slo_accounted={acc!r} must be an int "
                    f">= 0")
        acc = None
    if acc is not None and _int(served) and acc > served:
        errs.append(
            f"{name}: slo_accounted={acc} > served={served} — the "
            f"SLO good fraction was computed over shed queries; SLO "
            f"accounting covers ADMITTED retirements only")
    # round-24 self-healing gauges: respawns/quarantines/mttr_s/
    # journal_replayed ride every chaos line (the fleet runs with
    # the resurrection supervisor + durable admission journal armed)
    missing24 = [k for k in ("respawns", "quarantines", "mttr_s",
                             "journal_replayed") if k not in obj]
    if missing24:
        errs.append(f"{name}: serve-chaos line missing the "
                    f"self-healing record {missing24}")
    resp = obj.get("respawns")
    if resp is not None and (not _int(resp) or resp < 0):
        errs.append(f"{name}: respawns={resp!r} must be an int >= 0")
        resp = None
    if resp is not None and resp > 0 and reps == 1:
        errs.append(
            f"{name}: respawns={resp} with replicas=1 — a "
            f"single-replica fleet that lost its only member had "
            f"nothing serving to detect the loss mid-drain, and the "
            f"line claims resurrections without a surviving "
            f"supervisor; the topology contradicts the record")
    quar = obj.get("quarantines")
    if quar is not None and (not _int(quar) or quar < 0):
        errs.append(f"{name}: quarantines={quar!r} must be an int "
                    f">= 0")
        quar = None
    mttr = obj.get("mttr_s")
    if mttr is not None and (not _is_num(mttr) or mttr < 0):
        errs.append(f"{name}: mttr_s={mttr!r} must be null or a "
                    f"finite number >= 0")
        mttr = None
    if mttr is not None and fo is not None and fo == 0 \
            and resp is not None and resp == 0:
        errs.append(
            f"{name}: mttr_s={mttr} with failovers=0 and "
            f"respawns=0 — repair time without any recorded loss or "
            f"repair; nothing was killed, so there is no outage to "
            f"time")
    jr = obj.get("journal_replayed")
    if jr is not None and (not _int(jr) or jr < 0):
        errs.append(f"{name}: journal_replayed={jr!r} must be an "
                    f"int >= 0")
        jr = None
    if jr is not None and _int(submitted) and jr > submitted:
        errs.append(
            f"{name}: journal_replayed={jr} > submitted="
            f"{submitted} — a recovery cannot re-dispatch more "
            f"admitted-unretired queries than were ever submitted; "
            f"the journal claims queries the load never offered")
    return errs


def check_serve_live_fields(name: str, obj: dict) -> list[str]:
    """Round-20 live-graph serving lines (see SERVE_LIVE_METRIC): the
    mutation/epoch/compaction/cache record must be present and free
    of the contradictions an honest live-serving run cannot produce
    — epochs that advanced without mutations (the monotone counter
    only moves when an append batch publishes), a hit fraction
    outside [0, 1], and a compaction count whose claimed trigger
    (delta occupancy crossing the threshold) never happened."""
    errs = []

    def _int(x) -> bool:
        return isinstance(x, int) and not isinstance(x, bool)

    missing = [k for k in ("mutations", "epochs_advanced",
                           "compactions", "cache_hit_fraction",
                           "peak_occupancy", "compact_threshold",
                           "deletions", "reweights", "reseeds",
                           "scheduler_compactions")
               if k not in obj]
    if missing:
        errs.append(f"{name}: serve-live line missing {missing}")
    muts = obj.get("mutations")
    if muts is not None and (not _int(muts) or muts < 0):
        errs.append(f"{name}: mutations={muts!r} must be an int "
                    f">= 0")
        muts = None
    # round-21 mutation-algebra fields: simple int >= 0 counters
    algebra = {}
    for k in ("deletions", "reweights", "reseeds",
              "scheduler_compactions"):
        v = obj.get(k)
        if v is not None and (not _int(v) or v < 0):
            errs.append(f"{name}: {k}={v!r} must be an int >= 0")
            v = None
        algebra[k] = v
    anti = (None
            if algebra["deletions"] is None
            or algebra["reweights"] is None
            else algebra["deletions"] + algebra["reweights"])
    # round-22: the headline line is weighted (the reweight leg of
    # the mutation algebra was previously exercised only by tests —
    # a headline carrying reweights=0 measures half the algebra).
    # ``weighted`` is optional (pre-round-22 artifacts omit it) but
    # present it must agree with the reweight counter both ways:
    # a reweight needs a weight array to rewrite, and a weighted
    # live line that never reweights is the regression this field
    # exists to catch.
    wtd = obj.get("weighted")
    if "weighted" in obj and not isinstance(wtd, bool):
        errs.append(f"{name}: weighted={wtd!r} must be a bool")
        wtd = None
    if wtd is False and algebra["reweights"] is not None \
            and algebra["reweights"] > 0:
        errs.append(
            f"{name}: reweights={algebra['reweights']} on an "
            f"UNWEIGHTED line — a reweight rewrites an edge's "
            f"weight; with no weight array the counter cannot have "
            f"moved (lux_tpu/livegraph.py)")
    if wtd is True and algebra["reweights"] == 0:
        errs.append(
            f"{name}: weighted=True with reweights=0 — the weighted "
            f"headline exists to exercise the reweight leg of the "
            f"mutation algebra; a weighted run that never reweights "
            f"is the round-22 regression this field guards against")
    if algebra["reseeds"] is not None and anti is not None \
            and algebra["reseeds"] > 0 and anti == 0:
        errs.append(
            f"{name}: reseeds={algebra['reseeds']} with "
            f"deletions=0 and reweights=0 — the anti-monotone "
            f"re-seed only runs past a published deletion/reweight; "
            f"a re-seed with nothing to re-seed FROM contradicts "
            f"the line's own mutation record")
    if muts is not None and anti is not None and anti > muts:
        errs.append(
            f"{name}: deletions+reweights={anti} > "
            f"mutations={muts} — every deletion/reweight IS a "
            f"mutation; the algebra counters exceed their own "
            f"total")
    eps = obj.get("epochs_advanced")
    if eps is not None and (not _int(eps) or eps < 0):
        errs.append(f"{name}: epochs_advanced={eps!r} must be an "
                    f"int >= 0")
        eps = None
    if eps is not None and muts is not None:
        if eps > 0 and muts == 0:
            errs.append(
                f"{name}: epochs_advanced={eps} with mutations=0 — "
                f"the monotone epoch counter only advances when a "
                f"mutation batch publishes; the line contradicts "
                f"its own ingest record")
        if muts > 0 and eps == 0:
            errs.append(
                f"{name}: mutations={muts} with epochs_advanced=0 — "
                f"every published append batch IS one epoch "
                f"advance; acknowledged mutations cannot be "
                f"epoch-invisible")
        if eps > muts:
            errs.append(
                f"{name}: epochs_advanced={eps} > mutations={muts} "
                f"— one epoch per PUBLISHED BATCH of >= 1 edge(s); "
                f"more epochs than edges is a contradiction")
    frac = obj.get("cache_hit_fraction")
    if frac is not None and (not _is_num(frac)
                             or not 0.0 <= frac <= 1.0):
        errs.append(f"{name}: cache_hit_fraction={frac!r} must be a "
                    f"finite number in [0, 1]")
    occ = obj.get("peak_occupancy")
    if occ is not None and (not _is_num(occ)
                            or not 0.0 <= occ <= 1.0):
        errs.append(f"{name}: peak_occupancy={occ!r} must be a "
                    f"finite number in [0, 1] (count/capacity of a "
                    f"fixed-capacity block)")
        occ = None
    thr = obj.get("compact_threshold")
    if thr is not None and (not _is_num(thr) or not 0.0 < thr <= 1.0):
        errs.append(f"{name}: compact_threshold={thr!r} must be a "
                    f"finite number in (0, 1]")
        thr = None
    comp = obj.get("compactions")
    if comp is not None and (not _int(comp) or comp < 0):
        errs.append(f"{name}: compactions={comp!r} must be an int "
                    f">= 0")
        comp = None
    if comp is not None and comp > 0 and occ is not None \
            and thr is not None and occ < thr - 1e-9 \
            and (anti is None or anti == 0):
        errs.append(
            f"{name}: compactions={comp} but peak_occupancy={occ} "
            f"never reached compact_threshold={thr} (and no "
            f"deletion/reweight was pending) — the trigger the line "
            f"claims fired could not have; occupancy and the "
            f"compaction count contradict each other")
    sched = algebra["scheduler_compactions"]
    if sched is not None and comp is not None and sched > comp:
        errs.append(
            f"{name}: scheduler_compactions={sched} > "
            f"compactions={comp} — every scheduler fold IS a "
            f"compaction; the scheduler cannot have folded more "
            f"often than the log compacted")
    if sched is not None and sched > 0 and anti is not None \
            and anti == 0 and occ is not None and thr is not None \
            and occ < thr - 1e-9:
        errs.append(
            f"{name}: scheduler_compactions={sched} with "
            f"deletions=0, reweights=0 and peak_occupancy={occ} "
            f"under compact_threshold={thr} — neither scheduler "
            f"trigger the line can evidence (pending anti-monotone "
            f"ops, occupancy) could have fired")
    cap = obj.get("delta_capacity")
    if cap is not None and (not _int(cap) or cap < 1):
        errs.append(f"{name}: delta_capacity={cap!r} must be an int "
                    f">= 1")
    return errs


def check_telemetry(name: str, obj: dict) -> list[str]:
    """Round-7 telemetry field: schema, runs-vs-attempts count, and
    each run's seconds re-deriving a recorded sample."""
    errs = []
    tel = obj["telemetry"]
    if not isinstance(tel, dict) or "runs" not in tel \
            or "counters" not in tel:
        return [f"{name}: telemetry must be a dict with 'runs' and "
                f"'counters', got {tel!r}"]

    runs = tel["runs"]
    if not isinstance(runs, list) or not runs or not all(
            isinstance(r, dict)
            and isinstance(r.get("repeat"), int) and r["repeat"] >= 0
            and isinstance(r.get("iters"), int) and r["iters"] >= 0
            and _is_num(r.get("seconds")) and r["seconds"] > 0
            for r in runs):
        return [f"{name}: telemetry.runs must be a non-empty list of "
                f"{{repeat>=0, iters>=0, seconds>0}}, got {runs!r}"]

    attempts = obj.get("attempts")
    # a retried config (run_attempts) or a crashed outlier rerun
    # (rerun_error) legitimately leaves timed_run events whose sample
    # never made it into the line — only require >= then
    loose = "run_attempts" in obj or "rerun_error" in obj
    if isinstance(attempts, int):
        if (len(runs) < attempts) or (not loose
                                      and len(runs) != attempts):
            errs.append(
                f"{name}: telemetry.runs has {len(runs)} timed runs "
                f"but attempts={attempts}"
                + ("" if loose else " (and the config was never "
                                    "retried)"))

    # per-run decomposition: ne*iters/seconds must land on a recorded
    # sample (kept or discarded) — the telemetry-era analogue of
    # 'per-segment seconds sum to the elapsed'
    ne = obj.get("ne")
    recorded = [s for s in (obj.get("samples") or []) if _is_num(s)] \
        + [d for d in (obj.get("discarded") or []) if _is_num(d)]
    if _is_num(ne) and recorded and not loose:
        for r in runs:
            if r["iters"] <= 0:
                continue
            implied = ne * r["iters"] / r["seconds"] / 1e9
            if min(abs(implied - s) for s in recorded) > 2e-4:
                errs.append(
                    f"{name}: run (repeat {r['repeat']}) implies "
                    f"{implied:.4f} GTEPS — matches no recorded "
                    f"sample; seconds and samples disagree")

    errs += check_health_digest(name, tel)
    errs += check_topology_digest(name, tel)
    errs += check_imbalance_digest(name, tel)

    cnt = tel["counters"]
    if cnt is not None:
        if (not isinstance(cnt, dict)
                or cnt.get("kind") not in ("push", "pull")
                or not isinstance(cnt.get("iters"), int)
                or cnt["iters"] < 0
                or not isinstance(cnt.get("truncated"), bool)):
            errs.append(f"{name}: telemetry.counters malformed: "
                        f"{cnt!r}")
        else:
            numeric = [k for k in ("frontier_last", "frontier_max",
                                   "frontier_sum", "edges_sum",
                                   "residual_first", "residual_last",
                                   "changed_last", "changed_sum")
                       if k in cnt and not _is_num(cnt[k])]
            if numeric:
                errs.append(f"{name}: telemetry.counters non-finite "
                            f"fields {numeric}")
    return errs


CAL_GRADES = ("canonical", "degraded", "uncalibrated")
CAL_DEVIATION_BOUND = 3.0     # lux_tpu/observe.py DEVIATION_BOUND


def check_calibration_field(name: str, obj: dict) -> list[str]:
    """Round-12 session-calibration digest (lux_tpu/observe.py,
    bench.py): a null field means the probe crashed — LOUDLY rejected
    (the line is unlabeled).  Present it must be well-formed AND
    grade "canonical": a "degraded" line was measured in a session
    whose reference probe ran >3x off the canonical figures (the 10x
    tunnel-variance trap, detected), and an "uncalibrated" line was
    measured on a platform with no canonical figures at all (e.g. the
    CPU test mesh) — neither may enter the trajectory silently.  A
    "canonical" grade contradicting its own deviation number is also
    rejected."""
    cal = obj["calibration"]
    if cal is None:
        return [f"{name}: calibration is null — the session probe "
                f"crashed, so the line is unlabeled and cannot enter "
                f"the trajectory (rerun; lux_tpu/observe.py)"]
    if not isinstance(cal, dict):
        return [f"{name}: calibration must be null or a dict, got "
                f"{cal!r}"]
    errs = []
    if not isinstance(cal.get("session"), str) or not cal.get("session"):
        errs.append(f"{name}: calibration.session must be a non-empty "
                    f"string, got {cal.get('session')!r}")
    for k in ("platform", "backend"):
        if not isinstance(cal.get(k), str):
            errs.append(f"{name}: calibration.{k} must be a string, "
                        f"got {cal.get(k)!r}")
    nd = cal.get("ndev")
    if not isinstance(nd, int) or isinstance(nd, bool) or nd < 1:
        errs.append(f"{name}: calibration.ndev={nd!r} must be an "
                    f"int >= 1")
    probe = cal.get("probe")
    if (not isinstance(probe, dict) or not probe
            or not all(_is_num(v) and v >= 0 for v in probe.values())):
        errs.append(f"{name}: calibration.probe must be a dict of "
                    f"finite measured figures, got {probe!r}")
    grade = cal.get("grade")
    dev = cal.get("deviation")
    if grade not in CAL_GRADES:
        errs.append(f"{name}: calibration.grade={grade!r} not one of "
                    f"{CAL_GRADES}")
    elif grade != "canonical":
        errs.append(
            f"{name}: metric line from a {grade.upper()} session "
            f"(probe deviation {dev!r}x vs canonical) — degraded or "
            f"uncalibrated samples never enter the bench trajectory "
            f"silently; rerun in a healthy tunnel session "
            f"(lux_tpu/observe.py)")
    if not _is_num(dev) or dev <= 0:
        errs.append(f"{name}: calibration.deviation={dev!r} must be "
                    f"a finite positive number")
    elif grade == "canonical" and (dev > CAL_DEVIATION_BOUND
                                   or dev < 1.0 / CAL_DEVIATION_BOUND):
        errs.append(
            f"{name}: calibration claims grade=canonical but "
            f"deviation={dev} is outside "
            f"[1/{CAL_DEVIATION_BOUND:g}, {CAL_DEVIATION_BOUND:g}]x "
            f"— the digest contradicts itself")
    aud = cal.get("audit")
    if not isinstance(aud, dict) or not all(
            isinstance(aud.get(k), int) and not isinstance(aud[k], bool)
            and aud[k] >= 0 for k in ("errors", "warnings")):
        errs.append(f"{name}: calibration.audit must be a dict with "
                    f"int errors/warnings >= 0, got {aud!r}")
    elif aud["errors"]:
        errs.append(
            f"{name}: calibration.audit records {aud['errors']} "
            f"error(s) — the probe programs failed their own static "
            f"audit (hoistable loop body / baked constant), so the "
            f"fingerprint measured nothing and cannot label a line")
    return errs


AUDIT_CHECKS = {"gather-budget", "const-bytes", "dtype-discipline",
                "loop-invariant", "collective-schedule",
                "callback-in-loop", "identity-init", "ledger-drift"}


def check_audit_field(name: str, obj: dict) -> list[str]:
    """Round-10 static-audit digest (bench.py -audit,
    lux_tpu/audit.py): optional (older artifacts and -audit off omit
    it); present it must be well-formed AND a clean bill — a metric
    line produced by an audit-failing build is rejected outright."""
    if "audit" not in obj:
        return []
    a = obj["audit"]
    if a is None:
        return []
    if not isinstance(a, dict):
        return [f"{name}: audit must be null or a dict, got {a!r}"]
    errs = []
    if a.get("mode") not in ("warn", "error"):
        errs.append(f"{name}: audit.mode={a.get('mode')!r} not "
                    f"warn|error")
    for k in ("errors", "warnings"):
        v = a.get(k)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errs.append(f"{name}: audit.{k}={v!r} must be an "
                        f"int >= 0")
    fc = a.get("failed_checks")
    if not isinstance(fc, list) or not all(isinstance(c, str)
                                           for c in fc):
        errs.append(f"{name}: audit.failed_checks must be a list of "
                    f"check names, got {fc!r}")
    else:
        unknown = sorted(set(fc) - AUDIT_CHECKS)
        if unknown:
            errs.append(f"{name}: audit.failed_checks has unknown "
                        f"checks {unknown}")
        if a.get("errors") or fc:
            errs.append(
                f"{name}: metric line produced by an -audit-FAILING "
                f"build (errors={a.get('errors')}, "
                f"failed_checks={fc}) — a number measured on a build "
                f"that violates the structural invariants cannot be "
                f"a metric of record (lux_tpu/audit.py)")
    return errs


COMM_TIERS = ("local", "ici", "dcn")


def check_comm_field(name: str, obj: dict) -> list[str]:
    """Round-19 comm-ledger digest (bench.py, lux_tpu/comms.py):
    optional (pre-round-19 artifacts and non-engine lines omit it);
    present it must be a clean, self-consistent byte bill.
    Contradiction rejects: a digest whose ledger FAILED its
    oracle/audit cross-check (errors > 0 — the number was measured on
    a build whose communication cannot be accounted), comm_frac
    outside [0, 1], bytes on a single device (ndev=1 ships nothing),
    a mesh owner/gather exchange shipping ZERO bytes (the exchange's
    collectives cannot be free), and a per-edge figure disagreeing
    with bytes_per_iter * ndev / ne."""
    if "comm" not in obj:
        return []
    c = obj["comm"]
    if c is None:
        return [f"{name}: comm digest is null — the ledger never "
                f"ran, so the line's communication is unaccounted "
                f"(lux_tpu/comms.py)"]
    if not isinstance(c, dict):
        return [f"{name}: comm must be a dict, got {c!r}"]
    errs = []
    ce = c.get("errors")
    if not isinstance(ce, int) or isinstance(ce, bool) or ce < 0:
        errs.append(f"{name}: comm.errors={ce!r} must be an int >= 0")
        return errs
    if ce:
        errs.append(
            f"{name}: comm digest from a LEDGER-FAILING build "
            f"(errors={ce}{': ' + str(c.get('error')) if c.get('error') else ''}) "
            f"— a metric whose byte bill failed its oracle/audit "
            f"cross-check cannot stand (lux_tpu/comms.py)")
        return errs
    nd = c.get("ndev")
    if not isinstance(nd, int) or isinstance(nd, bool) or nd < 1:
        errs.append(f"{name}: comm.ndev={nd!r} must be an int >= 1")
        nd = None
    tier = c.get("tier")
    if tier not in COMM_TIERS:
        errs.append(f"{name}: comm.tier={tier!r} not one of "
                    f"{COMM_TIERS}")
    bpi = c.get("bytes_per_iter")
    if not isinstance(bpi, int) or isinstance(bpi, bool) or bpi < 0:
        errs.append(f"{name}: comm.bytes_per_iter={bpi!r} must be an "
                    f"int >= 0")
        bpi = None
    msgs = c.get("messages")
    if not isinstance(msgs, int) or isinstance(msgs, bool) or msgs < 0:
        errs.append(f"{name}: comm.messages={msgs!r} must be an "
                    f"int >= 0")
        msgs = None
    frac = c.get("comm_frac")
    if not _is_num(frac) or not 0.0 <= frac <= 1.0:
        errs.append(f"{name}: comm.comm_frac={frac!r} must be a "
                    f"finite number in [0, 1] (the modeled comm "
                    f"share of one iteration)")
    bpe = c.get("comm_bytes_per_edge")
    if not _is_num(bpe) or bpe < 0:
        errs.append(f"{name}: comm.comm_bytes_per_edge={bpe!r} must "
                    f"be a finite number >= 0")
        bpe = None
    if nd == 1:
        if bpi:
            errs.append(
                f"{name}: comm.bytes_per_iter={bpi} on a SINGLE "
                f"device — one device has no link to ship over; the "
                f"digest contradicts its own placement")
        if msgs:
            errs.append(
                f"{name}: comm.messages={msgs} on a single device — "
                f"no mesh axis exists to launch collectives over")
        if tier in ("ici", "dcn"):
            errs.append(f"{name}: comm.tier={tier!r} with ndev=1 — a "
                        f"single device sits on no link tier")
    ex = c.get("exchange")
    if nd is not None and nd > 1 and ex in ("owner", "gather") \
            and bpi == 0:
        errs.append(
            f"{name}: comm.bytes_per_iter=0 with exchange={ex!r} on "
            f"{nd} devices — the {ex} exchange's collectives cannot "
            f"ship zero bytes; the digest contradicts the exchange "
            f"mode")
    ne = obj.get("ne")
    if _is_num(ne) and ne > 0 and bpi is not None and bpe is not None \
            and nd is not None:
        want = bpi * nd / ne
        if abs(bpe - want) > 1e-4 * max(1.0, want):
            errs.append(
                f"{name}: comm.comm_bytes_per_edge={bpe} disagrees "
                f"with bytes_per_iter * ndev / ne = {want:.6f} — the "
                f"per-edge claim contradicts the per-iteration bill")
    return errs


MEM_GRADES = ("measured", "modeled")


def check_mem_field(name: str, obj: dict) -> list[str]:
    """Round-22 memory digest (bench.py, lux_tpu/memwatch.py):
    optional (pre-round-22 artifacts omit it); present it must be a
    clean watermark-vs-ledger verdict.  Rejects: a null digest (the
    observatory never ran, so the line's bytes are unaccounted), a
    drifting digest (errors > 0 — the measured peak disagrees with
    the unified byte ledger beyond tolerance, so the run's memory
    cannot be accounted), an unknown grade, a ratio that contradicts
    its own errors=0 claim, and byte counts that are not ints."""
    if "mem" not in obj:
        return []
    m = obj["mem"]
    if m is None:
        return [f"{name}: mem digest is null — the memory "
                f"observatory never ran, so the line's bytes are "
                f"unaccounted (lux_tpu/memwatch.py)"]
    if not isinstance(m, dict):
        return [f"{name}: mem must be null or a dict, got {m!r}"]
    errs = []
    me = m.get("errors")
    if not isinstance(me, int) or isinstance(me, bool) or me < 0:
        errs.append(f"{name}: mem.errors={me!r} must be an int >= 0")
        return errs
    if me:
        errs.append(
            f"{name}: mem digest from a DRIFTING build (errors={me}"
            f"{': ' + str(m.get('error')) if m.get('error') else ''}) "
            f"— a metric whose measured peak disagrees with its own "
            f"byte ledger cannot stand (lux_tpu/memwatch.py)")
        return errs
    if m.get("error"):
        # digest construction failed; _mem_build records the message
        # with errors=1, so errors=0 alongside an error string is a
        # self-contradiction
        errs.append(f"{name}: mem.error={m.get('error')!r} with "
                    f"errors=0 — a failed digest cannot claim a "
                    f"clean bill")
        return errs
    grade = m.get("grade")
    if grade not in MEM_GRADES:
        errs.append(f"{name}: mem.grade={grade!r} not one of "
                    f"{MEM_GRADES}")
    skipped = m.get("skipped")
    if "skipped" in m and not isinstance(skipped, str):
        errs.append(f"{name}: mem.skipped={skipped!r} must be a "
                    f"string (the withheld-verdict reason)")
    if "skipped" in m and not m.get("warnings"):
        errs.append(f"{name}: mem digest skipped "
                    f"({skipped!r}) with warnings=0 — a withheld "
                    f"verdict must count as a warning")
    lb = m.get("ledger_bytes")
    if not isinstance(lb, int) or isinstance(lb, bool) or lb < 0:
        errs.append(f"{name}: mem.ledger_bytes={lb!r} must be an "
                    f"int >= 0")
    # a skipped digest (backend without AOT stats, or a shape under
    # the check floor) withholds the verdict: peak/ratio may be
    # absent or out-of-tolerance and the warning count says why
    pk = m.get("peak_bytes")
    if "skipped" not in m and (not isinstance(pk, int)
                               or isinstance(pk, bool) or pk < 0):
        errs.append(f"{name}: mem.peak_bytes={pk!r} must be an "
                    f"int >= 0")
    tol = m.get("tol")
    if not _is_num(tol) or tol <= 0:
        errs.append(f"{name}: mem.tol={tol!r} must be a finite "
                    f"number > 0")
        tol = None
    ratio = m.get("ratio")
    if "skipped" not in m and (not _is_num(ratio) or ratio < 0):
        errs.append(f"{name}: mem.ratio={ratio!r} must be a finite "
                    f"number >= 0")
        ratio = None
    if _is_num(ratio) and tol is not None and "skipped" not in m \
            and not (1.0 / (1.0 + tol) - 1e-9 <= ratio
                     <= 1.0 + tol + 1e-9):
        errs.append(
            f"{name}: mem.ratio={ratio} outside [1/(1+tol), 1+tol] "
            f"for tol={tol} with errors=0 — the digest contradicts "
            f"its own clean verdict (lux_tpu/memwatch.py drift "
            f"tolerance)")
    return errs


HEALTH_FLAGS = {"nonfinite_state", "nonfinite_residual", "divergence",
                "oscillation", "frontier_stall"}


def check_health_digest(name: str, tel: dict) -> list[str]:
    """Round-9 watchdog digest (bench.py -health): optional (older
    artifacts predate it), null when the watchdog was off; present it
    must be {engine: push|pull, tripped: bool, flags: [known names],
    iters: int >= 0}.  tripped=true with no flags — or flags on a
    clean line at all — is a contradiction: a tripped watchdog fails
    the config, so a metric line's digest must be a clean bill."""
    if "health" not in tel:
        return []
    h = tel["health"]
    if h is None:
        return []
    if not isinstance(h, dict):
        return [f"{name}: telemetry.health must be null or a dict, "
                f"got {h!r}"]
    errs = []
    if h.get("engine") not in ("push", "pull"):
        errs.append(f"{name}: telemetry.health.engine="
                    f"{h.get('engine')!r} not push|pull")
    if not isinstance(h.get("tripped"), bool):
        errs.append(f"{name}: telemetry.health.tripped must be a "
                    f"bool, got {h.get('tripped')!r}")
    flags = h.get("flags")
    if (not isinstance(flags, list)
            or not all(isinstance(f, str) for f in flags)):
        errs.append(f"{name}: telemetry.health.flags must be a list "
                    f"of check names, got {flags!r}")
    else:
        unknown = sorted(set(flags) - HEALTH_FLAGS)
        if unknown:
            errs.append(f"{name}: telemetry.health.flags has unknown "
                        f"checks {unknown}")
        if h.get("tripped") is True or flags:
            errs.append(
                f"{name}: telemetry.health reports a TRIP "
                f"(tripped={h.get('tripped')}, flags={flags}) — a "
                f"tripped watchdog fails its config with a _FAILED "
                f"line and cannot publish a metric line")
    it = h.get("iters")
    if not isinstance(it, int) or isinstance(it, bool) or it < 0:
        errs.append(f"{name}: telemetry.health.iters={it!r} must be "
                    f"an int >= 0")
    return errs


def check_imbalance_digest(name: str, tel: dict) -> list[str]:
    """Round-13 per-part imbalance digest (lux_tpu/tracing.py era,
    telemetry.IterStats.imbalance_digest): optional (older artifacts
    predate it), null when -iter-stats was off.  Present it must be
    {kind: push|pull, index: finite >= 1, parts: non-empty list of
    ints >= 0}, the index must equal max/mean of its own parts (to
    rounding), and — the health-digest contradiction pattern — the
    parts must SUM to the scalar counter digest's edges_sum (push) /
    changed_sum (pull): a published imbalance that contradicts the
    counters it claims to decompose is rejected."""
    if "imbalance" not in tel:
        return []
    imb = tel["imbalance"]
    if imb is None:
        return []
    if not isinstance(imb, dict):
        return [f"{name}: telemetry.imbalance must be null or a "
                f"dict, got {imb!r}"]
    errs = []
    kind = imb.get("kind")
    if kind not in ("push", "pull"):
        errs.append(f"{name}: telemetry.imbalance.kind={kind!r} not "
                    f"push|pull")
    parts = imb.get("parts")
    ints = (isinstance(parts, list) and parts
            and all(isinstance(p, int) and not isinstance(p, bool)
                    and p >= 0 for p in parts))
    if not ints:
        errs.append(f"{name}: telemetry.imbalance.parts must be a "
                    f"non-empty list of ints >= 0, got {parts!r}")
    idx = imb.get("index")
    if not _is_num(idx) or idx < 1.0 - 1e-9:
        errs.append(f"{name}: telemetry.imbalance.index={idx!r} must "
                    f"be a finite number >= 1 (max/mean)")
    elif ints:
        mean = sum(parts) / len(parts)
        if mean <= 0:
            errs.append(f"{name}: telemetry.imbalance over zero "
                        f"total work — a digest with no work cannot "
                        f"carry an index")
        elif abs(idx - max(parts) / mean) > 1e-3 * max(
                1.0, max(parts) / mean):
            errs.append(
                f"{name}: telemetry.imbalance.index={idx} "
                f"contradicts its own parts (max/mean = "
                f"{max(parts) / mean:.4f})")
    cnt = tel.get("counters")
    if ints and isinstance(cnt, dict) and cnt.get("kind") == kind:
        scalar = cnt.get("edges_sum" if kind == "push"
                         else "changed_sum")
        # congruence mod 2^32: the scalar series entries are device
        # uint32 sums (wrapping past 2^32 edges in one iteration on
        # billion-edge graphs) while the parts totals sum exactly on
        # the host — Σ(wrapped) ≡ Σ(exact) (mod 2^32) always holds
        # for an honest line
        if isinstance(scalar, int) and not isinstance(scalar, bool) \
                and (sum(parts) - scalar) % (1 << 32):
            errs.append(
                f"{name}: telemetry.imbalance parts sum "
                f"{sum(parts)} contradicts the counter digest's "
                f"scalar {scalar} (mod 2^32) — per-part and scalar "
                f"counters are the same device-side values and must "
                f"agree")
    return errs


def check_topology_digest(name: str, tel: dict) -> list[str]:
    """Round-11 elastic-recovery digest (bench.py, lux_tpu/
    resilience.py): optional (older artifacts predate it), null when
    the mesh never changed.  Present-and-nonnull it must be
    {shrinks: int >= 1, ndev_final: int >= 1} — and it FAILS the
    line: a mid-run mesh shrink means the number was measured partly
    on N devices and partly on fewer, so a degraded-mesh GTEPS must
    never publish as (or be compared against) a full-mesh metric
    line.  Rerun on the stable topology instead."""
    if "topology" not in tel:
        return []
    topo = tel["topology"]
    if topo is None:
        return []
    if not isinstance(topo, dict):
        return [f"{name}: telemetry.topology must be null or a dict, "
                f"got {topo!r}"]
    errs = []
    sh = topo.get("shrinks")
    if not isinstance(sh, int) or isinstance(sh, bool) or sh < 1:
        # a null digest means "no shrink"; a non-null one must record
        # at least one — shrinks=0 here would be a digest that claims
        # degradation happened while dodging the rejection below
        errs.append(f"{name}: telemetry.topology.shrinks={sh!r} must "
                    f"be an int >= 1 (a null digest means no shrink)")
        sh = None
    nf = topo.get("ndev_final")
    if nf is not None and (not isinstance(nf, int)
                           or isinstance(nf, bool) or nf < 1):
        errs.append(f"{name}: telemetry.topology.ndev_final={nf!r} "
                    f"must be an int >= 1")
    if sh:
        errs.append(
            f"{name}: telemetry.topology records {sh} mid-run mesh "
            f"shrink(s) (final ndev {nf}) — a degraded-mesh GTEPS "
            f"must never be compared against full-mesh lines; rerun "
            f"the config on the stable topology")
    return errs


def iter_event_lines(path: str):
    """Telemetry event objects ({"t": ..., "kind": ...} JSONL, the
    -events FILE format) — so an event log handed to this checker
    audits as events instead of failing as 'no metric lines'."""
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict) and "kind" in obj \
                    and "metric" not in obj:
                yield f"line {i}", obj


def check_event_lines(path: str, events):
    """Minimal schema for a telemetry event log: string kind, numeric
    timestamp, numeric seconds where present."""
    errs = []
    for where, ev in events:
        if not isinstance(ev.get("kind"), str):
            errs.append(f"{path} ({where}): event kind must be a "
                        f"string, got {ev.get('kind')!r}")
        if not _is_num(ev.get("t")):
            errs.append(f"{path} ({where}): event without a numeric "
                        f"'t' timestamp")
        if "seconds" in ev and not _is_num(ev["seconds"]):
            errs.append(f"{path} ({where}): non-finite seconds "
                        f"{ev['seconds']!r}")
    return errs


def check_reorder_pairs(lines) -> list[str]:
    """Cross-line audit of the round-16 reorder A/B (bench.py
    -reorder emits each reordered gather-ab line TOGETHER with its
    paired none baseline): for every reordered line whose paired
    none line (same gather mode, shape and scale) is in the same
    artifact, the measured ``page_fill`` must not DECREASE under the
    reorder — the reorder pass hill-climbs exactly this objective
    (lux_tpu/reorder.py), so a published pair where it fell is
    either a mislabeled line or a broken reorderer, both rejected."""
    errs = []
    by_key = {}
    for where, obj in lines:
        name = obj.get("metric", "")
        m = GATHER_AB_METRIC.match(name)
        if not m or not _is_num(obj.get("page_fill")):
            continue
        mode, ro, tag, scale = (m.group(1), m.group(2) or "none",
                                m.group(3), m.group(4))
        # num_parts is part of the pairing identity: padded fill
        # legitimately shifts with the common depth profile across
        # parts, so a cross-np comparison would reject correct data.
        # Keep EVERY line per key (repeated sessions all check).
        key = (mode, tag, scale, obj.get("np"))
        by_key.setdefault(key, {}).setdefault(ro, []).append(
            (where, name, obj["page_fill"]))
    for key, by_ro in by_key.items():
        for ro, entries in by_ro.items():
            if ro == "none":
                continue
            for where, name, pf in entries:
                for _bw, bname, bpf in by_ro.get("none", []):
                    if pf < bpf - 1e-9:
                        errs.append(
                            f"({where}): {name}: page_fill={pf} "
                            f"DECREASED vs its paired none line "
                            f"{bname} ({bpf}) — the reorder "
                            f"hill-climbs fill, a drop contradicts "
                            f"the published pair")
    return errs


def check_mxu_pairs(lines) -> list[str]:
    """Cross-line audit of the round-23 MXU A/B (bench.py -config
    mxu-ab always emits both sides): an mxu line may only publish
    NEXT TO its paired vpu baseline — same scale and num_parts, in
    the same artifact — and the pair must carry IDENTICAL modeled
    rates (both sides stamp the rates for both paths from one
    payload width, so a disagreement means the lines are not the
    same experiment).  A lone MXU number has no step-change to show
    and is rejected, the same pairing rule as the reorder A/B."""
    errs = []
    by_key = {}
    for where, obj in lines:
        m = MXU_AB_METRIC.match(obj.get("metric", ""))
        if not m:
            continue
        key = (m.group(2), obj.get("np"))
        by_key.setdefault(key, {}).setdefault(m.group(1), []).append(
            (where, obj.get("metric"), obj))
    for key, by_mode in by_key.items():
        for where, name, obj in by_mode.get("mxu", []):
            base = by_mode.get("vpu", [])
            if not base:
                errs.append(
                    f"({where}): {name}: mxu line has NO paired vpu "
                    f"baseline (same comm scale + np) in the "
                    f"artifact — a lone MXU number has no "
                    f"step-change to show")
                continue
            for _bw, bname, bobj in base:
                for k in ("mxu_row_ns", "vpu_row_ns"):
                    a, b = obj.get(k), bobj.get(k)
                    if _is_num(a) and _is_num(b) \
                            and abs(a - b) > 1e-9:
                        errs.append(
                            f"({where}): {name}: {k}={a} disagrees "
                            f"with its paired baseline {bname} "
                            f"({b}) — the sides modeled different "
                            f"payload widths; the pair is not one "
                            f"experiment")
    return errs


def check_file(path: str, *, legacy_ok: bool):
    errs, warns, n = [], [], 0
    try:
        lines = list(iter_metric_lines(path))
    except (OSError, UnicodeDecodeError) as e:
        return [f"{path}: unreadable ({e})"], [], 0
    if not lines:
        events = list(iter_event_lines(path))
        if events:
            return check_event_lines(path, events), [], len(events)
        return [f"{path}: no metric lines found"], [], 0
    for where, obj in lines:
        n += 1
        e, w = check_line(obj, legacy_ok=legacy_ok)
        errs += [f"{path} ({where}): {m}" for m in e]
        warns += [f"{path} ({where}): {m}" for m in w]
    errs += [f"{path} {m}" for m in check_reorder_pairs(lines)]
    errs += [f"{path} {m}" for m in check_mxu_pairs(lines)]
    return errs, warns, n


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate bench metric lines against the "
                    "round-6 resilience schema")
    ap.add_argument("files", nargs="+", metavar="FILE")
    ap.add_argument("-legacy-ok", action="store_true",
                    dest="legacy_ok",
                    help="downgrade pre-round-6 metadata gaps "
                         "(missing samples/attempts/discarded) to "
                         "warnings — for auditing historical "
                         "BENCH_r01-05 artifacts")
    args = ap.parse_args(argv)

    total_errs, total = [], 0
    for path in args.files:
        errs, warns, n = check_file(path, legacy_ok=args.legacy_ok)
        total += n
        total_errs += errs
        for w in warns:
            print(f"WARNING: {w}", file=sys.stderr)
    for e in total_errs:
        print(f"ERROR: {e}", file=sys.stderr)
    if total_errs:
        print(f"check_bench: {len(total_errs)} error(s) over {total} "
              f"metric line(s) — the bench schema audit FAILED",
              file=sys.stderr)
        return 1
    print(f"check_bench: {total} metric line(s) OK "
          f"({len(args.files)} file(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
