#!/usr/bin/env python
"""Validate bench metric lines against the resilience-era schema.

The driver's BENCH_*.json artifacts wrap bench.py's stdout; each
metric line there is one JSON object.  Round 6 added an audit trail
(lux_tpu/resilience.py): ``attempts`` (total timed runs, outlier
reruns included), ``discarded`` (samples thrown out by the >3x
discard-and-rerun rule), and ``run_attempts`` when a whole config was
retried after a transient crash.  A headline number whose line lacks
that metadata can silently median over a tunnel collapse — exactly
the BENCH_r05 pagerank-mp incident ([0.1116, 0.0107, 0.1118]) this
schema exists to make impossible — so missing metadata FAILS the
check.

Usage:
    python scripts/check_bench.py [-legacy-ok] FILE...

FILE is a driver artifact (JSON object with a ``tail`` transcript), a
raw JSONL of metric lines, or a single JSON metric object.
``-legacy-ok`` downgrades pre-round-6 metadata gaps (missing
samples/attempts/discarded) to warnings so the historical BENCH_r01-05
artifacts still audit cleanly; structural errors (bad median,
inconsistent counts, malformed lines) always fail.

Checked per metric line:
- required keys: metric, value, unit, vs_baseline
- samples: non-empty list of finite numbers, value == median(samples)
  (to rounding)
- attempts: int, == len(samples) + len(discarded) — every discarded
  sample was either re-run (adding a kept sample) or counted
- discarded: list of finite numbers, each >FACTORx off the kept median
  is not re-checked here (the factor is a bench flag), but discarded
  samples must not also appear in samples
- run_attempts (optional): int >= 2
- *_FAILED lines: error message plus attempts and failure_class
  ("retryable" | "fatal")

Exit status: 0 clean, 1 any error (loud, listed on stderr).
"""

from __future__ import annotations

import argparse
import json
import sys
from statistics import median

LEGACY_KEYS = ("samples", "attempts", "discarded")


def iter_metric_lines(path: str):
    """Yield (lineno_label, dict) metric objects from ``path``."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
    if isinstance(doc, dict) and "tail" in doc:      # driver artifact
        src = doc["tail"].splitlines()
        label = "tail line"
    elif isinstance(doc, dict) and "metric" in doc:  # one bare object
        yield "object", doc
        return
    else:                                            # raw JSONL
        src = text.splitlines()
        label = "line"
    for i, line in enumerate(src, 1):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            yield f"{label} {i}", {"_unparseable": line[:120]}
            continue
        if isinstance(obj, dict) and "metric" in obj:
            yield f"{label} {i}", obj


def _is_num(x) -> bool:
    return isinstance(x, (int, float)) and not isinstance(x, bool) \
        and x == x and abs(x) != float("inf")


def check_line(obj: dict, *, legacy_ok: bool):
    """Returns (errors, warnings) string lists for one metric line."""
    errs, warns = [], []
    if "_unparseable" in obj:
        return [f"unparseable JSON: {obj['_unparseable']}"], []
    name = obj.get("metric", "?")

    if name.endswith("_FAILED"):
        if not obj.get("error"):
            errs.append(f"{name}: failure line without an 'error'")
        missing = [k for k in ("attempts", "failure_class")
                   if k not in obj]
        if missing:
            (warns if legacy_ok else errs).append(
                f"{name}: failure line missing {missing}")
        elif obj["failure_class"] not in ("retryable", "fatal"):
            errs.append(f"{name}: failure_class="
                        f"{obj['failure_class']!r} not retryable|fatal")
        return errs, warns

    for k in ("metric", "value", "unit", "vs_baseline"):
        if k not in obj:
            errs.append(f"{name}: missing required key {k!r}")
    if "value" in obj and not _is_num(obj["value"]):
        errs.append(f"{name}: non-finite value {obj['value']!r}")

    missing = [k for k in LEGACY_KEYS if k not in obj]
    if missing:
        msg = (f"{name}: missing resilience metadata {missing} "
               f"(pre-round-6 schema)")
        (warns if legacy_ok else errs).append(msg)

    samples = obj.get("samples")
    if samples is not None:
        if (not isinstance(samples, list) or not samples
                or not all(_is_num(s) for s in samples)):
            errs.append(f"{name}: samples must be a non-empty list "
                        f"of finite numbers, got {samples!r}")
            samples = None
    if samples and _is_num(obj.get("value")):
        m = median(samples)
        # value = round(median(raw), 4) while samples are rounded
        # individually: the two medians agree to ~1e-4
        if abs(obj["value"] - m) > 2e-4:
            errs.append(f"{name}: value {obj['value']} is not the "
                        f"median of samples ({m:.4f}) — collapsed "
                        f"sample silently medianed?")

    discarded = obj.get("discarded")
    if discarded is not None:
        if (not isinstance(discarded, list)
                or not all(_is_num(d) for d in discarded)):
            errs.append(f"{name}: discarded must be a list of finite "
                        f"numbers, got {discarded!r}")
            discarded = None
    if samples and discarded:
        # a kept sample equal to a discarded one is a contradiction
        # (discards are >FACTORx off the median the keeps define) —
        # it means a discarded collapse was ALSO medianed
        overlap = sorted(set(samples) & set(discarded))
        if overlap:
            errs.append(f"{name}: {overlap} appear in both samples "
                        f"and discarded — discarded sample medianed")

    attempts = obj.get("attempts")
    if attempts is not None:
        if not isinstance(attempts, int) or attempts < 1:
            errs.append(f"{name}: attempts must be a positive int, "
                        f"got {attempts!r}")
        elif samples is not None and discarded is not None:
            want = len(samples) + len(discarded)
            if attempts != want:
                errs.append(
                    f"{name}: attempts={attempts} inconsistent with "
                    f"{len(samples)} samples + {len(discarded)} "
                    f"discarded (= {want})")

    ra = obj.get("run_attempts")
    if ra is not None and (not isinstance(ra, int) or ra < 2):
        errs.append(f"{name}: run_attempts={ra!r} (recorded only "
                    f"when >= 2)")
    return errs, warns


def check_file(path: str, *, legacy_ok: bool):
    errs, warns, n = [], [], 0
    try:
        lines = list(iter_metric_lines(path))
    except (OSError, UnicodeDecodeError) as e:
        return [f"{path}: unreadable ({e})"], [], 0
    if not lines:
        return [f"{path}: no metric lines found"], [], 0
    for where, obj in lines:
        n += 1
        e, w = check_line(obj, legacy_ok=legacy_ok)
        errs += [f"{path} ({where}): {m}" for m in e]
        warns += [f"{path} ({where}): {m}" for m in w]
    return errs, warns, n


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate bench metric lines against the "
                    "round-6 resilience schema")
    ap.add_argument("files", nargs="+", metavar="FILE")
    ap.add_argument("-legacy-ok", action="store_true",
                    dest="legacy_ok",
                    help="downgrade pre-round-6 metadata gaps "
                         "(missing samples/attempts/discarded) to "
                         "warnings — for auditing historical "
                         "BENCH_r01-05 artifacts")
    args = ap.parse_args(argv)

    total_errs, total = [], 0
    for path in args.files:
        errs, warns, n = check_file(path, legacy_ok=args.legacy_ok)
        total += n
        total_errs += errs
        for w in warns:
            print(f"WARNING: {w}", file=sys.stderr)
    for e in total_errs:
        print(f"ERROR: {e}", file=sys.stderr)
    if total_errs:
        print(f"check_bench: {len(total_errs)} error(s) over {total} "
              f"metric line(s) — the bench schema audit FAILED",
              file=sys.stderr)
        return 1
    print(f"check_bench: {total} metric line(s) OK "
          f"({len(args.files)} file(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
