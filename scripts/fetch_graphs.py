#!/usr/bin/env python
"""Downloadable real-graph suite (ROADMAP item 1, round 16).

The reference's own evaluation datasets — Twitter-2010 (LAW/WebGraph)
and the NetFlix prize ratings (reference README.md:88) — are where
page locality actually EXISTS (social/web graphs cluster; R-MAT does
not, the round-15 finding).  This script downloads a chosen dataset,
converts it to the .lux CSC format (lux_tpu/format.py), optionally
runs the page-aware reorder pass and writes its ``.perm`` sidecar,
and fscks the result — so a live-tunnel session can run

    python scripts/fetch_graphs.py twitter-2010 -out /data
    python bench.py -config gather-ab -reorder hillclimb ...

against a real locality-rich graph.  Everything network-facing is
gated and resumable: nothing in tier-1 depends on this script having
run (the offline counterpart is ``convert.community_graph``, the
scrambled planted-partition synthetic).

Sources (mirrors can be swapped with -url):
  twitter-2010  SNAP twitter-2010.txt.gz edge list (~25 GB unpacked;
                41.6M vertices, 1.47B edges)
  netflix       the NetFlix prize rating files are no longer
                hosted first-party; pass -url to a mirror of
                nf_prize_dataset.tar.gz, or use the synthetic
                ``convert.netflix_like_edges`` shape (bench_netflix)

Usage:
    python scripts/fetch_graphs.py DATASET [-out DIR] [-url URL]
        [-reorder none|native|hillclimb] [-np N]
"""

from __future__ import annotations

import argparse
import gzip
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

DATASETS = {
    "twitter-2010": {
        "url": "https://snap.stanford.edu/data/twitter-2010.txt.gz",
        "kind": "edge-list-gz",
    },
    "netflix": {
        "url": None,        # no stable first-party host; pass -url
        "kind": "netflix-tar",
    },
}

def _download(url: str, dest: str) -> str:
    if os.path.exists(dest) and os.path.getsize(dest) > 0:
        print(f"# {dest} already present, skipping download")
        return dest
    print(f"# downloading {url} -> {dest}")
    tmp = dest + ".part"
    with urllib.request.urlopen(url) as r, open(tmp, "wb") as f:
        total = 0
        while True:
            buf = r.read(1 << 22)
            if not buf:
                break
            f.write(buf)
            total += len(buf)
            print(f"\r#   {total / 1e9:.2f} GB", end="",
                  file=sys.stderr)
    print(file=sys.stderr)
    os.replace(tmp, dest)
    return dest


def _chunks(gz_path: str):
    """Yield ~64 MB text chunks split at line boundaries."""
    with gzip.open(gz_path, "rb") as f:
        rem = b""
        while True:
            buf = f.read(1 << 26)
            if not buf:
                if rem.strip():
                    yield rem
                return
            buf = rem + buf
            cut = buf.rfind(b"\n")
            if cut < 0:
                rem = buf
                continue
            yield buf[:cut]
            rem = buf[cut + 1:]


def _parse_pairs(chunk: bytes) -> np.ndarray:
    """Whitespace 'src dst' pairs -> int64 [n, 2] (comment lines
    dropped; no np.loadtxt — its per-line python path is hours over
    a billion-edge file)."""
    if b"#" in chunk:
        chunk = b"\n".join(ln for ln in chunk.split(b"\n")
                           if not ln.lstrip().startswith(b"#"))
    toks = chunk.split()
    if not toks:
        return np.zeros((0, 2), np.int64)
    arr = np.array(toks, dtype=np.int64)
    if arr.size % 2:
        raise ValueError("odd token count — not a 'src dst' list")
    return arr.reshape(-1, 2)


def _edge_list_gz_to_lux(gz_path: str, lux_path: str) -> None:
    """Stream a whitespace 'src dst' edge list (gz) into dst-sorted
    CSC and write .lux, in two passes: a counting pass (ne + max id)
    then a fill pass into PREALLOCATED uint32 arrays — peak memory is
    the 2 x 4 x ne edge arrays plus edges_to_csc's fused-radix
    temporaries (native.sort_kv carries payloads in place), never the
    chunk-list + concatenate doubling a single-pass build would pay
    at the 1.47B-edge Twitter-2010 size."""
    from lux_tpu.convert import edges_to_csc
    from lux_tpu import format as luxfmt

    ne = 0
    vmax = -1
    for chunk in _chunks(gz_path):
        arr = _parse_pairs(chunk)
        if arr.size:
            ne += len(arr)
            vmax = max(vmax, int(arr.max()))
        print(f"\r#   counted {ne / 1e6:.0f} M edges", end="",
              file=sys.stderr)
    print(file=sys.stderr)
    if vmax >= 1 << 32:
        raise ValueError(f"vertex id {vmax} exceeds the .lux uint32 "
                         f"id space")
    src = np.empty(ne, np.uint32)
    dst = np.empty(ne, np.uint32)
    pos = 0
    for chunk in _chunks(gz_path):
        arr = _parse_pairs(chunk)
        if arr.size:
            src[pos:pos + len(arr)] = arr[:, 0]
            dst[pos:pos + len(arr)] = arr[:, 1]
            pos += len(arr)
        print(f"\r#   parsed {pos / 1e6:.0f} M edges", end="",
              file=sys.stderr)
    print(file=sys.stderr)
    assert pos == ne
    nv = vmax + 1
    row_ptrs, col_idx, _w, deg = edges_to_csc(src, dst, nv)
    luxfmt.write_lux(lux_path, row_ptrs, col_idx,
                     degrees=deg.astype(np.uint32))
    print(f"# wrote {lux_path}: nv={nv} ne={len(col_idx)}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="download + convert the real-graph suite "
                    "(Twitter-2010 / NetFlix) to .lux")
    ap.add_argument("dataset", choices=sorted(DATASETS))
    ap.add_argument("-out", default=".", help="output directory")
    ap.add_argument("-url", default=None,
                    help="override/mirror URL for the raw download")
    ap.add_argument("-reorder", default="none",
                    choices=["none", "native", "hillclimb"],
                    help="run the page-aware reorder pass "
                         "(lux_tpu/reorder.py) and write the .perm "
                         "sidecar beside the .lux")
    ap.add_argument("-np", type=int, default=1, dest="num_parts",
                    help="partitions the reorder objective scores "
                         "against")
    args = ap.parse_args(argv)

    meta = DATASETS[args.dataset]
    url = args.url or meta["url"]
    if url is None:
        print(f"ERROR: {args.dataset} has no stable first-party "
              f"host; pass -url with a mirror "
              f"(see the module docstring)", file=sys.stderr)
        return 2
    os.makedirs(args.out, exist_ok=True)
    raw = os.path.join(args.out, os.path.basename(url))
    lux = os.path.join(args.out, args.dataset + ".lux")
    try:
        _download(url, raw)
    except OSError as e:
        print(f"ERROR: download failed ({e}); this script needs "
              f"network access — offline sessions use "
              f"convert.community_graph instead", file=sys.stderr)
        return 1

    if meta["kind"] == "edge-list-gz":
        if not os.path.exists(lux):
            _edge_list_gz_to_lux(raw, lux)
    else:
        print(f"ERROR: no converter implemented for "
              f"{meta['kind']!r} yet; unpack the ratings and use "
              f"scripts/bench_netflix.py's loader", file=sys.stderr)
        return 2

    if args.reorder != "none":
        from lux_tpu import format as luxfmt
        from lux_tpu.graph import Graph
        from lux_tpu.reorder import page_reorder

        g = Graph.from_file(lux, validate=True)
        _g2, perm, rep = page_reorder(g, method=args.reorder,
                                      num_parts=args.num_parts,
                                      verbose=True)
        luxfmt.write_perm_sidecar(lux, perm)
        print(f"# sidecar written: page_fill "
              f"{rep['baseline_fill']} -> {rep['chosen_fill']}")

    import subprocess
    fsck = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fsck_lux.py")
    return subprocess.call([sys.executable, fsck, lux])


if __name__ == "__main__":
    sys.exit(main())
