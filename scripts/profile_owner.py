"""Owner-side gather premise test (round 3, VERDICT #1).

The big-table tax: element gathers from tables past ~64-128 MB cost
14.6 ns/elem vs 8.8 below (profile_bigtable.py).  Owner-side message
generation only pays off if a PER-PART gather — each part fetching
from its OWN < 64 MB state shard — actually runs at the small-table
rate.  Three formulations of the same total work (N indices against a
[P, V] state table, every index local to its part):

  flat    one gather from the flattened [P*V] table (today's engine;
          the big-table baseline)
  vmap    jax.vmap over parts of take(state[p], idx[p]) — one batched
          gather; does the emitter see the small per-batch table?
  scan    lax.scan over parts, each step gathering from ONE [V] shard
          (dynamic-slice of the stacked state) — serial over parts,
          but each gather's operand is genuinely small

Methodology: the trusted recipe as a library call
(lux_tpu.timing.loop_bench, the PR-7/round-12 migration off the
documented timing traps): K iterations inside one jit, loop-DEPENDENT
carry, scalar output, host-fetch fence — big operands ride the carry
as jit arguments, and the median over repeats absorbs tunnel jitter.

Usage: PYTHONPATH=/root/repo:/root/.axon_site \
    python scripts/profile_owner.py [P logV]
"""

import sys
from statistics import median

import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu.timing import loop_bench

K = 10
P = int(sys.argv[1]) if len(sys.argv) > 1 else 4
logV = int(sys.argv[2]) if len(sys.argv) > 2 else 24   # 64 MB/part f32
V = 1 << logV
N = 1 << 25                      # total indices (33.5M)
Np = N // P
rng = np.random.default_rng(0)

state = jnp.asarray(rng.random((P, V), np.float32))
idx_local = jnp.asarray(rng.integers(0, V, (P, Np)).astype(np.int32))
# the same access pattern as one flat gather from [P*V]
idx_flat = (jnp.arange(P, dtype=jnp.int32)[:, None] * V +
            idx_local).reshape(-1)


def bench(name, fn, idx):
    def step(carry):
        t, i = carry
        sv = fn(t, i)
        return sv, (t + sv * 1e-30, i)

    samples, _ = loop_bench(step, (state, idx), K, repeats=3)
    dt = median(samples)
    print(f"{name:10s} {dt * 1e3:8.2f} ms  ({dt / N * 1e9:6.2f} "
          f"ns/elem)  [{' '.join(f'{s * 1e3:.2f}' for s in samples)}"
          f" ms]", flush=True)


def flat(t, i):
    return jnp.sum(jnp.take(t.reshape(-1), i, axis=0))


def vmapped(t, i):
    return jnp.sum(jax.vmap(lambda tp, ip: jnp.take(tp, ip, axis=0))(
        t, i))


def scanned(t, i):
    def step(acc, x):
        tp, ip = x
        return acc + jnp.sum(jnp.take(tp, ip, axis=0)), None
    out, _ = jax.lax.scan(step, jnp.float32(0), (t, i))
    return out


if __name__ == "__main__":
    print(f"P={P} V={V} ({V * 4 >> 20} MB/part, {P * V * 4 >> 20} MB "
          f"total), N={N}")
    bench("flat", flat, idx_flat)
    bench("vmap", vmapped, idx_local)
    bench("scan", scanned, idx_local)
