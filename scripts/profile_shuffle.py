"""Microbench pallas take_along_axis (tpu.dynamic_gather) throughput.

a) axis=1: per-row 128-lane shuffle on [R, 128]
b) axis=0: per-lane sublane gather on [M, 128] for varying M
c) transpose cost for comparison

Round 15: ported onto the observatory recipe (lux_tpu.timing
.loop_bench — loop-dependent carry, scalar output, one jit, fetch
fence); the old block_until_ready pattern is the PERF_NOTES trap and
is now grep-gated out of scripts/ (lint_lux bench-fence).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from lux_tpu.observe import median_mad
from lux_tpu.timing import loop_bench

REPS = 10
rng = np.random.default_rng(0)


def timeit(name, fn, x0, idx0, n_elems=None):
    """fn(x, idx) -> array; timed with a loop-dependent x carry so
    the kernel can neither hoist nor dead-code."""
    def step(c):
        x, i = c
        out = fn(x, i)
        sv = jnp.sum(out[..., :1])
        return sv, (x + sv * 1e-30, i)

    samples, _ = loop_bench(step, (x0, idx0), REPS, repeats=3)
    dt, mad = median_mad(samples)
    r = f"  ({n_elems / dt / 1e9:7.2f} G/s)" if n_elems else ""
    print(f"{name:44s} {dt * 1e3:8.2f} ms{r}  mad {mad * 1e3:.2f} ms")
    return dt


# ---- a) axis=1 lane shuffle ---------------------------------------------
R = 1 << 18  # 262144 rows x 128 = 33.5M elements
x = jnp.asarray(rng.random((R, 128), np.float32))
idx1 = jnp.asarray(rng.integers(0, 128, (R, 128)).astype(np.int32))


def shuffle_kernel(x_ref, i_ref, o_ref):
    o_ref[:] = jnp.take_along_axis(x_ref[:], i_ref[:], axis=1)


def lane_shuffle(x, idx, bm):
    return pl.pallas_call(
        shuffle_kernel,
        grid=(R // bm,),
        in_specs=[
            pl.BlockSpec((bm, 128), lambda b: (b, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, 128), lambda b: (b, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bm, 128), lambda b: (b, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((R, 128), x.dtype),
    )(x, idx)


for bm in (512, 2048):
    f = jax.jit(functools.partial(lane_shuffle, bm=bm))
    timeit(f"lane shuffle axis=1 [R,128] bm={bm}", f, x, idx1,
           n_elems=R * 128)

# ---- b) axis=0 sublane gather, varying M --------------------------------
def sub_kernel(x_ref, i_ref, o_ref):
    o_ref[:] = jnp.take_along_axis(x_ref[:], i_ref[:], axis=0)


def sub_gather(x, idx, M):
    return pl.pallas_call(
        sub_kernel,
        grid=(x.shape[0] // M,),
        in_specs=[
            pl.BlockSpec((M, 128), lambda b: (b, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((M, 128), lambda b: (b, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((M, 128), lambda b: (b, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x, idx)


for M in (8, 64, 512, 4096):
    idx0 = jnp.asarray(rng.integers(0, M, (R, 128)).astype(np.int32))
    f = jax.jit(functools.partial(sub_gather, M=M))
    timeit(f"sublane gather axis=0 M={M}", f, x, idx0, n_elems=R * 128)

# ---- c) transpose -------------------------------------------------------
xt = jnp.asarray(rng.random((16384, 2048), np.float32))
timeit("xla transpose [16384,2048]", lambda a, _i: a.T.copy(), xt,
       jnp.zeros((1,), jnp.int32), n_elems=16384 * 2048)
