#!/usr/bin/env python
"""AST-level convention linter for the lux_tpu Python tree.

The companion of lux_tpu/audit.py: where the auditor checks TRACED
jaxprs, this checks SOURCE against the repo conventions that cannot
be seen from a jaxpr (CLAUDE.md "Conventions"):

  jit-closure   A function handed to ``jax.jit`` (decorator, direct
                call, or ``functools.partial(jax.jit, ...)``) closes
                over a name bound in an enclosing function to an
                array-constructing expression (``jnp.asarray(...)``,
                ``self.arrays[...]``, ...).  Engines must take graph
                arrays as jit ARGUMENTS — a closed-over array bakes
                into the XLA program as a constant (the HTTP-413
                remote-compile wall; the jaxpr-level twin is the
                auditor's const-bytes ceiling).
  oracle        Every app module (lux_tpu/apps/*.py) must define a
                top-level NumPy oracle named ``reference_*`` — the
                "new device code gets an oracle test first"
                convention.  Round 21: deletion-capable builders
                (``*decremental*``, ``delete_edges``,
                ``reweight_edges``) anywhere in the library tree must
                define or cite a ``reference_*decremental`` oracle —
                anti-monotone mutations are proved equal to full
                recompute at the same epoch.
  citation      Every module in lux_tpu/engine/ and lux_tpu/ops/
                must cite the reference implementation (a
                ``file:line`` pattern like ``pull_model.inl:423``) in
                its module docstring, for parity auditing.
  part-stats-oracle
                Every engine ``*_stats``/``*_health`` loop variant
                whose docstring cites per-part counters (round 13,
                lux_tpu/tracing.py era) must be covered by a test
                that exercises it against a per-part NumPy oracle:
                some file under tests/ must reference BOTH the
                variant name AND a ``per_part*`` oracle helper —
                mirroring the app-module oracle-presence check, so a
                new per-part counter variant cannot ship without its
                sum-over-parts-bitwise proof.
  hot-path-metrics
                No metrics call (``metrics.counter(...)``,
                ``self.metrics.histogram(...).observe(...)``, any
                call whose target chain references a ``metrics``
                name or attribute — lux_tpu/metrics.py) may appear
                inside engine device code (lux_tpu/engine/,
                lux_tpu/ops/) or inside a fused-loop body (a
                function handed to ``fori_loop``/``while_loop``/
                ``scan``) anywhere in the tree.  Metrics are
                HOST-side, segment-boundary-only by contract — the
                same rationale as the audited callback-in-loop ban:
                a metrics call in a traced loop body either bakes a
                host callback into the fused program or silently
                records nothing per iteration.
  chaos-coverage
                Every fault-plan ACTION constant in lux_tpu/faults.py
                (a module-level ALL-CAPS name bound to a string
                literal — ``WORKER_KILL = "worker_kill"``, ...) must
                be exercised by at least one file under tests/: some
                test must reference the constant's name or its string
                value.  A fault action nobody drills is a recovery
                path that ships untested — the exact failure mode
                faults.py exists to prevent (round 24: the
                FLEET_CRASH / REPLICA_FLAP self-healing drills ride
                this gate).  Pragma-suppressible on the assignment
                line for actions that are deliberately
                library-internal.
  collective-scope
                No collective-primitive call (``jax.lax.ppermute``,
                ``all_to_all``, ``psum_scatter``/``reduce_scatter``,
                ``all_gather``, ``psum``/``pmin``/``pmax``) outside
                ``lux_tpu/ops/`` and ``lux_tpu/engine/``.  Those two
                trees are where the jaxpr auditor's
                collective-schedule check and the comm observatory's
                byte oracle (lux_tpu/comms.py) know to look — a
                collective planted elsewhere ships unaccounted bytes
                the ledger never prices.  Pragma-suppressible for
                deliberate exceptions (the link-bandwidth probes,
                the device placement check).
  bench-fence   (scripts/ only) No ``block_until_ready`` fencing in
                benchmark scripts: it can return early through the
                axon tunnel AND lets XLA hoist loop-invariant work,
                the two measurement traps PERF_NOTES documents — the
                trusted recipe is ``lux_tpu.timing.loop_bench``
                (loop-dependent carry, scalar output, one jit, fetch
                fence), which rounds 12/15 ported every profile
                script onto.

Suppression: an explicit ``# audit: allow(<check>)`` pragma on the
flagged line, or in the contiguous comment block directly above it,
with a one-line justification — the same syntax the jaxpr auditor
honors through eqn source info.

Usage:  python scripts/lint_lux.py [PATHS...]   (default: lux_tpu)
Exit status: 0 clean, 1 any unsuppressed finding.
"""

from __future__ import annotations

import argparse
import ast
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PRAGMA_RE = re.compile(r"#\s*audit:\s*allow\(([a-z-]+)\)")

CITATION_RE = re.compile(r"[\w/]+\.(?:h|cc|cu|cuh|inl|py|md):\d+")

# expressions whose result is (or wraps) a device/host array big
# enough to matter if baked into a jit as a constant
ARRAY_MAKER_FUNCS = {
    "asarray", "array", "zeros", "ones", "full", "arange", "empty",
    "linspace", "zeros_like", "ones_like", "full_like", "stack",
    "concatenate", "pad",
}
ARRAY_MAKER_MODULES = {"jnp", "np", "numpy", "jax"}
ARRAY_ATTR_SOURCES = {"arrays", "graph_args"}


class Finding:
    def __init__(self, path, line, check, message):
        self.path, self.line, self.check, self.message = \
            path, line, check, message

    def __str__(self):
        rel = os.path.relpath(self.path, REPO)
        return f"{rel}:{self.line}: [{self.check}] {self.message}"


def _suppressed(lines, line_no: int, check: str) -> bool:
    """Pragma on the flagged line or the contiguous comment block
    directly above it (mirrors lux_tpu/audit._pragma_allows)."""

    def hit(text):
        return any(m.group(1) == check
                   for m in PRAGMA_RE.finditer(text))

    if 0 < line_no <= len(lines) and hit(lines[line_no - 1]):
        return True
    ln = line_no - 2
    while ln >= 0:
        stripped = lines[ln].strip()
        if stripped.startswith("#"):
            if hit(stripped):
                return True
            ln -= 1
        elif not stripped or stripped.startswith("@"):
            # blank lines and decorators don't break the pragma
            # block (a pragma above a @jax.jit stack covers the def)
            ln -= 1
        else:
            break
    return False


# ---------------------------------------------------------------------
# check: jit-closure


def _is_array_maker(expr: ast.expr) -> bool:
    """Does this RHS construct an array?  (Heuristic on the repo's
    idioms: jnp/np makers, ``self.arrays[...]`` / ``.graph_args``
    access, or a tuple/starred of the same.)"""
    if isinstance(expr, ast.Call):
        f = expr.func
        if isinstance(f, ast.Attribute):
            base = f.value
            if (f.attr in ARRAY_MAKER_FUNCS
                    and isinstance(base, ast.Name)
                    and base.id in ARRAY_MAKER_MODULES):
                return True
            # jnp.asarray(...).reshape(...) etc.
            if isinstance(base, ast.Call):
                return _is_array_maker(base)
        if isinstance(f, ast.Name) and f.id in ("dev",):
            # the engines' ``dev = jnp.asarray`` placement helper
            return True
    if isinstance(expr, ast.Subscript):
        v = expr.value
        if isinstance(v, ast.Attribute) and v.attr in ARRAY_ATTR_SOURCES:
            return True
        if isinstance(v, ast.Name) and v.id in ARRAY_ATTR_SOURCES:
            return True
    if isinstance(expr, ast.Attribute) and expr.attr in ARRAY_ATTR_SOURCES:
        return True
    return False


def _jitted_functions(tree: ast.Module):
    """Yield (FunctionDef/Lambda node, report_line) for every function
    the module hands to jax.jit."""

    def is_jax_jit(node: ast.expr) -> bool:
        return (isinstance(node, ast.Attribute) and node.attr == "jit"
                and isinstance(node.value, ast.Name)
                and node.value.id == "jax") or (
            isinstance(node, ast.Name) and node.id == "jit")

    def is_partial_jit(call: ast.Call) -> bool:
        f = call.func
        is_partial = (isinstance(f, ast.Attribute)
                      and f.attr == "partial") or (
            isinstance(f, ast.Name) and f.id == "partial")
        return (is_partial and call.args
                and is_jax_jit(call.args[0]))

    # name -> def node, per enclosing function body (for jax.jit(name))
    defs_by_scope: dict[int, dict] = {}

    class Scoper(ast.NodeVisitor):
        def __init__(self):
            self.stack = []
            self.out = []

        def _local_defs(self):
            return defs_by_scope.setdefault(
                id(self.stack[-1]) if self.stack else 0, {})

        def visit_FunctionDef(self, node):
            self._local_defs()[node.name] = node
            for dec in node.decorator_list:
                if is_jax_jit(dec) or (isinstance(dec, ast.Call)
                                       and (is_jax_jit(dec.func)
                                            or is_partial_jit(dec))):
                    self.out.append((node, node.lineno))
            self.stack.append(node)
            self.generic_visit(node)
            self.stack.pop()

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Call(self, node):
            if is_jax_jit(node.func) and node.args:
                target = node.args[0]
                if isinstance(target, ast.Lambda):
                    self.out.append((target, node.lineno))
                elif isinstance(target, ast.Name):
                    fn = self._local_defs().get(target.id)
                    if fn is not None:
                        self.out.append((fn, node.lineno))
            self.generic_visit(node)

    s = Scoper()
    s.visit(tree)
    return s.out


class _ScopeInfo:
    """Names assigned per function scope, with array-maker marks."""

    def __init__(self):
        self.assigned: dict[str, bool] = {}   # name -> is array maker


def _collect_scopes(tree):
    """function node -> (_ScopeInfo, parent chain)."""
    info: dict = {}
    parents: dict = {}

    class V(ast.NodeVisitor):
        def __init__(self):
            self.stack = [None]

        def _scope(self):
            return info.setdefault(self.stack[-1], _ScopeInfo())

        def visit_FunctionDef(self, node):
            self._scope().assigned[node.name] = False
            parents[node] = self.stack[-1]
            self.stack.append(node)
            sc = self._scope()
            for a in node.args.args + node.args.kwonlyargs \
                    + node.args.posonlyargs:
                sc.assigned[a.arg] = False
            if node.args.vararg:
                sc.assigned[node.args.vararg.arg] = False
            if node.args.kwarg:
                sc.assigned[node.args.kwarg.arg] = False
            self.generic_visit(node)
            self.stack.pop()

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node):
            parents[node] = self.stack[-1]
            self.stack.append(node)
            sc = self._scope()
            for a in node.args.args:
                sc.assigned[a.arg] = False
            self.generic_visit(node)
            self.stack.pop()

        def visit_Assign(self, node):
            sc = self._scope()
            maker = _is_array_maker(node.value)
            for t in node.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        sc.assigned[n.id] = maker or \
                            sc.assigned.get(n.id, False)
            self.generic_visit(node)

        def visit_AugAssign(self, node):
            if isinstance(node.target, ast.Name):
                self._scope().assigned.setdefault(node.target.id, False)
            self.generic_visit(node)

        def visit_For(self, node):
            for n in ast.walk(node.target):
                if isinstance(n, ast.Name):
                    self._scope().assigned.setdefault(n.id, False)
            self.generic_visit(node)

        def visit_comprehension_target(self, node):
            pass

    V().visit(tree)
    return info, parents


def _free_loads(fn):
    """Names loaded in ``fn`` but not bound there (params, local
    assigns, inner defs, comprehension targets all bind)."""
    bound = set()
    args = fn.args
    for a in args.args + args.kwonlyargs + getattr(args, "posonlyargs",
                                                   []):
        bound.add(a.arg)
    if args.vararg:
        bound.add(args.vararg.arg)
    if args.kwarg:
        bound.add(args.kwarg.arg)
    loads = {}
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        for n in ast.walk(stmt):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                bound.add(n.name)
            elif isinstance(n, ast.Name):
                if isinstance(n.ctx, ast.Store):
                    bound.add(n.id)
                elif isinstance(n.ctx, ast.Load):
                    loads.setdefault(n.id, n.lineno)
            elif isinstance(n, ast.comprehension):
                for t in ast.walk(n.target):
                    if isinstance(t, ast.Name):
                        bound.add(t.id)
    return {k: v for k, v in loads.items() if k not in bound}


def check_jit_closures(path, tree, lines):
    findings = []
    info, parents = _collect_scopes(tree)
    for fn, line in _jitted_functions(tree):
        free = _free_loads(fn)
        # walk the enclosing scope chain out to module scope (None)
        chain, scope = [], parents.get(fn)
        while scope is not None:
            chain.append(scope)
            scope = parents.get(scope)
        chain.append(None)
        flagged = set()
        for scope in chain:
            sc = info.get(scope)
            if sc is None:
                continue
            for name in sorted(free):
                if name in flagged or not sc.assigned.get(name, False):
                    continue
                flagged.add(name)
                if not _suppressed(lines, line, "jit-closure"):
                    findings.append(Finding(
                        path, line, "jit-closure",
                        f"jitted function closes over array {name!r} "
                        f"bound in an enclosing scope — pass it as a "
                        f"jit ARGUMENT (closed-over arrays bake into "
                        f"the program as constants; remote compiles "
                        f"413 on them)"))
    return findings


# ---------------------------------------------------------------------
# check: oracle presence


def check_oracle(path, tree, lines):
    name = os.path.basename(path)
    if name == "__init__.py":
        return []
    has = any(isinstance(n, ast.FunctionDef)
              and n.name.startswith("reference_")
              for n in tree.body)
    findings = []
    if not has and not _suppressed(lines, 1, "oracle"):
        findings.append(Finding(
            path, 1, "oracle",
            "app module has no top-level reference_* NumPy oracle — "
            "every algorithm needs one (CLAUDE.md: new device code "
            "gets an oracle test first)"))
    # query-batched variants (ROADMAP item 2): a module shipping a
    # batched program builder must also ship its batched oracle —
    # the columns-bitwise-equal-B-independent-runs contract needs a
    # NumPy reference to be provable at all
    batched_defs = [n for n in tree.body
                    if isinstance(n, ast.FunctionDef)
                    and "batched" in n.name
                    and not n.name.startswith("reference_")]
    has_batched_oracle = any(
        isinstance(n, ast.FunctionDef)
        and n.name.startswith("reference_") and "batched" in n.name
        for n in tree.body)
    for n in batched_defs:
        if has_batched_oracle or _suppressed(lines, n.lineno,
                                             "oracle"):
            continue
        findings.append(Finding(
            path, n.lineno, "oracle",
            f"{n.name} builds a query-batched variant but the module "
            f"has no reference_*batched* NumPy oracle — batched "
            f"device code needs its columns-vs-independent-runs "
            f"oracle first (CLAUDE.md convention; ROADMAP item 2)"))
        break
    # incremental revalidation (round 20, live graphs): a module
    # shipping an incremental builder/revalidator must also ship its
    # incremental oracle — the proved-equal-to-full-recompute-at-the-
    # same-epoch contract (lux_tpu/livegraph.py) needs a NumPy
    # reference_*_incremental to be provable at all
    # ast.walk, not tree.body: the revalidator may be a METHOD
    # (LiveGraph.revalidate is exactly this shape) — a top-level-only
    # scan is dead for class-based code
    incr_defs = [n for n in ast.walk(tree)
                 if isinstance(n, ast.FunctionDef)
                 and ("incremental" in n.name
                      or "revalidate" in n.name)
                 and not n.name.startswith("reference_")]
    # the oracle may live in another module per convention ("oracle
    # in its app module or test") — an explicit reference_*incremental
    # citation anywhere in the source (docstring pointer, import)
    # satisfies the check; a module naming NO oracle at all fails
    has_incr_oracle = any(
        isinstance(n, ast.FunctionDef)
        and n.name.startswith("reference_")
        and "incremental" in n.name
        for n in tree.body) or bool(
            re.search(r"reference_\w*incremental", "\n".join(lines)))
    for n in incr_defs:
        if has_incr_oracle or _suppressed(lines, n.lineno, "oracle"):
            continue
        findings.append(Finding(
            path, n.lineno, "oracle",
            f"{n.name} builds an incremental-revalidation variant "
            f"but the module has no reference_*_incremental NumPy "
            f"oracle — incremental device code must be proved equal "
            f"to full recompute at the same epoch (CLAUDE.md "
            f"convention; lux_tpu/livegraph.py round 20)"))
        break
    return findings


def check_decremental_oracle(path, tree, lines):
    """Round 21 (mutation algebra): a deletion-capable builder — any
    def with ``decremental`` in its name, or named ``delete_edges`` /
    ``reweight_edges`` — must be provable against a decremental NumPy
    oracle: the module defines a ``reference_*decremental`` function
    or cites one (apps/sssp.reference_sssp_decremental,
    apps/components.reference_components_decremental).  Anti-monotone
    re-seed results (lux_tpu/livegraph.py) are proved equal to full
    recompute at the same epoch — deletion code with no decremental
    reference cannot carry that proof.  Same shape as the incremental
    rule above; ast.walk because the builders are METHODS."""
    decr_defs = [n for n in ast.walk(tree)
                 if isinstance(n, ast.FunctionDef)
                 and ("decremental" in n.name
                      or n.name in ("delete_edges", "reweight_edges"))
                 and not n.name.startswith("reference_")]
    if not decr_defs:
        return []
    has_decr_oracle = any(
        isinstance(n, ast.FunctionDef)
        and n.name.startswith("reference_")
        and "decremental" in n.name
        for n in ast.walk(tree)) or bool(
            re.search(r"reference_\w*decremental", "\n".join(lines)))
    findings = []
    for n in decr_defs:
        if has_decr_oracle or _suppressed(lines, n.lineno, "oracle"):
            continue
        findings.append(Finding(
            path, n.lineno, "oracle",
            f"{n.name} is a deletion-capable builder but the module "
            f"neither defines nor cites a reference_*decremental "
            f"NumPy oracle — anti-monotone mutations must be proved "
            f"equal to full recompute at the same epoch (CLAUDE.md "
            f"convention; lux_tpu/livegraph.py round 21)"))
        break
    return findings


# ---------------------------------------------------------------------
# check: byte-budgeted consumers register a gauge


def check_budget_gauge(path, tree, lines):
    """Round 22 (memory observatory): a memory-consumer class with a
    byte budget — any class whose ``__init__`` assigns
    ``self.max_bytes`` — must register a metrics gauge (reference a
    ``.gauge(`` call somewhere in the class) so its live occupancy is
    observable.  A budgeted consumer with no gauge is a byte ceiling
    the observatory cannot see approaching: the ledger can price it
    but no trail can watch it fill (lux_tpu/memwatch.py; the
    AnswerCache serve_cache_bytes gauge is the template).  Runs
    TREE-WIDE like the decremental rule — consumers live in serve.py
    / livegraph.py, not one directory."""
    findings = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        init = next((n for n in cls.body
                     if isinstance(n, ast.FunctionDef)
                     and n.name == "__init__"), None)
        if init is None:
            continue
        budgeted = any(
            isinstance(t, ast.Attribute) and t.attr == "max_bytes"
            and isinstance(t.value, ast.Name) and t.value.id == "self"
            for n in ast.walk(init) if isinstance(n, ast.Assign)
            for t in n.targets)
        if not budgeted:
            continue
        if _suppressed(lines, cls.lineno, "budget-gauge"):
            continue
        has_gauge = any(
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr == "gauge"
            for n in ast.walk(cls))
        if not has_gauge:
            findings.append(Finding(
                path, cls.lineno, "budget-gauge",
                f"{cls.name} budgets bytes (self.max_bytes) but "
                f"registers no metrics gauge — a byte ceiling the "
                f"memory observatory cannot watch fill "
                f"(lux_tpu/memwatch.py round 22; see "
                f"AnswerCache.set_metrics for the convention)"))
    return findings


# ---------------------------------------------------------------------
# check: citation presence


def check_citation(path, tree, lines):
    if os.path.basename(path) == "__init__.py":
        return []
    doc = ast.get_docstring(tree) or ""
    if CITATION_RE.search(doc) or _suppressed(lines, 1, "citation"):
        return []
    return [Finding(
        path, 1, "citation",
        "module docstring cites no reference file:line — engine/ops "
        "modules must anchor their design to the reference "
        "implementation for parity auditing (CLAUDE.md conventions)")]


# ---------------------------------------------------------------------
# check: per-part stats variants carry their per-part oracle test

PART_STATS_DOC = "per-part"
PART_ORACLE_TOKEN = re.compile(r"\bper_part\w*")
_TESTS_CACHE: list[str] | None = None


def _test_texts() -> list[str]:
    """Cached source texts of every tests/*.py (coverage scan)."""
    global _TESTS_CACHE
    if _TESTS_CACHE is None:
        texts = []
        tdir = os.path.join(REPO, "tests")
        if os.path.isdir(tdir):
            for f in sorted(os.listdir(tdir)):
                if f.endswith(".py"):
                    try:
                        with open(os.path.join(tdir, f)) as fh:
                            texts.append(fh.read())
                    except OSError:
                        continue
        _TESTS_CACHE = texts
    return _TESTS_CACHE


def check_part_stats_oracle(path, tree, lines):
    """Engine loop variants citing per-part counters must carry a
    per-part oracle test (see module docstring)."""
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        if not (node.name.endswith("_stats")
                or node.name.endswith("_health")):
            continue
        doc = ast.get_docstring(node) or ""
        if PART_STATS_DOC not in doc.lower():
            continue
        if _suppressed(lines, node.lineno, "part-stats-oracle"):
            continue
        covered = any(node.name in txt
                      and PART_ORACLE_TOKEN.search(txt)
                      for txt in _test_texts())
        if not covered:
            findings.append(Finding(
                path, node.lineno, "part-stats-oracle",
                f"{node.name} cites per-part counters but no test "
                f"under tests/ references it together with a "
                f"per_part* NumPy oracle — per-part counter "
                f"variants need their sum-over-parts-bitwise proof "
                f"(CLAUDE.md: new device code gets an oracle test "
                f"first)"))
    return findings


# ---------------------------------------------------------------------
# check: every faults.py plan action is drilled by some test

ACTION_NAME_RE = re.compile(r"^[A-Z][A-Z0-9_]*$")


def check_chaos_coverage(path, tree, lines):
    """Every fault-plan action constant (module-level ALL-CAPS name
    bound to a string literal in lux_tpu/faults.py) must appear — by
    constant name or by string value — in at least one tests/ file.
    An undrilled fault action is an untested recovery path (see
    module docstring)."""
    findings = []
    for node in tree.body:
        if not (isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and ACTION_NAME_RE.match(node.targets[0].id)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            continue
        name, value = node.targets[0].id, node.value.value
        if _suppressed(lines, node.lineno, "chaos-coverage"):
            continue
        covered = any(name in txt or value in txt
                      for txt in _test_texts())
        if not covered:
            findings.append(Finding(
                path, node.lineno, "chaos-coverage",
                f"fault action {name} = {value!r} is drilled by no "
                f"file under tests/ — a fault action nobody injects "
                f"is a recovery path that ships untested (faults.py's "
                f"whole purpose); add a drill or suppress with a "
                f"justification"))
    return findings


# ---------------------------------------------------------------------
# check: no metrics calls in engine device code / fused-loop bodies

# callable POSITIONAL slots per loop primitive (fori_loop(lo, hi,
# body, init): only arg 2 is traced code — treating bounds/init
# Names as body functions would scan unrelated same-named helpers)
LOOP_BODY_ARGS = {"fori_loop": (2,), "while_loop": (0, 1),
                  "scan": (0,)}
LOOP_BODY_KEYWORDS = {"body_fun", "cond_fun", "f", "body"}


def _references_metrics(expr) -> bool:
    """Does this call-target expression reach through a ``metrics``
    name or attribute (``metrics.counter(...)``,
    ``self.metrics.histogram(...).observe(...)``)?"""
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and n.id == "metrics":
            return True
        if isinstance(n, ast.Attribute) and n.attr == "metrics":
            return True
    return False


def _loop_body_targets(tree):
    """AST nodes whose bodies trace into fused loops: functions
    passed by name — and lambdas passed inline — in the CALLABLE
    slots of fori_loop/while_loop/scan calls (positional body/cond
    slots + the body_fun/cond_fun/f keywords; bounds and init-state
    arguments are data, never loop bodies)."""
    body_names, lambdas = set(), []
    for n in ast.walk(tree):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        fname = f.attr if isinstance(f, ast.Attribute) \
            else getattr(f, "id", None)
        if fname not in LOOP_BODY_ARGS:
            continue
        slots = [n.args[i] for i in LOOP_BODY_ARGS[fname]
                 if i < len(n.args)]
        slots += [kw.value for kw in n.keywords
                  if kw.arg in LOOP_BODY_KEYWORDS]
        for a in slots:
            if isinstance(a, ast.Name):
                body_names.add(a.id)
            elif isinstance(a, ast.Lambda):
                lambdas.append(a)
    return lambdas + [n for n in ast.walk(tree)
                      if isinstance(n, ast.FunctionDef)
                      and n.name in body_names]


def check_hot_path_metrics(path, tree, lines, whole_file: bool):
    """Flag metrics calls in device code (see module docstring):
    the WHOLE file for engine/ops modules, fused-loop bodies
    everywhere else in the library tree."""
    findings = []
    targets = [tree] if whole_file else _loop_body_targets(tree)
    seen = set()
    for t in targets:
        for n in ast.walk(t):
            if not (isinstance(n, ast.Call)
                    and _references_metrics(n.func)):
                continue
            line = getattr(n, "lineno", 1)
            if line in seen or _suppressed(lines, line,
                                           "hot-path-metrics"):
                continue
            seen.add(line)
            where = ("engine device code" if whole_file
                     else "a fused-loop body")
            findings.append(Finding(
                path, line, "hot-path-metrics",
                f"metrics call inside {where} — metrics are "
                f"host-side, segment-boundary only "
                f"(lux_tpu/metrics.py contract; the audited "
                f"callback-in-loop ban's source-level twin)"))
    return findings


# ---------------------------------------------------------------------
# check: collective primitives stay inside ops/ + engine/

COLLECTIVE_CALLS = {
    "ppermute", "all_to_all", "psum_scatter", "reduce_scatter",
    "all_gather", "psum", "pmin", "pmax",
}


def check_collective_scope(path, tree, lines):
    """Flag collective-primitive calls outside the audited trees (see
    module docstring): the byte ledger's oracle predicts collectives
    from engine layout config, so one planted elsewhere in the
    library is invisible to both the schedule audit and the ledger."""
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) \
            else getattr(f, "id", None)
        if name not in COLLECTIVE_CALLS:
            continue
        line = getattr(node, "lineno", 1)
        if _suppressed(lines, line, "collective-scope"):
            continue
        findings.append(Finding(
            path, line, "collective-scope",
            f"{name} call outside lux_tpu/ops/ + lux_tpu/engine/ — "
            f"the collective-schedule audit and the comm byte ledger "
            f"(lux_tpu/comms.py) only account collectives in those "
            f"trees; move it behind an op interface or carry an "
            f"explicit pragma with the justification"))
    return findings


# ---------------------------------------------------------------------
# check: no block_until_ready fencing in benchmark scripts


def check_bench_fence(path, tree, lines):
    """scripts/ may not fence timed regions with block_until_ready
    (see module docstring): flag any call or attribute reference."""
    findings = []
    for node in ast.walk(tree):
        name = None
        if isinstance(node, ast.Attribute) \
                and node.attr == "block_until_ready":
            name = node.attr
        elif isinstance(node, ast.Name) \
                and node.id == "block_until_ready":
            name = node.id
        if name is None:
            continue
        line = getattr(node, "lineno", 1)
        if _suppressed(lines, line, "bench-fence"):
            continue
        findings.append(Finding(
            path, line, "bench-fence",
            "block_until_ready fencing in a benchmark script — it "
            "returns early through the tunnel and lets XLA hoist "
            "loop-invariant work (PERF_NOTES traps); use "
            "lux_tpu.timing.loop_bench (loop-dependent carry, "
            "scalar output, one jit, fetch fence)"))
    return findings


# ---------------------------------------------------------------------
# driver


EVENT_EMIT_NAMES = {"emit", "_emit", "emit_sampled"}

_KNOWN_EVENTS_CACHE = None


def _known_events() -> set:
    """events_summary.py's KNOWN set, parsed statically (no import:
    the linter stays dependency-free)."""
    global _KNOWN_EVENTS_CACHE
    if _KNOWN_EVENTS_CACHE is None:
        path = os.path.join(REPO, "scripts", "events_summary.py")
        known = set()
        try:
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
            for node in tree.body:
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and node.targets[0].id == "KNOWN":
                    known = set(ast.literal_eval(node.value))
        except (OSError, SyntaxError, ValueError):
            pass
        _KNOWN_EVENTS_CACHE = known
    return _KNOWN_EVENTS_CACHE


def check_event_names(path, tree, lines):
    """event-name: every string LITERAL passed to a telemetry
    ``emit(...)`` / ``_emit(...)`` / ``emit_sampled(...)`` must be
    in events_summary.py's KNOWN set.  Without this, a new emitter
    fails the runtime events audit only when its event first FIRES
    — often a chaos leg nobody runs locally.  Adding the name to
    KNOWN (with its schema note) is the fix; a deliberate
    out-of-catalogue event carries ``# audit: allow(event-name)``
    with justification."""
    known = _known_events()
    if not known:
        return []
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = node.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else None)
        if name not in EVENT_EMIT_NAMES:
            continue
        arg = node.args[0]
        if not (isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)):
            continue
        if arg.value in known:
            continue
        if _suppressed(lines, node.lineno, "event-name"):
            continue
        findings.append(Finding(
            path, node.lineno, "event-name",
            f"emit({arg.value!r}) is not in events_summary.KNOWN "
            f"— add the event name to the KNOWN catalogue so the "
            f"runtime audit recognizes it before it first fires"))
    return findings


DOC_COMMAND_RE = re.compile(
    r"python\s+-m\s+(lux_tpu(?:\.[A-Za-z_][A-Za-z0-9_]*)+)")


def check_doc_commands(repo: str = REPO):
    """command-drift: every ``python -m lux_tpu.<mod>`` cited in
    CLAUDE.md / ARCHITECTURE.md must resolve to a module with an
    ``if __name__ == "__main__"`` entry (or a package __main__.py)
    — the docs can no longer name a smoke that doesn't exist."""
    findings = []
    for doc in ("CLAUDE.md", "ARCHITECTURE.md"):
        p = os.path.join(repo, doc)
        if not os.path.isfile(p):
            continue
        with open(p) as f:
            doc_lines = f.read().splitlines()
        for i, line in enumerate(doc_lines, 1):
            for m in DOC_COMMAND_RE.finditer(line):
                dotted = m.group(1)
                base = os.path.join(repo, *dotted.split("."))
                mod_py = base + ".py"
                pkg_main = os.path.join(base, "__main__.py")
                if os.path.isfile(pkg_main):
                    continue
                if not os.path.isfile(mod_py):
                    msg = (f"cites `python -m {dotted}` but no such "
                           f"module exists")
                else:
                    with open(mod_py) as f:
                        src = f.read()
                    if "__main__" in src:
                        continue
                    msg = (f"cites `python -m {dotted}` but "
                           f"{os.path.relpath(mod_py, repo)} has no "
                           f"`if __name__ == \"__main__\"` entry")
                if _suppressed(doc_lines, i, "command-drift"):
                    continue
                findings.append(Finding(p, i, "command-drift", msg))
    return findings


def lint_file(path: str):
    with open(path) as f:
        src = f.read()
    lines = src.splitlines()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 1, "parse",
                        f"syntax error: {e.msg}")]
    norm = path.replace(os.sep, "/")
    if "/scripts/" in norm:
        # benchmark scripts get ONLY the fencing gate — they are
        # exploratory by design and exempt from the library-tree
        # conventions (jit closures, oracles, citations) — plus the
        # event-name catalogue check (their emits feed the same
        # runtime audit)
        return (check_bench_fence(path, tree, lines)
                + check_event_names(path, tree, lines))
    findings = check_jit_closures(path, tree, lines)
    findings += check_event_names(path, tree, lines)
    findings += check_hot_path_metrics(
        path, tree, lines,
        whole_file=("/lux_tpu/engine/" in norm
                    or "/lux_tpu/ops/" in norm))
    if "/lux_tpu/engine/" not in norm and "/lux_tpu/ops/" not in norm:
        findings += check_collective_scope(path, tree, lines)
    if "/lux_tpu/apps/" in norm:
        findings += check_oracle(path, tree, lines)
    # decremental rule runs TREE-WIDE: the deletion-capable builders
    # live in lux_tpu/livegraph.py, not under apps/
    findings += check_decremental_oracle(path, tree, lines)
    # budget-gauge rule runs TREE-WIDE too: byte-budgeted consumers
    # live in serve.py / livegraph.py, not one directory
    findings += check_budget_gauge(path, tree, lines)
    if "/lux_tpu/engine/" in norm or "/lux_tpu/ops/" in norm:
        findings += check_citation(path, tree, lines)
    if "/lux_tpu/engine/" in norm:
        findings += check_part_stats_oracle(path, tree, lines)
    if norm.endswith("/lux_tpu/faults.py"):
        findings += check_chaos_coverage(path, tree, lines)
    return findings


def iter_py_files(paths):
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            yield p
        elif os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                if "__pycache__" in root:
                    continue
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def lint_paths(paths):
    findings = []
    for f in iter_py_files(paths):
        findings += lint_file(os.path.abspath(f))
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="AST convention linter (jit closures, app "
                    "oracles, reference citations, script bench "
                    "fencing)")
    ap.add_argument("paths", nargs="*",
                    default=[os.path.join(REPO, "lux_tpu"),
                             os.path.join(REPO, "scripts")])
    ap.add_argument("-q", action="store_true", dest="quiet")
    args = ap.parse_args(argv)

    findings = lint_paths(args.paths)
    # repo-level doc checks run regardless of the path selection:
    # the cited-command catalogue lives in CLAUDE.md/ARCHITECTURE.md
    findings += check_doc_commands()
    for f in findings:
        print(str(f), file=sys.stderr)
    if findings:
        print(f"lint_lux: {len(findings)} finding(s) — FAILED",
              file=sys.stderr)
        return 1
    if not args.quiet:
        print("lint_lux: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
