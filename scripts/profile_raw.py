"""Separate compute speed from data-movement speed on the axon TPU.

Round 15: ported onto the observatory recipe (lux_tpu.timing
.loop_bench — loop-dependent carry, scalar output, one jit, fetch
fence); the old block_until_ready pattern is the PERF_NOTES trap and
is now grep-gated out of scripts/ (lint_lux bench-fence).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu.observe import median_mad
from lux_tpu.timing import loop_bench

REPS = 5
rng = np.random.default_rng(0)


def timeit(name, fn, x0, work=0, bytes_=0):
    """fn(x) -> array; the x carry is loop-dependent so XLA cannot
    hoist the op out of the timed loop."""
    def step(c):
        (x,) = c
        out = fn(x)
        sv = jnp.sum(out.ravel()[:1]).astype(jnp.float32)
        return sv, (x + (sv * 1e-30).astype(x.dtype),)

    samples, _ = loop_bench(step, (x0,), REPS, repeats=3)
    dt, _mad = median_mad(samples)
    extra = []
    if work:
        extra.append(f"{work / dt / 1e12:7.2f} TFLOP/s")
    if bytes_:
        extra.append(f"{bytes_ / dt / 1e9:7.2f} GB/s")
    print(f"{name:40s} {dt * 1e3:9.2f} ms  " + "  ".join(extra))
    return dt


# big matmul: compute-bound
for n in (4096, 8192):
    a = jnp.asarray(rng.random((n, n), np.float32), dtype=jnp.bfloat16)
    f = jax.jit(lambda a: a @ a)
    timeit(f"matmul {n} bf16", f, a, work=2 * n**3)

# elementwise on big array: HBM-bound
x = jnp.asarray(rng.random((4096, 4096), np.float32))
f = jax.jit(lambda x: x * 1.0001 + 0.5)
timeit("elementwise 16M f32 (xla)", f, x, bytes_=2 * x.nbytes)

x2 = jnp.asarray(rng.random((16384, 4096), np.float32))
timeit("elementwise 64M f32 (xla)", f, x2, bytes_=2 * x2.nbytes)

# reduction
f = jax.jit(lambda x: jnp.sum(x))
timeit("sum 64M f32 (xla)", f, x2, bytes_=x2.nbytes)

# many small iterations inside one jit: dispatch/compute latency
y = jnp.asarray(rng.random((8, 128), np.float32))


@jax.jit
def loop_small(y):
    def body(i, y):
        return y * 1.0001 + 1e-6

    return jax.lax.fori_loop(0, 10000, body, y)


timeit("10k tiny fori iterations (one jit)", loop_small, y)
