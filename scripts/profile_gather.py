"""Isolate TPU gather lowering variants: plain vs vmapped vs one-dim.

Round 12: ported onto the observatory recipe (lux_tpu.timing
.loop_bench — loop-dependent inputs, scalar output, one jit, fetch
fence).  The original block_until_ready timing pattern is exactly the
trap PERF_NOTES documents (early returns through the tunnel + XLA
hoisting loop-invariant work), so these figures supersede it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu.observe import median_mad
from lux_tpu.timing import loop_bench

V = 1 << 21
N = 57636 * 1024  # ~59M slots
K = 5

rng = np.random.default_rng(0)
state = jnp.asarray(rng.random(V, np.float32))
idx_flat = jnp.asarray(rng.integers(0, V, N).astype(np.int32))
idx_2d = idx_flat.reshape(-1, 1024)
idx_3d = idx_flat.reshape(-1, 8, 128)


def timeit(name, gather_fn, idx):
    """gather_fn(state, idx) -> gathered values; timed with a
    loop-dependent state carry so the gather cannot hoist."""
    def step(c):
        s, i = c
        sv = jnp.sum(gather_fn(s, i))
        return sv, (s + sv * 1e-30, i)

    samples, _ = loop_bench(step, (state, idx), K, repeats=3)
    dt, mad = median_mad(samples)
    print(f"{name:44s} {dt * 1e3:8.2f} ms  ({N / dt / 1e9:6.2f} G/s, "
          f"mad {mad * 1e3:.2f} ms)")
    return dt


timeit("take flat [N]", lambda s, i: jnp.take(s, i), idx_flat)
timeit("take 2d [C,1024]", lambda s, i: jnp.take(s, i), idx_2d)
timeit("take 3d [C,8,128]", lambda s, i: jnp.take(s, i), idx_3d)

timeit("vmapped take [1,C,1024]",
       jax.vmap(lambda s, i: jnp.take(s, i), in_axes=(None, 0)),
       idx_2d[None])
timeit("vmapped take rows [C rows of 1024]",
       jax.vmap(lambda s, i: jnp.take(s, i), in_axes=(None, 0)),
       idx_2d)

# exact engine formulation: reshape then take
timeit("take axis=0 2d", lambda s, i: jnp.take(s, i, axis=0), idx_2d)

# sum fused over the middle axis
timeit("take+sum fused 3d",
       lambda s, i: jnp.take(s, i.reshape(-1, 8, 128), axis=0)
       .sum(axis=1), idx_flat)
