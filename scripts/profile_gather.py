"""Isolate TPU gather lowering variants: plain vs vmapped vs one-dim.

Round 12: ported onto the observatory recipe (lux_tpu.timing
.loop_bench — loop-dependent inputs, scalar output, one jit, fetch
fence).  The original block_until_ready timing pattern is exactly the
trap PERF_NOTES documents (early returns through the tunnel + XLA
hoisting loop-invariant work), so these figures supersede it; round
15 grep-gates the pattern out of scripts/ entirely
(scripts/lint_lux.py bench-fence) and adds the paged-vs-flat sweep
below (ops/pagegather.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu.observe import median_mad
from lux_tpu.timing import loop_bench

V = 1 << 21
N = 57636 * 1024  # ~59M slots
K = 5

rng = np.random.default_rng(0)
state = jnp.asarray(rng.random(V, np.float32))
idx_flat = jnp.asarray(rng.integers(0, V, N).astype(np.int32))
idx_2d = idx_flat.reshape(-1, 1024)
idx_3d = idx_flat.reshape(-1, 8, 128)


def timeit(name, gather_fn, idx):
    """gather_fn(state, idx) -> gathered values; timed with a
    loop-dependent state carry so the gather cannot hoist."""
    def step(c):
        s, i = c
        sv = jnp.sum(gather_fn(s, i))
        return sv, (s + sv * 1e-30, i)

    samples, _ = loop_bench(step, (state, idx), K, repeats=3)
    dt, mad = median_mad(samples)
    print(f"{name:44s} {dt * 1e3:8.2f} ms  ({N / dt / 1e9:6.2f} G/s, "
          f"mad {mad * 1e3:.2f} ms)")
    return dt


timeit("take flat [N]", lambda s, i: jnp.take(s, i), idx_flat)
timeit("take 2d [C,1024]", lambda s, i: jnp.take(s, i), idx_2d)
timeit("take 3d [C,8,128]", lambda s, i: jnp.take(s, i), idx_3d)

timeit("vmapped take [1,C,1024]",
       jax.vmap(lambda s, i: jnp.take(s, i), in_axes=(None, 0)),
       idx_2d[None])
timeit("vmapped take rows [C rows of 1024]",
       jax.vmap(lambda s, i: jnp.take(s, i), in_axes=(None, 0)),
       idx_2d)

# exact engine formulation: reshape then take
timeit("take axis=0 2d", lambda s, i: jnp.take(s, i, axis=0), idx_2d)

# sum fused over the middle axis
timeit("take+sum fused 3d",
       lambda s, i: jnp.take(s, i.reshape(-1, 8, 128), axis=0)
       .sum(axis=1), idx_flat)


# ---------------------------------------------------------------------
# Paged-vs-flat sweep (round 15, ops/pagegather.py): the same number
# of delivered edges served by (a) the flat per-edge gather and (b)
# the page-binned row fetch + lane shuffle, swept over table size and
# unique-page ratio — the measured side of the scalemodel break-even
# (scalemodel.page_gather_ns).  Both paths include the downstream
# compare-reduce so the A/B isolates the delivery swap.

def paged_sweep(rows=1 << 15, loop_k=4):
    from lux_tpu.ops.pagegather import lane_resolve
    from lux_tpu.ops.tiled import chunk_partials
    from lux_tpu import scalemodel

    method = "pallas" if jax.default_backend() == "tpu" else "xla"
    edges = rows * 128
    print(f"\n# paged-vs-flat sweep: {rows} rows x 128 lanes "
          f"({edges / 1e6:.1f}M edges), lane resolve = {method}")
    for logv in (18, 21, 24):
        T = (1 << logv) // 128
        tbl = jnp.asarray(rng.random((T, 128), np.float32))
        flat_tbl = tbl.reshape(-1)
        for pages_frac in (0.02, 0.25, 1.0):
            n_pages = max(1, int(T * pages_frac))
            slot = rng.integers(0, n_pages, rows)
            page_ids = jnp.asarray(
                rng.choice(T, size=n_pages, replace=False)
                .astype(np.int32))
            lane = rng.integers(0, 128, (rows, 128))
            sl = jnp.asarray(
                (slot[:, None].astype(np.uint32) << np.uint32(7))
                | lane.astype(np.uint32))
            rel = jnp.asarray(
                rng.integers(0, 128, (rows, 128)).astype(np.int8))
            flat_idx = jnp.asarray(
                rng.integers(0, T * 128,
                             (rows, 128)).astype(np.int32))

            def flat_step(c):
                t, i, r = c
                v = jax.lax.optimization_barrier(
                    jnp.take(t, i, axis=0))
                sv = jnp.sum(chunk_partials(v, r, 128, "sum"))
                return sv, (t + sv * 1e-30, i, r)

            def paged_step(c):
                t, ids, s, r = c
                pages = jnp.take(t, ids, axis=0)
                rs = jax.lax.shift_right_logical(
                    s[:, 0], jnp.uint32(7)).astype(jnp.int32)
                rws = jnp.take(pages, rs, axis=0)
                v = jax.lax.optimization_barrier(
                    lane_resolve(rws, s, method))
                sv = jnp.sum(chunk_partials(v, r, 128, "sum"))
                return sv, (t + sv * 1e-30, ids, s, r)

            fs, _ = loop_bench(flat_step, (flat_tbl, flat_idx, rel),
                               loop_k, repeats=3)
            ps, _ = loop_bench(paged_step, (tbl, page_ids, sl, rel),
                               loop_k, repeats=3)
            fm, _ = median_mad(fs)
            pm, _ = median_mad(ps)
            ratio = n_pages * 128 / edges
            model = scalemodel.page_gather_ns(ratio, 128.0)
            print(f"table 2^{logv}  page_ratio {ratio:7.4f}  "
                  f"flat {fm / edges * 1e9:6.2f} ns/e  "
                  f"paged {pm / edges * 1e9:6.2f} ns/e  "
                  f"(model {model:5.2f})  "
                  f"speedup {fm / pm:5.2f}x")


paged_sweep()
