"""Isolate TPU gather lowering variants: plain vs vmapped vs one-dim."""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

V = 1 << 21
N = 57636 * 1024  # ~59M slots
REPS = 5

rng = np.random.default_rng(0)
state = jnp.asarray(rng.random(V, np.float32))
idx_flat = jnp.asarray(rng.integers(0, V, N).astype(np.int32))
idx_2d = idx_flat.reshape(-1, 1024)
idx_3d = idx_flat.reshape(-1, 8, 128)


def timeit(name, fn, *args):
    out = fn(*args)
    jax.block_until_ready(out)
    np.asarray(jax.device_get(jax.tree.leaves(out)[0])).ravel()[:1]
    t0 = time.perf_counter()
    for _ in range(REPS):
        out = fn(*args)
    jax.block_until_ready(out)
    np.asarray(jax.device_get(jax.tree.leaves(out)[0])).ravel()[:1]
    dt = (time.perf_counter() - t0) / REPS
    print(f"{name:44s} {dt * 1e3:8.2f} ms  ({N / dt / 1e9:6.2f} G/s)")
    return dt


timeit("take flat [N]", jax.jit(lambda s, i: jnp.take(s, i)), state,
       idx_flat)
timeit("take 2d [C,1024]", jax.jit(lambda s, i: jnp.take(s, i)), state,
       idx_2d)
timeit("take 3d [C,8,128]", jax.jit(lambda s, i: jnp.take(s, i)), state,
       idx_3d)

vm = jax.jit(jax.vmap(lambda s, i: jnp.take(s, i), in_axes=(None, 0)))
timeit("vmapped take [1,C,1024]", vm, state, idx_2d[None])

vm1 = jax.jit(jax.vmap(lambda s, i: jnp.take(s, i), in_axes=(None, 0)))
timeit("vmapped take rows [C rows of 1024]", vm1, state, idx_2d)

# exact engine formulation: reshape then take then sum
def engine_like(s, i):
    v = jnp.take(s, i, axis=0)
    return v

timeit("take axis=0 2d", jax.jit(engine_like), state, idx_2d)

# take_along_axis formulation
def taa(s, i):
    return jnp.take_along_axis(s[None, :].repeat(1, 0),
                               i.reshape(1, -1), axis=1)

# one-hot matmul small sanity skipped

# sum fused
def gsum(s, i):
    return jnp.take(s, i.reshape(-1, 8, 128), axis=0).sum(axis=1)

timeit("take+sum fused 3d", jax.jit(gsum), state, idx_flat)
