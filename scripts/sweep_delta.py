"""Delta-stepping bucket-width sweep at the bench shape (VERDICT r3
next #7): BENCH_r03 measured sssp-delta (delta=mean weight) BELOW
plain frontier relaxation.  Structural context: every iteration of
the push engine is fixed-shape (dense = all edges; sparse = static
queue_cap/edge_budget), so delta-stepping cannot shrink per-iteration
cost — it can only (a) flip iterations from dense to the much cheaper
sparse path by keeping frontiers under nv/16, or (b) waste time on
relax-free bucket advances.  This sweep measures where that trade
lands.

Usage:
  PYTHONPATH=/root/repo:/root/.axon_site python scripts/sweep_delta.py \
      [scale=21] [ef=16] [repeats=3]

Prints one JSON line per width: the timed converge (median of
repeats), iterations, and GTEPS alongside the plain (delta=None) run.
"""

from __future__ import annotations

import json
import sys
import time


def main():
    scale = int(sys.argv[1]) if len(sys.argv) > 1 else 21
    ef = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    repeats = int(sys.argv[3]) if len(sys.argv) > 3 else 3

    import numpy as np

    from lux_tpu.apps import sssp
    from lux_tpu.convert import rmat_graph
    from lux_tpu.graph import pair_relabel
    from lux_tpu.timing import timed_converge

    t0 = time.time()
    g = rmat_graph(scale=scale, edge_factor=ef, seed=0)
    rng = np.random.default_rng(1)
    g.weights = rng.integers(1, 6, size=g.ne).astype(np.int32)
    g2, perm, starts = pair_relabel(g, 1, pair_threshold=16)
    rank = np.empty(g.nv, np.int64)
    rank[perm] = np.arange(g.nv)
    start = int(rank[0])
    print(f"# graph ready nv={g.nv} ne={g.ne} ({time.time()-t0:.0f}s)",
          flush=True)

    want = None
    for delta in [None, 1.0, 2.0, "auto", 5.0, 8.0, 16.0, 64.0]:
        eng = sssp.build_engine(g2, start_vertex=start, num_parts=1,
                                weighted=True, delta=delta,
                                pair_threshold=16, starts=starts)
        labels, iters, elapsed = timed_converge(eng, repeats=repeats)
        if want is None:
            want = labels
        else:
            np.testing.assert_allclose(labels, want, rtol=1e-6)
        med = sorted(elapsed)[len(elapsed) // 2]
        print(json.dumps({
            "delta": ("none" if delta is None else
                      round(eng.delta or 0, 3) if delta == "auto"
                      else delta),
            "iters": int(iters),
            "elapsed": [round(e, 3) for e in elapsed],
            "gteps": round(g.ne * iters / med / 1e9, 4)}), flush=True)


if __name__ == "__main__":
    main()
