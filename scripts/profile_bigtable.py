"""Round-3 premise test: the per-edge gather's big-table tax.

Round 1 measured gather flat at 8.97-9.26 ns/elem for 16 KB - 64 MB
tables; scale-25 phases showed ~16.6 ns/edge on a 135 MB table.  This
sweep extends the hoisting-proof harness past 64 MB and adds a
SORTED-index variant (the premise of the two-pass bucketed gather in
PERF_NOTES round-3 pointer #1: if locality matters at big tables,
bucketing by table region pays; if not, it cannot).

Usage: PYTHONPATH=/root/repo:/root/.axon_site \
    python scripts/profile_bigtable.py
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

K = 10
N = 1 << 25      # 33.5M indices per trial
rng = np.random.default_rng(0)


def bench(name, table, idx):
    def run(t0, i):
        def body(_, c):
            s, t = c
            v = jnp.take(t, i, axis=0)
            sv = jnp.sum(v)
            return (s + sv, t + sv * 1e-30)
        return jax.lax.fori_loop(0, K, body, (jnp.float32(0), t0))[0]

    r = jax.jit(run)
    float(r(table, idx))
    t0 = time.perf_counter()
    float(r(table, idx))
    dt = (time.perf_counter() - t0) / K
    print(f"{name:44s} {dt * 1e3:8.2f} ms  ({dt / N * 1e9:6.2f} "
          f"ns/elem)", flush=True)


for logv in (24, 25, 26):                 # 64 MB, 128 MB, 256 MB f32
    V = 1 << logv
    table = jnp.asarray(rng.random(V, np.float32))
    idx_r = rng.integers(0, V, N).astype(np.int32)
    bench(f"table {V * 4 >> 20:4d} MB, random idx",
          table, jnp.asarray(idx_r))
    bench(f"table {V * 4 >> 20:4d} MB, SORTED idx",
          table, jnp.asarray(np.sort(idx_r)))
    del table
