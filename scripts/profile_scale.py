"""Decompose the pagerank iteration across RMAT scales (VERDICT r1 #2:
21 -> 23 lost 2.5x per-edge throughput; the gather sweep is flat, so
the regression is elsewhere).

For each scale: build the bench engine (relabel + pair) and time
  full   the fused engine step (bench configuration)
  nopair the same graph with pair_threshold=None (pure gather path)
  pair   a jit of ONLY the pair delivery+reduce (rows gather, chunk
         partials, class combine)
  resid  a jit of ONLY the residual gather+tiled reduce
plus the plan shape stats (coverage, R rows, inflation, chunks C).

Methodology per PERF_NOTES, through the trusted library recipe
(lux_tpu.timing.loop_bench — the PR-7/round-12 migration of the
profile scripts off the documented timing traps): K iterations inside
one jit, loop-DEPENDENT carry, scalar output, host-fetch fence; big
operands ride the carry as jit arguments and the reported number is
the median over repeats.

Usage: PYTHONPATH=/root/repo:/root/.axon_site python scripts/profile_scale.py 21 22 23
"""

from __future__ import annotations

import sys
from statistics import median

import jax
import jax.numpy as jnp

from lux_tpu.apps import pagerank
from lux_tpu.convert import rmat_graph
from lux_tpu.graph import degree_relabel
from lux_tpu.timing import loop_bench

K = 5


def timed_scalar_loop(fn, state, *args):
    """K loop-dependent iterations of fn inside one jit
    (timing.loop_bench); returns median s/iter over 3 repeats."""

    def step(carry):
        s, rest = carry[0], carry[1:]
        out = fn(s, *rest)
        sv = jnp.sum(out.reshape(-1)[:8])
        return sv, (out * (1.0 - 1e-30 * sv), *rest)

    samples, _ = loop_bench(step, (state, *args), K, repeats=3)
    return median(samples)


def main(scales):
    for scale in scales:
        g = rmat_graph(scale=scale, edge_factor=16, seed=0)
        g2, _ = degree_relabel(g)
        eng = pagerank.build_engine(g2, num_parts=1, pair_threshold=16, exchange="gather")
        sp = eng.pairs
        lay = eng.tiles
        print(f"--- scale {scale}: ne={g.ne} "
              f"cov={sp.stats['coverage']:.3f} R={sp.R} Rp={sp.Rp} "
              f"infl={sp.stats['inflation']:.2f} "
              f"classes={len(sp.classes)} "
              f"resid_ne={int(eng.sg.ne_part[0])} C={lay.n_chunks}")

        # full step (the bench path)
        t_full = timed_scalar_loop(
            lambda s, *a: eng._step_core(s, *a), eng.init_state(),
            *eng.graph_args)

        # no-pair engine on the same relabeled graph
        eng0 = pagerank.build_engine(g2, num_parts=1, exchange="gather")
        t_nopair = timed_scalar_loop(
            lambda s, *a: eng0._step_core(s, *a), eng0.init_state(),
            *eng0.graph_args)

        # pair-only: delivery + reduce, state-shaped output
        from lux_tpu.ops.pairs import pair_partial
        gdict = dict(zip(eng._graph_keys, eng.graph_args))

        def pair_only(flat, rowbind, rel, tpos):
            red = pair_partial(sp, flat, rowbind, rel, None, tpos,
                               "sum", lambda v, w: v,
                               reduce_method=eng.reduce_method)
            return red[:eng.sg.vpad]

        t_pair = timed_scalar_loop(
            pair_only, eng.init_state().reshape(-1),
            gdict["pair_rowbind"][0], gdict["pair_rel"][0],
            gdict["pair_tile_pos"][0])

        # residual-only: per-edge gather + tiled reduce
        from lux_tpu.ops.tiled import tiled_segment_reduce

        def resid_only(flat, src_slot, cs, lc, rel):
            vals = jnp.take(flat, src_slot, axis=0)
            vals = jax.lax.optimization_barrier(vals)
            return tiled_segment_reduce(
                vals, lay, cs, lc, rel, eng.sg.vpad, "sum",
                method="pallas" if eng.reduce_method.startswith("pallas")
                else "xla")

        t_resid = timed_scalar_loop(
            resid_only, eng.init_state().reshape(-1),
            gdict["src_slot"][0], gdict["chunk_start"][0],
            gdict["last_chunk"][0], gdict["rel_dst"][0])

        print(f"    full={t_full * 1e3:8.1f} ms/iter "
              f"({g.ne / t_full / 1e9:.3f} GTEPS)")
        print(f"    nopair={t_nopair * 1e3:6.1f} ms/iter "
              f"({g.ne / t_nopair / 1e9:.3f} GTEPS)")
        print(f"    pair={t_pair * 1e3:8.1f} ms/iter  "
              f"resid={t_resid * 1e3:8.1f} ms/iter  "
              f"(sum {1e3 * (t_pair + t_resid):.1f})")


if __name__ == "__main__":
    main([int(s) for s in sys.argv[1:]] or [21, 23])
