"""Calibrate Mosaic VPU primitive throughput: elementwise, compare+select,
sublane reduce, at f32/bf16 — to find the real per-op cost."""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N = 64 * 1024 * 1024 // 128   # rows; N*128 = 64M elements
REPS = 10

rng = np.random.default_rng(0)
x = jnp.asarray(rng.random((N, 128), np.float32))
r = jnp.asarray(rng.integers(0, 128, (N, 128)).astype(np.int32))


def timeit(name, fn, x0, *rest, work):
    """Round 15: observatory recipe (lux_tpu.timing.loop_bench) —
    loop-dependent x carry, scalar output, one jit; block_until_ready
    fencing is grep-gated out of scripts/ (lint_lux bench-fence)."""
    from lux_tpu.observe import median_mad
    from lux_tpu.timing import loop_bench

    def step(c):
        x, extra = c
        out = fn(x, *extra)
        sv = jnp.sum(jax.tree.leaves(out)[0].ravel()[:1]).astype(
            jnp.float32)
        return sv, (x + (sv * 1e-30).astype(x.dtype), extra)

    samples, _ = loop_bench(step, (x0, tuple(rest)), REPS, repeats=3)
    dt, _mad = median_mad(samples)
    print(f"{name:46s} {dt * 1e3:8.2f} ms  ({work / dt / 1e12:6.2f} Tops/s)")
    return dt


def mk(body, n_in=2, bm=1024):
    def kern(*refs):
        out = refs[-1]
        out[:] = body(*[rr[:] for rr in refs[:-1]])

    def run(*arrs):
        return pl.pallas_call(
            kern,
            grid=(N // bm,),
            in_specs=[pl.BlockSpec((bm, 128), lambda b: (b, 0),
                                   memory_space=pltpu.VMEM)] * n_in,
            out_specs=pl.BlockSpec((bm, 128), lambda b: (b, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((N, 128), arrs[0].dtype),
        )(*arrs)

    return jax.jit(run)


# 1 op per element
timeit("add x+x (1 op/elem)", mk(lambda a, b: a + b), x, x, work=N * 128)

# 10 chained adds
def chain10(a, b):
    for _ in range(10):
        a = a + b
    return a

timeit("10 chained adds", mk(chain10), x, x, work=10 * N * 128)

# cmp + select + add vs iota-scalar, 16 rounds (like the reduce inner loop)
def cmpsel16(v, rr):
    acc = jnp.zeros_like(v)
    for wd in range(16):
        acc = acc + jnp.where(rr == wd, v, 0.0)
    return acc

timeit("16x (cmp+sel+add)", mk(cmpsel16), x, r, work=3 * 16 * N * 128)


# mul by bool instead of select
def cmpmul16(v, rr):
    acc = jnp.zeros_like(v)
    for wd in range(16):
        acc = acc + v * (rr == wd).astype(v.dtype)
    return acc

timeit("16x (cmp+cast+mul+add)", mk(cmpmul16), x, r,
       work=4 * 16 * N * 128)


# sublane reduce of [bm,128] -> [bm/8? ...]: sum groups of 8 sublanes
def subred(v):
    return v.reshape(-1, 8, 128).sum(axis=1).repeat(8, axis=0)

# skip: shape-changing; instead full-block reduce to one row
def redrow_kern(v_ref, o_ref):
    o_ref[:] = jnp.sum(v_ref[:], axis=0, keepdims=True)

def redrow(v, bm=1024):
    return pl.pallas_call(
        redrow_kern,
        grid=(N // bm,),
        in_specs=[pl.BlockSpec((bm, 128), lambda b: (b, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1, 128), lambda b: (b, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((N // 1024, 128), v.dtype),
    )(v)

timeit("block reduce rows [1024,128]->[1,128]", jax.jit(redrow), x,
       work=N * 128)

# bf16 comparison
xb = x.astype(jnp.bfloat16)
timeit("bf16 10 chained adds", mk(chain10), xb, xb, work=10 * N * 128)
