"""Measure step components correctly: K iterations inside one jit,
tiny output, so tunnel output-shipping doesn't pollute timings."""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu.apps import pagerank
from lux_tpu.convert import rmat_edges
from lux_tpu.graph import Graph

SCALE = 21
K = 10

src, dst, nv = rmat_edges(scale=SCALE, edge_factor=16, seed=0)
g = Graph.from_edges(src, dst, nv)
eng = pagerank.build_engine(g, num_parts=1)
sg, lay = eng.sg, eng.tiles
state0 = eng.init_state()
keys = eng._graph_keys
gargs = eng.graph_args
print(f"ne={sg.ne} C={lay.n_chunks} E={lay.E} edges+pad={lay.n_chunks*lay.E}")


def timeit(name, core):
    @jax.jit
    def run(state, *ga):
        def body(i, s):
            return core(s, *ga)
        s = jax.lax.fori_loop(0, K, body, state)
        return jnp.sum(s)

    out = run(state0, *gargs)
    float(out)
    t0 = time.perf_counter()
    out = run(state0, *gargs)
    float(out)
    dt = (time.perf_counter() - t0) / K
    print(f"{name:46s} {dt * 1e3:8.2f} ms/iter "
          f"({sg.ne / dt / 1e9:5.2f} GTEPS)")
    return dt


# full step
timeit("full step", eng._step_core)


# gather-only variant: reduce replaced by cheap sum over E
def core_gather(state, *ga):
    gd = dict(zip(keys, ga))
    flat = state.reshape((sg.num_parts * sg.vpad,) + state.shape[2:])

    def part(old_p, gp):
        sv = jnp.take(flat, gp["src_slot"], axis=0)   # [C, E]
        red = jnp.sum(sv, axis=1)                     # [C]
        # fold [C] back into a state-shaped update so the loop carries
        pad = jnp.zeros(sg.vpad, old_p.dtype).at[:red.shape[0] % sg.vpad
                                                 or sg.vpad].set(0)
        upd = jnp.zeros(sg.vpad, old_p.dtype)
        upd = upd.at[jnp.arange(red.shape[0]) % sg.vpad].add(0)
        return old_p * 0.99 + jnp.sum(red) * 1e-30 + pad + upd * 0

    return jax.vmap(part)(state, gd)


def core_gather_simple(state, *ga):
    gd = dict(zip(keys, ga))
    flat = state.reshape((sg.num_parts * sg.vpad,) + state.shape[2:])

    def part(old_p, gp):
        sv = jnp.take(flat, gp["src_slot"], axis=0)
        return old_p * 0.99 + jnp.sum(sv) * 1e-30

    return jax.vmap(part)(state, gd)


timeit("gather + scalar-sum only", core_gather_simple)


# reduce-only variant: vals = cheap broadcast (no gather)
def core_reduce(state, *ga):
    from lux_tpu.ops.pallas_reduce import chunk_partials_pallas
    from lux_tpu.ops.tiled import combine_chunks
    gd = dict(zip(keys, ga))

    def part(old_p, gp):
        sv = (old_p[:lay.E][None, :] *
              jnp.ones((lay.n_chunks, 1), old_p.dtype))  # [C, E] cheap
        partials = chunk_partials_pallas(sv, lay.W, "sum")
        red = combine_chunks(partials, lay, gp["chunk_start"],
                             gp["last_chunk"], "sum")
        flatshape = (lay.n_tiles * lay.W,)
        out = red.reshape(flatshape)[:sg.vpad]
        return old_p * 0.99 + out * 1e-30

    return jax.vmap(part)(state, gd)


timeit("pallas reduce + combine (no gather)", core_reduce)


# combine-only
def core_combine(state, *ga):
    from lux_tpu.ops.tiled import combine_chunks
    gd = dict(zip(keys, ga))

    def part(old_p, gp):
        partials = (old_p[:lay.W][None, :] *
                    jnp.ones((lay.n_chunks, 1), old_p.dtype))
        red = combine_chunks(partials, lay, gp["chunk_start"],
                             gp["last_chunk"], "sum")
        out = red.reshape((lay.n_tiles * lay.W,))[:sg.vpad]
        return old_p * 0.99 + out * 1e-30

    return jax.vmap(part)(state, gd)


timeit("combine_chunks only", core_combine)
