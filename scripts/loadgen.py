#!/usr/bin/env python
"""Open-loop Poisson load harness for the serving front-end.

Drives a ``lux_tpu.serve.Server`` with OPEN-LOOP arrivals — a seeded
Poisson process submits queries on its own wall-clock schedule,
independent of service progress, which is the only arrival discipline
under which queue wait is an honest signal (closed-loop harnesses
self-throttle and hide saturation; the Ragged-Paged-Attention-style
serving stacks in PAPERS.md are judged on exactly these
latency-vs-offered-rate curves).  Per ramp step:

- a submitter thread draws exponential inter-arrival gaps at the
  step's offered rate (seeded rng: the query set and schedule are
  reproducible) and submits a mixed-kind round-robin of query kinds;
- the main thread drains the server continuously
  (continuous-batching refill, ``Server.run``);
- the step's latency distribution is read BACK from the server's
  ``metrics_snapshot`` (lux_tpu/metrics.py) — per-kind log-linear
  histograms merged bucket-wise into one distribution — rather than
  recomputed from raw timestamps, so the harness exercises the same
  aggregation path every later SLO consumer will trust;
- offered vs achieved rates are both measured from the load start
  (offered = submitted / time-to-last-enqueue, achieved = served /
  time-to-last-retire), so achieved <= offered holds BY CONSTRUCTION
  — the contradiction scripts/check_bench.py rejects can only come
  from a lying line, never from honest timing.

The report is the latency-vs-offered-rate table plus the measured
SATURATION KNEE: the first ramp step whose achieved rate falls under
``KNEE_FRACTION`` of its offered rate.  ``bench.py -config
serve-slo`` wraps ``run_step`` into calibrated metric lines
(offered/achieved/p50/p99/SLO fields, validated by
scripts/check_bench.py); the on-device run is carried as debt
``serve-slo-on-device`` (lux_tpu/observe.py).

Usage:
    PYTHONPATH=. python scripts/loadgen.py -scale 9 -rates 5,15,40 \
        -queries 24 -slo-ms sssp=250,components=250,pagerank=1000 \
        [-events FILE] [-trace FILE]
"""

from __future__ import annotations

import argparse
import contextvars
import dataclasses
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# a step saturates when it achieves under this fraction of its
# offered rate — the knee of the latency-vs-rate curve
KNEE_FRACTION = 0.9
DRAIN_POLL_S = 0.002


@dataclasses.dataclass
class StepReport:
    """One ramp step's measured outcome (all rates in queries/s,
    latencies in ms; percentiles come from the merged
    metrics-snapshot histograms, per-kind detail preserved)."""
    step: int
    target_qps: float         # the nominal Poisson rate
    offered_qps: float        # measured: submitted / enqueue window
    achieved_qps: float       # measured: served / retire window
    submitted: int
    served: int
    elapsed_s: float          # load start -> last retirement
    p50_ms: float | None
    p99_ms: float | None
    slo_good_fraction: float | None
    per_kind: dict            # kind -> {count, p50_ms, p99_ms}
    drained: bool
    # serving-fleet fields (round 18, lux_tpu/fleet.py): queries the
    # tier SHED with a typed AdmissionError (admitted + shed
    # partition the submitted set — ``drained`` counts both), and
    # the snapshot's SLO-accounted retirement count (good +
    # violation — computed over ADMITTED queries only; check_bench
    # rejects a line whose accounting covers shed queries)
    shed: int = 0
    slo_accounted: int | None = None
    # the raw Response objects, for oracle verification by chaos
    # acceptance harnesses (not rendered, not serialized)
    responses: list = dataclasses.field(default_factory=list,
                                        repr=False)


def _merged_latency(snapshot) -> tuple:
    """(merged Histogram, {kind: entry}) of the snapshot's
    serve_latency_seconds series (lux_tpu/metrics.py from_snapshot +
    bucket-wise merge — the mergeability the histogram design buys)."""
    from lux_tpu import metrics as metrics_mod

    merged = metrics_mod.Histogram()
    per_kind = {}
    for h in snapshot.get("histograms", []):
        if h.get("name") != "serve_latency_seconds":
            continue
        kind = (h.get("labels") or {}).get("kind", "?")
        per_kind[kind] = h
        merged = merged.merge(metrics_mod.Histogram.from_snapshot(h))
    return merged, per_kind


def _slo_counts(snapshot) -> tuple:
    """(good, violation) totals of the snapshot's SLO counters."""
    good = bad = 0.0
    for c in snapshot.get("counters", []):
        if c.get("name") == "serve_slo_good_total":
            good += c.get("value", 0)
        elif c.get("name") == "serve_slo_violation_total":
            bad += c.get("value", 0)
    return good, bad


def _slo_fraction(snapshot) -> float | None:
    good, bad = _slo_counts(snapshot)
    if good + bad == 0:
        return None
    return good / (good + bad)


def run_step(srv, rate: float, n: int, kinds, rng,
             step: int = 0) -> StepReport:
    """One open-loop step: submit ``n`` mixed-kind queries at Poisson
    rate ``rate`` (qps) while continuously draining ``srv``; read the
    step's metrics snapshot back (the published ``metrics_snapshot``
    event — the same aggregate every later SLO consumer reads) and
    measure offered/achieved.  The step swaps in a FRESH metrics
    registry (``Server.set_metrics``) so its percentiles cover
    exactly this step."""
    from lux_tpu import metrics as metrics_mod

    if not rate > 0:
        raise ValueError(f"offered rate must be > 0 qps, got {rate}")
    reg = metrics_mod.Registry()
    srv.set_metrics(reg)
    specs = [(kinds[i % len(kinds)], int(rng.integers(0, srv.g.nv)))
             for i in range(n)]
    gaps = rng.exponential(1.0 / max(rate, 1e-9), size=n)

    done = threading.Event()
    enq_last = [0.0]
    shed0 = len(getattr(srv, "shed_records", ()))

    def submit_all():
        from lux_tpu.fleet import AdmissionError
        for (kind, s), gap in zip(specs, gaps):
            time.sleep(gap)
            try:
                srv.submit(kind, source=s)
            except AdmissionError:
                pass        # typed shed: counted via shed_records
            enq_last[0] = time.monotonic()
        done.set()

    # copy_context: the submitter must emit query_enqueue events into
    # the CALLER's telemetry scope (contextvars do not cross threads
    # by themselves)
    ctx = contextvars.copy_context()
    th = threading.Thread(target=lambda: ctx.run(submit_all),
                          daemon=True)
    responses = []
    t_start = time.monotonic()
    t_last = t_start
    th.start()
    while True:
        out = srv.run()
        if out:
            responses += out
            t_last = time.monotonic()
        # list(): the submitter thread may insert a new kind's
        # collector mid-iteration (the Server.run() hazard)
        if done.is_set() \
                and not any(len(c) for c in
                            list(srv._collectors.values())):
            break
        time.sleep(DRAIN_POLL_S)
    th.join()

    # the emitted event IS the published snapshot (None only without
    # an active event sink — fall back to the registry directly)
    snapshot = srv.emit_metrics_snapshot(step=step, target_qps=rate) \
        or reg.snapshot()

    merged, per_kind_hists = _merged_latency(snapshot)
    p50 = merged.quantile(0.5)
    p99 = merged.quantile(0.99)
    offered = len(specs) / max(enq_last[0] - t_start, 1e-9)
    achieved = len(responses) / max(t_last - t_start, 1e-9)
    shed = len(getattr(srv, "shed_records", ())) - shed0
    good, bad = _slo_counts(snapshot)
    per_kind = {
        k: {"count": h.get("count"),
            "p50_ms": None if h.get("p50") is None
            else h["p50"] * 1e3,
            "p99_ms": None if h.get("p99") is None
            else h["p99"] * 1e3}
        for k, h in sorted(per_kind_hists.items())}
    return StepReport(
        step=step, target_qps=rate, offered_qps=offered,
        achieved_qps=achieved, submitted=len(specs),
        served=len(responses), elapsed_s=t_last - t_start,
        p50_ms=None if p50 is None else p50 * 1e3,
        p99_ms=None if p99 is None else p99 * 1e3,
        slo_good_fraction=_slo_fraction(snapshot),
        per_kind=per_kind,
        drained=len(responses) + shed == len(specs),
        shed=shed,
        slo_accounted=(None if good + bad == 0
                       else int(good + bad)),
        responses=responses)


def warm(srv, kinds) -> int:
    """Build + compile each kind's engine OUTSIDE the measured load
    (one throwaway query per kind, drained before the ramp): the
    first drain otherwise bills remote/XLA compilation to step 0's
    latencies — the serving-tier analogue of the bench drivers'
    excluded warmup run.  Returns the number of warm queries."""
    for k in kinds:
        srv.submit(k, source=0)
    return len(srv.run())


def saturation_knee(reports) -> int | None:
    """Index of the first ramp step whose achieved rate fell under
    KNEE_FRACTION of its offered rate; None = never saturated."""
    for i, r in enumerate(reports):
        if r.achieved_qps < KNEE_FRACTION * r.offered_qps:
            return i
    return None


def render_table(reports, out=sys.stdout) -> None:
    print(f"{'step':>4} {'offered':>9} {'achieved':>9} "
          f"{'p50_ms':>9} {'p99_ms':>9} {'slo_good':>9} "
          f"{'served':>12}", file=out)
    for r in reports:
        frac = "-" if r.slo_good_fraction is None \
            else f"{r.slo_good_fraction:.3f}"
        p50 = "-" if r.p50_ms is None else f"{r.p50_ms:9.1f}"
        p99 = "-" if r.p99_ms is None else f"{r.p99_ms:9.1f}"
        print(f"{r.step:>4} {r.offered_qps:9.2f} "
              f"{r.achieved_qps:9.2f} {p50:>9} {p99:>9} {frac:>9} "
              f"{r.served:>5}/{r.submitted:<6}", file=out)
    knee = saturation_knee(reports)
    if knee is None:
        print("# no saturation knee inside the ramp "
              f"(achieved >= {KNEE_FRACTION:.0%} of offered at every "
              f"step)", file=out)
    else:
        r = reports[knee]
        print(f"# saturation knee at step {knee}: offered "
              f"{r.offered_qps:.2f} qps, achieved "
              f"{r.achieved_qps:.2f} qps", file=out)


def _parse_slo(text: str) -> dict:
    out = {}
    for tok in (text or "").split(","):
        tok = tok.strip()
        if not tok:
            continue
        k, _, v = tok.partition("=")
        out[k.strip()] = float(v)
    return out


def main(argv=None) -> int:
    from lux_tpu import serve, telemetry
    from lux_tpu.convert import rmat_graph

    ap = argparse.ArgumentParser(
        prog="python scripts/loadgen.py",
        description="open-loop Poisson load harness: ramped offered "
                    "rates against a continuous-batching Server; "
                    "reports the latency-vs-offered-rate table and "
                    "the measured saturation knee")
    ap.add_argument("-scale", type=int, default=10)
    ap.add_argument("-ef", type=int, default=8)
    ap.add_argument("-batch", type=int, default=4)
    ap.add_argument("-np", type=int, default=2, dest="num_parts")
    ap.add_argument("-seg-iters", type=int, default=2,
                    dest="seg_iters")
    ap.add_argument("-kinds", default="sssp,components,pagerank")
    ap.add_argument("-rates", default="5,15,40",
                    help="comma list of offered qps, one ramp step "
                         "each")
    ap.add_argument("-queries", type=int, default=24,
                    help="queries per ramp step")
    ap.add_argument("-seed", type=int, default=0)
    ap.add_argument("-slo-ms", dest="slo_ms",
                    default="sssp=250,components=250,pagerank=1000",
                    help="per-kind latency targets, kind=ms comma "
                         "list ('' disables SLO accounting)")
    ap.add_argument("-no-warm", action="store_true", dest="no_warm",
                    help="skip the excluded engine-compile warmup "
                         "(one throwaway query per kind)")
    ap.add_argument("-events", default=None, metavar="FILE",
                    help="append the telemetry trail (query events + "
                         "metrics_snapshot) as JSONL")
    ap.add_argument("-rotate-bytes", type=int, default=None,
                    dest="rotate_bytes",
                    help="EventLog size-rotation threshold for "
                         "-events (long-lived serving processes)")
    ap.add_argument("-trace", default=None, metavar="TRACE_JSON",
                    help="also export the per-query Perfetto trace "
                         "(lux_tpu.tracing.trace_export)")
    args = ap.parse_args(argv)

    kinds = [k.strip() for k in args.kinds.split(",") if k.strip()]
    for k in kinds:
        if k not in serve.KINDS:
            print(f"error: unknown kind {k!r}", file=sys.stderr)
            return 2
    rates = [float(r) for r in args.rates.split(",") if r.strip()]
    if not rates or any(not r > 0 for r in rates):
        print(f"error: -rates must be positive offered qps, got "
              f"{args.rates!r}", file=sys.stderr)
        return 2
    rng = np.random.default_rng(args.seed)
    g = rmat_graph(scale=args.scale, edge_factor=args.ef,
                   seed=args.seed)
    ev = telemetry.EventLog(args.events,
                            rotate_bytes=args.rotate_bytes) \
        if args.events else telemetry.EventLog()
    reports = []
    with telemetry.use(events=ev):
        ev.emit("run_start", schema=telemetry.SCHEMA, app="loadgen",
                file=f"<rmat{args.scale}>", np=args.num_parts)
        srv = serve.Server(g, batch=args.batch,
                           num_parts=args.num_parts,
                           seg_iters=args.seg_iters,
                           slo_ms=_parse_slo(args.slo_ms))
        t0 = time.perf_counter()
        if not args.no_warm:
            warm(srv, kinds)
        for i, rate in enumerate(rates):
            reports.append(run_step(srv, rate, args.queries, kinds,
                                    rng, step=i))
        ev.emit("run_done",
                seconds=round(time.perf_counter() - t0, 6),
                iters=sum(r.served for r in reports))
    ev.close()
    render_table(reports)
    if args.trace:
        from lux_tpu import tracing
        trace = tracing.trace_export(ev.events, out=args.trace)
        errs = tracing.validate_trace(trace)
        print(f"# trace: {args.trace} "
              f"({'VALID' if not errs else 'INVALID'})")
        for e in errs:
            print(f"ERROR: {e}", file=sys.stderr)
        if errs:
            return 1
    if not all(r.drained for r in reports):
        print("error: a ramp step did not drain", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
