"""Gather speed vs table size / dtype / sortedness, fused-loop method.
Plus host-side (src_tile, dst_tile) pair-density stats for RMAT21."""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

N = 1 << 25          # 33.5M gathers
K = 10
rng = np.random.default_rng(0)


def bench(name, table_log2, dtype, sorted_idx=False):
    V = 1 << table_log2
    table = jnp.asarray(rng.random(V, np.float32).astype(dtype))
    idx = rng.integers(0, V, N).astype(np.int32)
    if sorted_idx:
        idx = np.sort(idx)
    idx = jnp.asarray(idx)

    @jax.jit
    def run(t, i):
        def body(_, carry):
            s, t = carry
            v = jnp.take(t, i, axis=0)
            return (s + jnp.sum(v.astype(jnp.float32)),
                    t * jnp.float32(1.0).astype(t.dtype))
        s, _ = jax.lax.fori_loop(0, K, body,
                                 (jnp.float32(0.0), t))
        return s

    out = run(table, idx)
    float(out)
    t0 = time.perf_counter()
    out = run(table, idx)
    float(out)
    dt = (time.perf_counter() - t0) / K
    print(f"{name:44s} {dt * 1e3:8.2f} ms  ({dt / N * 1e9:5.2f} ns/elem)")
    return dt


bench("gather f32 table=2^21", 21, np.float32)
bench("gather f32 table=2^16", 16, np.float32)
bench("gather f32 table=2^12", 12, np.float32)
bench("gather f32 table=2^8", 8, np.float32)
bench("gather bf16 table=2^21", 21, jnp.bfloat16)
bench("gather f32 table=2^21 sorted idx", 21, np.float32, sorted_idx=True)

# ---- pair stats ---------------------------------------------------------
from lux_tpu.convert import rmat_edges
from lux_tpu.graph import Graph

src, dst, nv = rmat_edges(scale=21, edge_factor=16, seed=0)
g = Graph.from_edges(src, dst, nv)
indeg = g.in_degrees()
perm = np.argsort(-indeg, kind="stable")
rank = np.empty(nv, dtype=np.int64)
rank[perm] = np.arange(nv)

s_new = rank[g.col_idx.astype(np.int64)]
d_new = rank[np.repeat(np.arange(nv, dtype=np.int64), indeg)]
pair = (d_new // 128) * (nv // 128) + (s_new // 128)
upair, counts = np.unique(pair, return_counts=True)
print(f"\nRMAT21 deg-sorted 128x128 pairs: {len(upair)} nonzero "
      f"({g.ne / len(upair):.2f} edges/pair)")
for thresh in (1, 2, 4, 8, 16, 32, 64, 128, 256):
    sel = counts >= thresh
    print(f"  pairs>={thresh:4d}: {sel.sum():9d} pairs, "
          f"{counts[sel].sum() / g.ne * 100:5.1f}% of edges")
