"""Converter/loader at scale (VERDICT r2 missing #4): one scripted
end-to-end — generate a >=100M-edge TEXT edge list, run the C++
lux_converter on it, load the .lux through the native pthread loader,
verify against the in-memory CSC, and (unless -no-run) run the CLI
pagerank on the file.  Every stage timed.

This exercises the exact path the reference tool exists for
(reference tools/converter.cc:85-124: billions of text edges sorted
into binary CSC) at multi-GB size, which the golden tests only cover
on toy files.

Usage: PYTHONPATH=/root/repo:/root/.axon_site python \
    scripts/bench_converter.py [scale ef workdir] [-no-run]
"""

import os
import subprocess
import sys
import time

import numpy as np

scale = int(sys.argv[1]) if len(sys.argv) > 1 else 23
ef = int(sys.argv[2]) if len(sys.argv) > 2 else 16
workdir = sys.argv[3] if len(sys.argv) > 3 else "/tmp/convbench"
no_run = "-no-run" in sys.argv

from lux_tpu import native
from lux_tpu.convert import rmat_edges

os.makedirs(workdir, exist_ok=True)
txt = os.path.join(workdir, f"rmat{scale}_ef{ef}.txt")
lux = os.path.join(workdir, f"rmat{scale}_ef{ef}.lux")

t0 = time.time()
src, dst, nv = rmat_edges(scale=scale, edge_factor=ef, seed=0)
ne = len(src)
print(f"edges generated: nv={nv} ne={ne} ({time.time() - t0:.0f}s)",
      flush=True)

if not os.path.exists(txt):
    import pandas as pd
    t0 = time.time()
    pd.DataFrame({"s": src.astype(np.uint32),
                  "d": dst.astype(np.uint32)}).to_csv(
        txt, sep=" ", header=False, index=False)
    print(f"text edge list written: "
          f"{os.path.getsize(txt) / 1e9:.2f} GB "
          f"({time.time() - t0:.0f}s)", flush=True)

native.ensure_built()
conv = os.path.join(os.path.dirname(native.__file__), "build",
                    "lux_converter")
t0 = time.time()
subprocess.run([conv, "-nv", str(nv), "-ne", str(ne),
                "-input", txt, "-output", lux], check=True)
t_conv = time.time() - t0
print(f"lux_converter: {os.path.getsize(lux) / 1e9:.2f} GB "
      f"({t_conv:.0f}s, {ne / t_conv / 1e6:.1f} M edges/s)", flush=True)

# native loader + structural verification against the in-memory CSC
from lux_tpu.graph import Graph

t0 = time.time()
g = Graph.from_file(lux, use_native=True)
print(f"native load: ({time.time() - t0:.0f}s)", flush=True)
assert g.nv == nv and g.ne == ne
# converter sorts by dst (stable); verify per-vertex edge COUNTS and
# the multiset of sources for a sample of destinations
deg_in = np.bincount(dst, minlength=nv)
np.testing.assert_array_equal(
    np.diff(g.row_ptrs.astype(np.int64), prepend=0), deg_in)
rng = np.random.default_rng(0)
rp = g.row_ptrs.astype(np.int64)
order = np.argsort(dst, kind="stable")     # ONE sort; per-sample
dst_sorted = dst[order]                    # lookups are then O(log ne)
for v in rng.integers(0, nv, 50):
    lo = rp[v - 1] if v else 0
    got = np.sort(g.col_idx[lo:rp[v]])
    a, b = np.searchsorted(dst_sorted, [v, v + 1])
    want = np.sort(src[order[a:b]])
    np.testing.assert_array_equal(got, want)
print("structure verified (degrees exact + 50 sampled vertices)",
      flush=True)

if not no_run:
    t0 = time.time()
    r = subprocess.run(
        [sys.executable, "-m", "lux_tpu.cli", "pagerank", "-file", lux,
         "-ni", "5"], capture_output=True, text=True)
    print(r.stdout.strip(), flush=True)
    if r.returncode:
        print(r.stderr[-2000:], file=sys.stderr)
        sys.exit(1)
    print(f"cli pagerank end-to-end ({time.time() - t0:.0f}s)",
          flush=True)
