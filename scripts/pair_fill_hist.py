"""Host-side pair-row occupancy analysis (north-star work, round 5;
K-aware SDDMM economics, round 8).

For a graph (R-MAT by scale, or the synthesized NetFlix rating shape),
builds the pair analysis per part and prints the row-fill distribution
plus the min_fill economics curve: for each candidate F, how many rows
survive, what coverage remains, and the MODELED per-iteration delivery
cost

    rows * pair_row_ns(kdim) + residual_edges * residual_ns

so the best F is visible without a TPU run.  kdim > 1 prices K-dim
(SDDMM, ops/pairs.pair_partial_dot*) rows: row cost grows with K
(scalemodel.pair_row_ns — two [128, K] tile fetches + two 128x128xK
MXU contractions per row), so the break-even fill is HIGHER than the
scalar ~16 (~22 at colfilter's K=20).  No device work — pure numpy.

Usage:
  PYTHONPATH=/root/repo python scripts/pair_fill_hist.py \
      [mode=pair|page] [shape=rmat|netflix|community] [scale=21] \
      [ratings=100000000] [np=1] [pair=16] [kdim=1] [residual_ns=0] \
      [reorder=none|degree|native|hillclimb] [exchange=gather|owner]

residual_ns=0 uses the modeled K-aware default
(scalemodel.residual_edge_ns).  shape=netflix builds the bench shape
(scripts/bench_netflix.py, convert.netflix_like_edges) and defaults
kdim to colfilter's K=20.

mode=page (round 16): the PAGED delivery's per-(dst tile, src page)
fill histogram instead of the pair one — the objective the reorder
pass maximizes (lux_tpu/reorder.py; ``reorder=`` applies it first)
— plus the modeled break-even VERDICT: the plan's measured
padded_fill / page_ratio against scalemodel.page_break_even_fill and
what ``gather="auto"`` would resolve.  shape=community builds the
scrambled locality-rich synthetic (convert.community_edges).  All
host numpy — reorder gains are inspectable without a device.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def _build_graph(cfg):
    t0 = time.time()
    if cfg["shape"] == "netflix":
        from lux_tpu.convert import netflix_like_edges
        from lux_tpu.graph import Graph
        src, dst, w, nv = netflix_like_edges(n_ratings=cfg["ratings"])
        g = Graph.from_edges(src, dst, nv, weights=w)
    elif cfg["shape"] == "community":
        from lux_tpu.convert import community_graph
        g = community_graph(scale=cfg["scale"], edge_factor=16)
    else:
        from lux_tpu.convert import rmat_graph
        g = rmat_graph(scale=cfg["scale"], edge_factor=16, seed=0)
    print(f"# graph built in {time.time() - t0:.0f}s",
          file=sys.stderr)
    return g


def page_fill_main(cfg):
    """mode=page: per-(tile, page) fill histogram + break-even
    verdict for the given graph/order."""
    from lux_tpu.graph import ShardedGraph
    from lux_tpu.ops.pagegather import (plan_paged_gather,
                                        plan_owner_paged,
                                        plan_paged_stats,
                                        resolve_gather)
    from lux_tpu.reorder import page_reorder
    from lux_tpu.scalemodel import (page_break_even_fill,
                                    page_gather_ns)

    g = _build_graph(cfg)
    t0 = time.time()
    g2, _perm, report = page_reorder(g, method=cfg["reorder"],
                                     num_parts=cfg["np"],
                                     exchange=cfg["exchange"])
    print(f"# reorder {cfg['reorder']} in {time.time() - t0:.0f}s",
          file=sys.stderr)
    sg = ShardedGraph.build(g2, cfg["np"], vpad_align=128)
    owner = cfg["exchange"] == "owner"
    pp = plan_owner_paged(sg) if owner else plan_paged_gather(sg)
    stats = plan_paged_stats(sg, exchange=cfg["exchange"],
                             pagemajor=True)
    table_bytes = sg.num_parts * sg.vpad * 4
    be = page_break_even_fill(stats["page_ratio"], table_bytes)
    resolved = resolve_gather("auto", stats, table_bytes,
                              exchange=cfg["exchange"])
    verdict = dict(
        shape=cfg["shape"], reorder=cfg["reorder"],
        np=cfg["np"], exchange=cfg["exchange"], ne=int(sg.ne),
        page_fill=round(float(stats["padded_fill"]), 2),
        live_fill=round(float(stats["fill"]), 2),
        page_ratio=round(float(stats["page_ratio"]), 4),
        pm_g_fill=round(float(stats["pm_g_fill"]), 2),
        pm_vfill=round(float(stats["pm_padded_vfill"]), 2),
        break_even=be,
        modeled_ns_per_edge=round(page_gather_ns(
            stats["page_ratio"], stats["padded_fill"]), 2),
        auto_resolves=resolved,
        paged_pays=bool(stats["padded_fill"] >= be),
        reorder_trail=report["candidates"])
    print(json.dumps(verdict))
    # per-(tile, page) fill histogram over LIVE delivery rows (the
    # plan's row fill; class-ladder pad rows excluded here — the
    # padded economics are the verdict line's padded_fill)
    W = 128
    fills = np.zeros(W + 1, np.int64)
    for p in range(pp.slot_lane.shape[0]):
        live = (pp.rel_dst[p] != -1).sum(axis=1)
        fills += np.bincount(np.minimum(live, W), minlength=W + 1)
    fills[0] = 0                       # dead (pad) rows
    print("| fill | rows | edges |")
    print("|---|---|---|")
    edges = fills * np.arange(W + 1)
    bands = [(1, 8), (8, 16), (16, 23), (23, 32), (32, 64),
             (64, 128), (128, 129)]
    for lo, hi in bands:
        r = int(fills[lo:hi].sum())
        e = int(edges[lo:hi].sum())
        label = f"{lo}-{hi - 1}" if hi - lo > 1 else f"{lo}"
        print(f"| {label} | {r} | {e} |")


def main():
    cfg = dict(mode="pair", shape="rmat", scale=21,
               ratings=100_000_000, np=1, pair=16, kdim=0,
               residual_ns=0.0, reorder="none", exchange="gather")
    for a in sys.argv[1:]:
        k, v = a.split("=", 1)
        if k not in cfg:
            raise SystemExit(f"unknown arg {k!r} (known: "
                             f"{', '.join(cfg)})")
        cfg[k] = (v if k in ("shape", "mode", "reorder", "exchange")
                  else float(v) if k == "residual_ns" else int(v))
    if cfg["mode"] == "page":
        return page_fill_main(cfg)
    if cfg["mode"] != "pair":
        raise SystemExit(f"unknown mode {cfg['mode']!r} "
                         f"(pair or page)")

    from lux_tpu.graph import ShardedGraph, pair_relabel
    from lux_tpu.ops.pairs import W, analyze_pairs, fill_histogram
    from lux_tpu.scalemodel import (break_even_fill, pair_row_ns,
                                    residual_edge_ns)

    kdim = cfg["kdim"] or (20 if cfg["shape"] == "netflix" else 1)
    residual_ns = cfg["residual_ns"] or residual_edge_ns(kdim)
    row_ns = pair_row_ns(kdim)

    t0 = time.time()
    if cfg["shape"] == "netflix":
        from lux_tpu.convert import netflix_like_edges
        src, dst, w, nv = netflix_like_edges(n_ratings=cfg["ratings"])
        from lux_tpu.graph import Graph
        g = Graph.from_edges(src, dst, nv, weights=w)
        del src, dst, w
    else:
        from lux_tpu.convert import rmat_graph
        g = rmat_graph(scale=cfg["scale"], edge_factor=16, seed=0)
    g2, _perm, starts = pair_relabel(g, cfg["np"],
                                     pair_threshold=cfg["pair"])
    sg = ShardedGraph.build(g2, cfg["np"], starts=starts,
                            pair_threshold=cfg["pair"])
    print(f"# built in {time.time() - t0:.0f}s", file=sys.stderr)

    ne_total = g.ne
    # per-(pair, occ-level) fill histogram across all parts: level
    # fill == number of edges at that occurrence level (see
    # analyze_pairs min_fill docstring)
    fill_counts = np.zeros(W + 1, np.int64)   # fill value -> #rows
    for r in range(len(sg.part_ids())):
        nep = int(sg.ne_part[r])
        a = analyze_pairs(sg.src_slot[r, :nep], sg.dst_local[r, :nep],
                          sg.vpad, threshold=cfg["pair"])
        _gp, _go, fill = fill_histogram(a.pidx, a.occ)
        fill_counts += np.bincount(np.minimum(fill, W),
                                   minlength=W + 1)

    rows_total = int(fill_counts.sum())
    edges_by_fill = fill_counts * np.arange(W + 1)
    cov_total = int(edges_by_fill.sum())
    print(json.dumps(dict(
        shape=cfg["shape"], kdim=kdim,
        pair_row_ns=round(row_ns, 1),
        residual_ns=round(residual_ns, 2),
        break_even=break_even_fill(kdim, residual_ns),
        ne=ne_total, covered=cov_total, rows=rows_total,
        coverage=round(cov_total / ne_total, 4),
        mean_fill=round(cov_total / max(rows_total, 1), 2))))

    # economics: keep rows with fill >= F (the min_fill drop is the
    # per-pair occurrence tail, and fill is monotone in depth, so
    # thresholding the histogram models it exactly)
    print("| F | rows kept | coverage | modeled s/iter |")
    print("|---|---|---|---|")
    for F in (1, 4, 8, 12, 16, 20, 22, 24, 32, 48, 64):
        keep = fill_counts[F:].sum()
        cov = int(edges_by_fill[F:].sum())
        resid = ne_total - cov
        cost = (keep * row_ns + resid * residual_ns) * 1e-9
        print(f"| {F} | {int(keep)} | {cov / ne_total:.3f} "
              f"| {cost:.3f} |")


if __name__ == "__main__":
    main()
