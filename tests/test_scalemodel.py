"""Calibration tests: the mesh-scaling model must reproduce the
RECORDED single-chip measurements (PERF_NOTES round 3/4) from their
recorded layout stats before its multi-chip projections mean
anything.  chips=1 prices exactly the measured situation: one chip
scans every part sequentially."""

import pytest

from lux_tpu.scalemodel import project_pull, project_table

RMAT25_NE, RMAT25_NV = 2**25 * 16, 2**25
RMAT26_NE, RMAT26_NV = 2**26 * 16, 2**26


def test_calibration_rmat25_pair_owner():
    # RMAT25 np=4 pair(16)+owner(E=128): measured 5.13 s/iter,
    # 0.1046 GTEPS; stats: 45% coverage, 6.88x row inflation, 338M
    # owner slots over the 295M-edge residual (PERF_NOTES round 4)
    p = project_pull(RMAT25_NE, RMAT25_NV, chips=1,
                     chunk_inflation=338 / 295, pair_coverage=0.45,
                     pair_row_inflation=6.88)
    assert p.iter_s == pytest.approx(5.13, rel=0.15)
    assert p.gteps == pytest.approx(0.1046, rel=0.15)


def test_calibration_rmat26_owner():
    # RMAT26 np=8 owner(E=256) no-pair: measured 0.0675 GTEPS;
    # 1.6B padded slots over 1.07B edges (PERF_NOTES rounds 3-4)
    p = project_pull(RMAT26_NE, RMAT26_NV, chips=1,
                     chunk_inflation=1.49)
    assert p.gteps == pytest.approx(0.0675, rel=0.15)


def test_mesh_scaling_shape():
    # the economics the mesh is FOR: compute divides by chips, comm
    # stays O(state table) per chip -- near-linear until the per-chip
    # edge share shrinks toward the comm floor
    one = project_pull(RMAT26_NE, RMAT26_NV, 1, chunk_inflation=1.49)
    eight = project_pull(RMAT26_NE, RMAT26_NV, 8, chunk_inflation=1.49)
    sixtyfour = project_pull(RMAT26_NE, RMAT26_NV, 64,
                             chunk_inflation=1.49)
    assert eight.gteps == pytest.approx(8 * one.gteps, rel=0.05)
    assert sixtyfour.efficiency > 0.90
    assert sixtyfour.comm_s < 0.05 * sixtyfour.compute_s
    # comm volume per chip is flat in the mesh size, never growing
    assert sixtyfour.comm_s < 2 * eight.comm_s


def test_rejects_unknown_exchange():
    with pytest.raises(ValueError):
        project_pull(RMAT25_NE, RMAT25_NV, 4, exchange="shuffle")


def test_table_renders():
    t = project_table(RMAT26_NE, RMAT26_NV, chunk_inflation=1.49)
    assert t.count("\n") == 6 and "| 64 |" in t
