"""Golden tests for the .lux binary format (reference README.md:55-79)."""

import struct

import numpy as np
import pytest

from lux_tpu import format as luxfmt
from lux_tpu.convert import edges_to_csc, uniform_random_edges
from lux_tpu.graph import Graph


def tiny_graph():
    # 4 vertices; edges (src -> dst): 1->0, 2->0, 0->1, 3->2, 0->2, 2->3
    src = np.array([1, 2, 0, 3, 0, 2], dtype=np.uint32)
    dst = np.array([0, 0, 1, 2, 2, 3], dtype=np.uint32)
    return src, dst, 4


def test_csc_build_matches_hand_computed():
    src, dst, nv = tiny_graph()
    row_ptrs, col_idx, w, deg = edges_to_csc(src, dst, nv)
    # in-edges per dst: v0 <- {1,2}, v1 <- {0}, v2 <- {0,3}, v3 <- {2}
    assert row_ptrs.tolist() == [2, 3, 5, 6]          # END offsets
    assert col_idx.tolist() == [1, 2, 0, 0, 3, 2]     # (dst, src) order
    assert deg.tolist() == [2, 1, 2, 1]               # out-degrees


def test_file_byte_layout(tmp_path):
    """The exact byte layout: nv u32, ne u64, row_ptrs u64[nv],
    col_idx u32[ne], trailing degrees u32[nv]."""
    src, dst, nv = tiny_graph()
    row_ptrs, col_idx, _, deg = edges_to_csc(src, dst, nv)
    p = tmp_path / "tiny.lux"
    luxfmt.write_lux(str(p), row_ptrs, col_idx, degrees=deg)
    blob = p.read_bytes()
    assert len(blob) == 12 + 8 * 4 + 4 * 6 + 4 * 4
    assert struct.unpack_from("<I", blob, 0)[0] == 4
    assert struct.unpack_from("<Q", blob, 4)[0] == 6
    assert struct.unpack_from("<4Q", blob, 12) == (2, 3, 5, 6)
    assert struct.unpack_from("<6I", blob, 44) == (1, 2, 0, 0, 3, 2)
    assert struct.unpack_from("<4I", blob, 68) == (2, 1, 2, 1)


def test_roundtrip_unweighted(tmp_path):
    src, dst = uniform_random_edges(100, 1000, seed=3)
    g = Graph.from_edges(src, dst, 100)
    p = tmp_path / "g.lux"
    luxfmt.write_lux(str(p), g.row_ptrs, g.col_idx, degrees=g.out_degrees)
    g2 = Graph.from_file(str(p))
    np.testing.assert_array_equal(g.row_ptrs, g2.row_ptrs)
    np.testing.assert_array_equal(g.col_idx, g2.col_idx)
    np.testing.assert_array_equal(g.out_degrees, g2.out_degrees)
    assert g2.weights is None


def test_roundtrip_weighted(tmp_path):
    src, dst, w = uniform_random_edges(50, 400, seed=4, weighted=True)
    g = Graph.from_edges(src, dst, 50, weights=w)
    p = tmp_path / "gw.lux"
    luxfmt.write_lux(str(p), g.row_ptrs, g.col_idx, weights=g.weights,
                     degrees=g.out_degrees)
    g2 = Graph.from_file(str(p), weighted=True)
    np.testing.assert_array_equal(np.asarray(g.weights),
                                  np.asarray(g2.weights))
    np.testing.assert_array_equal(g.col_idx, g2.col_idx)


def test_peek_and_size_validation(tmp_path):
    src, dst, nv = tiny_graph()
    row_ptrs, col_idx, _, deg = edges_to_csc(src, dst, nv)
    p = tmp_path / "t.lux"
    luxfmt.write_lux(str(p), row_ptrs, col_idx)
    hdr = luxfmt.peek_lux(str(p))
    assert (hdr.nv, hdr.ne, hdr.has_weights, hdr.has_degrees) == \
        (4, 6, False, False)
    # corrupt: truncate
    blob = p.read_bytes()[:-3]
    p.write_bytes(blob)
    with pytest.raises(ValueError):
        luxfmt.peek_lux(str(p))


def test_write_rejects_inconsistent():
    with pytest.raises(ValueError):
        luxfmt.write_lux("/tmp/never.lux", np.array([1, 2], np.uint64),
                         np.array([0, 0, 0], np.uint32))


# -- round-9 validated loading: every malformed-input class is a TYPED
#    error naming the check, never a wrong-answer run ------------------

def _write_good(tmp_path, degrees=True):
    src, dst = uniform_random_edges(60, 400, seed=11)
    g = Graph.from_edges(src, dst, 60)
    p = tmp_path / "v.lux"
    luxfmt.write_lux(str(p), g.row_ptrs, g.col_idx,
                     degrees=g.out_degrees if degrees else None)
    return p, g


def test_validate_accepts_good_file(tmp_path):
    p, g = _write_good(tmp_path)
    hdr, rp, ci, _w, deg = luxfmt.read_lux(str(p), validate=True)
    np.testing.assert_array_equal(np.asarray(ci), g.col_idx)
    g2 = Graph.from_file(str(p), validate=True)
    np.testing.assert_array_equal(g2.col_idx, g.col_idx)


def test_validate_nonmonotone_row_ptrs(tmp_path):
    p, _g = _write_good(tmp_path)
    with open(p, "r+b") as f:
        f.seek(12 + 8 * 2)                  # row_ptrs[2] -> 0
        f.write(np.array([0], np.uint64).tobytes())
    with pytest.raises(luxfmt.GraphFormatError) as ei:
        luxfmt.read_lux(str(p), validate=True)
    assert ei.value.check == "row_ptrs_monotone"
    # WITHOUT validate it loads silently — exactly the hole -validate
    # closes (XLA gathers would clamp, producing wrong results)
    luxfmt.read_lux(str(p))


def test_validate_out_of_range_col_idx(tmp_path):
    p, _g = _write_good(tmp_path)
    with open(p, "r+b") as f:
        f.seek(12 + 8 * 60)                 # col_idx[0] -> 999
        f.write(np.array([999], np.uint32).tobytes())
    with pytest.raises(luxfmt.GraphFormatError) as ei:
        luxfmt.read_lux(str(p), validate=True)
    assert ei.value.check == "col_idx_range"


def test_validate_truncated_payload(tmp_path):
    p, _g = _write_good(tmp_path)
    blob = p.read_bytes()
    p.write_bytes(blob[:-7])
    with pytest.raises(luxfmt.GraphFormatError) as ei:
        luxfmt.read_lux(str(p), validate=True)
    assert ei.value.check == "section_size"


def test_validate_degree_mismatch(tmp_path):
    src, dst = uniform_random_edges(60, 400, seed=11)
    g = Graph.from_edges(src, dst, 60)
    deg = g.out_degrees.copy()
    deg[3] += 1
    p = tmp_path / "d.lux"
    luxfmt.write_lux(str(p), g.row_ptrs, g.col_idx, degrees=deg)
    with pytest.raises(luxfmt.GraphFormatError) as ei:
        luxfmt.read_lux(str(p), validate=True)
    assert ei.value.check == "degrees_consistent"


def test_weighted_mismatch_is_typed(tmp_path):
    """Opening an unweighted file as weighted raises the TYPED error
    (the CLI's -validate handler catches GraphFormatError only)."""
    p, _g = _write_good(tmp_path, degrees=False)
    with pytest.raises(luxfmt.GraphFormatError) as ei:
        luxfmt.peek_lux(str(p), weighted=True)
    assert ei.value.check == "weighted_mismatch"


def test_validate_graph_arrays_direct():
    luxfmt.validate_graph(3, 2, np.array([1, 2, 2], np.uint64),
                          np.array([0, 2], np.uint32))
    with pytest.raises(luxfmt.GraphFormatError) as ei:
        luxfmt.validate_graph(3, 5, np.array([1, 2, 2], np.uint64),
                              np.array([0, 2], np.uint32))
    assert ei.value.check == "row_ptrs_total"


def test_sharded_build_rejects_bad_partition():
    from lux_tpu.graph import ShardedGraph

    src, dst = uniform_random_edges(50, 300, seed=3)
    g = Graph.from_edges(src, dst, 50)
    with pytest.raises(luxfmt.GraphFormatError) as ei:
        ShardedGraph.build(g, 2, starts=np.array([0, 40, 30]))
    assert ei.value.check == "partition_starts"
    with pytest.raises(luxfmt.GraphFormatError):
        ShardedGraph.build(g, 2, starts=np.array([0, 25, 49]))


def test_sharded_build_rejects_corrupt_row_ptrs():
    """A malformed graph fed straight to the partition build (no
    -validate on the load) still errors on its shard boundaries."""
    from lux_tpu.graph import Graph as G
    from lux_tpu.graph import ShardedGraph

    src, dst = uniform_random_edges(50, 300, seed=3)
    g = G.from_edges(src, dst, 50)
    rp = g.row_ptrs.copy()
    rp[10] = 0                              # non-monotone
    bad = G(nv=g.nv, ne=g.ne, row_ptrs=rp, col_idx=g.col_idx,
            weights=None, out_degrees=g.out_degrees)
    with pytest.raises(luxfmt.GraphFormatError) as ei:
        ShardedGraph.build(bad, 2)
    assert ei.value.check in ("partition_edges", "partition_starts")


def test_fsck_lux_script(tmp_path):
    import subprocess
    import sys
    from pathlib import Path

    script = Path(__file__).resolve().parent.parent / "scripts" / \
        "fsck_lux.py"
    good, _g = _write_good(tmp_path)
    bad = tmp_path / "bad.lux"
    bad.write_bytes(good.read_bytes())
    with open(bad, "r+b") as f:
        f.seek(12 + 8 * 60)
        f.write(np.array([999], np.uint32).tobytes())
    r = subprocess.run([sys.executable, str(script), str(good)],
                       capture_output=True, text=True)
    assert r.returncode == 0 and "OK" in r.stdout
    r = subprocess.run([sys.executable, str(script), str(good),
                        str(bad)], capture_output=True, text=True)
    assert r.returncode == 1
    assert "col_idx_range" in r.stderr and "1 of 2" in r.stderr


# ---------------------------------------------------------------------
# round 20: the mutation-log (WAL) header (lux_tpu/livegraph.py)


def test_wal_header_roundtrip():
    head = luxfmt.pack_wal_header(1234, 64)
    assert len(head) == luxfmt.WAL_HEADER_SIZE
    assert head[:4] == luxfmt.WAL_MAGIC
    nv, cap, ver = luxfmt.read_wal_header("<mem>", head=head)
    assert (nv, cap, ver) == (1234, 64, luxfmt.WAL_VERSION)
    # the v2 reader still reads v1 headers (round-21 compat contract)
    head1 = luxfmt.pack_wal_header(1234, 64, version=1)
    nv, cap, ver = luxfmt.read_wal_header("<mem>", head=head1)
    assert (nv, cap, ver) == (1234, 64, 1)
    with pytest.raises(ValueError, match="unknown WAL version"):
        luxfmt.pack_wal_header(1234, 64, version=99)
    # the nv cross-check: a log from a DIFFERENT graph is typed
    with pytest.raises(luxfmt.GraphFormatError) as ei:
        luxfmt.read_wal_header("<mem>", nv=1235, head=head)
    assert ei.value.check == "wal_header"


def test_wal_header_rejects_garbage_and_versions(tmp_path):
    with pytest.raises(luxfmt.GraphFormatError) as ei:
        luxfmt.read_wal_header("<mem>", head=b"LUXWxx")   # short
    assert ei.value.check == "wal_header"
    with pytest.raises(luxfmt.GraphFormatError) as ei:
        luxfmt.read_wal_header(
            "<mem>", head=b"NOPE" + np.array([1, 4, 4],
                                             luxfmt.V_DTYPE).tobytes())
    assert ei.value.check == "wal_header"
    bad_ver = luxfmt.WAL_MAGIC + np.array(
        [luxfmt.WAL_VERSION + 1, 4, 4], luxfmt.V_DTYPE).tobytes()
    with pytest.raises(luxfmt.GraphFormatError) as ei:
        luxfmt.read_wal_header("<mem>", head=bad_ver)
    assert ei.value.check == "wal_version"
    bad_cap = luxfmt.WAL_MAGIC + np.array(
        [luxfmt.WAL_VERSION, 4, 0], luxfmt.V_DTYPE).tobytes()
    with pytest.raises(luxfmt.GraphFormatError) as ei:
        luxfmt.read_wal_header("<mem>", head=bad_cap)
    assert ei.value.check == "wal_capacity"
    # file-read path (no head=): same validation
    p = tmp_path / "g.wal"
    p.write_bytes(bad_ver)
    with pytest.raises(luxfmt.GraphFormatError):
        luxfmt.read_wal_header(str(p))
