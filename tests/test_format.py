"""Golden tests for the .lux binary format (reference README.md:55-79)."""

import struct

import numpy as np
import pytest

from lux_tpu import format as luxfmt
from lux_tpu.convert import edges_to_csc, uniform_random_edges
from lux_tpu.graph import Graph


def tiny_graph():
    # 4 vertices; edges (src -> dst): 1->0, 2->0, 0->1, 3->2, 0->2, 2->3
    src = np.array([1, 2, 0, 3, 0, 2], dtype=np.uint32)
    dst = np.array([0, 0, 1, 2, 2, 3], dtype=np.uint32)
    return src, dst, 4


def test_csc_build_matches_hand_computed():
    src, dst, nv = tiny_graph()
    row_ptrs, col_idx, w, deg = edges_to_csc(src, dst, nv)
    # in-edges per dst: v0 <- {1,2}, v1 <- {0}, v2 <- {0,3}, v3 <- {2}
    assert row_ptrs.tolist() == [2, 3, 5, 6]          # END offsets
    assert col_idx.tolist() == [1, 2, 0, 0, 3, 2]     # (dst, src) order
    assert deg.tolist() == [2, 1, 2, 1]               # out-degrees


def test_file_byte_layout(tmp_path):
    """The exact byte layout: nv u32, ne u64, row_ptrs u64[nv],
    col_idx u32[ne], trailing degrees u32[nv]."""
    src, dst, nv = tiny_graph()
    row_ptrs, col_idx, _, deg = edges_to_csc(src, dst, nv)
    p = tmp_path / "tiny.lux"
    luxfmt.write_lux(str(p), row_ptrs, col_idx, degrees=deg)
    blob = p.read_bytes()
    assert len(blob) == 12 + 8 * 4 + 4 * 6 + 4 * 4
    assert struct.unpack_from("<I", blob, 0)[0] == 4
    assert struct.unpack_from("<Q", blob, 4)[0] == 6
    assert struct.unpack_from("<4Q", blob, 12) == (2, 3, 5, 6)
    assert struct.unpack_from("<6I", blob, 44) == (1, 2, 0, 0, 3, 2)
    assert struct.unpack_from("<4I", blob, 68) == (2, 1, 2, 1)


def test_roundtrip_unweighted(tmp_path):
    src, dst = uniform_random_edges(100, 1000, seed=3)
    g = Graph.from_edges(src, dst, 100)
    p = tmp_path / "g.lux"
    luxfmt.write_lux(str(p), g.row_ptrs, g.col_idx, degrees=g.out_degrees)
    g2 = Graph.from_file(str(p))
    np.testing.assert_array_equal(g.row_ptrs, g2.row_ptrs)
    np.testing.assert_array_equal(g.col_idx, g2.col_idx)
    np.testing.assert_array_equal(g.out_degrees, g2.out_degrees)
    assert g2.weights is None


def test_roundtrip_weighted(tmp_path):
    src, dst, w = uniform_random_edges(50, 400, seed=4, weighted=True)
    g = Graph.from_edges(src, dst, 50, weights=w)
    p = tmp_path / "gw.lux"
    luxfmt.write_lux(str(p), g.row_ptrs, g.col_idx, weights=g.weights,
                     degrees=g.out_degrees)
    g2 = Graph.from_file(str(p), weighted=True)
    np.testing.assert_array_equal(np.asarray(g.weights),
                                  np.asarray(g2.weights))
    np.testing.assert_array_equal(g.col_idx, g2.col_idx)


def test_peek_and_size_validation(tmp_path):
    src, dst, nv = tiny_graph()
    row_ptrs, col_idx, _, deg = edges_to_csc(src, dst, nv)
    p = tmp_path / "t.lux"
    luxfmt.write_lux(str(p), row_ptrs, col_idx)
    hdr = luxfmt.peek_lux(str(p))
    assert (hdr.nv, hdr.ne, hdr.has_weights, hdr.has_degrees) == \
        (4, 6, False, False)
    # corrupt: truncate
    blob = p.read_bytes()[:-3]
    p.write_bytes(blob)
    with pytest.raises(ValueError):
        luxfmt.peek_lux(str(p))


def test_write_rejects_inconsistent():
    with pytest.raises(ValueError):
        luxfmt.write_lux("/tmp/never.lux", np.array([1, 2], np.uint64),
                         np.array([0, 0, 0], np.uint32))
