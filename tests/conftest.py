"""Test configuration: run JAX on a virtual 8-device CPU mesh.

This is the TPU-native analogue of the reference's "multi-node without a
cluster" gap (SURVEY.md §4): all sharding/collective paths are exercised
on host devices via --xla_force_host_platform_device_count.
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax

# Keep default 32-bit types: that is what runs on TPU.
jax.config.update("jax_platforms", "cpu")
