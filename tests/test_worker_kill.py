"""Kill-one-worker degraded recovery across REAL processes (round-11
satellite, beside tests/test_multiprocess.py): 2 jax.distributed
workers run a heartbeat-supervised checkpointed pagerank; worker 1 is
HARD-KILLED mid-run (faults.WORKER_KILL hard_kill — os._exit, no
goodbye); worker 0 detects the death through the heartbeat deadline
(no collective hang), agrees on the shrunken topology, and exits for
relaunch; the single-process relaunch resumes from the shared
checkpoint (placement ndev=8 re-placed onto 4 — a ``replace`` event)
and finishes to the NumPy oracle.

Capability-gated exactly like test_multiprocess.py: XLA CPU builds
without multi-process collectives skip on the known signature.
"""

import os
import re
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NPROC = 2

_CPU_MP_UNSUPPORTED = re.compile(
    r"[Mm]ultiprocess computations aren'?t implemented on the CPU "
    r"backend")


def test_worker_kill_degraded_relaunch(tmp_path):
    from lux_tpu import faults

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    env = dict(os.environ)
    env.update(PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=4")
    worker = os.path.join(REPO, "tests", "mp_elastic_worker.py")
    workdir = str(tmp_path)

    procs = [subprocess.Popen(
        [sys.executable, worker, str(i), str(NPROC), str(port),
         workdir, "distributed"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for i in range(NPROC)]
    try:
        outs = [p.communicate(timeout=600)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any(_CPU_MP_UNSUPPORTED.search(o) for o in outs):
        pytest.skip("this jaxlib's CPU backend does not implement "
                    "multi-process computations (capability probe "
                    "hit the known XLA signature)")

    # worker 1 died the hard way at segment boundary 1
    assert procs[1].returncode == faults.HARD_KILL_CODE, outs[1]
    # worker 0 detected it at the NEXT boundary (deadline, not hang),
    # agreed on the shrunken topology, and asked for a relaunch
    assert procs[0].returncode == 3, outs[0]
    assert "SHRINK pid=0" in outs[0], outs[0]
    assert "survivors=[0]" in outs[0], outs[0]
    # the shared checkpoint exists (written collectively, one writer)
    assert os.path.exists(os.path.join(workdir, "elastic.ckpt.npz"))

    # the degraded relaunch: one process, 4 local devices, resume
    solo = subprocess.run(
        [sys.executable, worker, "0", "1", "0", workdir, "solo"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=600)
    assert solo.returncode == 0, solo.stdout
    assert "SOLO_OK" in solo.stdout, solo.stdout
