"""PageRank vs NumPy oracle (the test pyramid the reference lacks,
SURVEY.md §4 item 4)."""

import numpy as np
import pytest

from lux_tpu.apps import pagerank
from lux_tpu.convert import rmat_edges, uniform_random_edges
from lux_tpu.graph import Graph


@pytest.fixture(scope="module")
def small_graph():
    src, dst = uniform_random_edges(300, 2400, seed=42)
    return Graph.from_edges(src, dst, 300)


@pytest.mark.parametrize("num_parts", [1, 4, 7])
@pytest.mark.parametrize("num_iters", [1, 5])
def test_matches_oracle(small_graph, num_parts, num_iters):
    got = pagerank.run(small_graph, num_iters, num_parts=num_parts)
    want = pagerank.reference_pagerank(small_graph, num_iters)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-9)


def test_skewed_graph():
    src, dst, nv = rmat_edges(scale=10, edge_factor=8, seed=3)
    g = Graph.from_edges(src, dst, nv)
    got = pagerank.run(g, 3, num_parts=6)
    want = pagerank.reference_pagerank(g, 3)
    np.testing.assert_allclose(got, want, rtol=5e-5, atol=1e-9)


def test_zero_degree_vertices():
    """Sinks (deg 0) keep un-normalized rank — reference behavior
    (pagerank_gpu.cu:98-99 divides only when degree != 0)."""
    # vertex 3 is a pure sink, vertex 4 isolated
    src = np.array([0, 1, 2, 0], dtype=np.uint32)
    dst = np.array([1, 2, 3, 3], dtype=np.uint32)
    g = Graph.from_edges(src, dst, 5)
    got = pagerank.run(g, 4, num_parts=2)
    want = pagerank.reference_pagerank(g, 4)
    np.testing.assert_allclose(got, want, rtol=1e-5)
    assert g.out_degrees[3] == 0 and g.out_degrees[4] == 0


def test_fused_equals_stepwise(small_graph):
    eng = pagerank.build_engine(small_graph, num_parts=3)
    s_fused = eng.run(eng.init_state(), 4, fused=True)
    s_step = eng.run(eng.init_state(), 4, fused=False)
    np.testing.assert_allclose(np.asarray(s_fused), np.asarray(s_step),
                               rtol=1e-6)


def test_true_ranks_sum_to_one(small_graph):
    """Un-normalized conventional ranks should sum to ~1 when the graph
    has no sinks (rank mass conserved up to damping leakage)."""
    norm = pagerank.run(small_graph, 10, num_parts=2)
    ranks = pagerank.true_ranks(norm, small_graph.out_degrees)
    # with ALPHA=0.15 damping-form, fixed point sums near (1-A)/(1-A) = 1
    # only approximately on random graphs; sanity band:
    assert 0.5 < ranks.sum() < 2.0


def test_run_until_matches_long_fixed_run():
    from lux_tpu.convert import uniform_random_edges
    src, dst = uniform_random_edges(120, 900, seed=55)
    g = Graph.from_edges(src, dst, 120)
    ranks, iters = pagerank.run_until(g, tol=1e-10, num_parts=2)
    fixed = pagerank.run(g, 200, num_parts=2)
    np.testing.assert_allclose(ranks, fixed, rtol=1e-6, atol=1e-12)
    assert 0 < iters < 200


def test_run_until_respects_max_iters():
    from lux_tpu.convert import uniform_random_edges
    src, dst = uniform_random_edges(80, 500, seed=56)
    g = Graph.from_edges(src, dst, 80)
    _, iters = pagerank.run_until(g, tol=0.0, max_iters=7, num_parts=1)
    assert iters == 7
