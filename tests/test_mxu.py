"""MXU compute core (round 23): bitwise MXU-vs-VPU oracles.

ops/tiled.py's one-hot contraction reduce (sum einsum + the
bit-serial compare tournament), the segmented-scan matmul, the
frontier cumsum-as-matmul (engine/frontier.py), the engine-level A/B
across kinds x payload widths x meshes x delivery modes (the swap
must be INVISIBLE: bitwise for integer states, reassociation-
tolerance for float sums), the typed unsupported error, the
``use_mxu="auto"`` break-even resolution (lux_tpu/scalemodel.py) and
the ``mxu_temp`` ledger term (graph.memory_report).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lux_tpu.apps import colfilter, components, pagerank, sssp
from lux_tpu.convert import uniform_random_edges
from lux_tpu.engine import frontier as fr
from lux_tpu.graph import Graph
from lux_tpu.ops.segment import identity_for, segment_reduce
from lux_tpu.ops.tiled import (MXUUnsupportedError, _order_decode,
                               _order_encode, _segscan,
                               _segscan_matmul, chunk_partials)
from lux_tpu.parallel.mesh import make_mesh

NV, NE = 256, 2048
SOURCES = [0, 5, 9, 100, 131, 7, 200, 63]        # B = 8


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(8)


@pytest.fixture(scope="module")
def g():
    src, dst = uniform_random_edges(NV, NE, seed=3)
    return Graph.from_edges(src, dst, NV)


@pytest.fixture(scope="module")
def gw():
    rng = np.random.default_rng(4)
    src, dst = uniform_random_edges(NV, NE, seed=4)
    return Graph.from_edges(src, dst, NV,
                            weights=rng.integers(1, 6, NE).astype(
                                np.float32))


# ---------------------------------------------------------------------
# ops level: chunk_partials MXU vs VPU, every kind x dtype x payload


def _rand_chunks(dtype, trail=(), seed=0, C=6, E=96, W=128):
    """Random [C, E(, K)] payload + rel_dst with ~15% pad lanes, one
    all-pad chunk (its slots must come back as the identity) and
    garbage payload values AT the pads (the contract: pads contribute
    the identity regardless of what the lanes carry)."""
    rng = np.random.default_rng(seed)
    dt = np.dtype(dtype)
    if dt.kind == "f":
        vals = (rng.standard_normal((C, E) + trail) * 100).astype(dt)
    else:
        info = np.iinfo(dt)
        vals = rng.integers(info.min, int(info.max) + 1,
                            (C, E) + trail, dtype=np.int64).astype(dt)
    rel = rng.integers(0, W, (C, E)).astype(np.int8)
    rel[rng.random((C, E)) < 0.15] = -1
    rel[C // 2] = -1
    return jnp.asarray(vals), jnp.asarray(rel)


COMPARE_DTYPES = [np.int32, np.int16, np.int8, np.uint32, np.uint16,
                  np.uint8, np.float32, np.float16]


@pytest.mark.parametrize("kind", ["min", "max"])
@pytest.mark.parametrize("dtype", COMPARE_DTYPES)
@pytest.mark.parametrize("trail", [(), (3,)])
def test_compare_reduce_bitwise(kind, dtype, trail):
    """The tournament is BITWISE-equal to the VPU masked reduce for
    every supported dtype — floats included (the order encoding is a
    total order, so there is no reassociation to diverge on)."""
    vals, rel = _rand_chunks(dtype, trail)
    want = np.asarray(chunk_partials(vals, rel, 128, kind))
    got = np.asarray(chunk_partials(vals, rel, 128, kind,
                                    use_mxu=True))
    assert got.dtype == want.dtype
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("dtype", [np.int32, np.uint32])
@pytest.mark.parametrize("trail", [(), (3,)])
def test_sum_contraction_bitwise_int(dtype, trail):
    vals, rel = _rand_chunks(dtype, trail, seed=1)
    want = np.asarray(chunk_partials(vals, rel, 128, "sum"))
    got = np.asarray(chunk_partials(vals, rel, 128, "sum",
                                    use_mxu=True))
    assert got.dtype == want.dtype
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("trail", [(), (5,)])
def test_sum_contraction_float_tolerance(trail):
    """Float sums reassociate under the contraction — tolerance, not
    bitwise, is the float-sum contract (same as the engines')."""
    vals, rel = _rand_chunks(np.float32, trail, seed=2)
    want = np.asarray(chunk_partials(vals, rel, 128, "sum"))
    got = np.asarray(chunk_partials(vals, rel, 128, "sum",
                                    use_mxu=True))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-3)


def test_all_pad_chunk_is_identity():
    for kind in ("sum", "min", "max"):
        vals, _ = _rand_chunks(np.int32)
        rel = jnp.full((6, 96), -1, jnp.int8)
        out = np.asarray(chunk_partials(vals, rel, 128, kind,
                                        use_mxu=True))
        ident = identity_for(kind, jnp.int32)
        np.testing.assert_array_equal(
            out, np.full((6, 128), np.asarray(ident), np.int32))


def test_order_encode_roundtrip_and_monotone():
    rng = np.random.default_rng(9)
    for dt in COMPARE_DTYPES:
        dt = np.dtype(dt)
        if dt.kind == "f":
            x = np.sort((rng.standard_normal(64) * 50).astype(dt))
            x = np.concatenate(([-np.inf], x, [np.inf])).astype(dt)
        else:
            info = np.iinfo(dt)
            x = np.sort(rng.integers(info.min, int(info.max) + 1, 64,
                                     dtype=np.int64)).astype(dt)
        enc = np.asarray(_order_encode(jnp.asarray(x)))
        assert enc.dtype == np.uint32
        # unsigned order == payload order, decode inverts
        assert (np.diff(enc.astype(np.uint64)) >= 0).all(), dt
        np.testing.assert_array_equal(
            np.asarray(_order_decode(jnp.asarray(enc), dt)), x)


@pytest.mark.parametrize("kind", ["sum", "min", "max"])
def test_unsupported_dtype_raises_typed(kind):
    vals = jnp.zeros((2, 8, 2), jnp.complex64)
    rel = jnp.zeros((2, 8), jnp.int8)
    with pytest.raises(MXUUnsupportedError) as ei:
        chunk_partials(vals, rel, 128, kind, use_mxu=True)
    # the error names the kind and dtype so the fallback is deliberate
    assert "complex64" in str(ei.value)
    assert ei.value.dtype == np.dtype(np.complex64)


# ---------------------------------------------------------------------
# segmented combine: the scan-as-matmul block recurrence


@pytest.mark.parametrize("trail", [(), (4,)])
@pytest.mark.parametrize("dtype", [np.int32, np.float32])
def test_segscan_matmul_matches_vpu_scan(trail, dtype):
    rng = np.random.default_rng(11)
    C = 300                                   # not a block multiple
    if np.dtype(dtype).kind == "f":
        vals = rng.random((C,) + trail).astype(dtype)
    else:
        vals = rng.integers(-1000, 1000, (C,) + trail).astype(dtype)
    flags = rng.random(C) < 0.07              # segments straddle blocks
    flags[0] = True
    fl = jnp.asarray(flags)
    fb = fl.reshape((C,) + (1,) * len(trail))
    want = np.asarray(_segscan(jnp.asarray(vals), fb, "sum"))
    for block in (7, 64, 512):
        got = np.asarray(_segscan_matmul(jnp.asarray(vals), fl,
                                         block=block))
        if np.dtype(dtype).kind == "f":
            np.testing.assert_allclose(got, want, rtol=1e-5)
        else:
            np.testing.assert_array_equal(got, want)


def test_segscan_matmul_no_leading_flag():
    """A block whose first chunk continues a straddling segment must
    absorb the carry (the sid==0 absorb lane)."""
    vals = jnp.asarray(np.arange(1, 9, dtype=np.int32))
    fl = jnp.asarray(np.array([1, 0, 0, 0, 0, 1, 0, 0], bool))
    want = np.asarray(_segscan(vals, fl, "sum"))
    got = np.asarray(_segscan_matmul(vals, fl, block=3))
    np.testing.assert_array_equal(got, want)


# ---------------------------------------------------------------------
# frontier: scatter-max/cummax as scatter-add/cumsum-matmul


def test_cumsum_matmul_bitwise():
    rng = np.random.default_rng(5)
    for n in (1, 7, 256, 1000):
        x = jnp.asarray(rng.integers(0, 100, n).astype(np.int32))
        np.testing.assert_array_equal(
            np.asarray(fr._cumsum_matmul(x, block=64)),
            np.cumsum(np.asarray(x), dtype=np.int32))


@pytest.mark.parametrize("seed", range(8))
def test_expand_frontier_mxu_bitwise(seed):
    """The MXU edge-slot expansion is bitwise-equal to the VPU
    scatter-max/cummax across randomized queues, truncation and
    degree-0 sources."""
    rng = np.random.default_rng(seed)
    nv = 40
    deg = rng.integers(0, 6, nv)
    deg[rng.random(nv) < 0.3] = 0
    rp = np.concatenate(([0], np.cumsum(deg)))
    present = np.nonzero(deg > 0)[0]
    off = np.concatenate(([0], np.cumsum(deg[present])))
    sids = jnp.asarray(present.astype(np.int32))
    soff = jnp.asarray(off.astype(np.int32))
    q = rng.integers(1, 9)
    ids_np = np.full(q, nv, np.int32)
    k = rng.integers(0, q + 1)
    if k:
        ids_np[:k] = rng.choice(nv, size=k, replace=False)
    ids = jnp.asarray(ids_np)
    vals = jnp.asarray(rng.integers(0, 100, q).astype(np.int32))
    budget = int(rng.integers(1, int(rp[-1]) + 4))
    out_v = fr.expand_frontier(ids, vals, sids, soff, nv, budget)
    out_m = fr.expand_frontier(ids, vals, sids, soff, nv, budget,
                               use_mxu=True)
    for a, b in zip(out_v, out_m):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------
# engine level: the A/B must be invisible in the answers


def _ab(build):
    em, ev = build(True), build(False)
    assert em.use_mxu is True and ev.use_mxu is False
    return em, ev


@pytest.mark.parametrize("gather", ["flat", "paged", "pagemajor"])
def test_pagerank_delivery_modes(g, gather):
    """Scalar f32 sum across delivery modes: the reduce swap is
    tolerance-invisible and the oracle still holds."""
    em, ev = _ab(lambda um: pagerank.build_engine(
        g, num_parts=2, gather=gather, use_mxu=um))
    got_m = em.unpad(em.run(em.init_state(), 5))
    got_v = ev.unpad(ev.run(ev.init_state(), 5))
    np.testing.assert_allclose(got_m, got_v, rtol=1e-5, atol=1e-8)
    np.testing.assert_allclose(got_m, pagerank.reference_pagerank(g, 5),
                               rtol=1e-4, atol=1e-7)


def test_ppr_batched_auto_engages_and_matches(g):
    """The flagship auto-engagement: B=8 batched personalized
    pagerank resolves use_mxu=True from the scalemodel break-even
    (wide 8 >= 2) and matches the forced-VPU build and the oracle."""
    em = pagerank.build_engine(g, num_parts=2, sources=SOURCES)
    assert em.use_mxu is True
    ev = pagerank.build_engine(g, num_parts=2, sources=SOURCES,
                               use_mxu=False)
    got_m = em.unpad(em.run(em.init_state(), 6))
    got_v = ev.unpad(ev.run(ev.init_state(), 6))
    np.testing.assert_allclose(got_m, got_v, rtol=1e-5, atol=1e-8)
    resets = pagerank.one_hot_resets(g.nv, SOURCES)
    np.testing.assert_allclose(
        got_m, pagerank.reference_pagerank_batched(g, resets, 6),
        rtol=1e-4, atol=1e-7)


def test_colfilter_k20_auto_engages(gw):
    """K=20 vector payload (sum): wide 20 >= 2 auto-engages, and the
    factors match the forced-VPU run."""
    em = colfilter.build_engine(gw, num_parts=2)
    assert em.use_mxu is True
    ev = colfilter.build_engine(gw, num_parts=2, use_mxu=False)
    got_m = em.unpad(em.run(em.init_state(), 3))
    got_v = ev.unpad(ev.run(ev.init_state(), 3))
    np.testing.assert_allclose(got_m, got_v, rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("np_parts,use_mesh", [(2, False), (8, True)])
def test_sssp_min_bitwise(g, mesh8, np_parts, use_mesh):
    """int32 min labels: the tournament swap is BITWISE-invisible,
    single-chip and on the 8-virtual-device mesh."""
    mesh = mesh8 if use_mesh else None
    em, ev = _ab(lambda um: sssp.build_engine(
        g, start_vertex=1, num_parts=np_parts, mesh=mesh,
        use_mxu=um))
    lm, _am, itm = em.converge(*em.init_state())
    lv, _av, itv = ev.converge(*ev.init_state())
    assert int(jax.device_get(itm)) == int(jax.device_get(itv))
    np.testing.assert_array_equal(np.asarray(jax.device_get(lm)),
                                  np.asarray(jax.device_get(lv)))
    np.testing.assert_array_equal(
        em.unpad(lm).astype(np.int64),
        np.where(sssp.reference_sssp(g, 1) >= int(sssp.HOP_INF),
                 int(sssp.HOP_INF), sssp.reference_sssp(g, 1)))


@pytest.mark.parametrize("exchange", ["gather", "owner"])
def test_components_max_bitwise(g, exchange):
    """max-label propagation through BOTH exchanges: bitwise."""
    em, ev = _ab(lambda um: components.build_engine(
        g, num_parts=2, exchange=exchange, use_mxu=um))
    lm = em.converge(*em.init_state())[0]
    lv = ev.converge(*ev.init_state())[0]
    np.testing.assert_array_equal(np.asarray(jax.device_get(lm)),
                                  np.asarray(jax.device_get(lv)))


def test_ksssp_batched_owner_mesh8_bitwise(g, mesh8):
    """B=8 k-source SSSP, owner exchange, mesh8: the full stack —
    batched tournament + owner-side combine + collectives — is
    bitwise-invisible and oracle-exact."""
    em, ev = _ab(lambda um: sssp.build_engine(
        g, sources=SOURCES, num_parts=8, mesh=mesh8,
        exchange="owner", use_mxu=um))
    lm = em.converge(*em.init_state())[0]
    lv = ev.converge(*ev.init_state())[0]
    np.testing.assert_array_equal(np.asarray(jax.device_get(lm)),
                                  np.asarray(jax.device_get(lv)))
    ref = sssp.reference_sssp_batched(g, SOURCES)
    np.testing.assert_array_equal(
        em.unpad(lm).astype(np.int64),
        np.where(ref >= int(sssp.HOP_INF), int(sssp.HOP_INF), ref))


def test_stats_counters_bitwise(g):
    """The stats loop variant: frontier/edge counters are exact
    integer series and must be BITWISE-equal across the swap."""
    em, ev = _ab(lambda um: sssp.build_engine(
        g, start_vertex=0, num_parts=2, use_mxu=um))
    lm, _a, itm, fszm, fedm, _fp, _ep = em.converge_stats(
        *em.init_state())
    lv, _a2, itv, fszv, fedv, _fp2, _ep2 = ev.converge_stats(
        *ev.init_state())
    it = int(jax.device_get(itm))
    assert it == int(jax.device_get(itv))
    np.testing.assert_array_equal(np.asarray(jax.device_get(lm)),
                                  np.asarray(jax.device_get(lv)))
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(fszm))[:it],
        np.asarray(jax.device_get(fszv))[:it])
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(fedm))[:it],
        np.asarray(jax.device_get(fedv))[:it])


def test_health_variant_matches(g):
    """The health loop variant runs the MXU path clean and lands on
    the same labels as the plain VPU converge."""
    from lux_tpu import health as hw

    em = sssp.build_engine(g, start_vertex=1, num_parts=2,
                           use_mxu=True, health=True)
    lm = em.converge_health(*em.init_state())
    h = lm[-1]
    assert not hw.ensure_ok(h, engine="push")["tripped"]
    ev = sssp.build_engine(g, start_vertex=1, num_parts=2,
                           use_mxu=False)
    lv = ev.converge(*ev.init_state())[0]
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(lm[0])),
        np.asarray(jax.device_get(lv)))


# ---------------------------------------------------------------------
# auto resolution, scalemodel terms, ledger term, carried debt


def test_break_even_table():
    from lux_tpu import scalemodel as sm

    assert sm.mxu_break_even_wide("sum") == 2
    # 32-bit compare: 64 contraction rounds outrun the VPU margin at
    # every width — min/max NEVER auto-engage (honest negative)
    assert sm.mxu_break_even_wide("min") >= 1 << 30
    assert sm.mxu_break_even_wide("max") >= 1 << 30
    # 16-bit states halve the tournament; finite break-even
    assert sm.mxu_break_even_wide("max", nbits=16) == 3
    assert sm.resolve_use_mxu("sum", wide=2) is True
    assert sm.resolve_use_mxu("sum", wide=1) is False
    assert sm.resolve_use_mxu("min", wide=4096) is False
    with pytest.raises(ValueError):
        sm.mxu_reduce_rounds("prod")


def test_engine_auto_resolution(g, gw):
    """Scalar sum stays VPU (preserving the f32 flagships' bitwise
    behavior), wide payloads engage, min never auto-engages, and a
    bogus flag raises."""
    assert pagerank.build_engine(g, num_parts=2).use_mxu is False
    assert sssp.build_engine(g, num_parts=2).use_mxu is False
    assert pagerank.build_engine(
        g, num_parts=2, sources=SOURCES).use_mxu is True
    with pytest.raises(ValueError, match="use_mxu"):
        pagerank.build_engine(g, num_parts=2, use_mxu="fast")


def test_phase_model_prices_mxu_reduce():
    from lux_tpu import scalemodel as sm

    kw = dict(engine="pull", exchange="gather", ne=10**7, nv=10**5)
    vpu = sm.phase_model(**kw)
    mxu = sm.phase_model(**kw, use_mxu=True, mxu_wide=8)
    # the VPU reduce rides inside the fused gather figure (no
    # separate constant); with use_mxu the contraction IS modeled
    assert mxu["reduce"] is not None and mxu["reduce"] > 0
    rows = 10**7 * 1.2 / 128
    assert mxu["reduce"] == pytest.approx(
        rows * sm.mxu_reduce_row_ns(8, "sum"), rel=1e-9)
    assert vpu.get("reduce") in (None, 0)


def test_memory_report_mxu_temp(g):
    from lux_tpu.graph import ShardedGraph
    from lux_tpu.ops.tiled import STREAM_BLOCK_CHUNKS

    sg = ShardedGraph.build(g, 2)
    rep = sg.memory_report()
    assert rep["mxu_temp_bytes_per_part"] == 0
    rep_m = sg.memory_report(use_mxu=True, mxu_tile_e=512)
    want = min(sg.epad, STREAM_BLOCK_CHUNKS * 512) * 128
    assert rep_m["mxu_temp_bytes_per_part"] == want
    assert rep_m["terms_per_part"]["mxu_temp"] == want
    assert (rep_m["total_bytes"] - rep["total_bytes"]
            == sg.num_parts * want)


def test_mxu_core_debt_carried():
    from lux_tpu import observe

    (d,) = [d for d in observe.DEBTS if d.id == "mxu-core-ab"]
    assert d.platform == "tpu"
    assert d.auto == "_debt_mxu_core_ab"
    assert callable(getattr(observe, d.auto))
