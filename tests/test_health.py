"""Device-side health watchdog (lux_tpu/health.py, round-9 tentpole).

The acceptance bar: every corruption class is deterministically
DIAGNOSED (a typed HealthError naming the check, part and iteration),
never a silent wrong answer — in particular ``run_until`` on a
NaN-seeded state must keep iterating (and the watchdog variant must
raise), where the old ``res > tol`` predicate exited reporting
convergence on garbage.  Watchdog-on loops must also be bit-identical
to watchdog-off on healthy runs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lux_tpu import health as hw
from lux_tpu import resilience, telemetry
from lux_tpu.apps import pagerank, sssp
from lux_tpu.convert import uniform_random_edges
from lux_tpu.engine.program import PullProgram
from lux_tpu.engine.pull import PullEngine
from lux_tpu.engine.push import PushEngine
from lux_tpu.graph import Graph, ShardedGraph
from lux_tpu.parallel.mesh import make_mesh

NOSLEEP = dict(sleep=lambda s: None)


def small_graph(nv=100, ne=700, seed=61):
    src, dst = uniform_random_edges(nv, ne, seed=seed)
    return Graph.from_edges(src, dst, nv)


def synthetic_program(apply_fn, init_val=1.0):
    """A pull program whose next state is a pure function of the old
    one — lets tests drive the residual trajectory exactly."""
    def edge_value(src_val, dst_val, weight):
        return src_val

    def init(sg):
        return np.full((sg.num_parts, sg.vpad), init_val, np.float32)

    return PullProgram(reduce="sum", edge_value=edge_value,
                       apply=lambda old, red, ctx: apply_fn(old),
                       init=init, name="synthetic")


# -- run_until can never report convergence on NaN ---------------------

def test_run_until_nan_residual_is_not_convergence():
    g = small_graph()
    eng = pagerank.build_engine(g, num_parts=2)
    bad = np.array(jax.device_get(eng.init_state()))
    bad[0, 0] = np.nan
    state, it, res = eng.run_until(eng.place(bad), 1e-3, max_iters=5)
    # the old (res > tol) predicate exited at it=1 claiming
    # convergence; the non-finite-safe predicate runs to the cap
    assert int(jax.device_get(it)) == 5
    assert np.isnan(float(jax.device_get(res)))


def test_run_until_health_raises_on_nan_seed():
    g = small_graph()
    eng = pagerank.build_engine(g, num_parts=2)
    bad = np.array(jax.device_get(eng.init_state()))
    bad[1, 0] = np.nan
    _s, it, _res, _rb, _cb, _rp, _cp, h = eng.run_until_health(
        eng.place(bad), 1e-3, max_iters=50)
    assert int(jax.device_get(it)) == 1      # exits AT the trip
    with pytest.raises(hw.HealthError) as ei:
        hw.ensure_ok(h, engine="pull", where="test")
    e = ei.value
    assert "nonfinite_state" in e.checks
    assert "nonfinite_residual" in e.checks
    # the NaN spreads along edges within the first iteration, so the
    # named part is the FIRST with damage, not necessarily the seeded
    assert e.iteration == 0 and e.part >= 0 and e.engine == "pull"
    assert e.count > 0


def test_healthy_run_until_matches_plain():
    g = small_graph()
    eng = pagerank.build_engine(g, num_parts=2)
    s1, it1, res1 = eng.run_until(eng.init_state(), 1e-7,
                                  max_iters=200)
    s2, it2, res2, _rb, _cb, _rp, _cp, h = eng.run_until_health(
        eng.init_state(), 1e-7, max_iters=200)
    assert not hw.ensure_ok(h, engine="pull")["tripped"]
    assert int(jax.device_get(it1)) == int(jax.device_get(it2))
    np.testing.assert_array_equal(np.asarray(jax.device_get(s1)),
                                  np.asarray(jax.device_get(s2)))


# -- pull: parity + each check trips deterministically ----------------

@pytest.mark.parametrize("np_parts,mesh_n", [(2, 0), (8, 8)])
def test_run_health_bitwise_matches_run(np_parts, mesh_n):
    g = small_graph(nv=180, ne=1400, seed=7)
    mesh = make_mesh(mesh_n) if mesh_n else None
    eng = pagerank.build_engine(g, num_parts=np_parts, mesh=mesh)
    want = eng.unpad(eng.run(eng.init_state(), 10))
    s, it, rb, cb, rbp, cbp, h = eng.run_health(eng.init_state(), 10)
    d = hw.ensure_ok(h, engine="pull")
    assert d == {"engine": "pull", "tripped": False, "flags": []}
    assert int(jax.device_get(it)) == 10
    np.testing.assert_array_equal(eng.unpad(s), want)
    # counters identical to the stats variant's
    s2, rb2, cb2, _rbp2, _cbp2 = eng.run_stats(eng.init_state(), 10)
    np.testing.assert_array_equal(np.asarray(jax.device_get(rb)),
                                  np.asarray(jax.device_get(rb2)))
    np.testing.assert_array_equal(np.asarray(jax.device_get(cb)),
                                  np.asarray(jax.device_get(cb2)))


def test_divergence_trips_after_window():
    """State doubles every iteration: residuals strictly increase and
    blow past the growth bound — DIVERGENCE trips the moment the
    trailing window fills, long before Inf/NaN."""
    g = small_graph(nv=40, ne=200, seed=3)
    sg = ShardedGraph.build(g, 2)
    eng = PullEngine(sg, synthetic_program(lambda old: old * 2,
                                           init_val=1e-3))
    _s, it, _rb, _cb, _rp, _cp, h = eng.run_health(eng.init_state(), 100)
    assert int(jax.device_get(it)) == hw.WINDOW
    with pytest.raises(hw.HealthError) as ei:
        hw.ensure_ok(h, engine="pull", where="test")
    assert ei.value.checks == ["divergence"]
    assert ei.value.iteration == hw.WINDOW - 1


def test_oscillation_trips_after_window():
    """A 4-cycle (0 -> 5 -> 2 -> -3 -> 0) makes the residual series
    5, 3, 5, 3, ...: strictly alternating differences with no net
    decrease — the limit cycle no tolerance will ever end."""
    def cycle(old):
        return jnp.where(old == 0., 5.,
                         jnp.where(old == 5., 2.,
                                   jnp.where(old == 2., -3., 0.)))

    g = small_graph(nv=40, ne=200, seed=3)
    sg = ShardedGraph.build(g, 2)
    eng = PullEngine(sg, synthetic_program(cycle, init_val=0.0))
    _s, it, _rb, _cb, _rp, _cp, h = eng.run_health(eng.init_state(), 100)
    assert int(jax.device_get(it)) == hw.WINDOW
    with pytest.raises(hw.HealthError) as ei:
        hw.ensure_ok(h, engine="pull", where="test")
    assert ei.value.checks == ["oscillation"]


def test_converging_run_never_false_positives():
    """A legitimately converging run (pagerank: residual strictly
    DECREASES) must stay clean far past the window."""
    g = small_graph()
    eng = pagerank.build_engine(g, num_parts=2, health=True)
    s, it, _rb, _cb, _rp, _cp, h = eng.run_health(eng.init_state(),
                                        4 * hw.WINDOW)
    assert not hw.ensure_ok(h, engine="pull")["tripped"]
    assert int(jax.device_get(it)) == 4 * hw.WINDOW


# -- push: parity + NaN labels + frontier stall ------------------------

@pytest.mark.parametrize("np_parts,mesh_n", [(2, 0), (8, 8)])
def test_converge_health_matches_converge(np_parts, mesh_n):
    g = small_graph(nv=180, ne=1400, seed=7)
    mesh = make_mesh(mesh_n) if mesh_n else None
    eng = sssp.build_engine(g, start_vertex=1, num_parts=np_parts,
                            mesh=mesh)
    l1, a1, it1 = eng.converge(*eng.init_state())
    l2, a2, it2, fsz, fed, _fp, _ep, h = eng.converge_health(*eng.init_state())
    assert not hw.ensure_ok(h, engine="push")["tripped"]
    assert int(jax.device_get(it1)) == int(jax.device_get(it2))
    np.testing.assert_array_equal(np.asarray(jax.device_get(l1)),
                                  np.asarray(jax.device_get(l2)))
    # counters identical to the stats variant's
    _l, _a, _it, fsz2, fed2, _fp2, _ep2 = eng.converge_stats(*eng.init_state())
    np.testing.assert_array_equal(np.asarray(jax.device_get(fsz)),
                                  np.asarray(jax.device_get(fsz2)))
    np.testing.assert_array_equal(np.asarray(jax.device_get(fed)),
                                  np.asarray(jax.device_get(fed2)))


def test_push_nan_labels_trip():
    src, dst, w = uniform_random_edges(100, 800, seed=5, weighted=True)
    g = Graph.from_edges(src, dst, 100, weights=w)
    eng = sssp.build_engine(g, start_vertex=0, num_parts=2,
                            weighted=True, health=True)
    label, active = eng.init_state()
    lb = np.array(jax.device_get(label))
    lb[0, 0] = np.nan
    label, active = eng.place(lb, np.array(jax.device_get(active)))
    _l, _a, _it, _f, _e, _fp, _ep, h = eng.converge_health(label, active)
    with pytest.raises(hw.HealthError) as ei:
        hw.ensure_ok(h, engine="push", where="test")
    assert ei.value.checks == ["nonfinite_state"]
    assert ei.value.iteration == 0 and ei.value.part == 0


def test_push_inf_sentinel_never_trips():
    """+Inf is the legitimate unreached sentinel for weighted sssp —
    a converged run full of them must stay clean."""
    src, dst, w = uniform_random_edges(100, 400, seed=9, weighted=True)
    # vertices 100..119 have no edges at all: provably unreachable
    g = Graph.from_edges(src, dst, 120, weights=w)
    eng = sssp.build_engine(g, start_vertex=0, num_parts=2,
                            weighted=True, health=True)
    label, _a, _it, _f, _e, _fp, _ep, h = eng.converge_health(*eng.init_state())
    assert not hw.ensure_ok(h, engine="push")["tripped"]
    assert np.isinf(np.asarray(jax.device_get(label))).any()


def test_frontier_stall_trips_and_exits_loop():
    """Truncation livelock: an edge budget below the start hub's
    out-degree makes the sparse queue's processed prefix stick at 0
    forever.  The plain converge spins to max_iters; the watchdog
    variant EXITS at STALL_N consecutive no-progress iterations with
    the frontier_stall diagnosis."""
    src, dst = uniform_random_edges(200, 1500, seed=62)
    g = Graph.from_edges(src, dst, 200)
    sg = ShardedGraph.build(g, 2)
    prog = sssp.make_program(0)
    eng = PushEngine(sg, prog, edge_budget=1, sparse_threshold=1,
                     health=True)
    label, active = eng.init_state()
    l0, a0, it0 = eng.converge(*eng.init_state(), max_iters=60)
    assert int(jax.device_get(it0)) == 60          # livelocked
    assert int(jax.device_get(jnp.sum(a0))) > 0
    _l, _a, it, _f, _e, _fp, _ep, h = eng.converge_health(label, active,
                                                max_iters=2000)
    assert int(jax.device_get(it)) < 60            # exited early
    with pytest.raises(hw.HealthError) as ei:
        hw.ensure_ok(h, engine="push", where="test")
    assert ei.value.checks == ["frontier_stall"]


# -- wiring: classification, supervisor, telemetry, eng.run ------------

def test_health_error_classifies_fatal():
    e = hw.HealthError("x", checks=["divergence"], iteration=9)
    assert resilience.classify(e) == resilience.FATAL


def test_supervised_run_trips_before_checkpointing_garbage(tmp_path):
    """The watchdog raises at the SEGMENT boundary, before the
    checkpoint save: a diverging run dies fatal-with-diagnosis on the
    first attempt (no retry — the corruption is in the state) and the
    checkpoint on disk stays at the last healthy segment."""
    from lux_tpu import checkpoint as ckpt

    g = small_graph(nv=40, ne=200, seed=3)
    sg = ShardedGraph.build(g, 2)
    eng = PullEngine(sg, synthetic_program(lambda old: old * 2,
                                           init_val=1e-3),
                     health=True)
    path = str(tmp_path / "div.npz")
    ev = telemetry.EventLog()
    with telemetry.use(events=ev):
        with pytest.raises(hw.HealthError):
            resilience.supervised_run(
                eng, 40, path, segment=4,
                policy=resilience.RetryPolicy(retries=3, **NOSLEEP))
    assert ev.counts().get("health_trip") == 1
    assert ev.counts().get("failure") == 1     # fatal: exactly one
    trip = [e for e in ev.events if e["kind"] == "health_trip"][0]
    assert trip["flags"] == ["divergence"]
    # only the first (healthy, iterations 0-3) segment was saved; the
    # residual window is THREADED across segments (segment=4 is
    # shorter than the window), so divergence still trips the
    # iteration the window fills — globally numbered via the tick
    _leaves, meta = ckpt.load(path)
    assert meta["iter"] == 4
    assert trip["iteration"] == hw.WINDOW - 1


def test_engine_run_uses_watchdog_when_enabled():
    g = small_graph(nv=40, ne=200, seed=3)
    sg = ShardedGraph.build(g, 2)
    eng = PullEngine(sg, synthetic_program(lambda old: old * 2,
                                           init_val=1e-3),
                     health=True)
    with pytest.raises(hw.HealthError):
        eng.run(eng.init_state(), 100)
    # push side: eng.run on a livelocked engine diagnoses instead of
    # spinning (frontier_stall), via the same run() entry point
    src, dst = uniform_random_edges(200, 1500, seed=62)
    g2 = Graph.from_edges(src, dst, 200)
    sg2 = ShardedGraph.build(g2, 2)
    e2 = PushEngine(sg2, sssp.make_program(0), edge_budget=1,
                    sparse_threshold=1, health=True)
    with pytest.raises(hw.HealthError):
        e2.run(max_iters=2000)


def test_timed_helpers_emit_health_digest():
    from lux_tpu.timing import timed_converge, timed_fused_run

    g = small_graph()
    eng = pagerank.build_engine(g, num_parts=2, health=True)
    ev = telemetry.EventLog()
    with telemetry.use(events=ev):
        _state, elapsed = timed_fused_run(eng, 5, repeats=2)
    assert len(elapsed) == 2
    hs = [e for e in ev.events if e["kind"] == "health"]
    assert len(hs) == 1 and hs[0]["tripped"] is False \
        and hs[0]["engine"] == "pull" and hs[0]["iters"] == 5

    e2 = sssp.build_engine(g, start_vertex=0, num_parts=2, health=True)
    ev2 = telemetry.EventLog()
    with telemetry.use(events=ev2):
        _labels, iters, _el = timed_converge(e2, repeats=1)
    hs = [e for e in ev2.events if e["kind"] == "health"]
    assert len(hs) == 1 and hs[0]["tripped"] is False \
        and hs[0]["engine"] == "push" and hs[0]["iters"] == iters


def test_word_decode_roundtrip():
    h = np.array([hw.DIVERGENCE | hw.NONFINITE_RESIDUAL, 12, 3, 7,
                  np.float32(2.5).view(np.int32), 0], np.int32)
    d = hw.digest(h, engine="pull", base_iter=100)
    assert d["tripped"] and d["iteration"] == 112 and d["part"] == 3
    assert d["flags"] == ["nonfinite_residual", "divergence"]
    assert d["residual"] == 2.5 and d["count"] == 7
