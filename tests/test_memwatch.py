"""lux_tpu/memwatch.py: the round-22 memory observatory.

Acceptance (ISSUE 17): the unified per-replica byte ledger is proved
against an independent NumPy oracle bitwise; a synthetic overdrift
raises the typed MemoryDriftError; a byte-budgeted FleetServer sheds
with the typed ``memory`` reason BEFORE any allocation failure, with
the forecaster's mem_pressure preceding the shed in the audited event
trail and every admitted answer oracle-correct; events_summary FAILS
a mem_pressure/OOM trail that carries no preceding occupancy sample;
`python -m lux_tpu.memwatch` (the repo-wide acceptance command) runs
green on CPU, tier-1-gated like `python -m lux_tpu.comms`; and the
round-22 serve-chaos regression (a kill plan armed on a replica the
routing loop starves never fires) stays fixed via
fleet.routing_target.
"""

import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from lux_tpu import audit, faults, fleet, livegraph, memwatch, \
    metrics, resilience, serve, telemetry
from lux_tpu.apps import sssp as sssp_app
from lux_tpu.convert import uniform_random_edges
from lux_tpu.graph import Graph

REPO = Path(__file__).resolve().parent.parent
SUMMARY = REPO / "scripts" / "events_summary.py"

NV, NE, SEED = 256, 2048, 7


@pytest.fixture(scope="module")
def g():
    src, dst = uniform_random_edges(NV, NE, seed=SEED)
    return Graph.from_edges(src, dst, NV)


def fast_retry():
    return resilience.RetryPolicy(retries=3, backoff_s=0.01,
                                  max_backoff_s=0.05, jitter_seed=0)


def make_fleet(g, tmp_path, **kw):
    kw.setdefault("replicas", 2)
    kw.setdefault("batch", 2)
    kw.setdefault("num_parts", 2)
    kw.setdefault("retry", fast_retry())
    kw.setdefault("board_path", str(tmp_path / "board"))
    return fleet.FleetServer(g, **kw)


# ---------------------------------------------------------------------
# pillar 2: the unified ledger vs an independent NumPy oracle


class TestLedgerOracle:
    def test_engine_ledger_bitwise_oracle(self, g):
        """Every term of the engine ledger re-derived independently
        from memory_report / program attributes matches bitwise, the
        total is the bitwise sum, and the argument-side quantity
        equals audit.priced_argument_bytes — the one number the
        compile-time drift check prices."""
        eng = sssp_app.build_engine(g, num_parts=2)
        led = memwatch.MemoryLedger.for_engine(eng, "oracle")
        P = eng.sg.num_parts
        rep = eng.sg.memory_report(**audit.report_kwargs(eng))
        # the decomposition identity memory_report promises
        assert rep["total_bytes"] == P * sum(
            rep["terms_per_part"].values())
        want = {f"graph_{k}": P * int(v)
                for k, v in rep["terms_per_part"].items() if v}
        sb = getattr(eng.program, "state_bytes", None)
        if sb:
            want["program_state"] = P * eng.sg.vpad * (sb - 4)
        xa = getattr(eng.program, "extra_arrays", None)
        if xa is not None:
            want["program_extra"] = sum(
                np.asarray(v).nbytes for v in xa(eng.sg).values())
        assert led.terms == want
        assert led.total_bytes == sum(want.values())
        assert led.argument_bytes() \
            == audit.priced_argument_bytes(eng)

    def test_consumer_terms_bitwise_oracle(self, g, tmp_path):
        """The dynamic consumer terms — AnswerCache bytes, live
        delta/history/multiset/WAL, checkpoint staging — each
        re-derived from the raw objects, bitwise."""
        cache = serve.AnswerCache(max_bytes=1 << 20)
        a1 = np.arange(NV, dtype=np.float32)
        a2 = np.arange(NV, dtype=np.int32)
        cache.put("sssp", serve.Request(qid=0, kind="sssp", source=3),
                  a1, 4, 0, now=0.0)
        cache.put("components",
                  serve.Request(qid=1, kind="components", source=5),
                  a2, 4, 0, now=0.0)
        lv = livegraph.LiveGraph(g, capacity=64,
                                 wal_path=str(tmp_path / "w.wal"))
        try:
            lv.append_edges([1, 2, 3], [4, 5, 6])
            lv.delete_edges([1], [4])      # builds the multiset
            memwatch.note_staging(12345)
            terms = memwatch.consumer_terms(cache=cache, live=lv)
            assert terms["cache"] == cache.bytes
            assert terms["live_delta"] == (
                lv.d_src.nbytes + lv.d_dst.nbytes + lv.d_w.nbytes
                + lv.d_kind.nbytes + lv.d_epoch.nbytes)
            assert terms["live_history"] == \
                len(lv._history) * livegraph.HISTORY_ENTRY_BYTES
            assert terms["live_multiset"] == \
                len(lv._edge_counts) * livegraph.MULTISET_ENTRY_BYTES
            assert terms["live_multiset"] > 0
            assert terms["live_wal"] == lv._wal.buffer_bytes()
            assert terms["live_wal"] > 0
            assert terms["checkpoint_staging"] == 12345
            led = memwatch.MemoryLedger(terms, "consumers")
            assert led.total_bytes == sum(terms.values())
        finally:
            memwatch.note_staging(0)
            lv.close()

    def test_cache_byte_ledger_tracks_put_and_evict(self):
        """The AnswerCache's internal byte ledger moves exactly with
        put/evict and the registry gauge mirrors it."""
        cache = serve.AnswerCache(max_bytes=4096)
        reg = metrics.Registry()
        cache.set_metrics(reg)
        a = np.zeros(256, np.float32)      # 1024 B payload

        def put(source):
            cache.put("sssp",
                      serve.Request(qid=source, kind="sssp",
                                    source=source),
                      a, 4, 0, now=0.0)

        put(1)
        assert cache.bytes == a.nbytes
        put(2)
        assert cache.bytes == 2 * a.nbytes
        # overflow evicts until under budget — the ledger never lies
        for s in range(3, 10):
            put(s)
        assert cache.bytes <= 4096
        g_ = reg.gauge("serve_cache_bytes")
        assert g_.value == cache.bytes


# ---------------------------------------------------------------------
# pillar 2: drift verdicts


class TestDrift:
    def test_overdrift_raises_typed_error(self):
        led = memwatch.MemoryLedger({"graph_edge": 1_000_000}, "syn")
        with pytest.raises(memwatch.MemoryDriftError) as ei:
            memwatch.check_drift(4_000_000, led, grade="modeled",
                                 where="syn", mode="error")
        e = ei.value
        assert e.check == "mem-drift"
        assert e.measured == 4_000_000
        assert e.ledger == 1_000_000
        assert e.ratio == pytest.approx(4.0)
        assert resilience.classify(e) is not None

    def test_underdrift_raises_too(self):
        """A measured peak far UNDER the ledger is the same lie in
        the other direction (the ledger prices ghosts)."""
        led = memwatch.MemoryLedger({"graph_edge": 4_000_000}, "syn")
        with pytest.raises(memwatch.MemoryDriftError):
            memwatch.check_drift(1_000_000, led, grade="modeled",
                                 where="syn", mode="error")

    def test_within_tolerance_is_clean(self):
        led = memwatch.MemoryLedger({"graph_edge": 1_000_000}, "syn")
        v = memwatch.check_drift(1_200_000, led, grade="measured",
                                 where="syn", mode="error")
        assert v["errors"] == 0
        assert v["grade"] == "measured"

    def test_warn_mode_warns_instead(self):
        led = memwatch.MemoryLedger({"graph_edge": 1_000_000}, "syn")
        with pytest.warns(UserWarning, match="unified ledger"):
            v = memwatch.check_drift(4_000_000, led, grade="modeled",
                                     where="syn", mode="warn")
        assert v["errors"] == 1

    def test_engine_verdict_cpu_is_accounted(self):
        """On CPU the AOT memory_analysis path produces a clean
        modeled verdict (or an explicitly-skipped digest — never a
        silent number) for a drift-checkable matrix config.  Tiny
        shapes are padding-dominated and NOT drift-checkable: only
        ledger-flag configs carry the guarantee (audit.check_ledger's
        rule; `python -m lux_tpu.memwatch` sweeps them all)."""
        label, build, _ = next(
            c for c in audit.matrix_configs() if c[2])
        v = memwatch.engine_verdict(build(), mode="error",
                                    where=label)
        assert v["grade"] == "modeled"
        assert v["errors"] == 0
        assert "skipped" not in v or v["warnings"] >= 1


# ---------------------------------------------------------------------
# pillar 3: the forecaster (pure policy, fake clock)


class TestForecaster:
    def test_ramp_fires_time_to_full_before_full(self):
        f = memwatch.MemoryForecaster(1000, horizon_s=5.0)
        d = f.record(100, t=0.0)
        assert d["action"] == "ok" and not d["fired"]
        d = f.record(200, t=1.0)       # 100 B/s, 800 B head: ttf 8 s
        assert d["action"] == "ok" and d["reason"] == "headroom"
        d = f.record(400, t=2.0)       # 150 B/s, 600 B head: ttf 4 s
        assert d["action"] == "pressure"
        assert d["reason"] == "time_to_full"
        assert d["fired"] and f.pressures == 1
        assert d["time_to_full_s"] == pytest.approx(4.0)
        assert d["burn"] > 1.0         # budget gone within a horizon
        # still pressed: no re-fire (one event per crossing)
        d = f.record(600, t=3.0)
        assert d["action"] == "pressure" and not d["fired"]
        assert f.pressures == 1

    def test_over_budget_reason_and_hysteresis(self):
        f = memwatch.MemoryForecaster(1000, horizon_s=1.0)
        f.record(500, t=0.0)
        d = f.record(1200, t=1.0)
        assert d["action"] == "pressure"
        assert d["reason"] == "over_budget"
        assert d["time_to_full_s"] == 0.0
        assert d["fired"]
        # recovery re-arms the latch; a second crossing fires again
        d = f.record(100, t=2.0)
        assert d["action"] == "ok" and not f.pressed
        d = f.record(1100, t=3.0)
        assert d["fired"] and f.pressures == 2

    def test_flat_trail_never_fires(self):
        f = memwatch.MemoryForecaster(1000, horizon_s=5.0)
        for i in range(6):
            d = f.record(400, t=float(i))
        assert d["action"] == "ok"
        assert d["time_to_full_s"] is None     # inf: flat
        assert f.pressures == 0


# ---------------------------------------------------------------------
# pillar 3: memory-aware admission on the fleet


class TestMemoryAdmission:
    def test_tiny_budget_sheds_typed(self, g, tmp_path):
        flt = make_fleet(g, tmp_path, mem_budget_bytes=1,
                         mem_clock=lambda: 0.0)
        flt.warm(["sssp"])
        with pytest.raises(fleet.AdmissionError) as ei:
            flt.submit("sssp", source=3)
        e = ei.value
        assert e.reason == fleet.SHED_MEMORY
        assert e.projected_bytes is not None and e.projected_bytes > 1
        assert e.budget_bytes == 1
        assert "projected" in str(e) and "budget" in str(e)

    def test_generous_budget_admits_and_serves(self, g, tmp_path):
        flt = make_fleet(g, tmp_path, mem_budget_bytes=1 << 40)
        flt.warm(["sssp"])
        qid = flt.submit("sssp", source=3)
        rs = flt.run()
        assert qid in {r.qid for r in rs}
        assert serve._check_answers(g, rs) == 0

    def test_cold_replica_is_not_priced(self, g, tmp_path):
        """Before warm no runner exists: cold admission stays
        optimistic (exactly like _projected_wait) — the budget only
        bites once the target replica has an engine to price."""
        flt = make_fleet(g, tmp_path, mem_budget_bytes=1)
        assert flt._projected_bytes("sssp") is None

    def test_pressure_precedes_shed_in_audited_trail(self, g,
                                                     tmp_path):
        """THE round-22 chaos-leg acceptance: a budgeted fleet under
        admission load with a growing consumer (the shared
        AnswerCache) emits the forecaster's mem_pressure BEFORE the
        first typed memory shed, the event trail passes the
        events_summary order audit, and every ADMITTED answer is
        oracle-correct."""
        # probe run: measure the projected admission bytes and the
        # per-retirement cache growth on an identical throwaway tier
        probe = make_fleet(g, tmp_path / "probe", cache=True)
        probe.warm(["sssp"])
        p0 = probe._projected_bytes("sssp")
        assert p0 is not None
        b0 = probe.cache.bytes
        probe.submit("sssp", source=11)
        probe.run()
        grow = probe.cache.bytes - b0
        assert grow > 0
        # budget: admits until the cache has grown ~3 retirements'
        # worth, then the projection crosses and admission sheds.
        # horizon huge: the first positive burn rate the boundary
        # sampler sees trips time_to_full immediately — the pressure
        # signal must land before the shed can.
        budget = p0 + 3 * grow
        ev = telemetry.EventLog(str(tmp_path / "ev.jsonl"))
        with telemetry.use(events=ev):
            flt = make_fleet(g, tmp_path, cache=True,
                             mem_budget_bytes=budget,
                             mem_horizon_s=1e9)
            flt.warm(["sssp"])
            admitted, shed = 0, 0
            for s in range(1, 25):
                try:
                    flt.submit("sssp", source=s)
                    admitted += 1
                except fleet.AdmissionError as e:
                    assert e.reason == fleet.SHED_MEMORY
                    shed += 1
                rs = flt.run()
                assert serve._check_answers(g, rs) == 0
        ev.close()
        assert shed >= 1, "budget never bit — test is vacuous"
        assert admitted >= 1, "nothing admitted — budget too tight"
        kinds = [json.loads(ln)["kind"]
                 for ln in Path(ev.path).read_text().splitlines()]
        events = [json.loads(ln)
                  for ln in Path(ev.path).read_text().splitlines()]
        assert "mem_sample" in kinds or "mem_watermark" in kinds
        assert "mem_pressure" in kinds, (
            "forecaster never fired despite the ramp to the budget")
        first_pressure = kinds.index("mem_pressure")
        first_mem_shed = next(
            i for i, e in enumerate(events)
            if e["kind"] == "query_shed"
            and e.get("reason") == fleet.SHED_MEMORY)
        assert first_pressure < first_mem_shed, (
            "forecaster fired AFTER admission already shed — the "
            "early-warning contract is inverted")
        shed_ev = events[first_mem_shed]
        assert shed_ev.get("projected_bytes", 0) > budget
        assert shed_ev.get("budget_bytes") == budget
        # the order-sensitive events_summary audit accepts the trail
        r = subprocess.run(
            [sys.executable, str(SUMMARY), ev.path],
            capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        assert "PRESSURE signal" in r.stdout


# ---------------------------------------------------------------------
# the events_summary order audit (negative side)


class TestEventsAudit:
    def _run(self, tmp_path, events):
        evp = tmp_path / "ev.jsonl"
        evp.write_text("".join(json.dumps(e) + "\n" for e in events))
        return subprocess.run(
            [sys.executable, str(SUMMARY), str(evp)],
            capture_output=True, text=True)

    def test_pressure_without_samples_fails(self, tmp_path):
        r = self._run(tmp_path, [
            {"t": 1.0, "tm": 1.0, "kind": "mem_pressure",
             "reason": "time_to_full", "live_bytes": 900,
             "budget_bytes": 1000, "burn": 2.0}])
        assert r.returncode == 1
        assert "no preceding mem_sample" in r.stderr

    def test_pressure_after_sample_passes(self, tmp_path):
        r = self._run(tmp_path, [
            {"t": 1.0, "tm": 1.0, "kind": "mem_sample",
             "grade": "modeled", "live_bytes": 500,
             "peak_bytes": 500},
            {"t": 2.0, "tm": 2.0, "kind": "mem_pressure",
             "reason": "time_to_full", "live_bytes": 900,
             "budget_bytes": 1000, "burn": 2.0}])
        assert r.returncode == 0, r.stderr
        assert "PRESSURE signal" in r.stdout

    def test_pressure_missing_economics_fails(self, tmp_path):
        r = self._run(tmp_path, [
            {"t": 1.0, "tm": 1.0, "kind": "mem_sample",
             "grade": "modeled", "live_bytes": 500,
             "peak_bytes": 500},
            {"t": 2.0, "tm": 2.0, "kind": "mem_pressure",
             "reason": "time_to_full"}])
        assert r.returncode == 1
        assert "cannot justify itself" in r.stderr

    def test_memory_shed_without_samples_fails(self, tmp_path):
        r = self._run(tmp_path, [
            {"t": 1.0, "tm": 1.0, "kind": "query_shed", "qid": 7,
             "query_kind": "sssp", "reason": "memory",
             "projected_bytes": 999, "budget_bytes": 100}])
        assert r.returncode == 1
        assert "never observed" in r.stderr


# ---------------------------------------------------------------------
# the round-22 serve-chaos regression (satellite a)


class TestChaosRoutingRegression:
    def test_kill_armed_on_routing_target_fires(self, g, tmp_path):
        """Routing is a positive-feedback loop (drain -> fresh beat
        -> picked again): a kill plan armed on the replica
        fleet.routing_target names MUST fire and fail over.  The
        seed armed a fixed index and silently measured a fault-free
        run whenever beat timing inside warm() handed the load to
        the other replica."""
        flt = make_fleet(g, tmp_path)
        flt.warm(["sssp"])
        victim = flt.routing_target("sssp")
        assert victim in flt.replica_names
        plan = faults.ReplicaKillPlan({victim: 1})
        flt.set_fault(plan)
        for s in range(1, 9):
            flt.submit("sssp", source=s)
        rs = flt.run()
        assert plan.fired, (
            "kill plan armed on the routing target never fired — "
            "the round-22 serve-chaos regression is back")
        assert flt.failovers >= 1
        assert len(rs) == 8
        assert serve._check_answers(g, rs) == 0


# ---------------------------------------------------------------------
# the weighted serve-live bench leg (satellite b)


class TestServeLiveBench:
    def test_weighted_line_through_check_bench(self, tmp_path):
        """bench.py -config serve-live produces a WEIGHTED line —
        reweights >= 1 so the headline finally measures the round-21
        reweight leg — carrying the round-22 mem digest, and
        scripts/check_bench.py ACCEPTS it (weighted schema + mem
        field included)."""
        import argparse

        import bench

        args = argparse.Namespace(
            scale=8, ef=8, ni=20, np=2, pair=0, min_fill=None,
            min_fill_dot=None, repeats=1, verbose=False,
            health=False, audit="warn", serve_queries=24,
            serve_batch=2, serve_kinds="sssp,components,pagerank",
            slo_ms="sssp=30000,components=30000,pagerank=30000",
            rates="150", batch="1", shape="rmat", reorder="none",
            serve_replicas=2, kill_boundary=1, delta_capacity=24)
        ev = telemetry.EventLog()
        with telemetry.use(events=ev):
            idx0 = len(ev.events)
            import bench as _b
            name, samples, extra, _rerun = _b.run_config(
                "serve-live@150", args)
            tel = _b.config_telemetry(ev, idx0, None)
        assert name == "serve_live_rmat8"
        assert extra["weighted"] is True
        assert extra["reweights"] >= 1
        assert extra["deletions"] >= 1 and extra["reseeds"] >= 1
        mem = extra["mem"]
        assert mem["errors"] == 0
        assert mem["grade"] in ("measured", "modeled")
        assert mem["consumer_bytes"] > 0    # cache/live/WAL priced
        value = round(float(np.median(samples)), 4)
        line = {"metric": f"{name}_qps_per_chip", "value": value,
                "unit": "qps", "vs_baseline": value,
                "samples": [round(s, 4) for s in samples],
                "attempts": len(samples), "discarded": [],
                "telemetry": tel, **extra}
        p = tmp_path / "bench.jsonl"
        p.write_text(json.dumps(line) + "\n")
        r = subprocess.run(
            [sys.executable,
             str(REPO / "scripts" / "check_bench.py"),
             "-legacy-ok", str(p)],
            capture_output=True, text=True)
        assert r.returncode == 0, r.stderr


# ---------------------------------------------------------------------
# the repo-wide acceptance command (tier-1 gate, like lux_tpu.comms)


class TestAcceptanceCommand:
    def test_memwatch_cli_green(self):
        r = subprocess.run(
            [sys.executable, "-m", "lux_tpu.memwatch"],
            capture_output=True, text=True, cwd=str(REPO),
            timeout=900)
        assert r.returncode == 0, (r.stdout or "") + (r.stderr or "")
        assert "memwatch: all configs green" in r.stdout
        assert "DRIFT" not in r.stdout
