"""Device audits (lux_tpu.device_check) against the NumPy oracles
(lux_tpu.check) — count-exact agreement, clean and corrupted states,
single-device and 8-device mesh."""

import numpy as np
import pytest

from lux_tpu import check, device_check
from lux_tpu.convert import rmat_graph
from lux_tpu.graph import Graph, ShardedGraph


@pytest.fixture(scope="module")
def graph():
    return rmat_graph(scale=9, edge_factor=8, seed=11)


@pytest.fixture(scope="module")
def wgraph():
    g = rmat_graph(scale=9, edge_factor=8, seed=12)
    rng = np.random.default_rng(0)
    g.weights = rng.integers(1, 6, size=g.ne).astype(np.int32)
    return g


def mesh8():
    from lux_tpu.parallel.mesh import make_mesh
    return make_mesh(8)


@pytest.mark.parametrize("np_mesh", [(1, False), (4, False), (8, True)])
def test_sssp_counts_match_oracle(graph, np_mesh):
    from lux_tpu.apps import sssp
    num_parts, use_mesh = np_mesh
    mesh = mesh8() if use_mesh else None
    dist = sssp.reference_sssp(graph, 0).astype(np.int32)
    sg = ShardedGraph.build(graph, num_parts)

    res = device_check.check_sssp_device(sg, dist, mesh=mesh)
    assert res.ok and res.checked == graph.ne
    assert res.per_part is not None and len(res.per_part) == num_parts

    # corrupt: claim a shorter-than-possible distance at some vertices
    bad = dist.copy()
    bad[::7] = 0
    bad[0] = dist[0]
    want = check.check_sssp(graph, bad).violations
    got = device_check.check_sssp_device(sg, bad, mesh=mesh)
    assert got.violations == want and want > 0


def test_sssp_weighted_counts(wgraph):
    from lux_tpu.apps import sssp
    dist = sssp.reference_sssp(wgraph, 0, weighted=True).astype(
        np.float32)
    sg = ShardedGraph.build(wgraph, 2)
    res = device_check.check_sssp_device(sg, dist, weighted=True)
    assert res.ok
    bad = dist.copy()
    bad[::5] = 0.0
    want = check.check_sssp(wgraph, bad, weighted=True).violations
    got = device_check.check_sssp_device(sg, bad, weighted=True)
    assert got.violations == want and want > 0


@pytest.mark.parametrize("num_parts", [1, 4])
def test_components_counts_match_oracle(graph, num_parts):
    from lux_tpu.apps import components
    s, d = components.symmetrize(*graph.edge_arrays())
    g = Graph.from_edges(s, d, graph.nv)
    labels, _ = components.run(g)
    sg = ShardedGraph.build(g, num_parts)
    assert device_check.check_components_device(sg, labels).ok

    bad = labels.copy().astype(np.int32)
    bad[::11] = -1
    want = check.check_components(g, bad).violations
    got = device_check.check_components_device(sg, bad)
    assert got.violations == want and want > 0


def test_pagerank_residual_matches_oracle(graph):
    from lux_tpu.apps import pagerank
    ranks = pagerank.run(graph, 30)
    sg = ShardedGraph.build(graph, 2)
    # converged-ish at loose tol: both report zero
    assert device_check.check_pagerank_device(sg, ranks, tol=1e-3).ok
    assert check.check_pagerank(graph, ranks, tol=1e-3).ok
    # strong corruption: identical counts despite f32 vs f64 residuals
    bad = np.asarray(ranks, np.float32).copy()
    bad[::13] += 1.0
    want = check.check_pagerank(graph, bad, tol=1e-3).violations
    got = device_check.check_pagerank_device(sg, bad, tol=1e-3)
    assert got.violations == want and want > 0
    assert got.checked == graph.nv


def test_colfilter_rmse_matches_oracle(wgraph):
    from lux_tpu.apps import colfilter
    g = wgraph
    eng = colfilter.build_engine(g, num_parts=2)
    state = eng.run(eng.init_state(), 3)
    out = eng.unpad(state)
    res = device_check.check_colfilter_device(eng.sg, out)
    host = check.check_colfilter(g, out)
    assert res.ok == host.ok

    # garbage factors must FAIL both
    bad = np.full_like(out, 10.0)
    assert not device_check.check_colfilter_device(eng.sg, bad).ok
    assert not check.check_colfilter(g, bad).ok


def test_device_check_accepts_padded_device_state(graph):
    """The audit consumes the engine's live padded state directly —
    no host round-trip of the labels (the at-scale use case)."""
    from lux_tpu.apps import sssp
    eng = sssp.build_engine(graph, start_vertex=0, num_parts=4)
    label, active = eng.init_state()
    label, active, _ = eng.converge(label, active)
    res = device_check.check_sssp_device(eng.sg, label)
    assert res.ok
