"""Native-tool bitrot guard (tier-1, CPU-only, fast).

``make -C lux_tpu/native smoke`` builds both native artifacts and runs
the converter end-to-end on a 3-edge list; the Python side then loads
the produced .lux through the pthread loader and round-trips
``native.sort_kv`` — so a broken toolchain, a stale .so, or an ABI
drift in the ctypes bindings fails HERE instead of minutes into a
big-graph benchmark (the converter/loader path was previously only
exercised by scripts/bench_converter.py, which needs multi-GB inputs).
"""

import os
import shutil
import subprocess

import numpy as np
import pytest

NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "lux_tpu", "native")


def test_make_smoke_and_bindings():
    # toolchain probe up front: no make / no C++ compiler is a
    # machine limitation, not bitrot — skip, don't fail
    cxx = os.environ.get("CXX", "g++").split()[0]
    if shutil.which("make") is None or shutil.which(cxx) is None:
        pytest.skip(f"no make/{cxx} toolchain on this machine")
    proc = subprocess.run(["make", "-C", NATIVE_DIR, "smoke"],
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (
        f"native smoke failed\nstdout:\n{proc.stdout}\n"
        f"stderr:\n{proc.stderr}")
    assert "smoke OK" in proc.stdout

    from lux_tpu import native
    assert native.available()

    # the converter's OUTPUT must pass the round-9 structural checker
    # (fsck_lux / format.validate_graph): a converter that emits
    # non-monotone row_ptrs or out-of-range sources fails HERE
    lux = os.path.join(NATIVE_DIR, "build", "smoke.lux")
    import sys
    fsck = os.path.join(os.path.dirname(NATIVE_DIR), "..", "scripts",
                        "fsck_lux.py")
    proc = subprocess.run([sys.executable, fsck, lux],
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "OK" in proc.stdout

    # the converter's smoke output loads through the pthread loader
    # with the exact 3-edge weighted graph (dst-sorted: 2->0, 0->1,
    # 1->2 with weights 1, 5, 3) — validate= runs the same pass on
    # the native load path
    from lux_tpu.graph import Graph
    g = Graph.from_file(lux, use_native=True, validate=True)
    assert (g.nv, g.ne) == (3, 3)
    src, dst = g.edge_arrays()
    np.testing.assert_array_equal(src, [2, 0, 1])
    np.testing.assert_array_equal(dst, [0, 1, 2])
    np.testing.assert_array_equal(np.asarray(g.weights), [1, 5, 3])

    # sort_kv round-trip: key sort carries payloads in lockstep
    keys = np.array([5, 1, 4, 1, 3], np.int64)
    pay = np.arange(5, dtype=np.int64)
    native.sort_kv(keys, (pay,))
    np.testing.assert_array_equal(keys, [1, 1, 3, 4, 5])
    assert sorted(pay.tolist()) == list(range(5))
    np.testing.assert_array_equal(keys, np.sort(
        np.array([5, 1, 4, 1, 3])))

    # reorder round-trip on the same 3-edge graph (round 16,
    # reorder.cc): every mode emits a bijection, and relabeling
    # through it preserves the degree histogram exactly
    src, dst = g.edge_arrays()
    deg = (np.bincount(src, minlength=3)
           + np.bincount(dst, minlength=3))
    for mode in ("cm", "hubs", "communities"):
        perm = native.reorder_cluster(src, dst, 3, mode=mode)
        assert sorted(perm.tolist()) == [0, 1, 2]
        rank = np.empty(3, np.int64)
        rank[perm] = np.arange(3)
        deg2 = (np.bincount(rank[src], minlength=3)
                + np.bincount(rank[dst], minlength=3))
        np.testing.assert_array_equal(deg2, deg[perm])


@pytest.mark.slow
def test_make_sanitize():
    """``make sanitize``: converter/loader/rmat/sort under
    -fsanitize=address,undefined -Wall -Werror, plus a native driver
    running the 3-edge smoke through the loader, a tiny R-MAT and the
    threaded radix sort.  Memory errors and UB in the native tools
    fail this (slow-marked) test instead of corrupting a multi-GB
    benchmark load; the sanitized binaries live in build/sanitize and
    never shadow the fast artifacts."""
    cxx = os.environ.get("CXX", "g++").split()[0]
    if shutil.which("make") is None or shutil.which(cxx) is None:
        pytest.skip(f"no make/{cxx} toolchain on this machine")
    # ASan availability probe (some minimal images lack libasan):
    # compiling an empty program tells us without failing the test
    probe = subprocess.run(
        [cxx, "-fsanitize=address,undefined", "-x", "c++", "-", "-o",
         "/dev/null"], input="int main(){return 0;}",
        capture_output=True, text=True, timeout=120)
    if probe.returncode != 0:
        pytest.skip("toolchain lacks asan/ubsan runtime")
    proc = subprocess.run(["make", "-C", NATIVE_DIR, "sanitize"],
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, (
        f"native sanitize failed\nstdout:\n{proc.stdout}\n"
        f"stderr:\n{proc.stderr}")
    assert "sanitize_driver OK" in proc.stdout
    assert "sanitize OK" in proc.stdout


@pytest.mark.slow
def test_make_analyze():
    """``make analyze`` (round 25): GCC -fanalyzer over all five
    native TUs with -Werror — the interprocedural path-sensitive
    pass catches double-free/use-after-free/fd-leak/NULL-deref
    paths at COMPILE time, including paths the sanitize smoke never
    executes.  Capability-gated: the analyzer needs gcc >= 10 (a
    clang CXX or an old gcc skips, it doesn't fail)."""
    cxx = os.environ.get("CXX", "g++").split()[0]
    if shutil.which("make") is None or shutil.which(cxx) is None:
        pytest.skip(f"no make/{cxx} toolchain on this machine")
    # -fanalyzer availability probe: clang and gcc < 10 reject the
    # flag (note -fsyntax-only would NOT probe the analyzer — gcc
    # stops before the pass — so probe with a real compile)
    probe = subprocess.run(
        [cxx, "-fanalyzer", "-x", "c++", "-c", "-", "-o",
         "/dev/null"], input="int main(){return 0;}",
        capture_output=True, text=True, timeout=120)
    if probe.returncode != 0:
        pytest.skip("toolchain lacks -fanalyzer (needs gcc >= 10)")
    proc = subprocess.run(["make", "-C", NATIVE_DIR, "analyze"],
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, (
        f"native analyze failed\nstdout:\n{proc.stdout}\n"
        f"stderr:\n{proc.stderr}")
    assert "analyze OK" in proc.stdout
