"""Streamed-vs-monolithic SDDMM pair delivery equivalence (round 8).

The streamed path (ops/pairs.pair_partial_dot_streamed) must be
EXACTLY the monolithic pair_partial_dot — same per-row pipeline, same
per-slot reduction order — and both must match the float64 NumPy
oracle (stacked_pair_dot_numpy) EXACTLY when states/weights are
integer-valued with products under 2^24 (all sums exact, so any
correct implementation agrees bitwise).  Covered: K in {1, 20}, depth
classes with ragged fill, multi-block scans + remainder blocks, the
min_fill-dropped-edges-ride-residual invariant, and engines on 1 part,
multi-part, and the 8-virtual-device mesh.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from lux_tpu.engine.program import PullProgram
from lux_tpu.engine.pull import PullEngine
from lux_tpu.graph import Graph, ShardedGraph
from lux_tpu.ops.pairs import (W, pair_partial_dot,
                               pair_partial_dot_streamed,
                               plan_sharded_pairs,
                               stacked_pair_dot_numpy)


def _rating_graph(seed=5, nv=512, ne=8000):
    """Hub-skewed weighted graph: dense tile pairs with RAGGED fill
    (zipf sources spread occurrence depth unevenly across slots)."""
    rng = np.random.default_rng(seed)
    src = (rng.zipf(1.3, ne) - 1) % nv
    dst = rng.integers(0, nv, ne)
    w = rng.integers(1, 6, ne).astype(np.int32)
    return Graph.from_edges(src.astype(np.uint32),
                            dst.astype(np.uint32), nv, weights=w)


def _int_state(rng, n, k):
    """Integer-valued f32 state: keeps every dot/message/sum exactly
    representable, so f32 == float64 oracle bitwise."""
    return rng.integers(0, 4, (n, k)).astype(np.float32)


def _msg_dot(s, dot, wt):
    # colfilter's gradient shape: (w - <s, d>) * s
    return (wt - dot)[..., None] * s


@pytest.mark.parametrize("kdim", [1, 20])
def test_streamed_matches_monolithic_and_oracle_exactly(kdim):
    g = _rating_graph()
    sg = ShardedGraph.build(g, 2, vpad_align=128)
    sp, _res = plan_sharded_pairs(sg, threshold=4)
    assert sp is not None and len(sp.classes) > 1   # ragged depths
    rng = np.random.default_rng(3)
    state = _int_state(rng, sg.num_parts * sg.vpad, kdim)

    for p in range(sg.num_parts):
        t0 = p * (sg.vpad // W)
        args = (sp, jnp.asarray(state), jnp.asarray(sp.rowbind[p]),
                jnp.asarray(sp.rel_dst[p]), jnp.asarray(sp.weight[p]),
                jnp.asarray(sp.row_tile[p]),
                jnp.asarray(sp.tile_pos[p]), t0, _msg_dot)
        mono = np.asarray(pair_partial_dot(*args))
        # tiny blocks force multi-block scans AND remainder blocks
        strm = np.asarray(pair_partial_dot_streamed(
            *args, block_bytes=1 << 16))
        np.testing.assert_array_equal(strm, mono)
        oracle = stacked_pair_dot_numpy(sp, p, state, t0, _msg_dot)
        np.testing.assert_array_equal(strm.astype(np.float64), oracle)


def _dot_program(k, gamma=1.0, lam=0.0):
    """colfilter-shaped program with integer-preserving apply
    (gamma=1, lam=0): one step on integer state stays exact."""

    def edge_value(s, d, w):
        err = w - jnp.sum(s * d, axis=-1)
        return err[..., None] * s

    def init(sg):
        rng = np.random.default_rng(11)
        return rng.integers(0, 4, (sg.num_parts, sg.vpad, k)).astype(
            np.float32)

    return PullProgram(
        reduce="sum", edge_value=edge_value,
        apply=lambda old, red, ctx: old + gamma * (red - lam * old),
        init=init, needs_dst=True,
        edge_value_from_dot=_msg_dot, state_bytes=4 * k)


@pytest.mark.parametrize("kdim", [1, 20])
@pytest.mark.parametrize("num_parts", [1, 3])
def test_engine_streamed_matches_monolithic(kdim, num_parts):
    """Whole-engine A/B: pair_stream=True vs False differ ONLY in the
    SDDMM delivery, so the stepped states must agree bitwise."""
    g = _rating_graph(seed=9)
    sg = ShardedGraph.build(g, num_parts, vpad_align=128)
    mono = PullEngine(sg, _dot_program(kdim), pair_threshold=4,
                      tile_e=128, pair_stream=False)
    strm = PullEngine(ShardedGraph.build(g, num_parts, vpad_align=128),
                      _dot_program(kdim), pair_threshold=4,
                      tile_e=128, pair_stream=True)
    assert mono.pairs is not None and mono.pairs.stats["covered"] > 0
    assert not mono.pair_dot_stream and strm.pair_dot_stream
    a = np.asarray(mono.step(mono.init_state()))
    b = np.asarray(strm.step(strm.init_state()))
    np.testing.assert_array_equal(b, a)


def test_engine_mesh_streamed_matches_single_device():
    """8 virtual devices: the shard_map'd streamed SDDMM path must
    equal the single-device run and the colfilter oracle."""
    from lux_tpu.apps import colfilter
    from lux_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(1)
    n_users, n_items, ne = 300, 80, 6000
    u = rng.integers(0, n_users, ne, dtype=np.uint32)
    i = rng.integers(0, n_items, ne, dtype=np.uint32) + n_users
    w = rng.integers(1, 6, ne, dtype=np.int32)
    g = Graph.from_edges(np.concatenate([u, i]), np.concatenate([i, u]),
                         n_users + n_items,
                         weights=np.concatenate([w, w]))
    want = colfilter.reference_colfilter(g, 3)

    mesh = make_mesh(8)
    sg = ShardedGraph.build(g, 8, pair_threshold=4)
    eng = PullEngine(sg, colfilter.make_program(), mesh=mesh,
                     pair_threshold=4, pair_stream=True, tile_e=128)
    assert eng.pairs is not None and eng.pair_dot_stream
    got = eng.unpad(eng.run(eng.init_state(), 3))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-7)

    solo = PullEngine(ShardedGraph.build(g, 8, pair_threshold=4),
                      colfilter.make_program(), pair_threshold=4,
                      pair_stream=True, tile_e=128)
    got_solo = solo.unpad(solo.run(solo.init_state(), 3))
    np.testing.assert_allclose(got, got_solo, rtol=1e-6, atol=1e-9)


def test_min_fill_dropped_edges_ride_residual_dot():
    """K-dim min_fill invariant: edges dropped from under-filled
    SDDMM rows must be served EXACTLY by the residual dot path — the
    pair+min_fill engine equals the no-pair engine bitwise on integer
    state (one gamma=1 step)."""
    g = _rating_graph(seed=21)
    K = 20
    base = PullEngine(ShardedGraph.build(g, 2, vpad_align=128),
                      _dot_program(K))
    capped = PullEngine(ShardedGraph.build(g, 2, vpad_align=128),
                        _dot_program(K), pair_threshold=4,
                        pair_min_fill=16, tile_e=128)
    assert capped.pairs is not None
    # every surviving row delivers >= min_fill live lanes
    fills = (capped.pairs.rel_dst != -1).sum(axis=2)
    live = fills[fills > 0]
    assert live.size and (live >= 16).all()
    # partition: covered + residual = all edges
    cov = capped.pairs.stats["covered"]
    resid = int(capped.sg.ne_part.sum())
    assert cov + resid == g.ne
    a = np.asarray(base.step(base.init_state()))
    b = np.asarray(capped.step(capped.init_state()))
    np.testing.assert_array_equal(b, a)


def test_auto_min_fill_is_k_aware():
    """min_fill='auto' resolves through the K-aware cost model: K-dim
    rows must be FULLER to beat their costlier delivery, so the K=20
    cap exceeds the scalar one and the planner caps exactly at the
    modeled break-even."""
    from lux_tpu.ops.pairs import analyze_pairs, resolve_min_fill
    from lux_tpu.scalemodel import break_even_fill

    assert break_even_fill(20) > break_even_fill(1)
    assert resolve_min_fill("auto", 20) == break_even_fill(20)
    assert resolve_min_fill("auto") == break_even_fill(1)
    assert resolve_min_fill(None) is None
    assert resolve_min_fill(7, 20) == 7
    with pytest.raises(ValueError, match="min_fill"):
        resolve_min_fill("bogus")

    g = _rating_graph(seed=33)
    sg = ShardedGraph.build(g, 1, vpad_align=128)
    nep = int(sg.ne_part[0])
    auto = analyze_pairs(sg.src_slot[0, :nep], sg.dst_local[0, :nep],
                         sg.vpad, threshold=4, min_fill="auto",
                         kdim=20)
    expl = analyze_pairs(sg.src_slot[0, :nep], sg.dst_local[0, :nep],
                         sg.vpad, threshold=4,
                         min_fill=break_even_fill(20))
    np.testing.assert_array_equal(auto.residual, expl.residual)
    np.testing.assert_array_equal(auto.cov, expl.cov)


def test_memory_report_prices_streamed_blocks():
    """memory_report(pairs=...) must price the STREAMED per-block
    temporary when streaming engages, not the monolithic [Rp, 128, K]
    tensor — and the monolithic figure when it is forced off."""
    from lux_tpu.ops.pairs import (PAIR_DOT_BLOCK_BYTES,
                                   PAIR_STREAM_BLOCK_BYTES)

    g = _rating_graph(seed=41)
    sg = ShardedGraph.build(g, 2, vpad_align=128)
    sp, res = plan_sharded_pairs(sg, threshold=4)
    assert sp is not None
    K = 20
    rep_s = res.memory_report(pairs=sp, pair_kdim=K, pair_stream=True)
    assert rep_s["pair_temp_bytes_per_part"] == PAIR_DOT_BLOCK_BYTES
    rep_m = res.memory_report(pairs=sp, pair_kdim=K, pair_stream=False)
    # partials + delivered tile values: XLA materializes both
    # (measured ~2x the partials tensor, PERF_NOTES round 8)
    assert rep_m["pair_temp_bytes_per_part"] == 2 * sp.Rp * W * K * 4
    # scalar plans price the scalar streamed block (the default path)
    rep_sc = res.memory_report(pairs=sp)
    assert rep_sc["pair_temp_bytes_per_part"] == PAIR_STREAM_BLOCK_BYTES
    # pair arrays themselves are priced either way
    assert rep_s["pair_bytes_per_part"] > 0
    assert rep_s["total_bytes"] > res.memory_report()["total_bytes"]
