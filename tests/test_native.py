"""Native C++ converter/loader vs the Python implementations."""

import subprocess

import numpy as np
import pytest

from lux_tpu import format as luxfmt
from lux_tpu import native
from lux_tpu.convert import uniform_random_edges
from lux_tpu.graph import Graph
from lux_tpu.partition import edge_balanced_bounds

pytestmark = pytest.mark.skipif(not native.ensure_built(),
                                reason="no C++ toolchain")


def _write_text(path, src, dst, w=None):
    with open(path, "w") as f:
        for i in range(len(src)):
            if w is None:
                f.write(f"{src[i]} {dst[i]}\n")
            else:
                f.write(f"{src[i]} {dst[i]} {w[i]}\n")


@pytest.mark.parametrize("weighted", [False, True])
def test_converter_byte_identical_with_python(tmp_path, weighted):
    if weighted:
        src, dst, w = uniform_random_edges(60, 500, seed=3, weighted=True)
    else:
        src, dst = uniform_random_edges(60, 500, seed=3)
        w = None
    txt = tmp_path / "e.txt"
    _write_text(txt, src, dst, w)

    # Python path
    g = Graph.from_edges(src, dst, 60, weights=w)
    py_out = tmp_path / "py.lux"
    luxfmt.write_lux(str(py_out), g.row_ptrs, g.col_idx,
                     weights=g.weights, degrees=g.out_degrees)

    # Native path
    cc_out = tmp_path / "cc.lux"
    cmd = [native.CONVERTER, "-nv", "60", "-input", str(txt),
           "-output", str(cc_out)]
    if weighted:
        cmd.append("-weighted")
    subprocess.run(cmd, check=True, capture_output=True)

    assert py_out.read_bytes() == cc_out.read_bytes()


def test_converter_rejects_bad_input(tmp_path):
    txt = tmp_path / "bad.txt"
    txt.write_text("0 99\n")  # out of range for nv=3
    r = subprocess.run([native.CONVERTER, "-nv", "3", "-input", str(txt),
                        "-output", str(tmp_path / "x.lux")],
                       capture_output=True)
    assert r.returncode == 1
    assert b"out of range" in r.stderr


def test_native_header_and_degrees(tmp_path):
    src, dst = uniform_random_edges(100, 900, seed=4)
    g = Graph.from_edges(src, dst, 100)
    p = tmp_path / "g.lux"
    luxfmt.write_lux(str(p), g.row_ptrs, g.col_idx)
    nv, ne = native.read_header(str(p))
    assert (nv, ne) == (100, 900)
    deg = native.count_degrees(str(p), nv, ne)
    np.testing.assert_array_equal(deg, g.out_degrees)


@pytest.mark.parametrize("weighted", [False, True])
def test_native_partition_slices(tmp_path, weighted):
    if weighted:
        src, dst, w = uniform_random_edges(80, 700, seed=5, weighted=True)
        g = Graph.from_edges(src, dst, 80, weights=w)
    else:
        src, dst = uniform_random_edges(80, 700, seed=5)
        g = Graph.from_edges(src, dst, 80)
    p = tmp_path / "g.lux"
    luxfmt.write_lux(str(p), g.row_ptrs, g.col_idx, weights=g.weights)

    starts = edge_balanced_bounds(g.row_ptrs, 4)
    for i in range(4):
        v0, v1 = int(starts[i]), int(starts[i + 1])
        rows, cols, ws, e_lo = native.load_partition(
            str(p), g.nv, g.ne, v0, v1, weighted=weighted)
        np.testing.assert_array_equal(rows, g.row_ptrs[v0:v1])
        lo = int(g.row_ptrs[v0 - 1]) if v0 else 0
        hi = int(g.row_ptrs[v1 - 1])
        assert e_lo == lo
        np.testing.assert_array_equal(cols, g.col_idx[lo:hi])
        if weighted:
            np.testing.assert_array_equal(ws, np.asarray(g.weights)[lo:hi])


def test_native_missing_file_error():
    with pytest.raises(OSError):
        native.read_header("/nonexistent/g.lux")


def test_graph_from_file_native_matches_mmap(tmp_path):
    src, dst = uniform_random_edges(90, 800, seed=6)
    g = Graph.from_edges(src, dst, 90)
    p = tmp_path / "g.lux"
    luxfmt.write_lux(str(p), g.row_ptrs, g.col_idx, degrees=g.out_degrees)
    gm = Graph.from_file(str(p))
    gn = Graph.from_file(str(p), use_native=True)
    np.testing.assert_array_equal(np.asarray(gm.row_ptrs),
                                  np.asarray(gn.row_ptrs))
    np.testing.assert_array_equal(np.asarray(gm.col_idx),
                                  np.asarray(gn.col_idx))
    np.testing.assert_array_equal(gm.out_degrees, gn.out_degrees)


def test_native_rmat_csc_valid_and_deterministic():
    from lux_tpu import native
    if not native.available():
        pytest.skip("native library unavailable")
    import numpy as np
    rp, ci, deg = native.rmat_csc(10, 8, seed=7)
    nv, ne = 1 << 10, (1 << 10) * 8
    assert rp.shape == (nv,) and ci.shape == (ne,)
    assert rp[-1] == ne and (np.diff(rp.astype(np.int64)) >= 0).all()
    assert (np.bincount(ci, minlength=nv) == deg).all()
    rp2, ci2, _ = native.rmat_csc(10, 8, seed=7)
    assert (ci2 == ci).all() and (rp2 == rp).all()
    _, ci3, _ = native.rmat_csc(10, 8, seed=8)
    assert not (ci3 == ci).all()


def test_rmat_graph_runs_apps():
    """The native-generated graph must drive the engines end to end."""
    import numpy as np
    from lux_tpu.apps import pagerank
    from lux_tpu.convert import rmat_graph
    g = rmat_graph(9, 4, seed=3)
    ranks = pagerank.run(g, 5, num_parts=2)
    assert np.isfinite(ranks).all() and ranks.shape == (g.nv,)


def test_argsort_u64_matches_numpy():
    """Parity for the parallel radix argsort (sort.cc): stable-equal
    to np.argsort for full-range and bounded (pass-skipping) keys,
    at 1 and several threads."""
    import numpy as np

    from lux_tpu import native

    if not native.available():
        import pytest
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(7)
    for hi in (1 << 60, 1 << 20, 8):
        keys = rng.integers(0, hi, 100_000).astype(np.int64)
        want = np.argsort(keys, kind="stable")
        for threads in (1, 3, 8):
            got = native.argsort_u64(keys, threads=threads)
            np.testing.assert_array_equal(got, want)
    # empty + single
    assert native.argsort_u64(np.empty(0, np.int64)).size == 0
    np.testing.assert_array_equal(
        native.argsort_u64(np.asarray([5], np.int64)), [0])


def test_sort_kv_matches_argsort_gathers():
    """The fused key+payload sort (sort.cc lux_sort_kv_u64) must equal
    argsort + per-array gathers: stable, every payload itemsize, both
    key dtypes, bounded keys (pass skipping), 1..8 threads, and the
    numpy fallback when native is unavailable."""
    import numpy as np

    from lux_tpu import native

    rng = np.random.default_rng(11)
    for n in (0, 1, 5, 40_000):
        for hi in (1, 200, 1 << 26, 1 << 52):
            keys = rng.integers(0, hi, n).astype(np.uint64)
            p32 = rng.integers(-2**31, 2**31, n).astype(np.int32)
            p8 = rng.integers(-128, 128, n).astype(np.int8)
            pf = rng.random(n).astype(np.float32)
            p64 = rng.integers(0, 2**60, n).astype(np.int64)
            order = np.argsort(keys, kind="stable")
            want = (keys[order], p32[order], p8[order], pf[order],
                    p64[order])
            for threads in (1, 3):
                got = (keys.copy(), p32.copy(), p8.copy(), pf.copy(),
                       p64.copy())
                native.sort_kv(got[0], got[1:], threads=threads)
                for g, w in zip(got, want):
                    np.testing.assert_array_equal(g, w)
    # int64 keys sort through a view; stability carries payload order
    k = np.asarray([3, 1, 3, 1, 3], np.int64)
    p = np.arange(5, dtype=np.uint32)
    native.sort_kv(k, (p,))
    assert k.tolist() == [1, 1, 3, 3, 3]
    assert p.tolist() == [1, 3, 0, 2, 4]
    # negative int64 keys are rejected (the u64 view would misorder)
    import pytest
    with pytest.raises(ValueError):
        native.sort_kv(np.asarray([-1, 2], np.int64))
    # numpy fallback path (length mismatch guard + forced fallback)
    with pytest.raises(ValueError):
        native.sort_kv(np.asarray([1, 2], np.uint64),
                       (np.zeros(3, np.int32),))
    import unittest.mock as mock
    k2 = np.asarray([2, 0, 1], np.uint64)
    p2 = np.asarray([9, 8, 7], np.int32)
    with mock.patch.object(native, "available", lambda: False):
        native.sort_kv(k2, (p2,))
    assert k2.tolist() == [0, 1, 2] and p2.tolist() == [8, 7, 9]
