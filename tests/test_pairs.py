"""Pair-lane planner: covered + residual must exactly partition the
edges, and the oracle reduce over pair rows plus a plain reduce over
residual edges must equal the full-graph reduce."""

import numpy as np
import pytest

from lux_tpu.ops.pairs import W, build_pair_plan, pair_reduce_numpy


def full_oracle(src_slot, dst_local, state, vpad):
    out = np.zeros(vpad)
    for s, d in zip(src_slot, dst_local):
        out[d] += state[s]
    return out


@pytest.mark.parametrize("seed,threshold", [(1, 2), (2, 4), (3, 8)])
def test_pair_plus_residual_equals_full(seed, threshold):
    rng = np.random.default_rng(seed)
    vpad = 4 * W
    n_state_rows = 6
    ne = 6000
    # skew sources so dense pairs exist
    src = (rng.zipf(1.4, ne) - 1) % (n_state_rows * W)
    dst = rng.integers(0, vpad, ne)
    plan = build_pair_plan(src, dst, vpad, threshold=threshold)
    state = rng.random(n_state_rows * W)

    # partition property
    assert plan.stats["covered"] + plan.residual.sum() == ne
    if threshold <= 4:
        assert plan.stats["covered"] > 0

    got = pair_reduce_numpy(plan, state)
    res = plan.residual
    got_res = full_oracle(src[res], dst[res], state, vpad)
    want = full_oracle(src, dst, state, vpad)
    np.testing.assert_allclose(got + got_res, want, rtol=1e-9)


def test_multiplicity_rows():
    # one source hitting one dst tile many times forces occurrence rows
    src = np.full(10, 5)
    dst = np.arange(10) % 3          # some duplicate dsts too
    state = np.arange(4 * W, dtype=np.float64)
    want = full_oracle(src, dst, state, 2 * W)

    # uncapped: fully covered
    plan = build_pair_plan(src, dst, 2 * W, threshold=2, max_occ=16)
    got = pair_reduce_numpy(plan, state)
    np.testing.assert_allclose(got, want)
    assert plan.residual.sum() == 0

    # occurrence cap pushes deep multi-edges to the residual, and
    # pair + residual still partition correctly
    plan = build_pair_plan(src, dst, 2 * W, threshold=2, max_occ=4)
    assert plan.residual.sum() == 6      # occ 4..9 of one source
    got = pair_reduce_numpy(plan, state)
    res = plan.residual
    got += full_oracle(src[res], dst[res], state, 2 * W)
    np.testing.assert_allclose(got, want)


def test_engine_pair_path_matches_plain():
    """PageRank with pair-lane delivery must equal the plain engine."""
    from lux_tpu.apps import pagerank
    from lux_tpu.convert import uniform_random_edges
    from lux_tpu.graph import Graph

    rng = np.random.default_rng(7)
    nv = 3 * W
    src = (rng.zipf(1.3, 4000) - 1) % nv
    dst = (rng.zipf(1.2, 4000) - 1) % nv
    g = Graph.from_edges(src.astype(np.uint32), dst.astype(np.uint32),
                         nv)
    g2, perm = pagerank.degree_relabel(g)

    plain = pagerank.run(g, 8)
    eng = pagerank.build_engine(g2, pair_threshold=4)
    assert eng.pairs is not None and eng.pairs.stats["covered"] > 0
    got_perm = eng.unpad(eng.run(eng.init_state(), 8))
    got = np.empty_like(got_perm)
    got[perm] = got_perm                   # back to original ids
    np.testing.assert_allclose(got, plain, rtol=1e-5)


def test_pair_path_applies_edge_value():
    """Programs transforming src values must agree between pair rows
    and the residual path."""
    from lux_tpu.engine.program import PullProgram
    from lux_tpu.engine.pull import PullEngine
    from lux_tpu.graph import Graph, ShardedGraph

    rng = np.random.default_rng(17)
    nv = 2 * W
    src = (rng.zipf(1.3, 2000) - 1) % nv
    dst = rng.integers(0, nv, 2000)
    g = Graph.from_edges(src.astype(np.uint32), dst.astype(np.uint32),
                         nv)

    def mk():
        return PullProgram(
            reduce="sum",
            edge_value=lambda s, d, w: s * 2.0 + 1.0,
            apply=lambda o, r, c: r,
            init=lambda sg: np.linspace(
                0, 1, sg.num_parts * sg.vpad,
                dtype=np.float32).reshape(sg.num_parts, sg.vpad))

    sgp = ShardedGraph.build(g, 1, vpad_align=128)
    plain = PullEngine(sgp, mk())
    pair = PullEngine(sgp, mk(), pair_threshold=2)
    assert pair.pairs is not None
    out_a = plain.unpad(plain.step(plain.init_state()))
    out_b = pair.unpad(pair.step(pair.init_state()))
    np.testing.assert_allclose(out_b, out_a, rtol=1e-5)


def test_pair_path_rejects_dst_programs():
    import pytest
    from lux_tpu.engine.program import PullProgram
    from lux_tpu.engine.pull import PullEngine
    from lux_tpu.graph import Graph, ShardedGraph
    src = np.zeros(40, np.uint32)
    dst = (np.arange(40) % 7).astype(np.uint32)
    g = Graph.from_edges(src, dst, 2 * W)
    sg = ShardedGraph.build(g, 1, vpad_align=128)
    prog = PullProgram(reduce="sum",
                       edge_value=lambda s, d, w: s * d,
                       apply=lambda o, r, c: r,
                       init=lambda sg: np.zeros(
                           (sg.num_parts, sg.vpad), np.float32),
                       needs_dst=True)
    with pytest.raises(ValueError, match="source"):
        PullEngine(sg, prog, pair_threshold=2)


# ---- stacked (multi-part / mesh / weighted / push) paths ------------


def _skewed_graph(seed, nv, ne, weighted=False):
    from lux_tpu.graph import Graph
    rng = np.random.default_rng(seed)
    src = (rng.zipf(1.3, ne) - 1) % nv
    dst = (rng.zipf(1.2, ne) - 1) % nv
    w = rng.integers(1, 6, ne).astype(np.float32) if weighted else None
    return Graph.from_edges(src.astype(np.uint32),
                            dst.astype(np.uint32), nv, weights=w)


def test_stacked_plan_oracle_partition():
    """Per-part stacked delivery + residual = full reduce, per part."""
    from lux_tpu.graph import ShardedGraph
    from lux_tpu.ops.pairs import (plan_sharded_pairs,
                                   stacked_pair_reduce_numpy)

    g = _skewed_graph(11, 4 * W, 9000)
    sg = ShardedGraph.build(g, 3, vpad_align=128)
    sp, res_sg = plan_sharded_pairs(sg, threshold=3)
    assert sp is not None and sp.stats["covered"] > 0
    state = np.random.default_rng(0).random(sg.num_parts * sg.vpad)
    for p in range(sg.num_parts):
        nep = int(sg.ne_part[p])
        want = full_oracle(sg.src_slot[p, :nep],
                           sg.dst_local[p, :nep], state, sg.vpad)
        got = stacked_pair_reduce_numpy(sp, p, state)[:sg.vpad]
        nr = int(res_sg.ne_part[p])
        got += full_oracle(res_sg.src_slot[p, :nr],
                           res_sg.dst_local[p, :nr], state, sg.vpad)
        np.testing.assert_allclose(got, want, rtol=1e-9)


@pytest.mark.parametrize("num_parts", [2, 4])
def test_pull_pair_multi_part_matches_plain(num_parts):
    from lux_tpu.apps import pagerank

    g = _skewed_graph(7, 3 * W, 4000)
    g2, perm = pagerank.degree_relabel(g)
    plain = pagerank.run(g, 8)
    eng = pagerank.build_engine(g2, num_parts=num_parts,
                                pair_threshold=4)
    assert eng.pairs is not None and eng.pairs.stats["covered"] > 0
    got_perm = eng.unpad(eng.run(eng.init_state(), 8))
    got = np.empty_like(got_perm)
    got[perm] = got_perm
    np.testing.assert_allclose(got, plain, rtol=1e-5)


def test_pull_pair_mesh_matches_plain():
    from lux_tpu.apps import pagerank
    from lux_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(8)
    g = _skewed_graph(8, 5 * W, 12000)
    g2, perm = pagerank.degree_relabel(g)
    plain = pagerank.run(g, 6)
    eng = pagerank.build_engine(g2, num_parts=8, mesh=mesh,
                                pair_threshold=4)
    assert eng.pairs is not None and eng.pairs.stats["covered"] > 0
    got_perm = eng.unpad(eng.run(eng.init_state(), 6))
    got = np.empty_like(got_perm)
    got[perm] = got_perm
    np.testing.assert_allclose(got, plain, rtol=1e-5)


def test_pull_pair_weighted_matches_plain():
    """Weighted pull program: per-lane weights must ride the pair rows."""
    from lux_tpu.engine.program import PullProgram
    from lux_tpu.engine.pull import PullEngine
    from lux_tpu.graph import ShardedGraph

    g = _skewed_graph(19, 2 * W, 3000, weighted=True)

    def mk():
        return PullProgram(
            reduce="sum",
            edge_value=lambda s, d, w: s * w,
            apply=lambda o, r, c: r,
            init=lambda sg: np.linspace(
                1, 2, sg.num_parts * sg.vpad,
                dtype=np.float32).reshape(sg.num_parts, sg.vpad))

    sg = ShardedGraph.build(g, 2, vpad_align=128)
    plain = PullEngine(sg, mk())
    pair = PullEngine(sg, mk(), pair_threshold=2)
    assert pair.pairs is not None
    assert pair.pairs.weight is not None
    out_a = plain.unpad(plain.step(plain.init_state()))
    out_b = pair.unpad(pair.step(pair.init_state()))
    np.testing.assert_allclose(out_b, out_a, rtol=1e-5)


def test_push_pair_cc_matches_oracle():
    from lux_tpu.apps import components
    from lux_tpu.graph import Graph, degree_relabel

    g0 = _skewed_graph(23, 3 * W, 5000)
    s, d = components.symmetrize(*g0.edge_arrays())
    g = Graph.from_edges(s, d, g0.nv)
    g2, perm = degree_relabel(g)
    eng = components.build_engine(g2, num_parts=2, pair_threshold=4)
    assert eng.pairs is not None and eng.pairs.stats["covered"] > 0
    lab2, _ = eng.run()
    # labels are NEW vertex ids; canonicalize per component via perm
    lab = np.empty(g.nv, np.int64)
    lab[perm] = perm[lab2]                 # orig vertex -> orig rep id
    want = components.reference_components(g)
    # same partition into components (representatives may differ)
    import collections
    rep_of = {}
    for v in range(g.nv):
        rep_of.setdefault(lab[v], set()).add(v)
    want_of = collections.defaultdict(set)
    for v in range(g.nv):
        want_of[want[v]].add(v)
    assert sorted(map(sorted, rep_of.values())) == \
        sorted(map(sorted, want_of.values()))


@pytest.mark.parametrize("weighted", [False, True])
def test_push_pair_sssp_dense_matches_oracle(weighted):
    from lux_tpu.apps import sssp
    from lux_tpu.engine.push import PushEngine
    from lux_tpu.graph import ShardedGraph, degree_relabel

    g = _skewed_graph(29, 3 * W, 6000, weighted=True)
    g2, perm = degree_relabel(g)
    sg = ShardedGraph.build(g2, 2, vpad_align=128)
    # start at the relabeled id of original vertex 0; disable the
    # sparse path so every iteration exercises dense + pairs
    rank = np.empty(g.nv, np.int64)
    rank[perm] = np.arange(g.nv)
    eng = PushEngine(sg, sssp.make_program(int(rank[0]), weighted),
                     enable_sparse=False, pair_threshold=4)
    assert eng.pairs is not None and eng.pairs.stats["covered"] > 0
    lab2, _ = eng.run()
    lab = np.empty(g.nv, lab2.dtype)
    lab[perm] = lab2
    want = sssp.reference_sssp(g, 0, weighted=weighted)
    reach = ~sssp.unreachable(lab)
    if weighted:
        np.testing.assert_allclose(lab[reach],
                                   want[reach].astype(np.float32),
                                   rtol=1e-5)
    else:
        np.testing.assert_array_equal(lab[reach], want[reach])
    assert np.array_equal(sssp.unreachable(lab), ~np.isfinite(want)
                          if weighted else want >= int(sssp.HOP_INF))


def test_stacked_rows_near_sum_of_parts():
    """With pair_relabel's tile dealing, parts share similar depth
    profiles, so common-frame stacking pads little (contiguous
    degree-sorted cuts measured 2.9-3.4x row padding at RMAT21/np=4;
    dealing measured 1.15x there and ~1.6x at this small scale where
    each part holds only ~16 tiles)."""
    from lux_tpu.convert import rmat_graph
    from lux_tpu.graph import ShardedGraph, pair_relabel
    from lux_tpu.ops.pairs import build_pair_plan, plan_sharded_pairs

    g = rmat_graph(scale=14, edge_factor=8, seed=3)
    P = 4
    g2, _perm, starts = pair_relabel(g, P, pair_threshold=4)
    sg = ShardedGraph.build(g2, P, starts=starts, pair_threshold=4)
    sp, _res = plan_sharded_pairs(sg, 4)
    assert sp is not None
    solo = sum(build_pair_plan(
        sg.src_slot[p, :int(sg.ne_part[p])],
        sg.dst_local[p, :int(sg.ne_part[p])], sg.vpad,
        threshold=4).stats["R"] for p in range(P))
    assert P * sp.Rp <= max(1.75 * solo, P * 256), \
        f"stacked rows {P * sp.Rp} vs per-part sum {solo}"


def test_pair_relabel_balances_residuals():
    """Tile dealing must spread residual (gather) edges better than
    contiguous degree-sorted cuts (measured 0.8M..5.9M at RMAT21)."""
    from lux_tpu.convert import rmat_graph
    from lux_tpu.graph import ShardedGraph, degree_relabel, pair_relabel
    from lux_tpu.ops.pairs import plan_sharded_pairs

    g = rmat_graph(scale=14, edge_factor=8, seed=3)
    P = 4

    def resid_spread(sg):
        _sp, res = plan_sharded_pairs(sg, 4)
        ne = np.asarray(res.ne_part, np.float64)
        return ne.max() / max(ne.mean(), 1)

    gd, _ = degree_relabel(g)
    plain = ShardedGraph.build(gd, P, vpad_align=128)
    g2, _perm, starts = pair_relabel(g, P, pair_threshold=4)
    rr = ShardedGraph.build(g2, P, starts=starts, pair_threshold=4)
    assert resid_spread(rr) <= resid_spread(plain) + 1e-9


def test_pair_relabel_preserves_results():
    """pair_relabel is a pure permutation: pagerank on the relabeled
    multi-part graph must match the plain run after unpermuting."""
    from lux_tpu.apps import pagerank
    from lux_tpu.graph import pair_relabel

    g = _skewed_graph(41, 5 * W + 17, 9000)   # nv NOT tile-aligned
    P = 4
    g2, perm, starts = pair_relabel(g, P)
    assert starts[-1] == g.nv and (np.diff(starts) > 0).all()
    assert sorted(perm.tolist()) == list(range(g.nv))
    plain = pagerank.run(g, 6)
    eng = pagerank.build_engine(g2, num_parts=P, pair_threshold=4,
                                starts=starts)
    got_perm = eng.unpad(eng.run(eng.init_state(), 6))
    got = np.empty_like(got_perm)
    got[perm] = got_perm
    np.testing.assert_allclose(got, plain, rtol=1e-5)


@pytest.mark.parametrize("weighted,kind", [(False, "sum"),
                                           (False, "min"),
                                           (True, "sum")])
def test_streamed_pair_partial_matches_monolithic(weighted, kind):
    """pair_partial_streamed must agree bit-for-bit with pair_partial
    — tiny block_bytes force multi-block scans plus remainders."""
    import jax.numpy as jnp
    from lux_tpu.graph import Graph, ShardedGraph
    from lux_tpu.ops.pairs import (pair_partial, pair_partial_streamed,
                                   plan_sharded_pairs)

    rng = np.random.default_rng(21)
    nv, ne = 512, 6000
    src = rng.integers(0, 64, ne)          # dense hub structure
    dst = rng.integers(0, nv, ne)
    w = rng.integers(1, 5, ne).astype(np.int32) if weighted else None
    g = Graph.from_edges(src, dst, nv, weights=w)
    sg = ShardedGraph.build(g, 2, vpad_align=128)
    sp, _res = plan_sharded_pairs(sg, threshold=4)
    assert sp is not None and len(sp.classes) > 1

    state = jnp.asarray(
        rng.random(sg.num_parts * sg.vpad).astype(np.float32))
    if weighted:
        def msg(vals, wt):
            return vals * wt
    else:
        def msg(vals, wt):
            return vals

    from lux_tpu.ops.pairs import stacked_pair_reduce_numpy
    for p in range(sg.num_parts):
        wgt = None if sp.weight is None else jnp.asarray(sp.weight[p])
        args = (sp, state, jnp.asarray(sp.rowbind[p]),
                jnp.asarray(sp.rel_dst[p]), wgt,
                jnp.asarray(sp.tile_pos[p]), kind, msg)
        want = np.asarray(pair_partial(*args))
        got = np.asarray(pair_partial_streamed(*args,
                                               block_bytes=1 << 14))
        if kind == "min":
            # order-insensitive: must agree exactly
            np.testing.assert_array_equal(got, want)
        else:
            # sums associate in block order: ulp-level drift only
            np.testing.assert_allclose(got, want, rtol=1e-5,
                                       atol=1e-6)
        # and both must match the float64 oracle
        oracle = stacked_pair_reduce_numpy(
            sp, p, np.asarray(state), kind,
            msg=lambda v, w: msg(v, w) if weighted else msg(v, None))
        np.testing.assert_allclose(got, oracle, rtol=1e-4, atol=1e-5)


def test_engine_pair_stream_matches_default():
    from lux_tpu.apps import pagerank, sssp
    from lux_tpu.engine.pull import PullEngine
    from lux_tpu.engine.push import PushEngine
    from lux_tpu.convert import rmat_graph
    from lux_tpu.graph import pair_relabel

    from lux_tpu.graph import ShardedGraph

    g = rmat_graph(scale=9, edge_factor=8, seed=6)
    g2, _perm, starts = pair_relabel(g, 2, pair_threshold=4)
    # pair_stream=False pins the MONOLITHIC path (streamed is the
    # engine default) so the two implementations actually face off
    base = PullEngine(ShardedGraph.build(g2, 2, starts=starts,
                                         pair_threshold=4),
                      pagerank.make_program(), pair_threshold=4,
                      tile_e=128, pair_stream=False)
    assert not base.pair_stream
    want = base.unpad(base.run(base.init_state(), 4))

    full = ShardedGraph.build(g2, 2, starts=starts, pair_threshold=4)
    eng = PullEngine(full, pagerank.make_program(), pair_threshold=4,
                     tile_e=128)
    assert eng.pair_stream
    got = eng.unpad(eng.run(eng.init_state(), 4))
    np.testing.assert_allclose(got, want, rtol=1e-6)

    p_base = PushEngine(ShardedGraph.build(g2, 2, starts=starts,
                                           pair_threshold=4),
                        sssp.make_program(0), pair_threshold=4,
                        pair_stream=False)
    p_str = PushEngine(ShardedGraph.build(g2, 2, starts=starts,
                                          pair_threshold=4),
                       sssp.make_program(0), pair_threshold=4)
    assert not p_base.pair_stream and p_str.pair_stream
    l0, a0 = p_base.init_state()
    l1, a1, _ = p_base.converge(l0, a0)
    l0, a0 = p_str.init_state()
    l2, a2, _ = p_str.converge(l0, a0)
    np.testing.assert_array_equal(p_base.unpad(l1), p_str.unpad(l2))


def test_streamed_msgs_matches_fused():
    """stream_msgs=True (billion-edge memory mode) must match the
    fully fused step, with and without pairs, and for weighted
    src-only programs."""
    import jax.numpy as jnp
    from lux_tpu.apps import pagerank
    from lux_tpu.convert import rmat_graph
    from lux_tpu.engine.program import PullProgram
    from lux_tpu.engine.pull import PullEngine
    from lux_tpu.graph import Graph, ShardedGraph, pair_relabel

    g = rmat_graph(scale=9, edge_factor=8, seed=13)
    g2, _perm, starts = pair_relabel(g, 2, pair_threshold=4)
    want_eng = pagerank.build_engine(g2, num_parts=2, pair_threshold=4,
                                     starts=starts)
    want = want_eng.unpad(want_eng.run(want_eng.init_state(), 4))
    eng = PullEngine(
        ShardedGraph.build(g2, 2, starts=starts, pair_threshold=4),
        pagerank.make_program(), pair_threshold=4, tile_e=128,
        stream_msgs=True)
    assert eng.stream_chunks
    got = eng.unpad(eng.run(eng.init_state(), 4))
    np.testing.assert_allclose(got, want, rtol=1e-6)

    # weighted src-only program (exercises the weight block slicing)
    rng = np.random.default_rng(2)
    w = rng.integers(1, 5, g.ne).astype(np.int32)
    gw = Graph.from_edges(*g.edge_arrays(), g.nv, weights=w)
    prog = PullProgram(
        reduce="sum",
        edge_value=lambda s, d, wt: s * wt,
        apply=lambda old, red, ctx: red,
        init=lambda sg: sg.to_padded(
            np.arange(sg.nv, dtype=np.float32) / sg.nv),
        needs_dst=False)
    base = PullEngine(ShardedGraph.build(gw, 2), prog)
    fast = PullEngine(ShardedGraph.build(gw, 2), prog, stream_msgs=True)
    s0 = base.init_state()
    np.testing.assert_allclose(
        np.asarray(fast.step(fast.init_state())),
        np.asarray(base.step(s0)), rtol=1e-6)


def test_streamed_msgs_vector_payload():
    """Vector-payload src-only programs must stream correctly too
    (the Pallas kernel is scalar-only; the streamed path must fall to
    the XLA formulation, not crash)."""
    from lux_tpu.convert import rmat_graph
    from lux_tpu.engine.program import PullProgram
    from lux_tpu.engine.pull import PullEngine
    from lux_tpu.graph import ShardedGraph

    g = rmat_graph(scale=8, edge_factor=8, seed=4)
    K = 5
    prog = PullProgram(
        reduce="sum",
        edge_value=lambda s, d, w: s * 2.0,
        apply=lambda old, red, ctx: red,
        init=lambda sg: sg.to_padded(
            np.arange(sg.nv * K, dtype=np.float32).reshape(sg.nv, K)
            / (sg.nv * K)),
        needs_dst=False)
    base = PullEngine(ShardedGraph.build(g, 2), prog)
    fast = PullEngine(ShardedGraph.build(g, 2), prog, stream_msgs=True)
    assert fast.stream_chunks
    np.testing.assert_allclose(
        np.asarray(fast.step(fast.init_state())),
        np.asarray(base.step(base.init_state())), rtol=1e-6)


def test_streamed_empty_classes_plan():
    """Direct callers may hand pair_partial_streamed a plan with NO
    classes (plan_sharded_pairs returns None first, but the function
    must not IndexError on the degenerate shape): every tile resolves
    to the trailing identity slot (ADVICE r2 #3 / VERDICT r3 #8)."""
    import jax.numpy as jnp
    from lux_tpu.ops.pairs import (StackedPairPlan, pair_partial,
                                   pair_partial_streamed)

    n_tiles = 3
    sp = StackedPairPlan(
        rowbind=np.zeros((1, 0), np.int32),
        rel_dst=np.full((1, 0, W), -1, np.int8), weight=None,
        tile_pos=np.full((1, n_tiles), 0, np.int32), classes=[],
        n_tiles=n_tiles, n_slots=0, R=0, Rp=0, stats={})
    flat = jnp.arange(n_tiles * W, dtype=jnp.float32)
    for fn in (pair_partial, pair_partial_streamed):
        out = np.asarray(fn(sp, flat, jnp.asarray(sp.rowbind[0]),
                            jnp.asarray(sp.rel_dst[0]), None,
                            jnp.asarray(sp.tile_pos[0]), "sum",
                            lambda v, w: v))
        assert out.shape == (n_tiles * W,)
        np.testing.assert_array_equal(out, 0.0)


def test_pair_relabel_rejects_bad_vpad_cap():
    """vpad_cap < 1 cannot cover every full tile: the capped LPT's
    argmin over an all-inf mask would silently dump the remainder on
    part 0 (ADVICE r3)."""
    from lux_tpu.graph import pair_relabel

    g = _skewed_graph(7, 4 * W, 3000)
    with pytest.raises(ValueError, match="vpad_cap"):
        pair_relabel(g, 2, pair_threshold=4, vpad_cap=0.5)


def test_occurrence_index_no_int64_alias():
    """pair ids past 2^31 (real at RMAT25/np4) must not alias: the old
    packed pair*2^32+slot key wrapped mod 2^64, merging groups that
    share a slot and differ by exactly k*2^32 in pair id — dropping
    edges at delivery.  occurrence_index must keep them separate."""
    from lux_tpu.ops.pairs import occurrence_index

    base = np.int64(25_000_000_000)           # > 2^32: wraps if packed
    pair = np.array([base, base + (1 << 32), base, base + (1 << 32),
                     base], np.int64)
    slot = np.array([7, 7, 7, 7, 7], np.int64)
    occ = occurrence_index(pair, slot)
    # group {0,2,4} -> 0,1,2 and group {1,3} -> 0,1 (any order within)
    assert sorted(occ[[0, 2, 4]].tolist()) == [0, 1, 2]
    assert sorted(occ[[1, 3]].tolist()) == [0, 1]


def test_pair_plan_occurrence_cap_path():
    """Duplicate (multigraph) edges past max_occ ride the residual;
    the kept set re-derives occurrences (the cap-rebuild path) and
    the delivered-lane invariant holds."""
    from lux_tpu.ops.pairs import build_pair_plan

    ne_dup = 40
    src = np.full(ne_dup, 3, np.int64)        # one (pair, slot) group
    dst = np.full(ne_dup, 5, np.int64)
    # plus a normal dense pair to keep the plan non-trivial
    src2 = np.arange(16, dtype=np.int64)
    dst2 = np.arange(16, dtype=np.int64) + 128
    plan = build_pair_plan(np.concatenate([src, src2]),
                           np.concatenate([dst, dst2]),
                           vpad=256, threshold=8, max_occ=8)
    # 8 of the 40 duplicates kept, 32 residual; dense pair fully kept
    assert int(plan.residual.sum()) == 32
    assert plan.stats["covered"] == 8 + 16


def test_min_fill_drops_skinny_rows():
    """Occupancy-aware packing: rows below min_fill live lanes move
    their edges to the residual (they cost ~150 ns/row but deliver
    under break-even); pair + residual still partition the edges."""
    rng = np.random.default_rng(11)
    vpad = 2 * W
    # pair A: 40 distinct sources in tile 0 -> dst tile 0, 1 edge each
    # (one fat, fully-fillable row) ... PLUS one source with 6 edges
    # (occurrences 1..5 ride 5 skinny rows without min_fill)
    srcA = np.concatenate([np.arange(40), np.full(5, 3)])
    dstA = np.concatenate([rng.integers(0, W, 40),
                           55 + np.arange(5)])
    # pair B: 16 edges all from ONE source (16 rows x 1 lane each —
    # pure waste; min_fill must drop the whole pair)
    srcB = np.full(16, W + 7)
    dstB = np.arange(16)
    src = np.concatenate([srcA, srcB])
    dst = np.concatenate([dstA, dstB])
    state = rng.random(4 * W)
    want = full_oracle(src, dst, state, vpad)

    base = build_pair_plan(src, dst, vpad, threshold=8)
    packed = build_pair_plan(src, dst, vpad, threshold=8, min_fill=8)
    # the fat row survives; the 5 occurrence-tail rows and all 16
    # one-lane rows are gone
    assert packed.stats["R"] < base.stats["R"]
    assert packed.stats["R"] == 1
    # occ level 0 carries one edge per live source = 40 (source 3's
    # occ-0 edge is among them); its occ 1..5 tail is dropped
    assert packed.stats["covered"] == 40
    # partition still exact
    got = pair_reduce_numpy(packed, state)
    res = packed.residual
    got += full_oracle(src[res], dst[res], state, vpad)
    np.testing.assert_allclose(got, want)


def test_min_fill_monotone_fill_cap():
    """Random graphs: every surviving row must have >= min_fill live
    lanes, and the engine result must stay oracle-exact."""
    from lux_tpu.apps import pagerank
    from lux_tpu.convert import uniform_random_edges
    from lux_tpu.graph import Graph

    src, dst = uniform_random_edges(512, 9000, seed=3)
    g = Graph.from_edges(src, dst, 512)
    for mf in (4, 16):
        plan = build_pair_plan(*_edges_of(g), 512, threshold=4,
                               min_fill=mf)
        fills = (plan.rel_dst != -1).sum(axis=1)
        live = fills[fills > 0]
        assert (live >= mf).all() or plan.stats["R"] == 0
        eng = pagerank.build_engine(g, num_parts=2, pair_threshold=4,
                                    pair_min_fill=mf)
        want = pagerank.reference_pagerank(g, 3)
        got = eng.unpad(eng.run(eng.init_state(), 3))
        np.testing.assert_allclose(got, want, rtol=5e-5, atol=1e-9)


def _edges_of(g):
    """(src_slot, dst_local) of a 1-part build of g (vpad 512)."""
    from lux_tpu.graph import ShardedGraph
    sg = ShardedGraph.build(g, 1)
    nep = int(sg.ne_part[0])
    return sg.src_slot[0, :nep], sg.dst_local[0, :nep]
