"""Pair-lane planner: covered + residual must exactly partition the
edges, and the oracle reduce over pair rows plus a plain reduce over
residual edges must equal the full-graph reduce."""

import numpy as np
import pytest

from lux_tpu.ops.pairs import W, build_pair_plan, pair_reduce_numpy


def full_oracle(src_slot, dst_local, state, vpad):
    out = np.zeros(vpad)
    for s, d in zip(src_slot, dst_local):
        out[d] += state[s]
    return out


@pytest.mark.parametrize("seed,threshold", [(1, 2), (2, 4), (3, 8)])
def test_pair_plus_residual_equals_full(seed, threshold):
    rng = np.random.default_rng(seed)
    vpad = 4 * W
    n_state_rows = 6
    ne = 6000
    # skew sources so dense pairs exist
    src = (rng.zipf(1.4, ne) - 1) % (n_state_rows * W)
    dst = rng.integers(0, vpad, ne)
    plan = build_pair_plan(src, dst, vpad, threshold=threshold)
    state = rng.random(n_state_rows * W)

    # partition property
    assert plan.stats["covered"] + plan.residual.sum() == ne
    if threshold <= 4:
        assert plan.stats["covered"] > 0

    got = pair_reduce_numpy(plan, state)
    res = plan.residual
    got_res = full_oracle(src[res], dst[res], state, vpad)
    want = full_oracle(src, dst, state, vpad)
    np.testing.assert_allclose(got + got_res, want, rtol=1e-9)


def test_multiplicity_rows():
    # one source hitting one dst tile many times forces occurrence rows
    src = np.full(10, 5)
    dst = np.arange(10) % 3          # some duplicate dsts too
    state = np.arange(4 * W, dtype=np.float64)
    want = full_oracle(src, dst, state, 2 * W)

    # uncapped: fully covered
    plan = build_pair_plan(src, dst, 2 * W, threshold=2, max_occ=16)
    got = pair_reduce_numpy(plan, state)
    np.testing.assert_allclose(got, want)
    assert plan.residual.sum() == 0

    # occurrence cap pushes deep multi-edges to the residual, and
    # pair + residual still partition correctly
    plan = build_pair_plan(src, dst, 2 * W, threshold=2, max_occ=4)
    assert plan.residual.sum() == 6      # occ 4..9 of one source
    got = pair_reduce_numpy(plan, state)
    res = plan.residual
    got += full_oracle(src[res], dst[res], state, 2 * W)
    np.testing.assert_allclose(got, want)


def test_engine_pair_path_matches_plain():
    """PageRank with pair-lane delivery must equal the plain engine."""
    from lux_tpu.apps import pagerank
    from lux_tpu.convert import uniform_random_edges
    from lux_tpu.graph import Graph

    rng = np.random.default_rng(7)
    nv = 3 * W
    src = (rng.zipf(1.3, 4000) - 1) % nv
    dst = (rng.zipf(1.2, 4000) - 1) % nv
    g = Graph.from_edges(src.astype(np.uint32), dst.astype(np.uint32),
                         nv)
    g2, perm = pagerank.degree_relabel(g)

    plain = pagerank.run(g, 8)
    eng = pagerank.build_engine(g2, pair_threshold=4)
    assert eng.pairs is not None and eng.pairs.stats["covered"] > 0
    got_perm = eng.unpad(eng.run(eng.init_state(), 8))
    got = np.empty_like(got_perm)
    got[perm] = got_perm                   # back to original ids
    np.testing.assert_allclose(got, plain, rtol=1e-5)


def test_pair_path_applies_edge_value():
    """Programs transforming src values must agree between pair rows
    and the residual path."""
    from lux_tpu.engine.program import PullProgram
    from lux_tpu.engine.pull import PullEngine
    from lux_tpu.graph import Graph, ShardedGraph

    rng = np.random.default_rng(17)
    nv = 2 * W
    src = (rng.zipf(1.3, 2000) - 1) % nv
    dst = rng.integers(0, nv, 2000)
    g = Graph.from_edges(src.astype(np.uint32), dst.astype(np.uint32),
                         nv)

    def mk():
        return PullProgram(
            reduce="sum",
            edge_value=lambda s, d, w: s * 2.0 + 1.0,
            apply=lambda o, r, c: r,
            init=lambda sg: np.linspace(
                0, 1, sg.num_parts * sg.vpad,
                dtype=np.float32).reshape(sg.num_parts, sg.vpad))

    sgp = ShardedGraph.build(g, 1, vpad_align=128)
    plain = PullEngine(sgp, mk())
    pair = PullEngine(sgp, mk(), pair_threshold=2)
    assert pair.pairs is not None
    out_a = plain.unpad(plain.step(plain.init_state()))
    out_b = pair.unpad(pair.step(pair.init_state()))
    np.testing.assert_allclose(out_b, out_a, rtol=1e-5)


def test_pair_path_rejects_dst_programs():
    import pytest
    from lux_tpu.engine.program import PullProgram
    from lux_tpu.engine.pull import PullEngine
    from lux_tpu.graph import Graph, ShardedGraph
    src = np.zeros(40, np.uint32)
    dst = (np.arange(40) % 7).astype(np.uint32)
    g = Graph.from_edges(src, dst, 2 * W)
    sg = ShardedGraph.build(g, 1, vpad_align=128)
    prog = PullProgram(reduce="sum",
                       edge_value=lambda s, d, w: s * d,
                       apply=lambda o, r, c: r,
                       init=lambda sg: np.zeros(
                           (sg.num_parts, sg.vpad), np.float32),
                       needs_dst=True)
    with pytest.raises(ValueError, match="source"):
        PullEngine(sg, prog, pair_threshold=2)
