"""The Pallas chunk-partial kernel, exercised off-TPU via interpret
mode (reduce_method='pallas-interpret'), must match the XLA
formulation and the NumPy oracle for every reduce kind."""

import numpy as np
import pytest

from lux_tpu.apps import pagerank
from lux_tpu.convert import uniform_random_edges
from lux_tpu.engine.pull import PullEngine
from lux_tpu.graph import Graph, ShardedGraph
from lux_tpu.ops.pallas_reduce import chunk_partials_pallas
from lux_tpu.ops.tiled import chunk_partials


def numpy_partials(vals, rel, W, kind):
    """Independent NumPy oracle for the per-chunk partial reduce."""
    C, E = vals.shape
    ident = {"sum": 0.0, "min": np.inf, "max": -np.inf}[kind]
    op = {"sum": np.add, "min": np.minimum, "max": np.maximum}[kind]
    out = np.full((C, W), ident, np.float64)
    for c in range(C):
        for e in range(E):
            if rel[c, e] < W:
                out[c, rel[c, e]] = op(out[c, rel[c, e]], vals[c, e])
    return out


@pytest.mark.parametrize("kind", ["sum", "min", "max"])
def test_kernel_matches_numpy_oracle_and_xla(kind):
    rng = np.random.default_rng(11)
    C, E, W = 16, 64, 128
    vals = rng.random((C, E)).astype(np.float32)
    # int16 is what TiledLayout/PairPlan ship to the kernel
    rel = np.sort(rng.integers(0, W + 1, (C, E)), axis=1).astype(np.int16)
    got = np.asarray(chunk_partials_pallas(vals, rel, W, kind,
                                           interpret=True))
    want = numpy_partials(vals, rel, W, kind)
    fin = np.isfinite(want)
    np.testing.assert_allclose(got[fin], want[fin], rtol=1e-6)
    # lanes with no contribution must hold the identity
    from lux_tpu.ops.segment import identity_for
    ident = float(identity_for(kind, np.dtype(np.float32)))
    np.testing.assert_array_equal(got[~fin],
                                  np.full((~fin).sum(), ident))
    xla = np.asarray(chunk_partials(vals, rel, W, kind))
    np.testing.assert_allclose(got, xla, rtol=1e-6)


def test_engine_pallas_interpret_matches_xla():
    src, dst = uniform_random_edges(150, 1200, seed=12)
    g = Graph.from_edges(src, dst, 150)
    sg = ShardedGraph.build(g, 2)
    prog = pagerank.make_program()
    e_xla = PullEngine(sg, prog, reduce_method="xla")
    e_pal = PullEngine(sg, prog, reduce_method="pallas-interpret")
    out_x = e_xla.unpad(e_xla.run(e_xla.init_state(), 6))
    out_p = e_pal.unpad(e_pal.run(e_pal.init_state(), 6))
    np.testing.assert_allclose(out_p, out_x, rtol=1e-6)
