"""Tiled scatter-free segment reduction vs. the scatter oracle.

The tiled layout (ops/tiled.py) must be bit-compatible in structure
with ``ops.segment.segment_reduce`` for every reduction kind, payload
rank, skew pattern, and partition count — it replaces the hot loop.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from lux_tpu.convert import rmat_edges, uniform_random_edges
from lux_tpu.graph import Graph, ShardedGraph
from lux_tpu.ops.segment import segment_reduce
from lux_tpu.ops.tiled import TiledLayout, tiled_segment_reduce


def _sharded(nv, ne, num_parts, seed=0):
    src, dst = uniform_random_edges(nv, ne, seed=seed)
    g = Graph.from_edges(src, dst, nv)
    return ShardedGraph.build(g, num_parts)


def _oracle(msgs, sg, p, kind):
    return np.asarray(segment_reduce(
        jnp.asarray(msgs), jnp.asarray(sg.dst_local[p]),
        sg.vpad + 1, kind)[:sg.vpad])


@pytest.mark.parametrize("kind", ["sum", "min", "max"])
@pytest.mark.parametrize("num_parts", [1, 3])
def test_matches_scatter_oracle(kind, num_parts):
    sg = _sharded(300, 2500, num_parts)
    lay = TiledLayout.build(sg.row_ptr_local, sg.dst_local, sg.vpad,
                            W=16, E=32)
    rng = np.random.default_rng(0)
    msgs_flat = rng.random((sg.num_parts, sg.epad)).astype(np.float32)
    # padding edges must carry the identity in the flat oracle too
    if kind != "sum":
        ident = np.inf if kind == "min" else -np.inf
        msgs_flat = np.where(sg.dst_local < sg.vpad, msgs_flat, ident)
    else:
        msgs_flat = np.where(sg.dst_local < sg.vpad, msgs_flat, 0.0)
    msgs_ch = lay.chunk(msgs_flat)
    for p in range(sg.num_parts):
        got = np.asarray(tiled_segment_reduce(
            jnp.asarray(msgs_ch[p]), lay, jnp.asarray(lay.chunk_start[p]),
            jnp.asarray(lay.last_chunk[p]), jnp.asarray(lay.rel_dst[p]),
            sg.vpad, kind))
        want = _oracle(msgs_flat[p], sg, p, kind)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_skewed_hub_graph_needs_scan():
    """A hub vertex forces multi-chunk tiles; scan path must be exact."""
    nv, ne = 64, 5000
    rng = np.random.default_rng(1)
    dst = np.where(rng.random(ne) < 0.6, 7,
                   rng.integers(0, nv, ne)).astype(np.uint32)
    src = rng.integers(0, nv, ne, dtype=np.uint32)
    g = Graph.from_edges(src, dst, nv)
    sg = ShardedGraph.build(g, 2)
    lay = TiledLayout.build(sg.row_ptr_local, sg.dst_local, sg.vpad,
                            W=8, E=16)
    assert lay.needs_scan
    msgs = np.where(sg.dst_local < sg.vpad, 1.0, 0.0).astype(np.float32)
    msgs_ch = lay.chunk(msgs)
    for p in range(sg.num_parts):
        got = np.asarray(tiled_segment_reduce(
            jnp.asarray(msgs_ch[p]), lay, jnp.asarray(lay.chunk_start[p]),
            jnp.asarray(lay.last_chunk[p]), jnp.asarray(lay.rel_dst[p]),
            sg.vpad, "sum"))
        want = _oracle(msgs[p], sg, p, "sum")
        np.testing.assert_allclose(got, want, rtol=1e-6)


@pytest.mark.parametrize("use_mxu", [False, True])
def test_vector_payload(use_mxu):
    """Colfilter-style [., K] payloads, both VPU and MXU strategies."""
    sg = _sharded(120, 900, 2, seed=3)
    lay = TiledLayout.build(sg.row_ptr_local, sg.dst_local, sg.vpad,
                            W=16, E=64)
    K = 5
    rng = np.random.default_rng(2)
    msgs = rng.random((sg.num_parts, sg.epad, K)).astype(np.float32)
    msgs = np.where((sg.dst_local < sg.vpad)[..., None], msgs, 0.0)
    msgs_ch = lay.chunk(msgs)
    for p in range(sg.num_parts):
        got = np.asarray(tiled_segment_reduce(
            jnp.asarray(msgs_ch[p]), lay, jnp.asarray(lay.chunk_start[p]),
            jnp.asarray(lay.last_chunk[p]), jnp.asarray(lay.rel_dst[p]),
            sg.vpad, "sum", use_mxu=use_mxu))
        want = np.asarray(segment_reduce(
            jnp.asarray(msgs[p]), jnp.asarray(sg.dst_local[p]),
            sg.vpad + 1, "sum")[:sg.vpad])
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_rmat_roundtrip_int():
    """Integer min-reduction (SSSP labels) on a power-law graph."""
    src, dst, nv = rmat_edges(scale=8, edge_factor=6, seed=5)
    g = Graph.from_edges(src, dst, nv)
    sg = ShardedGraph.build(g, 4)
    lay = TiledLayout.build(sg.row_ptr_local, sg.dst_local, sg.vpad,
                            W=32, E=128)
    rng = np.random.default_rng(4)
    msgs = rng.integers(0, 1000, (sg.num_parts, sg.epad)).astype(np.int32)
    msgs = np.where(sg.dst_local < sg.vpad, msgs,
                    np.iinfo(np.int32).max)
    msgs_ch = lay.chunk(msgs)
    for p in range(sg.num_parts):
        got = np.asarray(tiled_segment_reduce(
            jnp.asarray(msgs_ch[p]), lay, jnp.asarray(lay.chunk_start[p]),
            jnp.asarray(lay.last_chunk[p]), jnp.asarray(lay.rel_dst[p]),
            sg.vpad, "min"))
        want = _oracle(msgs[p], sg, p, "min")
        np.testing.assert_array_equal(got, want)


def test_rejects_wide_tiles():
    """rel_dst is int8 (lane offsets 0..127, -1 pad): W > 128 would
    wrap offsets negative and silently drop edges (ADVICE r3)."""
    import pytest
    from lux_tpu.graph import Graph, ShardedGraph
    from lux_tpu.ops.tiled import TiledLayout

    rng = np.random.default_rng(3)
    g = Graph.from_edges(rng.integers(0, 300, 2000),
                         rng.integers(0, 300, 2000), 300)
    sg = ShardedGraph.build(g, 2)
    with pytest.raises(ValueError, match="W=256 > 128"):
        TiledLayout.build(sg.row_ptr_local, sg.dst_local, sg.vpad,
                          W=256, E=64)


@pytest.mark.parametrize("kind", ["sum", "min", "max"])
@pytest.mark.parametrize("trail", [(), (5,)])
def test_blocked_segscan_matches_monolithic(kind, trail):
    """_segscan_blocked must equal the monolithic associative scan for
    every reduce kind, ragged segment patterns, block-boundary
    straddles, and vector payloads — it replaces the scan whose
    O(log C) tree OOMs 16 GB chips at C ~ 1.4M (PERF_NOTES r4)."""
    from lux_tpu.ops.tiled import _segscan, _segscan_blocked

    rng = np.random.default_rng(11)
    C = 300                                   # not a block multiple
    vals = jnp.asarray(rng.random((C,) + trail).astype(np.float32))
    flags = rng.random(C) < 0.07              # long segments straddle
    flags[0] = True
    fl = jnp.asarray(flags)
    fb = fl.reshape((C,) + (1,) * len(trail))
    want = np.asarray(_segscan(vals, fb, kind))
    for block in (7, 64, 512):
        got = np.asarray(_segscan_blocked(vals, fl, kind, block=block))
        np.testing.assert_allclose(got, want, rtol=1e-6)


def test_combine_chunks_blocked_engages(monkeypatch):
    """Above the threshold the engine output is unchanged."""
    import lux_tpu.ops.tiled as tiled
    from lux_tpu.apps import pagerank
    from lux_tpu.graph import Graph

    rng = np.random.default_rng(5)
    nv, ne = 700, 30000
    src = (rng.zipf(1.3, ne) - 1) % nv
    dst = (rng.zipf(1.2, ne) - 1) % nv
    g = Graph.from_edges(src.astype(np.uint32), dst.astype(np.uint32),
                         nv)
    want = pagerank.run(g, 6)
    monkeypatch.setattr(tiled, "SCAN_BLOCKED_ABOVE", 4)
    monkeypatch.setattr(tiled, "SCAN_BLOCK_CHUNKS", 8)
    got = pagerank.run(g, 6)
    np.testing.assert_allclose(got, want, rtol=1e-6)
    # and through the owner exchange (the config that OOM'd)
    eng = pagerank.build_engine(g, num_parts=4, exchange="owner",
                                owner_tile_e=8)
    got_o = eng.unpad(eng.run(eng.init_state(), 6))
    np.testing.assert_allclose(got_o, want, rtol=1e-6)
