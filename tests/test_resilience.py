"""Resilience layer: crash classification, supervised retry/resume,
deterministic fault injection, duration-budgeted segments, and the
bench outlier discard-and-rerun rule (round-6 ISSUE tentpole).

The scenarios mirror the tunnel's real failure modes (PERF_NOTES
round 5): a transient TPU worker death mid-run, a NaN-corrupted
segment, and a 10x-collapsed bench sample — each is injected
deterministically (lux_tpu/faults.py) and must recover to the NumPy
oracle's answer.
"""

import os

import numpy as np
import pytest

from lux_tpu import checkpoint as ckpt
from lux_tpu import debug, faults, resilience
from lux_tpu.apps import pagerank, sssp
from lux_tpu.convert import uniform_random_edges
from lux_tpu.graph import Graph
from lux_tpu.segmented import DurationBudget

NOSLEEP = dict(sleep=lambda s: None)


# -- classification ----------------------------------------------------

@pytest.mark.parametrize("exc,want", [
    (faults.InjectedWorkerCrash("boom"), resilience.RETRYABLE),
    (debug.DivergenceError("NaN escape"), resilience.RETRYABLE),
    (debug.StallError("no progress"), resilience.FATAL),
    (ConnectionError("tunnel dropped"), resilience.RETRYABLE),
    (TimeoutError("deadline"), resilience.RETRYABLE),
    (OSError("broken pipe to worker"), resilience.RETRYABLE),
    (RuntimeError("connection reset by peer"), resilience.RETRYABLE),
    (RuntimeError("TPU worker terminated unexpectedly"),
     resilience.RETRYABLE),
    (RuntimeError("HTTP 413 request entity too large"),
     resilience.FATAL),
    (RuntimeError("RESOURCE_EXHAUSTED: out of memory"),
     resilience.FATAL),
    (ValueError("bad argument"), resilience.FATAL),
    # deterministic filesystem errors (bad -resume path) never retry
    (FileNotFoundError(2, "No such file or directory"),
     resilience.FATAL),
    (PermissionError(13, "Permission denied"), resilience.FATAL),
    # round 9: a corrupt checkpoint retries INTO generation fallback
    # (load_any); a tripped health watchdog is fatal-with-diagnosis
    (ckpt.CorruptCheckpointError("/tmp/x.npz", "leaf 0 CRC"),
     resilience.RETRYABLE),
])
def test_classify(exc, want):
    assert resilience.classify(exc) == want


def test_classify_fatal_wins_over_transient_words():
    # an OOM whose message also mentions the worker must NOT retry
    e = RuntimeError("worker failed to allocate 3.1G (out of memory)")
    assert resilience.classify(e) == resilience.FATAL


def test_classify_typed_transport_beats_fatal_words():
    # a typed transport error is transient no matter what its message
    # says ("payload"/"too large" can appear in tunnel write errors)
    e = ConnectionError("aborted while writing request payload "
                        "(chunk too large for socket buffer)")
    assert resilience.classify(e) == resilience.RETRYABLE


def test_classify_413_needs_word_boundary():
    # "413" inside a request id / byte count must not condemn a
    # transient worker failure
    e = RuntimeError("worker terminated, request id 8413725")
    assert resilience.classify(e) == resilience.RETRYABLE
    assert resilience.classify(
        RuntimeError("compile rejected: HTTP 413")) == resilience.FATAL


# -- supervise (retry loop) --------------------------------------------

def test_supervise_retries_then_succeeds():
    calls = []

    def attempt(k):
        calls.append(k)
        if k < 2:
            raise ConnectionError("tunnel dropped")
        return "ok"

    policy = resilience.RetryPolicy(retries=3, **NOSLEEP)
    result, report = resilience.supervise(attempt, policy)
    assert result == "ok" and calls == [0, 1, 2]
    assert report.attempts == 3
    assert [f[2] for f in report.failures] == [resilience.RETRYABLE] * 2


def test_supervise_fatal_raises_immediately():
    calls = []

    def attempt(k):
        calls.append(k)
        raise ValueError("deterministic bug")

    with pytest.raises(ValueError):
        resilience.supervise(
            attempt, resilience.RetryPolicy(retries=5, **NOSLEEP))
    assert calls == [0]


def test_supervise_exhaustion_reraises_last():
    with pytest.raises(ConnectionError):
        resilience.supervise(
            lambda k: (_ for _ in ()).throw(ConnectionError("down")),
            resilience.RetryPolicy(retries=2, **NOSLEEP))


def test_retry_policy_backoff():
    # jitter=0: the exact exponential schedule (round 11 made
    # decorrelated jitter the default — see tests/test_elastic.py)
    p = resilience.RetryPolicy(backoff_s=1.0, backoff_factor=2.0,
                               max_backoff_s=5.0, jitter=0)
    assert [p.delay_s(k) for k in range(4)] == [1.0, 2.0, 4.0, 5.0]
    # the default (jittered) schedule stays within the same envelope
    j = resilience.RetryPolicy(backoff_s=1.0, backoff_factor=2.0,
                               max_backoff_s=5.0, jitter_seed=3)
    assert all(1.0 <= j.delay_s(k) <= 5.0 for k in range(4))


# -- fault plans -------------------------------------------------------

def test_seeded_plan_is_deterministic():
    a = faults.FaultPlan.seeded(7, n=32, p_crash=0.3, p_nan=0.2)
    b = faults.FaultPlan.seeded(7, n=32, p_crash=0.3, p_nan=0.2)
    assert a.schedule == b.schedule and a.schedule  # non-empty


def test_plan_counter_never_refires():
    plan = faults.FaultPlan(schedule={1: faults.CRASH})
    s = np.zeros(3, np.float32)
    assert plan.fire(s) is None            # boundary 0
    with pytest.raises(faults.InjectedWorkerCrash):
        plan.fire(s)                       # boundary 1: crash
    assert plan.fire(s) is None            # boundary 2: past it
    assert plan.fired == [(1, faults.CRASH)]


def test_nan_corrupt_pokes_first_float_leaf():
    state = (np.arange(4, dtype=np.int32),
             np.ones(5, dtype=np.float32))
    out = faults.nan_corrupt(state, count=2)
    np.testing.assert_array_equal(out[0], state[0])
    assert np.isnan(out[1][:2]).all() and np.isfinite(out[1][2:]).all()
    with pytest.raises(ValueError):
        faults.nan_corrupt((np.arange(3),))  # no float leaf


def test_int_corrupt_pokes_sentinel():
    """The one-sentinel convention: integer-labeled states corrupt by
    poking the program's identity (a lost update), skipping bool
    leaves (the active mask)."""
    state = (np.array([True, False]),
             np.arange(6, dtype=np.int32))
    out = faults.int_corrupt(state, count=2, value=-1)
    np.testing.assert_array_equal(out[0], state[0])
    np.testing.assert_array_equal(out[1], [-1, -1, 2, 3, 4, 5])
    with pytest.raises(ValueError, match="sentinel"):
        faults.int_corrupt(state, count=1)      # value required
    with pytest.raises(ValueError):
        faults.int_corrupt((np.ones(3, np.float32),), value=0)


def test_corrupt_state_is_type_appropriate():
    fl = faults.corrupt_state((np.ones(4, np.float32),), count=1)
    assert np.isnan(fl[0][0])
    it = faults.corrupt_state((np.arange(4, dtype=np.int32),),
                              count=1, int_value=7)
    assert it[0][0] == 7


# -- supervised crash recovery vs oracles (the acceptance test) --------

def _pagerank_setup(tmp_path):
    src, dst = uniform_random_edges(100, 700, seed=61)
    g = Graph.from_edges(src, dst, 100)
    eng = pagerank.build_engine(g, num_parts=2)
    return g, eng, str(tmp_path / "pr.npz")


def test_supervised_pull_killed_midrun_resumes_to_oracle(tmp_path):
    """A pagerank run dies at a segment boundary (injected worker
    crash); the supervisor auto-resumes from the last atomic
    checkpoint and the result still matches the NumPy oracle."""
    g, eng, path = _pagerank_setup(tmp_path)
    plan = faults.FaultPlan(schedule={1: faults.CRASH})
    state, report = resilience.supervised_run(
        eng, 10, path, segment=3, faults=plan,
        policy=resilience.RetryPolicy(retries=2, **NOSLEEP))
    np.testing.assert_allclose(
        eng.unpad(state), pagerank.reference_pagerank(g, 10),
        rtol=1e-5)
    assert report.attempts == 2
    assert plan.fired == [(1, faults.CRASH)]
    # the crash hit boundary 1 (iteration 6) BEFORE its save, so the
    # resume restarted from the iteration-3 checkpoint
    assert report.resumed_from == [3]
    assert [f[0] for f in report.failures] == ["InjectedWorkerCrash"]


def test_supervised_pull_nan_corruption_resumes_clean(tmp_path):
    """A segment output comes back NaN-corrupted; the finite guard
    raises BEFORE the save (the checkpoint stays clean), the failure
    classifies retryable, and the resume converges to the oracle."""
    g, eng, path = _pagerank_setup(tmp_path)
    plan = faults.FaultPlan(schedule={1: faults.NAN})
    state, report = resilience.supervised_run(
        eng, 10, path, segment=3, faults=plan,
        policy=resilience.RetryPolicy(retries=2, **NOSLEEP))
    np.testing.assert_allclose(
        eng.unpad(state), pagerank.reference_pagerank(g, 10),
        rtol=1e-5)
    assert report.attempts == 2
    assert [f[0] for f in report.failures] == ["DivergenceError"]
    assert report.resumed_from == [3]


def test_supervised_pull_repeated_crashes_exhaust_budget(tmp_path):
    g, eng, path = _pagerank_setup(tmp_path)
    plan = faults.FaultPlan(
        schedule={i: faults.CRASH for i in range(20)})
    with pytest.raises(faults.InjectedWorkerCrash):
        resilience.supervised_run(
            eng, 10, path, segment=3, faults=plan,
            policy=resilience.RetryPolicy(retries=2, **NOSLEEP))


def test_supervised_converge_killed_midway_resumes_to_oracle(tmp_path):
    """Push-engine convergence dies mid-way (the round-5 transient
    worker crash), auto-resumes from checkpoint, matches the
    Bellman-Ford oracle."""
    src, dst = uniform_random_edges(200, 1500, seed=62)
    g = Graph.from_edges(src, dst, 200)
    eng = sssp.build_engine(g, start_vertex=0, num_parts=2)
    path = str(tmp_path / "ss.npz")
    plan = faults.FaultPlan(schedule={1: faults.CRASH})
    label, _active, total, report = resilience.supervised_converge(
        eng, path, segment=2, faults=plan,
        policy=resilience.RetryPolicy(retries=2, **NOSLEEP))
    got = eng.unpad(label)
    want = sssp.reference_sssp(g, 0)
    reach = ~sssp.unreachable(got)
    np.testing.assert_array_equal(got[reach], want[reach])
    np.testing.assert_array_equal(reach, np.isfinite(want))
    assert report.attempts == 2 and total > 0
    assert report.resumed_from and report.resumed_from[0] >= 2


def test_supervised_run_fresh_start_clears_stale_checkpoint(tmp_path):
    g, eng, path = _pagerank_setup(tmp_path)
    ckpt.save(path, (np.zeros(4, np.float32),),
              {"iter": 99, "kind": "pull"})
    state, report = resilience.supervised_run(
        eng, 6, path, segment=3,
        policy=resilience.RetryPolicy(retries=0, **NOSLEEP))
    np.testing.assert_allclose(
        eng.unpad(state), pagerank.reference_pagerank(g, 6),
        rtol=1e-5)
    assert report.resumed_from == [] and report.attempts == 1
    _leaves, meta = ckpt.load(path)
    assert meta["iter"] == 6


def test_resume_rejects_mismatched_checkpoint(tmp_path):
    """A checkpoint from a different graph/scale must ERROR, not
    resume silently (XLA's clamping gathers would hide it)."""
    g, eng, path = _pagerank_setup(tmp_path)
    ckpt.save(path, (np.zeros(7, np.float32),),
              {"iter": 3, "kind": "pull"})
    with pytest.raises(ValueError, match="different graph"):
        resilience.supervised_run(
            eng, 6, path, segment=3, resume=True,
            policy=resilience.RetryPolicy(retries=0, **NOSLEEP))


def test_supervised_run_explicit_resume(tmp_path):
    """resume=True continues an interrupted run from its checkpoint
    (the cli.py -resume flag's path)."""
    g, eng, path = _pagerank_setup(tmp_path)
    # first run "preempted" after 4 of 10 iterations
    resilience.supervised_run(
        eng, 4, path, segment=2,
        policy=resilience.RetryPolicy(retries=0, **NOSLEEP))
    state, report = resilience.supervised_run(
        eng, 10, path, segment=4, resume=True,
        policy=resilience.RetryPolicy(retries=0, **NOSLEEP))
    np.testing.assert_allclose(
        eng.unpad(state), pagerank.reference_pagerank(g, 10),
        rtol=1e-5)
    assert report.resumed_from == [4]


# -- checkpoint corruption -> generation fallback (round 9) ------------

def _plain_pagerank_state(g, ni):
    eng = pagerank.build_engine(g, num_parts=2)
    return eng.unpad(eng.run(eng.init_state(), ni))


@pytest.mark.parametrize("action", [faults.CKPT_BITFLIP,
                                    faults.CKPT_TRUNCATE])
def test_supervised_pull_corrupt_checkpoint_falls_back(tmp_path,
                                                       action):
    """The torn-write scenario: the newest checkpoint generation is
    corrupted and the worker dies.  The retry's resume detects the
    corruption (CRC / typed container error), falls back one
    generation, replays the lost segment, and the final state is
    BITWISE the uninterrupted run's."""
    from lux_tpu import telemetry

    g, eng, path = _pagerank_setup(tmp_path)
    # boundary 2: generations iter-3 (.prev) and iter-6 exist; the
    # newest is corrupted + crash -> fallback resumes from 3
    plan = faults.FaultPlan(schedule={2: action})
    ev = telemetry.EventLog()
    with telemetry.use(events=ev):
        state, report = resilience.supervised_run(
            eng, 10, path, segment=3, faults=plan,
            policy=resilience.RetryPolicy(retries=2, **NOSLEEP))
    np.testing.assert_array_equal(eng.unpad(state),
                                  _plain_pagerank_state(g, 10))
    assert report.attempts == 2
    assert plan.fired == [(2, action)]
    assert report.resumed_from == [3]      # the FALLBACK generation
    assert ev.counts().get("checkpoint_fallback", 0) >= 1
    assert ckpt.load(path)[1]["iter"] == 10


def test_supervised_converge_corrupt_checkpoint_falls_back(tmp_path):
    src, dst = uniform_random_edges(200, 1500, seed=62)
    g = Graph.from_edges(src, dst, 200)
    eng = sssp.build_engine(g, start_vertex=0, num_parts=2)
    path = str(tmp_path / "ss.npz")
    plan = faults.FaultPlan(schedule={2: faults.CKPT_TRUNCATE})
    label, _active, total, report = resilience.supervised_converge(
        eng, path, segment=2, faults=plan,
        policy=resilience.RetryPolicy(retries=2, **NOSLEEP))
    got = eng.unpad(label)
    want = sssp.reference_sssp(g, 0)
    reach = ~sssp.unreachable(got)
    np.testing.assert_array_equal(got[reach], want[reach])
    assert report.attempts == 2 and plan.fired
    assert report.resumed_from and report.resumed_from[0] >= 2


def test_corrupt_only_generation_exhausts_retries(tmp_path):
    """With no second generation to fall back to, a corrupt newest
    checkpoint surfaces LOUDLY (typed, after the retry budget) —
    never a silent fresh restart."""
    g, eng, path = _pagerank_setup(tmp_path)
    plan = faults.FaultPlan(schedule={1: faults.CKPT_BITFLIP})
    with pytest.raises(ckpt.CorruptCheckpointError):
        resilience.supervised_run(
            eng, 10, path, segment=3, faults=plan,
            policy=resilience.RetryPolicy(retries=2, **NOSLEEP))


def test_seeded_nan_plan_works_on_integer_programs(tmp_path):
    """The round-9 satellite: a seeded plan with p_nan > 0 used to
    crash the harness on integer-labeled programs (sssp hops) with
    nan_corrupt's ValueError.  The supervisor now pokes the program's
    identity sentinel instead; the run completes and at most
    nan_count labels differ from the oracle (the poked cells)."""
    src, dst = uniform_random_edges(200, 1500, seed=62)
    g = Graph.from_edges(src, dst, 200)
    eng = sssp.build_engine(g, start_vertex=0, num_parts=2)
    path = str(tmp_path / "ss.npz")
    plan = faults.FaultPlan(schedule={1: faults.NAN}, nan_count=1)
    label, _active, total, report = resilience.supervised_converge(
        eng, path, segment=2, faults=plan,
        policy=resilience.RetryPolicy(retries=0, **NOSLEEP))
    assert plan.fired == [(1, faults.NAN)]
    got = eng.unpad(label)
    want = sssp.reference_sssp(g, 0)
    reach = ~sssp.unreachable(got)
    mism = int((got[reach] != want[reach]).sum())
    assert mism <= plan.nan_count


# -- duration-budgeted segmentation ------------------------------------

def test_duration_budget_locks_from_warmup_rate():
    b = DurationBudget(budget_s=1.0, probe_n=2, warmup=2,
                       max_segment=4096, headroom=0.8)
    assert b.next_n(100) == 2
    b.observe(2, 10.0)          # first exec carries the compile
    assert b.locked is None
    b.observe(2, 0.1)           # trusted rate: 0.05 s/iter
    assert b.locked == 16       # 0.8 * 1.0 / 0.05
    assert b.next_n(100) == 16
    assert b.next_n(5) == 5     # clamped to remaining


def test_duration_budget_halves_on_overrun():
    b = DurationBudget(budget_s=1.0, probe_n=1, warmup=1)
    b.observe(1, 0.01)
    n = b.locked
    b.observe(n, 5.0)           # first exec at this size: compile-exempt
    assert b.locked == n
    b.observe(n, 5.0)           # genuine overrun
    assert b.locked == n // 2


def test_duration_budget_converge_mode_halves_at_unseen_sizes():
    """per_size_compile=False (push converge: ONE compiled program,
    actual relax counts vary every segment): an overrun halves even
    at a never-seen size — otherwise delta-stepping's fresh counts
    would stay permanently compile-exempt."""
    b = DurationBudget(budget_s=1.0, probe_n=1, warmup=1,
                       per_size_compile=False)
    b.observe(3, 0.01)
    n = b.locked
    b.observe(n - 1, 5.0)       # unseen size, genuine overrun
    assert b.locked == n // 2


def test_duration_budget_rejects_nonpositive():
    with pytest.raises(ValueError):
        DurationBudget(budget_s=0.0)


def test_pull_run_with_duration_budget_matches_oracle(tmp_path):
    g, eng, _ = _pagerank_setup(tmp_path)
    state = eng.run(eng.init_state(), 10, seg_budget=30.0)
    np.testing.assert_allclose(
        eng.unpad(state), pagerank.reference_pagerank(g, 10),
        rtol=1e-5)


def test_push_run_with_duration_budget_matches_oracle():
    src, dst = uniform_random_edges(200, 1500, seed=62)
    g = Graph.from_edges(src, dst, 200)
    eng = sssp.build_engine(g, start_vertex=0, num_parts=2)
    got, iters = eng.run(seg_budget=30.0)
    want = sssp.reference_sssp(g, 0)
    reach = ~sssp.unreachable(got)
    np.testing.assert_array_equal(got[reach], want[reach])
    assert iters > 0


def test_supervised_run_with_budget_checkpoints(tmp_path):
    g, eng, path = _pagerank_setup(tmp_path)
    state, report = resilience.supervised_run(
        eng, 8, path, seg_budget=30.0,
        policy=resilience.RetryPolicy(retries=0, **NOSLEEP))
    np.testing.assert_allclose(
        eng.unpad(state), pagerank.reference_pagerank(g, 8),
        rtol=1e-5)
    assert report.segments >= 1
    _leaves, meta = ckpt.load(path)
    assert meta["iter"] == 8


# -- bench outlier discard-and-rerun (VERDICT r5 #7) -------------------

def test_screen_outliers_discards_planted_collapse():
    """The BENCH_r05 pagerank-mp collapse: [0.1116, 0.0107, 0.1118].
    The 10x-low sample is discarded, re-run once, and reported — not
    silently medianed."""
    reruns = []

    def rerun():
        reruns.append(1)
        return 0.1120

    kept, discarded, attempts = resilience.screen_outliers(
        [0.1116, 0.0107, 0.1118], rerun, factor=3.0)
    assert discarded == [0.0107]
    assert kept == [0.1116, 0.1118, 0.1120]
    assert attempts == 4 and len(reruns) == 1


def test_screen_outliers_collapsed_rerun_is_discarded_too():
    """The rerun gets ONE chance; if it also collapses it joins
    'discarded' — a collapsed rerun must never enter the median."""
    kept, discarded, attempts = resilience.screen_outliers(
        [0.1116, 0.0107, 0.1118], lambda: 0.0109, factor=3.0)
    assert kept == [0.1116, 0.1118]
    assert discarded == [0.0107, 0.0109]
    assert attempts == 4


def test_screen_outliers_clean_batch_untouched():
    kept, discarded, attempts = resilience.screen_outliers(
        [0.11, 0.12, 0.115], lambda: 1/0, factor=3.0)
    assert kept == [0.11, 0.12, 0.115]
    assert discarded == [] and attempts == 3


def test_screen_outliers_disabled_and_degenerate():
    kept, d, a = resilience.screen_outliers([0.1, 0.9], None, factor=0)
    assert kept == [0.1, 0.9] and d == [] and a == 2
    # rerun=None: discard is recorded but no replacement sample
    kept, d, a = resilience.screen_outliers([0.001, 1000.0, 5.0],
                                            None, factor=3.0)
    assert kept == [5.0] and d == [0.001, 1000.0] and a == 3
    # everything-an-outlier backstop (no majority to trust): keep all
    kept, d, a = resilience.screen_outliers([-1.0, 1.0], None,
                                            factor=3.0)
    assert kept == [-1.0, 1.0] and d == []


def test_bench_emit_records_audit_trail(capsys):
    """bench.py's JSON line carries the attempts/discarded audit
    trail after outlier screening (scripts/check_bench.py schema)."""
    import json

    import bench  # repo root is on sys.path when pytest runs there

    samples = [0.1116, 0.0107, 0.1118]
    kept, discarded, attempts = resilience.screen_outliers(
        samples, lambda: 0.1120, factor=3.0)
    bench.emit("pagerank_mp_rmat23", kept,
               {"np": 4, "scale": 23}, attempts=attempts,
               discarded=discarded)
    line = json.loads(capsys.readouterr().out)
    assert line["attempts"] == 4
    assert line["discarded"] == [0.0107]
    assert line["samples"] == [0.1116, 0.1118, 0.112]
    assert line["value"] == 0.1118      # median of KEPT, not of raw
