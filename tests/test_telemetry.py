"""In-loop telemetry (lux_tpu/telemetry.py): device-side iteration
counters against stepwise/NumPy oracles, the structured event log, and
the cross-layer wiring (segmented drivers, supervisor, timing helpers).

The counter contract under test is the acceptance bar of the round-7
ISSUE: the fused run's per-iteration frontier sizes / residuals must
equal what the old stepwise -verbose path printed — computed here by
actually stepping the engines one compiled iteration at a time.
"""

import json

import jax
import numpy as np
import pytest

from lux_tpu import telemetry
from lux_tpu.apps import components, pagerank, sssp
from lux_tpu.convert import uniform_random_edges
from lux_tpu.graph import Graph
from lux_tpu.parallel.mesh import make_mesh


def small_graph(nv=180, ne=1400, seed=7, weighted=False):
    if weighted:
        src, dst, w = uniform_random_edges(nv, ne, seed=seed,
                                           weighted=True)
        return Graph.from_edges(src, dst, nv, weights=w)
    src, dst = uniform_random_edges(nv, ne, seed=seed)
    return Graph.from_edges(src, dst, nv)


def stepwise_push_series(eng):
    """The old stepwise -verbose path: frontier size after each
    compiled step, plus each iteration's entering-frontier out-edges
    from the full graph's degrees (the NumPy side of the oracle)."""
    deg = np.asarray(eng.sg.deg_padded)
    label, active = eng.init_state()
    fronts, edges = [], []
    cnt = int(jax.device_get(np.sum(np.asarray(active))))
    while cnt > 0:
        act_np = np.asarray(jax.device_get(active))
        edges.append(int(deg[act_np].sum()))
        label, active, c = eng.step(label, active)
        cnt = int(jax.device_get(c))
        fronts.append(cnt)
    return fronts, edges


@pytest.mark.parametrize("np_parts,mesh_n", [(1, 0), (8, 8)])
def test_push_classic_counters_match_stepwise(np_parts, mesh_n):
    g = small_graph()
    mesh = make_mesh(mesh_n) if mesh_n else None
    eng = sssp.build_engine(g, start_vertex=1, num_parts=np_parts,
                            mesh=mesh)
    fronts, edges = stepwise_push_series(eng)

    label, active = eng.init_state()
    l2, a2, it, fsz, fed, fszp, fedp = eng.converge_stats(label, active)
    it = int(jax.device_get(it))
    assert it == len(fronts)
    assert np.asarray(fsz)[:it].tolist() == fronts
    assert np.asarray(fed)[:it].tolist() == edges
    # past-the-run entries stay zero, and the labels are the oracle's
    assert not np.asarray(fsz)[it:].any()
    dist = eng.unpad(l2)
    want = sssp.reference_sssp(g, start_vertex=1)
    reach = ~sssp.unreachable(dist)
    np.testing.assert_array_equal(dist[reach], want[reach])


@pytest.mark.parametrize("np_parts,mesh_n", [(1, 0), (8, 8)])
def test_components_counters_match_stepwise(np_parts, mesh_n):
    s, d = small_graph(seed=9).edge_arrays()
    g = Graph.from_edges(*components.symmetrize(s, d), 180)
    mesh = make_mesh(mesh_n) if mesh_n else None
    eng = components.build_engine(g, num_parts=np_parts, mesh=mesh)
    fronts, edges = stepwise_push_series(eng)
    label, active = eng.init_state()
    _l, _a, it, fsz, fed, fszp, fedp = eng.converge_stats(label, active)
    it = int(jax.device_get(it))
    assert np.asarray(fsz)[:it].tolist() == fronts
    assert np.asarray(fed)[:it].tolist() == edges


def test_push_delta_counters_match_timed_phases():
    """Delta engines record each relax step's bucket-front size — the
    exact schedule the instrumented stepwise path
    (timed_phases/_timed_phases_delta) replays."""
    g = small_graph(weighted=True)
    eng = sssp.build_engine(g, start_vertex=0, num_parts=1,
                            weighted=True, delta="auto")
    label, active = eng.init_state()
    _l, _a, it, fsz, _fed, _fp, _ep = eng.converge_stats(label, active)
    it = int(jax.device_get(it))
    lab0, act0 = eng.init_state()
    _l2, _a2, report = eng.timed_phases(lab0, act0, iters=it)
    assert [t["frontier"] for t in report] == \
        np.asarray(fsz)[:it].tolist()


@pytest.mark.parametrize("np_parts,mesh_n", [(1, 0), (8, 8)])
def test_pull_counters_match_stepwise(np_parts, mesh_n):
    g = small_graph(seed=11)
    mesh = make_mesh(mesh_n) if mesh_n else None
    eng = pagerank.build_engine(g, num_parts=np_parts, mesh=mesh)
    prev = np.asarray(jax.device_get(eng.init_state())).copy()
    res_oracle, chg_oracle = [], []
    s = eng.init_state()
    for _ in range(5):
        s = eng.step(s)
        cur = np.asarray(jax.device_get(s)).copy()
        d = np.abs(cur.astype(np.float32) - prev.astype(np.float32))
        res_oracle.append(float(d.max()))
        chg_oracle.append(int((d > 0).sum()))
        prev = cur

    s2, rb, cb, rbp, cbp = eng.run_stats(eng.init_state(), 5)
    np.testing.assert_allclose(np.asarray(rb)[:5], res_oracle,
                               rtol=1e-6)
    assert np.asarray(cb)[:5].tolist() == chg_oracle
    np.testing.assert_array_equal(np.asarray(jax.device_get(s2)), prev)


def test_pull_run_until_stats_matches_run_until():
    g = small_graph(seed=13)
    eng = pagerank.build_engine(g, num_parts=2)
    s1, it1, res1 = eng.run_until(eng.init_state(), 1e-6,
                                  max_iters=50)
    s2, it2, res2, rb, cb, rbp, cbp = eng.run_until_stats(
        eng.init_state(), 1e-6, max_iters=50)
    it1, it2 = int(jax.device_get(it1)), int(jax.device_get(it2))
    assert it1 == it2
    assert float(jax.device_get(res1)) == float(jax.device_get(res2))
    # the residual series ends exactly at the convergence residual,
    # and every earlier entry is above the tolerance
    rbn = np.asarray(rb)[:it2]
    assert rbn[-1] == pytest.approx(float(jax.device_get(res2)))
    assert (rbn[:-1] > 1e-6).all()
    np.testing.assert_array_equal(np.asarray(jax.device_get(s1)),
                                  np.asarray(jax.device_get(s2)))


def test_push_verbose_replays_counters(capsys):
    g = small_graph()
    eng = sssp.build_engine(g, start_vertex=1, num_parts=1)
    fronts, _ = stepwise_push_series(eng)
    eng2 = sssp.build_engine(g, start_vertex=1, num_parts=1)
    _labels, it = eng2.run(verbose=True)
    out = capsys.readouterr().out
    want = [f"iter {i}: frontier={f}" for i, f in enumerate(fronts, 1)]
    got = [ln for ln in out.splitlines() if ln.startswith("iter ")]
    assert [ln.split(" edges")[0] for ln in got] == want


def test_stats_cap_truncation():
    g = small_graph()
    eng = sssp.build_engine(g, start_vertex=1, num_parts=1)
    eng.stats_cap = 2     # read lazily when converge_stats compiles
    label, active = eng.init_state()
    _l, _a, it, fsz, fed, fszp, fedp = eng.converge_stats(label, active)
    it = int(jax.device_get(it))
    assert it > 2 and fsz.shape == (2,)
    st = telemetry.IterStats()
    st.extend_push(fsz, fed, it)
    assert st.truncated and len(st.frontier) == 2
    assert "truncated" in list(st.replay_lines())[-1]


def test_segmented_accumulation_matches_unsegmented():
    """Slice boundaries must be invisible in the counter series (the
    supervised/budgeted paths run through converge_segments)."""
    from lux_tpu.segmented import converge_segments, run_segments

    g = small_graph()
    eng = sssp.build_engine(g, start_vertex=1, num_parts=1)
    label, active = eng.init_state()
    _l, _a, it, fsz, _fed, _fp, _ep = eng.converge_stats(label, active)
    it = int(jax.device_get(it))

    st = telemetry.IterStats()
    ev = telemetry.EventLog()
    with telemetry.use(events=ev, iter_stats=st):
        label, active = eng.init_state()
        _l2, _a2, total = converge_segments(eng, label, active,
                                            segment=2)
    assert total == it
    assert st.frontier == np.asarray(fsz)[:it].tolist()
    segs = [e for e in ev.events if e["kind"] == "segment"]
    assert sum(e["iters"] for e in segs) == it
    assert all(e["engine"] == "push" for e in segs)

    peng = pagerank.build_engine(g, num_parts=1)
    _s, rb, cb, _rbp, _cbp = peng.run_stats(peng.init_state(), 6)
    st2 = telemetry.IterStats()
    with telemetry.use(iter_stats=st2):
        run_segments(peng, peng.init_state(), 6, segment=4)
    np.testing.assert_allclose(st2.residual, np.asarray(rb)[:6],
                               rtol=1e-6)
    assert st2.changed == np.asarray(cb)[:6].tolist()


def test_timed_helpers_emit_and_record(tmp_path):
    from lux_tpu.timing import timed_converge, timed_fused_run

    g = small_graph()
    eng = sssp.build_engine(g, start_vertex=1, num_parts=1)
    st = telemetry.IterStats()
    ev = telemetry.EventLog(str(tmp_path / "ev.jsonl"))
    with telemetry.use(events=ev, iter_stats=st):
        _labels, it, elapsed = timed_converge(eng, repeats=2)
    assert len(elapsed) == 2 and len(st.frontier) == it
    runs = [e for e in ev.events if e["kind"] == "timed_run"]
    assert [r["repeat"] for r in runs] == [0, 1]
    assert [r["seconds"] for r in runs] == \
        [round(e, 6) for e in elapsed]
    # the JSONL on disk is the same stream
    lines = [json.loads(s) for s in
             (tmp_path / "ev.jsonl").read_text().splitlines()]
    assert [ln["kind"] for ln in lines] == \
        [e["kind"] for e in ev.events]

    peng = pagerank.build_engine(g, num_parts=1)
    st2 = telemetry.IterStats()
    with telemetry.use(iter_stats=st2):
        timed_fused_run(peng, 4, repeats=1)
    assert st2.kind == "pull" and len(st2.residual) == 4


def test_supervised_run_report_carries_counters(tmp_path):
    from lux_tpu import resilience

    g = small_graph()
    eng = sssp.build_engine(g, start_vertex=1, num_parts=1)
    st = telemetry.IterStats()
    ev = telemetry.EventLog()
    with telemetry.use(events=ev, iter_stats=st):
        _label, _active, total, report = resilience.supervised_converge(
            eng, str(tmp_path / "ck.npz"), segment=2)
    assert report.counters is not None
    assert report.counters["kind"] == "push"
    assert report.counters["iters"] == total == len(st.frontier)
    assert report.as_dict()["counters"] == report.counters
    kinds = ev.counts()
    assert kinds.get("segment") and kinds.get("checkpoint_save")


def test_counters_exact_through_crash_resume(tmp_path):
    """Counters append only after the segment hook (checkpoint save)
    survives: a crash in the save window re-runs the slice on resume,
    and the accumulated series must NOT double-count it."""
    from lux_tpu import faults, resilience

    g = small_graph()
    eng = sssp.build_engine(g, start_vertex=1, num_parts=1)
    label, active = eng.init_state()
    _l, _a, it, fsz, _fed, _fp, _ep = eng.converge_stats(label, active)
    it = int(jax.device_get(it))
    ref = np.asarray(fsz)[:it].tolist()

    eng2 = sssp.build_engine(g, start_vertex=1, num_parts=1)
    plan = faults.FaultPlan.seeded(seed=3, n=8, p_crash=0.5)
    st = telemetry.IterStats()
    with telemetry.use(iter_stats=st):
        _lbl, _act, total, report = resilience.supervised_converge(
            eng2, str(tmp_path / "ck.npz"), segment=2, faults=plan,
            policy=resilience.RetryPolicy(retries=8, backoff_s=0.0))
    assert report.attempts > 1, "no injected crash fired"
    assert total == it
    assert st.frontier == ref


# -- round 13: per-part counters vs NumPy per-part oracles -------------
#    (sum-over-parts must BITWISE-equal the scalar counter series; the
#    engines reduce the same device-side values part-first)

def per_part_push_oracle(eng):
    """NumPy per-part oracle: stepwise frontier size and entering
    out-edges PER PART — the decomposition the fused per-part
    buffers must reproduce exactly."""
    deg = np.asarray(eng.sg.deg_padded)
    label, active = eng.init_state()
    fronts_p, edges_p = [], []
    cnt = int(jax.device_get(np.sum(np.asarray(active))))
    while cnt > 0:
        act = np.asarray(jax.device_get(active))
        edges_p.append([int(deg[p][act[p]].sum())
                        for p in range(act.shape[0])])
        label, active, c = eng.step(label, active)
        cnt = int(jax.device_get(c))
        act = np.asarray(jax.device_get(active))
        fronts_p.append([int(act[p].sum())
                         for p in range(act.shape[0])])
    return fronts_p, edges_p


def per_part_pull_oracle(eng, iters):
    """NumPy per-part oracle: stepwise max-abs residual and
    changed-vertex count per part."""
    prev = np.asarray(jax.device_get(eng.init_state())).copy()
    res_p, chg_p = [], []
    s = eng.init_state()
    for _ in range(iters):
        s = eng.step(s)
        cur = np.asarray(jax.device_get(s)).copy()
        d = np.abs(cur.astype(np.float32) - prev.astype(np.float32))
        dp = d.reshape(d.shape[0], -1)
        res_p.append(dp.max(axis=1).tolist())
        chg_p.append([int((row > 0).sum()) for row in dp])
        prev = cur
    return res_p, chg_p


@pytest.mark.parametrize("np_parts,mesh_n", [(4, 0), (8, 8)])
def test_push_per_part_counters_match_oracle(np_parts, mesh_n):
    """converge_stats per-part buffers vs the NumPy per-part oracle,
    on 1 device (mesh_n=0) and the full 8-virtual-device mesh; the
    scalar series must be the bitwise sum of the per-part rows."""
    g = small_graph()
    mesh = make_mesh(mesh_n) if mesh_n else None
    eng = sssp.build_engine(g, start_vertex=1, num_parts=np_parts,
                            mesh=mesh)
    fronts_p, edges_p = per_part_push_oracle(eng)
    label, active = eng.init_state()
    _l, _a, it, fsz, fed, fszp, fedp = eng.converge_stats(label,
                                                          active)
    it = int(jax.device_get(it))
    fszp = np.asarray(jax.device_get(fszp))
    fedp = np.asarray(jax.device_get(fedp))
    assert fszp.shape == (eng.stats_cap, np_parts)
    assert fszp[:it].tolist() == fronts_p
    assert fedp[:it].tolist() == edges_p
    # sum-over-parts == the scalar series, BITWISE
    np.testing.assert_array_equal(
        fszp[:it].sum(axis=1, dtype=np.int64),
        np.asarray(jax.device_get(fsz))[:it])
    np.testing.assert_array_equal(
        fedp[:it].astype(np.uint64).sum(axis=1).astype(np.uint32),
        np.asarray(jax.device_get(fed))[:it])
    assert not fszp[it:].any() and not fedp[it:].any()


@pytest.mark.parametrize("np_parts,mesh_n", [(4, 0), (8, 8)])
def test_pull_per_part_counters_match_oracle(np_parts, mesh_n):
    g = small_graph(seed=11)
    mesh = make_mesh(mesh_n) if mesh_n else None
    eng = pagerank.build_engine(g, num_parts=np_parts, mesh=mesh)
    res_p, chg_p = per_part_pull_oracle(eng, 5)
    _s, rb, cb, rbp, cbp = eng.run_stats(eng.init_state(), 5)
    rbp = np.asarray(jax.device_get(rbp))
    cbp = np.asarray(jax.device_get(cbp))
    np.testing.assert_array_equal(rbp[:5], np.asarray(res_p,
                                                      np.float32))
    assert cbp[:5].tolist() == chg_p
    # max/sum over parts == the scalar series, BITWISE
    np.testing.assert_array_equal(rbp[:5].max(axis=1),
                                  np.asarray(jax.device_get(rb))[:5])
    np.testing.assert_array_equal(
        cbp[:5].astype(np.uint64).sum(axis=1).astype(np.uint32),
        np.asarray(jax.device_get(cb))[:5])


def test_per_part_counters_ride_health_variants():
    """The *_health loop variants carry the same per-part counters
    (bitwise-equal to the *_stats variants'): converge_health,
    run_health and run_until_health vs their stats twins on the same
    per_part oracle contract."""
    g = small_graph()
    eng = sssp.build_engine(g, start_vertex=1, num_parts=4)
    _l, _a, it, _f, _e, fszp, fedp = eng.converge_stats(
        *eng.init_state())
    _l2, _a2, _it2, _f2, _e2, fszp2, fedp2, h = eng.converge_health(
        *eng.init_state())
    np.testing.assert_array_equal(np.asarray(jax.device_get(fszp)),
                                  np.asarray(jax.device_get(fszp2)))
    np.testing.assert_array_equal(np.asarray(jax.device_get(fedp)),
                                  np.asarray(jax.device_get(fedp2)))

    peng = pagerank.build_engine(g, num_parts=4)
    _s, _rb, _cb, rbp, cbp = peng.run_stats(peng.init_state(), 6)
    _s2, _it, _rb2, _cb2, rbp2, cbp2, _h = peng.run_health(
        peng.init_state(), 6)
    np.testing.assert_array_equal(np.asarray(jax.device_get(rbp)),
                                  np.asarray(jax.device_get(rbp2)))
    np.testing.assert_array_equal(np.asarray(jax.device_get(cbp)),
                                  np.asarray(jax.device_get(cbp2)))

    _s3, it3, _r3, rb3, cb3, rbp3, cbp3 = peng.run_until_stats(
        peng.init_state(), 1e-6, max_iters=6)
    _s4, it4, _r4, _rb4, _cb4, rbp4, cbp4, _h4 = \
        peng.run_until_health(peng.init_state(), 1e-6, max_iters=6)
    assert int(jax.device_get(it3)) == int(jax.device_get(it4))
    np.testing.assert_array_equal(np.asarray(jax.device_get(rbp3)),
                                  np.asarray(jax.device_get(rbp4)))
    np.testing.assert_array_equal(np.asarray(jax.device_get(cbp3)),
                                  np.asarray(jax.device_get(cbp4)))


def test_iter_stats_imbalance_digest():
    """IterStats per-part accumulation: part totals, the max/mean
    imbalance index, the summary fields and the bench digest."""
    st = telemetry.IterStats()
    fsz = np.asarray([3, 2], np.int32)
    fed = np.asarray([30, 10], np.uint32)
    fszp = np.asarray([[2, 1], [1, 1]], np.int32)
    fedp = np.asarray([[25, 5], [5, 5]], np.uint32)
    st.extend_push(fsz, fed, 2, fszp, fedp)
    assert st.num_parts() == 2
    assert st.part_totals() == [30, 10]          # edges per part
    assert st.imbalance() == pytest.approx(30 / 20)
    s = st.summary()
    assert s["parts"] == 2 and s["parts_edges"] == [30, 10]
    assert s["imbalance"] == pytest.approx(1.5)
    assert sum(s["parts_edges"]) == s["edges_sum"]    # bitwise
    d = st.imbalance_digest()
    assert d == {"kind": "push", "index": 1.5, "parts": [30, 10]}
    lines = list(st.parts_lines())
    assert "imbalance 1.500" in lines[0]
    assert any("part 0: 30" in ln for ln in lines)
    # per-part-free runs keep the legacy digest shape
    st2 = telemetry.IterStats()
    st2.extend_push(fsz, fed, 2)
    assert st2.part_totals() is None
    assert st2.imbalance_digest() is None
    assert "parts" not in st2.summary()


def test_segmented_per_part_accumulation_matches_unsegmented():
    """Per-part series must be boundary-invisible exactly like the
    scalar series (the supervised drivers fetch the part buffers once
    per segment)."""
    from lux_tpu.segmented import converge_segments

    g = small_graph()
    eng = sssp.build_engine(g, start_vertex=1, num_parts=4)
    label, active = eng.init_state()
    _l, _a, it, _f, _e, fszp, fedp = eng.converge_stats(label, active)
    it = int(jax.device_get(it))
    st = telemetry.IterStats()
    with telemetry.use(iter_stats=st):
        label, active = eng.init_state()
        converge_segments(eng, label, active, segment=2)
    assert st.frontier_parts == \
        np.asarray(jax.device_get(fszp))[:it].tolist()
    assert st.edges_parts == \
        np.asarray(jax.device_get(fedp))[:it].tolist()
    # and the digest's bitwise contract holds over the whole run
    s = st.summary()
    assert sum(s["parts_edges"]) == s["edges_sum"]


def test_event_log_and_null_handle():
    ev = telemetry.EventLog()
    ev.emit("header", nv=4)
    ev.emit("segment", engine="pull", seconds=0.5)
    assert ev.counts() == {"header": 1, "segment": 1}
    # the null handle swallows emits and reports no sinks
    assert telemetry.current().emit("anything") is None
    assert telemetry.current().iter_stats is None
    # nested scopes restore the previous handle
    with telemetry.use(events=ev) as tel:
        assert telemetry.current() is tel
    assert telemetry.current().events is None


def test_event_log_rotation_single_process(tmp_path):
    """Round-17 bounded EventLog: size-triggered rotation shifts
    generations (.1 -> .2, live -> .1), stamps each fresh live file
    with a log_rotate event, drops generations past the window, and
    telemetry.rotated_paths lists the surviving set oldest-first
    with per-stream tm still monotone across the concatenation."""
    import os

    path = str(tmp_path / "ev.jsonl")
    ev = telemetry.EventLog(path, rotate_bytes=4000)
    pad = "x" * 200
    for i in range(60):
        ev.emit("mark", i=i, pad=pad)
    ev.close()
    assert ev.rotations >= 2
    paths = telemetry.rotated_paths(path)
    assert paths == [f"{path}.2", f"{path}.1", path]
    assert all(os.path.exists(p) for p in paths)
    events = []
    for p in paths:
        events += [json.loads(ln)
                   for ln in open(p).read().splitlines()]
    marks = [e["i"] for e in events if e["kind"] == "mark"]
    # oldest generations beyond the window dropped; the kept tail is
    # contiguous and ends at the newest event
    assert marks == list(range(marks[0], 60))
    rots = [e for e in events if e["kind"] == "log_rotate"]
    assert rots and all(r["path"] == path for r in rots)
    tms = [e["tm"] for e in events]
    assert tms == sorted(tms)
    # in-memory view complete while under the MEM_KEEP bound (a
    # rotation only trims once the list outgrows it)
    assert len(ev.events) < ev.MEM_KEEP
    assert [e["i"] for e in ev.events
            if e["kind"] == "mark"] == list(range(60))

    with pytest.raises(ValueError):
        telemetry.EventLog(path, rotate_bytes=0)
    with pytest.raises(ValueError):
        telemetry.EventLog(path, rotate_bytes=100, generations=0)
    # a plain (never-rotated) path is its own one-element set
    lone = str(tmp_path / "lone.jsonl")
    assert telemetry.rotated_paths(lone) == [lone]
