"""In-loop telemetry (lux_tpu/telemetry.py): device-side iteration
counters against stepwise/NumPy oracles, the structured event log, and
the cross-layer wiring (segmented drivers, supervisor, timing helpers).

The counter contract under test is the acceptance bar of the round-7
ISSUE: the fused run's per-iteration frontier sizes / residuals must
equal what the old stepwise -verbose path printed — computed here by
actually stepping the engines one compiled iteration at a time.
"""

import json

import jax
import numpy as np
import pytest

from lux_tpu import telemetry
from lux_tpu.apps import components, pagerank, sssp
from lux_tpu.convert import uniform_random_edges
from lux_tpu.graph import Graph
from lux_tpu.parallel.mesh import make_mesh


def small_graph(nv=180, ne=1400, seed=7, weighted=False):
    if weighted:
        src, dst, w = uniform_random_edges(nv, ne, seed=seed,
                                           weighted=True)
        return Graph.from_edges(src, dst, nv, weights=w)
    src, dst = uniform_random_edges(nv, ne, seed=seed)
    return Graph.from_edges(src, dst, nv)


def stepwise_push_series(eng):
    """The old stepwise -verbose path: frontier size after each
    compiled step, plus each iteration's entering-frontier out-edges
    from the full graph's degrees (the NumPy side of the oracle)."""
    deg = np.asarray(eng.sg.deg_padded)
    label, active = eng.init_state()
    fronts, edges = [], []
    cnt = int(jax.device_get(np.sum(np.asarray(active))))
    while cnt > 0:
        act_np = np.asarray(jax.device_get(active))
        edges.append(int(deg[act_np].sum()))
        label, active, c = eng.step(label, active)
        cnt = int(jax.device_get(c))
        fronts.append(cnt)
    return fronts, edges


@pytest.mark.parametrize("np_parts,mesh_n", [(1, 0), (8, 8)])
def test_push_classic_counters_match_stepwise(np_parts, mesh_n):
    g = small_graph()
    mesh = make_mesh(mesh_n) if mesh_n else None
    eng = sssp.build_engine(g, start_vertex=1, num_parts=np_parts,
                            mesh=mesh)
    fronts, edges = stepwise_push_series(eng)

    label, active = eng.init_state()
    l2, a2, it, fsz, fed = eng.converge_stats(label, active)
    it = int(jax.device_get(it))
    assert it == len(fronts)
    assert np.asarray(fsz)[:it].tolist() == fronts
    assert np.asarray(fed)[:it].tolist() == edges
    # past-the-run entries stay zero, and the labels are the oracle's
    assert not np.asarray(fsz)[it:].any()
    dist = eng.unpad(l2)
    want = sssp.reference_sssp(g, start_vertex=1)
    reach = ~sssp.unreachable(dist)
    np.testing.assert_array_equal(dist[reach], want[reach])


@pytest.mark.parametrize("np_parts,mesh_n", [(1, 0), (8, 8)])
def test_components_counters_match_stepwise(np_parts, mesh_n):
    s, d = small_graph(seed=9).edge_arrays()
    g = Graph.from_edges(*components.symmetrize(s, d), 180)
    mesh = make_mesh(mesh_n) if mesh_n else None
    eng = components.build_engine(g, num_parts=np_parts, mesh=mesh)
    fronts, edges = stepwise_push_series(eng)
    label, active = eng.init_state()
    _l, _a, it, fsz, fed = eng.converge_stats(label, active)
    it = int(jax.device_get(it))
    assert np.asarray(fsz)[:it].tolist() == fronts
    assert np.asarray(fed)[:it].tolist() == edges


def test_push_delta_counters_match_timed_phases():
    """Delta engines record each relax step's bucket-front size — the
    exact schedule the instrumented stepwise path
    (timed_phases/_timed_phases_delta) replays."""
    g = small_graph(weighted=True)
    eng = sssp.build_engine(g, start_vertex=0, num_parts=1,
                            weighted=True, delta="auto")
    label, active = eng.init_state()
    _l, _a, it, fsz, _fed = eng.converge_stats(label, active)
    it = int(jax.device_get(it))
    lab0, act0 = eng.init_state()
    _l2, _a2, report = eng.timed_phases(lab0, act0, iters=it)
    assert [t["frontier"] for t in report] == \
        np.asarray(fsz)[:it].tolist()


@pytest.mark.parametrize("np_parts,mesh_n", [(1, 0), (8, 8)])
def test_pull_counters_match_stepwise(np_parts, mesh_n):
    g = small_graph(seed=11)
    mesh = make_mesh(mesh_n) if mesh_n else None
    eng = pagerank.build_engine(g, num_parts=np_parts, mesh=mesh)
    prev = np.asarray(jax.device_get(eng.init_state())).copy()
    res_oracle, chg_oracle = [], []
    s = eng.init_state()
    for _ in range(5):
        s = eng.step(s)
        cur = np.asarray(jax.device_get(s)).copy()
        d = np.abs(cur.astype(np.float32) - prev.astype(np.float32))
        res_oracle.append(float(d.max()))
        chg_oracle.append(int((d > 0).sum()))
        prev = cur

    s2, rb, cb = eng.run_stats(eng.init_state(), 5)
    np.testing.assert_allclose(np.asarray(rb)[:5], res_oracle,
                               rtol=1e-6)
    assert np.asarray(cb)[:5].tolist() == chg_oracle
    np.testing.assert_array_equal(np.asarray(jax.device_get(s2)), prev)


def test_pull_run_until_stats_matches_run_until():
    g = small_graph(seed=13)
    eng = pagerank.build_engine(g, num_parts=2)
    s1, it1, res1 = eng.run_until(eng.init_state(), 1e-6,
                                  max_iters=50)
    s2, it2, res2, rb, cb = eng.run_until_stats(
        eng.init_state(), 1e-6, max_iters=50)
    it1, it2 = int(jax.device_get(it1)), int(jax.device_get(it2))
    assert it1 == it2
    assert float(jax.device_get(res1)) == float(jax.device_get(res2))
    # the residual series ends exactly at the convergence residual,
    # and every earlier entry is above the tolerance
    rbn = np.asarray(rb)[:it2]
    assert rbn[-1] == pytest.approx(float(jax.device_get(res2)))
    assert (rbn[:-1] > 1e-6).all()
    np.testing.assert_array_equal(np.asarray(jax.device_get(s1)),
                                  np.asarray(jax.device_get(s2)))


def test_push_verbose_replays_counters(capsys):
    g = small_graph()
    eng = sssp.build_engine(g, start_vertex=1, num_parts=1)
    fronts, _ = stepwise_push_series(eng)
    eng2 = sssp.build_engine(g, start_vertex=1, num_parts=1)
    _labels, it = eng2.run(verbose=True)
    out = capsys.readouterr().out
    want = [f"iter {i}: frontier={f}" for i, f in enumerate(fronts, 1)]
    got = [ln for ln in out.splitlines() if ln.startswith("iter ")]
    assert [ln.split(" edges")[0] for ln in got] == want


def test_stats_cap_truncation():
    g = small_graph()
    eng = sssp.build_engine(g, start_vertex=1, num_parts=1)
    eng.stats_cap = 2     # read lazily when converge_stats compiles
    label, active = eng.init_state()
    _l, _a, it, fsz, fed = eng.converge_stats(label, active)
    it = int(jax.device_get(it))
    assert it > 2 and fsz.shape == (2,)
    st = telemetry.IterStats()
    st.extend_push(fsz, fed, it)
    assert st.truncated and len(st.frontier) == 2
    assert "truncated" in list(st.replay_lines())[-1]


def test_segmented_accumulation_matches_unsegmented():
    """Slice boundaries must be invisible in the counter series (the
    supervised/budgeted paths run through converge_segments)."""
    from lux_tpu.segmented import converge_segments, run_segments

    g = small_graph()
    eng = sssp.build_engine(g, start_vertex=1, num_parts=1)
    label, active = eng.init_state()
    _l, _a, it, fsz, _fed = eng.converge_stats(label, active)
    it = int(jax.device_get(it))

    st = telemetry.IterStats()
    ev = telemetry.EventLog()
    with telemetry.use(events=ev, iter_stats=st):
        label, active = eng.init_state()
        _l2, _a2, total = converge_segments(eng, label, active,
                                            segment=2)
    assert total == it
    assert st.frontier == np.asarray(fsz)[:it].tolist()
    segs = [e for e in ev.events if e["kind"] == "segment"]
    assert sum(e["iters"] for e in segs) == it
    assert all(e["engine"] == "push" for e in segs)

    peng = pagerank.build_engine(g, num_parts=1)
    _s, rb, cb = peng.run_stats(peng.init_state(), 6)
    st2 = telemetry.IterStats()
    with telemetry.use(iter_stats=st2):
        run_segments(peng, peng.init_state(), 6, segment=4)
    np.testing.assert_allclose(st2.residual, np.asarray(rb)[:6],
                               rtol=1e-6)
    assert st2.changed == np.asarray(cb)[:6].tolist()


def test_timed_helpers_emit_and_record(tmp_path):
    from lux_tpu.timing import timed_converge, timed_fused_run

    g = small_graph()
    eng = sssp.build_engine(g, start_vertex=1, num_parts=1)
    st = telemetry.IterStats()
    ev = telemetry.EventLog(str(tmp_path / "ev.jsonl"))
    with telemetry.use(events=ev, iter_stats=st):
        _labels, it, elapsed = timed_converge(eng, repeats=2)
    assert len(elapsed) == 2 and len(st.frontier) == it
    runs = [e for e in ev.events if e["kind"] == "timed_run"]
    assert [r["repeat"] for r in runs] == [0, 1]
    assert [r["seconds"] for r in runs] == \
        [round(e, 6) for e in elapsed]
    # the JSONL on disk is the same stream
    lines = [json.loads(s) for s in
             (tmp_path / "ev.jsonl").read_text().splitlines()]
    assert [ln["kind"] for ln in lines] == \
        [e["kind"] for e in ev.events]

    peng = pagerank.build_engine(g, num_parts=1)
    st2 = telemetry.IterStats()
    with telemetry.use(iter_stats=st2):
        timed_fused_run(peng, 4, repeats=1)
    assert st2.kind == "pull" and len(st2.residual) == 4


def test_supervised_run_report_carries_counters(tmp_path):
    from lux_tpu import resilience

    g = small_graph()
    eng = sssp.build_engine(g, start_vertex=1, num_parts=1)
    st = telemetry.IterStats()
    ev = telemetry.EventLog()
    with telemetry.use(events=ev, iter_stats=st):
        _label, _active, total, report = resilience.supervised_converge(
            eng, str(tmp_path / "ck.npz"), segment=2)
    assert report.counters is not None
    assert report.counters["kind"] == "push"
    assert report.counters["iters"] == total == len(st.frontier)
    assert report.as_dict()["counters"] == report.counters
    kinds = ev.counts()
    assert kinds.get("segment") and kinds.get("checkpoint_save")


def test_counters_exact_through_crash_resume(tmp_path):
    """Counters append only after the segment hook (checkpoint save)
    survives: a crash in the save window re-runs the slice on resume,
    and the accumulated series must NOT double-count it."""
    from lux_tpu import faults, resilience

    g = small_graph()
    eng = sssp.build_engine(g, start_vertex=1, num_parts=1)
    label, active = eng.init_state()
    _l, _a, it, fsz, _fed = eng.converge_stats(label, active)
    it = int(jax.device_get(it))
    ref = np.asarray(fsz)[:it].tolist()

    eng2 = sssp.build_engine(g, start_vertex=1, num_parts=1)
    plan = faults.FaultPlan.seeded(seed=3, n=8, p_crash=0.5)
    st = telemetry.IterStats()
    with telemetry.use(iter_stats=st):
        _lbl, _act, total, report = resilience.supervised_converge(
            eng2, str(tmp_path / "ck.npz"), segment=2, faults=plan,
            policy=resilience.RetryPolicy(retries=8, backoff_s=0.0))
    assert report.attempts > 1, "no injected crash fired"
    assert total == it
    assert st.frontier == ref


def test_event_log_and_null_handle():
    ev = telemetry.EventLog()
    ev.emit("header", nv=4)
    ev.emit("segment", engine="pull", seconds=0.5)
    assert ev.counts() == {"header": 1, "segment": 1}
    # the null handle swallows emits and reports no sinks
    assert telemetry.current().emit("anything") is None
    assert telemetry.current().iter_stats is None
    # nested scopes restore the previous handle
    with telemetry.use(events=ev) as tel:
        assert telemetry.current() is tel
    assert telemetry.current().events is None
