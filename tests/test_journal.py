"""lux_tpu/journal.py: the durable admission journal (round 24).

The corruption suite mirrors tests/test_livegraph.py's
TestMutationLog contract record for record: bitwise roundtrip, the
recoverable torn tail (truncated by replay, never re-dispatched),
typed refusal of everything that cannot be a torn append (broken CRC
chain, unknown record kinds, duplicate/unmatched/double retirement,
backwards qids, a foreign graph's header), plus the fsck legs and
the reset-digest rule (the journal stores 8 bytes of blake2b, never
the vector).
"""

import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from lux_tpu import format as luxfmt
from lux_tpu.convert import uniform_random_edges
from lux_tpu.graph import Graph
from lux_tpu.journal import (AdmissionJournal, AdmissionJournalError,
                             reset_digest)
from lux_tpu.serve import Request

REPO = Path(__file__).resolve().parent.parent
FSCK = REPO / "scripts" / "fsck_lux.py"

NV = 64


@pytest.fixture(scope="module")
def g():
    src, dst = uniform_random_edges(NV, 256, seed=9)
    return Graph.from_edges(src, dst, NV)


def _req(qid, kind="sssp", **kw):
    kw.setdefault("source", 3)
    return Request(qid=qid, kind=kind, t_enqueue=0.0, **kw)


def _fresh(tmp_path, name="a.journal"):
    path = str(tmp_path / name)
    return path, AdmissionJournal(path, nv=NV)


class TestJournalRoundtrip:
    def test_roundtrip_bitwise(self, tmp_path):
        """Every ADMIT field survives the 48-byte record exactly:
        source/reset, epoch/static, deadline, negative priority,
        tenant bytes, and the retirement causes."""
        path, j = _fresh(tmp_path)
        reset = np.zeros(NV, np.float32)
        reset[5] = 1.0
        reqs = [
            _req(0),
            _req(1, kind="components", source=7, epoch=4,
                 tenant="paid", priority=-2, deadline_s=1.5),
            _req(2, kind="pagerank", source=None, reset=reset,
                 tenant="free", priority=9),
        ]
        for r in reqs:
            j.append_admit(r)
        j.append_retire(0, "answered")
        j.append_retire(2, "shed")
        j.close()

        opens, retired, hnv, torn = AdmissionJournal.scan(path, nv=NV)
        assert (hnv, torn) == (NV, 0)
        assert retired == {0: "answered", 2: "shed"}
        (rec,) = opens
        assert rec.qid == 1 and rec.kind == "components"
        assert rec.source == 7 and rec.epoch == 4
        assert rec.tenant == "paid" and rec.priority == -2
        assert rec.deadline_s == pytest.approx(1.5)
        assert rec.digest is None

    def test_reset_query_persists_digest_only(self, tmp_path):
        path, j = _fresh(tmp_path)
        reset = np.linspace(0, 1, NV).astype(np.float32)
        j.append_admit(_req(0, kind="pagerank", source=None,
                            reset=reset))
        j.close()
        (rec,), _, _, _ = AdmissionJournal.scan(path, nv=NV)
        assert rec.source is None
        assert rec.digest == reset_digest(reset)
        assert len(rec.digest) == 8
        # a different vector fingerprints differently — recovery's
        # mismatch shed hangs off this inequality
        other = reset.copy()
        other[0] += 1.0
        assert reset_digest(other) != rec.digest

    def test_tiny_deadline_never_collapses_to_none(self, tmp_path):
        """Deadlines round UP to >= 1 ms: a 0.1 ms deadline must not
        decode as the no-deadline sentinel (0)."""
        path, j = _fresh(tmp_path)
        j.append_admit(_req(0, deadline_s=1e-4))
        j.close()
        (rec,), _, _, _ = AdmissionJournal.scan(path, nv=NV)
        assert rec.deadline_s == pytest.approx(0.001)

    def test_buffer_bytes_tracks_appends(self, tmp_path):
        path, j = _fresh(tmp_path)
        assert j.buffer_bytes() == luxfmt.JOURNAL_HEADER_SIZE
        j.append_admit(_req(0))
        j.append_retire(0)
        assert j.buffer_bytes() == (luxfmt.JOURNAL_HEADER_SIZE
                                    + 2 * luxfmt.JOURNAL_RECORD_SIZE)
        j.close()

    def test_existing_journal_refused_typed(self, tmp_path):
        path, j = _fresh(tmp_path)
        j.close()
        with pytest.raises(AdmissionJournalError) as ei:
            AdmissionJournal(path, nv=NV)
        assert ei.value.check == "journal_exists"
        assert "recover" in ei.value.detail

    def test_oversize_tenant_refused_typed(self, tmp_path):
        path, j = _fresh(tmp_path)
        with pytest.raises(AdmissionJournalError) as ei:
            j.append_admit(_req(0, tenant="enterprise-gold"))
        assert ei.value.check == "tenant_size"
        j.close()
        # the refused append left NOTHING on disk — the journal is
        # still clean and appendable
        opens, retired, _, torn = AdmissionJournal.scan(path, nv=NV)
        assert (opens, retired, torn) == ([], {}, 0)


class TestJournalTornTail:
    def test_torn_tail_reported_then_truncated(self, tmp_path):
        """A strict-prefix torn append (power loss mid-write) is the
        RECOVERABLE class: scan reports it, replay truncates it and
        resumes the chain — and the resumed handle's appends
        re-validate."""
        path, j = _fresh(tmp_path)
        j.append_admit(_req(0))
        j.write_torn(j.pack_admit(_req(1)))
        j.close()
        opens, retired, _, torn = AdmissionJournal.scan(path, nv=NV)
        assert len(opens) == 1 and 0 < torn < \
            luxfmt.JOURNAL_RECORD_SIZE
        opens, retired, torn2, j2 = AdmissionJournal.replay(
            path, nv=NV)
        assert torn2 == torn and [r.qid for r in opens] == [0]
        # the torn record was never acknowledged: qid 1 may be
        # re-issued, and the resumed chain stays valid
        j2.append_retire(0, "answered")
        j2.append_admit(_req(1))
        j2.close()
        opens, retired, _, torn = AdmissionJournal.scan(path, nv=NV)
        assert [r.qid for r in opens] == [1]
        assert retired == {0: "answered"} and torn == 0

    def test_full_record_bad_crc_tail_is_rot(self, tmp_path):
        """A FULL-SIZE record failing the chain CRC is corruption of
        a possibly-fsync-acknowledged append — typed refusal, never a
        torn-tail truncation (the MutationLog contract, mirrored)."""
        path, j = _fresh(tmp_path)
        j.append_admit(_req(0))
        j.close()
        with open(path, "ab") as f:
            f.write(b"\x7f" * luxfmt.JOURNAL_RECORD_SIZE)
        with pytest.raises(AdmissionJournalError) as ei:
            AdmissionJournal.scan(path, nv=NV)
        assert ei.value.check == "crc_chain"
        assert "possibly-acknowledged" in ei.value.detail

    def test_midfile_corruption_typed(self, tmp_path):
        path, j = _fresh(tmp_path)
        for qid in range(3):
            j.append_admit(_req(qid))
        j.close()
        off = luxfmt.JOURNAL_HEADER_SIZE + luxfmt.JOURNAL_RECORD_SIZE
        with open(path, "r+b") as f:
            f.seek(off + 4)
            b = f.read(1)
            f.seek(off + 4)
            f.write(bytes([b[0] ^ 0x01]))
        with pytest.raises(AdmissionJournalError) as ei:
            AdmissionJournal.scan(path, nv=NV)
        assert ei.value.check == "crc_chain"
        assert "mid-file" in ei.value.detail


class TestJournalPairing:
    """ADMIT/RETIRE pairing at rest: the records are appended through
    the journal's own sealer (so every CRC is VALID) — the pairing
    audits must catch the semantic corruption the chain cannot."""

    def test_admit_dup_typed(self, tmp_path):
        path, j = _fresh(tmp_path)
        j.append_admit(_req(0))
        j._append(j.pack_admit(_req(0)))
        j.close()
        with pytest.raises(AdmissionJournalError) as ei:
            AdmissionJournal.scan(path, nv=NV)
        assert ei.value.check == "admit_dup"

    def test_readmit_after_retire_typed(self, tmp_path):
        path, j = _fresh(tmp_path)
        j.append_admit(_req(0))
        j.append_retire(0, "answered")
        j._append(j.pack_admit(_req(0)))
        j.close()
        with pytest.raises(AdmissionJournalError) as ei:
            AdmissionJournal.scan(path, nv=NV)
        assert ei.value.check == "admit_dup"

    def test_qid_order_typed(self, tmp_path):
        path, j = _fresh(tmp_path)
        j.append_admit(_req(5))
        j._append(j.pack_admit(_req(3)))
        j.close()
        with pytest.raises(AdmissionJournalError) as ei:
            AdmissionJournal.scan(path, nv=NV)
        assert ei.value.check == "qid_order"

    def test_retire_unmatched_typed(self, tmp_path):
        path, j = _fresh(tmp_path)
        j.append_admit(_req(0))
        j._append(j.pack_retire(9, "answered"))
        j.close()
        with pytest.raises(AdmissionJournalError) as ei:
            AdmissionJournal.scan(path, nv=NV)
        assert ei.value.check == "retire_unmatched"

    def test_retire_dup_typed(self, tmp_path):
        path, j = _fresh(tmp_path)
        j.append_admit(_req(0))
        j.append_retire(0, "answered")
        j._append(j.pack_retire(0, "answered"))
        j.close()
        with pytest.raises(AdmissionJournalError) as ei:
            AdmissionJournal.scan(path, nv=NV)
        assert ei.value.check == "retire_dup"

    def test_unknown_record_kind_typed(self, tmp_path):
        path, j = _fresh(tmp_path)
        words = np.zeros(11, luxfmt.V_DTYPE)
        words[0] = 9
        j._append(j._seal(words))
        j.close()
        with pytest.raises(AdmissionJournalError) as ei:
            AdmissionJournal.scan(path, nv=NV)
        assert ei.value.check == "record_kind"

    def test_unknown_retire_cause_typed(self, tmp_path):
        path, j = _fresh(tmp_path)
        j.append_admit(_req(0))
        words = np.zeros(11, luxfmt.V_DTYPE)
        words[0] = 2            # RETIRE
        words[1] = 0
        words[2] = 7            # no such cause
        j._append(j._seal(words))
        j.close()
        with pytest.raises(AdmissionJournalError) as ei:
            AdmissionJournal.scan(path, nv=NV)
        assert ei.value.check == "record_kind"

    def test_unknown_query_kind_code_typed(self, tmp_path):
        path, j = _fresh(tmp_path)
        words = np.zeros(11, luxfmt.V_DTYPE)
        words[0] = 1            # ADMIT
        words[1] = 0
        words[2] = 200          # no such serve.KINDS index
        j._append(j._seal(words))
        j.close()
        with pytest.raises(AdmissionJournalError) as ei:
            AdmissionJournal.scan(path, nv=NV)
        assert ei.value.check == "record_kind"


class TestJournalHeader:
    def test_foreign_graph_header_typed(self, tmp_path):
        path, j = _fresh(tmp_path)
        j.append_admit(_req(0))
        j.close()
        with pytest.raises(luxfmt.GraphFormatError) as ei:
            AdmissionJournal.scan(path, nv=NV + 1)
        assert ei.value.check == "journal_header"
        assert "different graph" in ei.value.detail

    def test_not_a_journal_typed(self, tmp_path):
        path = str(tmp_path / "x.journal")
        Path(path).write_bytes(b"LUXG" + b"\x00" * 12)
        with pytest.raises(luxfmt.GraphFormatError) as ei:
            AdmissionJournal.scan(path, nv=NV)
        assert ei.value.check == "journal_header"

    def test_unknown_version_typed(self, tmp_path):
        path = str(tmp_path / "x.journal")
        head = bytearray(luxfmt.pack_journal_header(NV))
        head[4:8] = (99).to_bytes(4, "little")
        Path(path).write_bytes(bytes(head))
        with pytest.raises(luxfmt.GraphFormatError) as ei:
            AdmissionJournal.scan(path, nv=NV)
        assert ei.value.check == "journal_version"


class TestFsckJournal:
    def _fsck(self, *paths):
        return subprocess.run(
            [sys.executable, str(FSCK), *map(str, paths)],
            capture_output=True, text=True)

    def test_clean_and_torn_pass_corrupt_exits_2(self, tmp_path):
        path, j = _fresh(tmp_path)
        j.append_admit(_req(0))
        j.append_admit(_req(1))
        j.append_retire(0, "shed")
        j.write_torn(j.pack_admit(_req(2)))
        j.close()
        r = self._fsck(path)
        assert r.returncode == 0, r.stderr
        assert "OK journal v1" in r.stdout
        assert "open=1 retired=1 shed=1" in r.stdout
        assert "TORN-TAIL" in r.stdout and "recoverable" in r.stdout
        # rot the tail up to a full record: exit 2 (the typed
        # integrity-refusal convention)
        with open(path, "ab") as f:
            f.write(b"\x7f" * luxfmt.JOURNAL_RECORD_SIZE)
        r = self._fsck(path)
        assert r.returncode == 2
        assert "crc_chain" in r.stderr

    def test_sidecar_checked_against_its_graph(self, g, tmp_path):
        """A <graph>.lux.journal sidecar beside a checked .lux is
        verified AGAINST that graph — a journal for a different nv
        fails at rest, never as re-dispatched queries against the
        wrong graph."""
        lux = str(tmp_path / "g.lux")
        luxfmt.write_lux(lux, g.row_ptrs, g.col_idx)
        side = luxfmt.journal_sidecar_path(lux)
        j = AdmissionJournal(side, nv=g.nv)
        j.append_admit(_req(0))
        j.close()
        r = self._fsck(lux)
        assert r.returncode == 0, r.stderr
        assert "OK journal" in r.stdout
        # now a FOREIGN journal (wrong nv) under the sidecar name
        Path(side).unlink()
        j = AdmissionJournal(side, nv=g.nv + 3)
        j.close()
        r = self._fsck(lux)
        assert r.returncode == 2
        assert "different graph" in r.stderr
