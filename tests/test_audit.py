"""lux_tpu/audit.py: the compile-time program auditor.

Three layers:
- one deliberately-violating synthetic program per check class, each
  raising the NAMED AuditError subclass;
- bitwise no-op proof: ``audit=`` never alters compiled outputs;
- the repo-wide audit + AST lint (the tier-1 gate): every engine
  configuration's every program variant, clean on the CPU backend —
  budgeted well under 60 s.
"""

import functools
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lux_tpu import audit
from lux_tpu.audit import (AuditError, CallbackInLoopError,
                           CollectiveScheduleError, ConstBytesError,
                           DtypeDisciplineError, GatherBudgetError,
                           IdentityInitError, LedgerDriftError,
                           LoopInvariantError, ProgramSpec)
from lux_tpu.graph import Graph

REPO = Path(__file__).resolve().parent.parent


def _graph(nv=256, ne=2048, weighted=False, seed=0):
    rng = np.random.default_rng(seed)
    w = rng.integers(1, 6, ne).astype(np.float32) if weighted else None
    return Graph.from_edges(rng.integers(0, nv, ne),
                            rng.integers(0, nv, ne), nv, weights=w)


def _raise_all(findings, **kw):
    audit.raise_findings(findings, **kw)


# ---------------------------------------------------------------------
# synthetic violators — one per check class


def test_gather_budget_violation():
    """Two per-element gathers from the state table inside one fused
    loop body: the dense-iteration contract is ONE (mask pre-gather,
    PERF_NOTES)."""
    table_shape = (1024,)

    def bad(s, table, idx):
        def body(i, acc):
            a = jnp.take(table, idx + i, axis=0)        # gather 1
            b = jnp.take(table, idx * 2 + i, axis=0)    # gather 2
            return acc + jnp.sum(a) + jnp.sum(b)

        return jax.lax.fori_loop(0, 4, body, s)

    closed = jax.make_jaxpr(bad)(
        jnp.float32(0), jnp.zeros(table_shape, jnp.float32),
        jnp.zeros((16,), jnp.int32))
    spec = ProgramSpec(table_shape=table_shape, gather_budget=1)
    findings = audit.audit_jaxpr(closed, spec, where="synthetic")
    assert any(f.check == "gather-budget" for f in findings)
    with pytest.raises(GatherBudgetError):
        _raise_all(findings)

    # the same body under budget 2 is clean
    spec2 = ProgramSpec(table_shape=table_shape, gather_budget=2)
    fs2 = audit.check_gather_budget(closed, spec2, "synthetic")
    assert fs2 == []


def test_const_bytes_violation():
    """A closed-over 2 MB constant bakes into the program — the
    HTTP-413 remote-compile wall, caught before any tunnel
    round-trip."""
    big = jnp.zeros((1 << 19,), jnp.float32)          # 2 MiB
    closed = jax.make_jaxpr(lambda x: x + jnp.sum(big))(
        jnp.float32(1))
    findings = audit.audit_jaxpr(closed, ProgramSpec(),
                                 where="synthetic")
    assert any(f.check == "const-bytes" for f in findings)
    with pytest.raises(ConstBytesError):
        _raise_all(findings)

    # passing the array as an ARGUMENT is the fix
    ok = jax.make_jaxpr(lambda x, b: x + jnp.sum(b))(
        jnp.float32(1), big)
    assert audit.check_const_bytes(ok, ProgramSpec(), "s") == []


def test_dtype_discipline_violation():
    """f64 avals (or any promotion past the state dtype) are
    forbidden — TPUs run 32-bit and silent x64 promotions double
    every table."""
    from jax.experimental import enable_x64
    with enable_x64():
        closed = jax.make_jaxpr(
            lambda x: x.astype(jnp.float64) * 2.0)(
            jnp.ones((8,), jnp.float32))
    findings = audit.audit_jaxpr(closed, ProgramSpec(),
                                 where="synthetic")
    assert any(f.check == "dtype-discipline" for f in findings)
    with pytest.raises(DtypeDisciplineError):
        _raise_all(findings)

    # an 8-byte state dtype legitimizes 8-byte avals
    spec = ProgramSpec(state_itemsize=8)
    assert audit.check_dtypes(closed, spec, "s") == []


def test_loop_invariant_violation():
    """An expensive dot of two loop-invariant operands inside a
    fori_loop body: XLA hoists it, so a benchmark timing the loop
    measures nothing (the CLAUDE.md trap) — a warning-class
    finding."""

    def bad(A, B, s0):
        def body(i, s):
            return s + jnp.sum(jnp.dot(A, B))     # A, B invariant

        return jax.lax.fori_loop(0, 8, body, s0)

    closed = jax.make_jaxpr(bad)(
        jnp.zeros((64, 64), jnp.float32),
        jnp.zeros((64, 64), jnp.float32), jnp.float32(0))
    findings = audit.audit_jaxpr(closed, ProgramSpec(),
                                 where="synthetic")
    inv = [f for f in findings if f.check == "loop-invariant"]
    assert inv and all(f.severity == "warn" for f in inv)
    _raise_all(findings)          # warnings alone do not raise...
    with pytest.raises(LoopInvariantError):      # ...unless asked
        _raise_all(findings, warnings_as_errors=True)

    # a dot CONSUMING the carry is loop-variant and clean
    def good2(A, s0):
        def body(i, s):
            return s + jnp.dot(A, s)
        return jax.lax.fori_loop(0, 8, body, s0)

    ok = jax.make_jaxpr(good2)(jnp.zeros((64, 64), jnp.float32),
                               jnp.zeros((64,), jnp.float32))
    assert audit.check_loop_invariant(ok, ProgramSpec(), "s") == []


def test_collective_schedule_violation():
    """A 'ring' taking ndev hops instead of ndev-1, and an owner
    exchange without its generation scan."""
    from lux_tpu.parallel.mesh import PARTS_AXIS, make_mesh
    from jax.sharding import PartitionSpec as P
    mesh = make_mesh(2)

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=P(PARTS_AXIS), out_specs=P(PARTS_AXIS))
    def bad_ring(x):
        for _ in range(2):                       # ndev hops: one too many
            x = jax.lax.ppermute(x, PARTS_AXIS, [(0, 1), (1, 0)])
        return x

    closed = jax.make_jaxpr(bad_ring)(jnp.zeros((2, 8), jnp.float32))
    spec = ProgramSpec(ppermute_hops=1, ring_size=2)
    findings = audit.audit_jaxpr(closed, spec, where="synthetic")
    assert any(f.check == "collective-schedule" for f in findings)
    with pytest.raises(CollectiveScheduleError):
        _raise_all(findings)

    # missing generation scan (require_scan_len with no scan at all)
    closed2 = jax.make_jaxpr(lambda x: x * 2)(jnp.zeros((4,)))
    fs2 = audit.check_collectives(
        closed2, ProgramSpec(require_scan_len=4), "synthetic")
    assert fs2 and fs2[0].check == "collective-schedule"
    with pytest.raises(CollectiveScheduleError):
        _raise_all(fs2)

    # a scan of the right LENGTH that never gathers from the state
    # shard (e.g. the fused iteration loop when num_iters happens to
    # equal the local part count) must NOT satisfy the owner check
    closed3 = jax.make_jaxpr(
        lambda x: jax.lax.fori_loop(0, 4, lambda i, s: s * 2.0, x))(
        jnp.float32(1))
    fs3 = audit.check_collectives(
        closed3, ProgramSpec(require_scan_len=4,
                             require_scan_shard_shape=(64,)),
        "synthetic")
    assert fs3 and fs3[0].check == "collective-schedule"


def test_callback_in_loop_violation():
    """A host callback inside a fused loop is a per-iteration tunnel
    round-trip — the exact failure the fused designs exist to
    avoid."""

    def bad(s):
        def body(i, acc):
            jax.debug.print("iter {i}", i=i)
            return acc + 1.0

        return jax.lax.fori_loop(0, 4, body, s)

    closed = jax.make_jaxpr(bad)(jnp.float32(0))
    findings = audit.audit_jaxpr(closed, ProgramSpec(),
                                 where="synthetic")
    assert any(f.check == "callback-in-loop" for f in findings)
    with pytest.raises(CallbackInLoopError):
        _raise_all(findings)

    # pure_callback is flagged too
    def bad2(s):
        def body(i, acc):
            v = jax.pure_callback(
                lambda a: a, jax.ShapeDtypeStruct((), jnp.float32),
                acc)
            return acc + v

        return jax.lax.fori_loop(0, 4, body, s)

    closed2 = jax.make_jaxpr(bad2)(jnp.float32(0))
    fs2 = audit.check_callbacks(closed2, ProgramSpec(), "s")
    assert fs2

    # the SAME callback outside any loop is fine (fetch at segment
    # boundaries is the sanctioned pattern)
    closed3 = jax.make_jaxpr(
        lambda s: jax.pure_callback(
            lambda a: a, jax.ShapeDtypeStruct((), jnp.float32), s))(
        jnp.float32(0))
    assert audit.check_callbacks(closed3, ProgramSpec(), "s") == []


def test_identity_init_violation():
    """A scatter-min onto a zeros-initialized buffer clamps every
    positive result — init must be the reduce identity (+inf)."""
    closed = jax.make_jaxpr(
        lambda v, i: jnp.zeros((8,), jnp.float32).at[i].min(v))(
        jnp.ones((16,), jnp.float32), jnp.zeros((16,), jnp.int32))
    findings = audit.audit_jaxpr(closed, ProgramSpec(),
                                 where="synthetic")
    assert any(f.check == "identity-init" for f in findings)
    with pytest.raises(IdentityInitError):
        _raise_all(findings)

    # the identity-initialized form is clean, and so is reducing
    # onto CARRIED data (a semantic relaxation, not an init)
    ok = jax.make_jaxpr(
        lambda v, i: jnp.full((8,), jnp.inf, jnp.float32)
        .at[i].min(v))(
        jnp.ones((16,), jnp.float32), jnp.zeros((16,), jnp.int32))
    assert audit.check_identity_inits(ok, ProgramSpec(), "s") == []
    carried = jax.make_jaxpr(
        lambda lab, v, i: lab.at[i].min(v))(
        jnp.ones((8,), jnp.float32), jnp.ones((16,), jnp.float32),
        jnp.zeros((16,), jnp.int32))
    assert audit.check_identity_inits(carried, ProgramSpec(),
                                      "s") == []


def test_ledger_drift_violation():
    """On a toy graph the tiled arrays' chunk padding dwarfs the
    epad-priced ledger; a near-zero tolerance turns that into the
    drift error (the stated default tolerance absorbs it only on
    dense graphs — see the audit module docstring)."""
    from lux_tpu.apps import pagerank
    eng = pagerank.build_engine(_graph(64, 400), num_parts=2)
    findings = audit.check_ledger(eng, tol=0.001)
    assert findings and findings[0].check == "ledger-drift"
    with pytest.raises(LedgerDriftError):
        _raise_all(findings)

    # a bench-shaped graph passes at the stated tolerance
    eng2 = pagerank.build_engine(_graph(2048, 32768, seed=2),
                                 num_parts=2)
    assert audit.check_ledger(eng2, tol=0.5) == []


# ---------------------------------------------------------------------
# allow= / pragma mechanics


def test_frontier_pragma_is_honored():
    """The push sparse path's CSR-expand scatter-max deliberately
    inits with 0 (1-based marks; see engine/frontier.py) — its
    ``# audit: allow(identity-init)`` pragma must suppress the
    finding, which the clean repo-wide audit depends on."""
    from lux_tpu.apps import sssp
    eng = sssp.build_engine(_graph(), 0, num_parts=2)
    findings = audit.audit_engine(eng, mode=None)
    assert [f for f in findings if f.check == "identity-init"] == []


# ---------------------------------------------------------------------
# audit= is a bitwise no-op on compiled outputs


def test_audit_never_alters_pull_outputs():
    from lux_tpu.apps import pagerank
    g = _graph()
    eng_a = pagerank.build_engine(g, num_parts=2, audit="error")
    eng_b = pagerank.build_engine(g, num_parts=2)
    out_a = np.asarray(eng_a.run(eng_a.init_state(), 4))
    out_b = np.asarray(eng_b.run(eng_b.init_state(), 4))
    np.testing.assert_array_equal(out_a, out_b)   # bitwise


def test_audit_never_alters_push_outputs():
    from lux_tpu.apps import sssp
    g = _graph()
    eng_a = sssp.build_engine(g, 0, num_parts=2, audit="error")
    eng_b = sssp.build_engine(g, 0, num_parts=2)
    lab_a, it_a = eng_a.run()
    lab_b, it_b = eng_b.run()
    assert it_a == it_b
    np.testing.assert_array_equal(lab_a, lab_b)   # bitwise


def test_audit_warn_mode_warns_not_raises(monkeypatch):
    """mode='warn' surfaces findings as AuditWarnings and returns
    them; mode='error' raises."""
    from lux_tpu.apps import pagerank
    g = _graph(64, 400)
    eng = pagerank.build_engine(g, num_parts=2)

    # inject a failing check by shrinking the const ceiling to 0
    real_spec = audit.engine_spec

    def tight_spec(engine, aval):
        return audit.ProgramSpec(
            **{**real_spec(engine, aval).__dict__,
               "const_bytes_max": -1})

    monkeypatch.setattr(audit, "engine_spec", tight_spec)
    with pytest.warns(audit.AuditWarning):
        fs = audit.audit_engine(eng, mode="warn")
    assert any(f.check == "const-bytes" for f in fs)
    with pytest.raises(ConstBytesError):
        audit.audit_engine(eng, mode="error")
    # allow= is the pragma mechanism's programmatic form
    fs = audit.audit_engine(eng, mode="error",
                            allow={"const-bytes"})
    assert fs == []


# ---------------------------------------------------------------------
# the tier-1 gate: repo-wide audit + AST lint, clean and fast


def test_repo_audit_clean():
    """Every engine configuration x every program variant traces and
    audits clean on the CPU backend (pragma-exempted findings
    included); the ledger cross-validation runs on the bench-shaped
    configs.  Budget: well under 60 s (measured ~5 s)."""
    findings = audit.run_repo_audit(ledger=True)
    assert findings == [], "\n".join(str(f) for f in findings)


def test_repo_audit_cli():
    """``python -m lux_tpu.audit`` (tracing-only form) exits 0."""
    assert audit.main(["-no-ledger"]) == 0


def test_lint_repo_clean():
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint_lux.py")],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr


def test_lint_event_name_drift(tmp_path):
    """Round-25 event-name check: an emit() with a string literal
    outside events_summary.KNOWN is drift (it would fail the
    runtime events audit only when it first fires); the pragma
    suppresses with justification."""
    bad = tmp_path / "emitter.py"
    bad.write_text(
        "def go(t):\n"
        "    t.emit(\"totally_unknown_event\", x=1)\n")
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint_lux.py"),
         str(bad)], capture_output=True, text=True, timeout=60)
    assert r.returncode == 1
    assert "event-name" in r.stderr

    ok = tmp_path / "ok.py"
    ok.write_text(
        "def go(t):\n"
        "    # audit: allow(event-name) test-only fixture event\n"
        "    t.emit(\"totally_unknown_event\", x=1)\n")
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint_lux.py"),
         str(ok)], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr


def test_lint_command_drift(tmp_path):
    """Round-25 command-drift check: a doc-cited
    ``python -m lux_tpu.<mod>`` must resolve to a module with a
    __main__ entry; the shipped docs are clean."""
    sys.path.insert(0, str(REPO / "scripts"))
    try:
        import lint_lux
    finally:
        sys.path.pop(0)
    (tmp_path / "CLAUDE.md").write_text(
        "smoke: `python -m lux_tpu.missing_mod`\n")
    (tmp_path / "lux_tpu").mkdir()
    (tmp_path / "lux_tpu" / "quiet.py").write_text(
        "def main():\n    return 0\n")
    (tmp_path / "ARCHITECTURE.md").write_text(
        "run `python -m lux_tpu.quiet` for the smoke\n")
    found = lint_lux.check_doc_commands(repo=str(tmp_path))
    checks = [f.check for f in found]
    assert checks.count("command-drift") == 2, found
    # the real repo docs resolve every cited command
    assert lint_lux.check_doc_commands() == []


def test_lockcheck_repo_clean():
    """The third enforcing tool (round 25): the host-concurrency &
    durability analyzer is green over the threaded serving modules
    — guarded-field, lock-order, durable-before-visible,
    snapshot-iteration, toctou-gate (tests/test_lockcheck.py holds
    the per-check violating fixtures).  Budget: ~2 s CPU."""
    from lux_tpu import lockcheck
    findings = lockcheck.run_lockcheck(mode="findings")
    assert findings == [], "\n".join(str(f) for f in findings)


def test_lint_detects_and_suppresses(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\nimport jax.numpy as jnp\n\n\n"
        "def build(x):\n"
        "    big = jnp.asarray(x)\n\n"
        "    @jax.jit\n"
        "    def step(s):\n"
        "        return s + big\n\n"
        "    return step\n")
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint_lux.py"),
         str(bad)], capture_output=True, text=True, timeout=60)
    assert r.returncode == 1
    assert "jit-closure" in r.stderr

    ok = tmp_path / "ok.py"
    ok.write_text(
        "import jax\nimport jax.numpy as jnp\n\n\n"
        "def build(x):\n"
        "    big = jnp.asarray(x)\n\n"
        "    # audit: allow(jit-closure) — test fixture\n"
        "    @jax.jit\n"
        "    def step(s):\n"
        "        return s + big\n\n"
        "    return step\n")
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint_lux.py"),
         str(ok)], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr


def test_lint_hot_path_metrics(tmp_path):
    """Round-17 hot-path-metrics check: a metrics call inside engine
    device code or a fused-loop body is flagged (metrics are
    host-side, segment-boundary only); host-side calls outside loop
    bodies pass, and the pragma suppresses per convention."""
    eng = tmp_path / "lux_tpu" / "engine"
    eng.mkdir(parents=True)
    bad_eng = eng / "bad.py"
    bad_eng.write_text(
        '"""Demo engine. reference pull_model.inl:423"""\n\n\n'
        "def build(metrics):\n"
        "    metrics.counter('x').inc()\n")
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint_lux.py"),
         str(bad_eng)], capture_output=True, text=True, timeout=60)
    assert r.returncode == 1
    assert "hot-path-metrics" in r.stderr

    loopy = tmp_path / "lux_tpu" / "loopy.py"
    loopy.write_text(
        "import jax\n\n\n"
        "def run(self):\n"
        "    def body(i, c):\n"
        "        self.metrics.gauge('g').set(i)\n"
        "        return c\n"
        "    return jax.lax.fori_loop(0, 3, body, 0)\n")
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint_lux.py"),
         str(loopy)], capture_output=True, text=True, timeout=60)
    assert r.returncode == 1
    assert "fused-loop body" in r.stderr

    # host-side (boundary) calls outside loop bodies are the contract
    fine = tmp_path / "lux_tpu" / "fine.py"
    fine.write_text(
        "def boundary(self, queued):\n"
        "    self.metrics.gauge('serve_queue_depth').set(queued)\n")
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint_lux.py"),
         str(fine)], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr

    loopy.write_text(
        "import jax\n\n\n"
        "def run(self):\n"
        "    def body(i, c):\n"
        "        # audit: allow(hot-path-metrics) test fixture\n"
        "        self.metrics.gauge('g').set(i)\n"
        "        return c\n"
        "    return jax.lax.fori_loop(0, 3, body, 0)\n")
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint_lux.py"),
         str(loopy)], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr


def test_lint_batched_oracle_coverage(tmp_path):
    """An app module shipping a batched builder without its batched
    oracle is flagged (ROADMAP item 2 oracle-first contract); adding
    the reference_*batched* oracle clears it."""
    apps = tmp_path / "lux_tpu" / "apps"
    apps.mkdir(parents=True)
    bad = apps / "newapp.py"
    bad.write_text(
        "def make_batched_program(sources):\n    return None\n\n\n"
        "def reference_newapp(g):\n    return None\n")
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint_lux.py"),
         str(bad)], capture_output=True, text=True, timeout=60)
    assert r.returncode == 1
    assert "batched" in r.stderr and "oracle" in r.stderr

    bad.write_text(
        "def make_batched_program(sources):\n    return None\n\n\n"
        "def reference_newapp_batched(g, sources):\n    return None\n")
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint_lux.py"),
         str(bad)], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr


def test_lint_incremental_oracle_coverage(tmp_path):
    """Round 20 (live graphs): an app module shipping an incremental
    builder/revalidator without its reference_*_incremental oracle is
    flagged — incremental device code must be provable equal to full
    recompute at the same epoch (lux_tpu/livegraph.py); adding the
    oracle clears it."""
    apps = tmp_path / "lux_tpu" / "apps"
    apps.mkdir(parents=True)
    bad = apps / "newapp.py"
    bad.write_text(
        "def build_incremental_step(g):\n    return None\n\n\n"
        "def reference_newapp(g):\n    return None\n")
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint_lux.py"),
         str(bad)], capture_output=True, text=True, timeout=60)
    assert r.returncode == 1
    assert "incremental" in r.stderr and "oracle" in r.stderr

    bad.write_text(
        "def build_incremental_step(g):\n    return None\n\n\n"
        "def reference_newapp(g):\n    return None\n\n\n"
        "def reference_newapp_incremental(g, old):\n    return None\n")
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint_lux.py"),
         str(bad)], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr

    # a METHOD revalidator (the LiveGraph.revalidate shape) is
    # caught too — tree.body-only scans are blind to it
    bad.write_text(
        "class Live:\n"
        "    def revalidate(self, eng):\n        return None\n\n\n"
        "def reference_newapp(g):\n    return None\n")
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint_lux.py"),
         str(bad)], capture_output=True, text=True, timeout=60)
    assert r.returncode == 1
    assert "incremental" in r.stderr and "oracle" in r.stderr

    # ... and an explicit cross-module oracle citation clears it
    # (the convention allows the oracle to live in its app module)
    bad.write_text(
        "class Live:\n"
        "    def revalidate(self, eng):\n"
        "        '''proved equal to apps/sssp."
        "reference_sssp_incremental'''\n"
        "        return None\n\n\n"
        "def reference_newapp(g):\n    return None\n")
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint_lux.py"),
         str(bad)], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr


def test_lint_chaos_coverage(tmp_path):
    """Round 24 (self-healing): every fault-plan action constant in
    lux_tpu/faults.py must be drilled by some tests/ file — an action
    nobody injects is a recovery path that ships untested.  A bogus
    undrilled action is flagged, the pragma suppresses it, and a
    really-drilled action (WORKER_KILL) passes."""
    pkg = tmp_path / "lux_tpu"
    pkg.mkdir(parents=True)
    fake = pkg / "faults.py"
    # build the undrilled name/value by concatenation — writing them
    # as literals HERE would put them in tests/ and satisfy the scan
    name = "BOGUS_" + "UNDRILLED"
    value = "bogus_" + "undrilled_xyz"
    fake.write_text(f'{name} = "{value}"\n')
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint_lux.py"),
         str(fake)], capture_output=True, text=True, timeout=60)
    assert r.returncode == 1
    assert "chaos-coverage" in r.stderr
    assert value in r.stderr

    fake.write_text(
        "# audit: allow(chaos-coverage) — lint test fixture\n"
        f'{name} = "{value}"\n')
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint_lux.py"),
         str(fake)], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr

    # an action the suite actually drills (tests/test_fleet.py arms
    # WORKER_KILL plans) is clean without any pragma
    fake.write_text('WORKER_KILL = "worker_kill"\n')
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint_lux.py"),
         str(fake)], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr


def test_unknown_audit_mode_is_typed_error():
    """A typo'd mode must not silently disable enforcement — both
    the engine param and audit_engine reject it."""
    from lux_tpu.apps import pagerank
    g = _graph(64, 400)
    with pytest.raises(ValueError, match="audit mode"):
        pagerank.build_engine(g, num_parts=2, audit="Error")
    eng = pagerank.build_engine(g, num_parts=2)
    with pytest.raises(ValueError, match="audit mode"):
        audit.audit_engine(eng, mode="off")


def test_audit_errors_classify_fatal():
    """A static-audit violation is a property of the BUILD: the
    resilience supervisor must never retry it — even when the finding
    text happens to contain words ('tunnel', '413') the retryable
    message scan matches."""
    from lux_tpu import resilience
    assert resilience.classify(
        CallbackInLoopError("a host round-trip per iteration "
                            "through the tunnel")) == "fatal"
    assert resilience.classify(
        ConstBytesError("remote compiler rejects ... HTTP 413")) \
        == "fatal"


def test_gather_budget_pragma_exempts_eqn(tmp_path):
    """An explicit source pragma on a gather excludes it from the
    budget count (the eqn-anchored exemption form)."""
    import importlib.util
    mod_path = tmp_path / "praggather.py"
    mod_path.write_text(
        "import jax\nimport jax.numpy as jnp\n\n\n"
        "def bad(s, table, idx):\n"
        "    def body(i, acc):\n"
        "        a = jnp.take(table, idx + i, axis=0)\n"
        "        # audit: allow(gather-budget) — test fixture\n"
        "        b = jnp.take(table, idx * 2 + i, axis=0)\n"
        "        return acc + jnp.sum(a) + jnp.sum(b)\n\n"
        "    return jax.lax.fori_loop(0, 4, body, s)\n")
    spec_m = importlib.util.spec_from_file_location("praggather",
                                                    mod_path)
    mod = importlib.util.module_from_spec(spec_m)
    spec_m.loader.exec_module(mod)
    closed = jax.make_jaxpr(mod.bad)(
        jnp.float32(0), jnp.zeros((1024,), jnp.float32),
        jnp.zeros((16,), jnp.int32))
    spec = ProgramSpec(table_shape=(1024,), gather_budget=1)
    assert audit.check_gather_budget(closed, spec, "s") == []


def test_digest_shape():
    fs = [audit.Finding("gather-budget", "error", "x", "d"),
          audit.Finding("loop-invariant", "warn", "x", "d")]
    d = audit.digest(fs, mode="error")
    assert d == {"mode": "error", "errors": 1, "warnings": 1,
                 "failed_checks": ["gather-budget"]}
