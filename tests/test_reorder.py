"""Locality harvest (round 16): page-aware vertex reordering.

- native/numpy reorder contract: bijection, degree histogram
  preserved, every mode;
- the hill-climb driver's measured-objective trail and the ROADMAP
  acceptance: on the scrambled locality-rich community shape the
  measured ``page_fill`` rises from the R-MAT 6-12 band to >= 23
  (the paged break-even) and ``gather="auto"`` leaves the flat path;
- permutation-invariance oracles: each of the four apps runs on a
  reordered graph, results map back through the inverse permutation
  and must equal the unreordered run — BITWISE for the integer
  (min/max) apps, tolerance for the float (sum) apps whose reductions
  re-associate — on 1 and 8 virtual devices;
- the ``.perm`` sidecar round-trip through ``Graph.from_file``;
- the bench gather-ab reorder lines end-to-end through
  scripts/check_bench.py.
"""

import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from lux_tpu import format as luxfmt
from lux_tpu import native
from lux_tpu.convert import community_graph
from lux_tpu.graph import Graph
from lux_tpu.reorder import apply_perm, page_fill_stats, page_reorder

REPO = Path(__file__).resolve().parent.parent


def _community(scale=12, ef=8, cs=7, seed=0, weighted=False):
    return community_graph(scale=scale, edge_factor=ef,
                           community_scale=cs, seed=seed,
                           weighted=weighted)


# ---------------------------------------------------------------------
# reorder pass contract


@pytest.mark.parametrize("mode", ["cm", "hubs", "communities"])
def test_reorder_cluster_bijection_and_degrees(mode):
    g = _community(scale=10, ef=6)
    src, dst = g.edge_arrays()
    perm = native.reorder_cluster(src, dst, g.nv, mode=mode)
    assert sorted(perm.tolist()) == list(range(g.nv))
    # degree histogram preserved under the relabel: deg_new[i] ==
    # deg_old[perm[i]] (so the multiset is invariant)
    deg = (np.bincount(src, minlength=g.nv)
           + np.bincount(dst, minlength=g.nv))
    g2 = apply_perm(g, perm)
    s2, d2 = g2.edge_arrays()
    deg2 = (np.bincount(s2, minlength=g.nv)
            + np.bincount(d2, minlength=g.nv))
    assert np.array_equal(deg2, deg[perm])


@pytest.mark.parametrize("mode", [0, 1, 2])
def test_reorder_numpy_fallback_contract(mode):
    """The toolchain-less fallback holds the same contract (not the
    same order — the C++ pass is the production one)."""
    g = _community(scale=9, ef=6)
    src, dst = g.edge_arrays()
    perm = native._reorder_cluster_numpy(
        src.astype(np.uint32), dst.astype(np.uint32), g.nv, mode)
    assert sorted(perm.tolist()) == list(range(g.nv))


def test_reorder_cluster_guards():
    with pytest.raises(ValueError, match="mode"):
        native.reorder_cluster(np.zeros(1, np.uint32),
                               np.zeros(1, np.uint32), 2,
                               mode="bogus")
    with pytest.raises(ValueError, match="outside"):
        native.reorder_cluster(np.array([5], np.uint32),
                               np.array([0], np.uint32), 2)


def test_page_reorder_trail_and_methods():
    """The driver scores every candidate against the plan builder's
    measured objective and never returns a worse-than-baseline order;
    method='none' is the identity."""
    g = _community(scale=11)
    g0, perm0, rep0 = page_reorder(g, method="none")
    assert g0 is g and np.array_equal(perm0, np.arange(g.nv))
    for method in ("degree", "native", "hillclimb"):
        g2, perm, rep = page_reorder(g, method=method)
        assert sorted(perm.tolist()) == list(range(g.nv))
        assert rep["chosen_fill"] >= rep["baseline_fill"]
        assert "none" in rep["candidates"]
        # the report's chosen fill IS the returned order's measured
        # fill (the inspection trail is honest)
        st = page_fill_stats(g2)
        assert rep["chosen_fill"] == pytest.approx(
            st["padded_fill"], abs=1e-2)
    with pytest.raises(ValueError, match="method"):
        page_reorder(g, method="bogus")


def test_acceptance_fill_recovers_past_break_even():
    """THE round-16 acceptance: the scrambled community shape starts
    in the R-MAT 6-12 fill band; the reorder pass lifts the plan
    builder's measured page_fill past the break-even 23, and
    ``gather="auto"`` then leaves the flat path (both the resolution
    rule and a real engine build)."""
    from lux_tpu.apps import pagerank
    from lux_tpu.graph import ShardedGraph
    from lux_tpu.ops.pagegather import plan_paged_stats, resolve_gather
    from lux_tpu.scalemodel import page_break_even_fill

    g = community_graph(scale=14, edge_factor=8, community_scale=8,
                        seed=0)
    base = page_fill_stats(g)["padded_fill"]
    assert base < 13, "scramble must start in the R-MAT band"
    g2, _perm, rep = page_reorder(g, method="hillclimb")
    assert rep["chosen_fill"] >= 23
    assert rep["chosen_fill"] >= page_break_even_fill()

    sg = ShardedGraph.build(g2, 2, vpad_align=128)
    st = plan_paged_stats(sg, pagemajor=True)
    table = 4 * sg.num_parts * sg.vpad
    assert resolve_gather("auto", st, table) != "flat"
    # and on the UNREORDERED graph auto stays flat (the honest
    # round-15 negative, now an A/B inside one test)
    sg0 = ShardedGraph.build(g, 2, vpad_align=128)
    st0 = plan_paged_stats(sg0, pagemajor=True)
    assert resolve_gather("auto", st0, table) == "flat"

    eng = pagerank.build_engine(g2, num_parts=2, gather="auto")
    assert eng.gather in ("paged", "pagemajor")
    assert eng.page_plan is not None


# ---------------------------------------------------------------------
# permutation-invariance oracles: 4 apps, 1 and 8 devices


def _mesh8():
    from lux_tpu.parallel.mesh import make_mesh
    return make_mesh(8)


def _unmap(result, perm):
    """Map a reordered run's [nv, ...] result back to original ids:
    row new of the reordered run is original vertex perm[new]."""
    out = np.empty_like(result)
    out[np.asarray(perm)] = result
    return out


@pytest.mark.parametrize("np_mesh", [(2, False), (8, True)],
                         ids=["np2", "mesh8"])
def test_invariance_pagerank_colfilter_float(np_mesh):
    """Float (sum-reduce) apps: reorder + map-back equals the
    unreordered run to tight tolerance (sums re-associate across
    layouts, so bitwise is not the contract — same discipline as the
    paged parity tests)."""
    from lux_tpu.apps import colfilter, pagerank

    num_parts, use_mesh = np_mesh
    mesh = _mesh8() if use_mesh else None
    g = _community()
    g2, perm, _rep = page_reorder(g, method="native")

    eng = pagerank.build_engine(g, num_parts=num_parts, mesh=mesh)
    a = np.asarray(eng.unpad(eng.run(eng.init_state(), 5)))
    eng2 = pagerank.build_engine(g2, num_parts=num_parts,
                                 mesh=mesh, gather="auto")
    b = np.asarray(eng2.unpad(eng2.run(eng2.init_state(), 5)))
    np.testing.assert_allclose(_unmap(b, perm), a, rtol=2e-6,
                               atol=1e-9)

    gw = _community(weighted=True)
    gw2 = apply_perm(gw, perm)
    ec = colfilter.build_engine(gw, num_parts=num_parts, mesh=mesh)
    c = np.asarray(ec.unpad(ec.run(ec.init_state(), 3)))
    ec2 = colfilter.build_engine(gw2, num_parts=num_parts, mesh=mesh)
    d = np.asarray(ec2.unpad(ec2.run(ec2.init_state(), 3)))
    np.testing.assert_allclose(_unmap(d, perm), c, rtol=2e-5,
                               atol=1e-8)


@pytest.mark.parametrize("np_mesh", [(2, False), (8, True)],
                         ids=["np2", "mesh8"])
def test_invariance_sssp_components_bitwise(np_mesh):
    """Integer (min/max-reduce) apps: reorder + map-back is BITWISE
    equal to the unreordered run — min/max fixed points are
    order-independent, so any deviation is a real indexing bug."""
    from lux_tpu.apps import components, sssp

    num_parts, use_mesh = np_mesh
    mesh = _mesh8() if use_mesh else None
    gw = _community(weighted=True)
    g2, perm, _rep = page_reorder(gw, method="native")
    rank = np.empty(gw.nv, np.int64)
    rank[perm] = np.arange(gw.nv)

    start = 17
    ea = sssp.build_engine(gw, start, weighted=True,
                           num_parts=num_parts, mesh=mesh)
    la, aa = ea.init_state()
    la, _act, _it = ea.converge(la, aa)
    a = np.asarray(ea.unpad(la))
    eb = sssp.build_engine(g2, int(rank[start]), weighted=True,
                           num_parts=num_parts, mesh=mesh,
                           gather="auto")
    lb, ab = eb.init_state()
    lb, _act, _it = eb.converge(lb, ab)
    b = np.asarray(eb.unpad(lb))
    assert np.array_equal(_unmap(b, perm), a)

    # p_in=1.0: the scrambled communities ARE the components (32 of
    # them) — a far stronger partition-invariance probe than one
    # giant component
    giso = community_graph(scale=12, edge_factor=8,
                           community_scale=7, p_in=1.0, seed=4)
    s2, d2 = components.symmetrize(*giso.edge_arrays())
    gc = Graph.from_edges(s2.astype(np.uint32), d2.astype(np.uint32),
                          giso.nv)
    gc2 = apply_perm(gc, perm)
    ec = components.build_engine(gc, num_parts=num_parts, mesh=mesh,
                                 enable_sparse=False)
    lc, ac = ec.init_state()
    lc, _act, _it = ec.converge(lc, ac)
    c = np.asarray(ec.unpad(lc))
    ed = components.build_engine(gc2, num_parts=num_parts, mesh=mesh,
                                 enable_sparse=False, gather="auto")
    ld, ad = ed.init_state()
    ld, _act, _it = ed.converge(ld, ad)
    d = np.asarray(ed.unpad(ld))
    # component LABELS are representative vertex ids (max over the
    # component), and the max of the NEW ids is a different vertex —
    # the invariant is the PARTITION: the mapped-back labeling must
    # induce exactly the original equivalence classes (a bijection
    # between label values), checked bitwise on the canonicalized
    # labelings
    dm = _unmap(d, perm)

    def canonical(lab):
        # relabel every class by its smallest member index
        first = {}
        out = np.empty_like(lab)
        for i, v in enumerate(lab.tolist()):
            if v not in first:
                first[v] = i
            out[i] = first[v]
        return out

    assert np.array_equal(canonical(dm), canonical(c))


# ---------------------------------------------------------------------
# sidecar + load path


def test_sidecar_roundtrip_through_from_file(tmp_path):
    g = _community(scale=10)
    p = str(tmp_path / "g.lux")
    luxfmt.write_lux(p, g.row_ptrs, g.col_idx)
    _g2, perm, _rep = page_reorder(g, method="native")
    luxfmt.write_perm_sidecar(p, perm)
    loaded = Graph.from_file(p, reorder=True)
    want = apply_perm(g, perm)
    assert np.array_equal(loaded.col_idx, want.col_idx)
    assert np.array_equal(loaded.row_ptrs, want.row_ptrs)
    # auto: applies when present, identity when absent
    auto = Graph.from_file(p, reorder="auto")
    assert np.array_equal(auto.col_idx, want.col_idx)
    p2 = str(tmp_path / "bare.lux")
    luxfmt.write_lux(p2, g.row_ptrs, g.col_idx)
    bare = Graph.from_file(p2, reorder="auto")
    assert np.array_equal(np.asarray(bare.col_idx),
                          np.asarray(g.col_idx))
    with pytest.raises(luxfmt.GraphFormatError, match="perm"):
        Graph.from_file(p2, reorder=True)
    with pytest.raises(ValueError, match="reorder"):
        Graph.from_file(p, reorder="sometimes")


def test_sidecar_validation_typed_errors(tmp_path):
    g = _community(scale=9)
    p = str(tmp_path / "g.lux")
    luxfmt.write_lux(p, g.row_ptrs, g.col_idx)
    perm = np.random.default_rng(0).permutation(g.nv)
    sp = luxfmt.write_perm_sidecar(p, perm)
    assert np.array_equal(luxfmt.read_perm_sidecar(p, nv=g.nv), perm)
    # duplicate entry -> bijection check
    bad = perm.copy()
    bad[0] = bad[1]
    with pytest.raises(luxfmt.GraphFormatError) as e:
        luxfmt.validate_perm(bad, g.nv, "x")
    assert e.value.check == "perm_bijection"
    # wrong nv -> length check
    with pytest.raises(luxfmt.GraphFormatError) as e:
        luxfmt.read_perm_sidecar(p, nv=g.nv + 1)
    assert e.value.check == "perm_length"
    # truncated payload
    raw = open(sp, "rb").read()
    open(sp, "wb").write(raw[:-4])
    with pytest.raises(luxfmt.GraphFormatError) as e:
        luxfmt.read_perm_sidecar(p, nv=g.nv)
    assert e.value.check == "perm_length"
    # bad magic
    open(sp, "wb").write(b"XXXX" + raw[4:])
    with pytest.raises(luxfmt.GraphFormatError) as e:
        luxfmt.read_perm_sidecar(p, nv=g.nv)
    assert e.value.check == "perm_header"
    # a corrupt sidecar cannot be WRITTEN either
    with pytest.raises(luxfmt.GraphFormatError):
        luxfmt.write_perm_sidecar(p, bad)


def test_fsck_reports_sidecar(tmp_path):
    g = _community(scale=9)
    p = str(tmp_path / "g.lux")
    luxfmt.write_lux(p, g.row_ptrs, g.col_idx)
    fsck = str(REPO / "scripts" / "fsck_lux.py")
    r = subprocess.run([sys.executable, fsck, p],
                       capture_output=True, text=True)
    assert r.returncode == 0 and "perm=no" in r.stdout
    luxfmt.write_perm_sidecar(p, np.arange(g.nv))
    r = subprocess.run([sys.executable, fsck, p],
                       capture_output=True, text=True)
    assert r.returncode == 0 and "perm=yes" in r.stdout
    # torn sidecar fails the file
    with open(p + ".perm", "r+b") as f:
        f.seek(9)
        f.write(b"\xff\xff\xff")
    r = subprocess.run([sys.executable, fsck, p],
                       capture_output=True, text=True)
    assert r.returncode == 1 and "perm_" in r.stderr


# ---------------------------------------------------------------------
# bench gather-ab reorder lines -> check_bench


def test_bench_gather_ab_reorder_lines(tmp_path):
    """The acceptance instrument end-to-end (in-process, tiny shape):
    bench.run_config produces the reordered + paired none gather-ab
    lines on the community shape; the reordered line's measured
    page_fill crosses the break-even, auto selects the page-binned
    path, and scripts/check_bench.py ACCEPTS the artifact (schema +
    the fill-not-decreased pairing rule)."""
    import argparse

    sys.path.insert(0, str(REPO))
    import bench

    args = argparse.Namespace(
        scale=13, ef=8, np=1, ni=2, repeats=1, pair=0, verbose=False,
        health=False, audit="warn", shape="community",
        reorder="hillclimb", batch="1")
    lines = []
    for cfg in ("gather-ab@paged", "gather-ab@flat",
                "gather-ab@paged:hillclimb",
                "gather-ab@flat:hillclimb"):
        name, samples, extra, _rerun = bench.run_config(cfg, args)
        value = round(float(np.median(samples)), 4)
        line = dict(metric=name + "_gteps_per_chip", value=value,
                    unit="GTEPS", vs_baseline=value,
                    samples=[round(s, 4) for s in samples],
                    attempts=len(samples), discarded=[], **extra)
        lines.append(line)
    by = {ln["metric"]: ln for ln in lines}
    pn = by["pagerank_paged_comm13_gteps_per_chip"]
    pr = by["pagerank_paged_hillclimb_comm13_gteps_per_chip"]
    assert pn["reorder"] == "none" and pr["reorder"] == "hillclimb"
    assert pr["page_fill"] >= 23 > pn["page_fill"]
    assert pr["page_ratio"] > 0

    out = tmp_path / "bench.jsonl"
    out.write_text("".join(json.dumps(ln) + "\n" for ln in lines))
    chk = str(REPO / "scripts" / "check_bench.py")
    r = subprocess.run([sys.executable, chk, "-legacy-ok", str(out)],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr

    # the pairing rule rejects a published pair whose fill DROPPED
    pr_bad = dict(pr, page_fill=pn["page_fill"] - 1)
    bad = tmp_path / "bad.jsonl"
    bad.write_text("".join(json.dumps(ln) + "\n"
                           for ln in [pn, pr_bad]))
    r = subprocess.run([sys.executable, chk, "-legacy-ok", str(bad)],
                       capture_output=True, text=True)
    assert r.returncode == 1 and "DECREASED" in r.stderr


def test_observe_debts_registered():
    """The round-16 carried debts are machine-encoded; the
    reorder-fill-ab probe is implemented (platform-any: the fill
    objective is host-measured) and pagemajor-route-ab waits on a
    real mesh."""
    from lux_tpu import observe

    ids = {d.id: d for d in observe.DEBTS}
    assert ids["reorder-fill-ab"].auto == "_debt_reorder_fill_ab"
    assert ids["reorder-fill-ab"].platform == "any"
    assert ids["pagemajor-route-ab"].auto is None
    assert ids["pagemajor-route-ab"].min_ndev >= 2


@pytest.mark.slow
def test_reorder_fill_debt_probe():
    """The probe itself: fills for all three orders, hillclimb >=
    native >= ... and the payload is ledger-shaped."""
    from lux_tpu import observe

    fp = observe.calibrate()
    rec = observe._debt_reorder_fill_ab(fp)
    assert rec["debt"] == "reorder-fill-ab"
    orders = rec["orders"]
    assert set(orders) == {"none", "native", "hillclimb"}
    assert orders["hillclimb"]["page_fill"] >= \
        orders["none"]["page_fill"]
    assert orders["hillclimb"]["auto_resolves"] != "flat"
