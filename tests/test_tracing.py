"""lux_tpu/tracing.py: span timeline export, crash flight recorder,
and the line-atomic multi-writer event log (round-13 tentpole).

Acceptance bars under test:
- trace-export round trip on a RECORDED elastic-drill event log:
  spans nest, no orphans, the mesh-shrink instant marker is present
  and post-shrink execution spans move to a new track;
- two concurrent writer processes sharing one event file can never
  interleave mid-line (EventLog's single-write O_APPEND contract),
  and the merged log exports with one trace process per stream;
- the flight recorder dumps a diagnosable FLIGHT.json on an injected
  NaN fault (last health word + recent-event ring), atomically;
- the ``python -m lux_tpu.tracing`` smoke exports a valid trace from
  a CPU app run (the tier-1 smoke).
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from lux_tpu import telemetry, tracing

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _no_leaked_recorder():
    yield
    tracing.uninstall_flight_recorder()


def _spans(trace, cat=None):
    return [e for e in trace["traceEvents"] if e.get("ph") == "X"
            and (cat is None or e.get("cat") == cat)]


def _instants(trace, name=None):
    return [e for e in trace["traceEvents"] if e.get("ph") == "i"
            and (name is None or e["name"] == name)]


# ---------------------------------------------------------------------
# trace export: recorded elastic drill round trip

@pytest.fixture(scope="module")
def drill_events(tmp_path_factory):
    """One recorded in-process elastic drill (DEVICE_LOSS at a
    segment boundary, re-placement onto the surviving half-mesh) —
    the round-trip source log."""
    wd = tmp_path_factory.mktemp("drill")
    path = str(wd / "events.jsonl")
    tracing.run_loss_drill(str(wd), path)
    events, errs = tracing.load_events(path)
    assert not errs, errs
    return events


def test_trace_export_round_trip_elastic_drill(drill_events,
                                               tmp_path):
    out = str(tmp_path / "trace.json")
    trace = tracing.trace_export(drill_events, out=out)
    # the written artifact IS the returned trace
    assert json.load(open(out)) == trace
    # machine-validated: spans nest, no orphans
    assert tracing.validate_trace(trace) == []
    # the elastic story is on the timeline: a run span, >= 2 attempt
    # spans (the topology fault forced a retry), the mesh-shrink
    # instant marker, and execution spans on BOTH sides of the shrink
    assert len(_spans(trace, "run")) == 1
    assert len(_spans(trace, "attempt")) >= 2
    assert len(_instants(trace, "mesh_shrink")) == 1
    tids = {e["tid"] for e in _spans(trace, "exec")}
    assert len(tids) >= 2, \
        "post-shrink exec spans must move to a new track"
    # every exec span has positive extent and numeric bounds
    assert all(e["dur"] >= 0 and e["ts"] >= 0
               for e in _spans(trace))
    # the run span carries the per-part imbalance digest
    run = _spans(trace, "run")[0]
    ist = run.get("args", {}).get("iter_stats")
    assert ist and "imbalance" in ist and "parts_changed" in ist
    assert sum(ist["parts_changed"]) == ist["changed_sum"]


def test_trace_export_merges_streams_onto_separate_tracks(
        drill_events, tmp_path):
    """A two-process log (same shape a heartbeat drill appends into
    one shared file) exports with one trace process per (session,
    pid) stream."""
    second = []
    for ev in drill_events:
        ev2 = dict(ev)
        ev2["session"] = "feedfacebeef"
        ev2["pid"] = 424242
        second.append(ev2)
    merged = []
    for a, b in zip(drill_events, second):   # fully interleaved
        merged += [a, b]
    trace = tracing.trace_export(merged)
    assert tracing.validate_trace(trace) == []
    assert trace["otherData"]["streams"] == 2
    pids = {e.get("pid") for e in _spans(trace, "run")}
    assert len(pids) == 2
    names = {e["args"]["name"]
             for e in trace["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert any("feedfacebeef" in n for n in names)


def test_validate_trace_catches_overlap_and_orphan():
    base = dict(ph="X", cat="exec", pid=0, tid=1)
    run = dict(ph="X", cat="run", name="run", ts=0.0, dur=100.0,
               pid=0, tid=0)
    # partial overlap on one track
    bad = {"traceEvents": [
        run, dict(base, name="a", ts=10.0, dur=50.0),
        dict(base, name="b", ts=40.0, dur=50.0)]}
    errs = tracing.validate_trace(bad)
    assert any("must nest" in e for e in errs)
    # orphan: outside every run span
    bad2 = {"traceEvents": [
        run, dict(base, name="late", ts=150.0, dur=10.0)]}
    errs2 = tracing.validate_trace(bad2)
    assert any("orphan" in e for e in errs2)
    # the clean version of the same shapes validates
    good = {"traceEvents": [
        run, dict(base, name="a", ts=10.0, dur=30.0),
        dict(base, name="b", ts=50.0, dur=30.0)]}
    assert tracing.validate_trace(good) == []


def _fleet_events():
    """Synthetic serving-fleet trail: qid 0 starts on replica r1,
    fails over to r0 mid-flight, retires on r0."""
    base = {"pid": 1, "session": "s"}
    return [
        dict(base, t=1.0, tm=1.0, kind="run_start", schema=1,
             app="fleet"),
        dict(base, t=1.1, tm=1.1, kind="query_enqueue", qid=0,
             query_kind="sssp"),
        dict(base, t=1.2, tm=1.2, kind="query_start", qid=0,
             query_kind="sssp", col=0, wait_s=0.1, replica="r1"),
        dict(base, t=1.5, tm=1.5, kind="replica_lost", replica="r1",
             error="InjectedWorkerKill", message="boom", inflight=1),
        dict(base, t=1.55, tm=1.55, kind="failover", qid=0,
             query_kind="sssp", from_replica="r1", to_replica="r0",
             attempt=1, backoff_s=0.01),
        dict(base, t=1.6, tm=1.6, kind="query_start", qid=0,
             query_kind="sssp", col=0, wait_s=0.5, replica="r0"),
        dict(base, t=2.0, tm=2.0, kind="query_done", qid=0,
             query_kind="sssp", col=0, iters=4, segments=2,
             latency_s=0.9, wait_s=0.1, converged=True,
             replica="r0"),
        dict(base, t=2.1, tm=2.1, kind="run_done", seconds=1.1,
             iters=4),
    ]


def test_failover_renders_as_query_track_transition(tmp_path):
    """Round 18 (lux_tpu/fleet.py): a failover SPLITS the qid's span
    — the pre-failover segment sits on the dead replica's lane
    group, the post-failover segment (carrying the failover record)
    on the survivor's, and validate_trace accepts the transition."""
    trace = tracing.trace_export(_fleet_events(),
                                 out=str(tmp_path / "t.json"))
    assert tracing.validate_trace(trace) == []
    qs = sorted(_spans(trace, "query"), key=lambda e: e["ts"])
    assert len(qs) == 2
    pre, post = qs
    assert pre["args"]["replica"] == "r1"
    assert "failover_from" not in pre["args"]
    assert post["args"]["failover_from"] == "r1"
    assert post["args"]["failover_to"] == "r0"
    assert post["args"]["replica"] == "r0"
    assert pre["tid"] != post["tid"], \
        "failover did not transition tracks"
    # lanes are labeled per replica
    names = {e["args"]["name"] for e in trace["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "thread_name"}
    assert "queries[r1].0" in names and "queries[r0].0" in names


def test_validate_trace_rejects_broken_failover():
    run = dict(ph="X", cat="run", name="run", ts=0.0, dur=100.0,
               pid=0, tid=0)

    def q(ts, dur, tid, **args):
        return dict(ph="X", cat="query", name="q0", ts=ts, dur=dur,
                    pid=0, tid=tid, args=dict(qid=0, **args))

    # two spans for one qid without a failover record = a duplicate
    # retirement
    dup = {"traceEvents": [run, q(10.0, 20.0, 100),
                           q(40.0, 20.0, 140)]}
    errs = tracing.validate_trace(dup)
    assert any("retire exactly once" in e for e in errs)
    # a post-failover span on the SAME track is no transition
    same = {"traceEvents": [run, q(10.0, 20.0, 100),
                            q(40.0, 20.0, 100, failover_from="r1",
                              failover_to="r0")]}
    errs = tracing.validate_trace(same)
    assert any("track transition" in e or "SAME track" in e
               for e in errs)
    # a post-failover span claiming a replica other than its own
    # failover target contradicts itself
    lie = {"traceEvents": [run, q(10.0, 20.0, 100),
                           q(40.0, 20.0, 140, failover_from="r1",
                             failover_to="r0", replica="r9")]}
    errs = tracing.validate_trace(lie)
    assert any("contradicts its own transition" in e for e in errs)
    # the clean split validates
    good = {"traceEvents": [run, q(10.0, 20.0, 100),
                            q(40.0, 20.0, 140, failover_from="r1",
                              failover_to="r0", replica="r0")]}
    assert tracing.validate_trace(good) == []


# ---------------------------------------------------------------------
# EventLog: line-atomic appends under concurrent multi-process writers

_WRITER = r"""
import sys
sys.path.insert(0, {repo!r})
from lux_tpu import telemetry
ev = telemetry.EventLog({path!r}, rotate_bytes={rotate!r})
pad = "x" * 2000          # long lines provoke torn buffered writes
for i in range(300):
    ev.emit("writer_mark", i=i, who={who!r}, pad=pad)
ev.close()
print("WRITER_DONE")
"""


def test_event_log_concurrent_writers_line_atomic(tmp_path):
    """Two processes appending 300 long events each into ONE file:
    every line must parse (no mid-line interleaving — the O_APPEND
    single-write contract) and each (session, pid) stream must be
    complete and in order."""
    path = str(tmp_path / "shared.jsonl")
    procs = [subprocess.Popen(
        [sys.executable, "-c",
         _WRITER.format(repo=str(REPO), path=path, who=f"w{i}",
                        rotate=None)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i in range(2)]
    outs = [p.communicate(timeout=120)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    lines = open(path).read().splitlines()
    assert len(lines) == 600
    events = [json.loads(ln) for ln in lines]      # raises on a tear
    by_pid = {}
    for e in events:
        assert e["kind"] == "writer_mark"
        by_pid.setdefault((e["session"], e["pid"]), []).append(e)
    assert len(by_pid) == 2
    for evs in by_pid.values():
        assert [e["i"] for e in evs] == list(range(300))
        tms = [e["tm"] for e in evs]
        assert tms == sorted(tms)


def test_event_log_rotation_concurrent_writers_line_atomic(tmp_path):
    """Round-17 regression beside the atomicity test: two processes
    appending through SIZE-TRIGGERED ROTATION (rotate_bytes) into one
    shared path.  The whole .2/.1/live generation set must hold every
    line un-torn, each writer's stream complete and tm-ordered across
    the generation concatenation, and the set must export as one
    valid trace (the rotated-file-set acceptance of trace_export)."""
    path = str(tmp_path / "shared.jsonl")
    # ~1.23 MB total at a 700 KB threshold -> exactly one or two
    # rotations: the 2-generation window retains every line
    procs = [subprocess.Popen(
        [sys.executable, "-c",
         _WRITER.format(repo=str(REPO), path=path, who=f"w{i}",
                        rotate=700_000)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i in range(2)]
    outs = [p.communicate(timeout=120)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    gens = telemetry.rotated_paths(path)
    assert len(gens) >= 2, "rotation never fired"
    events = []
    for gen in gens:
        for ln in open(gen).read().splitlines():
            events.append(json.loads(ln))          # raises on a tear
    rotations = [e for e in events if e["kind"] == "log_rotate"]
    assert rotations, "no log_rotate stamp in the generation set"
    by_pid = {}
    for e in events:
        if e["kind"] != "writer_mark":
            continue
        by_pid.setdefault((e["session"], e["pid"]), []).append(e)
    assert len(by_pid) == 2
    for evs in by_pid.values():
        # complete and in order ACROSS the generation boundary
        assert [e["i"] for e in evs] == list(range(300))
        tms = [e["tm"] for e in evs]
        assert tms == sorted(tms)
    # the rotated set exports as one multi-stream trace
    trace = tracing.trace_export(events)
    assert tracing.validate_trace(trace) == []
    assert trace["otherData"]["streams"] == 2
    # events_summary consumes the SET from the live path alone
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "events_summary.py"),
         path],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "log rotated" in r.stdout


# ---------------------------------------------------------------------
# flight recorder

def test_flight_dump_on_injected_nan_fault(tmp_path):
    """An injected NaN fault under the health watchdog kills the
    supervised run with a FATAL HealthError — and the flight recorder
    leaves a FLIGHT.json carrying the health_trip word, placement
    metadata and the recent-event ring."""
    from lux_tpu import faults, health, resilience
    from lux_tpu.apps import pagerank
    from lux_tpu.convert import uniform_random_edges
    from lux_tpu.graph import Graph

    src, dst = uniform_random_edges(100, 700, seed=61)
    g = Graph.from_edges(src, dst, 100)
    eng = pagerank.build_engine(g, num_parts=2, health=True)
    flight = str(tmp_path / "FLIGHT.json")
    rec = tracing.install_flight_recorder(flight, capacity=64)
    plan = faults.FaultPlan(schedule={1: faults.NAN})
    ev = telemetry.EventLog()
    with telemetry.use(events=ev):
        ev.emit("header", schema=telemetry.SCHEMA, nv=g.nv, ne=g.ne,
                num_parts=2)
        with pytest.raises(health.HealthError):
            resilience.supervised_run(
                eng, 12, str(tmp_path / "ck.npz"), segment=3,
                faults=plan, guard=False,
                policy=resilience.RetryPolicy(retries=2,
                                              sleep=lambda s: None))
    assert rec.dumps == 1
    assert os.path.exists(flight)
    doc = tracing.load_flight(flight)
    assert doc["classification"] == "fatal"
    assert "HealthError" in doc["reason"]
    assert doc["health"]["kind"] == "health_trip"
    assert "nonfinite_state" in doc["health"]["flags"]
    assert doc["placement"]["num_parts"] == 2
    kinds = {e["kind"] for e in doc["events"]}
    assert {"segment", "health_trip", "failure"} <= kinds
    # the dump itself left its trail in the event log
    assert ev.counts().get("flight_dump") == 1

    # events_summary renders the postmortem
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "events_summary.py"),
         "-flight", flight],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "nonfinite_state" in r.stdout
    assert "FLIGHT" in r.stdout


def test_flight_dump_is_atomic_and_bounded(tmp_path):
    rec = tracing.install_flight_recorder(
        str(tmp_path / "F.json"), capacity=8)
    with telemetry.use(events=telemetry.EventLog()) as tel:
        for i in range(40):
            tel.emit("segment", engine="pull", n=1, done=i,
                     seconds=0.01)
    path = tracing.flight_dump(reason="test", classification="fatal")
    doc = tracing.load_flight(path)
    assert len(doc["events"]) == 8                # ring is bounded
    assert doc["events"][-1]["done"] == 39        # ...keeping newest
    assert not [f for f in os.listdir(tmp_path)
                if f.endswith(".tmp")]            # atomic: no litter
    # no recorder installed -> dump is a no-op None
    tracing.uninstall_flight_recorder()
    assert tracing.flight_dump() is None


def test_observer_sees_events_without_a_sink():
    """A flight recorder must capture the trail even when no -events
    sink is configured (Telemetry.emit's observer-only path)."""
    rec = tracing.install_flight_recorder("unused.json", capacity=4)
    with telemetry.use():                      # no EventLog at all
        telemetry.current().emit("retry", attempt=0, error="X")
    assert [e["kind"] for e in rec.ring] == ["retry"]


# ---------------------------------------------------------------------
# CLI smoke (the tier-1 gate: python -m lux_tpu.tracing)

def test_tracing_cli_smoke(tmp_path):
    out = str(tmp_path / "trace.json")
    rc = tracing.main(["-scale", "6", "-np", "2", "-apps", "sssp",
                       "-o", out, "-workdir", str(tmp_path)])
    assert rc == 0
    trace = json.load(open(out))
    assert tracing.validate_trace(trace) == []
    assert len(_spans(trace, "run")) == 1
    assert _spans(trace, "exec")          # the timed run span
    # the events JSONL it recorded is events_summary-clean
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "events_summary.py"),
         str(tmp_path / "events.jsonl")],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "per-part" in r.stdout


def test_tracing_cli_exports_existing_log(drill_events, tmp_path):
    src = tmp_path / "ev.jsonl"
    src.write_text("".join(json.dumps(e) + "\n"
                           for e in drill_events))
    out = str(tmp_path / "t.json")
    rc = tracing.main([str(src), "-o", out])
    assert rc == 0
    trace = json.load(open(out))
    assert len(_instants(trace, "mesh_shrink")) == 1
