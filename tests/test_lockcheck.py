"""lux_tpu/lockcheck.py: the host-concurrency & durability static
analyzer (ISSUE 20, round 25).

Per-check deliberately-violating synthetic fixtures asserting the
NAMED ``LockCheckError(check=...)``, reproductions of the three
historical CHANGES.md bug shapes (the PR-15/20 compact() lock-window
double-loss, the PR-16 stamp-then-admit TOCTOU, the non-atomic
checkpoint publish) proven detected by check name, the PR-15
fifth-review refresh_live/run/compact three-way deadlock as the
lock-order fixture, clean-pattern fixtures guarding against false
positives (the caller-holds-the-lock idiom, list() snapshots, the
write→fsync→publish checkpoint), pragma suppression, the repo-wide
green gate, and the regression test for the real race lockcheck
surfaced in livegraph.view_epoch (truthiness gate then min() over a
list compact() clears under the lock)."""

import subprocess
import sys
from pathlib import Path

import pytest

from lux_tpu import lockcheck
from lux_tpu.lockcheck import LockCheckError

REPO = Path(__file__).resolve().parent.parent


def _findings(tmp_path, src, name="fixture.py"):
    p = tmp_path / name
    p.write_text(src)
    return lockcheck.analyze_paths([str(p)])


def _assert_raises_check(tmp_path, src, check):
    p = tmp_path / "fixture.py"
    p.write_text(src)
    with pytest.raises(LockCheckError) as ei:
        lockcheck.run_lockcheck([str(p)], mode="error")
    assert ei.value.check == check
    assert any(f.check == check for f in ei.value.findings)
    return ei.value


# ---------------------------------------------------------------------
# one violating synthetic per check class


def test_guarded_field_violation(tmp_path):
    err = _assert_raises_check(tmp_path, """
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0

    def add(self, n):
        with self._lock:
            self.total += n

    def reset(self):
        self.total = 0
""", "guarded-field")
    assert "Counter.total" in str(err)


def test_lock_order_cycle(tmp_path):
    # the PR-15 fifth-review shape: WAL fold -> server refresh_live,
    # server run -> live admit, live compact -> WAL fold — a
    # three-way lock cycle, deadlocked by three threads entering at
    # different points (CHANGES.md round 20 review trail)
    err = _assert_raises_check(tmp_path, """
import threading

class Wal:
    def __init__(self):
        self._lock = threading.Lock()

    def fold(self, srv):
        with self._lock:
            srv.refresh_live()

class Server:
    def __init__(self, live):
        self._lock = threading.Lock()
        self.live = live

    def refresh_live(self):
        with self._lock:
            pass

    def run(self):
        with self._lock:
            self.live.admit()

class Live:
    def __init__(self, wal):
        self._lock = threading.Lock()
        self.wal = wal

    def admit(self):
        with self._lock:
            pass

    def compact(self):
        with self._lock:
            self.wal.fold(None)
""", "lock-order")
    msg = str(err)
    for name in ("Wal._lock", "Server._lock", "Live._lock"):
        assert name in msg


def test_durable_before_visible_return(tmp_path):
    _assert_raises_check(tmp_path, """
def append_record(path, payload):
    f = open(path, "ab")
    f.write(payload)
    f.close()
    return True
""", "durable-before-visible")


def test_snapshot_iteration_violation(tmp_path):
    _assert_raises_check(tmp_path, """
import threading

class Board:
    def __init__(self):
        self._lock = threading.Lock()
        self.rows = []

    def add(self, r):
        with self._lock:
            self.rows.append(r)

    def total(self):
        n = 0
        for r in self.rows:
            n += r
        return n
""", "snapshot-iteration")


def test_toctou_gate_violation(tmp_path):
    _assert_raises_check(tmp_path, """
import threading

class Budget:
    def __init__(self):
        self._lock = threading.Lock()
        self.used = 0

    def charge(self, n):
        with self._lock:
            self.used += n

    def try_charge(self, n, cap):
        if self.used + n <= cap:
            with self._lock:
                self.used += n
            return True
        return False
""", "toctou-gate")


# ---------------------------------------------------------------------
# the three historical CHANGES.md bug shapes, detected by name


def test_historical_compact_lock_window(tmp_path):
    # PR-15/20: compact() released the lock mid-fold; a concurrent
    # append's published slot was silently dropped by the
    # fresh-delta swap (lost TWICE over, with its WAL record landing
    # before the epoch START marker) — the guarded-field class
    fnd = _findings(tmp_path, """
import threading

class MutLog:
    def __init__(self):
        self._lock = threading.Lock()
        self.slots = []
        self.epoch = 0

    def append(self, op):
        with self._lock:
            self.slots.append(op)
            self.epoch += 1

    def compact(self):
        with self._lock:
            folded = list(self.slots)
        fresh = [op for op in folded if op is not None]
        self.slots = fresh
        self.epoch += 1
""")
    hits = [f for f in fnd if f.check == "guarded-field"]
    assert hits, fnd
    assert any("compact" in f.message for f in hits)


def test_historical_stamp_then_admit(tmp_path):
    # PR-16: the epoch was stamped in one step and the query
    # admitted in another — a concurrent mutate+compact slipped
    # through the window and folded the stamped view away — the
    # toctou-gate class (livegraph.LiveGraph.admit is the
    # one-acquisition fix)
    fnd = _findings(tmp_path, """
import threading

class LiveView:
    def __init__(self):
        self._lock = threading.Lock()
        self.epoch = 0
        self.anti_epoch = None
        self.pins = {}

    def mutate(self):
        with self._lock:
            self.epoch += 1

    def admit(self, qid):
        stamp = self.epoch
        if self.anti_epoch is None or stamp < self.epoch + 1:
            with self._lock:
                self.pins[qid] = stamp
""")
    hits = [f for f in fnd if f.check == "toctou-gate"]
    assert hits, fnd
    assert any("admit" in f.message for f in hits)


def test_historical_nonatomic_checkpoint_publish(tmp_path):
    # the checkpoint contract: write-tmp -> fsync -> rename; a
    # publish with bytes still in the page cache can surface a torn
    # checkpoint after a crash — the durable-before-visible class
    fnd = _findings(tmp_path, """
import os

def save_checkpoint(path, blob):
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)
""")
    hits = [f for f in fnd if f.check == "durable-before-visible"]
    assert hits, fnd
    assert any("os.replace" in f.message for f in hits)


def test_atomic_checkpoint_is_clean(tmp_path):
    # the FIXED shape (checkpoint.save): fsync before the publish
    assert _findings(tmp_path, """
import os

def save_checkpoint(path, blob):
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
""") == []


def test_spool_json_must_be_last(tmp_path):
    # fleet._worker_main contract: the json's presence marks a
    # complete answer pair, so it is written LAST
    fnd = _findings(tmp_path, """
import json
import os

def spool_answer(base, payload):
    with open(base + ".json.tmp", "w") as f:
        json.dump({"ok": True}, f)
    os.replace(base + ".json.tmp", base + ".json")
    with open(base + ".npy.tmp", "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(base + ".npy.tmp", base + ".npy")
""")
    hits = [f for f in fnd if f.check == "durable-before-visible"]
    assert hits, fnd
    assert any("LAST" in f.message for f in hits)


# ---------------------------------------------------------------------
# clean patterns must stay clean (false-positive guards)


def test_clean_patterns_pass(tmp_path):
    assert _findings(tmp_path, """
import os
import threading

class Tracker:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0
        self.rows = []

    def _bump(self):
        # private helper: every call site holds the lock — the
        # caller-holds-the-lock idiom (inferred, no pragma needed)
        self.total += 1

    def add(self, r):
        with self._lock:
            self.rows.append(r)
            self._bump()

    def drain(self):
        with self._lock:
            out = list(self.rows)
            self.rows.clear()
            self._bump()
        return out

    def peek(self):
        # list() snapshot sanctions the lock-free iteration
        return [r for r in list(self.rows)]

    def try_add(self, r, cap):
        with self._lock:
            if len(self.rows) < cap:
                self.rows.append(r)
                return True
        return False

    @classmethod
    def recover(cls, rows):
        # construction phase: thread-confined until published
        t = cls()
        t.total = len(rows)
        return t


def append_record(path, payload):
    with open(path, "ab") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    return True
""") == []


def test_pragma_suppresses_finding(tmp_path):
    assert _findings(tmp_path, """
import threading

class Gauge:
    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0

    def inc(self, v):
        with self._lock:
            self.value += v

    def set(self, v):
        # lockcheck: allow(guarded-field) single GIL-atomic store
        self.value = float(v)
""") == []


def test_pragma_is_check_specific(tmp_path):
    # a pragma for the WRONG check must not suppress
    fnd = _findings(tmp_path, """
import threading

class Gauge:
    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0

    def inc(self, v):
        with self._lock:
            self.value += v

    def set(self, v):
        # lockcheck: allow(snapshot-iteration) wrong check name
        self.value = float(v)
""")
    assert any(f.check == "guarded-field" for f in fnd)


def test_run_lockcheck_rejects_unknown_mode():
    with pytest.raises(ValueError):
        lockcheck.run_lockcheck([], mode="bogus")


# ---------------------------------------------------------------------
# the real race lockcheck surfaced (satellite 1 regression test)


class _VanishingAnti(list):
    """Simulates compact() clearing ``_anti`` under the lock between
    view_epoch's truthiness gate and its min() iteration: truthy at
    the gate, already empty when iterated."""

    def __bool__(self):
        return True

    def __iter__(self):
        return iter(())


def test_view_epoch_snapshot_regression():
    # pre-fix view_epoch did `if self._anti: min(t[0] for t in
    # self._anti)` — a compact() landing between the two raised
    # ValueError on the emptied list; the list() snapshot fix
    # returns the published epoch instead
    from lux_tpu.convert import uniform_random_edges
    from lux_tpu.graph import Graph
    from lux_tpu.livegraph import LiveGraph

    src, dst = uniform_random_edges(64, 256, seed=3)
    g = Graph.from_edges(src, dst, 64)
    lg = LiveGraph(g, capacity=8)
    lg._anti = _VanishingAnti()
    assert lg.view_epoch("push") == lg.epoch
    assert lg.view_epoch("pull") == lg.epoch


# ---------------------------------------------------------------------
# repo-wide gates


def test_lockcheck_repo_clean():
    assert lockcheck.run_lockcheck(mode="findings") == []


def test_lockcheck_cli_green():
    proc = subprocess.run(
        [sys.executable, "-m", "lux_tpu.lockcheck"],
        cwd=REPO, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    assert "clean" in proc.stdout


def test_lockcheck_cli_red_on_violation(tmp_path):
    p = tmp_path / "bad.py"
    p.write_text("""
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def a(self):
        with self._lock:
            self.n += 1

    def b(self):
        self.n = 0
""")
    proc = subprocess.run(
        [sys.executable, "-m", "lux_tpu.lockcheck", str(p)],
        cwd=REPO, capture_output=True, text=True)
    assert proc.returncode == 1
    assert "guarded-field" in proc.stderr
