"""Randomized consistency sweep: every app, multiple seeds, multiple
partitionings, and both edge layouts must agree with the NumPy oracles
(and with each other) on arbitrary random graphs."""

import numpy as np
import pytest

from lux_tpu.apps import colfilter, components, pagerank, sssp
from lux_tpu.convert import uniform_random_edges
from lux_tpu.engine.pull import PullEngine
from lux_tpu.graph import Graph, ShardedGraph

SEEDS = [101, 202, 303]


@pytest.mark.parametrize("seed", SEEDS)
def test_pagerank_sweep(seed):
    rng = np.random.default_rng(seed)
    nv = int(rng.integers(50, 400))
    ne = int(rng.integers(nv, nv * 12))
    src, dst = uniform_random_edges(nv, ne, seed=seed)
    g = Graph.from_edges(src, dst, nv)
    parts = int(rng.integers(1, 6))
    got = pagerank.run(g, 8, num_parts=parts)

    # flat-layout oracle engine must agree exactly in structure
    sg = ShardedGraph.build(g, parts)
    eng = PullEngine(sg, pagerank.make_program(), layout="flat")
    flat = eng.unpad(eng.run(eng.init_state(), 8))
    np.testing.assert_allclose(got, flat, rtol=1e-5)


@pytest.mark.parametrize("seed", SEEDS)
def test_sssp_cc_sweep(seed):
    rng = np.random.default_rng(seed)
    nv = int(rng.integers(50, 300))
    ne = int(rng.integers(nv, nv * 10))
    src, dst = uniform_random_edges(nv, ne, seed=seed)
    g = Graph.from_edges(src, dst, nv)
    start = int(rng.integers(0, nv))
    parts = int(rng.integers(1, 5))

    dist, _ = sssp.run(g, start_vertex=start, num_parts=parts)
    want = sssp.reference_sssp(g, start_vertex=start)
    reach = ~sssp.unreachable(dist)
    np.testing.assert_array_equal(dist[reach], want[reach])

    s, d = components.symmetrize(src, dst)
    gs = Graph.from_edges(s, d, nv)
    labels, _ = components.run(gs, num_parts=parts)
    np.testing.assert_array_equal(labels,
                                  components.reference_components(gs))


@pytest.mark.parametrize("seed", SEEDS)
def test_colfilter_sweep(seed):
    rng = np.random.default_rng(seed)
    nv = int(rng.integers(40, 200))
    ne = int(rng.integers(nv, nv * 8))
    src, dst, w = uniform_random_edges(nv, ne, seed=seed, weighted=True)
    g = Graph.from_edges(src, dst, nv, weights=w)
    parts = int(rng.integers(1, 4))
    got = colfilter.run(g, 4, num_parts=parts)
    want = colfilter.reference_colfilter(g, 4)
    np.testing.assert_allclose(got, want.astype(np.float32), rtol=2e-4,
                               atol=1e-6)


@pytest.mark.parametrize("seed", SEEDS)
def test_weighted_delta_sweep(seed):
    rng = np.random.default_rng(seed)
    nv = int(rng.integers(50, 250))
    ne = int(rng.integers(nv, nv * 8))
    src, dst, w = uniform_random_edges(nv, ne, seed=seed, weighted=True)
    g = Graph.from_edges(src, dst, nv, weights=w)
    start = int(rng.integers(0, nv))
    want = sssp.reference_sssp(g, start_vertex=start, weighted=True)
    for delta in (None, "auto"):
        dist, _ = sssp.run(g, start_vertex=start, num_parts=2,
                           weighted=True, delta=delta)
        np.testing.assert_allclose(dist, want.astype(np.float32),
                                   rtol=1e-6)


@pytest.mark.parametrize("mesh_size", [2, 4])
def test_small_mesh_sizes_match_single(mesh_size):
    """mesh=8 is covered elsewhere; 2- and 4-device meshes must agree
    with single-device runs for pull and push engines."""
    from lux_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(mesh_size)
    src, dst = uniform_random_edges(300, 2400, seed=404)
    g = Graph.from_edges(src, dst, 300)

    r1 = pagerank.run(g, 6, num_parts=mesh_size)
    rm = pagerank.run(g, 6, num_parts=mesh_size, mesh=mesh)
    np.testing.assert_allclose(rm, r1, rtol=1e-6)

    d1, _ = sssp.run(g, start_vertex=2, num_parts=mesh_size)
    dm, _ = sssp.run(g, start_vertex=2, num_parts=mesh_size, mesh=mesh)
    np.testing.assert_array_equal(dm, d1)


def test_push_flat_layout_matches_tiled():
    from lux_tpu.engine.push import PushEngine
    from lux_tpu.apps.sssp import make_program
    src, dst = uniform_random_edges(200, 1500, seed=505)
    g = Graph.from_edges(src, dst, 200)
    sg = ShardedGraph.build(g, 2)
    t = PushEngine(sg, make_program(0))
    f = PushEngine(sg, make_program(0), layout="flat")
    lt, at = t.init_state()
    lf, af = f.init_state()
    lt, at, _ = t.converge(lt, at)
    lf, af, _ = f.converge(lf, af)
    np.testing.assert_array_equal(t.unpad(lt), f.unpad(lf))
