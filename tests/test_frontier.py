"""Unit tests for the sparse-frontier machinery (engine/frontier.py)
and the push engine's adaptive/truncation behavior.

The reference has no tests; its closest correctness machinery is the
-check fixed-point audit (reference sssp_gpu.cu:773-798).  These tests
go further: exact oracles plus adversarial capacity limits.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from lux_tpu.engine import frontier as fr
from lux_tpu.graph import Graph
from lux_tpu.apps import sssp, components


def test_compact_mask_basic():
    mask = jnp.asarray(np.array([0, 1, 0, 0, 1, 1, 0, 0], bool))
    labels = jnp.arange(8, dtype=jnp.int32) * 10
    ids, vals, count = fr.compact_mask(mask, labels, capacity=4)
    assert int(count) == 3
    assert ids.tolist() == [1, 4, 5, 8]          # 8 = vpad = invalid
    assert vals.tolist()[:3] == [10, 40, 50]


def test_compact_mask_truncates():
    mask = jnp.ones((8,), bool)
    labels = jnp.arange(8, dtype=jnp.int32)
    ids, vals, count = fr.compact_mask(mask, labels, capacity=3)
    assert int(count) == 8                        # true count reported
    assert ids.tolist() == [0, 1, 2]              # queue truncated


def _compress(row_ptr):
    """nv-wide END-offset row pointers -> (src_ids, src_off) compressed
    index, for readable test construction."""
    rp = np.asarray(row_ptr, np.int64)
    deg = np.diff(rp)
    present = np.nonzero(deg > 0)[0]
    off = np.concatenate(([0], np.cumsum(deg[present])))
    return (jnp.asarray(present.astype(np.int32)),
            jnp.asarray(off.astype(np.int32)))


def test_expand_frontier_owners():
    # vertices 0..3 with out-degrees 2, 0, 3, 1
    sids, soff = _compress([0, 2, 2, 5, 6])
    ids = jnp.asarray(np.array([2, 0, 4, 4], np.int32))   # nv=4 invalid
    vals = jnp.asarray(np.array([7, 9, 0, 0], np.int32))
    edge_idx, src_val, in_range, total, off = fr.expand_frontier(
        ids, vals, sids, soff, nv=4, edge_budget=8)
    assert int(total) == 5                        # deg(2) + deg(0)
    assert np.asarray(off).tolist() == [3, 5, 5, 5]
    ok = np.asarray(in_range)
    assert ok.tolist() == [True] * 5 + [False] * 3
    # first item (vertex 2) owns edges 2,3,4; second (vertex 0) 0,1
    assert np.asarray(edge_idx)[:5].tolist() == [2, 3, 4, 0, 1]
    assert np.asarray(src_val)[:5].tolist() == [7, 7, 7, 9, 9]


def test_expand_frontier_absent_source():
    # queue ids not present in this part's compressed index (zero
    # out-edges here) must expand to nothing
    sids, soff = _compress([0, 2, 2, 5, 6])       # vertex 1 absent
    ids = jnp.asarray(np.array([1, 3, 4, 4], np.int32))
    vals = jnp.asarray(np.array([5, 8, 0, 0], np.int32))
    edge_idx, src_val, in_range, total, off = fr.expand_frontier(
        ids, vals, sids, soff, nv=4, edge_budget=8)
    assert np.asarray(off).tolist() == [0, 1, 1, 1]
    assert int(total) == 1
    assert np.asarray(edge_idx)[:1].tolist() == [5]
    assert np.asarray(src_val)[:1].tolist() == [8]


def test_expand_frontier_gap_before_first_item():
    # invalid slots before the only real item (the flat multi-part
    # queue shape) must not confuse ownership
    sids, soff = _compress([0, 1, 3, 3])          # nv=3
    ids = jnp.asarray(np.array([3, 3, 1, 3], np.int32))
    vals = jnp.asarray(np.array([0, 0, 5, 0], np.int32))
    edge_idx, src_val, in_range, total, _off = fr.expand_frontier(
        ids, vals, sids, soff, nv=3, edge_budget=4)
    assert int(total) == 2
    assert np.asarray(edge_idx)[:2].tolist() == [1, 2]
    assert np.asarray(src_val)[:2].tolist() == [5, 5]


def test_expand_frontier_budget_truncation():
    sids, soff = _compress([0, 3, 6])             # nv=2, deg 3+3
    ids = jnp.asarray(np.array([0, 1], np.int32))
    vals = jnp.asarray(np.array([1, 2], np.int32))
    edge_idx, src_val, in_range, total, _off = fr.expand_frontier(
        ids, vals, sids, soff, nv=2, edge_budget=4)
    assert int(total) == 6                        # exceeds budget
    assert np.asarray(in_range).tolist() == [True] * 4
    assert np.asarray(edge_idx).tolist() == [0, 1, 2, 3]


def _random_graph(nv, ne, seed, weighted=False):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, nv, ne)
    dst = rng.integers(0, nv, ne)
    w = rng.integers(1, 10, ne).astype(np.int32) if weighted else None
    return Graph.from_edges(src, dst, nv, weights=w)


@pytest.mark.parametrize("num_parts", [1, 3])
def test_sssp_tiny_edge_budget_still_converges(num_parts):
    """Truncation safety: an edge budget far below frontier demand must
    still reach the exact fixed point (pending queue suffix stays
    active)."""
    g = _random_graph(60, 240, seed=3)
    eng = sssp.build_engine(g, start_vertex=0, num_parts=num_parts)
    # rebuild with a crippled budget (still >= max single in-part degree)
    from lux_tpu.engine.push import PushEngine
    max_deg = eng.sg.max_in_deg()
    eng2 = PushEngine(eng.sg, eng.program, edge_budget=max_deg + 2)
    dist, iters = eng2.run(max_iters=500)
    ref = sssp.reference_sssp(g, 0)
    np.testing.assert_array_equal(dist.astype(np.int64), ref)


def test_sssp_sparse_matches_dense_path():
    g = _random_graph(200, 900, seed=5)
    dense = sssp.build_engine(g, 0, num_parts=2)
    dense_eng = dense
    from lux_tpu.engine.push import PushEngine
    no_sparse = PushEngine(dense.sg, dense.program, enable_sparse=False)
    d1, _ = dense_eng.run(max_iters=300)
    d2, _ = no_sparse.run(max_iters=300)
    np.testing.assert_array_equal(d1, d2)


def test_components_sparse_enabled():
    g = _random_graph(120, 300, seed=9)
    labels, _ = components.run(g, num_parts=2, max_iters=300)
    ref = components.reference_components(g)
    np.testing.assert_array_equal(labels, ref)
