"""Multi-process worker for tests/test_multiprocess.py.

Run as a subprocess — one per simulated host — with a CPU platform and
4 virtual devices (the env is set by the spawning test, BEFORE python
starts, because jax reads JAX_PLATFORMS/XLA_FLAGS at import time).

This is the reference's "same binary on every node" model (reference
README.md:33-38, pagerank.cc:51-53): every process runs this exact
file; jax.distributed glues the address spaces together the way
GASNet/Realm did.
"""

import sys


def main():
    pid = int(sys.argv[1])
    nproc = int(sys.argv[2])
    port = sys.argv[3]
    lux_path = sys.argv[4]

    from lux_tpu.parallel import multihost
    multihost.initialize(coordinator_address=f"127.0.0.1:{port}",
                         num_processes=nproc, process_id=pid)

    import jax
    import numpy as np

    assert jax.process_count() == nproc, jax.process_count()
    ndev = len(jax.devices())
    assert ndev == 4 * nproc, ndev

    from lux_tpu.apps import pagerank, sssp
    from lux_tpu.engine.pull import PullEngine
    from lux_tpu.engine.push import PushEngine
    from lux_tpu.graph import Graph, ShardedGraph

    mesh = multihost.global_mesh()
    P = ndev

    g = Graph.from_file(lux_path)
    want_pr = pagerank.reference_pagerank(g, 5)
    want_ds = sssp.reference_sssp(g, 0)

    # 1. pull engine, full host arrays on every process (all-gather +
    #    fused fori_loop across the process group)
    eng = pagerank.build_engine(g, num_parts=P, mesh=mesh)
    state = eng.run(eng.init_state(), 5)
    np.testing.assert_allclose(eng.unpad(state), want_pr, rtol=2e-5)

    # 2. push engine to convergence (while_loop + psum halt + sparse
    #    queue all-gather + pmin, all across the process group)
    eng2 = sssp.build_engine(g, start_vertex=0, num_parts=P, mesh=mesh)
    dist, _iters = eng2.run()
    np.testing.assert_array_equal(dist.astype(np.int64), want_ds)

    # 3. per-host loading: each process materializes ONLY its parts
    #    from the .lux file (native.load_partition) and the engines
    #    assemble the global sharded arrays from process-local data.
    local = multihost.process_parts(P)
    sg = ShardedGraph.build_from_file(lux_path, P, parts=local)
    assert sg.local_parts is not None
    assert sg.src_slot.shape[0] == len(local)

    eng3 = PullEngine(sg, pagerank.make_program(), mesh=mesh)
    s3 = eng3.run(eng3.init_state(), 5)
    np.testing.assert_allclose(eng3.unpad(s3), want_pr, rtol=2e-5)

    eng4 = PushEngine(sg, sssp.make_program(0), mesh=mesh)
    label, active = eng4.init_state()
    label, active, _it = eng4.converge(label, active)
    np.testing.assert_array_equal(
        eng4.unpad(label).astype(np.int64), want_ds)

    # 4b. pair-lane delivery with per-host local-parts builds: each
    #     process plans only its rows against the process-group-
    #     allreduced common depth profile (plan_sharded_pairs) —
    #     round-2 VERDICT missing item #2, closed
    from lux_tpu.graph import pair_relabel
    g5, _perm5, starts5 = pair_relabel(g, P, pair_threshold=8)
    want5 = pagerank.reference_pagerank(g5, 5)
    sg5 = ShardedGraph.build(g5, P, starts=starts5, pair_threshold=8,
                             parts=local)
    assert sg5.local_parts is not None
    eng5 = PullEngine(sg5, pagerank.make_program(), mesh=mesh,
                      pair_threshold=8)
    assert eng5.pairs is not None, "pair plan must engage"
    s5 = eng5.run(eng5.init_state(), 5)
    np.testing.assert_allclose(eng5.unpad(s5), want5, rtol=2e-5)

    # 4c. the PUSH engine with the same local-parts pair build (dense
    #     pair delivery over a local residual + sparse queue exchange)
    rank5 = np.empty(g.nv, np.int64)
    rank5[_perm5] = np.arange(g.nv)
    want_ds5 = sssp.reference_sssp(g5, int(rank5[0]))
    eng6 = PushEngine(sg5, sssp.make_program(int(rank5[0])), mesh=mesh,
                      pair_threshold=8)
    assert eng6.pairs is not None, "push pair plan must engage"
    lab6, act6 = eng6.init_state()
    lab6, act6, _it6 = eng6.converge(lab6, act6)
    np.testing.assert_array_equal(
        eng6.unpad(lab6).astype(np.int64), want_ds5)

    # 4. on-device sharded audit over the engine's live global state
    #    (the pod-scale -check path: per-host edge arrays, no host
    #    edge-list rebuild)
    from lux_tpu import device_check
    res = device_check.check_sssp_device(sg, label, mesh=mesh)
    assert res.ok and res.checked == sg.ne, res

    # 5. the OWNER exchange on per-host local-parts builds (round-3
    #    VERDICT missing #3): the planning-time edge exchange streams
    #    dst-part rows across the process group, each process lays out
    #    only its SOURCE parts, and the per-iteration reduce_scatter
    #    replaces the state all_gather — across 2 real processes.
    eng7 = PullEngine(sg, pagerank.make_program(), mesh=mesh,
                      exchange="owner")
    own_arr = (eng7.owner.src_rel if eng7.owner.packed
               else eng7.owner.src_local)
    assert own_arr.shape[0] == len(list(local))
    s7 = eng7.run(eng7.init_state(), 5)
    np.testing.assert_allclose(eng7.unpad(s7), want_pr, rtol=2e-5)

    #    and the push engine's owner-side dense iterations (min
    #    labels ride the all_to_all exchange)
    eng8 = PushEngine(sg, sssp.make_program(0), mesh=mesh,
                      exchange="owner", enable_sparse=False)
    lab8, act8 = eng8.init_state()
    lab8, act8, _it8 = eng8.converge(lab8, act8)
    np.testing.assert_array_equal(
        eng8.unpad(lab8).astype(np.int64), want_ds)

    print(f"MP_OK pid={pid}", flush=True)


if __name__ == "__main__":
    main()
