"""Query-batched engines (ROADMAP item 2): batched NumPy oracles,
columns-bitwise-equal-independent-runs proofs on 1 and 8 virtual
devices (gather AND owner exchange, stats/health variants), the
single-gather audit hold at B > 1, and the batched memory ledger.
"""

import numpy as np
import pytest

from lux_tpu.apps import components, pagerank, sssp
from lux_tpu.convert import uniform_random_edges
from lux_tpu.graph import Graph, ShardedGraph
from lux_tpu.parallel.mesh import make_mesh

NV, NE = 256, 2048
SOURCES = [0, 5, 9, 100]


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(8)


@pytest.fixture(scope="module")
def g():
    src, dst = uniform_random_edges(NV, NE, seed=3)
    return Graph.from_edges(src, dst, NV)


@pytest.fixture(scope="module")
def gw():
    r = np.random.default_rng(4)
    src, dst = uniform_random_edges(NV, NE, seed=4)
    return Graph.from_edges(src, dst, NV,
                            weights=r.integers(1, 6, NE).astype(
                                np.float32))


def ksssp_ref(g, sources):
    ref = sssp.reference_sssp_batched(g, sources)
    return np.where(ref >= int(sssp.HOP_INF), int(sssp.HOP_INF), ref)


# ---------------------------------------------------------------------
# batched NumPy oracles: columns bitwise-equal B independent
# single-query oracle runs (the oracle-first contract)

class TestBatchedOracles:
    def test_ksssp_columns_bitwise(self, g):
        b = sssp.reference_sssp_batched(g, SOURCES)
        for q, s in enumerate(SOURCES):
            np.testing.assert_array_equal(
                b[:, q], sssp.reference_sssp(g, s))

    def test_ksssp_weighted_columns_bitwise(self, gw):
        b = sssp.reference_sssp_batched(gw, SOURCES, weighted=True)
        for q, s in enumerate(SOURCES):
            assert np.array_equal(
                b[:, q], sssp.reference_sssp(gw, s, weighted=True))

    def test_components_columns_bitwise(self, g):
        b = components.reference_components_batched(g, SOURCES)
        for q, s in enumerate(SOURCES):
            np.testing.assert_array_equal(
                b[:, q],
                components.reference_components_batched(g, [s])[:, 0])

    def test_ppr_columns_bitwise(self, g):
        resets = pagerank.one_hot_resets(g.nv, SOURCES)
        b = pagerank.reference_pagerank_batched(g, resets, 6)
        for q in range(len(SOURCES)):
            np.testing.assert_array_equal(
                b[:, q],
                pagerank.reference_pagerank_batched(
                    g, resets[:, q:q + 1], 6)[:, 0])

    def test_ppr_uniform_column_is_classic(self, g):
        u = np.full((g.nv, 1), 1.0 / g.nv)
        np.testing.assert_array_equal(
            pagerank.reference_pagerank_batched(g, u, 7)[:, 0],
            pagerank.reference_pagerank(g, 7))


# ---------------------------------------------------------------------
# batched engines vs oracles + independent single-query ENGINE runs

class TestBatchedPush:
    @pytest.mark.parametrize("num_parts,exchange",
                             [(1, "gather"), (2, "gather"),
                              (4, "owner")])
    def test_ksssp_matches_oracle(self, g, num_parts, exchange):
        eng = sssp.build_engine(g, sources=SOURCES,
                                num_parts=num_parts,
                                exchange=exchange)
        lab, act = eng.init_state()
        lab, act, _it = eng.converge(lab, act)
        np.testing.assert_array_equal(
            eng.unpad(lab).astype(np.int64), ksssp_ref(g, SOURCES))

    def test_ksssp_weighted_matches_oracle(self, gw):
        eng = sssp.build_engine(gw, sources=SOURCES, num_parts=2,
                                weighted=True)
        lab, act = eng.converge(*eng.init_state())[:2]
        ref = sssp.reference_sssp_batched(gw, SOURCES, weighted=True)
        out = eng.unpad(lab)
        np.testing.assert_array_equal(
            np.where(np.isinf(out), np.inf, out).astype(np.float64),
            ref)

    @pytest.mark.parametrize("exchange", ["gather", "owner"])
    def test_components_seeded_matches_oracle(self, g, exchange):
        eng = components.build_engine(g, sources=SOURCES,
                                      num_parts=2, exchange=exchange)
        lab, act = eng.converge(*eng.init_state())[:2]
        np.testing.assert_array_equal(
            eng.unpad(lab).astype(np.int64),
            components.reference_components_batched(g, SOURCES))

    def test_b64_mesh8_bitwise_vs_64_single_runs(self, g, mesh8):
        """The acceptance gate: B=64 k-source SSSP on the 8-virtual-
        device mesh, every column bitwise-equal its independent
        single-source engine run — and the audited dense iteration
        still holds ONE state-table gather at B=64."""
        rng = np.random.default_rng(11)
        sources = [int(s) for s in
                   rng.choice(g.nv, size=64, replace=False)]
        eng = sssp.build_engine(g, sources=sources, num_parts=8,
                                mesh=mesh8)

        from lux_tpu import audit
        findings = audit.audit_engine(eng, mode=None)
        assert not findings, findings
        # the gather-budget spec the auditor enforced really was the
        # batched one: one [P*vpad, 64] table gather per dense body
        spec = audit.engine_spec(
            eng, np.zeros((8, eng.sg.vpad, 64), np.int32))
        assert spec.table_shape == (8 * eng.sg.vpad, 64)
        assert spec.gather_budget == 1

        lab, act = eng.converge(*eng.init_state())[:2]
        out = eng.unpad(lab)

        single = sssp.build_engine(g, start_vertex=0, num_parts=8,
                                   mesh=mesh8)
        for q, s in enumerate(sources):
            d = np.full(g.nv, int(sssp.HOP_INF), np.int32)
            a = np.zeros(g.nv, bool)
            d[s], a[s] = 0, True
            l0, a0 = single.place(single.sg.to_padded(d),
                                  single.sg.to_padded(a))
            l1, _a1, _ = single.converge(l0, a0)
            np.testing.assert_array_equal(single.unpad(l1),
                                          out[:, q])

    def test_mesh8_owner_stats_variant(self, g, mesh8):
        """Owner exchange + counter variant on the mesh: labels match
        the oracle and the per-part counters sum bitwise to the
        scalar series (the per_part oracle contract), with the
        batched edges counter = out-edges of the UNION frontier."""
        eng = components.build_engine(g, sources=SOURCES,
                                      num_parts=8, mesh=mesh8,
                                      exchange="owner")
        lab, act, it, fsz, fed, fszp, fedp = eng.converge_stats(
            *eng.init_state())
        it = int(it)
        np.testing.assert_array_equal(
            eng.unpad(lab).astype(np.int64),
            components.reference_components_batched(g, SOURCES))
        np.testing.assert_array_equal(
            np.asarray(fszp[:it]).sum(axis=1), np.asarray(fsz[:it]))
        np.testing.assert_array_equal(
            np.asarray(fedp[:it]).sum(axis=1, dtype=np.uint32),
            np.asarray(fed[:it]))
        # per-part NumPy oracle for the batched counters: replay the
        # dense batched iteration host-side and count the union
        # frontier's out-edges per part each iteration
        sg = eng.sg
        deg = np.asarray(sg.deg_padded)
        lab_h, act_h = eng.program.init(sg)
        per_part_edges = []
        per_part_front = []
        src, dst = g.edge_arrays()
        for _ in range(it):
            union = act_h.any(axis=-1)
            per_part_edges.append(
                np.where(union, deg, 0).sum(axis=1, dtype=np.uint32))
            user = sg.from_padded(np.where(act_h, lab_h, -1))
            new = sg.from_padded(lab_h).copy()
            np.maximum.at(new, dst, user[src])
            old_user = sg.from_padded(lab_h)
            improved = new > old_user
            lab_h = sg.to_padded(np.where(improved, new, old_user))
            act_h = sg.to_padded(improved)
            per_part_front.append(
                act_h.sum(axis=(1, 2)).astype(np.int64))
        np.testing.assert_array_equal(np.asarray(fedp[:it]),
                                      np.stack(per_part_edges))
        np.testing.assert_array_equal(np.asarray(fszp[:it]),
                                      np.stack(per_part_front))

    def test_mesh8_health_variant(self, g, mesh8):
        from lux_tpu import health
        eng = sssp.build_engine(g, sources=SOURCES, num_parts=8,
                                mesh=mesh8, health=True)
        lab, act, it, *_bufs, watch = eng.converge_health(
            *eng.init_state())
        d = health.ensure_ok(watch, engine="push")
        assert not d["tripped"]
        np.testing.assert_array_equal(
            eng.unpad(lab).astype(np.int64), ksssp_ref(g, SOURCES))


class TestBatchedPull:
    @pytest.mark.parametrize("num_parts,exchange,mesh_n",
                             [(2, "gather", 0), (4, "owner", 0),
                              (8, "gather", 8), (8, "owner", 8)])
    def test_ppr_matches_oracle(self, g, num_parts, exchange, mesh_n,
                                mesh8):
        mesh = mesh8 if mesh_n else None
        eng = pagerank.build_engine(g, num_parts=num_parts,
                                    mesh=mesh, sources=SOURCES,
                                    exchange=exchange)
        out = eng.unpad(eng.run(eng.init_state(), 6))
        ref = pagerank.reference_pagerank_batched(
            g, pagerank.one_hot_resets(g.nv, SOURCES), 6)
        np.testing.assert_allclose(out, ref, atol=1e-6)

    def test_ppr_stats_and_health_variants(self, g, mesh8):
        from lux_tpu import health
        eng = pagerank.build_engine(g, num_parts=8, mesh=mesh8,
                                    sources=SOURCES, health=True)
        st, it, rb, cb, rbp, cbp, watch = eng.run_health(
            eng.init_state(), 6)
        d = health.ensure_ok(watch, engine="pull")
        assert not d["tripped"] and int(it) == 6
        # per-part scalar derivations stay bitwise at B > 1
        np.testing.assert_array_equal(
            np.asarray(rbp[:6]).max(axis=1), np.asarray(rb[:6]))
        np.testing.assert_array_equal(
            np.asarray(cbp[:6]).sum(axis=1, dtype=np.uint32),
            np.asarray(cb[:6]))
        ref = pagerank.reference_pagerank_batched(
            g, pagerank.one_hot_resets(g.nv, SOURCES), 6)
        np.testing.assert_allclose(eng.unpad(st), ref, atol=1e-6)

    def test_ppr_run_until_converges_all_columns(self, g):
        eng = pagerank.build_engine(g, num_parts=2, sources=SOURCES)
        st, it, res = eng.run_until(eng.init_state(), 1e-7, 500)
        assert float(res) <= 1e-7 and 0 < int(it) < 500

    def test_update_program_arrays_refill(self, g):
        """The serve refill path: swapping reset columns in place
        redirects the batch without a rebuild."""
        eng = pagerank.build_engine(g, num_parts=2, sources=SOURCES)
        eng.run(eng.init_state(), 2)
        new_resets = pagerank.one_hot_resets(g.nv, [7, 8, 11, 12])
        eng.update_program_arrays(
            reset=eng.sg.to_padded(new_resets))
        deg = np.asarray(g.out_degrees, np.float32)[:, None]
        st0 = np.where(deg > 0, new_resets / np.maximum(deg, 1),
                       new_resets).astype(np.float32)
        out = eng.unpad(eng.run(eng.place(eng.sg.to_padded(st0)), 5))
        ref = pagerank.reference_pagerank_batched(g, new_resets, 5)
        np.testing.assert_allclose(out, ref, atol=1e-6)

    def test_update_program_arrays_shape_guard(self, g):
        eng = pagerank.build_engine(g, num_parts=2, sources=SOURCES)
        with pytest.raises(ValueError, match="shape"):
            eng.update_program_arrays(
                reset=np.zeros((2, 3), np.float32))
        with pytest.raises(KeyError):
            eng.update_program_arrays(bogus=np.zeros(4))


# ---------------------------------------------------------------------
# guards: single-query machinery stays single-query

class TestBatchedGuards:
    def test_pair_threshold_rejected(self, g):
        with pytest.raises(ValueError, match="pair"):
            sssp.build_engine(g, sources=SOURCES, num_parts=2,
                              pair_threshold=8)
        with pytest.raises(ValueError, match="pair"):
            pagerank.build_engine(g, num_parts=2, sources=SOURCES,
                                  pair_threshold=8)

    def test_delta_rejected(self, gw):
        with pytest.raises(ValueError, match="single-query"):
            sssp.build_engine(gw, sources=SOURCES, num_parts=2,
                              weighted=True, delta=1.0)

    def test_batched_engine_runs_dense(self, g):
        eng = sssp.build_engine(g, sources=SOURCES, num_parts=2)
        assert not eng.enable_sparse
        assert eng.batch == len(SOURCES)

    def test_empty_sources_rejected(self, g):
        with pytest.raises(ValueError, match="at least one"):
            sssp.build_engine(g, sources=[], num_parts=2)


# ---------------------------------------------------------------------
# the batched memory ledger (graph.memory_report query_batch)

class TestBatchedLedger:
    def test_query_batch_pricing(self, g):
        sg = ShardedGraph.build(g, 2)
        r1 = sg.memory_report()
        r8 = sg.memory_report(query_batch=8)
        assert r1["query_batch"] == 1 and r8["query_batch"] == 8
        # B=1 keeps the legacy pricing; B=8 prices 5 bytes per
        # (vertex, query) + shared degrees
        assert r1["vertex_bytes_per_part"] == sg.vpad * 8
        assert r8["vertex_bytes_per_part"] == sg.vpad * (5 * 8 + 4)
        assert r8["total_bytes"] > r1["total_bytes"]
        # owner message accumulator priced but NOT in total (a
        # per-iteration temporary, not an argument array)
        ro = sg.memory_report(exchange="owner", query_batch=8)
        assert ro["owner_msg_bytes_per_part"] == sg.vpad * 4 * 8
        assert r8["owner_msg_bytes_per_part"] == 0
        with pytest.raises(ValueError, match="query_batch"):
            sg.memory_report(query_batch=0)

    def test_ledger_drift_clean_at_b8(self):
        """check_ledger with a batched push engine: the compiled step's
        argument bytes vs the query_batch-priced ledger.  Dense shape
        (the audit matrix's): the check is only meaningful where edge
        arrays dominate padding (check_ledger docstring)."""
        from lux_tpu import audit
        r = np.random.default_rng(0)
        gd = Graph.from_edges(r.integers(0, 2048, 32768),
                              r.integers(0, 2048, 32768), 2048)
        eng = sssp.build_engine(gd, sources=list(range(8)),
                                num_parts=2)
        findings = audit.check_ledger(eng)
        errs = [f for f in findings if f.severity == "error"]
        assert not errs, errs
