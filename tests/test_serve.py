"""lux_tpu/serve.py: continuous-batching serving front-end.

Oracle-checked drains through refill (push + pull runners), refill
determinism, the batch collector's deadline rule, and the per-query
telemetry round-trip through scripts/events_summary.py.
"""

import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from lux_tpu import serve, telemetry
from lux_tpu.apps import components, pagerank, sssp
from lux_tpu.convert import uniform_random_edges
from lux_tpu.graph import Graph

REPO = Path(__file__).resolve().parent.parent
SUMMARY = REPO / "scripts" / "events_summary.py"

NV, NE = 256, 2048


@pytest.fixture(scope="module")
def g():
    src, dst = uniform_random_edges(NV, NE, seed=5)
    return Graph.from_edges(src, dst, NV)


def submit_all(srv, specs):
    for kind, s in specs:
        srv.submit(kind, source=s)


def run_specs(g, specs, batch=2, seg_iters=2, **kw):
    srv = serve.Server(g, batch=batch, num_parts=2,
                       seg_iters=seg_iters, **kw)
    submit_all(srv, specs)
    return srv.run()


class TestPushServing:
    def test_oversubscribed_sssp_drains_with_refill(self, g):
        """5 queries through B=2 columns: later queries must enter
        through retire+refill boundaries, and every answer matches
        the single-query oracle."""
        specs = [("sssp", s) for s in (3, 17, 40, 99, 200)]
        ev = telemetry.EventLog()
        with telemetry.use(events=ev):
            responses = run_specs(g, specs, batch=2)
        assert len(responses) == 5
        assert [r.qid for r in responses] == sorted(
            r.qid for r in responses)[:len(responses)] or True
        for r in responses:
            ref = sssp.reference_sssp_batched(g, [r.source])[:, 0]
            ref = np.where(ref >= int(sssp.HOP_INF),
                           int(sssp.HOP_INF), ref)
            np.testing.assert_array_equal(
                r.answer.astype(np.int64), ref)
            assert r.converged and r.iters > 0 and r.latency_s >= 0
        refills = [e for e in ev.events
                   if e["kind"] == "serve_refill"
                   and e.get("retired") and e.get("filled")]
        assert refills, "oversubscribed drain without any refill"
        assert sum(1 for e in ev.events
                   if e["kind"] == "query_done") == 5

    def test_components_kind(self, g):
        responses = run_specs(g, [("components", s)
                                  for s in (3, 17, 40)], batch=2)
        for r in responses:
            np.testing.assert_array_equal(
                r.answer.astype(np.int64),
                components.reference_components_batched(
                    g, [r.source])[:, 0])


class TestPullServing:
    def test_pagerank_converges_to_oracle(self, g):
        responses = run_specs(g, [("pagerank", s)
                                  for s in (3, 17, 40)],
                              batch=2, tol=1e-9)
        for r in responses:
            assert r.converged
            reset = pagerank.one_hot_resets(g.nv, [r.source])
            ref = pagerank.reference_pagerank_batched(
                g, reset, r.iters)[:, 0]
            np.testing.assert_allclose(r.answer, ref, atol=5e-5)

    def test_segment_cap_retires_unconverged(self, g):
        srv = serve.Server(g, batch=2, num_parts=2, seg_iters=1,
                           tol=0.0)   # unreachable tolerance
        srv._runner("pagerank").max_segments = 3
        srv.submit("pagerank", source=3)
        (r,) = srv.run()
        assert not r.converged and r.segments == 3


class TestDeterminism:
    def test_refill_schedule_and_answers_deterministic(self, g):
        """Two identical submission sequences produce identical
        responses: same retirement order, iterations, segments and
        bitwise answers — continuous batching must not depend on
        wall clocks."""
        specs = ([("sssp", s) for s in (3, 17, 40, 99, 200)]
                 + [("components", s) for s in (7, 50, 120)])

        def one():
            evs = telemetry.EventLog()
            with telemetry.use(events=evs):
                rs = run_specs(g, specs, batch=2)
            sched = [(e["qid"], e["col"]) for e in evs.events
                     if e["kind"] == "query_start"]
            return rs, sched

        r1, s1 = one()
        r2, s2 = one()
        assert s1 == s2
        assert [(r.qid, r.iters, r.segments, r.converged)
                for r in r1] == \
               [(r.qid, r.iters, r.segments, r.converged)
                for r in r2]
        for a, b in zip(r1, r2):
            np.testing.assert_array_equal(a.answer, b.answer)


class TestCollector:
    def test_collect_up_to_n(self):
        c = serve.BatchCollector()
        for i in range(5):
            c.put(serve.Request(qid=i, kind="sssp", source=i))
        got = c.collect(3)
        assert [r.qid for r in got] == [0, 1, 2]
        assert len(c) == 2
        assert [r.qid for r in c.collect(8)] == [3, 4]

    def test_deadline_zero_never_blocks(self):
        c = serve.BatchCollector()
        assert c.collect(4, deadline_s=0.0) == []

    def test_deadline_waits_for_first(self):
        import threading
        c = serve.BatchCollector()

        def feed():
            c.put(serve.Request(qid=7, kind="sssp", source=1))

        t = threading.Timer(0.05, feed)
        t.start()
        got = c.collect(2, deadline_s=2.0)
        t.join()
        assert [r.qid for r in got] == [7]


class TestTelemetryRoundTrip:
    def test_events_summary_validates_query_trail(self, g, tmp_path):
        path = tmp_path / "serve_ev.jsonl"
        ev = telemetry.EventLog(str(path))
        with telemetry.use(events=ev):
            ev.emit("run_start", schema=telemetry.SCHEMA,
                    app="serve", file="<test>")
            responses = run_specs(g, [("sssp", s)
                                      for s in (3, 17, 40, 99)],
                                  batch=2)
            ev.emit("run_done", seconds=1.0,
                    iters=sum(r.iters for r in responses))
        ev.close()
        r = subprocess.run([sys.executable, str(SUMMARY), str(path)],
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        assert "queries served: 4" in r.stdout
        assert "continuous batching:" in r.stdout

    def test_events_summary_rejects_broken_query_done(self, tmp_path):
        path = tmp_path / "bad.jsonl"
        evs = [
            {"t": 1.0, "tm": 1.0, "pid": 1, "session": "s",
             "kind": "query_enqueue", "qid": 0, "query_kind": "sssp"},
            # missing latency_s / iters — an unaccountable query
            {"t": 1.2, "tm": 1.2, "pid": 1, "session": "s",
             "kind": "query_done", "qid": 0, "query_kind": "sssp",
             "segments": 1},
        ]
        path.write_text("".join(json.dumps(e) + "\n" for e in evs))
        r = subprocess.run([sys.executable, str(SUMMARY), str(path)],
                           capture_output=True, text=True)
        assert r.returncode == 1
        assert "query_done missing" in r.stderr

    def test_events_summary_rejects_unenqueued_done(self, tmp_path):
        path = tmp_path / "bad2.jsonl"
        evs = [
            {"t": 1.0, "tm": 1.0, "pid": 1, "session": "s",
             "kind": "query_enqueue", "qid": 0, "query_kind": "sssp"},
            {"t": 1.2, "tm": 1.2, "pid": 1, "session": "s",
             "kind": "query_done", "qid": 5, "query_kind": "sssp",
             "iters": 3, "segments": 1, "latency_s": 0.2,
             "wait_s": 0.0},
        ]
        path.write_text("".join(json.dumps(e) + "\n" for e in evs))
        r = subprocess.run([sys.executable, str(SUMMARY), str(path)],
                           capture_output=True, text=True)
        assert r.returncode == 1
        assert "never enqueued" in r.stderr


class TestServeSmoke:
    def test_main_smoke(self, tmp_path):
        """The acceptance smoke: 2B mixed queries drain via refill
        with oracle-matching answers and a validated event trail."""
        path = tmp_path / "ev.jsonl"
        rc = serve.main(["-scale", "8", "-ef", "8", "-batch", "3",
                         "-np", "2", "-events", str(path)])
        assert rc == 0
        r = subprocess.run([sys.executable, str(SUMMARY), str(path)],
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        assert "queries served: 6" in r.stdout
