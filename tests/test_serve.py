"""lux_tpu/serve.py: continuous-batching serving front-end.

Oracle-checked drains through refill (push + pull runners), refill
determinism, the batch collector's deadline rule, and the per-query
telemetry round-trip through scripts/events_summary.py.

Round 17 (serving observability) acceptance bars:
- SLO good/violation counters and the rolling burn-rate gauge match
  a NumPy oracle over the responses' own latencies;
- scripts/loadgen.py against an OVERSUBSCRIBED mixed-kind Server on
  the 8-virtual-device CPU mesh: the metrics snapshot's per-kind
  p50/p99 agree with a NumPy quantile oracle over the raw query_done
  events within the histogram's pinned error bound, the Perfetto
  export carries per-query spans that pass validate_trace, and the
  bench.py serve-slo line is accepted by scripts/check_bench.py.
"""

import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from lux_tpu import metrics as metrics_mod
from lux_tpu import serve, telemetry
from lux_tpu.apps import components, pagerank, sssp
from lux_tpu.convert import uniform_random_edges
from lux_tpu.graph import Graph

REPO = Path(__file__).resolve().parent.parent
SUMMARY = REPO / "scripts" / "events_summary.py"
CHECK_BENCH = REPO / "scripts" / "check_bench.py"
sys.path.insert(0, str(REPO / "scripts"))
sys.path.insert(0, str(REPO))

NV, NE = 256, 2048


@pytest.fixture(scope="module")
def g():
    src, dst = uniform_random_edges(NV, NE, seed=5)
    return Graph.from_edges(src, dst, NV)


def submit_all(srv, specs):
    for kind, s in specs:
        srv.submit(kind, source=s)


def run_specs(g, specs, batch=2, seg_iters=2, **kw):
    srv = serve.Server(g, batch=batch, num_parts=2,
                       seg_iters=seg_iters, **kw)
    submit_all(srv, specs)
    return srv.run()


class TestPushServing:
    def test_oversubscribed_sssp_drains_with_refill(self, g):
        """5 queries through B=2 columns: later queries must enter
        through retire+refill boundaries, and every answer matches
        the single-query oracle."""
        specs = [("sssp", s) for s in (3, 17, 40, 99, 200)]
        ev = telemetry.EventLog()
        with telemetry.use(events=ev):
            responses = run_specs(g, specs, batch=2)
        assert len(responses) == 5
        assert [r.qid for r in responses] == sorted(
            r.qid for r in responses)[:len(responses)] or True
        for r in responses:
            ref = sssp.reference_sssp_batched(g, [r.source])[:, 0]
            ref = np.where(ref >= int(sssp.HOP_INF),
                           int(sssp.HOP_INF), ref)
            np.testing.assert_array_equal(
                r.answer.astype(np.int64), ref)
            assert r.converged and r.iters > 0 and r.latency_s >= 0
        refills = [e for e in ev.events
                   if e["kind"] == "serve_refill"
                   and e.get("retired") and e.get("filled")]
        assert refills, "oversubscribed drain without any refill"
        assert sum(1 for e in ev.events
                   if e["kind"] == "query_done") == 5

    def test_components_kind(self, g):
        responses = run_specs(g, [("components", s)
                                  for s in (3, 17, 40)], batch=2)
        for r in responses:
            np.testing.assert_array_equal(
                r.answer.astype(np.int64),
                components.reference_components_batched(
                    g, [r.source])[:, 0])


class TestPullServing:
    def test_pagerank_converges_to_oracle(self, g):
        responses = run_specs(g, [("pagerank", s)
                                  for s in (3, 17, 40)],
                              batch=2, tol=1e-9)
        for r in responses:
            assert r.converged
            reset = pagerank.one_hot_resets(g.nv, [r.source])
            ref = pagerank.reference_pagerank_batched(
                g, reset, r.iters)[:, 0]
            np.testing.assert_allclose(r.answer, ref, atol=5e-5)

    def test_segment_cap_retires_unconverged(self, g):
        srv = serve.Server(g, batch=2, num_parts=2, seg_iters=1,
                           tol=0.0)   # unreachable tolerance
        srv._runner("pagerank").max_segments = 3
        srv.submit("pagerank", source=3)
        (r,) = srv.run()
        assert not r.converged and r.segments == 3


class TestDeterminism:
    def test_refill_schedule_and_answers_deterministic(self, g):
        """Two identical submission sequences produce identical
        responses: same retirement order, iterations, segments and
        bitwise answers — continuous batching must not depend on
        wall clocks."""
        specs = ([("sssp", s) for s in (3, 17, 40, 99, 200)]
                 + [("components", s) for s in (7, 50, 120)])

        def one():
            evs = telemetry.EventLog()
            with telemetry.use(events=evs):
                rs = run_specs(g, specs, batch=2)
            sched = [(e["qid"], e["col"]) for e in evs.events
                     if e["kind"] == "query_start"]
            return rs, sched

        r1, s1 = one()
        r2, s2 = one()
        assert s1 == s2
        assert [(r.qid, r.iters, r.segments, r.converged)
                for r in r1] == \
               [(r.qid, r.iters, r.segments, r.converged)
                for r in r2]
        for a, b in zip(r1, r2):
            np.testing.assert_array_equal(a.answer, b.answer)


class TestCollector:
    def test_collect_up_to_n(self):
        c = serve.BatchCollector()
        for i in range(5):
            c.put(serve.Request(qid=i, kind="sssp", source=i))
        got = c.collect(3)
        assert [r.qid for r in got] == [0, 1, 2]
        assert len(c) == 2
        assert [r.qid for r in c.collect(8)] == [3, 4]

    def test_deadline_zero_never_blocks(self):
        c = serve.BatchCollector()
        assert c.collect(4, deadline_s=0.0) == []

    def test_deadline_waits_for_first(self):
        import threading
        c = serve.BatchCollector()

        def feed():
            c.put(serve.Request(qid=7, kind="sssp", source=1))

        t = threading.Timer(0.05, feed)
        t.start()
        got = c.collect(2, deadline_s=2.0)
        t.join()
        assert [r.qid for r in got] == [7]


class TestPriorityCollector:
    """Round-18 deadline-priority collection (lux_tpu/fleet.py's
    admission queue) — the PINNED ordering rule under a
    deterministic injected clock: priority-desc FIFO, EXCEPT that a
    request past HALF its deadline is AGED and cannot be displaced
    further."""

    @staticmethod
    def make(clock):
        return serve.PriorityCollector(now=lambda: clock[0])

    @staticmethod
    def req(qid, priority=0, deadline_s=None, t=0.0):
        return serve.Request(qid=qid, kind="sssp", source=qid,
                             t_enqueue=t, priority=priority,
                             deadline_s=deadline_s)

    def test_priority_order_fifo_within(self):
        clock = [0.0]
        c = self.make(clock)
        for qid, pr in ((0, 0), (1, 2), (2, 1), (3, 2)):
            c.put(self.req(qid, priority=pr))
        assert [r.qid for r in c.collect(10)] == [1, 3, 2, 0]

    def test_deadline_semantics_match_base(self):
        import threading
        clock = [0.0]
        c = self.make(clock)
        assert c.collect(4, deadline_s=0.0) == []   # never blocks
        t = threading.Timer(0.05, lambda: c.put(self.req(9)))
        t.start()
        got = c.collect(2, deadline_s=2.0)   # waits for the FIRST
        t.join()
        assert [r.qid for r in got] == [9]

    def test_aged_low_priority_not_displaced(self):
        """The pinned aging rule: a low-priority request past half
        its deadline outranks fresh high-priority traffic — a
        saturated priority stream cannot displace it indefinitely."""
        clock = [0.0]
        c = self.make(clock)
        c.put(self.req(0, priority=0, deadline_s=10.0, t=0.0))
        for i in range(1, 4):
            c.put(self.req(i, priority=5, t=0.0))
        # fresh: high priority first, the low-priority one last
        assert [r.qid for r in c.collect(2)] == [1, 2]
        # past HALF the deadline: the aged request now leads
        clock[0] = 5.0
        c.put(self.req(4, priority=5, t=4.9))
        assert [r.qid for r in c.collect(2)] == [0, 3]

    def test_aged_order_earliest_deadline_first(self):
        clock = [10.0]
        c = self.make(clock)
        c.put(self.req(0, priority=0, deadline_s=16.0, t=0.0))
        c.put(self.req(1, priority=0, deadline_s=12.0, t=0.0))
        c.put(self.req(2, priority=9))
        # both aged (past half deadline); nearest absolute deadline
        # (t=0 + 12) collects first, the un-aged priority-9 last
        assert [r.qid for r in c.collect(3)] == [1, 0, 2]

    def test_unaged_deadline_keeps_priority_order(self):
        clock = [1.0]
        c = self.make(clock)
        c.put(self.req(0, priority=0, deadline_s=100.0, t=0.0))
        c.put(self.req(1, priority=3, deadline_s=100.0, t=0.5))
        assert [r.qid for r in c.collect(2)] == [1, 0]


class TestTelemetryRoundTrip:
    def test_events_summary_validates_query_trail(self, g, tmp_path):
        path = tmp_path / "serve_ev.jsonl"
        ev = telemetry.EventLog(str(path))
        with telemetry.use(events=ev):
            ev.emit("run_start", schema=telemetry.SCHEMA,
                    app="serve", file="<test>")
            responses = run_specs(g, [("sssp", s)
                                      for s in (3, 17, 40, 99)],
                                  batch=2)
            ev.emit("run_done", seconds=1.0,
                    iters=sum(r.iters for r in responses))
        ev.close()
        r = subprocess.run([sys.executable, str(SUMMARY), str(path)],
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        assert "queries served: 4" in r.stdout
        assert "continuous batching:" in r.stdout

    def test_events_summary_rejects_broken_query_done(self, tmp_path):
        path = tmp_path / "bad.jsonl"
        evs = [
            {"t": 1.0, "tm": 1.0, "pid": 1, "session": "s",
             "kind": "query_enqueue", "qid": 0, "query_kind": "sssp"},
            # missing latency_s / iters — an unaccountable query
            {"t": 1.2, "tm": 1.2, "pid": 1, "session": "s",
             "kind": "query_done", "qid": 0, "query_kind": "sssp",
             "segments": 1},
        ]
        path.write_text("".join(json.dumps(e) + "\n" for e in evs))
        r = subprocess.run([sys.executable, str(SUMMARY), str(path)],
                           capture_output=True, text=True)
        assert r.returncode == 1
        assert "query_done missing" in r.stderr

    def test_events_summary_rejects_unenqueued_done(self, tmp_path):
        path = tmp_path / "bad2.jsonl"
        evs = [
            {"t": 1.0, "tm": 1.0, "pid": 1, "session": "s",
             "kind": "query_enqueue", "qid": 0, "query_kind": "sssp"},
            {"t": 1.2, "tm": 1.2, "pid": 1, "session": "s",
             "kind": "query_done", "qid": 5, "query_kind": "sssp",
             "iters": 3, "segments": 1, "latency_s": 0.2,
             "wait_s": 0.0},
        ]
        path.write_text("".join(json.dumps(e) + "\n" for e in evs))
        r = subprocess.run([sys.executable, str(SUMMARY), str(path)],
                           capture_output=True, text=True)
        assert r.returncode == 1
        assert "never enqueued" in r.stderr


class TestServingMetricsAndSLO:
    def test_slo_accounting_matches_oracle(self, g):
        """The SLO counters, burn-rate gauge and per-event slo_ok
        flags must all re-derive from the responses' OWN latencies —
        the accounting can never disagree with the stream it
        aggregates."""
        slo = 40.0
        ev = telemetry.EventLog()
        with telemetry.use(events=ev):
            srv = serve.Server(g, batch=2, num_parts=2, seg_iters=2,
                               slo_ms={"sssp": slo})
            for s in (3, 17, 40, 99, 200):
                srv.submit("sssp", source=s)
            responses = srv.run()
        assert len(responses) == 5
        want_good = sum(r.latency_s * 1e3 <= slo for r in responses)
        want_bad = 5 - want_good
        reg = srv.metrics

        def counter(name):
            c = reg.counter(name, kind="sssp")
            return c.value

        assert counter("serve_slo_good_total") == want_good
        assert counter("serve_slo_violation_total") == want_bad
        burn = reg.gauge("serve_slo_burn_rate", kind="sssp").value
        assert burn == pytest.approx(want_bad / 5)
        # the per-event record carries the same verdicts
        done = [e for e in ev.events if e["kind"] == "query_done"]
        assert len(done) == 5
        by_qid = {r.qid: r for r in responses}
        for e in done:
            assert e["slo_ms"] == slo
            assert e["slo_ok"] == \
                (by_qid[e["qid"]].latency_s * 1e3 <= slo)
        # latency histogram count equals retirements; queue drained
        h = reg.histogram("serve_latency_seconds", kind="sssp")
        assert h.count == 5
        assert reg.gauge("serve_queue_depth", kind="sssp").value == 0
        # the drain published a snapshot event
        assert any(e["kind"] == "metrics_snapshot"
                   for e in ev.events)
        # events_summary cross-audit accepts the consistent trail
        # (snapshot counts vs query_done events) — in-process render
        import io

        import events_summary as es
        out = io.StringIO()
        errs = []
        streams, serrs = es.split_streams(ev.events)
        for _key, stream in streams:
            for run in es.split_runs(stream):
                errs += es.render_run(run, out=out)
        assert serrs == [] and errs == []
        assert "metrics snapshot" in out.getvalue()

    def test_metrics_false_disables_cleanly(self, g):
        srv = serve.Server(g, batch=2, num_parts=2, seg_iters=2,
                           metrics=False)
        assert srv.metrics is None
        srv.submit("sssp", source=3)
        ev = telemetry.EventLog()
        with telemetry.use(events=ev):
            (r,) = srv.run()
        assert r.converged
        assert not any(e["kind"] == "metrics_snapshot"
                       for e in ev.events)
        assert srv.emit_metrics_snapshot() is None

    def test_unknown_slo_kind_rejected(self, g):
        with pytest.raises(ValueError):
            serve.Server(g, slo_ms={"bogus": 10.0})

    def test_loadgen_acceptance_oversubscribed_mesh(self, g,
                                                    tmp_path):
        """THE round-17 acceptance: an open-loop oversubscribed
        mixed-kind load on the 8-virtual-device mesh — snapshot
        percentiles against the NumPy oracle at the pinned bound,
        per-query spans through validate_trace, and the rendered
        events_summary audit."""
        import loadgen

        from lux_tpu import tracing
        from lux_tpu.parallel.mesh import make_mesh

        kinds = ["sssp", "components", "pagerank"]
        path = tmp_path / "serve_ev.jsonl"
        ev = telemetry.EventLog(str(path))
        with telemetry.use(events=ev):
            ev.emit("run_start", schema=telemetry.SCHEMA,
                    app="serve", file="<test>", mesh=8)
            srv = serve.Server(g, batch=2, num_parts=8,
                               mesh=make_mesh(8), seg_iters=2,
                               slo_ms={"sssp": 250.0,
                                       "components": 250.0,
                                       "pagerank": 1000.0})
            import time as _time
            t0 = _time.perf_counter()
            loadgen.warm(srv, kinds)
            idx0 = len(ev.events)
            rng = np.random.default_rng(3)
            # rate far past the CPU mesh's service rate: every query
            # arrives up front, so the B=2 columns OVERSUBSCRIBE and
            # later queries enter through retire+refill
            rep = loadgen.run_step(srv, rate=500.0, n=12,
                                   kinds=kinds, rng=rng, step=0)
            ev.emit("run_done",
                    seconds=round(_time.perf_counter() - t0, 6),
                    iters=rep.served)
        ev.close()
        assert rep.drained and rep.served == 12
        assert rep.achieved_qps <= rep.offered_qps * (1 + 1e-9)
        assert rep.p50_ms is not None and rep.p99_ms is not None
        assert rep.p50_ms <= rep.p99_ms
        assert rep.slo_good_fraction is not None
        # oversubscription really exercised continuous batching
        refills = [e for e in ev.events[idx0:]
                   if e["kind"] == "serve_refill"
                   and e.get("retired") and e.get("filled")]
        assert refills, "oversubscribed load drained without refill"

        # (a) snapshot percentiles vs the NumPy oracle over the raw
        # query_done stream, within the histogram's PINNED bound
        snaps = [e for e in ev.events
                 if e["kind"] == "metrics_snapshot"
                 and e.get("step") == 0]
        assert snaps
        done = [e for e in ev.events[idx0:]
                if e["kind"] == "query_done"]
        assert len(done) == 12
        checked = 0
        for h in snaps[-1]["histograms"]:
            if h["name"] != "serve_latency_seconds":
                continue
            kind = h["labels"]["kind"]
            lats = [e["latency_s"] for e in done
                    if e["query_kind"] == kind]
            assert h["count"] == len(lats)
            for q, key in ((0.5, "p50"), (0.99, "p99")):
                oracle = float(np.quantile(lats, q,
                                           method="inverted_cdf"))
                # + 1e-3: the event stream rounds latency_s to 1e-6
                assert abs(h[key] - oracle) / oracle <= \
                    metrics_mod.QUANTILE_REL_ERR + 1e-3, (kind, key)
            checked += 1
        assert checked == len(kinds)

        # (b) per-query spans through validate_trace
        trace = tracing.trace_export(ev.events,
                                     out=str(tmp_path / "t.json"))
        assert tracing.validate_trace(trace) == []
        qspans = [e for e in trace["traceEvents"]
                  if e.get("cat") == "query"]
        phases = [e for e in trace["traceEvents"]
                  if e.get("cat") == "query_phase"]
        assert len(qspans) >= 12          # warm queries also render
        assert {e["name"] for e in phases} >= {"wait"}
        waits = {}
        for e in trace["traceEvents"]:
            if e.get("cat") == "query" and "slo_ok" in e.get("args",
                                                            {}):
                waits[e["args"]["qid"]] = e["args"]["wait_s"]
        assert waits                      # spans carry the SLO verdict

        # events_summary renders + audits the full trail
        r = subprocess.run([sys.executable, str(SUMMARY), str(path)],
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        assert "metrics snapshot" in r.stdout

    def test_serve_slo_bench_line_through_check_bench(self, tmp_path):
        """(c) of the acceptance: bench.py -config serve-slo produces
        a metric line scripts/check_bench.py ACCEPTS, and the
        contradiction mutations are rejected."""
        import argparse

        import bench

        args = argparse.Namespace(
            scale=8, ef=8, ni=20, np=2, pair=0, min_fill=None,
            min_fill_dot=None, repeats=1, verbose=False,
            health=False, audit="warn", serve_queries=10,
            serve_batch=2, serve_kinds="sssp,components,pagerank",
            slo_ms="sssp=250,components=250,pagerank=1000",
            rates="60", batch="1", shape="rmat", reorder="none")
        ev = telemetry.EventLog()
        with telemetry.use(events=ev):
            idx0 = len(ev.events)
            name, samples, extra, _rerun = bench.run_config(
                "serve-slo@60", args)
            tel = bench.config_telemetry(ev, idx0, None)
        assert name == "serve_slo_q60_rmat8"
        assert extra["unit"] == "qps"
        assert extra["audit"]["errors"] == 0
        value = round(float(np.median(samples)), 4)
        line = {"metric": f"{name}_qps_per_chip", "value": value,
                "unit": "qps", "vs_baseline": value,
                "samples": [round(s, 4) for s in samples],
                "attempts": len(samples), "discarded": [],
                "telemetry": tel, **extra}
        p = tmp_path / "bench.jsonl"
        p.write_text(json.dumps(line) + "\n")
        r = subprocess.run([sys.executable, str(CHECK_BENCH),
                            "-legacy-ok", str(p)],
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr

        def rejects(mutate, needle):
            bad = json.loads(json.dumps(line))
            mutate(bad)
            p.write_text(json.dumps(bad) + "\n")
            rr = subprocess.run([sys.executable, str(CHECK_BENCH),
                                 "-legacy-ok", str(p)],
                                capture_output=True, text=True)
            assert rr.returncode == 1 and needle in rr.stderr, \
                (needle, rr.stderr)

        rejects(lambda d: d.update(p99_ms=d["p50_ms"] / 2),
                "p99_ms")
        rejects(lambda d: d.update(
            achieved_qps=d["offered_qps"] * 2,
            value=round(d["offered_qps"] * 2, 4),
            samples=[round(d["offered_qps"] * 2, 4)]),
            "outrun arrivals")
        rejects(lambda d: d.update(slo_good_fraction=1.2),
                "slo_good_fraction")
        rejects(lambda d: d.pop("offered_qps"),
                "serve-slo line missing")
        rejects(lambda d: d.update(value=d["value"] + 1,
                                   samples=[d["value"] + 1]),
                "achieved_qps")


class TestServeSmoke:
    def test_main_smoke(self, tmp_path):
        """The acceptance smoke: 2B mixed queries drain via refill
        with oracle-matching answers and a validated event trail."""
        path = tmp_path / "ev.jsonl"
        rc = serve.main(["-scale", "8", "-ef", "8", "-batch", "3",
                         "-np", "2", "-events", str(path)])
        assert rc == 0
        r = subprocess.run([sys.executable, str(SUMMARY), str(path)],
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        assert "queries served: 6" in r.stdout
