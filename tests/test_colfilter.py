"""Collaborative filtering vs NumPy oracle."""

import numpy as np
import pytest

from lux_tpu.apps import colfilter
from lux_tpu.convert import uniform_random_edges
from lux_tpu.graph import Graph


def bipartite_graph(n_users=40, n_items=25, ne=600, seed=0):
    """Ratings graph with edges in both directions (the reference runs
    CF as a pull program over in-edges, so a symmetrized bipartite
    graph updates both users and items)."""
    rng = np.random.default_rng(seed)
    u = rng.integers(0, n_users, size=ne, dtype=np.uint32)
    i = rng.integers(0, n_items, size=ne, dtype=np.uint32) + n_users
    w = rng.integers(1, 6, size=ne, dtype=np.int32)
    src = np.concatenate([u, i])
    dst = np.concatenate([i, u])
    ww = np.concatenate([w, w])
    return Graph.from_edges(src, dst, n_users + n_items, weights=ww)


@pytest.mark.parametrize("num_parts", [1, 3])
def test_matches_oracle(num_parts):
    g = bipartite_graph()
    got = colfilter.run(g, 3, num_parts=num_parts)
    want = colfilter.reference_colfilter(g, 3)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-7)


def test_sgd_reduces_rmse():
    """Training actually learns: RMSE after many iters < at init.
    (With the reference's tiny GAMMA this is a small but real drop.)"""
    g = bipartite_graph(ne=2000)
    s0 = colfilter.reference_colfilter(g, 0)
    s = colfilter.run(g, 50, num_parts=2)
    assert colfilter.rmse(g, s) < colfilter.rmse(g, s0)


def test_unweighted_rejected():
    src, dst = uniform_random_edges(10, 30, seed=1)
    g = Graph.from_edges(src, dst, 10)
    with pytest.raises(ValueError):
        colfilter.build_engine(g)


def test_dot_path_rejects_bad_programs():
    import pytest
    from lux_tpu.engine.program import PullProgram
    from lux_tpu.engine.pull import PullEngine
    from lux_tpu.graph import Graph, ShardedGraph
    from lux_tpu.convert import uniform_random_edges
    import numpy as np

    src, dst, w = uniform_random_edges(60, 300, seed=91, weighted=True)
    gw = Graph.from_edges(src, dst, 60, weights=w)
    gu = Graph.from_edges(src, dst, 60)

    def mk(reduce):
        return PullProgram(
            reduce=reduce, edge_value=lambda s, d, w: s,
            apply=lambda o, r, c: r,
            init=lambda sg: np.zeros((sg.num_parts, sg.vpad, 4),
                                     np.float32),
            edge_value_from_dot=lambda s, dot, w: s)

    with pytest.raises(ValueError, match="sum"):
        PullEngine(ShardedGraph.build(gw, 1), mk("min"))
    with pytest.raises(ValueError, match="weighted"):
        PullEngine(ShardedGraph.build(gu, 1), mk("sum"))
    # needs_dst=False with edge_value_from_dot must still work
    eng = PullEngine(ShardedGraph.build(gw, 1), mk("sum"))
    out = eng.step(eng.init_state())
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.parametrize("num_parts,use_mesh", [(1, False), (2, False),
                                                (8, True)])
def test_pair_dot_path_matches_oracle(num_parts, use_mesh):
    """The blocked-SDDMM pair path (pair_partial_dot) must agree with
    the NumPy oracle after relabeling — dense rating blocks leave the
    per-edge row-gather path, residual edges keep the dot path."""
    from lux_tpu.graph import pair_relabel
    mesh = None
    if use_mesh:
        from lux_tpu.parallel.mesh import make_mesh
        mesh = make_mesh(8)
    # heavy repeat-structure so tile pairs are dense at threshold 4
    g = bipartite_graph(n_users=90, n_items=70, ne=4000, seed=3)
    g2, perm, starts = pair_relabel(g, num_parts, pair_threshold=4)
    eng = colfilter.build_engine(g2, num_parts=num_parts, mesh=mesh,
                                 pair_threshold=4, starts=starts)
    assert eng.pairs is not None and eng.pairs.stats["covered"] > 0
    state = eng.run(eng.init_state(), 3)
    got = eng.unpad(state)
    want = colfilter.reference_colfilter(g, 3)[perm]
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-7)


def test_pair_dot_cli(tmp_path, capsys):
    from lux_tpu import cli
    from lux_tpu.format import write_lux
    g = bipartite_graph(ne=1500, seed=5)
    path = str(tmp_path / "cf.lux")
    write_lux(path, g.row_ptrs, g.col_idx, weights=g.weights,
              degrees=g.out_degrees)
    rc = cli.main(["colfilter", "-file", path, "-ni", "2", "-pair", "4",
                   "-check"])
    out = capsys.readouterr().out
    assert rc == 0 and "PASS" in out


def test_netflix_like_generator():
    """The NetFlix-shape synthesizer (scripts/bench_netflix.py's
    input): bipartite endpoints, deduplicated (user, item) pairs,
    both directions, integer ratings 1..5, heavier skew on items."""
    from lux_tpu.convert import netflix_like_edges
    src, dst, w, nv = netflix_like_edges(n_users=300, n_items=40,
                                         n_ratings=3000, seed=7)
    assert nv == 340 and len(src) == len(dst) == len(w)
    assert len(src) % 2 == 0
    half = len(src) // 2
    # first half user->item, second the exact reverse
    assert (src[:half] < 300).all() and (dst[:half] >= 300).all()
    np.testing.assert_array_equal(src[half:], dst[:half])
    np.testing.assert_array_equal(dst[half:], src[:half])
    np.testing.assert_array_equal(w[half:], w[:half])
    assert w.min() >= 1 and w.max() <= 5
    # dedup: no repeated (user, item) pair
    key = src[:half].astype(np.int64) * nv + dst[:half]
    assert len(np.unique(key)) == half
    # skew: the most-rated item outdraws the median item by a lot
    item_deg = np.bincount(dst[:half] - 300, minlength=40)
    assert item_deg.max() > 4 * np.median(item_deg)
    # the engine + oracle run on it
    g = Graph.from_edges(src, dst, nv, weights=w)
    got = colfilter.run(g, 2, num_parts=2)
    want = colfilter.reference_colfilter(g, 2)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-7)
