"""Tests for the padded part-major device layout (ShardedGraph)."""

import numpy as np
import pytest

from lux_tpu.convert import rmat_edges, uniform_random_edges
from lux_tpu.graph import Graph, ShardedGraph


@pytest.mark.parametrize("num_parts", [1, 3, 8])
def test_layout_roundtrip(num_parts):
    src, dst = uniform_random_edges(200, 1500, seed=7)
    g = Graph.from_edges(src, dst, 200)
    sg = ShardedGraph.build(g, num_parts)
    x = np.random.default_rng(0).random(200).astype(np.float32)
    np.testing.assert_array_equal(sg.from_padded(sg.to_padded(x)), x)


@pytest.mark.parametrize("num_parts", [1, 2, 5])
def test_edges_reconstruct_graph(num_parts):
    """Every original edge appears exactly once in the padded layout,
    with src_slot/dst_local translating back to the original ids."""
    src, dst = uniform_random_edges(100, 800, seed=11)
    g = Graph.from_edges(src, dst, 100)
    sg = ShardedGraph.build(g, num_parts)

    got = []
    for p in range(num_parts):
        nep = int(sg.ne_part[p])
        for e in range(nep):
            slot = int(sg.src_slot[p, e])
            sp, sl = divmod(slot, sg.vpad)
            s_global = int(sg.starts[sp]) + sl
            d_global = int(sg.starts[p]) + int(sg.dst_local[p, e])
            got.append((s_global, d_global))
        # padding edges must point at the trash segment
        assert np.all(sg.dst_local[p, nep:] == sg.vpad)
    want = sorted(zip(src.tolist(), dst.tolist()))
    assert sorted(got) == want


def test_dst_local_sorted_within_part():
    """Edges stay dst-sorted per part — the invariant the segmented
    reductions and Pallas kernels rely on."""
    src, dst, nv = rmat_edges(scale=10, edge_factor=8, seed=2)
    g = Graph.from_edges(src, dst, nv)
    sg = ShardedGraph.build(g, 4)
    for p in range(4):
        nep = int(sg.ne_part[p])
        d = sg.dst_local[p, :nep]
        assert np.all(np.diff(d.astype(np.int64)) >= 0)


def test_row_ptr_local_consistent():
    src, dst = uniform_random_edges(123, 999, seed=5)
    g = Graph.from_edges(src, dst, 123)
    sg = ShardedGraph.build(g, 3)
    for p in range(3):
        nvp = int(sg.nv_part[p])
        nep = int(sg.ne_part[p])
        rpl = sg.row_ptr_local[p]
        assert rpl[0] == 0
        assert rpl[nvp] == nep
        assert np.all(np.diff(rpl) >= 0)
        # in-degree run-lengths match dst_local runs
        in_deg = np.diff(rpl[:nvp + 1])
        counts = np.bincount(sg.dst_local[p, :nep], minlength=sg.vpad + 1)
        np.testing.assert_array_equal(in_deg, counts[:nvp])


def test_weighted_layout():
    src, dst, w = uniform_random_edges(60, 500, seed=9, weighted=True)
    g = Graph.from_edges(src, dst, 60, weights=w)
    sg = ShardedGraph.build(g, 2)
    assert sg.weighted and sg.edge_weight is not None
    tot = sum(float(sg.edge_weight[p, :int(sg.ne_part[p])].sum())
              for p in range(2))
    assert tot == pytest.approx(float(np.asarray(w).sum()))
    # padding weights are zero
    for p in range(2):
        assert np.all(sg.edge_weight[p, int(sg.ne_part[p]):] == 0)


def test_memory_report():
    src, dst = uniform_random_edges(100, 700, seed=1)
    g = Graph.from_edges(src, dst, 100)
    sg = ShardedGraph.build(g, 4)
    rep = sg.memory_report()
    assert rep["total_bytes"] > 0 and rep["num_parts"] == 4
    assert rep["push_sparse_bytes_per_part"] == 0

    # the push fit plan: sparse view prices the second edge array
    push = sg.memory_report(push_sparse=True)
    assert push["push_sparse_bytes_per_part"] >= sg.epad * 4
    assert push["total_bytes"] > rep["total_bytes"]

    # owner pricing uses the real (padded) slot count when given;
    # packed (one uint32/slot) is inferred for small vpad, classic
    # (int32 + int8) on request
    own = sg.memory_report(exchange="owner",
                           owner_slots_per_part=2 * sg.epad)
    assert own["edge_bytes_per_part"] == 2 * sg.epad * 4
    classic = sg.memory_report(exchange="owner",
                               owner_slots_per_part=2 * sg.epad,
                               owner_packed=False)
    assert classic["edge_bytes_per_part"] == 2 * sg.epad * 5


def test_src_sorted_compressed_index_oracle():
    """The compressed source index must list exactly each part's edges
    grouped by global source (the dense nv-wide row-pointer oracle),
    and be much smaller than nv on graphs with few distinct sources."""
    rng = np.random.default_rng(4)
    nv, ne = 400, 900
    src = rng.integers(0, 40, ne)        # only 40 possible sources
    dst = rng.integers(0, nv, ne)
    g = Graph.from_edges(src, dst, nv)
    sg = ShardedGraph.build(g, 3)
    ss = sg.src_sorted()
    S = ss["src_ids"].shape[1]
    assert S <= 40                        # compressed far below nv
    for p in range(3):
        v0 = int(sg.starts[p])
        # oracle: per-part in-part out-edge lists by global source
        gsrc, gdst = g.edge_arrays()
        in_part = (gdst >= v0) & (gdst < int(sg.starts[p + 1]))
        want = {}
        for s, d in zip(gsrc[in_part], gdst[in_part]):
            want.setdefault(int(s), []).append(int(d) - v0)
        ids, off = ss["src_ids"][p], ss["src_off"][p]
        got = {}
        for i, s in enumerate(ids):
            if s == sg.nv:
                break
            got[int(s)] = sorted(
                ss["ss_dst"][p, off[i]:off[i + 1]].tolist())
        assert got == {k: sorted(v) for k, v in want.items()}
    # explicit s_pad: too small -> error; larger -> padded shape
    import pytest as _pytest
    with _pytest.raises(ValueError):
        sg.src_sorted(s_pad=1)
    assert sg.src_sorted(s_pad=64)["src_ids"].shape[1] == 64
