"""Elastic degraded-mesh recovery (round-11 ISSUE tentpole): topology
fault classification, checkpoint re-placement onto surviving devices,
heartbeat supervision, and the injectable DEVICE_LOSS / WORKER_KILL
fault actions.

The acceptance scenario: a deterministically injected device loss
mid-run on the 8-virtual-device CPU mesh (tests/conftest.py) resumes
on 4 devices and produces final state BITWISE-identical to an
uninterrupted 4-device run — for a pull app (pagerank) and a push app
(sssp) — and the static audit passes at both mesh sizes.
"""

import os

import numpy as np
import pytest

from lux_tpu import checkpoint as ckpt
from lux_tpu import faults, heartbeat, resilience, telemetry
from lux_tpu.apps import colfilter, components, pagerank, sssp
from lux_tpu.convert import uniform_random_edges
from lux_tpu.graph import Graph, ShardedGraph
from lux_tpu.parallel.mesh import make_mesh
from lux_tpu.segmented import DurationBudget

NOSLEEP = dict(sleep=lambda s: None, jitter=0)


def _graph(nv=256, ne=1800, seed=61, weighted=False):
    src, dst = uniform_random_edges(nv, ne, seed=seed)
    g = Graph.from_edges(src, dst, nv)
    if weighted:
        rng = np.random.default_rng(seed + 1)
        g.weights = rng.integers(1, 6, size=g.ne).astype(np.float32)
    return g


# -- classification ----------------------------------------------------

@pytest.mark.parametrize("exc,want", [
    (faults.InjectedDeviceLoss("chip gone", (7,)), resilience.TOPOLOGY),
    (faults.InjectedWorkerKill("worker gone", (6, 7)),
     resilience.TOPOLOGY),
    (heartbeat.WorkerLostError([1], 3, 55.0), resilience.TOPOLOGY),
    (RuntimeError("failed to connect to coordination service at "
                  "10.0.0.1:8471"), resilience.TOPOLOGY),
    (RuntimeError("Device TPU_3 is unavailable"), resilience.TOPOLOGY),
    (RuntimeError("heartbeat timed out waiting for task 2"),
     resilience.TOPOLOGY),
    # the PR-1 classes are untouched: a generic worker death stays
    # retryable (same mesh, fresh attempt), OOM stays fatal
    (RuntimeError("TPU worker terminated unexpectedly"),
     resilience.RETRYABLE),
    (RuntimeError("RESOURCE_EXHAUSTED: out of memory"),
     resilience.FATAL),
    (ConnectionError("heartbeat socket reset"), resilience.RETRYABLE),
    #  ^ typed transport outranks the topology message scan (PR-1
    #    convention: typed checks beat words)
])
def test_classify_topology(exc, want):
    assert resilience.classify(exc) == want


# -- RetryPolicy decorrelated jitter -----------------------------------

def test_jitter_is_seeded_and_deterministic():
    a = resilience.RetryPolicy(jitter_seed=7)
    b = resilience.RetryPolicy(jitter_seed=7)
    da = [a.delay_s(k) for k in range(6)]
    assert da == [b.delay_s(k) for k in range(6)]
    # stable within one instance (supervise reads it once per failure,
    # but a re-read must not advance the stream)
    assert a.delay_s(2) == da[2]
    # bounded by [backoff_s, max_backoff_s]
    assert all(1.0 <= d <= 60.0 for d in da)


def test_jitter_decorrelates_across_seeds():
    # two "worker processes": different seeds, different schedules —
    # the whole point (synchronized backoff is a retry stampede)
    da = [resilience.RetryPolicy(jitter_seed=1).delay_s(k)
          for k in range(6)]
    db = [resilience.RetryPolicy(jitter_seed=2).delay_s(k)
          for k in range(6)]
    assert da != db


def test_jitter_zero_restores_exponential():
    p = resilience.RetryPolicy(backoff_s=1.0, backoff_factor=2.0,
                               max_backoff_s=5.0, jitter=0)
    assert [p.delay_s(k) for k in range(4)] == [1.0, 2.0, 4.0, 5.0]


# -- DurationBudget rate reset on topology change ----------------------

def test_duration_budget_reset_rate_reenters_warmup():
    ev = telemetry.EventLog()
    b = DurationBudget(budget_s=1.0, probe_n=2, warmup=2)
    b.observe(2, 10.0)
    b.observe(2, 0.1)
    assert b.locked == 16
    with telemetry.use(events=ev):
        b.reset_rate(reason="mesh_shrink")
    assert b.locked is None and b.per_iter is None
    assert b.next_n(100) == 2          # back to the probe size
    assert ev.counts().get("budget_reset") == 1
    assert ev.events[-1]["reason"] == "mesh_shrink"
    # the per-size compile exemption reset too: every size is a fresh
    # compile on the new mesh
    assert not b._seen


# -- compatible mesh sizes ---------------------------------------------

def test_compatible_mesh_sizes():
    g = _graph(nv=64, ne=400)
    sg = ShardedGraph.build(g, num_parts=8)
    assert sg.compatible_mesh_sizes(8) == [8, 4, 2, 1]
    assert sg.compatible_mesh_sizes(7) == [4, 2, 1]
    assert sg.compatible_mesh_sizes(3) == [2, 1]
    assert sg.compatible_mesh_sizes(1) == [1]


# -- fault actions -----------------------------------------------------

def test_device_loss_action_names_mesh_tail():
    plan = faults.FaultPlan(schedule={0: faults.DEVICE_LOSS}, lose=2)
    with pytest.raises(faults.InjectedDeviceLoss) as ei:
        plan.fire(np.zeros(3), device_ids=[0, 1, 2, 5, 7])
    assert ei.value.lost_devices == (5, 7)
    assert resilience.classify(ei.value) == resilience.TOPOLOGY
    assert plan.fired == [(0, faults.DEVICE_LOSS)]


def test_device_loss_explicit_ids():
    plan = faults.FaultPlan(schedule={0: faults.DEVICE_LOSS},
                            lose=(3,))
    with pytest.raises(faults.InjectedDeviceLoss) as ei:
        plan.fire(np.zeros(3), device_ids=[0, 1, 2, 3])
    assert ei.value.lost_devices == (3,)


def test_worker_kill_action_raises_typed_without_hard_kill():
    plan = faults.FaultPlan(schedule={0: faults.WORKER_KILL}, lose=4)
    with pytest.raises(faults.InjectedWorkerKill) as ei:
        plan.fire(np.zeros(3), device_ids=list(range(8)))
    assert ei.value.lost_devices == (4, 5, 6, 7)
    assert "coordination service heartbeat" in str(ei.value)
    assert resilience.classify(ei.value) == resilience.TOPOLOGY


# -- the acceptance scenario: 8 -> 4 bitwise re-placement --------------

def _pr_factory(g):
    sg = ShardedGraph.build(g, num_parts=8)
    return lambda mesh: pagerank.build_engine(g, num_parts=8,
                                              mesh=mesh, sg=sg)


def test_pull_device_loss_resumes_bitwise_on_4(tmp_path):
    """Device loss at a segment boundary on the 8-device mesh: the
    supervisor shrinks to 4 survivors, re-places the checkpoint, and
    the final state is BITWISE the uninterrupted 4-device run's."""
    g = _graph()
    factory = _pr_factory(g)
    eng8 = factory(make_mesh(8))
    plan = faults.FaultPlan(schedule={1: faults.DEVICE_LOSS}, lose=1)
    path = str(tmp_path / "pr.npz")
    ev = telemetry.EventLog()
    with telemetry.use(events=ev):
        state, report = resilience.supervised_run(
            eng8, 10, path, segment=3, faults=plan, elastic=factory,
            policy=resilience.RetryPolicy(retries=2, **NOSLEEP))
    eng4 = factory(make_mesh(4))
    want = eng4.run(eng4.init_state(), 10)
    np.testing.assert_array_equal(eng8.unpad(state), eng4.unpad(want))
    assert report.topology == [
        {"from_ndev": 8, "to_ndev": 4, "lost_devices": [7]}]
    assert report.attempts == 2
    c = ev.counts()
    assert c.get("topology_fault") == 1
    assert c.get("mesh_shrink") == 1
    assert c.get("replace") == 1           # the checkpoint re-shard
    # the run finished DEGRADED and its report says so
    assert report.as_dict()["topology"][0]["to_ndev"] == 4


def test_push_device_loss_resumes_bitwise_on_4(tmp_path):
    """Same acceptance scenario for the push engine (sssp): the
    re-placed convergence finishes bitwise-equal to an uninterrupted
    4-device run."""
    g = _graph(nv=256, ne=2000, seed=62)
    sg = ShardedGraph.build(g, num_parts=8)

    def factory(mesh):
        return sssp.build_engine(g, start_vertex=0, num_parts=8,
                                 mesh=mesh, sg=sg)

    eng8 = factory(make_mesh(8))
    plan = faults.FaultPlan(schedule={1: faults.DEVICE_LOSS}, lose=1)
    path = str(tmp_path / "ss.npz")
    label, _active, total, report = resilience.supervised_converge(
        eng8, path, segment=2, faults=plan, elastic=factory,
        policy=resilience.RetryPolicy(retries=2, **NOSLEEP))
    eng4 = factory(make_mesh(4))
    l4, a4 = eng4.init_state()
    l4, _a4, _it = eng4.converge(l4, a4)
    np.testing.assert_array_equal(eng8.unpad(label), eng4.unpad(l4))
    assert report.topology == [
        {"from_ndev": 8, "to_ndev": 4, "lost_devices": [7]}]
    assert total > 0


def test_audit_passes_at_both_mesh_sizes():
    """The acceptance gate: the static audit's collective-schedule
    check must hold at the ORIGINAL ndev and at the post-shrink one
    (owner scan covers 2 device-local parts there)."""
    from lux_tpu import audit

    g = _graph(nv=128, ne=900, seed=63)
    sg = ShardedGraph.build(g, num_parts=8)
    for nd in (8, 4):
        eng = pagerank.build_engine(g, num_parts=8,
                                    mesh=make_mesh(nd), sg=sg,
                                    exchange="owner")
        errs = [f for f in audit.audit_engine(eng, mode=None)
                if f.severity == "error"]
        assert not errs, f"ndev={nd}: {errs}"


def test_pull_double_shrink_8_4_2(tmp_path):
    """Two topology faults in one run: 8 -> 4 -> 2, each re-placed,
    final state bitwise the uninterrupted 2-device run's."""
    g = _graph()
    factory = _pr_factory(g)
    eng8 = factory(make_mesh(8))
    plan = faults.FaultPlan(
        schedule={1: faults.DEVICE_LOSS, 3: faults.DEVICE_LOSS},
        lose=1)
    path = str(tmp_path / "pr2.npz")
    state, report = resilience.supervised_run(
        eng8, 12, path, segment=3, faults=plan, elastic=factory,
        policy=resilience.RetryPolicy(retries=3, **NOSLEEP))
    eng2 = factory(make_mesh(2))
    want = eng2.run(eng2.init_state(), 12)
    np.testing.assert_array_equal(eng8.unpad(state), eng2.unpad(want))
    assert [(t["from_ndev"], t["to_ndev"]) for t in report.topology] \
        == [(8, 4), (4, 2)]


# -- DEVICE_LOSS / WORKER_KILL coverage across all four apps -----------

def test_components_worker_kill_recovers(tmp_path):
    g = _graph(nv=200, ne=1500, seed=64)
    sg = ShardedGraph.build(g, num_parts=8)

    def factory(mesh):
        return components.build_engine(g, num_parts=8, mesh=mesh,
                                       sg=sg)

    eng8 = factory(make_mesh(8))
    # a dead WORKER takes its devices with it: 2 of 8 here
    plan = faults.FaultPlan(schedule={1: faults.WORKER_KILL}, lose=2)
    path = str(tmp_path / "cc.npz")
    label, _active, _total, report = resilience.supervised_converge(
        eng8, path, segment=2, faults=plan, elastic=factory,
        policy=resilience.RetryPolicy(retries=2, **NOSLEEP))
    eng4 = factory(make_mesh(4))
    l4, a4 = eng4.init_state()
    l4, _a4, _it = eng4.converge(l4, a4)
    np.testing.assert_array_equal(eng8.unpad(label), eng4.unpad(l4))
    assert report.topology[0]["from_ndev"] == 8
    assert report.topology[0]["to_ndev"] == 4
    assert report.topology[0]["lost_devices"] == [6, 7]


def test_colfilter_worker_kill_recovers(tmp_path):
    g = _graph(nv=128, ne=1500, seed=65, weighted=True)
    sg = ShardedGraph.build(g, num_parts=8)

    def factory(mesh):
        return colfilter.build_engine(g, num_parts=8, mesh=mesh,
                                      sg=sg)

    eng8 = factory(make_mesh(8))
    plan = faults.FaultPlan(schedule={1: faults.WORKER_KILL}, lose=2)
    path = str(tmp_path / "cf.npz")
    state, report = resilience.supervised_run(
        eng8, 6, path, segment=2, faults=plan, elastic=factory,
        policy=resilience.RetryPolicy(retries=2, **NOSLEEP))
    eng4 = factory(make_mesh(4))
    want = eng4.run(eng4.init_state(), 6)
    np.testing.assert_allclose(eng8.unpad(state), eng4.unpad(want),
                               rtol=1e-6)
    assert report.topology[0]["to_ndev"] == 4


# -- unhandled topology faults stay fatal ------------------------------

def test_topology_fault_without_elastic_is_fatal(tmp_path):
    """No elastic factory: a topology fault must NOT blind-retry on
    the same dead mesh — it re-raises even with retry budget left."""
    g = _graph()
    eng = pagerank.build_engine(g, num_parts=8, mesh=make_mesh(8))
    plan = faults.FaultPlan(schedule={1: faults.DEVICE_LOSS}, lose=1)
    report = resilience.RunReport()
    with pytest.raises(faults.InjectedDeviceLoss):
        resilience.supervised_run(
            eng, 10, str(tmp_path / "x.npz"), segment=3, faults=plan,
            policy=resilience.RetryPolicy(retries=3, **NOSLEEP),
            report=report)
    # no blind retry happened: the topology fault was fatal at once
    assert report.attempts == 1
    assert report.failures[0][2] == resilience.TOPOLOGY


def test_single_device_engine_has_no_topology_to_shrink(tmp_path):
    g = _graph(nv=64, ne=400)
    eng = pagerank.build_engine(g, num_parts=2)      # mesh=None

    def factory(mesh):                               # never callable
        raise AssertionError("must not rebuild without a mesh")

    plan = faults.FaultPlan(schedule={1: faults.DEVICE_LOSS}, lose=1)
    with pytest.raises(faults.InjectedDeviceLoss):
        resilience.supervised_run(
            eng, 10, str(tmp_path / "x.npz"), segment=3, faults=plan,
            elastic=factory,
            policy=resilience.RetryPolicy(retries=2, **NOSLEEP))


# -- checkpoint placement metadata -------------------------------------

def test_checkpoint_records_placement(tmp_path):
    g = _graph(nv=64, ne=400)
    eng = pagerank.build_engine(g, num_parts=2)
    path = str(tmp_path / "pr.npz")
    resilience.supervised_run(
        eng, 4, path, segment=2,
        policy=resilience.RetryPolicy(retries=0, **NOSLEEP))
    _leaves, meta = ckpt.load(path)
    pl = meta["placement"]
    assert pl["ndev"] == 1 and pl["num_parts"] == 2
    assert pl["vpad"] == eng.sg.vpad
    assert pl["exchange"] == "gather"


def test_resume_routes_mesh_mismatch_into_replacement(tmp_path):
    """A checkpoint written on 8 devices resumed by a 4-device engine
    is NOT an error: the global host view re-shards (eng.place), a
    ``replace`` event records it, and the result is bitwise the
    uninterrupted 4-device run's — the re-placement contract."""
    g = _graph()
    factory = _pr_factory(g)
    path = str(tmp_path / "pr.npz")
    eng8 = factory(make_mesh(8))
    resilience.supervised_run(
        eng8, 4, path, segment=2,
        policy=resilience.RetryPolicy(retries=0, **NOSLEEP))
    eng4 = factory(make_mesh(4))
    ev = telemetry.EventLog()
    with telemetry.use(events=ev):
        state, report = resilience.supervised_run(
            eng4, 10, path, segment=4, resume=True,
            policy=resilience.RetryPolicy(retries=0, **NOSLEEP))
    assert ev.counts().get("replace") == 1
    rp = [e for e in ev.events if e["kind"] == "replace"][0]
    assert (rp["from_ndev"], rp["to_ndev"]) == (8, 4)
    want = factory(make_mesh(4)).run(factory(make_mesh(4)).init_state(),
                                     10)
    np.testing.assert_array_equal(eng4.unpad(state), eng8.unpad(want))


def test_resume_rejects_exchange_mismatch(tmp_path):
    """Exchange modes reduce floats in different orders: resuming a
    gather-engine checkpoint into an owner engine (or vice versa)
    would silently break bitwise reproducibility — typed refusal."""
    g = _graph(nv=64, ne=400)
    eng = pagerank.build_engine(g, num_parts=2)
    path = str(tmp_path / "pr.npz")
    resilience.supervised_run(
        eng, 4, path, segment=2,
        policy=resilience.RetryPolicy(retries=0, **NOSLEEP))
    leaves, meta = ckpt.load(path)
    meta["placement"]["exchange"] = "owner"
    ckpt.save(path, tuple(leaves), meta)
    with pytest.raises(ValueError, match="exchange"):
        resilience.supervised_run(
            eng, 8, path, segment=2, resume=True,
            policy=resilience.RetryPolicy(retries=0, **NOSLEEP))


def test_resume_rejects_num_parts_mismatch(tmp_path):
    g = _graph(nv=64, ne=400)
    eng = pagerank.build_engine(g, num_parts=2)
    path = str(tmp_path / "pr.npz")
    resilience.supervised_run(
        eng, 4, path, segment=2,
        policy=resilience.RetryPolicy(retries=0, **NOSLEEP))
    leaves, meta = ckpt.load(path)
    meta["placement"]["num_parts"] = 4
    ckpt.save(path, tuple(leaves), meta)
    with pytest.raises(ValueError, match="num_parts"):
        resilience.supervised_run(
            eng, 8, path, segment=2, resume=True,
            policy=resilience.RetryPolicy(retries=0, **NOSLEEP))


def test_legacy_checkpoint_without_placement_resumes(tmp_path):
    """Pre-round-11 checkpoints carry no placement block; they keep
    resuming through the shape/dtype check alone."""
    g = _graph(nv=64, ne=400)
    eng = pagerank.build_engine(g, num_parts=2)
    path = str(tmp_path / "pr.npz")
    resilience.supervised_run(
        eng, 4, path, segment=2,
        policy=resilience.RetryPolicy(retries=0, **NOSLEEP))
    leaves, meta = ckpt.load(path)
    del meta["placement"]
    ckpt.save(path, tuple(leaves), meta)
    state, report = resilience.supervised_run(
        eng, 8, path, segment=4, resume=True,
        policy=resilience.RetryPolicy(retries=0, **NOSLEEP))
    np.testing.assert_allclose(
        eng.unpad(state), pagerank.reference_pagerank(g, 8), rtol=1e-5)


# -- heartbeat supervision (fake clock) --------------------------------

class _Clock:
    def __init__(self):
        self.t = 0.0

    def now(self):
        return self.t

    def sleep(self, s):
        self.t += s


def _board(tmp_path, pid, clock, nproc=2, deadline=10.0, **kw):
    return heartbeat.Heartbeat(
        path=str(tmp_path), pid=pid, nproc=nproc, deadline_s=deadline,
        poll_s=0.5, now=clock.now, sleep=clock.sleep, **kw)


def test_heartbeat_sync_returns_when_peers_reach_boundary(tmp_path):
    clk = _Clock()
    b0 = _board(tmp_path, 0, clk)
    b1 = _board(tmp_path, 1, clk)
    b1.beat(0)
    b0.sync(0)                         # peer already there: no wait
    assert clk.t == 0.0
    assert b0.survivors() == [0, 1]


def test_heartbeat_dead_peer_raises_worker_lost(tmp_path):
    clk = _Clock()
    b0 = _board(tmp_path, 0, clk)
    b1 = _board(tmp_path, 1, clk)
    b1.beat(0)                         # then silence
    with pytest.raises(heartbeat.WorkerLostError) as ei:
        b0.sync(1)
    assert ei.value.lost == (1,) and ei.value.boundary == 1
    assert clk.t > 10.0                # waited the full deadline
    assert resilience.classify(ei.value) == resilience.TOPOLOGY
    assert b0.survivors() == [0]


def test_heartbeat_never_started_peer_gets_launch_grace(tmp_path):
    clk = _Clock()
    b0 = _board(tmp_path, 0, clk)      # peer 1 never writes anything
    with pytest.raises(heartbeat.WorkerLostError) as ei:
        b0.sync(0)
    assert ei.value.lost == (1,)


def test_heartbeat_done_peer_satisfies_sync(tmp_path):
    clk = _Clock()
    b0 = _board(tmp_path, 0, clk)
    b1 = _board(tmp_path, 1, clk)
    b1.finish()
    b0.sync(7)                         # finished peers never block
    assert b0.survivors() == [0, 1]


def test_heartbeat_straggler_emits_event_then_catches_up(tmp_path):
    clk = _Clock()
    b0 = _board(tmp_path, 0, clk, deadline=20.0)
    b1 = _board(tmp_path, 1, clk, deadline=20.0)
    b1.beat(0)
    orig_sleep = clk.sleep

    def sleep(s):                      # the peer recovers at t=15
        orig_sleep(s)
        if clk.t >= 15:
            b1.beat(1)

    b0.sleep = sleep
    ev = telemetry.EventLog()
    with telemetry.use(events=ev):
        b0.sync(1)
    assert ev.counts().get("straggler") == 1
    assert ev.events[0]["peers"] == [1]


def test_heartbeat_propose_shrink_agrees(tmp_path):
    clk = _Clock()
    b0 = _board(tmp_path, 0, clk)
    b1 = _board(tmp_path, 1, clk, nproc=3)
    # worker 2 died; 0 (coordinator) proposes, 1 reads the same record
    t0 = b0.propose_shrink([0, 1], generation=1)
    t1 = b1.propose_shrink([0, 1], generation=1)
    assert t0 == t1
    assert t0["survivors"] == [0, 1] and t0["nproc"] == 2


def test_supervised_run_syncs_heartbeat_per_segment(tmp_path):
    """The distributed supervision wiring: a supervised run beats at
    every segment boundary and finishes done — a (simulated) peer
    board sees it alive throughout and finished at the end."""
    g = _graph(nv=64, ne=400)
    eng = pagerank.build_engine(g, num_parts=2)
    hb = heartbeat.Heartbeat(path=str(tmp_path / "hb"), pid=0,
                             nproc=1, deadline_s=30.0)
    state, report = resilience.supervised_run(
        eng, 6, str(tmp_path / "pr.npz"), segment=2, heartbeat=hb,
        policy=resilience.RetryPolicy(retries=0, **NOSLEEP))
    np.testing.assert_allclose(
        eng.unpad(state), pagerank.reference_pagerank(g, 6), rtol=1e-5)
    last = hb.read(0)
    assert last["done"] is True
    assert report.segments == 3


def test_device_loss_lose_more_than_mesh_takes_everything():
    """lose >= the whole mesh must name EVERY device (a wrapped
    negative slice would under-report the loss and let the handler
    'shrink' a mesh with no survivors)."""
    plan = faults.FaultPlan(schedule={0: faults.DEVICE_LOSS}, lose=3)
    with pytest.raises(faults.InjectedDeviceLoss) as ei:
        plan.fire(np.zeros(3), device_ids=[4, 9])
    assert ei.value.lost_devices == (4, 9)
