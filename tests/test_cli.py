"""CLI smoke tests (reference flag surface, README.md:40-52)."""

import numpy as np
import pytest

from lux_tpu import cli
from lux_tpu.convert import uniform_random_edges
from lux_tpu.graph import Graph
from lux_tpu import format as luxfmt


@pytest.fixture()
def lux_file(tmp_path):
    src, dst = uniform_random_edges(120, 900, seed=50)
    g = Graph.from_edges(src, dst, 120)
    p = tmp_path / "g.lux"
    luxfmt.write_lux(str(p), g.row_ptrs, g.col_idx, degrees=g.out_degrees)
    return str(p)


@pytest.fixture()
def weighted_lux_file(tmp_path):
    src, dst, w = uniform_random_edges(80, 600, seed=51, weighted=True)
    # symmetrize so colfilter updates both sides
    g = Graph.from_edges(np.concatenate([src, dst]),
                         np.concatenate([dst, src]), 80,
                         weights=np.concatenate([w, w]))
    p = tmp_path / "gw.lux"
    luxfmt.write_lux(str(p), g.row_ptrs, g.col_idx, weights=g.weights)
    return str(p)


def test_pagerank_cli(lux_file, capsys):
    rc = cli.main(["pagerank", "-file", lux_file, "-ni", "3", "-np", "2",
                   "-check", "-verbose"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "ELAPSED TIME" in out and "GTEPS" in out and "memory:" in out
    assert "[PASS]" in out


def test_health_flag_cli(lux_file, weighted_lux_file, capsys):
    """-health runs the watchdog loop variants on the fused AND the
    supervised paths, for pull and push apps alike."""
    rc = cli.main(["pagerank", "-file", lux_file, "-ni", "3",
                   "-np", "2", "-health"])
    assert rc == 0
    rc = cli.main(["sssp", "-file", lux_file, "-start", "0",
                   "-health"])
    assert rc == 0
    rc = cli.main(["components", "-file", lux_file, "-health",
                   "-retries", "1"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "ELAPSED TIME" in out


def test_validate_flag_cli(lux_file, tmp_path, capsys):
    """-validate: a good file runs; a corrupted one exits 2 with the
    typed check name, never a wrong-answer run."""
    rc = cli.main(["pagerank", "-file", lux_file, "-ni", "2",
                   "-validate"])
    assert rc == 0
    bad = tmp_path / "bad.lux"
    bad.write_bytes(open(lux_file, "rb").read())
    with open(bad, "r+b") as f:
        f.seek(12 + 8 * 120)                 # col_idx[0] out of range
        f.write(np.array([10 ** 6], np.uint32).tobytes())
    with pytest.raises(SystemExit) as ei:
        cli.main(["pagerank", "-file", str(bad), "-ni", "2",
                  "-validate"])
    assert ei.value.code == 2
    err = capsys.readouterr().err
    assert "col_idx_range" in err


def test_pagerank_cli_supervised_resume(lux_file, tmp_path, capsys):
    """-retries/-seg-budget/-resume run the supervised path
    (lux_tpu/resilience.py) and a second invocation resumes from the
    checkpoint instead of recomputing."""
    ck = str(tmp_path / "pr.ckpt.npz")
    rc = cli.main(["pagerank", "-file", lux_file, "-ni", "6", "-np", "2",
                   "-retries", "1", "-seg-budget", "30",
                   "-resume", ck, "-check"])
    out = capsys.readouterr().out
    assert rc == 0 and "[PASS]" in out
    assert "# supervisor: attempts=1" in out
    import os
    assert os.path.exists(ck)
    rc = cli.main(["pagerank", "-file", lux_file, "-ni", "6", "-np", "2",
                   "-resume", ck, "-check"])
    out = capsys.readouterr().out
    assert rc == 0 and "[PASS]" in out
    assert "resumed_from=[6]" in out


def test_sssp_cli_supervised(lux_file, capsys):
    rc = cli.main(["sssp", "-file", lux_file, "-start", "1",
                   "-retries", "1", "-seg-budget", "30", "-check"])
    out = capsys.readouterr().out
    assert rc == 0 and "[PASS]" in out
    assert "# supervisor:" in out


def test_sssp_cli(lux_file, capsys):
    rc = cli.main(["sssp", "-file", lux_file, "-start", "1", "-check"])
    out = capsys.readouterr().out
    assert rc == 0 and "[PASS]" in out and "iterations" in out


def test_sssp_weighted_cli(weighted_lux_file, capsys):
    rc = cli.main(["sssp", "-file", weighted_lux_file, "-weighted",
                   "-check"])
    assert rc == 0
    assert "[PASS]" in capsys.readouterr().out


def test_components_cli(lux_file, capsys):
    rc = cli.main(["components", "-file", lux_file, "-check"])
    assert rc == 0
    assert "[PASS]" in capsys.readouterr().out


def test_colfilter_cli(weighted_lux_file, capsys):
    rc = cli.main(["colfilter", "-file", weighted_lux_file, "-ni", "2"])
    out = capsys.readouterr().out
    assert rc == 0 and "RMSE" in out


def test_pair_flag_cli(lux_file, capsys):
    """-pair relabels internally and maps results back to input ids,
    so -check (which runs against the INPUT graph for pagerank/sssp)
    must still pass."""
    for app, extra in [("pagerank", ["-ni", "3"]),
                       ("sssp", ["-start", "1"]),
                       ("components", [])]:
        rc = cli.main([app, "-file", lux_file, "-pair", "2", "-check",
                       *extra])
        out = capsys.readouterr().out
        assert rc == 0, f"{app}: {out}"
        assert "[PASS]" in out, f"{app}: {out}"


def _iter_lines(out):
    return [ln for ln in out.splitlines() if ln.startswith("iter ")]


def test_iter_stats_matches_verbose_replay(lux_file, capsys):
    """-iter-stats on the fused timed path reports the same
    per-iteration frontier series as -verbose (both replay the
    device-side counters; test_telemetry ties that series to the
    stepwise NumPy oracle)."""
    rc = cli.main(["sssp", "-file", lux_file, "-start", "1",
                   "-iter-stats"])
    stats_out = capsys.readouterr().out
    assert rc == 0
    assert "# iter-stats" in stats_out
    rc = cli.main(["sssp", "-file", lux_file, "-start", "1",
                   "-verbose"])
    verbose_out = capsys.readouterr().out
    assert rc == 0
    assert _iter_lines(stats_out) == _iter_lines(verbose_out)
    assert _iter_lines(stats_out), "no per-iteration lines printed"


def test_events_flag_writes_jsonl(lux_file, tmp_path, capsys):
    import json

    ev = tmp_path / "events.jsonl"
    rc = cli.main(["pagerank", "-file", lux_file, "-ni", "3",
                   "-np", "2", "-events", str(ev), "-iter-stats"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "iter 1: residual=" in out
    events = [json.loads(s) for s in ev.read_text().splitlines()]
    kinds = [e["kind"] for e in events]
    assert kinds[0] == "run_start" and "header" in kinds
    assert "run_done" in kinds and "iter_stats" in kinds
    hdr = events[kinds.index("header")]
    assert hdr["nv"] == 120 and hdr["memory"]["total_bytes"] > 0


def test_iter_stats_supervised_segments(lux_file, tmp_path, capsys):
    """Counters accumulate across supervised segment boundaries: the
    supervised run's series equals the plain fused run's."""
    import json

    ev = tmp_path / "events.jsonl"
    rc = cli.main(["sssp", "-file", lux_file, "-start", "1",
                   "-iter-stats"])
    plain = _iter_lines(capsys.readouterr().out)
    assert rc == 0
    rc = cli.main(["sssp", "-file", lux_file, "-start", "1",
                   "-iter-stats", "-retries", "1", "-seg-budget", "30",
                   "-events", str(ev)])
    sup_out = capsys.readouterr().out
    assert rc == 0
    assert _iter_lines(sup_out) == plain
    kinds = [json.loads(s)["kind"] for s in ev.read_text().splitlines()]
    assert "segment" in kinds and "checkpoint_save" in kinds


def test_convert_cli(tmp_path, capsys):
    txt = tmp_path / "e.txt"
    txt.write_text("0 1\n1 2\n2 0\n")
    out = tmp_path / "e.lux"
    rc = cli.main(["convert", "-input", str(txt), "-output", str(out),
                   "-nv", "3"])
    assert rc == 0
    # nv == ne makes the size-based layout inference ambiguous; be
    # explicit like any caller that knows its file
    g = Graph.from_file(str(out), weighted=False)
    assert g.nv == 3 and g.ne == 3


def test_unknown_app(capsys):
    assert cli.main(["nope"]) == 2


def test_help(capsys):
    assert cli.main([]) == 2
    assert cli.main(["-h"]) == 0


def test_elastic_flag_without_mesh_notes_and_runs(lux_file, capsys):
    """-elastic on a single-device run has no topology to shrink: the
    CLI says so and the supervised run still completes (round 11)."""
    rc = cli.main(["pagerank", "-file", lux_file, "-ni", "4",
                   "-np", "2", "-retries", "1", "-elastic"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "-elastic needs -mesh > 1" in out
    assert "GTEPS" in out


def test_elastic_flag_armed_with_mesh(lux_file, capsys):
    """-elastic with a real mesh arms the supervised path (no fault
    fires here — the recovery itself is exercised in
    tests/test_elastic.py; this is the CLI wiring)."""
    rc = cli.main(["pagerank", "-file", lux_file, "-ni", "4",
                   "-np", "2", "-mesh", "2", "-retries", "1",
                   "-elastic"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "-elastic needs" not in out
    assert "supervisor: attempts=1" in out


def test_elastic_flag_without_supervised_path_notes(lux_file, capsys):
    """-elastic with no -retries/-seg-budget/-resume has no
    checkpoint to re-place from: the CLI says so instead of silently
    dropping the recovery flag."""
    rc = cli.main(["pagerank", "-file", lux_file, "-ni", "3",
                   "-np", "2", "-mesh", "2", "-elastic"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "-elastic implies the supervised path" in out


def test_elastic_flag_with_zero_retries_notes(lux_file, capsys):
    """Armed-but-inert: with -resume but -retries 0 the topology
    handler is never consulted — the CLI warns."""
    import os
    rc = cli.main(["pagerank", "-file", lux_file, "-ni", "3",
                   "-np", "2", "-mesh", "2", "-elastic",
                   "-seg-budget", "30"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "-elastic needs -retries >= 1" in out
