"""timing.fence / _cksum: the O(1)-byte completion fence.

Round-7 regression (ISSUE 2 satellite): the checksum used to cast
every leaf through float32, whose 24-bit mantissa collapses integer
values differing only above bit 24 — exactly the packed uint32 pair
rows (src << 7 | rel).  Wide integer leaves must now sum exactly.
"""

import jax.numpy as jnp
import numpy as np

from lux_tpu.timing import _cksum, fence, fetch


def ck(*leaves):
    return np.asarray(_cksum(*leaves))


def test_wide_uint32_values_distinguished():
    """Two packed-pair-row buffers differing only above float32
    precision must produce different checksums (the old float32 path
    mapped both to the same number)."""
    a = jnp.full((8,), 1 << 25, jnp.uint32)
    b = a.at[0].set((1 << 25) + 1)
    old_a = float(jnp.sum(a[:8].astype(jnp.float32)))
    old_b = float(jnp.sum(b[:8].astype(jnp.float32)))
    assert old_a == old_b          # the bug this test pins down
    assert not np.array_equal(ck(a), ck(b))


def test_wide_int_sum_is_exact_and_deterministic():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 1 << 32, size=8, dtype=np.uint32))
    assert np.array_equal(ck(x), ck(x))
    # flipping any single low bit moves the checksum
    for i in range(8):
        y = x.at[i].set(x[i] ^ 1)
        assert not np.array_equal(ck(x), ck(y)), f"lane {i}"


def test_narrow_and_float_leaves_ride_the_float_channel():
    # int16 fits float32 exactly: stays on the float channel
    small = jnp.arange(8, dtype=jnp.int16)
    f = jnp.linspace(0.0, 1.0, 8, dtype=jnp.float32)
    c = ck(small, f)
    assert c.shape == (3,)
    assert c[1] == 0 and c[2] == 0       # int channels untouched
    # mixed wide + float: each rides its own channel
    wide = jnp.full((8,), (1 << 30) + 7, jnp.uint32)
    c2 = ck(f, wide)
    assert c2[0] == float(jnp.sum(f))
    assert (c2[1], c2[2]) != (0.0, 0.0)


def test_fence_handles_packed_pytrees():
    """fence() on a state pytree containing wide uint32 leaves (the
    packed owner layout) completes without error and leaves the state
    intact."""
    state = {"rows": jnp.full((4, 8), (1 << 26) + 3, jnp.uint32),
             "vals": jnp.ones((4, 8), jnp.float32)}
    fence(state)
    np.testing.assert_array_equal(fetch(state["rows"]),
                                  np.full((4, 8), (1 << 26) + 3,
                                          np.uint64).astype(np.uint32))
