"""lux_tpu/livegraph.py: live graphs — crash-consistent mutation log,
snapshot-isolated epochs, incremental revalidation, chaos-drilled
compaction (ISSUE 15, round 20), and the FULL mutation algebra
(ISSUE 16, round 21): edge deletions + weight updates (v2 WAL
records, v1 bitwise compat), the anti-monotone re-seed proved equal
to full recompute at the same epoch (bitwise for the integer apps)
against the decremental oracles, pull-kind incremental revalidation
(pagerank epochs advance WITHOUT a fold), and the economics-driven
CompactionScheduler soak-proven on mesh8 under seeded mixed traffic
with bounded occupancy and zero delta_full sheds.

THE chaos acceptance: oversubscribed mixed-kind open-loop loadgen
traffic on the 8-virtual-device mesh with a LIVE mutation stream
(ingest concurrent with the drain), one replica killed mid-drain AND
one injected crash mid-compaction — every admitted answer equals its
NumPy oracle evaluated at the query's ADMISSION epoch (bitwise for
the integer apps), zero torn reads (the events_summary torn-epoch
audit is armed on every live answer), zero duplicate retirements, and
the WAL replay after the crash is bitwise-identical.

Plus: WAL round-trip/torn-tail/typed-corruption units, the
MUT_CRASH / WAL_TORN / COMPACT_CRASH fault legs, incremental oracles
proved equal to full recompute, the device revalidation proved equal
at the same epoch (per-column epochs = snapshot isolation inside one
dispatch), the epoch-keyed answer cache (a stale-epoch hit is a test
failure), and the delta_full backpressure shed.
"""

import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from lux_tpu import faults, format as luxfmt, telemetry
from lux_tpu.apps import components, sssp
from lux_tpu.convert import uniform_random_edges
from lux_tpu.graph import Graph
from lux_tpu.livegraph import (CompactPinnedError, DeltaFullError,
                               EPOCH_SENTINEL, LiveGraph, MutationLog,
                               MutationLogError, check_live_answers)

REPO = Path(__file__).resolve().parent.parent
SUMMARY = REPO / "scripts" / "events_summary.py"
FSCK = REPO / "scripts" / "fsck_lux.py"
sys.path.insert(0, str(REPO / "scripts"))
sys.path.insert(0, str(REPO))

NV, NE, SEED = 256, 2048, 5


@pytest.fixture(scope="module")
def g():
    src, dst = uniform_random_edges(NV, NE, seed=SEED)
    return Graph.from_edges(src, dst, NV)


@pytest.fixture(scope="module")
def gw():
    src, dst = uniform_random_edges(NV, NE, seed=SEED)
    rng = np.random.default_rng(11)
    w = rng.uniform(0.5, 4.0, size=NE).astype(np.float32)
    return Graph.from_edges(src, dst, NV, weights=w)


def _mutations(nv, n, seed):
    rng = np.random.default_rng(seed)
    return rng.integers(nv, size=n), rng.integers(nv, size=n)


def _sssp_host(eng, label):
    import jax
    h = eng.sg.from_padded(np.asarray(jax.device_get(label)))
    return np.where(h >= int(sssp.HOP_INF), int(sssp.HOP_INF),
                    h.astype(np.int64))


def _clamp_ref(ref):
    return np.where(ref >= int(sssp.HOP_INF), int(sssp.HOP_INF), ref)


def _wal_state(lg: LiveGraph):
    """Everything the WAL-replay bitwise contract covers.  Round-21
    leaves (d_kind, the deletion/reweight counters) append at the
    END — test_wal_torn_fault_mid_append slices positionally."""
    return (lg.base.row_ptrs.copy(), lg.base.col_idx.copy(),
            None if lg.base.weights is None else lg.base.weights.copy(),
            lg.d_src.copy(), lg.d_dst.copy(), lg.d_w.copy(),
            lg.d_epoch.copy(), lg.count, lg.epoch, lg.base_epoch,
            lg.generation, lg.compactions, lg.d_kind.copy(),
            lg.deletions, lg.reweights)


def _live_edge(g, i: int = 0):
    """The i-th base edge — a guaranteed-live deletion/reweight
    target at epoch 0."""
    src, dst = g.edge_arrays()
    return int(src[i]), int(dst[i])


def _phantom_edge(g):
    """A (src, dst) pair that is NOT an edge of g."""
    src, dst = g.edge_arrays()
    have = set(zip(src.tolist(), dst.tolist()))
    for s in range(g.nv):
        for d in range(g.nv):
            if (s, d) not in have:
                return s, d
    raise AssertionError("complete graph")


def _assert_state_equal(a, b):
    for i, (x, y) in enumerate(zip(a, b)):
        if isinstance(x, np.ndarray):
            np.testing.assert_array_equal(x, y, err_msg=f"leaf {i}")
        else:
            assert x == y, f"leaf {i}: {x} != {y}"


# ---------------------------------------------------------------------
# the mutation log


class TestMutationLog:
    def test_wal_roundtrip_bitwise(self, g, tmp_path):
        wal = str(tmp_path / "g.lux.wal")
        lg = LiveGraph(g, capacity=64, wal_path=wal)
        s1, d1 = _mutations(g.nv, 5, 1)
        s2, d2 = _mutations(g.nv, 3, 2)
        lg.append_edges(s1, d1)
        lg.append_edges(s2, d2)
        want = _wal_state(lg)
        lg.close()
        lg2 = LiveGraph.recover(g, wal)
        _assert_state_equal(_wal_state(lg2), want)
        # the recovered log is RESUMABLE: the chain continues
        lg2.append_edges([1], [2])
        lg2.close()
        lg3 = LiveGraph.recover(g, wal)
        assert lg3.epoch == 3 and lg3.count == 9
        lg3.close()

    def test_torn_tail_at_rest_truncated(self, g, tmp_path):
        wal = str(tmp_path / "g.lux.wal")
        lg = LiveGraph(g, capacity=16, wal_path=wal)
        lg.append_edges([1, 2], [3, 4])
        want = _wal_state(lg)
        lg.close()
        faults.tear_wal(wal, keep_bytes=9)
        recs, _nv, _cap, torn = MutationLog.scan(wal, nv=g.nv)
        assert len(recs) == 2 and torn == 9
        ev = telemetry.EventLog()
        with telemetry.use(events=ev):
            lg2 = LiveGraph.recover(g, wal)
        _assert_state_equal(_wal_state(lg2), want)
        assert any(e["kind"] == "wal_truncate" for e in ev.events)
        # the truncation really happened on disk: a re-scan is clean
        _, _, _, torn2 = MutationLog.scan(wal, nv=g.nv)
        assert torn2 == 0
        lg2.close()

    def test_weights_on_unweighted_live_graph_refused(self, g):
        """REGRESSION: weights passed to an unweighted live graph
        were silently zeroed — journaled as 0.0 bits and served as
        hop counts with no signal the caller's data vanished.
        Graph.with_edges refuses this same mismatch typed."""
        lg = LiveGraph(g, capacity=8)
        with pytest.raises(ValueError, match="UNWEIGHTED"):
            lg.append_edges([1], [2], weights=[2.5])
        assert lg.count == 0 and lg.epoch == 0

    def test_existing_wal_refused_typed(self, g, tmp_path):
        """REGRESSION: restarting with the same construction call
        after a crash — the very situation the WAL exists for — used
        to die on a raw FileExistsError; every other integrity
        refusal here is typed.  The refusal now names recover()."""
        wal = str(tmp_path / "g.lux.wal")
        lg = LiveGraph(g, capacity=8, wal_path=wal)
        lg.append_edges([1], [2])
        lg.close()
        with pytest.raises(MutationLogError, match="recover") as ei:
            LiveGraph(g, capacity=8, wal_path=wal)
        assert ei.value.check == "wal_exists"
        # the durable history is untouched by the refusal
        lg2 = LiveGraph.recover(g, wal)
        assert lg2.count == 1 and lg2.epoch == 1
        lg2.close()

    def test_tear_wal_clamped_to_strict_record_prefix(self, g,
                                                      tmp_path):
        """REGRESSION: a mid-append tear is by definition a STRICT
        record prefix, but tear_wal(keep_bytes >= WAL_RECORD_SIZE)
        used to append a full-record-sized garbage tail — which scan
        rightly classifies as hard crc_chain corruption of a
        possibly-acknowledged record, the opposite of the
        recoverable torn tail the helper promises.  The clamp keeps
        every keep_bytes recoverable."""
        wal = str(tmp_path / "g.lux.wal")
        lg = LiveGraph(g, capacity=16, wal_path=wal)
        lg.append_edges([1, 2], [3, 4])
        want = _wal_state(lg)
        lg.close()
        faults.tear_wal(wal, keep_bytes=luxfmt.WAL_RECORD_SIZE)
        recs, _nv, _cap, torn = MutationLog.scan(wal, nv=g.nv)
        assert len(recs) == 2
        assert 0 < torn < luxfmt.WAL_RECORD_SIZE
        lg2 = LiveGraph.recover(g, wal)
        _assert_state_equal(_wal_state(lg2), want)
        lg2.close()

    def test_wal_torn_fault_mid_append(self, g, tmp_path):
        """The WAL_TORN leg: the injected crash tears the record
        mid-write; replay truncates and recovers the exact
        pre-append state."""
        wal = str(tmp_path / "g.lux.wal")
        plan = faults.MutationFaultPlan(
            schedule={3: faults.WAL_TORN})
        lg = LiveGraph(g, capacity=16, wal_path=wal, fault=plan)
        lg.append_edges([1, 2], [3, 4])
        want = _wal_state(lg)
        with pytest.raises(faults.InjectedWorkerCrash):
            lg.append_edges([5, 6], [7, 8])
        assert plan.fired == [(3, faults.WAL_TORN)]
        lg.close()
        lg2 = LiveGraph.recover(g, wal)
        # the durable prefix of the crashed batch replays (edge 5->7
        # landed whole before the tear at the second edge)
        assert lg2.count == 3 and lg2.epoch == 2
        np.testing.assert_array_equal(lg2.d_src[:3], [1, 2, 5])
        # the pre-batch state is a strict prefix: nothing invented
        _assert_state_equal(
            tuple(x[:2] if isinstance(x, np.ndarray) and x.shape
                  and len(x) == 16 else x
                  for x in _wal_state(lg2)[:7]) + _wal_state(lg2)[9:],
            tuple(x[:2] if isinstance(x, np.ndarray) and x.shape
                  and len(x) == 16 else x
                  for x in want[:7]) + want[9:])
        lg2.close()

    def test_mut_crash_leaves_nothing(self, g, tmp_path):
        wal = str(tmp_path / "g.lux.wal")
        plan = faults.MutationFaultPlan(
            schedule={2: faults.MUT_CRASH})
        lg = LiveGraph(g, capacity=16, wal_path=wal, fault=plan)
        lg.append_edges([1, 2], [3, 4])
        want = _wal_state(lg)
        with pytest.raises(faults.InjectedWorkerCrash):
            lg.append_edges([9], [10])
        lg.close()
        lg2 = LiveGraph.recover(g, wal)
        _assert_state_equal(_wal_state(lg2), want)
        lg2.close()

    def test_midfile_corruption_typed(self, g, tmp_path):
        wal = str(tmp_path / "g.lux.wal")
        lg = LiveGraph(g, capacity=16, wal_path=wal)
        lg.append_edges([1, 2, 3], [4, 5, 6])
        lg.close()
        blob = bytearray(open(wal, "rb").read())
        blob[luxfmt.WAL_HEADER_SIZE + 4] ^= 0xFF
        open(wal, "wb").write(bytes(blob))
        with pytest.raises(MutationLogError) as ei:
            MutationLog.scan(wal)
        assert ei.value.check == "crc_chain"
        with pytest.raises(MutationLogError):
            LiveGraph.recover(g, wal)

    def test_full_record_bad_crc_tail_is_corruption(self, g,
                                                    tmp_path):
        """A FULL-SIZE final record failing its CRC is rot of a
        possibly-fsync-acknowledged append — a torn append can only
        leave a strict prefix — so scan must raise crc_chain, never
        silently truncate an acknowledged mutation away."""
        wal = str(tmp_path / "g.lux.wal")
        log = MutationLog(wal, g.nv, 8)
        log.append_edge(1, 1, 2, 0)
        log.append_edge(2, 3, 4, 0)
        log.close()
        blob = bytearray(open(wal, "rb").read())
        blob[-10] ^= 0xFF               # inside the LAST record
        open(wal, "wb").write(bytes(blob))
        with pytest.raises(MutationLogError) as ei:
            MutationLog.scan(wal)
        assert ei.value.check == "crc_chain"
        assert "acknowledged" in str(ei.value)

    def test_epoch_regression_typed(self, g, tmp_path):
        wal = str(tmp_path / "g.lux.wal")
        log = MutationLog(wal, g.nv, 16)
        log.append_edge(3, 1, 2, 0)
        log.append_edge(1, 3, 4, 0)     # epoch going BACKWARDS
        log.close()
        with pytest.raises(MutationLogError) as ei:
            MutationLog.scan(wal)
        assert ei.value.check == "epoch_order"

    def test_unknown_record_kind_typed(self, g, tmp_path):
        from lux_tpu.livegraph import _pack_record
        wal = str(tmp_path / "g.lux.wal")
        log = MutationLog(wal, g.nv, 16)
        log._append(_pack_record(1, 9, 0, 0, 0, log._crc))
        log.close()
        with pytest.raises(MutationLogError) as ei:
            MutationLog.scan(wal)
        assert ei.value.check == "record_kind"

    def test_foreign_graph_header_typed(self, g, tmp_path):
        wal = str(tmp_path / "g.lux.wal")
        lg = LiveGraph(g, capacity=16, wal_path=wal)
        lg.append_edges([1], [2])
        lg.close()
        with pytest.raises(luxfmt.GraphFormatError) as ei:
            MutationLog.scan(wal, nv=g.nv + 1)
        assert ei.value.check == "wal_header"
        # garbage file: header magic check
        bad = str(tmp_path / "junk.wal")
        open(bad, "wb").write(b"NOPE" + b"\0" * 20)
        with pytest.raises(luxfmt.GraphFormatError) as ei:
            MutationLog.scan(bad)
        assert ei.value.check == "wal_header"

    def test_compact_done_without_start_typed(self, g, tmp_path):
        from lux_tpu.livegraph import REC_COMPACT_DONE
        wal = str(tmp_path / "g.lux.wal")
        log = MutationLog(wal, g.nv, 16)
        log.append_edge(1, 1, 2, 0)
        log.append_marker(1, REC_COMPACT_DONE, 1, 1)
        log.close()
        with pytest.raises(MutationLogError) as ei:
            LiveGraph.recover(g, wal)
        assert ei.value.check == "compact_pair"

    def test_capacity_overflow_replay_typed(self, g, tmp_path):
        wal = str(tmp_path / "g.lux.wal")
        log = MutationLog(wal, g.nv, 2)
        for i in range(3):
            log.append_edge(i + 1, 1, 2, 0)
        log.close()
        with pytest.raises(MutationLogError) as ei:
            LiveGraph.recover(g, wal)
        assert ei.value.check == "capacity_overflow"

    def test_fsck_wal_legs(self, g, tmp_path):
        """scripts/fsck_lux.py knows the WAL format: clean log OK,
        torn tail reported-but-clean, corruption exit 2, a sidecar
        from a different graph exit 2."""
        lux = str(tmp_path / "g.lux")
        luxfmt.write_lux(lux, g.row_ptrs, g.col_idx)
        wal = luxfmt.wal_sidecar_path(lux)
        lg = LiveGraph(g, capacity=16, wal_path=wal)
        lg.append_edges([1, 2], [3, 4])
        lg.compact(force=True)
        lg.close()
        r = subprocess.run([sys.executable, str(FSCK), lux],
                           capture_output=True, text=True)
        assert r.returncode == 0 and "OK wal" in r.stdout
        faults.tear_wal(wal)
        r = subprocess.run([sys.executable, str(FSCK), wal],
                           capture_output=True, text=True)
        assert r.returncode == 0 and "TORN-TAIL" in r.stdout
        blob = bytearray(open(wal, "rb").read())
        blob[luxfmt.WAL_HEADER_SIZE + 1] ^= 0xFF
        open(wal, "wb").write(bytes(blob))
        r = subprocess.run([sys.executable, str(FSCK), wal],
                           capture_output=True, text=True)
        assert r.returncode == 2 and "crc_chain" in r.stderr


# ---------------------------------------------------------------------
# the live graph: epochs, delta blocks, compaction


class TestLiveGraph:
    def test_epochs_monotone_and_delta_full(self, g):
        lg = LiveGraph(g, capacity=4)
        assert lg.append_edges([1], [2]) == 1
        assert lg.append_edges([3, 4], [5, 6]) == 2
        assert lg.epoch == 2 and lg.count == 3
        assert lg.occupancy() == 0.75
        with pytest.raises(DeltaFullError):
            lg.append_edges([7, 8], [9, 10])
        # the refused batch published NOTHING (epoch and slots)
        assert lg.epoch == 2 and lg.count == 3
        # unwritten slots carry the sentinel (torn-read-free mask)
        assert lg.d_epoch[3] == EPOCH_SENTINEL

    def test_append_validation_typed(self, g, gw):
        lg = LiveGraph(g, capacity=4)
        with pytest.raises(ValueError, match="length mismatch"):
            lg.append_edges([1, 2], [3])
        with pytest.raises(ValueError, match="outside"):
            lg.append_edges([g.nv], [0])
        with pytest.raises(ValueError, match="weights"):
            LiveGraph(gw, capacity=4).append_edges([1], [2])
        # a SHORT weights array must refuse BEFORE any WAL append /
        # delta publish — not IndexError mid-batch with edges already
        # durable
        lw = LiveGraph(gw, capacity=4)
        with pytest.raises(ValueError, match="weights length"):
            lw.append_edges([1, 2, 3], [4, 5, 6], weights=[0.5, 0.5])
        assert lw.epoch == 0 and lw.count == 0
        with pytest.raises(ValueError, match="capacity"):
            LiveGraph(g, capacity=0)

    def test_graph_at_is_the_oracle_surface(self, g):
        lg = LiveGraph(g, capacity=8)
        s1, d1 = _mutations(g.nv, 3, 3)
        lg.append_edges(s1, d1)
        assert lg.graph_at(0).ne == g.ne
        g1 = lg.graph_at(1)
        want = g.with_edges(s1, d1)
        np.testing.assert_array_equal(g1.row_ptrs, want.row_ptrs)
        np.testing.assert_array_equal(g1.col_idx, want.col_idx)
        with pytest.raises(ValueError):
            lg.graph_at(2)

    def test_compact_swaps_generation_atomically(self, g, tmp_path):
        wal = str(tmp_path / "g.lux.wal")
        lg = LiveGraph(g, capacity=8, wal_path=wal,
                       compact_threshold=0.5)
        s1, d1 = _mutations(g.nv, 4, 4)
        lg.append_edges(s1, d1)
        assert lg.should_compact()
        eco = lg.compact_economics()
        assert eco["should_compact"] and eco["delta_count"] == 4
        old_delta = lg.d_epoch      # published block stays immutable
        assert lg.compact() == 1
        assert lg.generation == 1 and lg.base_epoch == 1
        assert lg.count == 0 and lg.base.ne == g.ne + 4
        # FRESH arrays, not zeroed-under-the-reader ones
        assert lg.d_epoch is not old_delta
        assert (old_delta[:4] == 1).all()
        # pull view now sees the folded epoch; push always the latest
        assert lg.view_epoch("pull") == 1
        assert lg.view_epoch("push") == 1
        lg.close()
        # recovery re-folds the COMPLETED compaction bitwise
        lg2 = LiveGraph.recover(g, wal)
        np.testing.assert_array_equal(lg2.base.row_ptrs,
                                      lg.base.row_ptrs)
        np.testing.assert_array_equal(lg2.base.col_idx,
                                      lg.base.col_idx)
        assert lg2.generation == 1 and lg2.count == 0
        lg2.close()

    def test_compact_refused_while_pinned(self, g):
        lg = LiveGraph(g, capacity=4)
        lg.append_edges([1], [2])
        lg.pin()
        with pytest.raises(CompactPinnedError):
            lg.compact(force=True)
        lg.unpin()
        assert lg.compact(force=True) == 1

    def test_compact_crash_recovers_surviving_generation(
            self, g, tmp_path):
        """THE COMPACT_CRASH leg: the crash lands between the WAL
        COMPACT_START marker and the generation swap; recovery comes
        up on the SURVIVING generation (origin base + full published
        delta) bitwise, and the next compaction completes."""
        wal = str(tmp_path / "g.lux.wal")
        plan = faults.MutationFaultPlan(
            compact_schedule={0: faults.COMPACT_CRASH})
        lg = LiveGraph(g, capacity=8, wal_path=wal, fault=plan)
        s1, d1 = _mutations(g.nv, 5, 6)
        lg.append_edges(s1, d1)
        want = _wal_state(lg)
        with pytest.raises(faults.InjectedWorkerCrash):
            lg.compact(force=True)
        assert plan.fired == [(0, faults.COMPACT_CRASH)]
        lg.close()
        # the log holds a START without a DONE; fsck still reports
        # the file clean (an open compaction is a crash signature,
        # not corruption)
        r = subprocess.run([sys.executable, str(FSCK), wal],
                           capture_output=True, text=True)
        assert r.returncode == 0 and "open-compaction" in r.stdout
        lg2 = LiveGraph.recover(g, wal)
        _assert_state_equal(_wal_state(lg2), want)
        # and the generation is fully usable: compact completes now
        assert lg2.compact(force=True) == 1
        assert lg2.base.ne == g.ne + 5
        lg2.close()

    def test_concurrent_append_during_compact_loses_nothing(
            self, g, tmp_path):
        """compact() holds the mutation lock end to end: an append
        racing the ~40ms fold must land either wholly BEFORE the
        swap (folded into the new base) or wholly AFTER (published
        in the fresh delta) — never silently dropped, and never as
        an epoch-e+1 WAL record ahead of the epoch-e START marker
        (which would fail the log's own epoch_order validation)."""
        wal = str(tmp_path / "g.lux.wal")
        lg = LiveGraph(g, capacity=512, wal_path=wal)
        stop = threading.Event()
        drained = threading.Event()
        appended = []

        def ingest():
            rng = np.random.default_rng(99)
            while not stop.is_set():
                s = int(rng.integers(g.nv))
                d = int(rng.integers(g.nv))
                try:
                    lg.append_edges([s], [d])
                except DeltaFullError:
                    # wait for the compactor's signal instead of a
                    # wall-clock sleep (flaky under CI load); the
                    # timeout is liveness only, not pacing
                    drained.clear()
                    drained.wait(0.1)
                    continue
                appended.append((s, d))

        th = threading.Thread(target=ingest)
        th.start()
        compactions = 0
        deadline = time.monotonic() + 3.0
        while compactions < 4 and time.monotonic() < deadline:
            if lg.compact(force=True) is not None:
                compactions += 1
                drained.set()
        stop.set()
        drained.set()
        th.join()
        assert compactions >= 2 and len(appended) > 0
        # every acknowledged edge is in new-base-or-delta
        total = lg.base.ne + lg.count
        assert total == g.ne + len(appended)
        lg.close()
        # and the WAL both scans clean and replays to the same count
        lg2 = LiveGraph.recover(g, wal)
        assert lg2.base.ne + lg2.count == g.ne + len(appended)
        lg2.close()


# ---------------------------------------------------------------------
# incremental oracles — proved equal to full recompute


class TestIncrementalOracles:
    @pytest.mark.parametrize("n_new,seed", [(1, 21), (7, 22),
                                            (40, 23)])
    def test_sssp_incremental_equals_full(self, g, n_new, seed):
        src, dst = _mutations(g.nv, n_new, seed)
        g_new = g.with_edges(src, dst)
        d0 = sssp.reference_sssp(g, 0)
        inc = sssp.reference_sssp_incremental(g_new, d0, src, dst)
        np.testing.assert_array_equal(inc,
                                      sssp.reference_sssp(g_new, 0))

    @pytest.mark.parametrize("n_new,seed", [(3, 31), (25, 32)])
    def test_sssp_weighted_incremental_equals_full(self, gw, n_new,
                                                   seed):
        src, dst = _mutations(gw.nv, n_new, seed)
        rng = np.random.default_rng(seed)
        w = rng.uniform(0.5, 4.0, size=n_new).astype(np.float32)
        g_new = gw.with_edges(src, dst, w)
        d0 = sssp.reference_sssp(gw, 0, weighted=True)
        inc = sssp.reference_sssp_incremental(
            g_new, d0, src, dst, new_w=w, weighted=True)
        np.testing.assert_array_equal(
            inc, sssp.reference_sssp(g_new, 0, weighted=True))

    def test_weighted_incremental_requires_new_w(self, gw):
        """A silently one-weighted append would seed BELOW the true
        fixed point — unrepairable by monotone propagation, so the
        oracle refuses (the Graph.with_edges contract)."""
        g_new = gw.with_edges([1], [2], [2.0])
        d0 = sssp.reference_sssp(gw, 0, weighted=True)
        with pytest.raises(ValueError, match="new_w"):
            sssp.reference_sssp_incremental(g_new, d0, [1], [2],
                                            weighted=True)

    @pytest.mark.parametrize("n_new,seed", [(1, 41), (7, 42),
                                            (40, 43)])
    def test_components_incremental_equals_full(self, g, n_new, seed):
        src, dst = _mutations(g.nv, n_new, seed)
        g_new = g.with_edges(src, dst)
        c0 = components.reference_components(g)
        inc = components.reference_components_incremental(
            g_new, c0, src, dst)
        np.testing.assert_array_equal(
            inc, components.reference_components(g_new))


# ---------------------------------------------------------------------
# device revalidation — proved equal at the same epoch


class TestRevalidate:
    @pytest.mark.parametrize("num_parts", [1, 2])
    def test_sssp_revalidate_bitwise(self, g, num_parts):
        eng = sssp.build_engine(g, 0, num_parts=num_parts)
        lab, act = eng.init_state()
        lab, act, _ = eng.converge(lab, act)
        lg = LiveGraph(g, capacity=32)
        s1, d1 = _mutations(g.nv, 9, 51)
        lg.append_edges(s1, d1)
        lab, act, _ = lg.revalidate(eng, lab, act)
        ref = _clamp_ref(sssp.reference_sssp(lg.graph_at(1), 0))
        np.testing.assert_array_equal(_sssp_host(eng, lab), ref)

    def test_sssp_weighted_revalidate(self, gw):
        import jax
        eng = sssp.build_engine(gw, 0, num_parts=2, weighted=True)
        lab, act = eng.init_state()
        lab, act, _ = eng.converge(lab, act)
        lg = LiveGraph(gw, capacity=32)
        s1, d1 = _mutations(gw.nv, 9, 52)
        rng = np.random.default_rng(52)
        w = rng.uniform(0.5, 4.0, size=9).astype(np.float32)
        lg.append_edges(s1, d1, w)
        lab, act, _ = lg.revalidate(eng, lab, act)
        h = eng.sg.from_padded(np.asarray(jax.device_get(lab)))
        ref = sssp.reference_sssp(lg.graph_at(1), 0, weighted=True)
        reach = np.isfinite(ref)
        np.testing.assert_allclose(h[reach], ref[reach], rtol=1e-5)
        assert not np.isfinite(h[~reach]).any()

    def test_components_revalidate_bitwise(self, g):
        import jax
        eng = components.build_engine(g, num_parts=2)
        lab, act = eng.init_state()
        lab, act, _ = eng.converge(lab, act)
        lg = LiveGraph(g, capacity=32)
        s1, d1 = _mutations(g.nv, 9, 53)
        lg.append_edges(s1, d1)
        lab, act, _ = lg.revalidate(eng, lab, act)
        h = eng.sg.from_padded(np.asarray(jax.device_get(lab)))
        ref = components.reference_components(lg.graph_at(1))
        np.testing.assert_array_equal(h.astype(np.int64), ref)

    def test_batched_per_column_epochs_snapshot_isolated(self, g):
        """Snapshot isolation INSIDE one dispatch: four query columns
        pinned to epochs [0, 1, 2, 2] share one delta-relax + one
        converge, and each lands bitwise on the oracle of ITS OWN
        epoch's graph — the per-column epoch mask is the machine
        proof that a column can never see a later edge."""
        sources = [3, 17, 40, 99]
        eng = sssp.build_engine(g, num_parts=2, sources=sources)
        lab, act = eng.init_state()
        lab, act, _ = eng.converge(lab, act)
        lg = LiveGraph(g, capacity=32)
        s1, d1 = _mutations(g.nv, 8, 54)
        s2, d2 = _mutations(g.nv, 8, 55)
        lg.append_edges(s1, d1)     # epoch 1
        lg.append_edges(s2, d2)     # epoch 2
        col_epoch = np.array([0, 1, 2, 2], np.int32)
        lab, act, _ = lg.revalidate(eng, lab, act,
                                    col_epoch=col_epoch)
        h = _sssp_host(eng, lab)    # [nv, B]
        for q, (s, e) in enumerate(zip(sources, col_epoch)):
            ref = _clamp_ref(sssp.reference_sssp(lg.graph_at(int(e)),
                                                 s))
            np.testing.assert_array_equal(
                h[:, q], ref,
                err_msg=f"column {q} pinned to epoch {e}")

    def test_revalidate_mesh8(self, g):
        from lux_tpu.parallel.mesh import make_mesh
        eng = sssp.build_engine(g, 0, num_parts=8, mesh=make_mesh(8))
        lab, act = eng.init_state()
        lab, act, _ = eng.converge(lab, act)
        lg = LiveGraph(g, capacity=32)
        s1, d1 = _mutations(g.nv, 9, 56)
        lg.append_edges(s1, d1)
        lab, act, _ = lg.revalidate(eng, lab, act)
        ref = _clamp_ref(sssp.reference_sssp(lg.graph_at(1), 0))
        np.testing.assert_array_equal(_sssp_host(eng, lab), ref)

    def test_delta_step_rejects_pull_programs(self, g):
        from lux_tpu.apps import pagerank
        eng = pagerank.build_engine(g, num_parts=2)
        lg = LiveGraph(g, capacity=8)
        with pytest.raises(ValueError, match="monotone"):
            lg.delta_step(eng)

    def test_dead_cache_entries_evicted(self, g):
        """REGRESSION: the id()-keyed geometry/engine caches validate
        hits by weakref identity but never dropped dead entries —
        every refresh_live rebuilds engines at fresh addresses, so a
        long-lived server leaked an O(nv) slot map and a compiled
        step per retired generation.  A miss now sweeps dead
        referents, bounding each cache at the live engines."""
        import gc
        lg = LiveGraph(g, capacity=16)
        lg.append_edges([1, 2], [3, 4])
        for _ in range(4):
            eng = sssp.build_engine(g, 0, num_parts=2)
            lab, act = eng.init_state()
            lab, act, _ = eng.converge(lab, act)
            lg.revalidate(eng, lab, act)
            del eng, lab, act
            gc.collect()
        assert len(lg._vslot_cache) <= 1
        assert len(lg._slot_cache) <= 1
        assert len(lg._step_cache) <= 1

    def test_delta_step_audits_clean(self, g):
        """The delta-relax step holds the engines' own gather budget
        (ONE state-table gather) under the repo auditor — the same
        machine check the three ``*_live_*`` matrix configs run
        repo-wide in tests/test_audit.py."""
        from lux_tpu import audit
        eng = sssp.build_engine(g, 0, num_parts=2)
        lg = LiveGraph(g, capacity=16)
        lg.append_edges([1, 2], [3, 4])
        lg.register_audit(eng)
        assert audit.audit_engine(eng, mode=None) == []


# ---------------------------------------------------------------------
# serving: epoch pinning, the answer cache, backpressure


class TestServeLive:
    def _server(self, g, lg, **kw):
        from lux_tpu import serve
        kw.setdefault("batch", 2)
        kw.setdefault("num_parts", 2)
        kw.setdefault("seg_iters", 4)
        return serve.Server(g, live=lg, **kw)

    def test_mixed_epochs_in_one_drain(self, g):
        """Queries admitted at DIFFERENT epochs share one drain (one
        batched dispatch) and each answers bitwise at its own
        admission epoch — the serving-layer snapshot-isolation
        proof."""
        lg = LiveGraph(g, capacity=32)
        srv = self._server(g, lg, batch=4)
        srv.submit("sssp", source=3)
        srv.submit("components", source=17)
        s1, d1 = _mutations(g.nv, 10, 61)
        srv.mutate(s1, d1)
        srv.submit("sssp", source=3)        # same source, NEW epoch
        srv.submit("components", source=17)
        responses = srv.run()
        assert len(responses) == 4
        epochs = sorted(r.epoch for r in responses)
        assert epochs == [0, 0, 1, 1]
        assert check_live_answers(lg, responses) == 0
        # the two sssp answers genuinely differ across the epochs or
        # the isolation claim is vacuous for this seed
        a = {(r.kind, r.epoch): r.answer for r in responses}
        assert not np.array_equal(a[("sssp", 0)], a[("sssp", 1)]) \
            or not np.array_equal(a[("components", 0)],
                                  a[("components", 1)])

    def test_cache_hits_same_epoch_invalidated_on_advance(self, g):
        lg = LiveGraph(g, capacity=32)
        srv = self._server(g, lg, cache=True)
        srv.submit("sssp", source=7)
        r1 = srv.run()
        srv.submit("sssp", source=7)        # same epoch: HIT
        r2 = srv.run()
        assert [r.cached for r in r2] == [True]
        assert r2[0].segments == 0
        np.testing.assert_array_equal(r1[0].answer, r2[0].answer)
        assert srv.cache.hits == 1
        s1, d1 = _mutations(g.nv, 5, 62)
        srv.mutate(s1, d1)
        srv.submit("sssp", source=7)        # new epoch: MISS
        r3 = srv.run()
        assert not r3[0].cached and r3[0].epoch == 1
        assert check_live_answers(lg, r1 + r2 + r3) == 0
        # the epoch-0 entries were swept on the advance (no view
        # exposes epoch 0 anymore)
        assert all(k[2] != 0 for k in srv.cache._d)

    def test_cache_byte_budget_binds_on_big_answers(self):
        """REGRESSION: an entry-count cap alone scales cache memory
        with GRAPH SIZE (each entry copies a full nv-length answer) —
        the byte budget must evict LRU before the count cap on big
        answers, and the ledger must stay exact across replace/
        expire/sweep."""
        from lux_tpu.serve import AnswerCache, Request
        cache = AnswerCache(max_entries=64, max_bytes=4096)
        ans = np.zeros(256, np.int32)           # 1024 B each
        for s in range(8):
            cache.put("sssp", Request(qid=s, kind="sssp", source=s,
                                      t_enqueue=0.0, epoch=0),
                      ans, 1, 0, 0.0)
        assert len(cache._d) == 4               # 4 x 1024 = budget
        assert cache.bytes == 4096
        # LRU: the oldest sources were evicted, the newest retained
        hit = cache.get("sssp", Request(qid=9, kind="sssp", source=7,
                                        t_enqueue=0.0, epoch=0), 0.0)
        assert hit is not None
        miss = cache.get("sssp", Request(qid=10, kind="sssp",
                                         source=0, t_enqueue=0.0,
                                         epoch=0), 0.0)
        assert miss is None
        # replacing a key must not double-count its bytes
        cache.put("sssp", Request(qid=11, kind="sssp", source=7,
                                  t_enqueue=0.0, epoch=0),
                  ans, 1, 0, 0.0)
        assert cache.bytes == 4096 and len(cache._d) == 4
        # true LRU, not FIFO: a hit renews recency, so the hot
        # oldest-inserted entry survives the next eviction and the
        # cold one goes instead
        assert cache.get("sssp", Request(qid=12, kind="sssp",
                                         source=4, t_enqueue=0.0,
                                         epoch=0), 0.0) is not None
        cache.put("sssp", Request(qid=13, kind="sssp", source=8,
                                  t_enqueue=0.0, epoch=0),
                  ans, 1, 0, 0.0)
        assert cache.get("sssp", Request(qid=14, kind="sssp",
                                         source=4, t_enqueue=0.0,
                                         epoch=0), 0.0) is not None
        assert cache.get("sssp", Request(qid=15, kind="sssp",
                                         source=5, t_enqueue=0.0,
                                         epoch=0), 0.0) is None
        # sweep keeps the ledger exact
        cache.sweep({"sssp": 1})
        assert len(cache._d) == 0 and cache.bytes == 0

    def test_stale_epoch_hit_is_a_test_failure(self, g):
        """A stale-epoch hit is impossible BY KEY; this pins the
        oracle harness that would catch the bug class anyway: poison
        the cache with an old-epoch answer under the new epoch's key
        and the per-epoch oracle check MUST flag the served
        response."""
        lg = LiveGraph(g, capacity=32)
        srv = self._server(g, lg, cache=True)
        srv.submit("sssp", source=3)
        (r0,) = srv.run()
        # mutate so the epoch-1 answer for source 3 changes
        rng = np.random.default_rng(63)
        while True:
            s1, d1 = rng.integers(g.nv, size=6), rng.integers(
                g.nv, size=6)
            if not np.array_equal(
                    _clamp_ref(sssp.reference_sssp(
                        g.with_edges(s1, d1), 3)),
                    _clamp_ref(sssp.reference_sssp(g, 3))):
                break
        srv.mutate(s1, d1)
        # POISON: the epoch-0 answer filed under the epoch-1 key —
        # exactly what a buggy cache would serve
        from lux_tpu.serve import Request
        fake = Request(qid=-1, kind="sssp", source=3, t_enqueue=0.0,
                       epoch=1)
        srv.cache.put("sssp", fake, r0.answer, r0.iters, 1, 0.0)
        srv.submit("sssp", source=3)
        (r1,) = srv.run()
        assert r1.cached    # the poisoned entry served
        assert check_live_answers(lg, [r1]) == 1, \
            "the oracle harness failed to flag a stale-epoch answer"

    def test_pagerank_advances_epochs_without_fold(self, g):
        """Round 21 (pull-kind incremental revalidation): appends
        advance the PULL admission epoch with NO compaction — the
        engine normalizes by effective degree (the deg_corr program
        array) and the drain hook adds the delta appends' rank mass
        per column's admission epoch, together one exact PPR
        iteration over graph_at(epoch)."""
        lg = LiveGraph(g, capacity=32)
        srv = self._server(g, lg)
        s1, d1 = _mutations(g.nv, 6, 64)
        srv.mutate(s1, d1)
        srv.submit("pagerank", source=5)
        (r,) = srv.run()
        assert r.epoch == 1 and lg.compactions == 0
        assert check_live_answers(lg, [r]) == 0
        # a DELETION caps pull admission below its epoch — the host
        # correction is append-linear and cannot express an
        # anti-monotone op
        ds, dd = _live_edge(g, 3)
        srv.mutate([ds], [dd], op="delete")
        srv.submit("pagerank", source=5)
        (r2,) = srv.run()
        assert r2.epoch == 1
        assert check_live_answers(lg, [r2]) == 0
        # the fold + adoption advances past the deletion
        lg.compact(force=True)
        srv.refresh_live()
        srv.submit("pagerank", source=5)
        (r3,) = srv.run()
        assert r3.epoch == 2
        assert check_live_answers(lg, [r3]) == 0

    def test_refresh_live_guards_and_delta_full(self, g):
        lg = LiveGraph(g, capacity=4)
        srv = self._server(g, lg)
        srv.submit("sssp", source=1)
        lg.append_edges([1], [2])
        # the queued query pinned epoch 0 >= base_epoch 0: the delta
        # mask replays it, so adoption must NOT refuse (the old
        # latest-epoch comparison wrongly raised here)
        srv.refresh_live()
        # the defensive arm: an epoch below base_epoch really is
        # irreproducible (an invariant breach — live compaction is
        # ledger-guarded against folding under an admitted query)
        req = srv._collector("sssp").pending_requests()[0]
        req.epoch = -1
        with pytest.raises(RuntimeError, match="reproduce"):
            srv.refresh_live()
        req.epoch = 0
        srv.run()
        with pytest.raises(DeltaFullError):
            srv.mutate(*_mutations(g.nv, 5, 65))
        lg.compact(force=True)
        srv.refresh_live()
        assert srv.g is lg.base
        srv.submit("sssp", source=1)
        (r,) = srv.run()
        assert check_live_answers(lg, [r]) == 0

    def test_run_refuses_stale_generation_then_unwedges(self, g):
        """Generation adoption is ENFORCED: serving on a stale base
        after a compaction would converge old-base + empty delta — a
        wrong answer whose answer_epoch equals its admission epoch.
        run() refuses typed; a query submitted between compact and
        refresh_live re-stamps to the same epoch on the new
        generation, so adoption unwedges it."""
        lg = LiveGraph(g, capacity=8)
        srv = self._server(g, lg)
        lg.append_edges([1], [2])
        srv.submit("sssp", source=1)
        srv.run()
        lg.compact(force=True)
        srv.submit("sssp", source=2)
        with pytest.raises(RuntimeError, match="refresh_live"):
            srv.run()
        srv.refresh_live()
        (r,) = srv.run()
        assert r.epoch == 1
        assert check_live_answers(lg, [r]) == 0

    def test_ingest_between_compact_and_refresh_not_wedged(self, g):
        """REGRESSION: a mutation landing between compact() and
        refresh_live() while a reproducible push query sat queued
        wedged the server three ways — refresh_live refused on a
        false epoch mismatch (it compared against the LATEST view
        epoch, not reproducibility), run() refused on the stale
        base, and compact() refused on the admission ledger, with no
        recovery path.  The query pinned the NEW base_epoch, which
        the per-column delta mask replays exactly; adoption must
        proceed and serve it oracle-correct at its admission
        epoch."""
        lg = LiveGraph(g, capacity=32)
        srv = self._server(g, lg)
        lg.append_edges([1], [2])
        lg.compact(force=True)              # base_epoch -> 1
        srv.submit("sssp", source=3)        # admitted at epoch 1
        s1, d1 = _mutations(g.nv, 6, 91)
        srv.mutate(s1, d1)                  # epoch -> 2
        srv.refresh_live()                  # must NOT raise
        (r,) = srv.run()
        assert r.epoch == 1
        assert check_live_answers(lg, [r]) == 0
        # a query admitted after the ingest serves at the new epoch
        srv.submit("sssp", source=3)
        (r2,) = srv.run()
        assert r2.epoch == 2
        assert check_live_answers(lg, [r2]) == 0

    def test_compact_refuses_admitted_queued_queries(self, g):
        """An admitted-but-QUEUED query already pinned its epoch at
        submit; compacting before it reaches a column would fold the
        delta out from under the old-base engines it will be served
        on — a wrong answer with answer_epoch == admission epoch,
        structurally invisible to the torn-epoch audit.  The
        admission ledger makes compact refuse typed instead."""
        lg = LiveGraph(g, capacity=8)
        srv = self._server(g, lg)
        lg.append_edges([1], [2])
        srv.submit("sssp", source=1)
        with pytest.raises(CompactPinnedError, match="admitted"):
            lg.compact(force=True)
        (r,) = srv.run()
        assert check_live_answers(lg, [r]) == 0
        # drained: the release at retirement re-arms compaction
        assert lg.compact(force=True) == 1

    def test_server_requires_live_base(self, g):
        lg = LiveGraph(g, capacity=4)
        other = g.with_edges([1], [2])
        with pytest.raises(ValueError, match="live.base"):
            self._server(other, lg)

    def test_drag_samples_feed_scheduler_economics(self, g):
        """The serve runners fence-time every Nth delta boundary and
        feed it to the live graph (round 21) — after a few live
        drains the scheduler's economics run on MEASURED drag, not
        the scalemodel term."""
        lg = LiveGraph(g, capacity=64)
        srv = self._server(g, lg, batch=4)
        s1, d1 = _mutations(g.nv, 10, 71)
        srv.mutate(s1, d1)
        for q in range(4):
            srv.submit("sssp", source=q + 1)
        responses = srv.run()
        assert check_live_answers(lg, responses) == 0
        assert len(lg._drag_samples) >= 1
        eco = lg.compact_economics()
        assert eco["drag_source"] == "measured"
        assert eco["drag_samples"] >= 1
        assert eco["delta_drag_ns_per_boundary"] > 0

    def test_mutate_routes_the_algebra(self, g, gw):
        """Server.mutate is the single ingest door for all three
        ops; an unknown op refuses typed."""
        lg = LiveGraph(g, capacity=16)
        srv = self._server(g, lg)
        es, ed = _live_edge(g, 2)
        srv.mutate([es], [ed], op="delete")
        assert lg.deletions == 1 and lg.epoch == 1
        with pytest.raises(ValueError, match="unknown mutation op"):
            srv.mutate([1], [2], op="merge")
        lgw = LiveGraph(gw, capacity=16)
        srvw = self._server(gw, lgw, weighted=True)
        rs, rd = _live_edge(gw, 4)
        srvw.mutate([rs], [rd], weights=[1.25], op="reweight")
        assert lgw.reweights == 1
        # the admission cap is live through the serving door too
        assert lgw.view_epoch("push") == 0
        srvw.submit("sssp", source=3)
        (r,) = srvw.run()
        assert r.epoch == 0
        assert check_live_answers(lgw, [r], weighted=True) == 0


class TestFleetLive:
    def _fleet(self, g, lg, tmp_path, **kw):
        from lux_tpu import fleet, resilience
        kw.setdefault("replicas", 2)
        kw.setdefault("batch", 2)
        kw.setdefault("num_parts", 2)
        kw.setdefault("retry",
                      resilience.RetryPolicy(retries=3,
                                             backoff_s=0.01,
                                             max_backoff_s=0.05,
                                             jitter_seed=0))
        kw.setdefault("board_path", str(tmp_path / "board"))
        return fleet.FleetServer(g, live=lg, **kw)

    def test_failover_answers_at_original_admission_epoch(
            self, g, tmp_path):
        """THE fleet-failover satellite: queries admitted at epoch e,
        the serving replica killed mid-drain, MORE mutations land
        after admission — the re-dispatched queries still answer at
        epoch e, bitwise (integer apps), never at the later epoch."""
        from lux_tpu import fleet
        lg = LiveGraph(g, capacity=64)
        flt = self._fleet(g, lg, tmp_path)
        flt.warm(["sssp", "components"])
        s1, d1 = _mutations(g.nv, 10, 71)
        flt.mutate(s1, d1)                  # epoch 1
        specs = [("sssp", s) for s in (3, 17, 40)] \
            + [("components", s) for s in (7, 50, 120)]
        qids = {}
        for kind, s in specs:
            qids[flt.submit(kind, source=s)] = (kind, s)
        # mutations land AFTER admission: epoch moves to 2, but the
        # in-flight queries stay pinned to 1
        s2, d2 = _mutations(g.nv, 10, 72)
        flt.mutate(s2, d2)
        flt.set_fault(faults.ReplicaKillPlan({"r1": 1}))
        rs = flt.run()
        assert len(rs) == len(specs) and flt.failovers >= 1
        assert all(r.epoch == 1 for r in rs)
        assert check_live_answers(lg, rs) == 0
        # bitwise vs a fault-free fleet serving the SAME epoch
        lg2 = LiveGraph(g, capacity=64)
        lg2.append_edges(s1, d1)
        flt2 = self._fleet(g, lg2, tmp_path)
        want = {}
        for kind, s in specs:
            want[flt2.submit(kind, source=s)] = (kind, s)
        rs2 = flt2.run()
        by_spec = {qids[r.qid]: r.answer for r in rs}
        by_spec2 = {want[r.qid]: r.answer for r in rs2}
        for spec in by_spec:
            np.testing.assert_array_equal(by_spec[spec],
                                          by_spec2[spec])

    def test_fleet_ingest_between_compact_and_refresh(self, g,
                                                      tmp_path):
        """REGRESSION (serve.Server's wedge, fleet leg): a mutation
        between compact() and refresh_live() with a reproducible
        push query centrally queued must not wedge the fleet — the
        query pinned the new base_epoch, which the delta mask
        replays."""
        lg = LiveGraph(g, capacity=64)
        flt = self._fleet(g, lg, tmp_path)
        flt.warm(["sssp"])
        lg.append_edges([1], [2])
        lg.compact(force=True)              # base_epoch -> 1
        flt.submit("sssp", source=3)        # admitted at epoch 1
        s1, d1 = _mutations(g.nv, 6, 92)
        flt.mutate(s1, d1)                  # epoch -> 2
        flt.refresh_live()                  # must NOT raise
        rs = flt.run()
        assert len(rs) == 1 and rs[0].epoch == 1
        assert check_live_answers(lg, rs) == 0

    def test_live_fleet_refuses_subprocess_replicas(self, g,
                                                    tmp_path):
        """A subprocess replica serves the static graph spec — in a
        live fleet its answers would wear epoch=None and evade the
        torn-epoch audit, so the spawn is a typed refusal."""
        lg = LiveGraph(g, capacity=8)
        flt = self._fleet(g, lg, tmp_path)
        with pytest.raises(ValueError, match="admission epoch"):
            flt.add_subprocess_replica({"kind": "rmat", "scale": 5})

    def test_cached_hits_skip_service_histogram(self, g, tmp_path):
        """REGRESSION: cache hits retire in ~0s without touching an
        engine; feeding them into fleet_service_seconds dragged down
        the mean the deadline-admission projection divides by, so
        tight-deadline queries that would really wait a full drain
        were admitted instead of shed typed."""
        lg = LiveGraph(g, capacity=32)
        flt = self._fleet(g, lg, tmp_path, cache=True)
        flt.submit("sssp", source=3)
        rs = flt.run()
        assert len(rs) == 1 and not rs[0].cached
        h = flt.metrics.histogram("fleet_service_seconds",
                                  kind="sssp")
        assert h.count == 1
        flt.submit("sssp", source=3)        # same key, same epoch
        rs2 = flt.run()
        assert len(rs2) == 1 and rs2[0].cached
        # the cached retirement must NOT add a ~0s sample
        assert h.count == 1

    def test_delta_full_sheds_typed(self, g, tmp_path):
        from lux_tpu import fleet
        ev = telemetry.EventLog()
        lg = LiveGraph(g, capacity=4)
        with telemetry.use(events=ev):
            flt = self._fleet(g, lg, tmp_path)
            with pytest.raises(fleet.AdmissionError) as ei:
                flt.mutate(*_mutations(g.nv, 6, 73))
            assert ei.value.reason == fleet.SHED_DELTA_FULL
            assert ei.value.qid in {e.qid for e in flt.shed_records}
        sheds = [e for e in ev.events if e["kind"] == "query_shed"]
        assert sheds and sheds[0]["reason"] == "delta_full"


# ---------------------------------------------------------------------
# round 21: the mutation algebra — v2 WAL records, version compat


class TestMutationAlgebraLog:
    def test_wal_v2_roundtrip_bitwise(self, gw, tmp_path):
        """Deletes + reweights journal as v2 records and recover
        BITWISE — including the d_kind block, the op counters, and
        the pending-anti admission cap."""
        wal = str(tmp_path / "g.lux.wal")
        lg = LiveGraph(gw, capacity=64, wal_path=wal)
        s1, d1 = _mutations(gw.nv, 5, 1)
        rng = np.random.default_rng(7)
        lg.append_edges(s1, d1,
                        rng.uniform(0.5, 4.0, 5).astype(np.float32))
        es, ed = _live_edge(gw, 3)
        lg.delete_edges([es], [ed])
        rs, rd = _live_edge(gw, 10)
        lg.reweight_edges([rs], [rd], [2.25])
        assert lg.deletions == 1 and lg.reweights == 1
        assert lg.anti_pending() == 2
        want = _wal_state(lg)
        lg.close()
        lg2 = LiveGraph.recover(gw, wal)
        _assert_state_equal(_wal_state(lg2), want)
        # recovery restores the ANTI ledger: admission stays capped
        # below the earliest pending deletion for BOTH families
        assert lg2.anti_pending() == 2
        assert lg2.view_epoch("push") == 1
        assert lg2.view_epoch("pull") == 1
        # the oracle surfaces agree bitwise at every epoch
        for e in range(lg2.epoch + 1):
            a, b = lg.graph_at(e), lg2.graph_at(e)
            np.testing.assert_array_equal(a.col_idx, b.col_idx)
            np.testing.assert_array_equal(a.weights, b.weights)
        lg2.close()

    def test_wal_v1_replays_bitwise_under_v2_reader(self, g,
                                                    tmp_path):
        """Version compat: a v1 (round-20, append-only) log replays
        bitwise under the round-21 reader, the recovered log RESUMES
        at the HEADER's version, and the v2 kinds refuse typed
        against it — never silently journaling a record a v1 reader
        would reject as corruption."""
        wal = str(tmp_path / "g.lux.wal")
        log = MutationLog(wal, g.nv, 16, version=1)
        log.append_edge(1, 1, 2, 0)
        log.append_edge(2, 3, 4, 0)
        log.close()
        assert luxfmt.read_wal_header(wal, nv=g.nv)[2] == 1
        lg = LiveGraph.recover(g, wal)
        assert lg.count == 2 and lg.epoch == 2
        np.testing.assert_array_equal(lg.d_src[:2], [1, 3])
        np.testing.assert_array_equal(lg.d_kind[:2], [0, 0])
        assert lg.anti_pending() == 0
        # appends keep chaining onto the resumed v1 log ...
        lg.append_edges([5], [6])
        assert lg.epoch == 3
        # ... but the v2 mutation kinds refuse typed (the kind set
        # is part of the header version's contract)
        with pytest.raises(MutationLogError) as ei:
            lg.delete_edges([1], [2])
        assert ei.value.check == "record_kind"
        # the refusal journaled NOTHING: state unchanged, replayable
        assert lg.epoch == 3 and lg.deletions == 0
        lg.close()
        lg2 = LiveGraph.recover(g, wal)
        assert lg2.count == 3 and lg2.epoch == 3
        lg2.close()

    def test_v2_kind_inside_v1_header_is_corruption(self, g,
                                                    tmp_path):
        """A DELETE record inside a v1-headed log at rest is typed
        record_kind corruption — scan enforces the header version's
        kind set, so a v1 reader and the v2 reader agree the file is
        bad rather than disagreeing on its meaning."""
        from lux_tpu.livegraph import REC_DELETE, _pack_record
        wal = str(tmp_path / "g.lux.wal")
        log = MutationLog(wal, g.nv, 16, version=1)
        log.append_edge(1, 1, 2, 0)
        log._append(_pack_record(2, REC_DELETE, 1, 2, 0, log._crc))
        log.close()
        with pytest.raises(MutationLogError) as ei:
            MutationLog.scan(wal)
        assert ei.value.check == "record_kind"

    @pytest.mark.parametrize("op", ["delete", "reweight"])
    def test_torn_tail_and_rot_per_new_kind(self, gw, tmp_path, op):
        """Per new record kind: a torn tail is recoverable (strict
        prefix, truncated deterministically), a FULL-SIZE bad-CRC
        final record is hard corruption — same taxonomy as the
        round-20 append records."""
        wal = str(tmp_path / "g.lux.wal")
        lg = LiveGraph(gw, capacity=16, wal_path=wal)
        s0, d0 = _live_edge(gw, 2)
        if op == "delete":
            lg.delete_edges([s0], [d0])
        else:
            lg.reweight_edges([s0], [d0], [3.5])
        want = _wal_state(lg)
        lg.close()
        good = open(wal, "rb").read()
        faults.tear_wal(wal, keep_bytes=9)
        recs, _nv, _cap, torn = MutationLog.scan(wal, nv=gw.nv)
        assert len(recs) == 1 and torn == 9
        lg2 = LiveGraph.recover(gw, wal)
        _assert_state_equal(_wal_state(lg2), want)
        assert lg2.anti_pending() == 1
        lg2.close()
        # full-size rot INSIDE the mutation record: typed crc_chain
        blob = bytearray(good)
        blob[-10] ^= 0xFF
        open(wal, "wb").write(bytes(blob))
        with pytest.raises(MutationLogError) as ei:
            MutationLog.scan(wal)
        assert ei.value.check == "crc_chain"

    @pytest.mark.parametrize("action,op", [
        (faults.MUT_DELETE, "delete"),
        (faults.MUT_REWEIGHT, "reweight")])
    def test_mut_delete_reweight_crash_legs(self, gw, tmp_path,
                                            action, op):
        """The op-asserting crash legs: the injected crash lands
        BEFORE the WAL record — recovery is bitwise the pre-batch
        state with the anti ledger intact — and a plan written
        against the wrong op refuses loudly instead of drilling a
        different stream than intended."""
        wal = str(tmp_path / "g.lux.wal")
        plan = faults.MutationFaultPlan(schedule={1: action})
        lg = LiveGraph(gw, capacity=16, wal_path=wal, fault=plan)
        lg.append_edges([1], [2], [1.0])
        want = _wal_state(lg)
        s0, d0 = _live_edge(gw, 4)
        with pytest.raises(faults.InjectedWorkerCrash):
            if op == "delete":
                lg.delete_edges([s0], [d0])
            else:
                lg.reweight_edges([s0], [d0], [2.0])
        assert plan.fired == [(1, action)]
        assert lg.anti_pending() == 0
        lg.close()
        lg2 = LiveGraph.recover(gw, wal)
        _assert_state_equal(_wal_state(lg2), want)
        lg2.close()
        # the op-assert arm: an append firing where the plan
        # scheduled a delete/reweight crash is a drill-script bug
        plan2 = faults.MutationFaultPlan(schedule={0: action})
        lg3 = LiveGraph(gw, capacity=16, fault=plan2)
        with pytest.raises(ValueError, match="expects"):
            lg3.append_edges([3], [4], [1.0])

    def test_fsck_reports_v2_mutation_mix(self, gw, g, tmp_path):
        """scripts/fsck_lux.py renders the v2 mutation mix; a v1 log
        reports its version with no phantom algebra counters."""
        wal = str(tmp_path / "g.lux.wal")
        lg = LiveGraph(gw, capacity=16, wal_path=wal)
        lg.append_edges([1], [2], [1.5])
        ds, dd = _live_edge(gw, 0)
        lg.delete_edges([ds], [dd])
        rs, rd = _live_edge(gw, 5)
        lg.reweight_edges([rs], [rd], [0.75])
        lg.close()
        r = subprocess.run([sys.executable, str(FSCK), wal],
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        assert "OK wal v2" in r.stdout
        assert "deletes=1 reweights=1" in r.stdout
        wal1 = str(tmp_path / "v1.lux.wal")
        log = MutationLog(wal1, g.nv, 8, version=1)
        log.append_edge(1, 1, 2, 0)
        log.close()
        r = subprocess.run([sys.executable, str(FSCK), wal1],
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        assert "OK wal v1" in r.stdout
        assert "deletes=" not in r.stdout


# ---------------------------------------------------------------------
# round 21: deletions / reweights on the live graph


class TestMutationAlgebraLive:
    def test_delete_validation_typed(self, g, gw):
        lg = LiveGraph(g, capacity=8)
        ps, pd = _phantom_edge(g)
        with pytest.raises(ValueError, match="live edge"):
            lg.delete_edges([ps], [pd])
        assert lg.epoch == 0 and lg.count == 0
        # deletions CONSUME multiplicity: a batch deleting one edge
        # more often than it lives refuses whole
        us, ud = _live_edge(g, 0)
        k = int(np.sum((g.edge_arrays()[0] == us)
                       & (g.edge_arrays()[1] == ud)))
        with pytest.raises(ValueError, match="live edge"):
            lg.delete_edges([us] * (k + 1), [ud] * (k + 1))
        assert lg.epoch == 0
        # reweight on an unweighted base refuses before journaling
        with pytest.raises(ValueError, match="UNWEIGHTED"):
            lg.reweight_edges([us], [ud], [2.0])
        lw = LiveGraph(gw, capacity=8)
        with pytest.raises(ValueError, match="weights"):
            lw.reweight_edges([us], [ud], None)

    def test_tombstones_consume_delta_capacity(self, g):
        lg = LiveGraph(g, capacity=2)
        s0, d0 = _live_edge(g, 0)
        s1, d1 = _live_edge(g, 1)
        lg.delete_edges([s0], [d0])
        lg.append_edges([1], [2])
        with pytest.raises(DeltaFullError):
            lg.delete_edges([s1], [d1])
        assert lg.epoch == 2 and lg.count == 2

    def test_graph_at_and_compact_fold_deletions(self, gw,
                                                 tmp_path):
        """The deterministic fold: graph_at (the oracle surface),
        compact (the live base), and recover (the crash path) all
        run _apply_ops, so all three agree bitwise on which edge a
        deletion tombstones and which a reweight restates."""
        wal = str(tmp_path / "g.lux.wal")
        lg = LiveGraph(gw, capacity=16, wal_path=wal)
        s0, d0 = _live_edge(gw, 6)
        lg.delete_edges([s0], [d0])                 # epoch 1
        # a MULTIPLICITY-1 edge, so the restatement is unambiguous
        # (duplicate pairs would leave "which instance" to the
        # deterministic targeting rule, fine for the fold-equality
        # checks below but not for a direct weight assertion)
        sa, da = gw.edge_arrays()
        uniq = next(i for i in range(gw.ne)
                    if (sa[i], da[i]) != (s0, d0)
                    and np.sum((sa == sa[i]) & (da == da[i])) == 1)
        rs, rd = int(sa[uniq]), int(da[uniq])
        lg.reweight_edges([rs], [rd], [9.0])        # epoch 2
        g1, g2 = lg.graph_at(1), lg.graph_at(2)
        assert g1.ne == gw.ne - 1 and g2.ne == gw.ne - 1
        s2, d2 = g2.edge_arrays()
        m = (s2 == rs) & (d2 == rd)
        assert m.sum() == 1
        assert np.isclose(float(np.asarray(g2.weights)[m][0]), 9.0)
        gen = lg.compact(force=True)
        assert gen == 1 and lg.anti_pending() == 0
        np.testing.assert_array_equal(lg.base.col_idx, g2.col_idx)
        np.testing.assert_array_equal(lg.base.weights, g2.weights)
        assert lg.view_epoch("push") == 2
        lg.close()
        lg2 = LiveGraph.recover(gw, wal)
        assert lg2.generation == 1 and lg2.anti_pending() == 0
        np.testing.assert_array_equal(lg2.base.col_idx,
                                      lg.base.col_idx)
        np.testing.assert_array_equal(lg2.base.weights,
                                      lg.base.weights)
        lg2.close()

    def test_view_epoch_caps_both_families_until_fold(self, g):
        lg = LiveGraph(g, capacity=16)
        lg.append_edges([1], [2])                   # epoch 1
        s0, d0 = _live_edge(g, 0)
        lg.delete_edges([s0], [d0])                 # epoch 2 (anti)
        lg.append_edges([3], [4])                   # epoch 3
        for fam in ("push", "pull"):
            assert lg.view_epoch(fam) == 1
        lg.compact(force=True)
        for fam in ("push", "pull"):
            assert lg.view_epoch(fam) == 3


# ---------------------------------------------------------------------
# round 21: decremental oracles — proved equal to full recompute


class TestDecrementalOracles:
    @pytest.mark.parametrize("n_del,seed", [(1, 24), (5, 25),
                                            (40, 26)])
    def test_sssp_decremental_equals_full(self, g, n_del, seed):
        rng = np.random.default_rng(seed)
        src, dst = g.edge_arrays()
        idx = rng.choice(g.ne, size=n_del, replace=False)
        keep = np.ones(g.ne, bool)
        keep[idx] = False
        g_new = Graph.from_edges(src[keep], dst[keep], g.nv)
        d0 = sssp.reference_sssp(g, 0)
        dec = sssp.reference_sssp_decremental(g_new, d0, dst[idx])
        np.testing.assert_array_equal(
            _clamp_ref(dec), _clamp_ref(sssp.reference_sssp(g_new,
                                                            0)))

    @pytest.mark.parametrize("n_mut,seed", [(3, 34), (25, 35)])
    def test_sssp_weighted_reweight_equals_full(self, gw, n_mut,
                                                seed):
        """Weight updates in BOTH directions (increases degrade the
        fixed point, decreases improve it) repair to exactly the
        full recompute through the same cone rule."""
        rng = np.random.default_rng(seed)
        src, dst = gw.edge_arrays()
        idx = rng.choice(gw.ne, size=n_mut, replace=False)
        w_new = np.asarray(gw.weights).copy()
        w_new[idx] = rng.uniform(0.25, 8.0,
                                 size=n_mut).astype(np.float32)
        g_new = Graph.from_edges(src, dst, gw.nv, weights=w_new)
        d0 = sssp.reference_sssp(gw, 0, weighted=True)
        dec = sssp.reference_sssp_decremental(
            g_new, d0, dst[idx], weighted=True)
        np.testing.assert_allclose(
            dec, sssp.reference_sssp(g_new, 0, weighted=True),
            rtol=1e-6)

    @pytest.mark.parametrize("n_del,seed", [(1, 44), (7, 45),
                                            (40, 46)])
    def test_components_decremental_equals_full(self, g, n_del,
                                                seed):
        rng = np.random.default_rng(seed)
        src, dst = g.edge_arrays()
        idx = rng.choice(g.ne, size=n_del, replace=False)
        keep = np.ones(g.ne, bool)
        keep[idx] = False
        g_new = Graph.from_edges(src[keep], dst[keep], g.nv)
        c0 = components.reference_components(g)
        dec = components.reference_components_decremental(
            g_new, c0, dst[idx])
        np.testing.assert_array_equal(
            dec, components.reference_components(g_new))


# ---------------------------------------------------------------------
# round 21: the anti-monotone re-seed on device


class TestReseed:
    def _deleted(self, lg, g, rng, n):
        """Delete n distinct base edges; returns their dst."""
        src, dst = g.edge_arrays()
        idx = rng.choice(g.ne, size=n, replace=False)
        lg.delete_edges(src[idx], dst[idx])
        return dst[idx]

    def test_sssp_deletion_reseed_bitwise(self, g):
        """Converged push state + deletions -> revalidate dispatches
        to the cone re-seed and lands BITWISE on full recompute at
        the target epoch (== the decremental oracle)."""
        import jax
        eng0 = sssp.build_engine(g, 0, num_parts=2)
        lab, act = eng0.init_state()
        lab, act, _ = eng0.converge(lab, act)
        dist0 = eng0.sg.from_padded(np.asarray(jax.device_get(lab)))
        lg = LiveGraph(g, capacity=32)
        rng = np.random.default_rng(57)
        touched = self._deleted(lg, g, rng, 5)
        g_new = lg.graph_at(lg.epoch)
        # CONTRACT: the re-seed engine is built over graph_at(target)
        eng = sssp.build_engine(g_new, 0, num_parts=2)
        lab1, act1 = eng.place(
            eng.sg.to_padded(dist0),
            eng.sg.to_padded(np.zeros(g.nv, bool)))
        lab1, act1, _ = lg.revalidate(eng, lab1, act1)
        assert lg.reseeds == 1
        full = _clamp_ref(sssp.reference_sssp(g_new, 0))
        dec = _clamp_ref(sssp.reference_sssp_decremental(
            g_new, _clamp_ref(sssp.reference_sssp(g, 0)), touched))
        np.testing.assert_array_equal(dec, full)
        np.testing.assert_array_equal(_sssp_host(eng, lab1), full)

    def test_sssp_weighted_reweight_reseed(self, gw):
        import jax
        eng0 = sssp.build_engine(gw, 0, num_parts=2, weighted=True)
        lab, act = eng0.init_state()
        lab, act, _ = eng0.converge(lab, act)
        d0 = eng0.sg.from_padded(np.asarray(jax.device_get(lab)))
        lg = LiveGraph(gw, capacity=32)
        rng = np.random.default_rng(58)
        src, dst = gw.edge_arrays()
        idx = rng.choice(gw.ne, size=4, replace=False)
        # both directions: two raises, two improvements
        w_new = np.concatenate([
            rng.uniform(4.5, 8.0, 2),
            rng.uniform(0.1, 0.4, 2)]).astype(np.float32)
        lg.reweight_edges(src[idx], dst[idx], w_new)
        g_new = lg.graph_at(1)
        eng = sssp.build_engine(g_new, 0, num_parts=2,
                                weighted=True)
        lab1, act1 = eng.place(
            eng.sg.to_padded(d0),
            eng.sg.to_padded(np.zeros(gw.nv, bool)))
        lab1, act1, _ = lg.revalidate(eng, lab1, act1)
        h = eng.sg.from_padded(np.asarray(jax.device_get(lab1)))
        ref = sssp.reference_sssp(g_new, 0, weighted=True)
        reach = np.isfinite(ref)
        np.testing.assert_allclose(h[reach], ref[reach], rtol=1e-5)
        assert not np.isfinite(h[~reach]).any()

    def test_components_deletion_reseed_bitwise(self, g):
        import jax
        eng0 = components.build_engine(g, num_parts=2)
        lab, act = eng0.init_state()
        lab, act, _ = eng0.converge(lab, act)
        c0 = eng0.sg.from_padded(np.asarray(jax.device_get(lab)))
        lg = LiveGraph(g, capacity=32)
        rng = np.random.default_rng(59)
        self._deleted(lg, g, rng, 5)
        g_new = lg.graph_at(lg.epoch)
        eng = components.build_engine(g_new, num_parts=2)
        lab1, act1 = eng.place(
            eng.sg.to_padded(c0),
            eng.sg.to_padded(np.zeros(g.nv, bool)))
        lab1, _, _ = lg.revalidate(eng, lab1, act1)
        h = eng.sg.from_padded(np.asarray(jax.device_get(lab1)))
        np.testing.assert_array_equal(
            h.astype(np.int64),
            components.reference_components(g_new))

    def test_cone_cap_falls_back_to_full_recompute(self, g):
        lg = LiveGraph(g, capacity=32, cone_cap=1 / g.nv)
        rng = np.random.default_rng(60)
        self._deleted(lg, g, rng, 2)
        g_new = lg.graph_at(lg.epoch)
        eng = sssp.build_engine(g_new, 0, num_parts=2)
        lab, act = eng.init_state()
        lab, act, _ = lg.revalidate(eng, lab, act)
        assert lg.reseeds == 1 and lg.reseed_fallbacks == 1
        np.testing.assert_array_equal(
            _sssp_host(eng, lab),
            _clamp_ref(sssp.reference_sssp(g_new, 0)))

    def test_reseed_crash_leaves_anti_pending(self, g):
        """The RESEED_CRASH leg: the crash lands between the cone
        computation and the re-converge — no answer was produced
        from the half-re-seeded state, the anti ledger is intact,
        admission stays capped, and the retry completes bitwise."""
        plan = faults.MutationFaultPlan(
            reseed_schedule={0: faults.RESEED_CRASH})
        lg = LiveGraph(g, capacity=32, fault=plan)
        s0, d0 = _live_edge(g, 4)
        lg.delete_edges([s0], [d0])
        g_new = lg.graph_at(1)
        eng = sssp.build_engine(g_new, 0, num_parts=2)
        lab, act = eng.init_state()
        with pytest.raises(faults.InjectedWorkerCrash):
            lg.revalidate(eng, lab, act)
        assert plan.fired == [(0, faults.RESEED_CRASH)]
        assert lg.reseeds == 0 and lg.anti_pending() == 1
        assert lg.view_epoch("push") == 0
        # the retry (schedule exhausted) converges to full recompute
        lab, act = eng.init_state()
        lab, act, _ = lg.revalidate(eng, lab, act)
        assert lg.reseeds == 1
        np.testing.assert_array_equal(
            _sssp_host(eng, lab),
            _clamp_ref(sssp.reference_sssp(g_new, 0)))

    def test_per_column_targets_cannot_cross_anti_epoch(self, g):
        from lux_tpu.livegraph import LiveGraphError
        lg = LiveGraph(g, capacity=32)
        lg.append_edges([1], [2])
        s0, d0 = _live_edge(g, 0)
        lg.delete_edges([s0], [d0])             # anti at epoch 2
        eng = sssp.build_engine(g, num_parts=2, sources=[3, 17])
        lab, act = eng.init_state()
        with pytest.raises(LiveGraphError, match="anti-monotone"):
            lg.revalidate(eng, lab, act,
                          col_epoch=np.array([1, 2], np.int32))


# ---------------------------------------------------------------------
# round 21: the economics-driven compaction scheduler


class TestCompactionScheduler:
    def test_decision_ladder(self, g):
        """Every leg of the decision order, in order: empty ->
        admitted -> slo_burn -> anti_monotone -> occupancy -> drag
        -> idle."""
        from lux_tpu.livegraph import CompactionScheduler
        lg = LiveGraph(g, capacity=64, compact_threshold=0.5)
        sched = CompactionScheduler(lg, burn=lambda: 0.0)
        d = sched.decide()
        assert (d["action"], d["reason"]) == ("none", "empty")
        lg.append_edges([1], [2])
        d = sched.decide()
        assert (d["action"], d["reason"]) == ("none", "idle")
        # economics ride on every decision
        for f in ("occupancy", "threshold", "delta_count",
                  "anti_pending", "drag_ns", "drag_source",
                  "admitted", "pins", "burn"):
            assert f in d
        lg.admit("push")
        assert sched.decide()["reason"] == "admitted"
        lg.release()
        # slo burn defers non-urgent folds
        s0, d0 = _live_edge(g, 3)
        lg.delete_edges([s0], [d0])
        hot = CompactionScheduler(lg, burn=lambda: 0.9)
        assert hot.decide()["reason"] == "slo_burn"
        # anti-monotone pressure folds at the first quiet window
        d = sched.decide()
        assert (d["action"], d["reason"]) == ("compact",
                                              "anti_monotone")
        r = sched.maybe_compact()
        assert r["action"] == "compact" and r["generation"] == 1
        assert sched.scheduler_compactions == 1
        assert lg.anti_pending() == 0 and lg.count == 0
        # occupancy trigger
        for i in range(33):
            lg.append_edges([i % g.nv], [(i + 1) % g.nv])
        d = sched.decide()
        assert (d["action"], d["reason"]) == ("compact", "occupancy")
        # measured drag trigger (below threshold, standing drag)
        lg2 = LiveGraph(g, capacity=4096, compact_threshold=0.99)
        lg2.append_edges(np.arange(10) % g.nv,
                         (np.arange(10) + 1) % g.nv)
        lg2.record_drag_sample(1e-3, 10)    # 1e5 ns/slot
        sched2 = CompactionScheduler(lg2)
        d = sched2.decide()
        assert (d["action"], d["reason"]) == ("compact", "drag")
        assert d["drag_source"] == "measured"

    def test_pin_race_demotes_to_deferral(self, g):
        from lux_tpu.livegraph import CompactionScheduler

        class Racy(CompactionScheduler):
            def decide(self):
                d = super().decide()
                if d["action"] == "compact":
                    self.live.pin()     # the race window
                return d

        lg = LiveGraph(g, capacity=8)
        s0, d0 = _live_edge(g, 0)
        lg.delete_edges([s0], [d0])
        sched = Racy(lg)
        d = sched.maybe_compact()
        assert (d["action"], d["reason"]) == ("defer", "pin_race")
        assert sched.scheduler_compactions == 0
        lg.unpin()

    def test_scheduler_soak_mesh8(self, g):
        """THE round-21 scheduler acceptance: seeded Poisson mixed
        traffic (all three kinds) + a live mutation stream with
        deletions on mesh8, the scheduler alone deciding folds —
        occupancy stays bounded, ZERO delta_full sheds, at least
        one scheduler compaction fires, and every admitted answer
        equals its oracle at its admission epoch."""
        from lux_tpu import serve
        from lux_tpu.livegraph import CompactionScheduler
        from lux_tpu.parallel.mesh import make_mesh

        lg = LiveGraph(g, capacity=48, compact_threshold=0.5)
        srv = serve.Server(g, batch=2, num_parts=8,
                           mesh=make_mesh(8), live=lg, seg_iters=4)
        sched = CompactionScheduler(lg, burn=srv.slo_burn)
        rng = np.random.default_rng(67)
        kinds = ["sssp", "components", "pagerank"]
        appended: list = []
        responses = []
        peak_occ = 0.0
        for step in range(8):
            for _ in range(int(rng.poisson(3)) + 1):
                srv.submit(rng.choice(kinds),
                           source=int(rng.integers(g.nv)))
            n = int(rng.poisson(5)) + 1
            s, d = rng.integers(g.nv, size=n), rng.integers(
                g.nv, size=n)
            srv.mutate(s, d)            # zero delta_full sheds: a
            appended += list(zip(s.tolist(), d.tolist()))
            if step in (2, 5):          # deletions in the stream
                es, ed = appended.pop(0)
                srv.mutate([es], [ed], op="delete")
            peak_occ = max(peak_occ, lg.occupancy())
            responses += srv.run()
            sched.maybe_compact(server=srv)
        assert peak_occ < 1.0
        assert sched.scheduler_compactions >= 1
        assert lg.deletions == 2
        assert check_live_answers(lg, responses) == 0
        # the trail is coherent: every fold the scheduler ran is a
        # real compaction, and deferrals never exceeded decisions
        assert lg.compactions == sched.scheduler_compactions


# ---------------------------------------------------------------------
# THE chaos acceptance


class TestLiveChaosAcceptance:
    def test_mutation_stream_kill_and_compact_crash_mesh8(
            self, g, tmp_path):
        """Oversubscribed mixed-kind open-loop load on the 8-virtual-
        device mesh + a live mutation stream concurrent with the
        drain + replica r1 killed mid-drain + an injected crash
        mid-compaction.  Every admitted answer equals its NumPy
        oracle at its ADMISSION epoch (bitwise for the integer apps),
        zero torn reads, zero duplicate retirements, WAL replay
        bitwise-identical, and the event trail (with the torn-epoch
        audit armed) renders clean."""
        import contextvars

        import loadgen

        from lux_tpu import fleet, resilience
        from lux_tpu.parallel.mesh import make_mesh

        kinds = ["sssp", "components", "pagerank"]
        slo = {k: 60000.0 for k in kinds}
        wal = str(tmp_path / "g.lux.wal")
        plan = faults.MutationFaultPlan(
            compact_schedule={0: faults.COMPACT_CRASH})
        live = LiveGraph(g, capacity=96, wal_path=wal, fault=plan,
                         compact_threshold=0.5)
        path = tmp_path / "live_chaos_ev.jsonl"
        ev = telemetry.EventLog(str(path))
        with telemetry.use(events=ev):
            ev.emit("run_start", schema=telemetry.SCHEMA,
                    app="live-fleet", file="<test>", mesh=8)
            t0 = time.perf_counter()
            flt = fleet.FleetServer(
                g, live=live, cache=True, replicas=2, batch=2,
                num_parts=8, mesh=make_mesh(8), slo_ms=slo,
                retry=resilience.RetryPolicy(retries=3,
                                             backoff_s=0.01,
                                             max_backoff_s=0.05,
                                             jitter_seed=0),
                board_path=str(tmp_path / "board"))
            flt.warm(kinds)
            flt.mutate(*_mutations(g.nv, 8, 81))   # epoch 1 pre-load
            kill = faults.ReplicaKillPlan({"r1": 1})
            flt.set_fault(kill)

            # the LIVE mutation stream: ingest concurrent with the
            # drain (appends take the LiveGraph lock; published slots
            # are immutable; epoch advances last — the torn-read-free
            # construction this drill exercises)
            stop = threading.Event()
            mrng = np.random.default_rng(82)

            def mutator():
                # stream until the load ends, leaving headroom under
                # the threshold so the post-load top-up controls the
                # exact trigger point.  Pace by OBSERVED drain
                # progress (new query_start/query_done events in the
                # in-memory trail — append-only, len() is a safe
                # probe) rather than a wall-clock sleep: under CI
                # load a timed cadence either starves the stream or
                # outruns the drain.  stop.wait is a poll tick only.
                seen = len(ev.events)
                while not stop.is_set() and live.occupancy() < 0.4:
                    now = len(ev.events)
                    progressed = any(
                        e.get("kind") in ("query_start", "query_done")
                        for e in ev.events[seen:now])
                    seen = now
                    if not progressed:
                        stop.wait(0.005)
                        continue
                    try:
                        flt.mutate(mrng.integers(g.nv, size=4),
                                   mrng.integers(g.nv, size=4))
                    except fleet.AdmissionError:
                        break       # delta_full: typed backpressure

            ctx = contextvars.copy_context()
            mth = threading.Thread(
                target=lambda: ctx.run(mutator), daemon=True)
            mth.start()
            rng = np.random.default_rng(83)
            rep = loadgen.run_step(flt, rate=500.0, n=14,
                                   kinds=kinds, rng=rng, step=0)
            stop.set()
            mth.join(timeout=10.0)

            # top the stream up past the compaction trigger (the
            # drain may have outrun the mutator's cadence)
            while not live.should_compact():
                flt.mutate(mrng.integers(g.nv, size=4),
                           mrng.integers(g.nv, size=4))
            # crash mid-compaction (between drains, nothing pinned)
            assert live.should_compact()
            with pytest.raises(faults.InjectedWorkerCrash):
                live.compact()
            pre_crash = _wal_state(live)
            live.close()

            # recovery: bitwise-identical WAL replay
            live2 = LiveGraph.recover(g, wal)
            _assert_state_equal(_wal_state(live2), pre_crash)
            # ... and the recovered generation completes the fold +
            # keeps serving: a fresh fleet over the compacted base
            assert live2.compact(force=True) == 1
            # a NEW run boundary: the recovered fleet restarts its
            # qid space, exactly like a recovered process would
            ev.emit("run_start", schema=telemetry.SCHEMA,
                    app="live-fleet-recovered", file="<test>",
                    mesh=8)
            flt2 = fleet.FleetServer(
                live2.base, live=live2, cache=True, replicas=2,
                batch=2, num_parts=8, mesh=make_mesh(8), slo_ms=slo,
                board_path=str(tmp_path / "board2"))
            post = []
            for kind in kinds:
                flt2.submit(kind, source=9)
            post = flt2.run()
            ev.emit("run_done",
                    seconds=round(time.perf_counter() - t0, 6),
                    iters=rep.served + len(post))
        ev.close()

        # the kill fired mid-drain and queries failed over
        assert kill.fired and kill.fired[0][0] == "r1"
        assert flt.failovers >= 1
        # the mutation stream really ran DURING the load
        assert live2.mutations > 8
        # admitted + shed partition the load; exactly-once retirement
        assert rep.drained
        assert rep.served + rep.shed == rep.submitted
        qids = [r.qid for r in rep.responses]
        assert len(set(qids)) == len(qids)
        assert flt.dup_dropped == 0
        # every admitted answer equals its oracle AT ITS ADMISSION
        # EPOCH — bitwise for sssp/components (check_live_answers
        # uses array_equal there), including the failed-over ones
        assert check_live_answers(live2, rep.responses) == 0
        assert check_live_answers(live2, post) == 0
        # zero torn reads: the events trail carries epoch +
        # answer_epoch on every live answer and the summary's
        # torn-epoch audit (+ compaction bracket + replay regression
        # rules) must pass
        r = subprocess.run([sys.executable, str(SUMMARY), str(path)],
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        assert "live graph:" in r.stdout
        assert "WAL replay:" in r.stdout
        assert "replicas: 2 up, 1 lost (r1)" in r.stdout
        live2.close()

    def test_mutation_algebra_chaos_mesh8(self, g, tmp_path):
        """THE round-21 chaos acceptance: the FULL mutation algebra
        under fire on mesh8 — deletions in the live stream, a
        replica killed mid-drain, an injected crash MID-RE-SEED and
        another mid-compaction, WAL replay bitwise with the anti
        ledger intact, the retried re-seed bitwise-equal to both the
        full recompute and the decremental oracle, the scheduler
        completing the crashed fold, and every admitted answer
        oracle-equal at its admission epoch with the events trail
        (re-seed pairing + scheduler economics audits armed)
        rendering clean."""
        import loadgen

        from lux_tpu import fleet, resilience
        from lux_tpu.livegraph import CompactionScheduler
        from lux_tpu.parallel.mesh import make_mesh

        kinds = ["sssp", "components", "pagerank"]
        slo = {k: 60000.0 for k in kinds}
        wal = str(tmp_path / "g.lux.wal")
        plan = faults.MutationFaultPlan(
            compact_schedule={0: faults.COMPACT_CRASH},
            reseed_schedule={0: faults.RESEED_CRASH})
        live = LiveGraph(g, capacity=96, wal_path=wal, fault=plan,
                         compact_threshold=0.5)
        path = tmp_path / "algebra_chaos_ev.jsonl"
        ev = telemetry.EventLog(str(path))
        with telemetry.use(events=ev):
            ev.emit("run_start", schema=telemetry.SCHEMA,
                    app="live-algebra", file="<test>", mesh=8)
            t0 = time.perf_counter()
            flt = fleet.FleetServer(
                g, live=live, cache=True, replicas=2, batch=2,
                num_parts=8, mesh=make_mesh(8), slo_ms=slo,
                retry=resilience.RetryPolicy(retries=3,
                                             backoff_s=0.01,
                                             max_backoff_s=0.05,
                                             jitter_seed=0),
                board_path=str(tmp_path / "board"))
            flt.warm(kinds)
            flt.mutate(*_mutations(g.nv, 8, 91))   # epoch 1
            s7, d7 = _live_edge(g, 7)
            flt.mutate([s7], [d7], op="delete")    # epoch 2 (anti)
            assert live.view_epoch("push") == 1
            kill = faults.ReplicaKillPlan({"r1": 1})
            flt.set_fault(kill)
            rng = np.random.default_rng(92)
            rep = loadgen.run_step(flt, rate=500.0, n=12,
                                   kinds=kinds, rng=rng, step=0)
            # admission NEVER crossed the pending deletion
            assert rep.drained
            assert all(r.epoch <= 1 for r in rep.responses)
            s31, d31 = _live_edge(g, 31)
            flt.mutate([s31], [d31], op="delete")  # epoch 3 (anti)

            # the HONEST re-seed: a standalone engine over
            # graph_at(3) — crash lands between cone and converge
            g3 = live.graph_at(3)
            eng = sssp.build_engine(g3, 0, num_parts=2)
            lab, act = eng.init_state()
            with pytest.raises(faults.InjectedWorkerCrash):
                live.revalidate(eng, lab, act)
            # no answer escaped the half-re-seeded state: ledger
            # intact, admission still capped, nothing counted
            assert live.anti_pending() == 2
            assert live.view_epoch("push") == 1
            assert live.reseeds == 0
            # the retry (schedule exhausted) lands bitwise on BOTH
            # the full recompute and the decremental oracle
            lab, act = eng.init_state()
            lab, act, _ = live.revalidate(eng, lab, act)
            got = _sssp_host(eng, lab)
            full = _clamp_ref(sssp.reference_sssp(g3, 0))
            dec = _clamp_ref(sssp.reference_sssp_decremental(
                g3, _clamp_ref(sssp.reference_sssp(live.graph_at(1),
                                                   0)),
                np.array([d7, d31])))
            np.testing.assert_array_equal(dec, full)
            np.testing.assert_array_equal(got, full)
            assert live.reseeds == 1

            # the scheduler sees the anti pressure; its first fold
            # hits the injected COMPACT_CRASH
            sched = CompactionScheduler(live, burn=flt.slo_burn)
            d = sched.decide()
            assert (d["action"], d["reason"]) == ("compact",
                                                  "anti_monotone")
            with pytest.raises(faults.InjectedWorkerCrash):
                live.compact(force=True)
            pre_crash = _wal_state(live)
            live.close()

            # recovery: bitwise replay, anti ledger restored
            live2 = LiveGraph.recover(g, wal)
            _assert_state_equal(_wal_state(live2), pre_crash)
            assert live2.anti_pending() == 2
            assert live2.deletions == 2
            # the scheduler completes the crashed fold on the
            # recovered log (schedule exhausted)
            sched2 = CompactionScheduler(live2)
            r2 = sched2.maybe_compact()
            assert r2["action"] == "compact"
            assert r2["generation"] == 1
            assert live2.anti_pending() == 0
            ev.emit("run_start", schema=telemetry.SCHEMA,
                    app="live-algebra-recovered", file="<test>",
                    mesh=8)
            flt2 = fleet.FleetServer(
                live2.base, live=live2, cache=True, replicas=2,
                batch=2, num_parts=8, mesh=make_mesh(8),
                slo_ms=slo, board_path=str(tmp_path / "board2"))
            for kind in kinds:
                flt2.submit(kind, source=9)
            post = flt2.run()
            assert all(r.epoch == live2.epoch for r in post)
            ev.emit("run_done",
                    seconds=round(time.perf_counter() - t0, 6),
                    iters=rep.served + len(post))
        ev.close()

        assert kill.fired and kill.fired[0][0] == "r1"
        assert rep.served + rep.shed == rep.submitted
        qids = [r.qid for r in rep.responses]
        assert len(set(qids)) == len(qids)
        # every admitted answer oracle-equal at its admission epoch
        # — through two deletions, a kill, and two injected crashes
        assert check_live_answers(live2, rep.responses) == 0
        assert check_live_answers(live2, post) == 0
        # the trail renders clean with the round-21 audits armed:
        # re-seed pairing, scheduler economics, epoch regression
        r = subprocess.run([sys.executable, str(SUMMARY), str(path)],
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        assert "re-seed: 1 anti-monotone revalidation(s)" in r.stdout
        assert "compaction scheduler:" in r.stdout
        assert "delete" in r.stdout
        live2.close()
