"""Worker for tests/test_worker_kill.py — the kill-one-worker
degraded recovery drill.

Two phases, same file (the reference's "same binary on every node"
model, like tests/mp_worker.py):

- ``distributed``: 2 jax.distributed processes x 4 CPU devices run a
  supervised, checkpointed, HEARTBEAT-SUPERVISED pagerank.  Worker 1
  carries a WORKER_KILL fault plan with ``hard_kill=True`` — at
  segment boundary 1 it os._exit()s with no goodbye, exactly like a
  preempted host.  Worker 0's next heartbeat sync misses the deadline,
  raises the TOPOLOGY-classified WorkerLostError BEFORE entering the
  next segment's collective (no hang), records the agreed shrunken
  topology through the board (propose_shrink), and exits with code 3:
  degraded-relaunch-requested.  jax.distributed cannot drop a member
  in-process, so the shrink is a coordinated RELAUNCH, not an
  in-process mesh rebuild.
- ``solo``: the relaunch.  A single process over its 4 local devices
  resumes from the SHARED checkpoint (written collectively, one
  writer) — the placement metadata records ndev=8, the resuming
  engine has 4, and checkpoint.py routes that into re-placement (a
  ``replace`` event) instead of rejecting it.  The finished state is
  checked against the NumPy oracle.
"""

import os
import sys


def _graph():
    from lux_tpu.convert import uniform_random_edges
    from lux_tpu.graph import Graph

    src, dst = uniform_random_edges(128, 900, seed=5)
    return Graph.from_edges(src, dst, 128)


NI = 10
SEG = 3


def run_distributed(pid: int, nproc: int, port: str, workdir: str):
    from lux_tpu.parallel import multihost
    multihost.initialize(coordinator_address=f"127.0.0.1:{port}",
                         num_processes=nproc, process_id=pid)

    from lux_tpu import faults, heartbeat, resilience
    from lux_tpu.apps import pagerank

    g = _graph()
    mesh = multihost.global_mesh()
    eng = pagerank.build_engine(g, num_parts=8, mesh=mesh)
    hb = heartbeat.Heartbeat(path=os.path.join(workdir, "hb"),
                             pid=pid, nproc=nproc, deadline_s=10.0)
    plan = None
    if pid == 1:
        plan = faults.FaultPlan(schedule={1: faults.WORKER_KILL},
                                hard_kill=True)
    path = os.path.join(workdir, "elastic.ckpt.npz")
    try:
        # guard=False: the finite guard fetches the global state at
        # every boundary; the heartbeat IS the boundary check here
        resilience.supervised_run(
            eng, NI, path, segment=SEG, faults=plan, heartbeat=hb,
            guard=False,
            policy=resilience.RetryPolicy(retries=0, jitter=0,
                                          sleep=lambda s: None))
    except heartbeat.WorkerLostError as e:
        survivors = hb.survivors()
        topo = hb.propose_shrink(survivors, generation=1)
        print(f"SHRINK pid={pid} lost={list(e.lost)} "
              f"survivors={topo['survivors']}", flush=True)
        sys.exit(3)
    print(f"MP_ELASTIC_OK pid={pid}", flush=True)


def run_solo(workdir: str):
    import json

    import numpy as np

    from lux_tpu import resilience, telemetry
    from lux_tpu.apps import pagerank
    from lux_tpu.parallel.mesh import make_mesh

    with open(os.path.join(workdir, "hb", "topology.json")) as f:
        topo = json.load(f)
    assert topo["survivors"] == [0], topo

    import jax
    g = _graph()
    ndev = min(4, len(jax.devices()))
    eng = pagerank.build_engine(g, num_parts=8, mesh=make_mesh(ndev))
    path = os.path.join(workdir, "elastic.ckpt.npz")
    ev = telemetry.EventLog(os.path.join(workdir, "solo_events.jsonl"))
    with telemetry.use(events=ev):
        state, report = resilience.supervised_run(
            eng, NI, path, segment=SEG, resume=True,
            policy=resilience.RetryPolicy(retries=0, jitter=0,
                                          sleep=lambda s: None))
    assert ev.counts().get("replace") == 1, ev.counts()
    assert report.initial_resume == SEG, report.initial_resume
    want = pagerank.reference_pagerank(g, NI)
    np.testing.assert_allclose(eng.unpad(state), want, rtol=2e-5)
    print("SOLO_OK", flush=True)


def main():
    pid = int(sys.argv[1])
    nproc = int(sys.argv[2])
    port = sys.argv[3]
    workdir = sys.argv[4]
    phase = sys.argv[5]
    if phase == "solo":
        run_solo(workdir)
    else:
        run_distributed(pid, nproc, port, workdir)


if __name__ == "__main__":
    main()
