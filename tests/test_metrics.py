"""lux_tpu/metrics.py: the streaming SLO metrics subsystem.

Acceptance bars under test:
- histogram quantiles agree with a NumPy nearest-rank oracle within
  the PINNED error bound (metrics.QUANTILE_REL_ERR — the log-linear
  bucket geometry's published guarantee);
- merge is lossless and associative (bucket-wise add), so per-kind /
  per-replica series combine into one distribution exactly;
- labels isolate series; type punning a name is a hard error;
- the Prometheus text exposition round-trips (cumulative le buckets
  reparse to the exact per-bucket counts and _sum/_count);
- the metrics_snapshot event schema is JSON-ready, self-consistent
  (count == sum of bucket cells) and rebuilds into a mergeable
  histogram (Histogram.from_snapshot);
- the stdlib-http /metrics endpoint serves the exposition.
"""

import json
import re
import threading
import urllib.request

import numpy as np
import pytest

from lux_tpu import metrics, telemetry


def fill(values):
    h = metrics.Histogram()
    for v in values:
        h.observe(float(v))
    return h


# ---------------------------------------------------------------------
# quantile accuracy vs the NumPy oracle, at the pinned bound

@pytest.mark.parametrize("dist,seed", [
    ("lognormal", 0), ("lognormal", 1), ("exponential", 2),
    ("uniform", 3)])
def test_quantile_accuracy_within_pinned_bound(dist, seed):
    """Histogram quantiles vs NumPy's nearest-rank (inverted_cdf)
    oracle: relative error must stay under the PINNED
    QUANTILE_REL_ERR for every standard quantile — this is the bound
    the serving SLO numbers inherit."""
    rng = np.random.default_rng(seed)
    if dist == "lognormal":
        xs = rng.lognormal(mean=-3.0, sigma=1.5, size=5000)
    elif dist == "exponential":
        xs = rng.exponential(scale=0.05, size=5000)
    else:
        xs = rng.uniform(1e-4, 10.0, size=5000)
    h = fill(xs)
    for q in (0.5, 0.9, 0.99, 0.999):
        oracle = float(np.quantile(xs, q, method="inverted_cdf"))
        got = h.quantile(q)
        assert abs(got - oracle) / oracle <= metrics.QUANTILE_REL_ERR, \
            (dist, q, got, oracle)


def test_quantile_edges_and_exact_scalars():
    xs = [0.001, 0.002, 0.004, 0.008, 0.016]
    h = fill(xs)
    assert h.count == 5
    assert h.sum == pytest.approx(sum(xs))
    assert h.min == 0.001 and h.max == 0.016
    # q=0 -> first value's bucket, q=1 -> last value's bucket
    assert abs(h.quantile(0.0) - 0.001) / 0.001 \
        <= metrics.QUANTILE_REL_ERR
    assert abs(h.quantile(1.0) - 0.016) / 0.016 \
        <= metrics.QUANTILE_REL_ERR
    assert metrics.Histogram().quantile(0.5) is None
    with pytest.raises(ValueError):
        h.quantile(1.5)


def test_mean_is_exact_and_empty_is_none():
    """Histogram.mean (round 18: the fleet's projected-wait
    estimator input) is EXACT — sum/count ride beside the quantized
    buckets — and None on an empty series."""
    xs = [0.001, 0.003, 0.007, 0.2]
    h = fill(xs)
    assert h.mean() == pytest.approx(sum(xs) / len(xs), rel=1e-12)
    assert metrics.Histogram().mean() is None
    merged = h.merge(fill([1.0]))
    assert merged.mean() == pytest.approx((sum(xs) + 1.0) / 5)


def test_bucket_geometry_is_consistent():
    """Every in-range value lands in a bucket whose [lo, hi) contains
    it — the invariant the error bound rests on."""
    rng = np.random.default_rng(7)
    for v in rng.lognormal(mean=0.0, sigma=4.0, size=2000):
        v = float(v)
        if not (2.0 ** metrics.HIST_EXP_MIN < v
                < 2.0 ** metrics.HIST_EXP_MAX):
            continue
        idx = metrics.bucket_index(v)
        assert metrics.bucket_lo(idx) <= v <= metrics.bucket_hi(idx)


# ---------------------------------------------------------------------
# merge: lossless, associative

def test_merge_is_lossless_and_associative():
    rng = np.random.default_rng(11)
    xs = rng.lognormal(mean=-2.0, sigma=1.0, size=900)
    a, b, c = fill(xs[:300]), fill(xs[300:600]), fill(xs[600:])
    whole = fill(xs)
    left = a.merge(b).merge(c)
    right = a.merge(b.merge(c))
    for m in (left, right):
        assert m.buckets == whole.buckets        # bucket-wise exact
        assert m.count == whole.count
        assert m.sum == pytest.approx(whole.sum)
        assert m.min == whole.min and m.max == whole.max
        for q in (0.5, 0.9, 0.99):
            assert m.quantile(q) == whole.quantile(q)


def test_merge_with_empty_is_identity():
    h = fill([0.01, 0.02])
    e = metrics.Histogram()
    assert h.merge(e).buckets == h.buckets
    assert e.merge(h).min == h.min and e.merge(h).max == h.max


# ---------------------------------------------------------------------
# registry: labels isolate, types pin

def test_label_isolation_and_identity():
    reg = metrics.Registry()
    a = reg.counter("queries_total", kind="sssp")
    b = reg.counter("queries_total", kind="pagerank")
    a.inc(3)
    b.inc()
    assert a is not b
    assert reg.counter("queries_total", kind="sssp") is a
    assert a.value == 3 and b.value == 1
    h1 = reg.histogram("lat", kind="a", tenant="t0")
    h2 = reg.histogram("lat", tenant="t0", kind="a")   # order-free
    assert h1 is h2


def test_type_conflict_is_an_error():
    reg = metrics.Registry()
    reg.counter("x")
    with pytest.raises(ValueError):
        reg.gauge("x")
    with pytest.raises(ValueError):
        reg.counter("x").inc(-1)


# ---------------------------------------------------------------------
# Prometheus exposition round-trip

PROM_LINE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)$")


def parse_prometheus(text):
    """{(name, frozen labels): float value} over all sample lines."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = PROM_LINE.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        labels = {}
        for tok in (m.group("labels") or "").split(","):
            if not tok:
                continue
            k, _, v = tok.partition("=")
            labels[k] = v.strip('"')
        out[(m.group("name"), tuple(sorted(labels.items())))] = \
            float(m.group("value"))
    return out


def test_prometheus_round_trip():
    reg = metrics.Registry()
    reg.counter("served_total", kind="sssp").inc(7)
    reg.gauge("queue_depth", kind="sssp").set(3)
    xs = [0.001, 0.001, 0.004, 0.02, 0.02, 0.02, 5.0]
    h = reg.histogram("lat_seconds", kind="sssp")
    for v in xs:
        h.observe(v)
    parsed = parse_prometheus(reg.prometheus_text())
    assert parsed[("served_total", (("kind", "sssp"),))] == 7
    assert parsed[("queue_depth", (("kind", "sssp"),))] == 3
    assert parsed[("lat_seconds_count", (("kind", "sssp"),))] == 7
    assert parsed[("lat_seconds_sum", (("kind", "sssp"),))] == \
        pytest.approx(sum(xs))
    # cumulative le buckets re-derive the exact per-bucket counts
    les = {k: v for k, v in parsed.items()
           if k[0] == "lat_seconds_bucket"}
    inf_key = ("lat_seconds_bucket",
               (("kind", "sssp"), ("le", "+Inf")))
    assert les.pop(inf_key) == 7
    bounds = sorted((float(dict(k[1])["le"]), v)
                    for k, v in les.items())
    cums = [v for _le, v in bounds]
    assert cums == sorted(cums) and cums[-1] == 7
    per_bucket = [c - p for c, p in zip(cums, [0] + cums[:-1])]
    assert sorted(h.buckets.values()) == sorted(per_bucket)
    # every observation is under its claimed upper bound
    for (le, cum), n in zip(bounds, per_bucket):
        assert n >= 0 and le > 0


# ---------------------------------------------------------------------
# snapshot event schema + rebuild

def test_snapshot_event_schema_and_rebuild():
    reg = metrics.Registry()
    reg.counter("served_total", kind="sssp").inc(4)
    reg.gauge("occupancy", kind="sssp").set(2)
    xs = [0.003, 0.005, 0.009, 0.2]
    h = reg.histogram("serve_latency_seconds", kind="sssp")
    for v in xs:
        h.observe(v)
    ev = telemetry.EventLog()
    with telemetry.use(events=ev):
        out = reg.emit_snapshot(step=3)
    assert out["kind"] == "metrics_snapshot"
    assert out["schema"] == metrics.SCHEMA and out["step"] == 3
    # JSON-ready: the wire line round-trips
    assert json.loads(json.dumps(out)) == out
    (hs,) = out["histograms"]
    assert hs["name"] == "serve_latency_seconds"
    assert hs["labels"] == {"kind": "sssp"}
    assert hs["count"] == 4 == sum(hs["buckets"].values())
    assert hs["min"] == 0.003 and hs["max"] == 0.2
    assert hs["p50"] is not None and hs["p99"] is not None
    assert hs["p50"] <= hs["p99"]
    rebuilt = metrics.Histogram.from_snapshot(hs)
    assert rebuilt.buckets == h.buckets
    assert rebuilt.quantile(0.5) == h.quantile(0.5)
    (c,) = out["counters"]
    assert c == {"name": "served_total", "labels": {"kind": "sssp"},
                 "value": 4.0}
    # null telemetry handle: emit_snapshot is a no-op None
    assert reg.emit_snapshot() is None


# ---------------------------------------------------------------------
# the stdlib-http endpoint

def test_http_metrics_endpoint():
    reg = metrics.Registry()
    reg.counter("served_total", kind="sssp").inc(9)
    srv = metrics.serve_http(reg, 0)            # ephemeral port
    th = threading.Thread(target=srv.serve_forever, daemon=True)
    th.start()
    try:
        port = srv.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            assert r.status == 200
            assert "version=0.0.4" in r.headers["Content-Type"]
            body = r.read().decode()
        assert 'served_total{kind="sssp"} 9' in body
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/nope", timeout=10)
    finally:
        srv.shutdown()
        srv.server_close()
        th.join(timeout=10)


def test_cli_prints_exposition(capsys):
    rc = metrics.main(["-demo"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "# TYPE serve_latency_seconds histogram" in out
    assert "serve_queries_total" in out
