"""lux_tpu/fleet.py: the resilient serving tier.

THE round-18 chaos acceptance (ISSUE 13): 2+ replicas on the
8-virtual-device mesh under oversubscribed mixed-kind open-loop
loadgen traffic, one replica killed mid-load — every ADMITTED query
retires with an oracle-correct answer, zero duplicate retirements,
every shed query carries a typed AdmissionError, the SLO-good
fraction over admitted queries holds, and the trace/event trails
validate.  Plus the subprocess hard-kill drill (capability-probed,
in-process WORKER_KILL fallback), admission-control units
(queue_full / deadline / quota / brownout), exactly-once dedup, and
the AdmissionError FATAL classification.
"""

import json
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from lux_tpu import faults, fleet, resilience, telemetry
from lux_tpu.convert import uniform_random_edges
from lux_tpu.graph import Graph
from lux_tpu.serve import _check_answers

REPO = Path(__file__).resolve().parent.parent
SUMMARY = REPO / "scripts" / "events_summary.py"
sys.path.insert(0, str(REPO / "scripts"))
sys.path.insert(0, str(REPO))

NV, NE, SEED = 256, 2048, 5
GRAPH_SPEC = {"kind": "uniform", "nv": NV, "ne": NE, "seed": SEED}


@pytest.fixture(scope="module")
def g():
    src, dst = uniform_random_edges(NV, NE, seed=SEED)
    return Graph.from_edges(src, dst, NV)


def fast_retry():
    return resilience.RetryPolicy(retries=3, backoff_s=0.01,
                                  max_backoff_s=0.05, jitter_seed=0)


def make_fleet(g, tmp_path, **kw):
    kw.setdefault("replicas", 2)
    kw.setdefault("batch", 2)
    kw.setdefault("num_parts", 2)
    kw.setdefault("retry", fast_retry())
    kw.setdefault("board_path", str(tmp_path / "board"))
    return fleet.FleetServer(g, **kw)


class TestChaosAcceptance:
    def test_kill_midload_oversubscribed_mesh8(self, g, tmp_path):
        """THE acceptance: replica r1 dies mid-drain under an
        oversubscribed open-loop mixed-kind load on the
        8-virtual-device mesh; admitted answers are oracle-correct
        and bitwise-stable, nothing retires twice, sheds are typed,
        the SLO-good fraction over admitted queries holds, and the
        failover renders as a validated track transition."""
        import loadgen

        from lux_tpu import tracing
        from lux_tpu.parallel.mesh import make_mesh

        kinds = ["sssp", "components", "pagerank"]
        slo = {k: 60000.0 for k in kinds}   # generous: CPU mesh
        path = tmp_path / "chaos_ev.jsonl"
        ev = telemetry.EventLog(str(path))
        with telemetry.use(events=ev):
            ev.emit("run_start", schema=telemetry.SCHEMA, app="fleet",
                    file="<test>", mesh=8)
            flt = make_fleet(g, tmp_path, num_parts=8,
                             mesh=make_mesh(8), slo_ms=slo,
                             brownout_min_priority=1)
            t0 = time.perf_counter()
            flt.warm(kinds)     # every (replica, kind) engine
            idx0 = len(ev.events)
            # arm AFTER warm: r1 dies at its 2nd loaded boundary
            plan = faults.ReplicaKillPlan({"r1": 1})
            flt.set_fault(plan)
            rng = np.random.default_rng(3)
            # rate far past the CPU mesh's service rate: the whole
            # load arrives up front and the B=2 columns oversubscribe
            rep = loadgen.run_step(flt, rate=500.0, n=14,
                                   kinds=kinds, rng=rng, step=0)
            # post-kill determinism: the fleet is browned out, so a
            # below-floor query sheds with a TYPED rejection while a
            # priority-1 query is still admitted and served
            assert flt._brownout == 1
            with pytest.raises(fleet.AdmissionError) as ei:
                flt.submit("sssp", source=3, tenant="free",
                           priority=0)
            assert ei.value.reason == fleet.SHED_BROWNOUT
            assert ei.value.qid in {e.qid for e in flt.shed_records}
            paid_qid = flt.submit("sssp", source=3, tenant="paid",
                                  priority=1)
            (paid,) = flt.run()
            assert paid.qid == paid_qid
            ev.emit("run_done",
                    seconds=round(time.perf_counter() - t0, 6),
                    iters=rep.served + 1)
        ev.close()

        # the kill fired and at least one query failed over
        assert plan.fired and plan.fired[0][0] == "r1"
        assert flt.failovers >= 1
        # admitted + shed partition the offered load; nothing twice
        assert rep.drained
        assert rep.served + rep.shed == rep.submitted
        done = [e for e in ev.events[idx0:]
                if e["kind"] == "query_done"]
        qids = [e["qid"] for e in done]
        assert len(set(qids)) == len(qids), "duplicate retirement"
        assert flt.dup_dropped == 0
        # every shed carries a typed AdmissionError record
        shed_evs = [e for e in ev.events
                    if e["kind"] == "query_shed"]
        assert {e.qid for e in flt.shed_records} == \
            {e["qid"] for e in shed_evs}
        assert all(isinstance(e, fleet.AdmissionError)
                   for e in flt.shed_records)
        # SLO-good fraction over ADMITTED queries at target
        assert rep.slo_good_fraction is not None
        assert rep.slo_good_fraction >= 0.9
        assert rep.slo_accounted == rep.served

        # every admitted answer matches its NumPy oracle — including
        # the failed-over ones, bitwise for the integer apps
        assert _check_answers(g, rep.responses + [paid]) == 0

        # the failover is a validated track transition on the query
        # lanes: post-failover segments sit on the NEW replica's
        # track group
        trace = tracing.trace_export(ev.events,
                                     out=str(tmp_path / "t.json"))
        assert tracing.validate_trace(trace) == []
        fo_spans = [e for e in trace["traceEvents"]
                    if e.get("cat") == "query"
                    and "failover_from" in (e.get("args") or {})]
        assert fo_spans, "no failover split rendered"
        for e in fo_spans:
            assert e["args"]["failover_from"] == "r1"
            assert e["args"]["replica"] == e["args"]["failover_to"]

        # the full event trail renders + audits clean
        r = subprocess.run([sys.executable, str(SUMMARY), str(path)],
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        assert "replicas: 2 up, 1 lost (r1)" in r.stdout
        assert "failovers:" in r.stdout
        assert "BROWNOUT level=1" in r.stdout


class TestSubprocessDrill:
    def test_hard_kill_subprocess_failover(self, g, tmp_path):
        """The hard-kill drill: a subprocess replica (its own OS
        process, fed through the spool dir, beating the shared
        ReplicaBoard) is killed by its armed ReplicaKillPlan
        mid-drain; the parent detects the death and fails the
        in-flight queries over to the in-process survivor.  Where
        the capability probe cannot spawn the worker, the documented
        fallback runs the same drill with an in-process
        WORKER_KILL."""
        ev = telemetry.EventLog()
        with telemetry.use(events=ev):
            flt = make_fleet(g, tmp_path, replicas=1,
                             replica_deadline_s=10.0)
            rep = flt.add_subprocess_replica(
                GRAPH_SPEC, workdir=str(tmp_path / "spool"),
                kill_boundary=2, spawn_budget_s=90.0)
            if rep is None:     # capability probe failed: fallback
                flt._add_inproc_replica()
                flt.set_fault(faults.ReplicaKillPlan(
                    {flt.replica_names[-1]: 2}))
            rng = np.random.default_rng(1)
            for i in range(8):
                flt.submit(["sssp", "components"][i % 2],
                           source=int(rng.integers(0, g.nv)))
            # a personalized-pagerank reset vector must survive the
            # spool serialization (npy sidecar) wherever it lands
            reset = np.zeros(g.nv, np.float32)
            reset[5] = 0.5
            reset[9] = 0.5
            ppr_qid = flt.submit("pagerank", reset=reset)
            responses = flt.run()
            flt.close()
        assert len(responses) == 9
        qids = [r.qid for r in responses]
        assert len(set(qids)) == len(qids)
        assert flt.failovers >= 1, \
            "the killed replica's queries never failed over"
        assert flt._replicas[1].state == "lost"
        (ppr,) = [r for r in responses if r.qid == ppr_qid]
        from lux_tpu.apps import pagerank
        ref = pagerank.reference_pagerank_batched(
            g, reset[:, None], max(1, ppr.iters))[:, 0]
        np.testing.assert_allclose(ppr.answer, ref, atol=5e-5)
        assert _check_answers(g, [r for r in responses
                                  if r.qid != ppr_qid]) == 0
        lost = [e for e in ev.events if e["kind"] == "replica_lost"]
        assert lost and lost[0]["replica"] == "r1"


class TestAdmission:
    def test_queue_full_sheds_typed(self, g, tmp_path):
        flt = make_fleet(g, tmp_path, max_queue=2)
        flt.submit("sssp", source=1)
        flt.submit("sssp", source=2)
        with pytest.raises(fleet.AdmissionError) as ei:
            flt.submit("sssp", source=3)
        assert ei.value.reason == fleet.SHED_QUEUE_FULL
        # the queued two still serve
        rs = flt.run()
        assert sorted(r.qid for r in rs) == [0, 1]

    def test_deadline_projected_wait_sheds(self, g, tmp_path):
        """Seed the service-time histogram, stuff the queue, then a
        tight-deadline query must shed with the projected wait on the
        typed error; a no-deadline query is still admitted."""
        flt = make_fleet(g, tmp_path)
        h = flt.metrics.histogram("fleet_service_seconds",
                                  kind="sssp")
        for _ in range(4):
            h.observe(1.0)      # 1 s mean service time
        for i in range(8):      # 8 queued / (2 replicas x B=2) = 2 s
            flt._queue("sssp").put(
                fleet.Request(qid=1000 + i, kind="sssp", source=1,
                              t_enqueue=time.monotonic()))
        with pytest.raises(fleet.AdmissionError) as ei:
            flt.submit("sssp", source=3, deadline_s=0.5)
        assert ei.value.reason == fleet.SHED_DEADLINE
        assert ei.value.projected_wait_s == pytest.approx(2.0)
        assert flt.submit("sssp", source=3) >= 0

    def test_tenant_quota_sheds(self, g, tmp_path):
        flt = make_fleet(g, tmp_path, quota={"free": 2})
        flt.submit("sssp", source=1, tenant="free")
        flt.submit("sssp", source=2, tenant="free")
        with pytest.raises(fleet.AdmissionError) as ei:
            flt.submit("sssp", source=3, tenant="free")
        assert ei.value.reason == fleet.SHED_QUOTA
        # another tenant is unaffected
        flt.submit("sssp", source=3, tenant="paid")
        rs = flt.run()
        assert len(rs) == 3
        # retirement releases the quota
        assert flt.submit("sssp", source=4, tenant="free") >= 0

    def test_admission_error_classifies_fatal(self):
        err = fleet.AdmissionError(1, "sssp", "free",
                                   fleet.SHED_DEADLINE,
                                   projected_wait_s=2.0,
                                   deadline_s=0.5)
        assert resilience.classify(err) == resilience.FATAL

    def test_priority_collector_on_replica_columns(self, g,
                                                   tmp_path):
        """Replica collectors are PriorityCollectors: with one
        replica and B=1 columns, a high-priority late arrival is
        collected before earlier low-priority requests."""
        ev = telemetry.EventLog()
        with telemetry.use(events=ev):
            flt = make_fleet(g, tmp_path, replicas=1, batch=1)
            q0 = flt.submit("sssp", source=1, priority=0)
            q1 = flt.submit("sssp", source=2, priority=0)
            q2 = flt.submit("sssp", source=3, priority=5)
            rs = flt.run()
        assert len(rs) == 3
        starts = [e["qid"] for e in ev.events
                  if e["kind"] == "query_start"]
        # the priority-5 query starts before the second priority-0
        assert starts.index(q2) < starts.index(q1)
        assert starts[0] in (q0, q2)


class TestExactlyOnce:
    def test_replayed_retired_query_dropped(self, g, tmp_path):
        """The replayed-query guard: re-dispatching a qid that
        already retired (the detection race) is DROPPED — no second
        query_done, no double answer."""
        ev = telemetry.EventLog()
        with telemetry.use(events=ev):
            flt = make_fleet(g, tmp_path)
            flt.submit("sssp", source=7)
            (r,) = flt.run()
            req = fleet.Request(qid=r.qid, kind="sssp", source=7,
                                t_enqueue=time.monotonic())
            flt._failover(req, flt._replicas[0])
            out = flt.run()
        assert out == []
        assert flt.dup_dropped == 1
        assert flt.failovers == 0
        dones = [e for e in ev.events if e["kind"] == "query_done"]
        assert len(dones) == 1

    def test_answers_bitwise_equal_faultfree(self, g, tmp_path):
        """Failed-over integer-app answers are BITWISE equal to a
        fault-free fleet's: engines are deterministic in the graph
        arrays and the source, so a restart on the survivor loses
        time, never bits."""
        specs = [("sssp", s) for s in (3, 17, 40, 99)] \
            + [("components", s) for s in (7, 50, 120, 200)]

        def run_once(fault):
            flt = make_fleet(g, tmp_path, fault=fault)
            for kind, s in specs:
                flt.submit(kind, source=s)
            rs = flt.run()
            assert len(rs) == len(specs)
            return {r.qid: r.answer for r in rs}, flt

        plain, _ = run_once(None)
        chaos, flt = run_once(
            faults.ReplicaKillPlan({"r1": 1}))
        assert flt.failovers >= 1
        for qid in plain:
            np.testing.assert_array_equal(plain[qid], chaos[qid])


class TestServeChaosBench:
    def test_serve_chaos_line_through_check_bench(self, tmp_path):
        """The acceptance's bench leg: bench.py -config serve-chaos
        produces a metric line scripts/check_bench.py ACCEPTS, the
        kill verifiably fired, and the failovers/shed record rides
        the line."""
        import argparse

        import bench

        args = argparse.Namespace(
            scale=8, ef=8, ni=20, np=2, pair=0, min_fill=None,
            min_fill_dot=None, repeats=1, verbose=False,
            health=False, audit="warn", serve_queries=12,
            serve_batch=2, serve_kinds="sssp,components,pagerank",
            slo_ms="sssp=30000,components=30000,pagerank=30000",
            rates="150", batch="1", shape="rmat", reorder="none",
            serve_replicas=2, kill_boundary=1)
        ev = telemetry.EventLog()
        with telemetry.use(events=ev):
            idx0 = len(ev.events)
            name, samples, extra, _rerun = bench.run_config(
                "serve-chaos@150", args)
            tel = bench.config_telemetry(ev, idx0, None)
        assert name == "serve_chaos_q150_rmat8"
        assert extra["replicas"] == 2 and extra["failovers"] >= 1
        assert extra["served"] + extra["shed"] == extra["submitted"]
        assert extra["audit"]["errors"] == 0
        # round 24: the self-healing record rides the line — the
        # heal-armed drill respawned (or quarantined) the kill
        assert extra["respawns"] + extra["quarantines"] >= 1
        assert extra["journal_replayed"] >= 0
        assert extra["mttr_s"] is None or extra["mttr_s"] >= 0.0
        value = round(float(np.median(samples)), 4)
        line = {"metric": f"{name}_qps_per_chip", "value": value,
                "unit": "qps", "vs_baseline": value,
                "samples": [round(s, 4) for s in samples],
                "attempts": len(samples), "discarded": [],
                "telemetry": tel, **extra}
        p = tmp_path / "bench.jsonl"
        p.write_text(json.dumps(line) + "\n")
        r = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "check_bench.py"),
             "-legacy-ok", str(p)],
            capture_output=True, text=True)
        assert r.returncode == 0, r.stderr

    def test_serve_chaos_rejects_single_replica(self):
        import argparse

        import bench

        args = argparse.Namespace(
            scale=8, ef=8, ni=20, np=2, pair=0, min_fill=None,
            min_fill_dot=None, repeats=1, verbose=False,
            health=False, audit="off", serve_queries=4,
            serve_batch=2, serve_kinds="sssp",
            slo_ms="sssp=30000", rates="50", batch="1",
            shape="rmat", reorder="none",
            serve_replicas=1, kill_boundary=1)
        with pytest.raises(ValueError, match="serve-replicas"):
            bench.run_config("serve-chaos@50", args)


def heal_retry():
    """Zero-delay, never-sleeping respawn backoff: resurrection tests
    drive the supervisor deterministically without wall-clock."""
    return resilience.RetryPolicy(retries=5, backoff_s=0.0,
                                  max_backoff_s=0.0, jitter_seed=0,
                                  sleep=lambda s: None)


class TestSelfHealing:
    """Round 24: durable admission journal, replica resurrection, and
    THE whole-fleet kill drill (ISSUE 19)."""

    def test_fleet_crash_recover_drill_mesh8(self, g, tmp_path):
        """THE round-24 acceptance: a live journalled fleet on the
        8-virtual-device mesh under an oversubscribed mixed-kind load
        with mutations in the stream is killed ENTIRELY (coordinator
        + every replica) mid-drain; restart from the mutation WAL +
        admission journal re-answers every admitted-unretired query
        at its ORIGINAL admission epoch — zero lost admitted queries,
        zero duplicate retirements, oracle-equal answers, and the
        event trail (journal-replay + recovered-enqueue audits armed)
        renders clean."""
        from lux_tpu.journal import AdmissionJournal
        from lux_tpu.livegraph import LiveGraph, check_live_answers
        from lux_tpu.parallel.mesh import make_mesh

        kinds = ["sssp", "components", "pagerank"]
        slo = {k: 60000.0 for k in kinds}
        wal = str(tmp_path / "g.lux.wal")
        jpath = str(tmp_path / "g.lux.journal")
        path = tmp_path / "heal_ev.jsonl"
        live = LiveGraph(g, capacity=64, wal_path=wal)
        ev = telemetry.EventLog(str(path))
        with telemetry.use(events=ev):
            ev.emit("run_start", schema=telemetry.SCHEMA, app="fleet",
                    file="<test>", mesh=8)
            t0 = time.perf_counter()
            flt = make_fleet(g, tmp_path, num_parts=8,
                             mesh=make_mesh(8), slo_ms=slo,
                             live=live, journal_path=jpath)
            flt.warm(kinds)
            flt.mutate([1, 2, 3], [4, 5, 6])        # epoch 1
            rng = np.random.default_rng(7)
            qids = [flt.submit(kinds[i % 3],
                               source=int(rng.integers(g.nv)))
                    for i in range(9)]
            # a mutation MID-STREAM: later admits pin epoch 2, so
            # recovery must reproduce TWO distinct epochs
            flt.mutate([7, 8], [9, 10])             # epoch 2
            qids += [flt.submit(kinds[i % 3],
                                source=int(rng.integers(g.nv)))
                     for i in range(3)]
            reset = np.zeros(g.nv, np.float32)
            reset[3] = 0.5
            reset[17] = 0.5
            ppq = flt.submit("pagerank", reset=reset)
            qids.append(ppq)
            # the whole fleet dies at the routed replica's 2nd loaded
            # boundary (armed via routing_target — the round-22 rule)
            target = flt.routing_target("sssp")
            plan = faults.ReplicaKillPlan({target: 2},
                                          action=faults.FLEET_CRASH)
            flt.set_fault(plan)
            with pytest.raises(faults.InjectedFleetCrash) as ei:
                flt.run()
            assert ei.value.replica == target
            # process death: every in-memory handle is gone — only
            # the fsync'd WAL + journal survive
            flt.close()
            live.close()

            # recovery ORDERING (ARCHITECTURE.md "Self-healing
            # fleet"): WAL replay adopts the generation FIRST, then
            # the journal re-dispatches over it
            live2 = LiveGraph.recover(g, wal)
            flt2 = fleet.FleetServer.recover(
                live2.base, jpath, live=live2,
                resets={ppq: reset}, replicas=2, batch=2,
                num_parts=8, mesh=make_mesh(8), slo_ms=slo,
                retry=fast_retry(),
                board_path=str(tmp_path / "board2"))
            assert flt2.journal_replayed >= 1
            rec = flt2.run()
            ev.emit("run_done",
                    seconds=round(time.perf_counter() - t0, 6),
                    iters=len(rec))
            flt2.close()
        ev.close()

        assert plan.fired and plan.fired[0][2] == faults.FLEET_CRASH
        # the journal replay counter rode into the registry too
        assert flt2.metrics.counter(
            "fleet_journal_replayed_total").value \
            == flt2.journal_replayed
        # zero duplicate retirements across the restart
        rqids = [r.qid for r in rec]
        assert len(set(rqids)) == len(rqids)
        assert flt2.dup_dropped == 0
        # every recovered answer equals its oracle AT ITS ADMISSION
        # epoch (bitwise for the integer apps); the reset query is
        # checked against the reference at ITS epoch by hand
        # (check_live_answers covers one-hot sources only)
        assert check_live_answers(
            live2, [r for r in rec if r.qid != ppq]) == 0
        ppr = next((r for r in rec if r.qid == ppq), None)
        if ppr is not None:     # not retired before the crash
            from lux_tpu.apps import pagerank
            g_e = live2.graph_at(ppr.epoch or 0)
            ref = pagerank.reference_pagerank_batched(
                g_e, reset[:, None], max(1, ppr.iters))[:, 0]
            np.testing.assert_allclose(ppr.answer, ref, atol=5e-5)
        # ZERO lost admitted queries: after the recovered drain the
        # journal holds no open entry, and every admitted qid closed
        # exactly once (pre-crash answers + recovered answers + typed
        # sheds partition the admitted set)
        opens, retired, _, torn = AdmissionJournal.scan(jpath,
                                                        nv=g.nv)
        assert opens == [] and torn == 0
        assert set(retired) == set(qids)
        shed_qids = {e.qid for e in flt2.shed_records}
        for qid in qids:
            pre = qid not in {r.qid for r in rec} \
                and qid not in shed_qids
            assert retired[qid] == ("answered" if qid in rqids or pre
                                    else "shed")
        # the trail renders + audits clean, journal replay included
        r = subprocess.run([sys.executable, str(SUMMARY), str(path)],
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr + r.stdout
        assert "admission journal replay:" in r.stdout
        live2.close()

    def test_respawn_canary_gated_mttr(self, g, tmp_path):
        """Resurrection: a heal-armed fleet loses a replica
        mid-drain, respawns it under the (zero-delay) backoff, the
        canary passes, routing re-enters, brownout decays to 0, and
        MTTR is gauged — all before run() returns."""
        ev = telemetry.EventLog()
        with telemetry.use(events=ev):
            flt = make_fleet(g, tmp_path, heal=True,
                             respawn_retry=heal_retry())
            qids = [flt.submit("sssp", source=s)
                    for s in (1, 5, 9, 13)]
            target = flt.routing_target("sssp")
            flt.set_fault(faults.ReplicaKillPlan({target: 1}))
            rs = flt.run()
        assert sorted(r.qid for r in rs) == qids
        assert _check_answers(g, rs) == 0
        assert flt.failovers >= 1
        assert flt.respawns == 1 and flt.quarantines == 0
        assert [r.state for r in flt._replicas] == ["up", "up"]
        assert flt._brownout == 0
        assert flt.mttr_s is not None and flt.mttr_s >= 0.0
        assert flt.metrics.gauge("fleet_mttr_seconds").value >= 0.0
        assert flt.metrics.counter("fleet_respawns_total").value == 1
        # the canary gated re-entry and the trail shows the order:
        # lost BEFORE respawn, with a passing canary between
        canaries = [e for e in ev.events if e["kind"] == "canary"]
        assert canaries and canaries[-1]["ok"] is True
        assert canaries[-1]["replica"] == target
        resp = [e for e in ev.events
                if e["kind"] == "replica_respawn"]
        assert len(resp) == 1 and resp[0]["replica"] == target
        assert resp[0]["canary_ok"] is True
        order = [e["kind"] for e in ev.events
                 if e["kind"] in ("replica_lost", "canary",
                                  "replica_respawn")]
        assert order.index("replica_lost") \
            < order.index("canary") < order.index("replica_respawn")
        # the canary probe is NOT traffic: its throwaway qid never
        # reaches the caller's responses
        assert not (set(r.qid for r in rs)
                    & {e["qid"] for e in canaries})

    def test_replica_flap_trips_quarantine(self, g, tmp_path):
        """REPLICA_FLAP (the one re-firing action): the respawned
        replica dies again at every boundary until flap detection
        trips the typed quarantine — the survivor still answers every
        admitted query, and the brownout HOLDS (a quarantined replica
        is not coming back)."""
        ev = telemetry.EventLog()
        with telemetry.use(events=ev):
            flt = make_fleet(g, tmp_path, heal=True,
                             respawn_retry=heal_retry(),
                             flap_threshold=3, flap_window_s=60.0)
            qids = [flt.submit("components", source=s)
                    for s in (2, 7, 11)]
            target = flt.routing_target("components")
            plan = faults.ReplicaKillPlan(
                {target: 1}, action=faults.REPLICA_FLAP)
            flt.set_fault(plan)
            rs = flt.run()
        assert sorted(r.qid for r in rs) == qids
        assert _check_answers(g, rs) == 0
        assert flt.quarantines == 1 and flt.respawns == 0
        assert sorted(r.state for r in flt._replicas) == \
            ["quarantined", "up"]
        assert flt._brownout == 1
        assert flt.mttr_s is None       # the pool never healed whole
        assert flt.flap.deaths(target) >= flt.flap.threshold
        kills = [f for f in plan.fired if f[0] == target]
        assert len(kills) >= flt.flap.threshold     # it re-fired
        q = [e for e in ev.events
             if e["kind"] == "replica_quarantine"]
        assert len(q) == 1 and q[0]["replica"] == target
        assert q[0]["reason"] == "flap"
        assert q[0]["deaths"] >= 3
        assert flt.metrics.counter(
            "fleet_quarantines_total").value == 1

    def test_manual_resurrect_heal_off(self, g, tmp_path):
        """resurrect() heals between drains with heal=False: the
        supervisor never runs inside run(), but the operator can
        drive the same respawn/canary path to quiescence by hand."""
        flt = make_fleet(g, tmp_path, respawn_retry=heal_retry())
        qids = [flt.submit("sssp", source=s) for s in (3, 8)]
        target = flt.routing_target("sssp")
        flt.set_fault(faults.ReplicaKillPlan({target: 1}))
        rs = flt.run()
        assert sorted(r.qid for r in rs) == qids
        assert flt.respawns == 0        # heal=False: nothing auto
        assert any(r.state == "lost" for r in flt._replicas)
        assert flt._brownout == 1
        flt.set_fault(None)
        assert flt.resurrect() == [target]
        assert all(r.state == "up" for r in flt._replicas)
        assert flt._brownout == 0 and flt.respawns == 1
        q2 = flt.submit("sssp", source=40)
        (r2,) = flt.run()
        assert r2.qid == q2 and _check_answers(g, [r2]) == 0

    @pytest.mark.parametrize("verdict", ["missing", "wrong", "right"])
    def test_recover_reset_digest_verdicts(self, g, tmp_path,
                                           verdict):
        """A journalled reset query re-dispatches ONLY when recovery
        re-supplies the vector matching the persisted digest; a
        missing or mismatching vector closes the entry as a typed
        reset_unavailable shed (never a silent drop, never a
        DIFFERENT query than the one admitted)."""
        from lux_tpu.journal import AdmissionJournal

        jpath = str(tmp_path / "g.journal")
        reset = np.zeros(g.nv, np.float32)
        reset[4] = 0.75
        reset[11] = 0.25
        flt = make_fleet(g, tmp_path, journal_path=jpath)
        sq = flt.submit("sssp", source=6)
        pq = flt.submit("pagerank", reset=reset)
        flt.close()                     # crash: nothing drained

        resets = {"missing": None,
                  "wrong": {pq: np.roll(reset, 1)},
                  "right": {pq: reset}}[verdict]
        flt2 = fleet.FleetServer.recover(
            g, jpath, resets=resets, replicas=2, batch=2,
            num_parts=2, retry=fast_retry(),
            board_path=str(tmp_path / "board2"))
        assert flt2.journal_replayed == 2
        rs = flt2.run()
        flt2.close()
        assert _check_answers(
            g, [r for r in rs if r.qid == sq]) == 0
        if verdict == "right":
            assert sorted(r.qid for r in rs) == [sq, pq]
            (ppr,) = [r for r in rs if r.qid == pq]
            from lux_tpu.apps import pagerank
            ref = pagerank.reference_pagerank_batched(
                g, reset[:, None], max(1, ppr.iters))[:, 0]
            np.testing.assert_allclose(ppr.answer, ref, atol=5e-5)
            want = {sq: "answered", pq: "answered"}
        else:
            assert [r.qid for r in rs] == [sq]
            (err,) = [e for e in flt2.shed_records if e.qid == pq]
            assert err.reason == fleet.SHED_RESET_UNAVAILABLE
            want = {sq: "answered", pq: "shed"}
        opens, retired, _, _ = AdmissionJournal.scan(jpath, nv=g.nv)
        assert opens == [] and retired == want

    def test_recover_epoch_folded_sheds_typed(self, g, tmp_path):
        """A recovered base that durably compacted PAST a record's
        admission epoch cannot answer it bitwise at that epoch — the
        entry closes as a typed epoch_folded shed with a journal
        RETIRE(shed) record."""
        from lux_tpu.journal import AdmissionJournal
        from lux_tpu.livegraph import LiveGraph

        wal = str(tmp_path / "g.lux.wal")
        jpath = str(tmp_path / "g.lux.journal")
        live = LiveGraph(g, capacity=32, wal_path=wal)
        flt = make_fleet(g, tmp_path, live=live, journal_path=jpath)
        flt.mutate([1, 2], [3, 4])          # epoch 1
        qid = flt.submit("sssp", source=5)  # admitted AT epoch 1
        flt.close()
        live.close()                        # crash

        live2 = LiveGraph.recover(g, wal)
        live2.append_edges([5], [6])        # epoch 2
        assert live2.compact(force=True) is not None
        assert live2.base_epoch == 2        # epoch 1 folded away
        flt2 = fleet.FleetServer.recover(
            live2.base, jpath, live=live2, replicas=2, batch=2,
            num_parts=2, retry=fast_retry(),
            board_path=str(tmp_path / "board2"))
        assert flt2.journal_replayed == 1
        assert flt2.run() == []
        (err,) = flt2.shed_records
        assert err.qid == qid
        assert err.reason == fleet.SHED_EPOCH_FOLDED
        flt2.close()
        opens, retired, _, _ = AdmissionJournal.scan(jpath, nv=g.nv)
        assert opens == [] and retired == {qid: "shed"}
        live2.close()

    def test_double_recover_is_exactly_once(self, g, tmp_path):
        """Retirement is exactly-once ACROSS restarts: a second
        recover over a fully-retired journal replays nothing,
        answers nothing, and the qid space continues monotonically
        past everything the journal has seen."""
        jpath = str(tmp_path / "g.journal")
        flt = make_fleet(g, tmp_path, journal_path=jpath)
        qids = [flt.submit("sssp", source=s) for s in (1, 9)]
        flt.close()                         # crash before any drain
        flt2 = fleet.FleetServer.recover(
            g, jpath, replicas=2, batch=2, num_parts=2,
            retry=fast_retry(), board_path=str(tmp_path / "b2"))
        assert flt2.journal_replayed == 2
        rs = flt2.run()
        assert sorted(r.qid for r in rs) == qids
        flt2.close()
        flt3 = fleet.FleetServer.recover(
            g, jpath, replicas=2, batch=2, num_parts=2,
            retry=fast_retry(), board_path=str(tmp_path / "b3"))
        assert flt3.journal_replayed == 0
        assert flt3.run() == []
        assert flt3.submit("sssp", source=2) > max(qids)
        assert len(flt3.run()) == 1
        flt3.close()

    def test_recover_rejects_journal_path_kw(self, g, tmp_path):
        with pytest.raises(ValueError, match="journal_path"):
            fleet.FleetServer.recover(
                g, str(tmp_path / "j.journal"),
                journal_path=str(tmp_path / "j.journal"))


class TestBoard:
    def test_replica_board_ages_with_fake_clock(self, tmp_path):
        clock = [100.0]
        board = __import__("lux_tpu.heartbeat",
                           fromlist=["ReplicaBoard"]).ReplicaBoard(
            str(tmp_path / "b"), deadline_s=3.0,
            now=lambda: clock[0])
        assert board.age("r0") is None
        board.beat("r0", status="up")
        assert board.age("r0") == 0.0
        assert board.alive("r0")
        clock[0] += 5.0
        assert board.age("r0") == 5.0
        assert not board.alive("r0")
        assert board.replicas() == ["r0"]
