"""Sharded (mesh) execution must match single-device execution exactly.

This exercises the shard_map + all_gather path on the 8-device virtual
CPU mesh — the TPU-native analogue of the reference's multi-node runs
(SURVEY.md §4 item 3: "multi-node without a cluster").
"""

import jax
import numpy as np
import pytest

from lux_tpu.apps import colfilter, pagerank
from lux_tpu.convert import rmat_edges, uniform_random_edges
from lux_tpu.graph import Graph
from lux_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) == 8, "conftest should force 8 CPU devices"
    return make_mesh(8)


def test_pagerank_mesh_matches_single(mesh8):
    src, dst, nv = rmat_edges(scale=11, edge_factor=8, seed=5)
    g = Graph.from_edges(src, dst, nv)
    single = pagerank.run(g, 4, num_parts=8)
    sharded = pagerank.run(g, 4, num_parts=8, mesh=mesh8)
    np.testing.assert_allclose(sharded, single, rtol=1e-6)
    want = pagerank.reference_pagerank(g, 4)
    np.testing.assert_allclose(sharded, want, rtol=5e-5, atol=1e-9)


def test_more_parts_than_devices(mesh8):
    """num_parts = 16 on 8 devices: 2 parts per device."""
    src, dst = uniform_random_edges(400, 3000, seed=8)
    g = Graph.from_edges(src, dst, 400)
    sharded = pagerank.run(g, 3, num_parts=16, mesh=mesh8)
    want = pagerank.reference_pagerank(g, 3)
    np.testing.assert_allclose(sharded, want, rtol=5e-5, atol=1e-9)


def test_mesh_subset(mesh8):
    """Mesh smaller than the device pool (2 of 8)."""
    mesh2 = make_mesh(2)
    src, dst = uniform_random_edges(100, 900, seed=9)
    g = Graph.from_edges(src, dst, 100)
    sharded = pagerank.run(g, 2, num_parts=2, mesh=mesh2)
    want = pagerank.reference_pagerank(g, 2)
    np.testing.assert_allclose(sharded, want, rtol=5e-5, atol=1e-9)


def test_colfilter_mesh(mesh8):
    from tests.test_colfilter import bipartite_graph
    g = bipartite_graph(ne=1200)
    single = colfilter.run(g, 2, num_parts=8)
    sharded = colfilter.run(g, 2, num_parts=8, mesh=mesh8)
    np.testing.assert_allclose(sharded, single, rtol=1e-6, atol=1e-8)


def test_indivisible_parts_rejected(mesh8):
    src, dst = uniform_random_edges(50, 300, seed=2)
    g = Graph.from_edges(src, dst, 50)
    with pytest.raises(ValueError):
        pagerank.run(g, 1, num_parts=3, mesh=mesh8)
