"""scripts/events_summary.py: render + audit of -events JSONL logs."""

import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
SCRIPT = REPO / "scripts" / "events_summary.py"


def run_summary(*argv):
    return subprocess.run(
        [sys.executable, str(SCRIPT), *map(str, argv)],
        capture_output=True, text=True)


def write_log(path, events):
    path.write_text("".join(json.dumps(e) + "\n" for e in events))


GOOD = [
    {"t": 1.0, "kind": "run_start", "schema": 1, "app": "pagerank"},
    {"t": 1.1, "kind": "header", "schema": 1, "nv": 120, "ne": 900,
     "num_parts": 2,
     "memory": {"edge_bytes_per_part": 2560,
                "vertex_bytes_per_part": 512,
                "total_bytes": 6144}},
    {"t": 1.2, "kind": "segment", "engine": "pull", "n": 2, "done": 2,
     "seconds": 0.12},
    {"t": 1.3, "kind": "checkpoint_save", "iter": 2, "engine": "pull",
     "path": "/tmp/x.npz", "seconds": 0.01},
    {"t": 1.4, "kind": "segment", "engine": "pull", "n": 2, "done": 4,
     "seconds": 0.10},
    {"t": 1.5, "kind": "run_done", "seconds": 0.30, "iters": 4},
    {"t": 1.6, "kind": "iter_stats", "engine": "pull", "iters": 4,
     "truncated": False, "residual_first": 3.5e-4,
     "residual_last": 9.7e-8, "changed_last": 120},
    {"t": 1.7, "kind": "phases", "iters": 1,
     "report": [{"exchange": 0.002, "gather": 0.003, "reduce": 0.004,
                 "apply": 0.001}]},
]


def test_good_log_renders(tmp_path):
    p = tmp_path / "ev.jsonl"
    write_log(p, GOOD)
    r = run_summary(p)
    assert r.returncode == 0, r.stderr
    out = r.stdout
    assert "== pagerank ==" in out
    assert "nv=120" in out and "ne=900" in out
    assert "segments: 2" in out
    assert "checkpoint saves: 1" in out
    assert "loadTime/compTime/updateTime" in out
    assert "counters (pull)" in out
    assert "ELAPSED TIME = 0.3" in out


def test_cli_produced_log_accepted(tmp_path):
    """End-to-end: a real -events run (the acceptance criterion's
    'a JSONL that events_summary.py accepts')."""
    import numpy as np

    from lux_tpu import cli
    from lux_tpu import format as luxfmt
    from lux_tpu.convert import uniform_random_edges
    from lux_tpu.graph import Graph

    src, dst = uniform_random_edges(100, 700, seed=4)
    g = Graph.from_edges(src, dst, 100)
    lux = tmp_path / "g.lux"
    luxfmt.write_lux(str(lux), g.row_ptrs, g.col_idx,
                     degrees=g.out_degrees)
    ev = tmp_path / "events.jsonl"
    rc = cli.main(["sssp", "-file", str(lux), "-start", "0",
                   "-iter-stats", "-events", str(ev)])
    assert rc == 0 and ev.exists()
    kinds = [json.loads(s)["kind"] for s in
             ev.read_text().splitlines()]
    assert {"run_start", "header", "timed_run", "run_done",
            "iter_stats"} <= set(kinds)
    r = run_summary(ev)
    assert r.returncode == 0, r.stderr
    assert "== sssp ==" in r.stdout
    assert "counters (push)" in r.stdout


def test_unparseable_line_fails(tmp_path):
    p = tmp_path / "ev.jsonl"
    p.write_text('{"kind": "header"}\nnot json at all {\n')
    r = run_summary(p)
    assert r.returncode == 1
    assert "unparseable" in r.stderr


def test_missing_kind_fails(tmp_path):
    p = tmp_path / "ev.jsonl"
    p.write_text('{"t": 1.0, "no_kind": true}\n')
    r = run_summary(p)
    assert r.returncode == 1
    assert "without a 'kind'" in r.stderr


def test_segment_overcount_fails(tmp_path):
    """Segment seconds summing PAST the run's elapsed means the
    fenced slice timings overlap or double-count — the audit must
    fail (under-sum is legitimate: elapsed also bills checkpoint
    saves and host driver time)."""
    bad = [
        {"t": 1.0, "kind": "run_start", "app": "pagerank"},
        {"t": 1.1, "kind": "segment", "engine": "pull", "n": 2,
         "done": 2, "seconds": 5.0},
        {"t": 1.2, "kind": "run_done", "seconds": 0.5, "iters": 2},
    ]
    p = tmp_path / "ev.jsonl"
    write_log(p, bad)
    r = run_summary(p)
    assert r.returncode == 1
    assert "overlap" in r.stderr


def test_timed_event_missing_seconds_fails(tmp_path):
    bad = [
        {"t": 1.0, "kind": "run_start", "app": "sssp"},
        {"t": 1.1, "kind": "timed_run", "repeat": 0, "iters": 5},
    ]
    p = tmp_path / "ev.jsonl"
    write_log(p, bad)
    r = run_summary(p)
    assert r.returncode == 1
    assert "without numeric 'seconds'" in r.stderr


def test_health_events_render(tmp_path):
    """Round-9 guarded-execution events: watchdog digests/trips and
    checkpoint generation fallbacks render by name."""
    events = [
        {"t": 1.0, "kind": "run_start", "app": "pagerank"},
        {"t": 1.1, "kind": "health", "engine": "pull",
         "tripped": False, "flags": [], "iters": 20},
        {"t": 1.2, "kind": "health_trip", "engine": "pull",
         "flags": ["divergence"], "iteration": 7, "part": 1,
         "count": 0, "tripped": True, "where": "pull segment 1"},
        {"t": 1.3, "kind": "checkpoint_fallback",
         "path": "/tmp/c.npz", "fallback": "/tmp/c.npz.prev",
         "error": "leaf 0 CRC32 mismatch"},
    ]
    p = tmp_path / "ev.jsonl"
    write_log(p, events)
    r = run_summary(p)
    assert r.returncode == 0, r.stderr
    assert "watchdog (pull): clean over 20 iters" in r.stdout
    assert "WATCHDOG TRIPPED (pull): divergence at iteration 7, " \
           "part 1" in r.stdout
    assert "CHECKPOINT FALLBACK: /tmp/c.npz corrupt" in r.stdout


def test_malformed_health_event_fails_not_crashes(tmp_path):
    """A health digest with null/missing flags must produce a NAMED
    audit error, never a TypeError traceback."""
    events = [
        {"t": 1.0, "kind": "run_start", "app": "pagerank"},
        {"t": 1.1, "kind": "health", "engine": "pull",
         "tripped": True},
    ]
    p = tmp_path / "ev.jsonl"
    write_log(p, events)
    r = run_summary(p)
    assert r.returncode == 1
    assert "malformed health event" in r.stderr
    assert "Traceback" not in r.stderr


def test_undiagnosable_health_trip_fails(tmp_path):
    """A health_trip without flags/iteration/part/engine defeats the
    watchdog's purpose — the audit fails it."""
    events = [
        {"t": 1.0, "kind": "run_start", "app": "pagerank"},
        {"t": 1.1, "kind": "health_trip", "tripped": True},
    ]
    p = tmp_path / "ev.jsonl"
    write_log(p, events)
    r = run_summary(p)
    assert r.returncode == 1
    assert "health_trip" in r.stderr and "missing" in r.stderr


def test_multi_run_log_splits(tmp_path):
    events = GOOD + [
        {"t": 2.0, "kind": "config_start", "config": "sssp"},
        {"t": 2.1, "kind": "timed_run", "repeat": 0, "iters": 5,
         "seconds": 0.02},
    ]
    p = tmp_path / "ev.jsonl"
    write_log(p, events)
    r = run_summary(p)
    assert r.returncode == 0, r.stderr
    assert "== pagerank ==" in r.stdout and "== sssp ==" in r.stdout
    assert "timed runs: 1" in r.stdout


# -- round-12 multi-process merge + observatory events -----------------

def _proc_events(session, pid, app, tm0):
    return [
        {"t": 9.0, "tm": tm0, "pid": pid, "session": session,
         "kind": "run_start", "app": app},
        {"t": 9.1, "tm": tm0 + 0.1, "pid": pid, "session": session,
         "kind": "timed_run", "repeat": 0, "iters": 3,
         "seconds": 0.05},
    ]


def test_multi_process_log_merges_by_session_pid(tmp_path):
    """Two processes interleaved in ONE shared file (the heartbeat
    drill shape): events group per (session, pid) stream, each
    rendering under its own process header — never conflated into one
    run."""
    a = _proc_events("aaaa11112222", 100, "pagerank", 5.0)
    b = _proc_events("bbbb33334444", 200, "sssp", 50.0)
    # fully interleaved on disk
    merged = [a[0], b[0], a[1], b[1]]
    p = tmp_path / "ev.jsonl"
    write_log(p, merged)
    r = run_summary(p)
    assert r.returncode == 0, r.stderr
    out = r.stdout
    assert "-- process session=aaaa11112222 pid=100 --" in out
    assert "-- process session=bbbb33334444 pid=200 --" in out
    assert "== pagerank ==" in out and "== sssp ==" in out
    # each stream keeps its own timed run (not 2 in one run)
    assert out.count("timed runs: 1") == 2


def test_backwards_monotonic_tm_fails(tmp_path):
    """One (session, pid) stream whose monotonic clock goes BACKWARDS
    means two processes' events were conflated under one key — the
    merge audit fails."""
    a = _proc_events("aaaa11112222", 100, "pagerank", 5.0)
    a[1]["tm"] = 1.0                     # earlier than run_start's 5.0
    p = tmp_path / "ev.jsonl"
    write_log(p, a)
    r = run_summary(p)
    assert r.returncode == 1
    assert "monotonic tm went backwards" in r.stderr


def test_observatory_events_render(tmp_path):
    events = [
        {"t": 9.0, "kind": "run_start", "app": "pagerank"},
        {"t": 9.1, "kind": "calibration", "schema": 1,
         "session": "aaaa11112222", "platform": "tpu",
         "backend": "tpu", "ndev": 4, "grade": "canonical",
         "deviation": 1.05,
         "probe": {"gather_small_ns": 9.4}},
        {"t": 9.2, "kind": "phase_cost", "app": "pagerank",
         "phase": "gather", "median_s": 0.01, "mad_s": 0.001,
         "predicted_s": 0.009, "verdict": "ok"},
        {"t": 9.3, "kind": "drift", "app": "pagerank",
         "phase": "apply", "verdict": "drift_slow",
         "measured_s": 0.02, "predicted_s": 0.002, "ratio": 10.0,
         "session": "aaaa11112222"},
        {"t": 9.4, "kind": "debt_collected",
         "debt": "pair-dot-row-k-sweep"},
    ]
    p = tmp_path / "ev.jsonl"
    write_log(p, events)
    r = run_summary(p)
    assert r.returncode == 0, r.stderr
    out = r.stdout
    assert "calibration: session aaaa11112222" in out
    assert "grade=canonical" in out
    assert "phase attribution: 1 phase(s)" in out
    assert "DRIFT (pagerank/apply): drift_slow" in out
    assert "carried debt collected: pair-dot-row-k-sweep" in out


# -- round-11 elastic-recovery events ----------------------------------

ELASTIC = [
    {"t": 3.0, "kind": "run_start", "app": "pagerank"},
    {"t": 3.1, "kind": "topology_fault", "attempt": 0,
     "error": "InjectedDeviceLoss",
     "message": "devices [7] unavailable", "handled": True},
    {"t": 3.2, "kind": "mesh_shrink", "from_ndev": 8, "to_ndev": 4,
     "lost": [7], "parts": 8, "error": "InjectedDeviceLoss",
     "rebuild_seconds": 0.4},
    {"t": 3.3, "kind": "budget_reset", "reason": "mesh_shrink",
     "locked": 16, "per_iter_s": 0.05},
    {"t": 3.4, "kind": "replace", "engine": "pull", "from_ndev": 8,
     "to_ndev": 4, "iter": 3, "path": "/tmp/x.npz"},
    {"t": 3.5, "kind": "straggler", "boundary": 2, "peers": [1],
     "behind_s": 6.2},
]


def test_elastic_events_render(tmp_path):
    p = tmp_path / "ev.jsonl"
    write_log(p, ELASTIC)
    r = run_summary(p)
    assert r.returncode == 0, r.stderr
    out = r.stdout
    assert "TOPOLOGY FAULT: InjectedDeviceLoss" in out
    assert "re-placed" in out
    assert "MESH SHRINK: 8 -> 4 devices" in out
    assert "re-placement: checkpoint from a 8-device mesh" in out
    assert "budget rate reset (mesh_shrink" in out
    assert "straggler: peer(s) [1]" in out


def test_heartbeat_protocol_shrink_renders(tmp_path):
    """The multi-process shrink records process counts, not device
    counts — both spellings must render."""
    events = [
        {"t": 4.0, "kind": "run_start", "app": "pagerank"},
        {"t": 4.1, "kind": "mesh_shrink", "protocol": "heartbeat",
         "from_nproc": 2, "to_nproc": 1, "survivors": [0],
         "generation": 1},
    ]
    p = tmp_path / "ev.jsonl"
    write_log(p, events)
    r = run_summary(p)
    assert r.returncode == 0, r.stderr
    assert "MESH SHRINK: 2 -> 1 process" in r.stdout
    # the heartbeat record names SURVIVORS — rendering them under a
    # "lost" label would invert the diagnosis
    assert "survivors [0]" in r.stdout and "lost [0]" not in r.stdout


# -- round-13 per-part attribution + flight recorder -------------------

PARTS_STATS = {"t": 8.0, "kind": "iter_stats", "engine": "push",
               "iters": 3, "truncated": False, "frontier_last": 1,
               "frontier_max": 9, "frontier_sum": 14,
               "edges_sum": 40, "parts": 2, "parts_edges": [30, 10],
               "imbalance": 1.5}


def test_per_part_table_renders(tmp_path):
    events = [{"t": 7.9, "kind": "run_start", "app": "sssp"},
              PARTS_STATS]
    p = tmp_path / "ev.jsonl"
    write_log(p, events)
    r = run_summary(p)
    assert r.returncode == 0, r.stderr
    assert "per-part edges (P=2, imbalance 1.5" in r.stdout
    assert "part 0:" in r.stdout and "75.0%" in r.stdout


def test_per_part_sum_contradiction_fails(tmp_path):
    """Per-part totals not summing to the scalar counter means the
    imbalance table lies about the series it decomposes."""
    bad = dict(PARTS_STATS, parts_edges=[30, 11])
    p = tmp_path / "ev.jsonl"
    write_log(p, [{"t": 7.9, "kind": "run_start", "app": "sssp"},
                  bad])
    r = run_summary(p)
    assert r.returncode == 1
    assert "contradicts the counters" in r.stderr


def test_per_part_imbalance_contradiction_fails(tmp_path):
    bad = dict(PARTS_STATS, imbalance=3.0)
    p = tmp_path / "ev.jsonl"
    write_log(p, [{"t": 7.9, "kind": "run_start", "app": "sssp"},
                  bad])
    r = run_summary(p)
    assert r.returncode == 1
    assert "max/mean" in r.stderr


def test_heartbeat_and_flight_dump_render(tmp_path):
    events = [
        {"t": 8.0, "kind": "run_start", "app": "pagerank"},
        {"t": 8.1, "kind": "heartbeat", "boundary": 0, "nproc": 2,
         "waited_s": 0.05},
        {"t": 8.2, "kind": "heartbeat", "boundary": 1, "nproc": 2,
         "waited_s": 0.02},
        {"t": 8.3, "kind": "flight_dump", "path": "FLIGHT.json",
         "reason": "HealthError: tripped", "classification": "fatal",
         "events": 64},
    ]
    p = tmp_path / "ev.jsonl"
    write_log(p, events)
    r = run_summary(p)
    assert r.returncode == 0, r.stderr
    assert "heartbeats: 2 boundary sync(s), last boundary 1" \
        in r.stdout
    assert "FLIGHT RECORDER: 64 event(s) dumped to FLIGHT.json" \
        in r.stdout


def test_flight_mode_renders_dump(tmp_path):
    dump = {"schema": 1, "t": 1.0, "session": "aaaa11112222",
            "pid": 7, "reason": "HealthError: watchdog tripped",
            "classification": "fatal",
            "placement": {"nv": 100, "ne": 700, "num_parts": 2,
                          "ndev": 4},
            "health": {"kind": "health_trip", "engine": "pull",
                       "flags": ["nonfinite_state"], "iteration": 3,
                       "part": 1, "tripped": True},
            "calibration": None,
            "counts": {"segment": 3, "health_trip": 1},
            "events": [{"t": 0.9, "tm": 1.1, "kind": "segment",
                        "seconds": 0.1},
                       {"t": 1.0, "tm": 1.2, "kind": "health_trip",
                        "flags": ["nonfinite_state"]}]}
    p = tmp_path / "FLIGHT.json"
    p.write_text(json.dumps(dump))
    r = run_summary("-flight", p)
    assert r.returncode == 0, r.stderr
    assert "== FLIGHT" in r.stdout
    assert "reason: [fatal] HealthError" in r.stdout
    assert "last health word: nonfinite_state" in r.stdout
    assert "num_parts=2" in r.stdout and "ring: 2 event(s)" \
        in r.stdout


def test_flight_mode_rejects_non_dump(tmp_path):
    p = tmp_path / "notflight.json"
    p.write_text(json.dumps({"kind": "segment"}))
    r = run_summary("-flight", p)
    assert r.returncode == 1
    assert "not a flight-recorder dump" in r.stderr


def test_topology_fault_without_error_fails(tmp_path):
    events = [
        {"t": 5.0, "kind": "run_start", "app": "pagerank"},
        {"t": 5.1, "kind": "topology_fault", "handled": False},
    ]
    p = tmp_path / "ev.jsonl"
    write_log(p, events)
    r = run_summary(p)
    assert r.returncode == 1
    assert "topology_fault" in r.stderr


def test_non_shrinking_mesh_shrink_fails(tmp_path):
    """A mesh_shrink that does not shrink (or has no counts at all)
    is an undiagnosable topology change."""
    for bad in ({"t": 6.1, "kind": "mesh_shrink", "from_ndev": 4,
                 "to_ndev": 8},
                {"t": 6.1, "kind": "mesh_shrink", "lost": [7]}):
        p = tmp_path / "ev.jsonl"
        write_log(p, [{"t": 6.0, "kind": "run_start",
                       "app": "pagerank"}, bad])
        r = run_summary(p)
        assert r.returncode == 1
        assert "mesh_shrink" in r.stderr


def test_replace_without_mesh_pair_fails(tmp_path):
    events = [
        {"t": 7.0, "kind": "run_start", "app": "pagerank"},
        {"t": 7.1, "kind": "replace", "iter": 3},
    ]
    p = tmp_path / "ev.jsonl"
    write_log(p, events)
    r = run_summary(p)
    assert r.returncode == 1
    assert "replace" in r.stderr


# -- round-17 serving observability: metrics_snapshot cross-audit ------

def _snapshot_event(count=2, buckets=None, p50=0.01, p99=0.02,
                    kind="sssp"):
    return {
        "t": 1.5, "kind": "metrics_snapshot", "schema": 1,
        "counters": [
            {"name": "serve_slo_good_total",
             "labels": {"kind": kind}, "value": count},
            {"name": "serve_slo_violation_total",
             "labels": {"kind": kind}, "value": 0},
        ],
        "gauges": [
            {"name": "serve_queue_depth", "labels": {"kind": kind},
             "value": 0},
            {"name": "serve_slo_burn_rate", "labels": {"kind": kind},
             "value": 0.0},
        ],
        "histograms": [
            {"name": "serve_latency_seconds",
             "labels": {"kind": kind}, "count": count, "sum": 0.03,
             "min": 0.01, "max": 0.02, "p50": p50, "p90": p99,
             "p99": p99,
             "buckets": {"800": count} if buckets is None
             else buckets},
        ],
    }


def _qdone(qid, kind="sssp"):
    return {"t": 1.2 + qid * 0.01, "kind": "query_done", "qid": qid,
            "query_kind": kind, "iters": 3, "segments": 1,
            "latency_s": 0.015, "wait_s": 0.001}


def _serve_run(snapshot, n_done=2):
    evs = [{"t": 1.0, "kind": "run_start", "app": "serve"}]
    evs += [{"t": 1.1 + q * 0.01, "kind": "query_enqueue", "qid": q,
             "query_kind": "sssp"} for q in range(n_done)]
    evs += [_qdone(q) for q in range(n_done)]
    evs.append(snapshot)
    return evs


def test_metrics_snapshot_renders(tmp_path):
    p = tmp_path / "ev.jsonl"
    write_log(p, _serve_run(_snapshot_event()))
    r = run_summary(p)
    assert r.returncode == 0, r.stderr
    assert "metrics snapshot" in r.stdout
    assert "per-kind latency" in r.stdout
    assert "queue depth: sssp=0" in r.stdout
    assert "SLO burn: sssp" in r.stdout


def test_snapshot_overcount_contradiction_fails(tmp_path):
    """THE round-17 contradiction: a snapshot claiming MORE retired
    queries than query_done events exist is lying about the stream
    it aggregates."""
    p = tmp_path / "ev.jsonl"
    write_log(p, _serve_run(_snapshot_event(count=5,
                                            buckets={"800": 5})))
    r = run_summary(p)
    assert r.returncode == 1
    assert "contradicts the raw per-query stream" in r.stderr


def test_snapshot_bucket_count_mismatch_fails(tmp_path):
    p = tmp_path / "ev.jsonl"
    write_log(p, _serve_run(_snapshot_event(count=2,
                                            buckets={"800": 3})))
    r = run_summary(p)
    assert r.returncode == 1
    assert "bucket cells" in r.stderr


def test_snapshot_percentile_inversion_fails(tmp_path):
    p = tmp_path / "ev.jsonl"
    write_log(p, _serve_run(_snapshot_event(p50=0.05, p99=0.01)))
    r = run_summary(p)
    assert r.returncode == 1
    assert "p99" in r.stderr


def test_rotated_file_set_consumed_as_one_stream(tmp_path):
    """A size-rotated EventLog's .1 + live generations render as ONE
    run: the snapshot in the live file audits against query_done
    events that rotated into the older generation."""
    evs = _serve_run(_snapshot_event())
    split = len(evs) - 1
    p = tmp_path / "ev.jsonl"
    write_log(Path(str(p) + ".1"), evs[:split])
    write_log(p, [{"t": 1.45, "kind": "log_rotate",
                   "path": str(p), "rotation": 1,
                   "rotate_bytes": 1000, "generations": 2}]
              + evs[split:])
    r = run_summary(p)
    assert r.returncode == 0, r.stderr
    assert "log rotated" in r.stdout
    assert "metrics snapshot" in r.stdout
    # the set is what saves it: the live generation's content ALONE
    # (no .1 sibling) overcounts — the snapshot's retirements rotated
    # into the older file — and the audit fails it
    alone = tmp_path / "alone.jsonl"
    alone.write_text(p.read_text())
    r_alone = run_summary(alone)
    assert r_alone.returncode == 1
    assert "contradicts the raw per-query stream" in r_alone.stderr


def test_snapshot_malformed_gauge_fails_not_crashes(tmp_path):
    """A gauge missing its value must produce a NAMED audit error,
    never a TypeError traceback (the malformed-health-event rule
    applied to snapshots)."""
    snap = _snapshot_event()
    snap["gauges"][0].pop("value")
    snap["gauges"][1]["value"] = "hot"
    p = tmp_path / "ev.jsonl"
    write_log(p, _serve_run(snap))
    r = run_summary(p)
    assert r.returncode == 1
    assert "non-numeric value" in r.stderr
    assert "Traceback" not in r.stderr


def test_snapshot_overcount_disarmed_by_rotation_truncation(
        tmp_path):
    """A long-lived trail whose oldest generations were DROPPED by
    rotation (rotation count > kept generations) legitimately shows
    fewer query_done events than the cumulative registry count — the
    overcount audit must stand down, while the self-consistency
    checks (bucket cells, p99 >= p50) stay armed."""
    evs = _serve_run(_snapshot_event(count=5, buckets={"800": 5}))
    evs.insert(1, {"t": 1.05, "kind": "log_rotate",
                   "path": "ev.jsonl", "rotation": 3,
                   "rotate_bytes": 1000, "generations": 2})
    p = tmp_path / "ev.jsonl"
    write_log(p, evs)
    r = run_summary(p)
    assert r.returncode == 0, r.stderr
    # rotations within the kept window keep the audit armed
    evs[1]["rotation"] = 2
    write_log(p, evs)
    r = run_summary(p)
    assert r.returncode == 1
    assert "contradicts the raw per-query stream" in r.stderr


# ---------------------------------------------------------------------
# round 18 (serving fleet, lux_tpu/fleet.py): the resilience trail


def _fleet_run(extra=()):
    base = {"pid": 1, "session": "s"}
    evs = [
        dict(base, t=1.0, tm=1.0, kind="run_start", schema=1,
             app="fleet"),
        dict(base, t=1.01, tm=1.01, kind="replica_up", replica="r0",
             remote=False, capacity=2),
        dict(base, t=1.02, tm=1.02, kind="replica_up", replica="r1",
             remote=False, capacity=2),
        dict(base, t=1.1, tm=1.1, kind="query_enqueue", qid=0,
             query_kind="sssp"),
        dict(base, t=1.15, tm=1.15, kind="query_enqueue", qid=1,
             query_kind="sssp"),
        dict(base, t=1.5, tm=1.5, kind="replica_lost", replica="r1",
             error="InjectedWorkerKill", message="boom", inflight=1),
        dict(base, t=1.52, tm=1.52, kind="brownout", level=1,
             capacity_frac=0.5, min_priority=1),
        dict(base, t=1.55, tm=1.55, kind="failover", qid=1,
             query_kind="sssp", from_replica="r1", to_replica="r0",
             attempt=1, backoff_s=0.01),
        dict(base, t=2.0, tm=2.0, kind="query_done", qid=0,
             query_kind="sssp", iters=4, segments=2, latency_s=0.9,
             wait_s=0.1, converged=True, replica="r0"),
        dict(base, t=2.1, tm=2.1, kind="query_done", qid=1,
             query_kind="sssp", iters=4, segments=2, latency_s=1.0,
             wait_s=0.2, converged=True, replica="r0"),
        dict(base, t=2.2, tm=2.2, kind="run_done", seconds=1.2,
             iters=8),
    ]
    evs.extend(extra)
    evs.sort(key=lambda e: e["t"])
    return evs


def test_fleet_trail_renders_clean(tmp_path):
    p = tmp_path / "ev.jsonl"
    write_log(p, _fleet_run())
    r = run_summary(p)
    assert r.returncode == 0, r.stderr
    assert "replicas: 2 up, 1 lost (r1)" in r.stdout
    assert "failovers: 1 re-dispatch(es) over 1 qid(s)" in r.stdout
    assert "BROWNOUT level=1" in r.stdout


def test_double_query_done_fails(tmp_path):
    """Exactly-once retirement: a qid retiring twice must fail the
    audit — the duplicate answer would double-count every SLO
    series."""
    dup = {"pid": 1, "session": "s", "t": 2.15, "tm": 2.15,
           "kind": "query_done", "qid": 1, "query_kind": "sssp",
           "iters": 4, "segments": 2, "latency_s": 1.05,
           "wait_s": 0.2, "converged": True, "replica": "r0"}
    p = tmp_path / "ev.jsonl"
    write_log(p, _fleet_run([dup]))
    r = run_summary(p)
    assert r.returncode == 1
    assert "retired 2 times" in r.stderr
    assert "exactly-once" in r.stderr


def test_query_done_after_shed_fails(tmp_path):
    """A shed query must never retire: the typed rejection and a
    served answer for one qid contradict each other."""
    shed = {"pid": 1, "session": "s", "t": 1.9, "tm": 1.9,
            "kind": "query_shed", "qid": 1, "query_kind": "sssp",
            "tenant": "free", "priority": 0, "reason": "brownout"}
    p = tmp_path / "ev.jsonl"
    write_log(p, _fleet_run([shed]))
    r = run_summary(p)
    assert r.returncode == 1
    assert "SHED" in r.stderr and "never retire" in r.stderr


def test_undiagnosed_replica_lost_fails(tmp_path):
    """A replica_lost with in-flight queries but no failover or shed
    accounting for them is an UNDIAGNOSED loss — queries vanished
    without a trail."""
    evs = [e for e in _fleet_run()
           if e["kind"] not in ("failover",)]
    p = tmp_path / "ev.jsonl"
    write_log(p, evs)
    r = run_summary(p)
    assert r.returncode == 1
    assert "undiagnosed loss" in r.stderr
    # inflight=0 needs no diagnosis (the replica died idle)
    evs2 = _fleet_run()
    evs2 = [e for e in evs2 if e["kind"] != "failover"]
    for e in evs2:
        if e["kind"] == "replica_lost":
            e["inflight"] = 0
    write_log(p, evs2)
    r = run_summary(p)
    assert r.returncode == 0, r.stderr


def test_malformed_shed_and_lost_fail(tmp_path):
    bad_shed = {"pid": 1, "session": "s", "t": 1.9, "tm": 1.9,
                "kind": "query_shed", "qid": 7}   # no kind/reason
    p = tmp_path / "ev.jsonl"
    write_log(p, _fleet_run([bad_shed]))
    r = run_summary(p)
    assert r.returncode == 1
    assert "query_shed missing" in r.stderr
    evs = _fleet_run()
    for e in evs:
        if e["kind"] == "replica_lost":
            del e["error"]
    write_log(p, evs)
    r = run_summary(p)
    assert r.returncode == 1
    assert "replica_lost without" in r.stderr


# ---------------------------------------------------------------------
# round 20: the live-graph mutation/epoch/compaction/cache trail
# (lux_tpu/livegraph.py) — torn-epoch, compaction-bracket and
# replay-regression audits


def _live_run(extra=(), drop=()):
    base = {"pid": 1, "session": "s"}
    evs = [
        dict(base, t=1.0, tm=1.0, kind="run_start", schema=1,
             app="live"),
        dict(base, t=1.1, tm=1.1, kind="query_enqueue", qid=0,
             query_kind="sssp", source=3, tenant="default",
             priority=0, queued=1),
        # WAL-backed publishes always carry the wal path (livegraph
        # wal_kw) — the replay-regression audit pairs on it
        dict(base, t=1.2, tm=1.2, kind="mutation", edges=4, epoch=1,
             delta_count=4, occupancy=0.25, wal="/tmp/g.wal"),
        dict(base, t=1.2, tm=1.21, kind="epoch_advance",
             from_epoch=0, to_epoch=1, wal="/tmp/g.wal"),
        dict(base, t=1.3, tm=1.3, kind="query_enqueue", qid=1,
             query_kind="sssp", source=3, tenant="default",
             priority=0, queued=1),
        dict(base, t=1.4, tm=1.4, kind="query_start", qid=0,
             query_kind="sssp", col=0, wait_s=0.1, epoch=0),
        dict(base, t=1.5, tm=1.5, kind="query_done", qid=0,
             query_kind="sssp", col=0, iters=4, segments=2,
             latency_s=0.5, wait_s=0.1, converged=True, epoch=0,
             answer_epoch=0),
        dict(base, t=1.6, tm=1.6, kind="query_done", qid=1,
             query_kind="sssp", col=-1, iters=4, segments=0,
             latency_s=0.01, wait_s=0.01, converged=True, epoch=1,
             answer_epoch=1, cached=True),
        dict(base, t=1.7, tm=1.7, kind="compact_start", epoch=1,
             generation=1, delta_count=4, occupancy=0.25),
        dict(base, t=1.8, tm=1.8, kind="compact_done", epoch=1,
             generation=1, folded=4, ne=904),
        dict(base, t=1.9, tm=1.9, kind="wal_replay",
             path="/tmp/g.wal", records=6, epoch=1, generation=1,
             truncated_bytes=0, delta_count=0),
        dict(base, t=2.0, tm=2.0, kind="run_done", seconds=1.0,
             iters=8),
    ]
    evs = [e for e in evs if e["kind"] not in drop]
    evs.extend(extra)
    evs.sort(key=lambda e: e["tm"])
    return evs


def test_live_trail_renders_clean(tmp_path):
    p = tmp_path / "ev.jsonl"
    write_log(p, _live_run())
    r = run_summary(p)
    assert r.returncode == 0, r.stderr
    assert "live graph: 4 edge(s) over 1 mutation batch(es)" \
        in r.stdout
    assert "compaction: 1 completed, 4 edge(s) folded" in r.stdout
    assert "WAL replay: 6 record(s)" in r.stdout
    assert "answer cache: 1 of 2 served cached" in r.stdout


def test_torn_epoch_answer_fails(tmp_path):
    """THE snapshot-isolation audit: a query answered at a different
    epoch than its admission pinned is a torn read published as an
    answer."""
    evs = _live_run()
    for e in evs:
        if e["kind"] == "query_done" and e["qid"] == 0:
            e["answer_epoch"] = 1       # admitted at 0, answered at 1
    p = tmp_path / "ev.jsonl"
    write_log(p, evs)
    r = run_summary(p)
    assert r.returncode == 1
    assert "TORN-EPOCH" in r.stderr


def test_epoch_without_answer_epoch_fails(tmp_path):
    evs = _live_run()
    for e in evs:
        if e["kind"] == "query_done" and e["qid"] == 0:
            del e["answer_epoch"]
    p = tmp_path / "ev.jsonl"
    write_log(p, evs)
    r = run_summary(p)
    assert r.returncode == 1
    assert "no answer_epoch" in r.stderr


def test_compact_done_without_start_fails(tmp_path):
    p = tmp_path / "ev.jsonl"
    write_log(p, _live_run(drop=("compact_start",)))
    r = run_summary(p)
    assert r.returncode == 1
    assert "without a preceding compact_start" in r.stderr


def test_open_compaction_renders_not_fails(tmp_path):
    """A compact_start with no done is the COMPACT_CRASH signature —
    rendered as open, never an audit failure (recovery's job)."""
    p = tmp_path / "ev.jsonl"
    write_log(p, _live_run(drop=("compact_done", "wal_replay")))
    r = run_summary(p)
    assert r.returncode == 0, r.stderr
    assert "OPEN (crashed mid-compaction)" in r.stdout


def test_replay_epoch_regression_fails(tmp_path):
    """A WAL replay that comes up at a lower epoch than the trail
    already published means acknowledged mutations vanished."""
    evs = _live_run()
    for e in evs:
        if e["kind"] == "wal_replay":
            e["epoch"] = 0              # published epoch was 1
    p = tmp_path / "ev.jsonl"
    write_log(p, evs)
    r = run_summary(p)
    assert r.returncode == 1
    assert "epoch regression" in r.stderr


def test_replay_of_other_log_not_a_regression(tmp_path):
    """REGRESSION: the in-stream audit kept one path-BLIND epoch
    high-water mark, so a replay of an UNRELATED log legitimately
    recovering a lower epoch (two live graphs beside each other, or
    a recovery drill beside a live bench) failed a clean trail —
    publishes and replays pair on the wal path, exactly like the
    cross-process audit_wal_replays."""
    evs = _live_run()
    for e in evs:
        if e["kind"] == "wal_replay":
            e["path"] = "/tmp/other.wal"
            e["epoch"] = 0              # log A published epoch 1
    p = tmp_path / "ev.jsonl"
    write_log(p, evs)
    r = run_summary(p)
    assert r.returncode == 0, r.stderr


def _crash_recovery_streams(replay_epoch):
    """Process A (pid 1) publishes up to epoch 2 on a WAL and
    crashes; process B (pid 2) recovers the SAME wal later.  The
    per-run walk can never pair these — only audit_wal_replays."""
    a = {"pid": 1, "session": "aaaa"}
    b = {"pid": 2, "session": "bbbb"}
    return [
        dict(a, t=1.0, tm=1.0, kind="run_start", schema=1,
             app="live"),
        dict(a, t=1.2, tm=1.2, kind="mutation", edges=4, epoch=1,
             delta_count=4, occupancy=0.25, wal="/tmp/g.wal"),
        dict(a, t=1.21, tm=1.21, kind="epoch_advance", from_epoch=0,
             to_epoch=1, wal="/tmp/g.wal"),
        dict(a, t=1.3, tm=1.3, kind="mutation", edges=2, epoch=2,
             delta_count=6, occupancy=0.375, wal="/tmp/g.wal"),
        dict(a, t=1.31, tm=1.31, kind="epoch_advance", from_epoch=1,
             to_epoch=2, wal="/tmp/g.wal"),
        # process A crashes here (no run_done) — B recovers
        dict(b, t=5.0, tm=0.1, kind="run_start", schema=1,
             app="live"),
        dict(b, t=5.1, tm=0.2, kind="wal_replay", path="/tmp/g.wal",
             records=6, epoch=replay_epoch, generation=1,
             truncated_bytes=0, delta_count=6),
        dict(b, t=5.2, tm=0.3, kind="run_done", seconds=0.2,
             iters=0),
    ]


def test_cross_process_replay_epoch_regression_fails(tmp_path):
    """THE crash shape the audit was built for: publisher and
    recoverer are different processes (different (session, pid)
    streams), so only the cross-process pairing on the WAL path can
    see acknowledged epoch-2 mutations vanish."""
    p = tmp_path / "ev.jsonl"
    write_log(p, _crash_recovery_streams(replay_epoch=1))
    r = run_summary(p)
    assert r.returncode == 1
    assert "cross-process replay-after-crash" in r.stderr


def test_cross_process_replay_clean(tmp_path):
    """The same two-process shape with a FULL recovery (epoch 2)
    audits clean — and a replay at a HIGHER epoch (another process
    kept appending) is never a regression."""
    p = tmp_path / "ev.jsonl"
    write_log(p, _crash_recovery_streams(replay_epoch=2))
    r = run_summary(p)
    assert r.returncode == 0, r.stderr
    write_log(p, _crash_recovery_streams(replay_epoch=3))
    r = run_summary(p)
    assert r.returncode == 0, r.stderr


# ---------------------------------------------------------------------
# round 21: the mutation-algebra trail — re-seed pairing + scheduler
# economics audits (lux_tpu/livegraph.py deletions/reweights +
# CompactionScheduler)


def _algebra_run(extra=(), drop=(), patch=None):
    base = {"pid": 1, "session": "s"}
    evs = [
        dict(base, t=1.0, tm=1.0, kind="run_start", schema=1,
             app="live"),
        dict(base, t=1.1, tm=1.1, kind="mutation", edges=4, epoch=1,
             delta_count=4, occupancy=0.25, wal="/tmp/g.wal"),
        dict(base, t=1.11, tm=1.11, kind="epoch_advance",
             from_epoch=0, to_epoch=1, wal="/tmp/g.wal"),
        dict(base, t=1.2, tm=1.2, kind="mutation", op="delete",
             edges=1, epoch=2, delta_count=5, occupancy=0.3125,
             wal="/tmp/g.wal"),
        dict(base, t=1.21, tm=1.21, kind="epoch_advance",
             from_epoch=1, to_epoch=2, wal="/tmp/g.wal"),
        dict(base, t=1.3, tm=1.3, kind="reseed", epoch=2, cone=37,
             cone_frac=0.1445, fallback=False, anti=1,
             wal="/tmp/g.wal"),
        dict(base, t=1.4, tm=1.4, kind="compact_scheduled",
             action="compact", reason="anti_monotone",
             occupancy=0.3125, threshold=0.5, delta_count=5,
             anti_pending=1, drag_ns=44.8, drag_source="modeled",
             admitted=0, pins=0, burn=0.0),
        dict(base, t=1.5, tm=1.5, kind="compact_start", epoch=2,
             generation=1, delta_count=5, occupancy=0.3125),
        dict(base, t=1.6, tm=1.6, kind="compact_done", epoch=2,
             generation=1, folded=5, ne=903),
        dict(base, t=2.0, tm=2.0, kind="run_done", seconds=1.0,
             iters=4),
    ]
    evs = [e for e in evs if e["kind"] not in drop]
    if patch:
        for e in evs:
            patch(e)
    evs.extend(extra)
    evs.sort(key=lambda e: e["tm"])
    return evs


def test_algebra_trail_renders_clean(tmp_path):
    p = tmp_path / "ev.jsonl"
    write_log(p, _algebra_run())
    r = run_summary(p)
    assert r.returncode == 0, r.stderr
    assert "(1 delete, 0 reweight batch(es))" in r.stdout
    assert ("re-seed: 1 anti-monotone revalidation(s), peak cone 37 "
            "vertex(ices), 0 full-recompute fallback(s)") in r.stdout
    assert ("compaction scheduler: 1 fold(s) scheduled "
            "(1 anti_monotone)") in r.stdout


def test_reseed_without_anti_publish_fails(tmp_path):
    """A re-seed with no preceding delete/reweight publish (or WAL
    replay) on its log has nothing to revalidate — the trail claims
    a repair it never journaled a cause for."""
    def patch(e):
        if e["kind"] == "mutation" and e.get("op") == "delete":
            del e["op"]                 # now a plain append
    p = tmp_path / "ev.jsonl"
    write_log(p, _algebra_run(patch=patch))
    r = run_summary(p)
    assert r.returncode == 1
    assert "without any preceding delete/reweight publish" \
        in r.stderr


def test_reseed_after_wal_replay_ok(tmp_path):
    """Recovery re-seeds anti ops it REPLAYED rather than published
    — a wal_replay on the same path justifies the re-seed, exactly
    like the cross-process epoch audit pairs on the log path."""
    base = {"pid": 2, "session": "r"}
    evs = [
        dict(base, t=1.0, tm=1.0, kind="run_start", schema=1,
             app="live"),
        dict(base, t=1.1, tm=1.1, kind="wal_replay",
             path="/tmp/g.wal", records=5, epoch=2, generation=0,
             truncated_bytes=0, delta_count=5),
        dict(base, t=1.2, tm=1.2, kind="reseed", epoch=2, cone=12,
             cone_frac=0.05, fallback=True, anti=1,
             wal="/tmp/g.wal"),
        dict(base, t=2.0, tm=2.0, kind="run_done", seconds=1.0,
             iters=0),
    ]
    p = tmp_path / "ev.jsonl"
    write_log(p, evs)
    r = run_summary(p)
    assert r.returncode == 0, r.stderr
    assert "1 full-recompute fallback(s)" in r.stdout


def test_compact_scheduled_missing_economics_fails(tmp_path):
    def patch(e):
        if e["kind"] == "compact_scheduled":
            del e["drag_ns"]
            del e["drag_source"]
    p = tmp_path / "ev.jsonl"
    write_log(p, _algebra_run(patch=patch))
    r = run_summary(p)
    assert r.returncode == 1
    assert "cannot justify itself" in r.stderr


# ---------------------------------------------------------------------
# round 24: the self-healing trail (respawn / quarantine / canary +
# admission-journal recovery records)


def _heal_extra():
    """The healthy resurrection + journal-recovery tail grafted onto
    the round-18 fleet trail: r1's loss is followed by a PASSING
    canary, the respawn, the journal replay, and qid 1's recovered
    re-dispatch (whose second query_done is the legitimate
    at-least-once-compute seam)."""
    base = {"pid": 1, "session": "s"}
    return [
        dict(base, t=1.60, tm=1.60, kind="canary", replica="r1",
             qid=90, query_kind="components", ok=True),
        dict(base, t=1.65, tm=1.65, kind="replica_respawn",
             replica="r1", attempt=1, backoff_s=0.01,
             canary_ok=True),
        dict(base, t=1.68, tm=1.68, kind="journal_truncate",
             path="/tmp/g.lux.journal", torn_bytes=24, open=1,
             retired=1),
        dict(base, t=1.70, tm=1.70, kind="journal_replay",
             path="/tmp/g.lux.journal", replayed=1, retired=1,
             torn_bytes=24),
        dict(base, t=1.75, tm=1.75, kind="query_enqueue", qid=1,
             query_kind="sssp", recovered=True),
        dict(base, t=2.15, tm=2.15, kind="query_done", qid=1,
             query_kind="sssp", iters=4, segments=2, latency_s=1.0,
             wait_s=0.2, converged=True, replica="r0"),
    ]


def test_self_healing_trail_renders_clean(tmp_path):
    p = tmp_path / "ev.jsonl"
    write_log(p, _fleet_run(extra=_heal_extra()))
    r = run_summary(p)
    assert r.returncode == 0, r.stderr
    assert ("self-healing: 1 respawn(s), 0 quarantine(s), "
            "canaries 1/1 passed") in r.stdout
    assert "admission journal torn tail truncated: 24 byte(s)" \
        in r.stdout
    assert ("admission journal replay: 1 re-dispatched, "
            "1 already retired (torn 24 B)") in r.stdout


def test_recovered_qid_two_dones_pass_three_fail(tmp_path):
    """ONE extra query_done per recovered qid is the legitimate
    at-least-once-compute seam (the crash interposed between the
    runner's retire and delivery); a THIRD is still a duplicate."""
    base = {"pid": 1, "session": "s"}
    third = dict(base, t=2.2, tm=2.2, kind="query_done", qid=1,
                 query_kind="sssp", iters=4, segments=2,
                 latency_s=1.1, wait_s=0.2, converged=True,
                 replica="r0")
    p = tmp_path / "ev.jsonl"
    write_log(p, _fleet_run(extra=_heal_extra() + [third]))
    r = run_summary(p)
    assert r.returncode == 1
    assert "qid=1 retired 3 times" in r.stderr


def test_respawn_without_loss_fails(tmp_path):
    """A resurrection of a replica that never died is a trail that
    cannot be trusted — r0 was never lost."""
    base = {"pid": 1, "session": "s"}
    extra = [
        dict(base, t=1.60, tm=1.60, kind="canary", replica="r0",
             qid=90, query_kind="components", ok=True),
        dict(base, t=1.65, tm=1.65, kind="replica_respawn",
             replica="r0", attempt=1, backoff_s=0.01,
             canary_ok=True),
    ]
    p = tmp_path / "ev.jsonl"
    write_log(p, _fleet_run(extra=extra))
    r = run_summary(p)
    assert r.returncode == 1
    assert "without a preceding replica_lost" in r.stderr


def test_respawn_with_failed_canary_fails(tmp_path):
    """Routing re-entry with a FAILED (or missing) canary since the
    loss means unproven answers could route."""
    base = {"pid": 1, "session": "s"}
    extra = [
        dict(base, t=1.60, tm=1.60, kind="canary", replica="r1",
             qid=90, query_kind="components", ok=False,
             reason="oracle_mismatch"),
        dict(base, t=1.65, tm=1.65, kind="replica_respawn",
             replica="r1", attempt=1, backoff_s=0.01,
             canary_ok=True),
    ]
    p = tmp_path / "ev.jsonl"
    write_log(p, _fleet_run(extra=extra))
    r = run_summary(p)
    assert r.returncode == 1
    assert "without a passing canary since its loss" in r.stderr


def test_recovered_enqueue_without_replay_fails(tmp_path):
    base = {"pid": 1, "session": "s"}
    extra = [
        dict(base, t=1.75, tm=1.75, kind="query_enqueue", qid=1,
             query_kind="sssp", recovered=True),
        dict(base, t=2.15, tm=2.15, kind="query_done", qid=1,
             query_kind="sssp", iters=4, segments=2, latency_s=1.0,
             wait_s=0.2, converged=True, replica="r0"),
    ]
    p = tmp_path / "ev.jsonl"
    write_log(p, _fleet_run(extra=extra))
    r = run_summary(p)
    assert r.returncode == 1
    assert "recovered query_enqueue" in r.stderr
    assert "no preceding journal_replay" in r.stderr


def test_malformed_canary_fails(tmp_path):
    base = {"pid": 1, "session": "s"}
    extra = [dict(base, t=1.60, tm=1.60, kind="canary",
                  replica="r1", qid=90, query_kind="components")]
    p = tmp_path / "ev.jsonl"
    write_log(p, _fleet_run(extra=extra))
    r = run_summary(p)
    assert r.returncode == 1
    assert "canary without its" in r.stderr


def test_malformed_quarantine_fails(tmp_path):
    base = {"pid": 1, "session": "s"}
    extra = [dict(base, t=1.60, tm=1.60, kind="replica_quarantine",
                  replica="r1", deaths=3, window_s=60.0)]
    p = tmp_path / "ev.jsonl"
    write_log(p, _fleet_run(extra=extra))
    r = run_summary(p)
    assert r.returncode == 1
    assert "replica_quarantine without" in r.stderr


def test_quarantine_renders_reason_mix(tmp_path):
    base = {"pid": 1, "session": "s"}
    extra = [dict(base, t=1.60, tm=1.60, kind="replica_quarantine",
                  replica="r1", reason="flap", deaths=3,
                  window_s=60.0)]
    p = tmp_path / "ev.jsonl"
    write_log(p, _fleet_run(extra=extra))
    r = run_summary(p)
    assert r.returncode == 0, r.stderr
    assert ("self-healing: 0 respawn(s), 1 quarantine(s) (1 flap), "
            "canaries 0/0 passed") in r.stdout
