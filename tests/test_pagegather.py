"""Paged two-level gather (ops/pagegather.py): plan-resolution oracle
(every edge's (page, slot, lane) decodes back to its original index;
padding hits the identity), device-vs-oracle agreement, paged-vs-flat
engine equivalence for all four apps on 1 and 8 virtual devices
(stats/health variants and a batched config included), the scalemodel
break-even pin, the ledger pricing, and the observe phase model.

Bitwise discipline: min/max reductions (sssp, cc) are order-
independent, so paged-vs-flat is ``array_equal`` outright.  Sum
reductions re-associate between layouts by construction, so the exact
proof runs on sub-2^24 integer-valued states where f32 sums are exact
in ANY order — the repo's established trick
(ops/pairs.stacked_pair_dot_numpy); the real pagerank/colfilter apps
are additionally held to tight allclose.
"""

import numpy as np
import pytest

from lux_tpu.graph import Graph, ShardedGraph
from lux_tpu.ops.pagegather import (W, decode_plan, paged_dot_numpy,
                                    paged_reduce_numpy,
                                    plan_owner_paged, plan_paged_gather,
                                    resolve_gather)


def _skewed_graph(seed, nv, ne, weighted=False):
    rng = np.random.default_rng(seed)
    src = (rng.zipf(1.3, ne) - 1) % nv
    dst = (rng.zipf(1.2, ne) - 1) % nv
    w = rng.integers(1, 6, ne).astype(np.float32) if weighted else None
    return Graph.from_edges(src.astype(np.uint32),
                            dst.astype(np.uint32), nv, weights=w)


def full_oracle(src_slot, dst_local, state, vpad):
    out = np.zeros(vpad)
    for s, d in zip(src_slot, dst_local):
        out[d] += state[s]
    return out


# ---------------------------------------------------------------------
# plan builder oracle


@pytest.mark.parametrize("num_parts", [1, 3])
def test_plan_resolves_every_edge(num_parts):
    """Every edge's (page, slot, lane) decodes back to its original
    (src, dst) index — multiset equality per part — and dead lanes
    (rel == -1) are exactly the padding."""
    g = _skewed_graph(3, 4 * W, 7000)
    sg = ShardedGraph.build(g, num_parts, vpad_align=128)
    pp = plan_paged_gather(sg)
    assert pp.stats["ne"] == g.ne
    for p in range(num_parts):
        nep = int(sg.ne_part[p])
        src, dst = decode_plan(pp, p)
        assert len(src) == nep          # total coverage, no drops
        want = sorted(zip(sg.src_slot[p, :nep].tolist(),
                          sg.dst_local[p, :nep].tolist()))
        got = sorted(zip(src.tolist(), dst.tolist()))
        assert got == want
        # every live lane's page slot is in range of the dedup list
        sl = pp.slot_lane[p]
        live = pp.rel_dst[p] != -1
        slots = (sl[:, 0] >> np.uint32(7)).astype(np.int64)
        used = slots[live.any(axis=1)]
        assert used.size == 0 or used.max() < pp.n_pages


def test_plan_padding_hits_identity():
    """Dead lanes and dead rows contribute the reduce identity: the
    oracle partial over a plan equals the full flat reduce."""
    g = _skewed_graph(5, 3 * W, 5000)
    sg = ShardedGraph.build(g, 2, vpad_align=128)
    pp = plan_paged_gather(sg)
    state = np.random.default_rng(0).random(sg.num_parts * sg.vpad)
    for p in range(sg.num_parts):
        nep = int(sg.ne_part[p])
        want = full_oracle(sg.src_slot[p, :nep],
                           sg.dst_local[p, :nep], state, sg.vpad)
        got = paged_reduce_numpy(pp, p, state)[:sg.vpad]
        np.testing.assert_allclose(got, want, rtol=1e-9)


def test_plan_stats_recorded():
    from lux_tpu.ops.pagegather import plan_owner_paged, plan_paged_stats

    g = _skewed_graph(7, 4 * W, 9000)
    sg = ShardedGraph.build(g, 2, vpad_align=128)
    pp = plan_paged_gather(sg)
    st = pp.stats
    assert st["rows"] >= 1 and st["fill"] == pytest.approx(
        st["ne"] / st["rows"])
    assert st["page_ratio"] == pytest.approx(
        st["unique_pages"] * W / st["ne"])
    # the padded leading dims never collide with the reshaped state
    # table's row count (the audit operand-shape disambiguation)
    n_src_rows = sg.num_parts * sg.vpad // W
    assert pp.Rp != n_src_rows and pp.n_pages != n_src_rows
    # the counting-only fast path (what gather="auto" resolves from
    # without materializing plan arrays) must agree with the full
    # build EXACTLY, dense and owner
    assert plan_paged_stats(sg) == st
    assert plan_paged_stats(sg, exchange="owner") \
        == plan_owner_paged(sg).stats


def test_owner_plan_resolves_every_edge():
    """Owner plan: per SOURCE part, pages within the own shard and
    GLOBAL destination tiles — decoded edges must partition the whole
    edge set by source part."""
    g = _skewed_graph(11, 4 * W, 6000)
    sg = ShardedGraph.build(g, 2, vpad_align=128)
    pp = plan_owner_paged(sg)
    assert pp.n_tiles == sg.num_parts * sg.vpad // W
    want_all = []
    for r in range(sg.num_parts):
        nep = int(sg.ne_part[r])
        slot = sg.src_slot[r, :nep].astype(np.int64)
        dst = sg.dst_local[r, :nep].astype(np.int64)
        s = slot // sg.vpad
        gdst = r * sg.vpad + dst        # global tile*W + rel encoding
        want_all += list(zip(s.tolist(), (slot - s * sg.vpad).tolist(),
                             gdst.tolist()))
    got_all = []
    for p in range(sg.num_parts):
        src, dst = decode_plan(pp, p)
        got_all += [(p, int(a), int(b)) for a, b in zip(src, dst)]
    assert sorted(got_all) == sorted(want_all)


# ---------------------------------------------------------------------
# scalemodel break-even pin (the round-15 recorded threshold)


def test_page_break_even_pinned():
    from lux_tpu import scalemodel as sm
    # modeled row cost: measured pair-row machinery + the 128-lane
    # shuffle
    assert sm.PAGED_ROW_NS == pytest.approx(150.0 + 128 * 0.38)
    # small-table scalar break-even at page_ratio 1: fill >= 23
    assert sm.page_break_even_fill() == 23
    # past the big-table cliff the flat rate is worse, so the paged
    # path pays at lower fill
    assert sm.page_break_even_fill(table_bytes=200e6) == 14
    # a page ratio so high the dedup'd fetch alone exceeds the flat
    # rate can never win
    assert sm.page_break_even_fill(page_ratio=100.0) >= 1 << 30
    # threshold in the other direction: the unique-page ratio below
    # which full rows beat the flat gather
    r = sm.page_break_even_ratio(128.0)
    assert r == pytest.approx(
        (sm.GATHER_SMALL_NS - sm.PAGED_ROW_NS / 128.0)
        / (sm.PAGE_ROW_FETCH_NS / 128.0))
    assert sm.page_gather_ns(1.0, 128.0) < sm.GATHER_SMALL_NS
    assert sm.page_gather_ns(1.0, 4.0) > sm.GATHER_SMALL_NS


def test_resolve_gather_auto():
    from lux_tpu import scalemodel as sm

    dense = dict(page_ratio=0.5, fill=100.0)
    sparse = dict(page_ratio=3.0, fill=2.0)
    assert resolve_gather("auto", dense, 1 << 20) == "paged"
    assert resolve_gather("auto", sparse, 1 << 20) == "flat"
    assert resolve_gather("paged", sparse, 1 << 20) == "paged"
    assert resolve_gather("flat", dense, 1 << 20) == "flat"
    with pytest.raises(ValueError, match="gather"):
        resolve_gather("bogus", dense, 1)
    # owner engines compare against the owner scan rate (~11.9
    # ns/slot), NOT the big-table flat cliff (14.6): a plan whose
    # modeled cost lands between the two must stay flat on an owner
    # engine (it would regress vs the scan) while beating the flat
    # gather past the cliff
    fill_mid = dict(page_ratio=0.1, fill=15.0, padded_fill=15.0)
    mid = sm.page_gather_ns(0.1, 15.0)
    assert sm.OWNER_SLOT_NS * 1.2 < mid < sm.GATHER_BIG_NS
    big = int(200e6)
    assert resolve_gather("auto", fill_mid, big) == "paged"
    assert resolve_gather("auto", fill_mid, big,
                          exchange="owner") == "flat"


# ---------------------------------------------------------------------
# engine equivalence: paged vs flat, all four apps


def _converge(eng):
    label, active = eng.init_state()
    label, _a, _it = eng.converge(label, active)
    return eng.unpad(label)


def test_sssp_cc_paged_bitwise_single_and_mesh():
    """min/max reductions are order-independent: paged and flat runs
    are ``array_equal`` outright, on one device AND the 8-virtual-
    device mesh — the acceptance equivalence for the push apps."""
    from lux_tpu.apps import components, sssp
    from lux_tpu.engine.push import PushEngine
    from lux_tpu.parallel.mesh import make_mesh

    g = _skewed_graph(7, 3 * W, 4000)
    sg = ShardedGraph.build(g, 2, vpad_align=128)
    flat = _converge(PushEngine(sg, sssp.make_program(0)))
    paged = _converge(PushEngine(sg, sssp.make_program(0),
                                 gather="paged"))
    assert np.array_equal(flat, paged)
    assert np.array_equal(
        paged, sssp.reference_sssp(g, 0).astype(paged.dtype))

    s2, d2 = components.symmetrize(*g.edge_arrays())
    gc = Graph.from_edges(s2.astype(np.uint32), d2.astype(np.uint32),
                          g.nv)
    sgc = ShardedGraph.build(gc, 2, vpad_align=128)
    cf = _converge(PushEngine(sgc, components.make_program()))
    cp = _converge(PushEngine(sgc, components.make_program(),
                              gather="paged"))
    assert np.array_equal(cf, cp)

    mesh = make_mesh(8)
    sg8 = ShardedGraph.build(g, 8, vpad_align=128)
    mp = _converge(PushEngine(sg8, sssp.make_program(0), mesh=mesh,
                              gather="paged"))
    assert np.array_equal(mp, flat)


def test_sum_paged_exact_on_integer_states():
    """f32 sums re-associate between the paged and flat layouts by
    construction, so the exact proof runs on sub-2^24 integer-valued
    states where f32 addition is exact in ANY order (the repo's
    established trick, ops/pairs.stacked_pair_dot_numpy) — paged and
    flat sum engines are then ``array_equal``, single device and
    8-device mesh."""
    from lux_tpu.engine.program import PullProgram
    from lux_tpu.engine.pull import PullEngine
    from lux_tpu.parallel.mesh import make_mesh

    g = _skewed_graph(9, 3 * W, 4000)
    vals = np.random.default_rng(0).integers(0, 8, g.nv).astype(
        np.float32)

    def mk():
        return PullProgram(
            reduce="sum",
            edge_value=lambda s, d, w: s,
            apply=lambda o, r, c: r,
            init=lambda sg: sg.to_padded(vals))

    sg = ShardedGraph.build(g, 2, vpad_align=128)
    flat = PullEngine(sg, mk())
    paged = PullEngine(sg, mk(), gather="paged")
    a = flat.unpad(flat.step(flat.init_state()))
    b = paged.unpad(paged.step(paged.init_state()))
    assert np.array_equal(a, b)

    mesh = make_mesh(8)
    sg8 = ShardedGraph.build(g, 8, vpad_align=128)
    pm = PullEngine(sg8, mk(), mesh=mesh, gather="paged")
    c = pm.unpad(pm.step(pm.init_state()))
    assert np.array_equal(a, c)


def test_pagerank_colfilter_paged_vs_flat():
    """The real sum apps at tight tolerance (their f32 sum order
    differs between layouts; the exact proof is the integer-state
    test above), plus the colfilter SDDMM dot path."""
    from lux_tpu.apps import colfilter, pagerank
    from lux_tpu.engine.pull import PullEngine

    g = _skewed_graph(11, 3 * W, 4000, weighted=True)
    sg = ShardedGraph.build(g, 2, vpad_align=128)
    pf = PullEngine(sg, pagerank.make_program())
    pp_ = PullEngine(sg, pagerank.make_program(), gather="paged")
    a = pf.unpad(pf.run(pf.init_state(), 6))
    b = pp_.unpad(pp_.run(pp_.init_state(), 6))
    np.testing.assert_allclose(b, a, rtol=1e-6)

    cf = PullEngine(sg, colfilter.make_program())
    cp = PullEngine(sg, colfilter.make_program(), gather="paged")
    x = cf.unpad(cf.run(cf.init_state(), 3))
    y = cp.unpad(cp.run(cp.init_state(), 3))
    np.testing.assert_allclose(y, x, rtol=1e-5, atol=1e-8)
    np.testing.assert_allclose(
        y, colfilter.reference_colfilter(g, 3), rtol=1e-4, atol=1e-7)


def test_colfilter_paged_dot_exact_oracle():
    """Integer states/weights under 2^24: the paged SDDMM delivery is
    BITWISE equal to its float64 oracle (order-independent exactness;
    the dot-path acceptance proof)."""
    g = _skewed_graph(13, 2 * W, 2000, weighted=True)
    sg = ShardedGraph.build(g, 2, vpad_align=128)
    pp = plan_paged_gather(sg)
    rng = np.random.default_rng(0)
    K = 4
    state = rng.integers(0, 4, (sg.num_parts * sg.vpad, K)).astype(
        np.float32)
    import jax.numpy as jnp

    from lux_tpu.ops.pagegather import paged_partial_dot

    def msg(S, dot, wt):
        return (wt - dot)[..., None] * S

    for p in range(sg.num_parts):
        t0 = p * (sg.vpad // W)
        got = np.asarray(paged_partial_dot(
            pp, jnp.asarray(state), jnp.asarray(pp.page_ids[p]),
            jnp.asarray(pp.slot_lane[p]), jnp.asarray(pp.rel_dst[p]),
            jnp.asarray(pp.weight[p]), jnp.asarray(pp.row_tile[p]),
            jnp.asarray(pp.tile_pos[p]), t0, msg))
        want = paged_dot_numpy(pp, p, state, t0, msg)
        assert np.array_equal(got, want)


def test_owner_paged_matches_flat():
    """exchange='owner' + gather='paged': the generation scan runs
    the page-binned shard delivery — same fixed point as the flat
    owner AND the flat gather engines (min = bitwise)."""
    from lux_tpu.apps import pagerank, sssp
    from lux_tpu.engine.pull import PullEngine
    from lux_tpu.engine.push import PushEngine

    g = _skewed_graph(17, 3 * W, 4000)
    sg = ShardedGraph.build(g, 2, vpad_align=128)
    flat = _converge(PushEngine(sg, sssp.make_program(0)))
    op = _converge(PushEngine(sg, sssp.make_program(0),
                              exchange="owner", gather="paged"))
    assert np.array_equal(flat, op)

    pf = PullEngine(sg, pagerank.make_program())
    po = PullEngine(sg, pagerank.make_program(), exchange="owner",
                    gather="paged")
    assert po.page_plan is not None and po.owner is None
    a = pf.unpad(pf.run(pf.init_state(), 5))
    b = po.unpad(po.run(po.init_state(), 5))
    np.testing.assert_allclose(b, a, rtol=1e-6)


def test_batched_paged_bitwise():
    """One batched (B > 1) config: k-source SSSP columns are bitwise
    identical between the paged and flat dense iterations (min
    reduce), and personalized PageRank stays within float tolerance."""
    from lux_tpu.apps import pagerank, sssp
    from lux_tpu.engine.pull import PullEngine
    from lux_tpu.engine.push import PushEngine

    g = _skewed_graph(19, 3 * W, 4000)
    sg = ShardedGraph.build(g, 2, vpad_align=128)
    srcs = [0, 5, 11]
    bf = _converge(PushEngine(sg, sssp.make_batched_program(srcs)))
    bp = _converge(PushEngine(sg, sssp.make_batched_program(srcs),
                              gather="paged"))
    assert np.array_equal(bf, bp)

    resets = pagerank.one_hot_resets(g.nv, srcs)
    ef = PullEngine(sg, pagerank.make_batched_program(resets))
    ep = PullEngine(sg, pagerank.make_batched_program(resets),
                    gather="paged")
    a = ef.unpad(ef.run(ef.init_state(), 4))
    b = ep.unpad(ep.run(ep.init_state(), 4))
    # B=3 engages the auto MXU sum on both engines (round 23); the
    # paged and flat layouts contract lanes in different orders, so
    # float sums agree to tolerance, not bitwise (PERF_NOTES r23).
    np.testing.assert_allclose(b, a, rtol=5e-6)


def test_paged_stats_and_health_variants():
    """The counter/watchdog loop variants run the SAME paged core:
    states bitwise-equal to the plain run, counters well-formed,
    watchdog clean (the stats/health acceptance slice)."""
    import jax

    from lux_tpu import health as hw
    from lux_tpu.apps import pagerank, sssp
    from lux_tpu.engine.pull import PullEngine
    from lux_tpu.engine.push import PushEngine

    g = _skewed_graph(23, 3 * W, 4000)
    sg = ShardedGraph.build(g, 2, vpad_align=128)

    eng = PullEngine(sg, pagerank.make_program(), gather="paged")
    plain = eng.run(eng.init_state(), 4)
    s2, res, chg, resp, chgp = eng.run_stats(eng.init_state(), 4)
    assert np.array_equal(np.asarray(plain), np.asarray(s2))
    assert np.asarray(res)[:4].min() > 0
    s3, _it, rb, cb, rbp, cbp, watch = eng.run_health(
        eng.init_state(), 4)
    hw.ensure_ok(watch, engine="pull", where="paged stats test")
    assert np.array_equal(np.asarray(plain), np.asarray(s3))

    pe = PushEngine(sg, sssp.make_program(0), gather="paged",
                    health=True)
    l0, a0 = pe.init_state()
    l1, a1, it, fsz, fed, fszp, fedp, pwatch = pe.converge_health(
        l0, a0)
    hw.ensure_ok(pwatch, engine="push", where="paged push health")
    flat = _converge(PushEngine(sg, sssp.make_program(0)))
    assert np.array_equal(pe.unpad(l1), flat)
    it = int(jax.device_get(it))
    # scalar edge counters sum the per-part rows bitwise
    assert np.array_equal(np.asarray(fed)[:it],
                          np.asarray(fedp)[:it].sum(axis=1,
                                                    dtype=np.uint32))


def test_paged_rejects_bad_configs():
    from lux_tpu.apps import pagerank
    from lux_tpu.engine.pull import PullEngine

    g = _skewed_graph(29, 3 * W, 3000)
    sg8 = ShardedGraph.build(g, 2)            # vpad_align 8: unaligned
    with pytest.raises(ValueError, match="vpad"):
        PullEngine(sg8, pagerank.make_program(), gather="paged")
    # auto on an unaligned build silently stays flat
    eng = PullEngine(sg8, pagerank.make_program(), gather="auto")
    assert eng.page_plan is None and eng.gather == "flat"
    sg = ShardedGraph.build(g, 2, vpad_align=128)
    with pytest.raises(ValueError, match="pair"):
        PullEngine(sg, pagerank.make_program(), gather="paged",
                   pair_threshold=4)


# ---------------------------------------------------------------------
# ledger + observe + check_bench integration


def test_memory_report_prices_paged_plan():
    g = _skewed_graph(31, 3 * W, 4000, weighted=True)
    sg = ShardedGraph.build(g, 2, vpad_align=128)
    pp = plan_paged_gather(sg)
    base = sg.memory_report()
    rep = sg.memory_report(page_plan=pp)
    want_edges = (pp.slot_lane.nbytes + pp.rel_dst.nbytes
                  + pp.row_tile.nbytes + pp.tile_pos.nbytes
                  + pp.page_ids.nbytes + pp.weight.nbytes) // 2
    assert rep["edge_bytes_per_part"] == want_edges
    assert rep["page_buffer_bytes_per_part"] == pp.n_pages * 128 * 4
    # the delivered-rows temporaries (vals + row partials, the same
    # 2x-Rp term the pair path prices) must be in the advisor total:
    # an unpriced paged build would pass the advisor and OOM on its
    # first iteration (the pair path's measured RMAT25 failure mode)
    assert rep["page_temp_bytes_per_part"] == 2 * pp.Rp * 128 * 4
    assert rep["total_bytes"] != base["total_bytes"]


def test_engine_ledger_check_paged():
    """check_ledger on a paged engine: the priced plan arrays + page
    buffer stay within tolerance of the compiled step's argument
    bytes (the audit matrix's paged ledger config, asserted
    directly)."""
    from lux_tpu import audit
    from lux_tpu.apps import pagerank

    rng = np.random.default_rng(0)
    g = Graph.from_edges(rng.integers(0, 2048, 32768),
                         rng.integers(0, 2048, 32768), 2048)
    eng = pagerank.build_engine(g, num_parts=2, gather="paged")
    assert eng.page_plan is not None
    findings = audit.check_ledger(eng)
    assert [f for f in findings if f.severity == "error"] == []


def test_observe_decompose_paged():
    """The acceptance command path: a paged pull run decomposes with
    a phase-model PRICE for the paged delivery phase (not unmodeled)
    and a non-degraded session on CPU."""
    from lux_tpu import observe
    from lux_tpu.apps import pagerank

    g = _skewed_graph(37, 4 * W, 6000)
    eng = pagerank.build_engine(g, num_parts=2, gather="paged")
    fp = observe.calibrate()
    assert fp.grade != "degraded"
    assert "page_gather_row_ns" in fp.probe
    d = observe.decompose(eng, "pagerank", iters=2, fingerprint=fp)
    by = {p.phase: p for p in d.phases}
    assert "gather_reduce" in by
    pc = by["gather_reduce"]
    assert pc.predicted_s is not None and pc.predicted_s > 0
    assert pc.verdict != "unmodeled"


def test_check_bench_gather_fields(tmp_path):
    import subprocess
    import sys
    from pathlib import Path

    REPO = Path(__file__).resolve().parent.parent
    good = {"metric": "pagerank_paged_rmat21_gteps_per_chip",
            "value": 0.5, "unit": "GTEPS", "vs_baseline": 0.5,
            "samples": [0.5], "attempts": 1, "discarded": [],
            "gather": "paged", "page_ratio": 0.02, "page_fill": 97.3,
            "telemetry": {"runs": [{"repeat": 0, "iters": 20,
                                    "seconds": 1.0}],
                          "counters": None},
            "calibration": {
                "session": "s", "platform": "tpu", "backend": "tpu",
                "ndev": 1, "grade": "canonical", "deviation": 1.0,
                "probe": {"gather_small_ns": 9.0},
                "audit": {"errors": 0, "warnings": 0}}}
    import copy
    import json
    bad1 = copy.deepcopy(good)
    del bad1["page_ratio"]
    bad2 = copy.deepcopy(good)
    bad2["gather"] = "flat"    # contradicts the metric name
    bad3 = copy.deepcopy(good)
    bad3["page_fill"] = 600.0

    p = tmp_path / "lines.jsonl"
    p.write_text("\n".join(json.dumps(x) for x in [good]))
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_bench.py"),
         str(p)], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr

    p.write_text("\n".join(json.dumps(x)
                           for x in [bad1, bad2, bad3]))
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_bench.py"),
         str(p)], capture_output=True, text=True)
    assert r.returncode == 1
    assert "page_ratio" in r.stderr
    assert "contradicts the metric name" in r.stderr
    assert "page_fill" in r.stderr


def test_lint_gates_bench_fencing(tmp_path):
    """The bench-fence check: block_until_ready in a scripts/ file is
    a finding; the pragma suppresses it; lux_tpu files are exempt
    (the engines legitimately never use it anyway)."""
    import subprocess
    import sys
    from pathlib import Path

    REPO = Path(__file__).resolve().parent.parent
    sdir = tmp_path / "scripts"
    sdir.mkdir()
    bad = sdir / "profile_thing.py"
    bad.write_text("import jax\n"
                   "out = 1\n"
                   "jax.block_until_ready(out)\n")
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint_lux.py"),
         str(bad)], capture_output=True, text=True)
    assert r.returncode == 1 and "bench-fence" in r.stderr

    ok = sdir / "profile_ok.py"
    ok.write_text("import jax\n"
                  "out = 1\n"
                  "# one-off interactive poke, not a timed region\n"
                  "# audit: allow(bench-fence)\n"
                  "jax.block_until_ready(out)\n")
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint_lux.py"),
         str(ok)], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    # the repo's own scripts tree is clean under the gate (the
    # rounds-12/15 loop_bench port)
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint_lux.py"),
         str(REPO / "scripts")], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr


# ---------------------------------------------------------------------
# page-major layout (round 16): gather rows bind to pages first


def test_pagemajor_plan_resolves_every_edge():
    """Every edge decodes back through its virtual row's gather row —
    multiset equality per part — and the gather rows are near-full by
    construction (that is the mode's whole point)."""
    from lux_tpu.ops.pagegather import plan_pagemajor

    g = _skewed_graph(11, 4 * W, 7000)
    for P in (1, 3):
        sg = ShardedGraph.build(g, P, vpad_align=128)
        pp = plan_pagemajor(sg)
        assert pp.mode == "pagemajor"
        assert pp.stats["g_fill"] > pp.stats["fill"]
        for p in range(P):
            nep = int(sg.ne_part[p])
            src, dst = decode_plan(pp, p)
            assert len(src) == nep
            want = sorted(zip(sg.src_slot[p, :nep].tolist(),
                              sg.dst_local[p, :nep].tolist()))
            assert sorted(zip(src.tolist(), dst.tolist())) == want


def test_pagemajor_oracle_reduce_matches_flat():
    """paged_reduce_numpy through the virtual-row indirection equals
    the plain flat reduce (padding contributes the identity)."""
    from lux_tpu.ops.pagegather import plan_pagemajor

    g = _skewed_graph(12, 3 * W, 5000)
    sg = ShardedGraph.build(g, 2, vpad_align=128)
    pp = plan_pagemajor(sg)
    state = np.random.default_rng(1).random(sg.num_parts * sg.vpad)
    for p in range(2):
        nep = int(sg.ne_part[p])
        out = paged_reduce_numpy(pp, p, state, "sum")
        ref = np.zeros(sg.vpad)
        np.add.at(ref, sg.dst_local[p, :nep],
                  state[sg.src_slot[p, :nep]])
        assert np.allclose(out[:sg.vpad], ref)


def test_pagemajor_owner_plan_decodes():
    """The owner page-major plan's routed layout decodes back to the
    full edge multiset: every (src part, src local, global dst)
    appears exactly once across the destination parts' receive
    plans."""
    from lux_tpu.ops.pagegather import (decode_pagemajor_owner,
                                        plan_owner_pagemajor)

    g = _skewed_graph(13, 4 * W, 6000)
    P = 4
    sg = ShardedGraph.build(g, P, vpad_align=128)
    po = plan_owner_pagemajor(sg)
    assert po.route >= 8 and po.route % 8 == 0
    got = []
    for d in range(P):
        s, srcl, dstl = decode_pagemajor_owner(po, d)
        got += list(zip(s.tolist(), srcl.tolist(),
                        (d * sg.vpad + dstl).tolist()))
    want = []
    for r in range(P):
        nep = int(sg.ne_part[r])
        slot = sg.src_slot[r, :nep].astype(np.int64)
        sp = slot // sg.vpad
        want += list(zip(sp.tolist(),
                         (slot - sp * sg.vpad).tolist(),
                         (r * sg.vpad
                          + sg.dst_local[r, :nep]).tolist()))
    assert sorted(got) == sorted(want)


def test_pagemajor_engines_match_flat():
    """gather='pagemajor' engines reproduce the flat engines: bitwise
    for the min/max push apps (order-independent), on one device, the
    8-device mesh, the OWNER routing exchange, and a batched build;
    integer-exact for a sum pull step."""
    from lux_tpu.apps import sssp
    from lux_tpu.engine.program import PullProgram
    from lux_tpu.engine.pull import PullEngine
    from lux_tpu.engine.push import PushEngine
    from lux_tpu.parallel.mesh import make_mesh

    g = _skewed_graph(14, 4 * W, 6000)
    sg = ShardedGraph.build(g, 2, vpad_align=128)
    flat = _converge(PushEngine(sg, sssp.make_program(0)))
    pm = _converge(PushEngine(sg, sssp.make_program(0),
                              gather="pagemajor"))
    assert np.array_equal(flat, pm)
    pmo = _converge(PushEngine(sg, sssp.make_program(0),
                               exchange="owner",
                               gather="pagemajor"))
    assert np.array_equal(flat, pmo)

    mesh = make_mesh(8)
    sg8 = ShardedGraph.build(g, 8, vpad_align=128)
    pm8 = _converge(PushEngine(sg8, sssp.make_program(0), mesh=mesh,
                               gather="pagemajor"))
    assert np.array_equal(flat, pm8)
    pm8o = _converge(PushEngine(sg8, sssp.make_program(0), mesh=mesh,
                                exchange="owner",
                                gather="pagemajor"))
    assert np.array_equal(flat, pm8o)

    # batched (k-source) labels ride the trailing query axis
    ks_flat = _converge(PushEngine(sg, sssp.make_batched_program(
        [0, 5, 9])))
    ks_pm = _converge(PushEngine(sg, sssp.make_batched_program(
        [0, 5, 9]), gather="pagemajor"))
    assert np.array_equal(ks_flat, ks_pm)

    # integer-exact sum pull step (the established f32-exactness
    # trick): flat vs pagemajor vs pagemajor+owner
    vals = np.random.default_rng(2).integers(0, 8, g.nv).astype(
        np.float32)

    def mk():
        return PullProgram(
            reduce="sum",
            edge_value=lambda s, d, w: s,
            apply=lambda o, r, c: r,
            init=lambda sgx: sgx.to_padded(vals))

    a = PullEngine(sg, mk())
    b = PullEngine(sg, mk(), gather="pagemajor")
    c = PullEngine(sg, mk(), gather="pagemajor", exchange="owner")
    ra = a.unpad(a.step(a.init_state()))
    assert np.array_equal(ra, b.unpad(b.step(b.init_state())))
    assert np.array_equal(ra, c.unpad(c.step(c.init_state())))


def test_pagemajor_break_even_pinned():
    from lux_tpu import scalemodel as sm

    # the 150 ns pair-row machinery splits: 24 ns static row fetch +
    # the compare-reduce/combine remainder
    assert sm.VROW_REDUCE_NS == pytest.approx(150.0 - 24.0)
    # full gather rows pay fetch+shuffle once; the virtual-row
    # break-even undercuts the plain paged 23
    assert sm.pagemajor_break_even_vfill() == 19
    assert sm.pagemajor_break_even_vfill() < sm.page_break_even_fill()
    # the routing hop is ~0.1 ns/edge at full rows — priced, small
    assert 0.0 < sm.pagemajor_route_ns(128.0) < 0.2
    assert sm.pagemajor_break_even_vfill(routed=True) >= \
        sm.pagemajor_break_even_vfill()
    with pytest.raises(ValueError, match="K-dim"):
        sm.pagemajor_gather_ns(1.0, 128.0, 30.0, kdim=20)


def test_resolve_gather_three_way():
    """auto arbitration with the pm counting present: page-major wins
    exactly when its modeled split rate undercuts both flat and
    paged; without pm keys the old two-way behavior is unchanged."""
    from lux_tpu import scalemodel as sm

    # virtual fill below the paged break-even but above the
    # page-major one, gather rows full -> pagemajor
    st = dict(page_ratio=0.3, fill=20.0, padded_fill=20.0,
              pm_padded_vfill=20.0, pm_g_padded_fill=120.0)
    assert sm.pagemajor_gather_ns(0.3, 120.0, 20.0) \
        < sm.GATHER_SMALL_NS < sm.page_gather_ns(0.3, 20.0)
    assert resolve_gather("auto", st, 1 << 20) == "pagemajor"
    # high fill: paged's single-level pipeline models cheaper than
    # pm's extra virtual take whenever vfill ~ gfill
    dense = dict(page_ratio=0.3, fill=120.0, padded_fill=120.0,
                 pm_padded_vfill=120.0, pm_g_padded_fill=120.0)
    assert resolve_gather("auto", dense, 1 << 20) == "paged"
    # hopeless fills stay flat even with pm keys
    sparse = dict(page_ratio=3.0, fill=2.0, padded_fill=2.0,
                  pm_padded_vfill=2.0, pm_g_padded_fill=10.0)
    assert resolve_gather("auto", sparse, 1 << 20) == "flat"
    assert resolve_gather("pagemajor", sparse, 1 << 20) == "pagemajor"


def test_pagemajor_guards():
    """Typed refusals: K-dim (SDDMM) programs cannot take
    gather='pagemajor'; pair_threshold conflicts like paged."""
    from lux_tpu.apps import colfilter, pagerank

    gw = _skewed_graph(15, 3 * W, 4000, weighted=True)
    with pytest.raises(ValueError, match="K-dim|SDDMM"):
        colfilter.build_engine(gw, num_parts=1, gather="pagemajor")
    g = _skewed_graph(15, 3 * W, 4000)
    with pytest.raises(ValueError, match="pair"):
        pagerank.build_engine(g, num_parts=1, gather="pagemajor",
                              pair_threshold=8)


def test_pagemajor_ledger_prices_clean():
    """memory_report(page_plan=pm plan) prices the plan arrays + the
    gather-row buffer; the audit ledger check stays clean on a dense
    pagemajor build."""
    from lux_tpu import audit
    from lux_tpu.apps import pagerank

    r = np.random.default_rng(4)
    g = Graph.from_edges(r.integers(0, 2048, 32768),
                         r.integers(0, 2048, 32768), 2048)
    eng = pagerank.build_engine(g, num_parts=2, gather="pagemajor")
    assert eng.gather == "pagemajor"
    rep = eng.sg.memory_report(page_plan=eng.page_plan)
    assert rep["page_temp_bytes_per_part"] > 0
    assert rep["edge_bytes_per_part"] > 0
    findings = audit.audit_engine(eng, mode=None, ledger=True)
    assert not [f for f in findings if f.severity == "error"], \
        [str(f) for f in findings]
